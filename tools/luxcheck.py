#!/usr/bin/env python
"""luxcheck — run the repo-native static-analysis suite (lux_tpu.analysis).

Usage:
    python tools/luxcheck.py --all              # the full repo gate
    python tools/luxcheck.py lux_tpu/ops        # specific paths
    python tools/luxcheck.py --list-checkers
    python tools/luxcheck.py --all --fingerprints   # baseline-entry form

Exit codes: 0 = clean (no unsuppressed findings), 1 = findings, 2 = usage.

Runs as step -3 of tools/chip_day.sh (abort the window before any chip
budget is spent), inside tools/ci_check.sh, and as a tier-1 test
(tests/test_luxcheck.py::test_repo_is_luxcheck_clean).

Suppressing a finding (both forms REQUIRE a written justification):
  inline   —  # luxcheck: disable=LUX-T001 -- why this is safe
  baseline —  tools/luxcheck_baseline.txt: <path>:<code>:<fingerprint>  # why
The baseline ships empty; it exists for mid-chip-window emergencies, not
as a dumping ground — stale entries are themselves findings (LUX-X003).
"""
import argparse
import os
import sys

_TOOLS = os.path.dirname(os.path.abspath(__file__))
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

import _jaxfree  # noqa: E402

# the analysis package is pure stdlib; the stub keeps the preflight gate
# in milliseconds on a host whose jax install is in ANY state
REPO = _jaxfree.bare_package()

from lux_tpu.analysis import (  # noqa: E402
    ALL_CHECKERS, DEFAULT_TARGETS, check_paths,
)

DEFAULT_BASELINE = os.path.join("tools", "luxcheck_baseline.txt")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="repo-native static analysis (tracing-safety, "
                    "determinism, thread-safety, policy)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to check (repo-relative)")
    ap.add_argument("--all", action="store_true",
                    help=f"check the shipped targets: {DEFAULT_TARGETS}")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="suppressions file (default "
                         f"{DEFAULT_BASELINE}; '' disables)")
    ap.add_argument("--list-checkers", action="store_true")
    ap.add_argument("--fingerprints", action="store_true",
                    help="print findings as ready-to-paste baseline "
                         "entries instead of human-readable lines")
    args = ap.parse_args(argv)

    if args.list_checkers:
        for ch in ALL_CHECKERS:
            print(f"{ch.name:14s} family={ch.family}  "
                  f"({type(ch).__module__})")
        return 0

    paths = list(args.paths)
    if args.all:
        paths = list(DEFAULT_TARGETS) + paths
    if not paths:
        ap.print_usage(sys.stderr)
        print("error: give paths or --all", file=sys.stderr)
        return 2

    baseline = None
    if args.baseline:
        b = (args.baseline if os.path.isabs(args.baseline)
             else os.path.join(REPO, args.baseline))
        baseline = b
    findings = check_paths(paths, REPO, baseline_path=baseline)
    for f in findings:
        if args.fingerprints:
            print(f"{f.path}:{f.code}:{f.fingerprint()}  # JUSTIFY: "
                  f"{f.message[:60]}")
        else:
            print(f.format())
    n = len(findings)
    where = f"{len(paths)} target(s)"
    if n:
        print(f"\nluxcheck: {n} finding(s) in {where} — fix, or suppress "
              "WITH a justification (see docs/ANALYSIS.md)",
              file=sys.stderr)
        return 1
    print(f"luxcheck: clean ({where})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
