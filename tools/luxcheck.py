#!/usr/bin/env python
"""luxcheck — run the repo-native static-analysis suite (lux_tpu.analysis).

Usage:
    python tools/luxcheck.py --all              # the full repo gate
    python tools/luxcheck.py lux_tpu/ops        # specific paths
    python tools/luxcheck.py --list-checkers
    python tools/luxcheck.py --all --fingerprints   # baseline-entry form
    python tools/luxcheck.py --twins            # known-bad twins must fire
    python tools/luxcheck.py --check-baselines  # both baselines, jax-free

Exit codes: 0 = clean (no unsuppressed findings), 1 = findings, 2 = usage.

Runs as step -3 of tools/chip_day.sh (abort the window before any chip
budget is spent), inside tools/ci_check.sh, and as a tier-1 test
(tests/test_luxcheck.py::test_repo_is_luxcheck_clean).

Suppressing a finding (both forms REQUIRE a written justification):
  inline   —  # luxcheck: disable=LUX-T001 -- why this is safe
  baseline —  tools/luxcheck_baseline.txt: <path>:<code>:<fingerprint>  # why
The baseline ships empty; it exists for mid-chip-window emergencies, not
as a dumping ground — stale entries are themselves findings (LUX-X003).
"""
import argparse
import os
import sys

_TOOLS = os.path.dirname(os.path.abspath(__file__))
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

import _jaxfree  # noqa: E402

# the analysis package is pure stdlib; the stub keeps the preflight gate
# in milliseconds on a host whose jax install is in ANY state
REPO = _jaxfree.bare_package()

from lux_tpu.analysis import (  # noqa: E402
    ALL_CHECKERS, DEFAULT_TARGETS, check_paths, load_baseline,
)

DEFAULT_BASELINE = os.path.join("tools", "luxcheck_baseline.txt")
AUDIT_BASELINE = os.path.join("tools", "luxaudit_baseline.txt")


def _run_twins() -> int:
    from lux_tpu.analysis.twins import run_twins

    results = run_twins()
    silent = [r for r in results if not r[3]]
    for name, expected, fired, ok in results:
        mark = "ok" if ok else "SILENT"
        print(f"  twin {name:28s} expect={','.join(expected)} "
              f"fired={','.join(sorted(fired)) or '-'} [{mark}]")
    if silent:
        print(f"luxcheck --twins: {len(silent)} known-bad twin(s) came "
              "back clean — the CHECKER stopped firing, not the snippet",
              file=sys.stderr)
        return 1
    print(f"[PASS] luxcheck twins: {len(results)}/{len(results)} fired")
    return 0


def _check_baselines() -> int:
    """Staleness tripwire for BOTH baseline files, jax-free.

    luxcheck's baseline gets the real treatment: a full sweep with the
    baseline applied surfaces malformed entries (LUX-X002) and entries
    matching no current finding (LUX-X003).  luxaudit's sweep needs jax
    (it traces the real engines), so its baseline gets the checks that
    don't: entry structure, justification presence, and whether the
    file each entry names still exists — an entry for a deleted file is
    stale whatever the fingerprints say.
    """
    problems = []
    lc = os.path.join(REPO, DEFAULT_BASELINE)
    meta = [f for f in check_paths(list(DEFAULT_TARGETS), REPO,
                                   baseline_path=lc)
            if f.code in ("LUX-X002", "LUX-X003")]
    problems.extend(f.format() for f in meta)
    lc_entries, _ = load_baseline(lc)

    la = os.path.join(REPO, AUDIT_BASELINE)
    la_entries, bad = load_baseline(la)
    problems.extend(f.format() for f in bad)
    for e in la_entries:
        if not os.path.exists(os.path.join(REPO, e.path)):
            problems.append(
                f"{os.path.basename(la)}:{e.lineno}: entry names "
                f"'{e.path}' which no longer exists — stale")
    if problems:
        for p in problems:
            print(p)
        print(f"luxcheck --check-baselines: {len(problems)} problem(s)",
              file=sys.stderr)
        return 1
    print(f"[PASS] baselines: luxcheck={len(lc_entries)} "
          f"luxaudit={len(la_entries)} entr(ies), none stale or "
          "malformed")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="repo-native static analysis (tracing-safety, "
                    "determinism, thread-safety, policy)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to check (repo-relative)")
    ap.add_argument("--all", action="store_true",
                    help=f"check the shipped targets: {DEFAULT_TARGETS}")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="suppressions file (default "
                         f"{DEFAULT_BASELINE}; '' disables)")
    ap.add_argument("--list-checkers", action="store_true")
    ap.add_argument("--fingerprints", action="store_true",
                    help="print findings as ready-to-paste baseline "
                         "entries instead of human-readable lines")
    ap.add_argument("--twins", action="store_true",
                    help="run the LUX-G/LUX-R synthetic-positive twins: "
                         "known-bad snippets that MUST fire (a clean "
                         "twin means the checker rotted)")
    ap.add_argument("--check-baselines", action="store_true",
                    help="staleness tripwire for the luxcheck AND "
                         "luxaudit baseline files (jax-free)")
    args = ap.parse_args(argv)

    if args.list_checkers:
        for ch in ALL_CHECKERS:
            print(f"{ch.name:14s} family={ch.family}  "
                  f"({type(ch).__module__})")
        return 0
    if args.twins:
        return _run_twins()
    if args.check_baselines:
        return _check_baselines()

    paths = list(args.paths)
    if args.all:
        paths = list(DEFAULT_TARGETS) + paths
    if not paths:
        ap.print_usage(sys.stderr)
        print("error: give paths or --all", file=sys.stderr)
        return 2

    baseline = None
    if args.baseline:
        b = (args.baseline if os.path.isabs(args.baseline)
             else os.path.join(REPO, args.baseline))
        baseline = b
    findings = check_paths(paths, REPO, baseline_path=baseline)
    for f in findings:
        if args.fingerprints:
            print(f"{f.path}:{f.code}:{f.fingerprint()}  # JUSTIFY: "
                  f"{f.message[:60]}")
        else:
            print(f.format())
    n = len(findings)
    where = f"{len(paths)} target(s)"
    if n:
        print(f"\nluxcheck: {n} finding(s) in {where} — fix, or suppress "
              "WITH a justification (see docs/ANALYSIS.md)",
              file=sys.stderr)
        return 1
    print(f"luxcheck: clean ({where})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
