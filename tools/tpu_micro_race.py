#!/usr/bin/env python
"""Chip-window insurance: a sub-minute scan-vs-mxsum segment-sum race.

Round 2's only chip window lasted ~7 minutes; the full battery
(tools/chip_day.sh) needs hours.  This step-0 microbench banks a usable
sum-method decision in the first minute of ANY window:

  * ONE tiny graph (rmat17/ef16 by default — 131k vertices, 2.1M edges)
    so each worker is a single small compile (rep count n is TRACED, so
    slope-timing costs no extra compiles).
  * Each method runs in its OWN subprocess, mxsum first: its line is
    banked on disk the moment it exists, before scan — the one method
    that has ever wedged the tunnel (docs/PERF.md pitfall 3) — is risked
    at all.  A wedged worker is abandoned (never killed: it may hold the
    tunnel claim and must release it cleanly), exactly like bench.py's
    watchdog.
  * The parent imports no jax (a dead relay must cost milliseconds, not
    a C-level claim-retry hour) and auto-records the measurements under
    ``"tpu:micro_sum"`` in the winners overlay
    (lux_tpu.engine.methods.record_overlay_entry), so even a window that
    dies 90 seconds in leaves a measured artifact behind.

The race is sum-only on purpose: the headline app (PageRank) is a pure
segment-sum, mxsum is its fastest sum-only candidate, and scan is the
shipped blanket TPU default that has NEVER been timed on a chip
(engine/methods.WINNERS).  The full bench race still owns the
``"tpu:sum"`` blanket-default row; this tool only banks raw numbers plus
a ``winner`` field for the human / next-round fold-in.

Round-7 exactness-gated pairs (ISSUE 7): "fusedpf" vs "fusedmx" (the
MXREDUCE in-kernel MXU reduction) banks ``tpu:reduce_mode``, and
"cfdotvpu" vs "cfdotmxu" (the CF error-dot as VPU lane-sum vs a true
MXU matmul tile) banks ``tpu:cf_err_dot`` — each worker refuses to emit
a row that fails its NumPy-oracle gate, so a banked winner is always a
numerically-verified one.

Round-8 (ISSUE 11): "mxscan" — the segmented scan itself as blocked
masked-triangular MXU contractions (ops/pallas_scan) — joins the
segment-sum workers, and every segment-sum worker is now oracle-gated.
The THREE-WAY scan-family race (scan vs mxsum vs mxscan on one census)
banks ``tpu:sum`` only when all three flavors measure; the banked
winner retires the VPU default through engine/methods.sum_mode on the
csc gather-apply paths (CPU runs stay bitwise-unchanged).

Usage: python tools/tpu_micro_race.py [--scale 17] [--methods mxsum scan]
       (worker mode: --worker --method M, spawned internally)
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _env import env_int as _env_int  # noqa: E402 — jax-free twin of utils.config.env_int

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _fit(xs, ys):
    n = len(xs)
    mx, my = sum(xs) / n, sum(ys) / n
    num = sum((x - mx) * (y - my) for x, y in zip(xs, ys))
    den = sum((x - mx) ** 2 for x in xs)
    return num / den, my - (num / den) * mx


def worker_main(args) -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from lux_tpu.graph import generate
    from lux_tpu.ops import segment

    t_setup = time.perf_counter()
    g = generate.rmat(args.scale, args.ef, seed=0)
    rng = np.random.default_rng(0)
    state = jnp.asarray(rng.random(g.nv, np.float32))
    # each mode transfers ONLY its own operands: the first worker's row
    # must bank in a window's first minute, so no method pays another
    # mode's host->device traffic.
    # "gather"/"gatherc" time the OTHER hot-loop half: the per-edge
    # state read, direct vs through the compact mirror — the roofline's
    # dominant unknown, banked at micro scale in the same window.
    if args.method == "gather":
        src_pos = jnp.asarray(np.asarray(g.col_idx).astype(np.int32))
        jax.block_until_ready((state, src_pos))

        def f(x):
            return x[src_pos].reshape(g.nv, args.ef).sum(axis=1) * 1e-3
    elif args.method in ("route", "routepf"):
        # the routed-shuffle expand (ops/expand.py) standing in for the
        # flat gather: directly comparable to the "gather" row (same
        # reshape-sum tail).  "routepf" is the PASS-FUSED variant
        # (expand.to_pf: 2-3 Benes passes per kernel, VMEM-resident
        # intermediates) — the round-6 A/B this tool banks first.
        # Exactness is checked against the direct gather before timing.
        from lux_tpu.ops import expand

        src_pos = np.asarray(g.col_idx).astype(np.int32)
        t_plan = time.perf_counter()
        static, arrays_np = expand.plan_expand(src_pos, len(src_pos), g.nv)
        if args.method == "routepf":
            static, arrays_np = expand.to_pf((static, arrays_np))
        print(f"# {args.method} plan built in "
              f"{time.perf_counter() - t_plan:.1f}s "
              f"(n={static.n}, {len(arrays_np)} pass arrays)", flush=True)
        route_arrays = tuple(jnp.asarray(a) for a in arrays_np)
        interp = jax.default_backend() not in ("tpu", "axon")
        jax.block_until_ready((state,) + route_arrays)

        def f(x):
            vals = expand.apply_expand(x, static, route_arrays,
                                       interpret=interp)
            return vals[: g.ne].reshape(g.nv, args.ef).sum(axis=1) * 1e-3

        got = np.asarray(
            jax.jit(lambda x: expand.apply_expand(
                x, static, route_arrays, interpret=interp))(state))[: g.ne]
        want = np.asarray(state)[src_pos]
        exact = bool((got == want).all())
        print(f"# route exactness vs direct gather: {exact}", flush=True)
        if not exact:
            return 3
    elif args.method in ("fused", "fusedpf", "fusedmx"):
        # the COMPLETE fused routed hot loop (expand + reduce as routed
        # movement) — the number to weigh against gather + a segment-sum
        # row combined; "fusedpf" pass-fuses its r1/r2/vr routes,
        # "fusedmx" additionally computes the segmented reduction
        # INSIDE the final routed kernel as an MXU one-hot contraction
        # (ISSUE 7; its fusedpf-vs-fusedmx delta banks the
        # tpu:reduce_mode winner).  Exact for this check's sum only up
        # to group association; verified against the NumPy oracle with
        # rtol (the pf transform keeps the group layout, so fused and
        # fusedpf are bitwise EQUAL to each other; fusedmx has its own
        # deterministic association).
        from lux_tpu.ops import expand

        src_pos = np.asarray(g.col_idx).astype(np.int32)
        dst_local = g.dst_of_edges().astype(np.int32)
        t_plan = time.perf_counter()
        static, arrays_np = expand.plan_fused(
            src_pos, dst_local, g.ne, g.nv, g.nv, "sum",
            mx=args.method == "fusedmx")
        if args.method == "fusedpf":
            static, arrays_np = expand.to_pf((static, arrays_np))
        print(f"# {args.method} plan built in "
              f"{time.perf_counter() - t_plan:.1f}s "
              f"(n={static.n}, n2={static.n2}, "
              f"{len(static.groups)} groups)", flush=True)
        route_arrays = tuple(jnp.asarray(a) for a in arrays_np)
        interp = jax.default_backend() not in ("tpu", "axon")
        jax.block_until_ready((state,) + route_arrays)

        def f(x):
            acc = expand.apply_fused(x, static, route_arrays,
                                     interpret=interp)
            return acc * 1e-3

        got = np.asarray(
            jax.jit(lambda x: expand.apply_fused(
                x, static, route_arrays, interpret=interp))(state))
        want = np.zeros(g.nv, np.float32)
        np.add.at(want, dst_local, np.asarray(state)[src_pos])
        ok = bool(np.allclose(got, want, rtol=1e-4, atol=1e-6))
        print(f"# fused numerics vs oracle: {ok}", flush=True)
        if not ok:
            return 3
    elif args.method in ("cfdotvpu", "cfdotmxu"):
        # the CF error-dot (models/colfilter.err_dot): per-edge K=20
        # <v_src, v_dst> as VPU lane-sum vs a TRUE (rows, K) @ (K, 1)
        # MXU matmul tile.  Both workers share the identical gather, so
        # their delta isolates the contraction; exactness is gated
        # against the NumPy oracle with rtol (f32 association differs).
        # The pair banks the tpu:cf_err_dot winner.
        from lux_tpu.models.colfilter import K, err_dot

        mode = "mxu" if args.method == "cfdotmxu" else "vpu"
        vecs = jnp.asarray(rng.random((g.nv, K), np.float32))
        src_pos = jnp.asarray(np.asarray(g.col_idx).astype(np.int32))
        dst_pos = jnp.asarray(g.dst_of_edges().astype(np.int32))
        jax.block_until_ready((vecs, src_pos, dst_pos))
        got = np.asarray(jax.jit(
            lambda v: err_dot(v[src_pos], v[dst_pos], mode))(vecs))
        want = np.einsum(
            "ek,ek->e", np.asarray(vecs)[np.asarray(src_pos)],
            np.asarray(vecs)[np.asarray(dst_pos)]).astype(np.float32)
        ok = bool(np.allclose(got, want, rtol=1e-4, atol=1e-6))
        print(f"# cfdot({mode}) numerics vs oracle: {ok}", flush=True)
        if not ok:
            return 3
        state = vecs  # (nv, K) latent state replaces the scalar chain

        def f(v):
            e = err_dot(v[src_pos], v[dst_pos], mode)
            return v + e.sum() * jnp.float32(1e-12)
    elif args.method == "gatherc":
        col = np.asarray(g.col_idx).astype(np.int32)
        uniq = np.unique(col)
        mirror_pos = jnp.asarray(uniq.astype(np.int32))
        mirror_rel = jnp.asarray(np.searchsorted(uniq, col).astype(np.int32))
        jax.block_until_ready((state, mirror_pos, mirror_rel))
        print(f"# compact mirror: U={len(uniq)} ({len(uniq)/g.nv:.2f} of nv)",
              flush=True)

        def f(x):
            m = x[mirror_pos]
            return m[mirror_rel].reshape(g.nv, args.ef).sum(axis=1) * 1e-3
    else:
        row_ptr = jnp.asarray(g.row_ptr.astype(np.int32))
        head = np.zeros(g.ne, np.int32)
        head[g.row_ptr[:-1][g.row_ptr[:-1] < g.ne]] = 1
        head_flag = jnp.asarray(head.astype(bool))
        dst_local = jnp.asarray(g.dst_of_edges().astype(np.int32))
        vals_fixed = jnp.asarray(rng.random(g.ne, np.float32))
        jax.block_until_ready(
            (state, row_ptr, head_flag, dst_local, vals_fixed))

        def f(x):
            vals = vals_fixed * x[0]
            acc = segment.segment_sum_csc(
                vals, row_ptr, head_flag, dst_local, method=args.method)
            return acc * 0.999

        # exactness gate (ISSUE 11): every segment-sum worker must match
        # the NumPy f64 oracle before its time counts — the three-way
        # tpu:sum race (scan/mxsum/mxscan) only banks numerically
        # verified rows.  rtol covers each strategy's own deterministic
        # f32 association (mxsum's global prefix is the loosest).
        got = np.asarray(jax.jit(
            lambda x: segment.segment_sum_csc(
                vals_fixed * x[0], row_ptr, head_flag, dst_local,
                method=args.method))(state))
        s0 = float(np.asarray(state)[0])
        want = np.zeros(g.nv, np.float64)
        np.add.at(want, np.asarray(dst_local),
                  np.asarray(vals_fixed, np.float64) * s0)
        # atol scales with ne * f32-eps: the prefix-diff strategies'
        # documented global-prefix cancellation bound (measured at its
        # edge for mxsum/cumsum; scan/mxscan sit ~100x under it)
        atol = max(1e-5, g.ne * 6e-7)
        ok = bool(np.allclose(got[: g.nv], want, rtol=1e-3, atol=atol))
        print(f"# {args.method} numerics vs oracle: {ok}", flush=True)
        if not ok:
            return 3
    platform = jax.devices()[0].platform
    print(f"# micro worker: platform={platform} method={args.method} "
          f"nv={g.nv} ne={g.ne} setup={time.perf_counter()-t_setup:.1f}s",
          flush=True)

    # x_{k+1} = f(x_k) chaining (XLA cannot collapse reps); n traced ->
    # exactly one compile; fetch-based timing (device_get of a scalar is
    # the only timing the tunnel cannot fake, tools/tpu_timing_probe.py)
    @jax.jit
    def run(x0, n):
        def body(_, x):
            return f(x)
        return jax.lax.fori_loop(0, n, body, x0)

    t_c = time.perf_counter()
    for n in args.reps:  # warm: compile once, touch every rep count
        float(jax.device_get(run(state, jnp.int32(n)).ravel()[0]))
    compile_s = time.perf_counter() - t_c
    xs, ts = [], []
    for n in args.reps:
        t0 = time.perf_counter()
        float(jax.device_get(run(state, jnp.int32(n)).ravel()[0]))
        ts.append(time.perf_counter() - t0)
        xs.append(n)
    slope, icpt = _fit(xs, ts)
    gteps = g.ne / slope / 1e9 if slope > 0 else float("nan")
    kind = ("gather"
            if args.method in ("gather", "gatherc", "route", "routepf")
            else "fused" if args.method in ("fused", "fusedpf", "fusedmx")
            else "cfdot" if args.method in ("cfdotvpu", "cfdotmxu")
            else "segment_sum")
    print(json.dumps({
        "micro": kind, "method": args.method,
        "platform": platform, "scale": args.scale, "ne": int(g.ne),
        "ms_per_rep": round(slope * 1e3, 4), "gteps": round(gteps, 4),
        "intercept_ms": round(icpt * 1e3, 2),
        "compile_s": round(compile_s, 1),
        "raw": {str(n): round(t, 4) for n, t in zip(xs, ts)},
    }), flush=True)
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=int, default=17)
    ap.add_argument("--ef", type=int, default=16)
    ap.add_argument("--reps", type=int, nargs="+", default=[1, 8, 32])
    ap.add_argument("--methods", nargs="+",
                    default=["mxsum", "mxscan", "scan"],
                    help="race order; the risky method belongs LAST "
                         "(scan — the one observed tunnel-wedger; "
                         "mxscan is the new Pallas kernel, second to "
                         "last)")
    ap.add_argument("--method", help="(worker mode) single method to time")
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--per-method-s", type=int,
                    default=_env_int("LUX_MICRO_METHOD_S", 240),
                    help="abandon a worker after this long (wedge bound)")
    ap.add_argument("--outdir", default="/tmp/lux_micro_race")
    args = ap.parse_args(argv)
    if args.worker:
        return worker_main(args)

    # parent: no jax anywhere.  Relay gate first (milliseconds, not a
    # claim-retry hour) unless we're deliberately on CPU.
    on_cpu = os.environ.get("JAX_PLATFORMS", "") == "cpu"
    if not on_cpu:
        import socket

        try:
            socket.create_connection(("127.0.0.1", 8083), timeout=3).close()
        except OSError:
            print("relay down (127.0.0.1:8083) — nothing to race", flush=True)
            return 1
    os.makedirs(args.outdir, exist_ok=True)
    rows: dict[str, dict] = {}
    for m in args.methods:
        out_path = os.path.join(args.outdir, f"micro_{m}.out")
        cmd = [sys.executable, os.path.abspath(__file__), "--worker",
               "--method", m, "--scale", str(args.scale),
               "--ef", str(args.ef), "--reps",
               *[str(n) for n in args.reps]]
        t0 = time.monotonic()
        # Popen dups the descriptors into the child, so the with block
        # may close ours even when the worker is abandoned mid-write
        with open(out_path, "wb") as out, \
                open(out_path + ".err", "wb") as err:
            proc = subprocess.Popen(cmd, stdout=out, stderr=err,
                                    cwd=os.path.dirname(
                                        os.path.abspath(__file__)),
                                    start_new_session=True)
            while time.monotonic() - t0 < args.per_method_s:
                if proc.poll() is not None:
                    break
                time.sleep(1)
            abandoned = proc.poll() is None
        with open(out_path, "rb") as f:
            text = f.read().decode("utf8", "replace")
        for line in text.splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    rows[m] = json.loads(line)
                except ValueError:
                    pass
            elif line:
                print(line, flush=True)
        if m in rows:
            print(json.dumps(rows[m]), flush=True)
        if abandoned:
            # never kill: a wedged worker may still hold the tunnel claim
            # and must release it cleanly on its own (bench.py watchdog
            # semantics).  Stop racing — the tunnel is suspect now.
            print(f"# {m} ABANDONED after {args.per_method_s}s (pid "
                  f"{proc.pid} left to unwind); stopping race", flush=True)
            break
        if m not in rows:
            print(f"# {m} produced no measurement (rc={proc.returncode}; "
                  f"see {out_path}.err)", flush=True)
    if not rows:
        print("micro race: no measurements", flush=True)
        return 1
    # winner = fastest SUM strategy (gather rows time the other
    # hot-loop half; they inform the layout choice, not the method)
    timed = {m: r["ms_per_rep"] for m, r in rows.items()
             if r.get("ms_per_rep", 0) > 0
             and m not in ("gather", "gatherc", "route", "routepf",
                           "fused", "fusedpf", "fusedmx",
                           "cfdotvpu", "cfdotmxu")}
    winner = min(timed, key=timed.get) if timed else None
    platforms = {r.get("platform") for r in rows.values()}
    record = {
        "winner": winner, "scale": args.scale,
        "ms_per_rep": {m: r["ms_per_rep"] for m, r in rows.items()},
        "gteps": {m: r["gteps"] for m, r in rows.items()},
    }
    print(f"# micro race winner: {winner} ({record['ms_per_rep']})",
          flush=True)
    if platforms & {"tpu", "axon"}:
        from lux_tpu.engine import methods  # no-jax import (os/json only)

        methods.record_overlay_entry("tpu:micro_sum", record)
        # exactness-gated flavor pairs (ISSUE 7): a pair only banks a
        # DECISION when both members measured (each worker already
        # refused to emit a row that failed its oracle gate)
        t_pf = rows.get("fusedpf", {}).get("ms_per_rep", 0)
        t_mx = rows.get("fusedmx", {}).get("ms_per_rep", 0)
        if t_pf > 0 and t_mx > 0:
            red = "mxreduce" if t_mx <= t_pf else "group"
            methods.record_overlay_entry(methods.REDUCE_MODE_KEY, red)
            methods.record_overlay_entry(
                "tpu:micro_reduce",
                {"scale": args.scale, "winner": red,
                 "ms_per_rep": {"group": t_pf, "mxreduce": t_mx}})
        t_vpu = rows.get("cfdotvpu", {}).get("ms_per_rep", 0)
        t_mxu = rows.get("cfdotmxu", {}).get("ms_per_rep", 0)
        if t_vpu > 0 and t_mxu > 0:
            dot = "mxu" if t_mxu <= t_vpu else "vpu"
            methods.record_overlay_entry(methods.CF_DOT_KEY, dot)
            methods.record_overlay_entry(
                "tpu:micro_cfdot",
                {"scale": args.scale, "winner": dot,
                 "ms_per_rep": {"vpu": t_vpu, "mxu": t_mxu}})
        # the three-way tpu:sum scan-family race (ISSUE 11): the banked
        # winner is followed by engine/methods.sum_mode on the csc
        # gather-apply paths.  Banked ONLY when ALL THREE flavors
        # measured (each already oracle-gated in its worker): a partial
        # race must not retire the shipped VPU default on a guess.
        fam = {m: rows.get(m, {}).get("ms_per_rep", 0)
               for m in methods.SUM_MODES}
        if all(t > 0 for t in fam.values()):
            win = min(fam, key=fam.get)
            # never clobbers a measured blanket 'scatter' winner (this
            # race does not time scatter)
            methods.record_sum_family_winner(win)
            methods.record_overlay_entry(
                "tpu:micro_scan",
                {"scale": args.scale, "winner": win, "ms_per_rep": fam})
        else:
            missing = [m for m, t in fam.items() if t <= 0]
            print(f"# tpu:sum NOT banked (unmeasured flavors: {missing})",
                  flush=True)
    else:
        print(f"# not on tpu ({platforms}); overlay not recorded", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
