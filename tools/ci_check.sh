#!/bin/bash
# CI gate: static analysis + native sanitizer smoke + the fast tier-1
# subset — the pre-merge battery that needs NO accelerator and finishes
# in minutes (the full tier-1 suite is the ROADMAP.md command).
#
# Usage: bash tools/ci_check.sh [logdir]
# Exit: non-zero if ANY stage fails (stages run to completion so one log
# shows everything that is broken, like chip_day's continue-on-failure).
set -u
cd "$(dirname "$0")/.."
LOG=${1:-/tmp/lux_ci_$(date +%H%M%S)}
mkdir -p "$LOG"
echo "ci logs -> $LOG"
FAILED=0

stage() {  # stage <name> <timeout_s> <cmd...>
  local name=$1 to=$2; shift 2
  echo "=== $name"
  if timeout "$to" "$@" > "$LOG/$name.out" 2>&1; then
    echo "    ok"
  else
    echo "    FAIL (rc=$?); tail:"; tail -5 "$LOG/$name.out" | sed 's/^/    /'
    FAILED=1
  fi
}

# 1) luxcheck: the whole shipped surface, milliseconds, no jax import
stage luxcheck 120 python tools/luxcheck.py --all

# 1b) luxaudit fast tier: trace/lower the pull + push + routed-pf entry
#     points and audit the IR (retrace/donation/collective/VMEM/hbm
#     invariants) — the jaxpr-level half of the static gate
stage luxaudit 600 python tools/luxaudit.py --fast

# 1c) luxproto: exhaustive protocol model checking — election fencing,
#     two-phase publish tokens, the generation line, journal crash-
#     atomicity — each model checked to exhaustion, the broken twins
#     REQUIRED to fail (silent-pass tripwire), and the recorded soak
#     fixtures replayed through the models' legality rules.  Jax-free
#     like stage 1, sub-second, [PASS]-gated.
stage proto_smoke 120 bash -c '
set -e
out=$(python tools/luxproto.py --all --twins \
      --replay tests/data/chaos_soak_seed0.json \
               tests/data/chaos_soak_failover_seed3.json \
               tests/data/autopilot_soak_seed0.json)
echo "$out" | grep -q "\[PASS\] luxproto" || { echo "luxproto failed"; exit 1; }
echo "$out"
'

# 1d) luxguard smoke: the LUX-G/LUX-R synthetic-positive twins MUST
#     fire (a known-bad snippet coming back clean means the checker
#     rotted, not the code), and both suppression baselines must be
#     well-formed and stale-free.  The families' repo-wide sweep itself
#     runs inside stage 1's luxcheck --all.  Jax-free, [PASS]-gated.
stage guard_smoke 120 bash -c '
set -e
out=$(python tools/luxcheck.py --twins)
echo "$out" | grep -q "\[PASS\] luxcheck twins" || { echo "twins failed"; exit 1; }
echo "$out"
out=$(python tools/luxcheck.py --check-baselines)
echo "$out" | grep -q "\[PASS\] baselines" || { echo "baselines failed"; exit 1; }
echo "$out"
'

# 2) native sanitizer smoke: TSan (the multithreaded colorer, bitwise
#    vs serial), ASan + UBSan (lux_io's pread64 offset arithmetic).
#    Skipped quietly when the toolchain can't build them (the pytest
#    twin tests/test_native.py -k 'tsan or asan' skips the same way).
if make -C lux_tpu/native sanitize > "$LOG/san_build.out" 2>&1; then
  stage tsan  600 lux_tpu/native/build/lux-tsan-check  all
  stage asan  300 lux_tpu/native/build/lux-asan-check  all
  stage ubsan 300 lux_tpu/native/build/lux-ubsan-check all
else
  echo "=== sanitizers: toolchain can't build them — skipped"
  tail -3 "$LOG/san_build.out" | sed 's/^/    /'
fi

# 3) routed-pf interpret smoke: the pass-fused replay (ops/expand.to_pf)
#    must stay bitwise-identical to the direct gather on CPU — the
#    correctness gate that never waits on a chip window
stage routedpf_smoke 300 env JAX_PLATFORMS=cpu python -c "
import numpy as np, jax, jax.numpy as jnp
from lux_tpu.graph import generate
from lux_tpu.graph.shards import build_pull_shards
from lux_tpu.engine import pull
from lux_tpu.models.pagerank import PageRankProgram
from lux_tpu.ops import expand as E
g = generate.rmat(8, 8, seed=11)
sh = build_pull_shards(g, 2)
prog = PageRankProgram(nv=sh.spec.nv)
arr = jax.tree.map(jnp.asarray, sh.arrays)
s0 = pull.init_state(prog, arr)
d = pull.run_pull_fixed(prog, sh.spec, arr, s0, 3, method='scan')
r = pull.run_pull_fixed(prog, sh.spec, arr, s0, 3, method='scan',
                        route=E.plan_expand_shards(sh, pf=True))
assert (np.asarray(d) == np.asarray(r)).all(), 'routed-pf != direct'
print('routed-pf bitwise == direct')
"

# 3a) mxreduce interpret smoke (ISSUE 7): the MXU-resident segmented
#     reduction fused into the final routed kernel must match the plain
#     fused path — bitwise for the f32-exact integer-valued case — and
#     its accounted sweeps must drop below the fused-pf accounting
stage mxreduce_smoke 300 env JAX_PLATFORMS=cpu python -c "
import numpy as np, jax.numpy as jnp
from lux_tpu.ops import expand as E
from lux_tpu.utils import roofline
rng = np.random.default_rng(0)
m, nseg, ss = 700, 37, 500
dst = np.repeat(np.arange(nseg), rng.multinomial(m, np.ones(nseg)/nseg))
src = rng.integers(0, ss, m)
o = np.argsort(dst, kind='stable')
sp, dl = src[o].astype(np.int64), dst[o].astype(np.int64)
st, arr = E.plan_fused(sp, dl, m, ss, 64, 'sum')
sm, am = E.plan_fused(sp, dl, m, ss, 64, 'sum', mx=True)
x = jnp.asarray(rng.integers(-999, 999, ss).astype(np.float32))
ref = np.asarray(E.apply_fused(x, st, [jnp.asarray(a) for a in arr], interpret=True))
got = np.asarray(E.apply_fused(x, sm, [jnp.asarray(a) for a in am], interpret=True))
assert (ref[:nseg] == got[:nseg]).all(), 'mxreduce != fused (f32-exact)'
pf = roofline.routed_hbm_passes(E.to_pf((st, arr))[0])
mx = roofline.routed_hbm_passes(sm)
assert mx['total'] < pf['total'], (mx, pf)
print('mxreduce bitwise (f32-exact) == fused;',
      'sweeps', pf['total'], '->', mx['total'])
"

# 3a1) mxscan interpret smoke (ISSUE 11): the blocked MXU segmented
#      scan must be BITWISE equal to the VPU ladder for int32 and
#      min/max, within the documented tolerance for f32 sums, and the
#      row_ptr-free bucketed path must agree too
stage mxscan_smoke 300 env JAX_PLATFORMS=cpu python -c "
import numpy as np, jax.numpy as jnp
from lux_tpu.graph import generate
from lux_tpu.graph.shards import build_pull_shards
from lux_tpu.ops import segment
g = generate.rmat(8, 8, seed=11)
sh = build_pull_shards(g, 1)
a = sh.arrays
rng = np.random.default_rng(0)
rp, hf = jnp.asarray(a.row_ptr[0]), jnp.asarray(a.head_flag[0])
dl = jnp.asarray(a.dst_local[0])
e_pad = a.src_pos.shape[1]
iv = jnp.asarray(rng.integers(-999, 999, e_pad).astype(np.int32))
for fn in (segment.segment_sum_csc, segment.segment_min_csc,
           segment.segment_max_csc):
    ref = np.asarray(fn(iv, rp, hf, dl, method='scan'))
    got = np.asarray(fn(iv, rp, hf, dl, method='mxscan'))
    assert (ref == got).all(), fn
fv = jnp.asarray(rng.random(e_pad).astype(np.float32))
ref = np.asarray(segment.segment_sum_csc(fv, rp, hf, dl, method='scan'))
got = np.asarray(segment.segment_sum_csc(fv, rp, hf, dl, method='mxscan'))
assert np.allclose(ref, got, rtol=1e-4, atol=1e-5)
from lux_tpu.parallel.ring import mark_bucket_heads
V, m, B = 37, 60, 128
dlb = np.sort(rng.integers(0, V, m)).astype(np.int32)
dst = np.full(B, V, np.int32); dst[:m] = dlb
head = np.zeros(B, bool); mark_bucket_heads(head, dlb)
vals = np.zeros(B, np.float32); vals[:m] = rng.random(m) + 0.5
r2 = np.asarray(segment.segment_reduce_by_ends(
    jnp.asarray(vals), jnp.asarray(head), jnp.asarray(dst), V,
    reduce='sum', method='scan'))
g2 = np.asarray(segment.segment_reduce_by_ends(
    jnp.asarray(vals), jnp.asarray(head), jnp.asarray(dst), V,
    reduce='sum', method='mxscan'))
assert np.allclose(r2, g2, rtol=1e-5, atol=1e-6)
print('mxscan bitwise (int/min/max) == scan; f32 within tolerance')
"

# 3a2) mutate smoke (ISSUE 10): small graph -> 1% churn via the
#      delta-log -> warm overlay refresh -> compact -> the refreshed
#      distances AND the compacted graph arrays must be bitwise equal
#      to a from-scratch rebuild of the merged graph
stage mutate_smoke 300 env JAX_PLATFORMS=cpu python -c "
import numpy as np
from lux_tpu.graph import generate
from lux_tpu.mutate import MutableGraph
from lux_tpu.mutate import refresh as R
from lux_tpu.models.sssp import SSSPProgram, bfs_reference
from lux_tpu.engine import push
g = generate.rmat(9, 8, seed=3)
rng = np.random.default_rng(0)
mg = MutableGraph(g, num_parts=2)
start = int(np.argmax(np.bincount(g.col_idx, minlength=g.nv)))
st, _, _ = push.run_push(SSSPProgram(nv=g.nv, start=start), mg.push_shards)
d0 = mg.push_shards.scatter_to_global(np.asarray(st))
k = g.ne // 200
dele = rng.choice(g.ne, k, replace=False)
mg.apply(g.col_idx[dele], g.dst_of_edges()[dele], np.zeros(k, np.int8))
mg.apply(rng.integers(0, g.nv, k), rng.integers(0, g.nv, k), np.ones(k, np.int8))
d1, rounds = R.refresh_sssp(mg, d0, start)
merged = mg.log.merged_graph()
assert np.array_equal(d1, bfs_reference(merged, start)), 'refresh != cold'
rep = mg.compact()
assert np.array_equal(mg.base.col_idx, merged.col_idx), 'compact != merged'
print('mutate smoke: refresh bitwise in', rounds, 'rounds;',
      'invalidated', rep['invalidation']['changed'], '/',
      rep['invalidation']['parts'], 'buckets')
"

# 3a3) merge smoke (ISSUE 17): the asynchronous reduction tree must be
#      BITWISE the bulk merge for the integer min monoid (SSSP end to
#      end at an odd part count — the bye path), and a churn overlay on
#      the fused plan families must land on the routed-expand overlay's
#      exact bits — the luxmerge correctness gate, [PASS]-gated
stage merge_smoke 300 bash -c '
set -e
out=$(JAX_PLATFORMS=cpu python -c "
import numpy as np
from lux_tpu.engine import pull, push
from lux_tpu.graph import generate
from lux_tpu.graph.push_shards import build_push_shards
from lux_tpu.models.components import MaxLabelProgram
from lux_tpu.models.sssp import SSSPProgram, bfs_reference
from lux_tpu.mutate import MutableGraph
from lux_tpu.ops import expand
g = generate.rmat(8, 8, seed=11)
shm = build_push_shards(g, 3)
prog = SSSPProgram(nv=g.nv, start=0)
outs = {}
for mode in (\"bulk\", \"tree\"):
    st, _, _ = push.run_push(prog, shm, merge=mode)
    outs[mode] = shm.scatter_to_global(np.asarray(st))
assert np.array_equal(outs[\"bulk\"], outs[\"tree\"]), \"tree != bulk\"
got = np.where(outs[\"tree\"] >= prog.inf, g.nv, outs[\"tree\"])
assert np.array_equal(got, bfs_reference(g, 0)), \"tree != oracle\"
mg = MutableGraph(g, num_parts=2)
rng = np.random.default_rng(0)
k = 20
dele = rng.choice(g.ne, k, replace=False)
mg.apply(g.col_idx[dele], g.dst_of_edges()[dele], np.zeros(k, np.int8))
mg.apply(rng.integers(0, g.nv, k), rng.integers(0, g.nv, k),
         np.ones(k, np.int8))
sh = mg.pull_shards
ov = mg.pull_overlay()
cprog = MaxLabelProgram()
s0 = pull.init_state(cprog, sh.arrays)
a = pull.run_pull_fixed(cprog, sh.spec, sh.arrays, s0, 3,
                        method=\"scan\", overlay=ov,
                        route=expand.plan_expand_shards(sh, pf=True))
for name, pl in ((\"fused-pf\", expand.to_pf(
        expand.plan_fused_shards(sh, reduce=\"max\"))),
                 (\"fused-mx\", expand.plan_fused_shards(
        sh, reduce=\"max\", mx=True))):
    b = pull.run_pull_fixed(cprog, sh.spec, sh.arrays, s0, 3,
                            method=\"scan\", overlay=ov, route=pl)
    assert np.array_equal(np.asarray(a), np.asarray(b)), name
print(\"[PASS] merge smoke: tree==bulk bitwise (3 parts, bye path);\",
      \"overlay on fused-pf/fused-mx == expand overlay bitwise\")
")
echo "$out" | grep -q "\[PASS\] merge smoke" || { echo "merge smoke failed"; exit 1; }
echo "$out"
'

# 3b) obs smoke: a shell-seeded event log must round-trip through
#     luxview (the post-mortem path chip_day's EXIT trap depends on),
#     jax-free end to end; LUX-O itself runs inside stage 1's luxcheck
stage obs_smoke 120 bash -c '
set -e
export LUX_OBS_RUN_ID=ci_obs_$$
sid=$(python tools/obs_span.py begin step.ci timeout_s=9)
python tools/obs_span.py end "$sid" --rc 0
python tools/obs_span.py begin step.open_forever > /dev/null
out=$(python tools/luxview.py "$LUX_OBS_RUN_ID")
echo "$out" | grep -q "step.ci" || { echo "missing span"; exit 1; }
echo "$out" | grep -q "OPEN" || { echo "missing post-mortem"; exit 1; }
'

# 3c) fleet smoke (ISSUE 8): a 2-worker loopback fleet must answer,
#     republish with zero shed, survive a worker kill, and leave a
#     luxview-renderable event log — the whole controller/worker split
#     end to end on CPU
stage fleet_smoke 600 bash -c '
set -e
export LUX_OBS_RUN_ID=ci_fleet_$$
JAX_PLATFORMS=cpu python -c "
import numpy as np, tempfile, time
from lux_tpu.graph import generate
from lux_tpu.graph.format import write_lux
from lux_tpu.graph.shards import build_pull_shards
from lux_tpu.models.sssp import bfs_reference
from lux_tpu.serve.fleet.bench import start_fleet
g = generate.rmat(8, 4, seed=4)
snap = tempfile.mktemp(suffix=\".lux\"); write_lux(snap, g)
shards = build_pull_shards(g, 2)
fleet = start_fleet(2, shards=shards, graph_id=\"snap.lux\",
                    mode=\"thread\", buckets=(1, 4))
ctl = fleet.controller
try:
    for s in (0, 3, 7):
        assert np.array_equal(ctl.submit(s).result(timeout=60),
                              bfs_reference(g, s)), s
    rep = ctl.republish(snap, graph_id=\"snap.lux\")
    assert set(rep[\"generations\"].values()) == {1}, rep
    fleet.thread_workers[0].kill()
    time.sleep(0.3)
    for s in (0, 3, 7):
        assert np.array_equal(ctl.submit(s).result(timeout=60),
                              bfs_reference(g, s)), s
    st = ctl.stats()
    assert st[\"shed\"] == 0 and st[\"worker_deaths\"] == 1, st
    print(\"fleet smoke:\", st)
finally:
    fleet.close()
"
out=$(python tools/luxview.py "$LUX_OBS_RUN_ID")
echo "$out" | grep -q "fleet.start" || { echo "missing fleet.start"; exit 1; }
echo "$out" | grep -q "fleet.republish" || { echo "missing republish"; exit 1; }
'

# 3d) live smoke (ISSUE 12): a 2-worker thread-mode LIVE fleet — one
#     write batch admitted at the controller, replicated to every
#     replica, read back with a min_generation bound (read-your-writes)
#     and through the fleet-wide warm refresh, tagged >= the commit
#     generation and bitwise-equal to the merged-graph reference
stage live_smoke 600 env JAX_PLATFORMS=cpu python -c "
import numpy as np
from lux_tpu.graph import generate
from lux_tpu.models.sssp import bfs_reference
from lux_tpu.serve.live.controller import start_live_fleet
from lux_tpu.serve.live.bench import churn_batch
g = generate.rmat(8, 4, seed=4)
fleet = start_live_fleet(2, g, parts=2, cap=256, buckets=(1, 4),
                         standing=(('sssp', 0),))
ctl = fleet.controller
try:
    rng = np.random.default_rng(0)
    src, dst, op = churn_batch(ctl.journal.log, rng, 32)
    rep = ctl.admit_writes(src, dst, op)
    assert rep['generation'] == 1 and len(rep['acked']) == 2, rep
    merged = ctl.journal.log.merged_graph()
    for s in (0, 3, 7):
        f = ctl.submit(s, min_generation=1)
        assert np.array_equal(f.result(timeout=60),
                              bfs_reference(merged, s)), s
        assert f.generation >= 1
    ctl.refresh_fleet()
    allr = ctl.read_standing_all('sssp')
    for wid, ent in allr.items():
        assert ent['generation'] >= 1, wid
        assert np.array_equal(ent['state'], bfs_reference(merged, 0)), wid
    print('live smoke:', ctl.worker_generations())
finally:
    fleet.close()
"

# 3d1) fault smoke (ISSUE 14): a thread-mode live fleet under ONE
#      wire-fault plan (delayed + dropped query frames absorbed by the
#      retry envelope) and ONE controller kill + promotion — zero
#      acked-write loss, bitwise answers after failover, [PASS]-gated
stage fault_smoke 600 bash -c '
set -e
out=$(JAX_PLATFORMS=cpu python -c "
import numpy as np, os, tempfile
from lux_tpu import fault
from lux_tpu.fault.drills import wire_chaos
from lux_tpu.fault.plan import FaultPlan, FaultRule
from lux_tpu.graph import generate
from lux_tpu.models.sssp import bfs_reference
from lux_tpu.serve.live.bench import churn_batch
from lux_tpu.serve.live.controller import (
    promote_live_controller, start_live_fleet)
root = tempfile.mkdtemp(prefix=\"lux_fault_smoke_\")
g = generate.rmat(8, 4, seed=4)
fleet = start_live_fleet(2, g, parts=2, cap=512, buckets=(1, 4),
                         standing=((\"sssp\", 0),), journal_root=root)
ctl = fleet.controller
try:
    # wire-fault plan: every query frame delayed, first one dropped
    fault.install(FaultPlan([
        FaultRule(\"wire.send\", \"drop\", op=\"query\", count=1,
                  owner=\"controller\"),
        FaultRule(\"wire.recv\", \"delay\", op=\"query\", delay_ms=2.0),
    ], name=\"smoke\"))
    rng = np.random.default_rng(0)
    acked = 0
    for i in range(3):
        s, d, o = churn_batch(ctl.journal.log, rng, 16)
        acked = ctl.admit_writes(s, d, o,
                                 write_id=f\"smoke-{i}\")[\"generation\"]
    for s in (0, 3, 7):
        f = ctl.submit_retrying(s, deadline_s=60, attempt_timeout_s=5,
                                min_generation=acked)
        assert np.array_equal(f.result(timeout=0), bfs_reference(
            ctl.journal.log.merged_graph(), s)), s
    plan = fault.active_plan()
    assert plan.total_fired() > 0, \"no fault actually injected\"
    fault.uninstall()
    # controller-restart plan: kill + promote on the journal dir
    ctl.kill()
    eps = [(\"127.0.0.1\", w.port) for w in fleet.thread_workers]
    ctl2, rep = promote_live_controller(
        g, os.path.join(root, \"controller\"), None, eps)
    fleet.controller = ctl2
    assert sorted(rep[\"joined\"]) == [\"w0\", \"w1\"], rep
    assert ctl2.generation() == acked
    assert ctl2.journal.lookup_write(\"smoke-0\") == 1
    merged = ctl2.journal.log.merged_graph()
    for s in (0, 3, 7):
        f = ctl2.submit_retrying(s, deadline_s=60,
                                 min_generation=acked)
        assert np.array_equal(f.result(timeout=0),
                              bfs_reference(merged, s)), s
    print(\"[PASS] fault smoke: gen\", acked, \"failovers\",
          ctl2.stats()[\"failovers\"])
finally:
    fleet.close()
")
echo "$out" | grep -q "\[PASS\] fault smoke" || { echo "fault smoke failed"; exit 1; }
echo "$out"
'

# 3d2) dtrace smoke (ISSUE 15): a 2-worker thread fleet serves a
#      TRACED burst through an injected wire delay; luxstitch must
#      merge the per-process logs into causally-linked timelines
#      (request -> attempt -> worker spans, the injected fault visible
#      with its plan + seed) and luxview must render the cross-process
#      waterfall — the tool half runs JAX-FREE
stage dtrace_smoke 600 bash -c '
set -e
export LUX_OBS_RUN_ID=ci_dtrace_$$
JAX_PLATFORMS=cpu python -c "
import numpy as np
from lux_tpu import fault
from lux_tpu.fault.plan import FaultPlan, FaultRule
from lux_tpu.graph import generate
from lux_tpu.graph.shards import build_pull_shards
from lux_tpu.models.sssp import bfs_reference
from lux_tpu.obs.slo import default_fleet_slos
from lux_tpu.serve.fleet.bench import start_fleet
g = generate.rmat(8, 4, seed=4)
shards = build_pull_shards(g, 2)
fleet = start_fleet(2, shards=shards, graph_id=\"g\", mode=\"thread\",
                    buckets=(1, 4))
ctl = fleet.controller
ctl.set_slos(default_fleet_slos())
try:
    with fault.installed(FaultPlan([FaultRule(
            \"wire.recv\", \"delay\", op=\"query\", delay_ms=3.0)],
            name=\"ci_dtrace\", seed=7)):
        for s in (0, 3, 7, 9):
            f = ctl.submit(s, request_id=f\"ci-{s}\")
            assert np.array_equal(f.result(timeout=60),
                                  bfs_reference(g, s)), s
            assert f.trace_id, \"query was not traced\"
    st = ctl.slo_status()
    assert any(r[\"exemplar_traces\"] for r in st), st
    print(\"[PASS] dtrace burst:\",
          {r[\"name\"]: r[\"verdict\"] for r in st})
finally:
    fleet.close()
"
out=$(python tools/luxstitch.py "$LUX_OBS_RUN_ID")
echo "$out" | grep -q "fleet.request" || { echo "missing request span"; exit 1; }
echo "$out" | grep -q "worker.query" || { echo "missing worker span"; exit 1; }
echo "$out" | grep -q "FAULT wire.recv/delay" || { echo "missing fault point"; exit 1; }
echo "$out" | grep -q "seed=7" || { echo "missing fault seed"; exit 1; }
view=$(python tools/luxview.py "$LUX_OBS_RUN_ID")
echo "$view" | grep -q "## Distributed traces" || { echo "missing luxview section"; exit 1; }
echo "$view" | grep -q "fleet.request" || { echo "luxview missing trace"; exit 1; }
'

# 3d3) autopilot smoke (ISSUE 16): the FULL autonomous loop on a tiny
#      live fleet — a load ramp trips the autoscaler into a previewed
#      scale-up, a controller kill is detected by a STANDBY that wins
#      the fenced election and promotes unattended (the standing-query
#      subscription keeps delivering across the failover via hub
#      rebind), and fat churn batches overflow the delta capacity into
#      an escalated compaction — zero acked-write loss and bitwise
#      reads asserted inside the soak, [PASS]-gated here
stage autopilot_smoke 600 bash -c '
set -e
out=$(JAX_PLATFORMS=cpu python -c "
from lux_tpu.fault.chaos import autopilot_soak
report = autopilot_soak(0, steps=3, scale=6, cap=32, rows=8)
assert report[\"scale_ups\"] >= 1, report
assert report[\"elections\"] == 1 and report[\"winner\"] == 0, report
assert report[\"compactions\"] >= 1, report
assert report[\"sub_delivered\"], report
print(\"[PASS] autopilot smoke: gen\", report[\"generation\"],
      \"scale_ups\", report[\"scale_ups\"],
      \"elections\", report[\"elections\"],
      \"compactions\", report[\"compactions\"],
      \"sub\", report[\"sub_delivered\"])
")
echo "$out" | grep -q "\[PASS\] autopilot smoke" || { echo "autopilot smoke failed"; exit 1; }
echo "$out"
'

# 3d4) pod smoke (ISSUE 19): a 2-PROCESS pod over loopback TCP — each
#      worker in its own OS process with a PRIVATE launcher tmpdir (no
#      shared filesystem by construction), the snapshot streamed to
#      both over the bounded-frame wire, each worker loading only its
#      PlacementTree slice, and the sharded sssp answer BITWISE equal
#      to the single-host run — the placement-tree distribution path
#      end to end, [PASS]-gated
stage pod_smoke 600 bash -c '
set -e
out=$(JAX_PLATFORMS=cpu python -c "
import numpy as np, os, tempfile
from lux_tpu.engine import pull
from lux_tpu.graph import generate
from lux_tpu.graph.format import write_lux
from lux_tpu.graph.shards import build_pull_shards
from lux_tpu.models.sssp import SSSPProgram
from lux_tpu.program.spec import active_changed
from lux_tpu.serve.fleet.launcher import launch_pod_worker
from lux_tpu.serve.fleet.pod import run_pull_pod
g = generate.rmat(9, 8, seed=3)
snap = tempfile.mktemp(suffix=\".lux\"); write_lux(snap, g)
P = 4
sh = build_pull_shards(g, P)
start = int(np.argmax(g.out_degrees()))
prog = SSSPProgram(nv=sh.spec.nv, start=start)
s0 = pull.init_state(prog, sh.arrays)
want, iters = pull.run_pull_until(
    prog, sh.spec, sh.arrays, s0, 10_000, active_changed,
    method=\"auto\")
hs = [launch_pod_worker(f\"ci{i}\") for i in range(2)]
try:
    tmps = [h.tmpdir for h in hs]
    assert len(set(tmps)) == 2 and all(tmps), tmps
    res = run_pull_pod([(\"127.0.0.1\", h.port) for h in hs], snap, P,
                       app=\"sssp\", start=start)
    assert res[\"iters\"] == int(iters), (res[\"iters\"], int(iters))
    assert np.array_equal(res[\"state\"], np.asarray(want)), \"pod != single-host\"
    spans = sorted((w[\"lo\"], w[\"hi\"]) for w in res[\"workers\"].values())
    assert spans == [(0, 2), (2, 4)], spans
    for h in hs:
        assert h.proc.wait(timeout=30.0) == 0
finally:
    for h in hs:
        h.terminate()
assert not any(os.path.exists(t) for t in tmps), tmps
print(\"[PASS] pod smoke: 2 processes, private tmpdirs, snapshot over\",
      \"the wire, sssp bitwise in\", res[\"iters\"], \"iters\")
")
echo "$out" | grep -q "\[PASS\] pod smoke" || { echo "pod smoke failed"; exit 1; }
echo "$out"
'

# 3e) program smoke (ISSUE 13): one spec-only workload end-to-end
#     through the GENERIC driver on a tiny graph — the declarative
#     compiler's whole path (spec -> program -> engine -> [PASS] check)
#     plus the exact two-phase triangle count against its oracle
stage program_smoke 300 bash -c '
set -e
out=$(JAX_PLATFORMS=cpu python -m lux_tpu.apps.run bfs \
      --rmat-scale 7 --rmat-ef 5 --sources 0,3 -check)
echo "$out" | grep -q "\[PASS\] bfs" || { echo "bfs check failed"; exit 1; }
out=$(JAX_PLATFORMS=cpu python -m lux_tpu.apps.run triangles \
      --rmat-scale 7 --rmat-ef 5 -check)
echo "$out" | grep -q "\[PASS\] triangles" || { echo "triangles check failed"; exit 1; }
echo "$out" | grep "unit weights, exact"
'

# 4) fast tier-1 subset: the engine/analysis/native seams this script
#    exists to protect (full suite: ROADMAP.md "Tier-1 verify").
#    Budget sized to measured cost: test_fault.py alone runs ~300 s on
#    this quota-swinging host (live fleets + chaos seeds), on top of
#    the ~600 s the pre-ISSUE-14 subset already used.
stage tier1_fast 1200 env JAX_PLATFORMS=cpu python -m pytest -q \
    -m 'not slow' -p no:cacheprovider \
    tests/test_luxcheck.py tests/test_native.py tests/test_expand.py \
    tests/test_passfuse.py tests/test_mxreduce.py tests/test_mxscan.py \
    tests/test_obs.py tests/test_program.py \
    tests/test_determinism.py tests/test_serve_scheduler.py \
    tests/test_fleet.py tests/test_mutate.py tests/test_live.py \
    tests/test_fault.py tests/test_dtrace.py tests/test_autopilot.py \
    tests/test_merge_tree.py tests/test_placement.py tests/test_pod.py

if [ "$FAILED" -ne 0 ]; then
  echo "ci_check: FAILED (see $LOG)"; exit 1
fi
echo "ci_check: all stages clean"
