#!/usr/bin/env python
"""luxaudit — the jaxpr/HLO-level static auditor (lux_tpu.analysis.ir).

Usage:
    python tools/luxaudit.py --all                 # every audited entry point
    python tools/luxaudit.py --fast                # pull + push + one pf config
    python tools/luxaudit.py --all --json AUDIT_r06.json
    python tools/luxaudit.py --all --families donation,collective
    python tools/luxaudit.py --all --fingerprints  # baseline-entry form

Exit codes: 0 = clean, 1 = findings, 2 = usage.

Where luxcheck (step -3) lints the Python AST in milliseconds, this gate
traces and lowers the REAL engine entry points — pull fixed/until (direct
and routed-pf), the push chunk/step loops, the distributed push engines,
the serve batched steps — over a small fixture graph and audits the IR:

  LUX-J1  retrace stability   (J101 structural drift, J102 unhashable
                               statics, J103 dynamic-knob recompiles)
  LUX-J2  donation            (J201 donated leaf without an
                               input_output_alias in the lowered module)
  LUX-J3  collective order    (J301/J302 collectives under a predicate
                               that is not provably mesh-agreed)
  LUX-J4  VMEM budget         (J401 pass-fused group over the knob budget)
  LUX-J5  HBM-pass accounting (J501/J502 roofline hbm_passes vs the
                               kernels actually traced)

Runs entirely on CPU — chip-day step -3b aborts the window on findings
BEFORE the tunnel is needed; ci_check.sh runs the --fast tier.

Suppression is baseline-only (there is no source line to hang an inline
comment on): tools/luxaudit_baseline.txt, same format and policy as
luxcheck's (<path>:<code>:<fingerprint>  # why — ships EMPTY; stale or
unjustified entries are themselves findings).  Fingerprints hash the
audited target label, so they survive engine edits but die when the
target set changes.
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# This tool NEEDS jax (it traces the real engines) but must never touch
# an accelerator: force the CPU backend and the 8-device virtual mesh
# (tests/conftest.py's harness contract) BEFORE jax initializes.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
sys.path.insert(0, REPO)

DEFAULT_BASELINE = os.path.join("tools", "luxaudit_baseline.txt")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="jaxpr/HLO-level static audit of the engine entry "
                    "points (retrace, donation, collective-order, VMEM "
                    "budget, HBM passes)")
    ap.add_argument("--all", action="store_true",
                    help="audit every entry point (chip-day step -3b)")
    ap.add_argument("--fast", action="store_true",
                    help="pull + push + one pass-fused config (CI tier)")
    ap.add_argument("--families",
                    help="comma-separated subset of "
                         "retrace,donation,collective,vmem,hbm")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="suppressions file (default "
                         f"{DEFAULT_BASELINE}; '' disables)")
    ap.add_argument("--json", dest="json_out",
                    help="write the full audit record (units, timings, "
                         "findings) to this path, e.g. AUDIT_r06.json")
    ap.add_argument("--fingerprints", action="store_true",
                    help="print findings as ready-to-paste baseline "
                         "entries instead of human-readable lines")
    ap.add_argument("--progress",
                    help="append a one-line audit-status record to this "
                         "jsonl file (chip_day passes PROGRESS.jsonl so "
                         "each window's preflight verdict is on the "
                         "round's permanent record)")
    args = ap.parse_args(argv)

    if not (args.all or args.fast):
        ap.print_usage(sys.stderr)
        print("error: give --all or --fast", file=sys.stderr)
        return 2

    import jax

    # persistent compile cache: the dynamic-knob probes (LUX-J103)
    # execute two small compiles; repeat preflights hit the cache
    jax.config.update(
        "jax_compilation_cache_dir",
        os.environ.get("LUX_JAX_CACHE", "/tmp/lux_jax_cache"))

    from lux_tpu.analysis.ir import run_audit

    baseline = None
    if args.baseline:
        b = (args.baseline if os.path.isabs(args.baseline)
             else os.path.join(REPO, args.baseline))
        baseline = b
    families = (tuple(f.strip() for f in args.families.split(",")
                      if f.strip())
                if args.families else None)
    findings, report = run_audit(fast=not args.all,
                                 baseline_path=baseline,
                                 families=families)
    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=1)
            f.write("\n")
    if args.progress:
        import time

        with open(args.progress, "a", encoding="utf-8") as f:
            f.write(json.dumps({
                "ts": time.time(), "tool": "luxaudit",
                "tier": report["tier"], "clean": report["clean"],
                "findings": len(findings),
                "units": len(report["units"]),
            }) + "\n")
    for fi in findings:
        if args.fingerprints:
            print(f"{fi.path}:{fi.code}:{fi.fingerprint()}  # JUSTIFY: "
                  f"{fi.message[:60]}")
        else:
            print(f"{fi.format()}  [{fi.text}]")
    tier = "all" if args.all else "fast"
    n_units = len(report["units"])
    if findings:
        print(f"\nluxaudit: {len(findings)} finding(s) over {n_units} "
              f"audited entry point(s) ({tier} tier) — fix, or baseline "
              "WITH a justification (see docs/ANALYSIS.md)",
              file=sys.stderr)
        return 1
    print(f"luxaudit: clean ({n_units} entry point(s), {tier} tier, "
          f"jax {report['jax']})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
