#!/usr/bin/env python
"""Full benchmark table: all four apps on synthetic workloads.

Fills the BASELINE.md table (the per-app GTEPS derivations of SURVEY.md §6).
Unlike bench.py (ONE JSON line for the driver), this prints a markdown
table.  Usage:

    python tools/bench_all.py [--scale 18] [--parts 1] [--iters 10]
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=int, default=18)
    ap.add_argument("--ef", type=int, default=16)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--parts", type=int, default=1)
    ap.add_argument("--delta", type=int, default=8,
                    help="bucket width for the weighted-SSSP delta row")
    ap.add_argument("--routed", action="store_true",
                    help="add routed-hot-loop rows (ops/expand.py plans, "
                         "disk-cached) next to the direct rows for "
                         "pagerank/sssp/components/colfilter")
    args = ap.parse_args(argv)

    import dataclasses

    import jax
    import jax.numpy as jnp

    from lux_tpu.graph import generate
    from lux_tpu.graph.push_shards import build_push_shards
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.models import colfilter as cf, components, pagerank as pr, sssp

    rows = []

    def timed(name, fn, edges, base=0.0):
        """The model wrappers end in scatter_to_global(np.asarray(...)) — a
        full device->host transfer, so this timing is honest even where
        block_until_ready is not (the axon tunnel acks readiness early;
        see tools/tpu_timing_probe.py).  ``base`` is a measured 0-iteration
        run of the same app: compile-free dispatch + the same transfer,
        subtracted so GTEPS reflects iteration work, not tunnel latency."""
        t0 = time.perf_counter()
        out = fn()
        dt = time.perf_counter() - t0
        # floor at 10% of raw: when base ~= dt the difference is noise, and
        # an honest-but-noisy number must not explode into a absurd GTEPS
        net = max(dt - base, 0.1 * dt)
        gteps = edges / net / 1e9
        rows.append((name, dt, net, gteps))
        print(f"{name}: {dt:.3f}s raw, {net:.3f}s net  {gteps:.3f} GTEPS",
              flush=True)
        return out

    def device_pull(shards):
        """Pre-place shard arrays on device OUTSIDE the timed region (the
        model wrappers' jnp.asarray is then a no-op — host->device copies
        must not count toward GTEPS, same as bench.py)."""
        return dataclasses.replace(
            shards, arrays=jax.tree.map(jnp.asarray, shards.arrays)
        )

    def device_push(shards):
        return dataclasses.replace(
            shards,
            pull=device_pull(shards.pull),
            parrays=jax.tree.map(jnp.asarray, shards.parrays),
        )

    g = generate.rmat(args.scale, args.ef, seed=0)
    print(f"# graph: rmat{args.scale} nv={g.nv} ne={g.ne} "
          f"platform={jax.devices()[0].platform} parts={args.parts}")

    host_pull = build_pull_shards(g, args.parts)
    host_push = build_push_shards(g, args.parts)
    pr_route = push_route = None
    if args.routed:
        from lux_tpu.ops import expand

        t0 = time.perf_counter()
        pr_route = expand.plan_expand_shards_cached(host_pull)
        # device-resident once, like the shard arrays (H2D must not land
        # inside the timed region); the push layout embeds the SAME pull
        # layout, so one plan serves both
        pr_route = (pr_route[0], jax.tree.map(jnp.asarray, pr_route[1]))
        jax.block_until_ready(pr_route[1])
        push_route = pr_route
        print(f"# routed plan ready in {time.perf_counter()-t0:.0f}s",
              flush=True)
    pull_sh = device_pull(host_pull)
    push_sh = device_push(host_push)

    # warm with IDENTICAL args: num_iters is a static compile-cache key
    pr.pagerank(pull_sh, args.iters, args.parts)
    pr.pagerank(pull_sh, 0, args.parts)  # warm the 0-iter baseline program
    t0 = time.perf_counter()
    pr.pagerank(pull_sh, 0, args.parts)
    base = time.perf_counter() - t0  # dispatch + full-state D2H, no work
    print(f"# 0-iteration baseline (dispatch + state transfer): {base:.3f}s",
          flush=True)
    timed("pagerank", lambda: pr.pagerank(pull_sh, args.iters, args.parts),
          args.iters * g.ne, base)
    if pr_route is not None:
        pr.pagerank(pull_sh, args.iters, args.parts, route=pr_route)  # warm
        timed("pagerank-routed",
              lambda: pr.pagerank(pull_sh, args.iters, args.parts,
                                  route=pr_route),
              args.iters * g.ne, base)
    sssp.sssp(push_sh, start=0, num_parts=args.parts)  # warm
    timed("sssp", lambda: sssp.sssp(push_sh, start=0, num_parts=args.parts),
          g.ne, base)
    if push_route is not None:
        sssp.sssp(push_sh, start=0, num_parts=args.parts, route=push_route)
        timed("sssp-routed",
              lambda: sssp.sssp(push_sh, start=0, num_parts=args.parts,
                                route=push_route),
              g.ne, base)
    components.connected_components_push(push_sh, num_parts=args.parts)  # warm
    timed("components",
          lambda: components.connected_components_push(push_sh, num_parts=args.parts),
          g.ne, base)
    if push_route is not None:
        components.connected_components_push(push_sh, num_parts=args.parts,
                                             route=push_route)
        timed("components-routed",
              lambda: components.connected_components_push(
                  push_sh, num_parts=args.parts, route=push_route),
              g.ne, base)

    # weighted SSSP: chaotic relaxation vs delta-stepping on the SAME
    # graph/layout — GTEPS over edges ACTUALLY traversed (the engines'
    # exact counter), so the delta row shows the algorithmic win, not
    # just wall time
    from lux_tpu.engine import delta as delta_mod
    from lux_tpu.engine import push as push_eng

    import numpy as np

    gd = generate.rmat(args.scale, args.ef, seed=0, weighted=True,
                       max_weight=100)
    wpush = device_push(build_push_shards(gd, args.parts))
    wprog = sssp.WeightedSSSPProgram(nv=wpush.spec.nv, start=0)
    for name, run in (
        ("sssp-w-chaotic",
         lambda: push_eng.run_push(wprog, wpush)),
        (f"sssp-w-delta{args.delta}",
         lambda: delta_mod.run_push_delta(wprog, wpush, args.delta)),
    ):
        _, _, ed = run()  # warm; the exact edge counter is deterministic
        traversed = push_eng.edges_total(ed)
        # same full-state D2H ending as every other row, so subtracting
        # the shared `base` stays honest and the rows are comparable
        timed(f"{name} ({traversed} edges)",
              lambda run=run: wpush.scatter_to_global(np.asarray(run()[0])),
              traversed, base)

    gw = generate.bipartite_ratings(
        (1 << args.scale) // 2, (1 << args.scale) // 2,
        (1 << args.scale) * args.ef // 2, seed=0,
    )
    host_cf = build_pull_shards(gw, args.parts)
    cf_route = None
    if args.routed:
        from lux_tpu.ops import expand

        cf_route = expand.plan_cf_route_shards_cached(host_cf)
        cf_route = (cf_route[0], jax.tree.map(jnp.asarray, cf_route[1]))
        jax.block_until_ready(cf_route[1])
    cf_sh = device_pull(host_cf)
    cf.colfilter(cf_sh, args.iters, args.parts)  # warm (same static args)
    cf.colfilter(cf_sh, 0, args.parts)
    t0 = time.perf_counter()
    cf.colfilter(cf_sh, 0, args.parts)
    cf_base = time.perf_counter() - t0  # CF state is (V, K): own baseline
    timed("colfilter", lambda: cf.colfilter(cf_sh, args.iters, args.parts),
          args.iters * gw.ne, cf_base)
    if cf_route is not None:
        cf.colfilter(cf_sh, args.iters, args.parts, route=cf_route)  # warm
        timed("colfilter-routed",
              lambda: cf.colfilter(cf_sh, args.iters, args.parts,
                                   route=cf_route),
              args.iters * gw.ne, cf_base)

    print("\n| app | raw s | net s | GTEPS |")
    print("|---|---|---|---|")
    for name, dt, net, gteps in rows:
        print(f"| {name} | {dt:.3f} | {net:.3f} | {gteps:.3f} |")
    return 0


if __name__ == "__main__":
    sys.exit(main())
