#!/usr/bin/env python
"""lux_tpu graph converter CLI — text edge list -> `.lux` CSC binary.

Flag parity with the reference converter (tools/converter.cc: -nv -ne
-input -output), plus -weighted.  Prefers the native C++ counting-sort
converter (lux_tpu/native/build/lux-convert); falls back to NumPy.
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-nv", type=int, required=True, help="number of vertices")
    ap.add_argument("-ne", type=int, required=True, help="number of edges")
    ap.add_argument("-input", required=True, help="text edge list path")
    ap.add_argument("-output", required=True, help="output .lux path")
    ap.add_argument("-weighted", action="store_true")
    ap.add_argument(
        "--python", action="store_true", help="force the NumPy fallback path"
    )
    args = ap.parse_args(argv)

    if not args.python:
        from lux_tpu import native

        native.get_lib()  # triggers a build if needed
        if os.path.exists(native.CONVERTER_PATH):
            cmd = [
                native.CONVERTER_PATH, "-nv", str(args.nv), "-ne", str(args.ne),
                "-input", args.input, "-output", args.output,
            ] + (["-weighted"] if args.weighted else [])
            return subprocess.call(cmd)

    from lux_tpu.graph.csc import from_edge_list
    from lux_tpu.graph.format import read_edge_list_text, write_lux

    src, dst, w = read_edge_list_text(args.input, weighted=args.weighted)
    if len(src) != args.ne:
        print(f"expected {args.ne} edges, parsed {len(src)}", file=sys.stderr)
        return 1
    g = from_edge_list(src, dst, args.nv, weights=w)
    write_lux(args.output, g)
    print(f"wrote {args.output}: nv={g.nv} ne={g.ne}"
          + (" (weighted)" if args.weighted else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
