"""Fleet saturation benchmark: ramp offered QPS to the throughput knee.

Starts a 1/2/4-worker loopback serving fleet (lux_tpu.serve.fleet) on
one rmat graph — worker processes by default, shared-nothing, CPU by
design — and ramps an open-loop query load until the fleet stops
sustaining it.  Emits one bench.py-parsable JSON line per fleet width:

  * ``sssp_fleet_qps_w{W}_rmat{scale}_cpu`` — goodput QPS at the
    measured knee (value) with p50/p99 latency at the knee, the full
    per-level ramp table, and the controller's fleet counters
    (shed/rerouted/worker_deaths).

The acceptance bar this driver tracks: 2 workers beat 1 worker on
aggregate knee QPS (the controller/worker split actually scales), with
every controller/worker phase visible as luxtrace spans under ONE
fleet-wide run id (tools/luxview.py renders the whole fleet timeline).

Usage:
  python tools/fleet_bench.py [--rmat-scale 12] [--rmat-ef 8]
      [--workers 1,2,4] [--mode proc|thread] [--buckets 1,8]
      [--start-qps 8] [--growth 1.6] [--levels 12] [--window-s 1.5]
      [--graph path.lux] [--min-scaleup 0]

A nonzero --min-scaleup turns the run into a gate: exit 1 when
knee(2w)/knee(1w) falls below it.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    # scale 12 by default: per-query engine work must dominate the
    # controller's per-request Python cost, or the knee measures the
    # client, not the fleet (measured: at scale 10 a 2-core box caps
    # ~340 QPS on the controller regardless of width; at 12 the workers
    # are engine-bound and the width ramp is clean)
    ap.add_argument("--rmat-scale", type=int, default=12)
    ap.add_argument("--rmat-ef", type=int, default=8)
    ap.add_argument("--workers", default="1,2,4",
                    help="comma list of fleet widths to ramp")
    ap.add_argument("--mode", default="proc", choices=["proc", "thread"])
    ap.add_argument("--num-parts", type=int, default=1)
    ap.add_argument("--buckets", default="1,8")
    ap.add_argument("--start-qps", type=float, default=8.0)
    ap.add_argument("--growth", type=float, default=1.6)
    ap.add_argument("--levels", type=int, default=12)
    ap.add_argument("--window-s", type=float, default=1.5)
    ap.add_argument("--graph", default="",
                    help="existing .lux snapshot (overrides --rmat-*)")
    ap.add_argument("--no-pin", action="store_true",
                    help="do NOT pin one core per worker (pinning is the "
                         "default: a replica is a fixed-size unit, so the "
                         "width ramp measures scale-out, not XLA's thread "
                         "pool re-spreading over the box)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--min-scaleup", type=float, default=0.0,
                    help="exit 1 if knee(2w)/knee(1w) < this (CI gate)")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")  # fleet is CPU-native

    from lux_tpu import obs
    from lux_tpu.serve.fleet.bench import measure_fleet_saturation

    widths = tuple(int(w) for w in args.workers.split(",") if w.strip())
    print(f"# fleet_bench: scale={args.rmat_scale} widths={widths} "
          f"mode={args.mode} run_id={obs.run_id()}",
          file=sys.stderr, flush=True)
    res = measure_fleet_saturation(
        scale=args.rmat_scale, ef=args.rmat_ef, workers=widths,
        mode=args.mode, parts=args.num_parts,
        buckets=tuple(int(b) for b in args.buckets.split(",") if b),
        start_qps=args.start_qps, growth=args.growth,
        max_levels=args.levels, window_s=args.window_s, seed=args.seed,
        graph_path=args.graph, pin=not args.no_pin)
    for row in res["rows"]:
        print(json.dumps(row), flush=True)
    knees = res["knees"]
    print("# knees: " + " ".join(
        f"{w}w={knees[w]}" for w in sorted(knees))
        + (f" paired_2v1={res.get('scaleup_2v1')}"
           if "scaleup_2v1" in res else ""),
        file=sys.stderr, flush=True)
    if args.min_scaleup:
        ratio = res.get("scaleup_2v1")
        if ratio is None:
            # configuration failure, not a measured shortfall: the gate
            # needs the paired probe, which needs widths 1 AND 2
            print("# FAIL: --min-scaleup set but the paired 2w/1w probe "
                  "did not run (--workers must include 1 and 2)",
                  file=sys.stderr, flush=True)
            return 1
        if ratio < args.min_scaleup:
            print(f"# FAIL: paired 2w/1w {ratio} < {args.min_scaleup}",
                  file=sys.stderr, flush=True)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
