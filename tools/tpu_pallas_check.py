#!/usr/bin/env python
"""Mosaic compile + tune harness for the Pallas block-CSR kernels.

Run ON the TPU (default env): compiles every kernel with interpret=False,
checks numerics against interpret=True (the CPU-validated reference), then
sweeps (v_blk, t_chunk) on a PageRank iteration and prints a timing table.
This is the hardware-proof step VERDICT r1 #3 asks for; the sweep winner
is auto-recorded to the measured-winners overlay ("tpu:pallas_tiles" in
.lux_winners.json) and becomes every later build_blockcsr's default —
do NOT hand-edit ops/pallas_spmv.py's V_BLK/T_CHUNK constants.

Usage:
    python tools/tpu_pallas_check.py [--scale 18] [--ef 16] [--sweep]
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=int, default=18)
    ap.add_argument("--ef", type=int, default=16)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--sweep", action="store_true",
                    help="sweep v_blk/t_chunk after the compile check")
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from lux_tpu.graph import generate
    from lux_tpu.ops import pallas_spmv as ps

    platform = jax.devices()[0].platform
    print(f"# platform={platform}", flush=True)

    # --- 1) compile check: every op, tiny graph, interpret=False vs True
    g = generate.rmat(10, 8, seed=0)
    bc = ps.build_blockcsr(g)
    rng = np.random.default_rng(3)
    state = jnp.asarray(rng.random(bc.num_vblocks * bc.v_blk, np.float32))
    vals = state[jnp.asarray(bc.e_src_pos)]
    dst = jnp.asarray(bc.e_dst_rel)
    cb, cf = jnp.asarray(bc.chunk_block), jnp.asarray(bc.chunk_first)
    for op in ["sum", "min", "max"]:
        want = ps.spmv_blockcsr(vals, dst, cb, cf, op=op, v_blk=bc.v_blk,
                                num_vblocks=bc.num_vblocks, interpret=True)
        got = ps.spmv_blockcsr(vals, dst, cb, cf, op=op, v_blk=bc.v_blk,
                               num_vblocks=bc.num_vblocks, interpret=False)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5,
            err_msg=f"op={op} mosaic vs interpret",
        )
        print(f"# mosaic compile+numerics OK: op={op}", flush=True)
    # 2-D (CF) variant
    k = 8
    vk = jnp.asarray(rng.random((bc.num_chunks, bc.t_chunk, k), np.float32))
    want = ps.spmv_blockcsr_2d(vk, dst, cb, cf, v_blk=bc.v_blk,
                               num_vblocks=bc.num_vblocks, interpret=True)
    got = ps.spmv_blockcsr_2d(vk, dst, cb, cf, v_blk=bc.v_blk,
                              num_vblocks=bc.num_vblocks, interpret=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4)
    print("# mosaic compile+numerics OK: 2d sum", flush=True)

    if not args.sweep:
        return 0

    # --- 2) tile sweep on a real-size PageRank iteration
    from lux_tpu.models.pagerank import make_pallas_runner

    g = generate.rmat(args.scale, args.ef, seed=0)
    print(f"# sweep graph: nv={g.nv} ne={g.ne}", flush=True)
    rows = []
    for v_blk in (256, 512, 1024):
        for t_chunk in (256, 512, 1024):
            try:
                # dynamic_iters: ONE compile per config (tunnel compiles
                # cost minutes).  Timing ends in a 4-byte fetch and uses
                # the 1-vs-N slope — block_until_ready lies through the
                # tunnel (tools/tpu_timing_probe.py).
                run, s0 = make_pallas_runner(
                    g, v_blk=v_blk, t_chunk=t_chunk, dynamic_iters=True
                )

                def fetch(n):
                    t0 = time.perf_counter()
                    float(jax.device_get(run(s0, n).ravel()[0]))
                    return time.perf_counter() - t0

                fetch(1)  # compile + warm
                t1 = min(fetch(1), fetch(1))
                tn = min(fetch(args.iters), fetch(args.iters))
                per_iter = max((tn - t1) / max(args.iters - 1, 1), 1e-9)
                dt = per_iter * args.iters
                gteps = args.iters * g.ne / dt / 1e9
                rows.append((v_blk, t_chunk, dt, gteps))
                print(f"v_blk={v_blk:5d} t_chunk={t_chunk:5d} "
                      f"{per_iter*1e3:.2f} ms/iter {gteps:.3f} GTEPS",
                      flush=True)
            except Exception as e:  # noqa: BLE001 — record and continue
                print(f"v_blk={v_blk} t_chunk={t_chunk} FAILED: {e}",
                      flush=True)
    if rows:
        best = max(rows, key=lambda r: r[3])
        print(f"# best: v_blk={best[0]} t_chunk={best[1]} {best[3]:.3f} GTEPS")
        # persist so every later build_blockcsr defaults to the measured
        # tiles — an unattended chip window updates the Pallas defaults
        # without a code edit (same contract as bench.py's method winner)
        from lux_tpu.engine.methods import record_overlay_entry

        record_overlay_entry(
            "tpu:pallas_tiles",
            {"v_blk": int(best[0]), "t_chunk": int(best[1])},
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
