"""Shared bare-package stub for the jax-free tools.

`import lux_tpu` runs the package __init__, which imports jax (the
shard_map compat shim).  The preflight/post-mortem tools (luxcheck,
luxview, obs_span) must work in milliseconds on a host whose jax install
or device tunnel is in ANY state, so instead of executing the real
__init__ they register a bare package module pointing at the source
tree; pure-stdlib submodules (lux_tpu.analysis, lux_tpu.obs.recorder)
then import normally.

One copy of the trick lives here — a change to the stub (or to which
modules stay stdlib-pure) happens in one place, not per-tool.  Tools add
their own directory to sys.path before importing this module (they are
run as scripts / loaded by file location, so no package-relative form).
"""
from __future__ import annotations

import importlib
import os
import sys
import types

#: repo root (this file lives in tools/)
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def bare_package() -> str:
    """Register the bare ``lux_tpu`` stub (idempotent: an already
    imported real package — or a previous stub — is left alone).
    Returns the repo root."""
    if "lux_tpu" not in sys.modules:
        sys.path.insert(0, REPO)
        _pkg = types.ModuleType("lux_tpu")
        _pkg.__path__ = [os.path.join(REPO, "lux_tpu")]
        sys.modules["lux_tpu"] = _pkg
    return REPO


def load(modname: str):
    """Import one ``lux_tpu.*`` MODULE under the stub.  The package
    re-exports e.g. the ``recorder()`` accessor under the same name as
    its module, so callers resolve the module explicitly through here.
    """
    bare_package()
    return importlib.import_module(modname)
