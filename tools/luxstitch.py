#!/usr/bin/env python
"""luxstitch — merge N per-process luxtrace event logs into one
causally-ordered fleet timeline.

Usage:
    python tools/luxstitch.py <run_id | run dir> [--json FILE]
    python tools/luxstitch.py <run dir> --trace <trace_id>
    python tools/luxstitch.py --latest

Every process of a fleet run (controller, each worker, the bench
orchestrator) writes its own ``events-<pid>.jsonl`` under one run dir;
traced hops (``lux_tpu/obs/dtrace.py``) record spans carrying
``trace``/``span``/``parent_span`` attrs, and the wire layer stamps a
``dtrace.send``/``dtrace.recv`` point pair per traced frame.  This tool:

1. loads every event file, attributing each event to its process (the
   ``m`` meta line's pid);
2. **corrects clock skew**: for each process pair exchanging traced
   frames, a send at (corrected) time g1 must precede its recv at g2 —
   min over A->B frames of (recv - send) bounds offset(B) - offset(A)
   from above by transit, and the reverse direction bounds it from
   below; the midpoint of the two one-way minima is the classic
   NTP-style estimate, propagated BFS from a reference process (on one
   Linux host CLOCK_MONOTONIC is system-wide and the offsets come out
   ~0; across machines this is what makes the merged ordering honest);
3. groups spans by ``trace`` id and orders each trace causally —
   parents before children, siblings by corrected start time — and
   interleaves the ``fault.inject`` points whose firing falls inside
   the trace's time range, so an injected fault is visible NEXT TO the
   spans it perturbed, with its plan name + seed (the reproduction);
4. renders the cross-process waterfall (or emits the whole stitched
   structure as JSON for tooling).

Pure stdlib and jax-free like luxview (same bare-package stub): a
post-mortem stitch must run on any host.  luxview imports this module
for its "Distributed traces" section.
"""
from __future__ import annotations

import argparse
import collections
import glob
import json
import os
import sys

_TOOLS = os.path.dirname(os.path.abspath(__file__))
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

import _jaxfree  # noqa: E402

_rec = _jaxfree.load("lux_tpu.obs.recorder")


def load_files(paths):
    """Per-process event load: [{pid, meta, spans{sid->span},
    points[...]}] — like luxview.load_events but KEEPING the process
    attribution the skew solver needs (luxview's flat merge drops it)."""
    out = []
    for path in paths:
        pid = None
        spans = {}
        points = []
        meta = None
        order = 0
        try:
            f = open(path, encoding="utf-8")
        except OSError:
            continue
        with f:
            for raw in f:
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    ev = json.loads(raw)
                except ValueError:
                    continue  # torn final line of a killed process
                kind = ev.get("e")
                if kind == "m":
                    if meta is None:
                        meta = ev
                        pid = ev.get("pid")
                elif kind == "b":
                    spans[ev.get("s")] = {
                        "sid": ev.get("s"), "name": ev.get("n", "?"),
                        "t0": float(ev.get("t", 0.0)), "t1": None,
                        "ok": None, "attrs": ev.get("a", {}) or {},
                        "end_attrs": {}, "pid": pid, "order": order}
                    order += 1
                elif kind == "e":
                    sp = spans.get(ev.get("s"))
                    if sp is not None:
                        sp["t1"] = float(ev.get("t", 0.0))
                        sp["ok"] = bool(ev.get("ok", True))
                        sp["end_attrs"] = ev.get("a", {}) or {}
                elif kind == "p":
                    points.append({"name": ev.get("n", "?"),
                                   "t": float(ev.get("t", 0.0)),
                                   "attrs": ev.get("a", {}) or {},
                                   "pid": pid})
        if meta is not None or spans or points:
            out.append({"pid": pid, "meta": meta, "spans": spans,
                        "points": points, "path": path})
    return out


# ----------------------------------------------------------------------
# clock-skew correction
# ----------------------------------------------------------------------


def solve_offsets(files):
    """{pid: correction seconds} such that ``t + correction`` is on the
    shared timeline (reference = the first pid, correction 0).

    Bounds come from the dtrace.send/recv pairs: a frame's span id is
    stamped once on each side, so for processes A != B,

        (t_recv + c_B) - (t_send + c_A) = transit >= 0
        =>  c_B - c_A >= t_send - t_recv      (for every A->B frame)

    and the reverse direction gives the upper bound; the estimate is
    the midpoint of the tightest pair (standard one-way-delay
    symmetrization).  Pairs whose span id appears more than once per
    direction (barrier frames fanning one context to N workers) are
    skipped as ambiguous.  Processes with no traced exchange keep
    correction 0 (same-host monotonic is already shared)."""
    sends = collections.defaultdict(list)  # span -> [(pid, t)]
    recvs = collections.defaultdict(list)
    for f in files:
        for p in f["points"]:
            if p["name"] == "dtrace.send":
                sends[p["attrs"].get("span")].append((p["pid"], p["t"]))
            elif p["name"] == "dtrace.recv":
                recvs[p["attrs"].get("span")].append((p["pid"], p["t"]))
    #: (A, B) -> min over frames of (t_recv_B - t_send_A)
    lo = {}
    for span, snd in sends.items():
        rcv = recvs.get(span)
        if rcv is None or len(snd) != 1 or len(rcv) != 1:
            continue  # unmatched or ambiguous (fan-out frame)
        (pa, ts), (pb, tr) = snd[0], rcv[0]
        if pa == pb or pa is None or pb is None:
            continue
        d = tr - ts
        key = (pa, pb)
        if key not in lo or d < lo[key]:
            lo[key] = d
    pids = sorted({p for f in files if f["pid"] is not None
                   for p in [f["pid"]]})
    offsets = {p: 0.0 for p in pids}
    if not lo or not pids:
        return offsets
    # adjacency over measured pairs; BFS from the reference pid
    adj = collections.defaultdict(set)
    for a, b in lo:
        adj[a].add(b)
        adj[b].add(a)
    seen = set()
    for root in pids:
        if root in seen:
            continue
        seen.add(root)
        queue = [root]
        while queue:
            a = queue.pop(0)
            for b in adj[a]:
                if b in seen:
                    continue
                d_ab = lo.get((a, b))  # bound: c_b - c_a >= -d_ab
                d_ba = lo.get((b, a))  # bound: c_b - c_a <= +d_ba
                if d_ab is not None and d_ba is not None:
                    delta = (d_ba - d_ab) / 2.0
                elif d_ab is not None:
                    delta = -d_ab  # one-sided: assume zero transit
                else:
                    delta = d_ba
                offsets[b] = offsets[a] + delta
                seen.add(b)
                queue.append(b)
    return offsets


# ----------------------------------------------------------------------
# the stitch
# ----------------------------------------------------------------------


def stitch(files):
    """The merged structure::

        {offsets: {pid: seconds},
         traces: {trace_id: {spans: [span...causal order...],
                             t0, t1, faults: [point...]}},
         spans: {sid: span},    # every span, corrected times
         points: [point...]}    # every point, corrected times

    Span dicts gain ``g0``/``g1`` (corrected times) and ``trace``/
    ``span``/``parent_span`` lifted out of attrs."""
    offsets = solve_offsets(files)
    all_spans = {}
    all_points = []
    for f in files:
        c = offsets.get(f["pid"], 0.0)
        for sid, sp in f["spans"].items():
            sp = dict(sp)
            sp["g0"] = sp["t0"] + c
            sp["g1"] = None if sp["t1"] is None else sp["t1"] + c
            a = sp["attrs"]
            sp["trace"] = a.get("trace")
            sp["span"] = a.get("span")
            sp["parent_span"] = a.get("parent_span")
            all_spans[sid] = sp
        for p in f["points"]:
            p = dict(p)
            p["g"] = p["t"] + c
            all_points.append(p)
    all_points.sort(key=lambda p: p["g"])

    traces = {}
    by_trace = collections.defaultdict(list)
    for sp in all_spans.values():
        if sp["trace"] is not None:
            by_trace[sp["trace"]].append(sp)
    for tid, spans in by_trace.items():
        ordered = _causal_order(spans)
        t0 = min(sp["g0"] for sp in spans)
        t1 = max([sp["g1"] for sp in spans if sp["g1"] is not None]
                 or [t0])
        faults = [p for p in all_points
                  if p["name"] == "fault.inject"
                  and t0 - 0.05 <= p["g"] <= t1 + 0.05]
        traces[tid] = {"spans": ordered, "t0": t0, "t1": t1,
                       "faults": faults,
                       "pids": sorted({sp["pid"] for sp in spans
                                       if sp["pid"] is not None})}
    return {"offsets": offsets, "traces": traces, "spans": all_spans,
            "points": all_points}


def _causal_order(spans):
    """Parents before children; siblings (and spans whose parent is in
    another — unrecorded — hop) by corrected start time.  Duplicated
    dtrace span ids (a replayed keyed root) stay distinct luxtrace
    spans and sort by time."""
    by_id = collections.defaultdict(list)
    for sp in spans:
        if sp["span"] is not None:
            by_id[sp["span"]].append(sp)
    roots = []
    children = collections.defaultdict(list)
    for sp in spans:
        parent = sp["parent_span"]
        if parent is not None and parent in by_id:
            children[parent].append(sp)
        else:
            roots.append(sp)
    roots.sort(key=lambda s: s["g0"])
    out = []
    seen = set()

    def emit(sp, depth):
        key = id(sp)
        if key in seen:
            return
        seen.add(key)
        sp = dict(sp)
        sp["depth"] = depth
        out.append(sp)
        kids = sorted(children.get(sp["span"], []),
                      key=lambda s: s["g0"])
        for k in kids:
            emit(k, depth + 1)

    for r in roots:
        emit(r, 0)
    return out


# ----------------------------------------------------------------------
# rendering
# ----------------------------------------------------------------------


def _fmt_attrs(attrs, limit=5):
    drop = ("trace", "span", "parent_span")
    items = [(k, v) for k, v in attrs.items()
             if k not in drop and not isinstance(v, (list, dict))]
    if not items:
        return ""
    return "  [" + ", ".join(f"{k}={v}" for k, v in items[:limit]) + "]"


def render_trace(tid, tr, out, t_base=None):
    """One trace's cross-process waterfall: corrected offsets from the
    trace start, pid column, causal indentation, fault injections
    interleaved at their corrected times."""
    t0 = tr["t0"] if t_base is None else t_base
    out.append(f"### trace {tid}  — {len(tr['spans'])} span(s) across "
               f"{len(tr['pids'])} process(es) "
               f"{tr['pids']}, {tr['t1'] - tr['t0']:.3f}s")
    rows = []
    for sp in tr["spans"]:
        state = ""
        if sp["g1"] is None:
            state = "  ** OPEN **"
        elif sp["ok"] is False:
            state = "  !! failed"
        dur = (sp["g1"] - sp["g0"]) if sp["g1"] is not None else 0.0
        rows.append((sp["g0"], 0,
                     f"  {sp['g0'] - t0:+9.4f}s  [{sp['pid']}] "
                     f"{'  ' * sp['depth']}{sp['name']:<28} "
                     f"{dur * 1e3:9.2f}ms"
                     f"{_fmt_attrs({**sp['attrs'], **sp['end_attrs']})}"
                     f"{state}"))
    for p in tr["faults"]:
        a = p["attrs"]
        rows.append((p["g"], 1,
                     f"  {p['g'] - t0:+9.4f}s  [{p['pid']}] "
                     f"~~ FAULT {a.get('site')}/{a.get('action')} "
                     f"plan={a.get('plan')} seed={a.get('seed')}"
                     f"{_fmt_attrs({k: v for k, v in a.items() if k not in ('site', 'action', 'plan', 'seed', 'note')})}"))
    # interleave by corrected time, but keep the causal span order when
    # clocks tie (faults sort after the span that was running)
    for _, _, line in sorted(rows, key=lambda r: (r[0], r[1])):
        out.append(line)
    out.append("")


def render(stitched, max_traces=20):
    out = []
    offs = stitched["offsets"]
    traces = stitched["traces"]
    out.append(f"# luxstitch — {len(traces)} trace(s), "
               f"{len(stitched['spans'])} span(s), "
               f"{len(offs)} process(es)")
    nonzero = {p: round(c, 6) for p, c in offs.items() if c}
    out.append(f"- clock corrections (s): "
               f"{nonzero if nonzero else 'none needed (shared clock)'}")
    out.append("")
    ordered = sorted(traces.items(),
                     key=lambda kv: (-len(kv[1]["spans"]), kv[1]["t0"]))
    for tid, tr in ordered[:max_traces]:
        render_trace(tid, tr, out)
    if len(ordered) > max_traces:
        out.append(f"... ({len(ordered) - max_traces} more trace(s); "
                   "--trace <id> for one)")
    return "\n".join(out) + "\n"


# ----------------------------------------------------------------------
# CLI
# ----------------------------------------------------------------------


def resolve_target(target, root, latest):
    if latest:
        runs = [r for r in glob.glob(os.path.join(root, "*"))
                if os.path.isdir(r)]
        runs.sort(key=os.path.getmtime)
        if not runs:
            return [], root
        target = runs[-1]
    if target is None:
        return [], root
    if os.path.isfile(target):
        return [target], target
    d = target if os.path.isdir(target) else os.path.join(root, target)
    if os.path.isdir(d):
        return sorted(glob.glob(os.path.join(d, "events-*.jsonl"))), d
    return [], target


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="merge per-process luxtrace logs into one "
                    "causally-ordered, skew-corrected fleet timeline")
    ap.add_argument("target", nargs="?",
                    help="run id, run dir, or events-*.jsonl file")
    ap.add_argument("--latest", action="store_true",
                    help="newest run under the event-log root")
    ap.add_argument("--root", default=None,
                    help="event-log root (default: LUX_OBS_DIR or the "
                         "uid-scoped tmp dir)")
    ap.add_argument("--trace", default=None,
                    help="render only this trace id")
    ap.add_argument("--json", default=None,
                    help="write the stitched structure as JSON here")
    ap.add_argument("--out", default=None,
                    help="write the report here instead of stdout")
    args = ap.parse_args(argv)

    root = args.root or _rec.default_root()
    if not args.target and not args.latest:
        ap.print_usage(sys.stderr)
        print("error: give a run id/dir/file or --latest",
              file=sys.stderr)
        return 2
    paths, label = resolve_target(args.target, root, args.latest)
    if not paths:
        print(f"luxstitch: no event files for "
              f"{args.target or '--latest'} (root {root})",
              file=sys.stderr)
        return 2
    stitched = stitch(load_files(paths))
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(stitched, f, default=str)
        print(f"luxstitch: stitched JSON -> {args.json} "
              f"({len(stitched['traces'])} traces)")
    if args.trace:
        tr = stitched["traces"].get(args.trace)
        if tr is None:
            print(f"luxstitch: no trace {args.trace!r} in {label} "
                  f"(have: {sorted(stitched['traces'])[:10]}...)",
                  file=sys.stderr)
            return 2
        out = []
        render_trace(args.trace, tr, out)
        report = "\n".join(out) + "\n"
    else:
        report = render(stitched)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(report)
        print(f"luxstitch: report -> {args.out}")
    else:
        sys.stdout.write(report)
    return 0


if __name__ == "__main__":
    sys.exit(main())
