#!/usr/bin/env python
"""luxview — render a luxtrace event log into a human report.

Usage:
    python tools/luxview.py --latest                 # newest run under the root
    python tools/luxview.py <run_id | run dir | events.jsonl> [--out FILE]
    python tools/luxview.py --list                   # runs under the root

The report sections, in order: post-mortem (spans left OPEN by a dead
process — an aborted chip window's first question), the phase waterfall
(every span, nested, with offsets/durations on the shared monotonic
clock), distributed traces (the cross-process waterfalls luxstitch
builds from the fleet's trace-context span attrs, skew-corrected, with
fault injections interleaved), per-iteration telemetry curves (the
on-device rings flushed at run end), the XProf kernel-attribution
table, the last serving-metrics snapshot, and the bench rows that
carried this run_id.

Pure stdlib and jax-free (the same bare-package stub as luxcheck): a
post-mortem must render on a host whose jax install or device tunnel is
in ANY state.  Reading is safe on live logs — unfinished spans simply
show as OPEN.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

_TOOLS = os.path.dirname(os.path.abspath(__file__))
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

import _jaxfree  # noqa: E402
import luxstitch  # noqa: E402  — the stitcher library (jax-free too)

REPO = _jaxfree.REPO
_rec = _jaxfree.load("lux_tpu.obs.recorder")

#: sibling spans of one name under one parent collapse into a single
#: aggregate waterfall row past this count (the plan-build fan-out is
#: hundreds of per-part/per-bucket spans; the report needs one line)
COLLAPSE_AT = 6

SPARK = " ▁▂▃▄▅▆▇█"


def sparkline(values, width: int = 60) -> str:
    """ASCII curve: values bucketed to ``width`` columns (mean per
    bucket), scaled to the 8-level block ramp."""
    vals = [float(v) for v in values]
    if not vals:
        return ""
    if len(vals) > width:
        n = len(vals)
        vals = [
            sum(vals[i * n // width:(i + 1) * n // width])
            / max((i + 1) * n // width - i * n // width, 1)
            for i in range(width)
        ]
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        return SPARK[4] * len(vals)
    return "".join(SPARK[1 + int(round((v - lo) / span * 7))] for v in vals)


def load_events(paths):
    """Merge event files: (metas, spans{sid->dict}, points, bad_lines)."""
    metas, points, bad = [], [], 0
    spans = {}
    order = 0
    for path in paths:
        try:
            with open(path, encoding="utf-8") as f:
                lines = f.readlines()
        except OSError:
            continue
        for raw in lines:
            raw = raw.strip()
            if not raw:
                continue
            try:
                ev = json.loads(raw)
            except ValueError:
                bad += 1  # torn final line of a killed process
                continue
            kind = ev.get("e")
            if kind == "m":
                metas.append(ev)
            elif kind == "b":
                spans[ev.get("s")] = {
                    "name": ev.get("n", "?"), "t0": float(ev.get("t", 0.0)),
                    "t1": None, "ok": None, "parent": ev.get("p"),
                    "attrs": ev.get("a", {}), "end_attrs": {},
                    "order": order}
                order += 1
            elif kind == "e":
                sp = spans.get(ev.get("s"))
                if sp is not None:
                    sp["t1"] = float(ev.get("t", 0.0))
                    sp["ok"] = bool(ev.get("ok", True))
                    sp["end_attrs"] = ev.get("a", {})
            elif kind == "p":
                points.append({"name": ev.get("n", "?"),
                               "t": float(ev.get("t", 0.0)),
                               "attrs": ev.get("a", {})})
    return metas, spans, points, bad


def _fmt_attrs(attrs: dict, limit: int = 4) -> str:
    items = list(attrs.items())[:limit]
    if not items:
        return ""
    body = ", ".join(f"{k}={v}" for k, v in items
                     if not isinstance(v, (list, dict)))
    return f"  [{body}]" if body else ""


def _dur(sp, t_end: float) -> float:
    return (sp["t1"] if sp["t1"] is not None else t_end) - sp["t0"]


def render_waterfall(spans: dict, out: list, max_rows: int = 400) -> None:
    if not spans:
        out.append("(no spans recorded)")
        return
    t0 = min(sp["t0"] for sp in spans.values())
    t_end = max([sp["t1"] for sp in spans.values()
                 if sp["t1"] is not None] or [t0])
    t_end = max(t_end, max(sp["t0"] for sp in spans.values()))
    children: dict = {}
    for sid, sp in spans.items():
        parent = sp["parent"] if sp["parent"] in spans else None
        children.setdefault(parent, []).append(sid)
    for sids in children.values():
        sids.sort(key=lambda s: (spans[s]["t0"], spans[s]["order"]))
    rows = [0]

    def emit(sid, depth):
        if rows[0] >= max_rows:
            return
        sp = spans[sid]
        d = _dur(sp, t_end)
        state = ""
        if sp["t1"] is None:
            state = "  ** OPEN **"
        elif sp["ok"] is False:
            state = "  !! failed"
        # end attrs (Span.set / obs_span --rc) merge over begin attrs:
        # a failed step's exit code must be visible in the one report
        out.append(f"  {sp['t0'] - t0:9.3f}s  {'  ' * depth}"
                   f"{sp['name']:<{max(36 - 2 * depth, 8)}} "
                   f"{d:9.3f}s"
                   f"{_fmt_attrs({**sp['attrs'], **sp['end_attrs']})}"
                   f"{state}")
        rows[0] += 1
        emit_group(sid, depth + 1)

    def emit_group(parent, depth):
        by_name: dict = {}
        for sid in children.get(parent, []):
            by_name.setdefault(spans[sid]["name"], []).append(sid)
        collapsed = set()
        for sid in children.get(parent, []):
            name = spans[sid]["name"]
            if name in collapsed:
                continue
            group = by_name[name]
            if len(group) > COLLAPSE_AT:
                # fan-outs (per-part plan builds) render as ONE aggregate
                # row at their first occurrence; everything else stays in
                # plain start-time order
                durs = [_dur(spans[s], t_end) for s in group]
                n_open = sum(1 for s in group if spans[s]["t1"] is None)
                first = spans[group[0]]
                out.append(
                    f"  {first['t0'] - t0:9.3f}s  {'  ' * depth}"
                    f"{name} ×{len(group)}"
                    f"{'':<{max(36 - 2 * depth - len(name) - 5, 1)}}"
                    f" total {sum(durs):9.3f}s  "
                    f"(avg {sum(durs) / len(durs):.3f}s, "
                    f"max {max(durs):.3f}s"
                    + (f", {n_open} OPEN" if n_open else "") + ")")
                rows[0] += 1
                collapsed.add(name)
                continue
            emit(sid, depth)

    emit_group(None, 0)
    if rows[0] >= max_rows:
        out.append(f"  ... (truncated at {max_rows} rows)")


def render_rings(points, out: list) -> None:
    rings = [p for p in points if p["name"] == "telemetry.ring"]
    if not rings:
        out.append("(no on-device telemetry rings in this log)")
        return
    for p in rings:
        a = p["attrs"]
        cols = a.get("cols") or []
        rows = a.get("rows") or []
        n = a.get("n", len(rows))
        extra = {k: v for k, v in a.items()
                 if k not in ("kind", "cols", "rows", "n")}
        out.append(f"### ring: {a.get('kind', '?')} — {n} iteration(s) "
                   f"pushed, {len(rows)} recorded{_fmt_attrs(extra)}")
        if not rows or not cols:
            out.append("")
            continue
        for ci in range(1, len(cols)):
            series = [r[ci] for r in rows if len(r) > ci]
            if not series:
                continue
            out.append(f"  {cols[ci]:>12}: "
                       f"{sparkline(series)}  "
                       f"(first={series[0]:g}, last={series[-1]:g}, "
                       f"max={max(series):g})")
        head = rows[:4]
        tail = rows[-2:] if len(rows) > 6 else rows[4:]
        out.append("  " + "  ".join(f"{c:>12}" for c in cols))
        for r in head:
            out.append("  " + "  ".join(f"{v:12g}" for v in r))
        if len(rows) > 6:
            out.append(f"  {'...':>12}")
        for r in tail:
            out.append("  " + "  ".join(f"{v:12g}" for v in r))
        out.append("")


def render_kernels(points, out: list) -> None:
    ks = [p for p in points if p["name"] == "xprof.kernels"]
    if not ks:
        out.append("(no XProf kernel attribution in this log — pass a "
                   "trace dir to utils.profiling.trace to capture one)")
        return
    a = ks[-1]["attrs"]
    if a.get("host_only"):
        out.append("NOTE: no device lanes in this capture — times below "
                   "are HOST wall time (all pids), not device ms.")
        out.append("")
    classes = a.get("classes") or {}
    if classes:
        total = sum(classes.values()) or 1.0
        out.append("class rollup (device ms):")
        for cls, ms in sorted(classes.items(), key=lambda kv: -kv[1]):
            out.append(f"  {cls:<12} {ms:10.3f} ms  "
                       f"{100 * ms / total:5.1f}%")
        out.append("")
    out.append(f"{'kernel':<48} {'class':<11} {'ms':>10} {'calls':>6} "
               f"{'frac':>6}")
    for r in (a.get("rows") or [])[:25]:
        out.append(f"{str(r.get('name', ''))[:48]:<48} "
                   f"{r.get('class', ''):<11} {r.get('total_ms', 0):>10} "
                   f"{r.get('calls', 0):>6} {r.get('frac', 0):>6}")


def render_serve(points, out: list) -> None:
    snaps = [p for p in points if p["name"] == "serve.metrics"]
    if not snaps:
        out.append("(no serving-metrics snapshots in this log)")
        return
    a = snaps[-1]["attrs"]
    lat = a.get("latency_ms") or {}
    wait = a.get("queue_wait_ms") or {}
    out.append(f"snapshots: {len(snaps)} (showing last)")
    out.append(f"  completed={a.get('completed', 0)}  "
               f"timeouts={a.get('timeouts', 0)}  "
               f"rejected={a.get('rejected', 0)}  "
               f"batches={a.get('batches', 0)}")
    if "qps" in a:
        out.append(f"  qps={a['qps']}")
    if lat:
        out.append("  latency_ms: "
                   + "  ".join(f"{k}={v}" for k, v in lat.items()))
    if wait:
        out.append("  queue_wait_ms: "
                   + "  ".join(f"{k}={v}" for k, v in wait.items()))
    for k in ("queue_depth_max", "batch_occupancy", "warm_batch_ratio"):
        if k in a:
            out.append(f"  {k}={a[k]}")


def render_bench(points, out: list) -> None:
    rows = [p for p in points if p["name"] == "bench.row"]
    if not rows:
        out.append("(no bench rows in this log)")
        return
    out.append(f"{'metric':<48} {'value':>12} {'unit':<8} method")
    for p in rows:
        a = p["attrs"]
        out.append(f"{str(a.get('metric', ''))[:48]:<48} "
                   f"{a.get('value', ''):>12} {str(a.get('unit', '')):<8} "
                   f"{a.get('method', '')}")


def render_dtraces(stitched, out: list, max_traces: int = 8) -> None:
    """The cross-process waterfalls (luxstitch): one block per
    distributed trace, largest first."""
    traces = (stitched or {}).get("traces") or {}
    if not traces:
        out.append("(no distributed traces in this log — fleet frames "
                   "record them when LUX_DTRACE is on)")
        return
    offs = {p: round(c, 6)
            for p, c in stitched["offsets"].items() if c}
    out.append(f"{len(traces)} trace(s); clock corrections: "
               f"{offs if offs else 'none (shared clock)'}")
    out.append("")
    ordered = sorted(traces.items(),
                     key=lambda kv: (-len(kv[1]["spans"]),
                                     kv[1]["t0"]))
    for tid, tr in ordered[:max_traces]:
        luxstitch.render_trace(tid, tr, out)
    if len(ordered) > max_traces:
        out.append(f"... ({len(ordered) - max_traces} more; "
                   "tools/luxstitch.py renders them all)")


def render(metas, spans, points, bad, label: str, stitched=None) -> str:
    out = []
    run = metas[0].get("run") if metas else "?"
    out.append(f"# luxtrace report — run {run}")
    out.append("")
    if metas:
        wall0 = min(m.get("wall", 0.0) for m in metas)
        pids = sorted({m.get("pid") for m in metas})
        out.append(f"- started: "
                   f"{time.strftime('%Y-%m-%d %H:%M:%S', time.localtime(wall0))}"
                   f" (wall)")
        out.append(f"- processes: {len(pids)} (pids {pids})")
    n_open = sum(1 for sp in spans.values() if sp["t1"] is None)
    n_fail = sum(1 for sp in spans.values() if sp["ok"] is False)
    out.append(f"- events: {len(spans)} span(s), {len(points)} point(s)"
               + (f", {bad} torn line(s)" if bad else ""))
    out.append(f"- source: {label}")
    out.append("")
    if n_open or n_fail:
        out.append("## Post-mortem")
        out.append("")
        if n_open:
            out.append(f"{n_open} span(s) left OPEN — the process died (or "
                       "is still running) inside:")
            for sp in sorted((s for s in spans.values() if s["t1"] is None),
                             key=lambda s: s["t0"]):
                out.append(f"  - {sp['name']}{_fmt_attrs(sp['attrs'])}")
        if n_fail:
            out.append(f"{n_fail} span(s) exited via an exception:")
            for sp in sorted((s for s in spans.values()
                              if s["ok"] is False), key=lambda s: s["t0"]):
                out.append(f"  - {sp['name']}"
                           f"{_fmt_attrs({**sp['attrs'], **sp['end_attrs']})}")
        out.append("")
    out.append("## Phase waterfall")
    out.append("")
    render_waterfall(spans, out)
    out.append("")
    out.append("## Distributed traces")
    out.append("")
    render_dtraces(stitched, out)
    out.append("")
    out.append("## On-device iteration telemetry")
    out.append("")
    render_rings(points, out)
    out.append("")
    out.append("## Kernel attribution (XProf)")
    out.append("")
    render_kernels(points, out)
    out.append("")
    out.append("## Serving metrics")
    out.append("")
    render_serve(points, out)
    out.append("")
    out.append("## Bench rows")
    out.append("")
    render_bench(points, out)
    out.append("")
    out.append(f"run_id: {run}")
    return "\n".join(out) + "\n"


def resolve_target(target, root: str, latest: bool):
    """(event file list, label) for a run id / dir / file / --latest."""
    if latest:
        runs = sorted(glob.glob(os.path.join(root, "*")),
                      key=lambda p: os.path.getmtime(p)
                      if os.path.isdir(p) else 0)
        runs = [r for r in runs if os.path.isdir(r)]
        if not runs:
            return [], root
        target = runs[-1]
    if target is None:
        return [], root
    if os.path.isfile(target):
        return [target], target
    d = target if os.path.isdir(target) else os.path.join(root, target)
    if os.path.isdir(d):
        return sorted(glob.glob(os.path.join(d, "events-*.jsonl"))), d
    return [], target


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="render a luxtrace event log (flight-recorder "
                    "post-mortem, waterfall, telemetry, kernels, serve)")
    ap.add_argument("target", nargs="?",
                    help="run id, run dir, or events-*.jsonl file")
    ap.add_argument("--latest", action="store_true",
                    help="newest run under the event-log root")
    ap.add_argument("--list", action="store_true",
                    help="list runs under the event-log root")
    ap.add_argument("--root", default=None,
                    help="event-log root (default: LUX_OBS_DIR or the "
                         "uid-scoped tmp dir)")
    ap.add_argument("--out", default=None,
                    help="write the report here instead of stdout")
    args = ap.parse_args(argv)

    root = args.root or _rec.default_root()
    if args.list:
        runs = sorted(glob.glob(os.path.join(root, "*")))
        for r in runs:
            if os.path.isdir(r):
                files = glob.glob(os.path.join(r, "events-*.jsonl"))
                print(f"{os.path.basename(r)}  ({len(files)} file(s))")
        if not runs:
            print(f"(no runs under {root})")
        return 0

    if not args.target and not args.latest:
        ap.print_usage(sys.stderr)
        print("error: give a run id/dir/file, --latest, or --list",
              file=sys.stderr)
        return 2
    files, label = resolve_target(args.target, root, args.latest)
    if not files:
        print(f"luxview: no event files found for "
              f"{args.target or '--latest'} (root {root})", file=sys.stderr)
        return 2
    metas, spans, points, bad = load_events(files)
    stitched = luxstitch.stitch(luxstitch.load_files(files))
    report = render(metas, spans, points, bad, label, stitched=stitched)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(report)
        print(f"luxview: report -> {args.out} "
              f"({len(spans)} spans, {len(points)} points)")
    else:
        sys.stdout.write(report)
    return 0


if __name__ == "__main__":
    sys.exit(main())
