#!/usr/bin/env python
"""autopilot_demo — watch the fleet fly itself, end to end.

Runs the FULL autonomous loop (``lux_tpu/fault/chaos.autopilot_soak``)
with tracing on and narrates what the pilot did:

1. a load ramp above the per-worker knee trips the **Autoscaler** into
   a previewed, cooldown-gated scale-up (a new replica spawned, joined,
   rebalanced onto the ring);
2. the incumbent controller is killed — a **Standby** detects the
   silence, wins the incarnation-fenced election and runs
   ``promote_live_controller`` unattended, with the standing-query
   **subscription** still delivering across the failover (hub rebind);
3. fat churn batches overflow the delta capacity into an escalated
   fleet-wide **compaction**;

with zero acked-write loss and bitwise post-recovery answers asserted
throughout.  Every autonomous action lands as a span on a keyed
incident trace; the demo prints each incident's trace id and the
``luxstitch`` command that renders its causal timeline.

Usage:
  python tools/autopilot_demo.py [--seed 0] [--steps 4] [--scale 7]
      [--cap 48] [--json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--steps", type=int, default=4,
                    help="ramp-phase write/read/tick steps")
    ap.add_argument("--scale", type=int, default=7, help="rmat scale")
    ap.add_argument("--cap", type=int, default=48,
                    help="delta capacity (small -> compaction fires)")
    ap.add_argument("--json", action="store_true",
                    help="emit the raw soak report as JSON")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from lux_tpu import obs
    from lux_tpu.fault.chaos import autopilot_soak
    from lux_tpu.obs import dtrace
    from lux_tpu.obs.dtrace import _hex_hash

    rec = obs.Recorder()
    obs.install(rec)
    dtrace.set_enabled(True)
    try:
        report = autopilot_soak(args.seed, steps=args.steps,
                                scale=args.scale, cap=args.cap)
    finally:
        dtrace.set_enabled(None)

    if args.json:
        print(json.dumps(report, default=str))
        return 0

    keys = report["incident_keys"]
    print(f"autopilot soak (seed {args.seed}) — the fleet flew itself:")
    print(f"  writes admitted        : {report['writes']} "
          f"(generation {report['generation']}, zero acked loss)")
    print(f"  reads (bitwise checked): {report['reads']}")
    print(f"  scale-ups              : {report['scale_ups']}")
    print(f"  elections              : {report['elections']} "
          f"(standby {report['winner']} won, incarnation-fenced)")
    print(f"  compactions (overflow) : {report['compactions']}")
    print(f"  subscription deliveries: {len(report['sub_delivered'])} "
          f"(generations {report['sub_delivered']}) — survived the "
          "failover")
    print("\nincident traces (one stitched timeline per incident):")
    rows = [("election", keys["election"])] + [
        (f"scale #{i + 1}", k)
        for i, k in enumerate(keys["scale"])]
    for label, key in rows:
        tid = _hex_hash(f"lux:{key}", 8)
        print(f"  {label:<12} key={key}  trace={tid}")
        print(f"      python tools/luxstitch.py {rec.run_id} "
              f"--trace {tid}")
    print(f"\nfull timeline: python tools/luxstitch.py {rec.run_id}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
