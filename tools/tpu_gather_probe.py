#!/usr/bin/env python
"""Chip probe: which gather formulation is fast on this TPU?

Round-5 chip data (micro race + bench race) shows the hot loop is
GATHER-BOUND: XLA's flat 1-D gather runs ~7 cycles/element (0.14 GTEPS
at rmat17) while the segment reduce does 1.05 GTEPS — the reference's
coalesced load_kernel (pagerank_gpu.cu:34-47) has no XLA analog.  Mosaic
exposes the real hardware primitive (``tpu.dynamic_gather``) only for
2-D ``take_along_axis`` patterns: per-LANE gathers along sublanes
(axis 0) and per-SUBLANE gathers along lanes (axis 1), idx shape ==
operand shape (jax pallas mosaic lowering.py _gather_lowering_rule).

This tool times every candidate route to that primitive, each in its own
abandonable worker (micro-race harness semantics: banked to disk as soon
as measured, risky variants last, wedged workers never killed):

  flat     y = x[idx]                     XLA 1-D baseline (ties to micro)
  tala0    take_along_axis(x2d, i, 0)     XLA-level, per-lane rows
  tala1    take_along_axis(x2d, i, 1)     XLA-level, per-sublane lanes
  ptala0   same as tala0 inside Pallas    block-local (VMEM) rows
  ptala1   same as tala1 inside Pallas    128-lane shuffle
  route    full Benes permutation replay  ops/route + ops/pallas_shuffle:
                                          2k-1 digit-gather passes + one
                                          transpose each — the production
                                          rival of `flat` for the fixed
                                          per-edge state-read permutation
  pstream  arbitrary full-column gather   Pallas: stream in-blocks, mask
                                          + accumulate (KNOWN-FAILING on
                                          v5e: sublane dynamic_gather is
                                          single-vreg only; kept last as
                                          a canary for that constraint)

Every worker numerics-checks its first result against NumPy (exact for
f32 moves) — on-chip Mosaic validation, not just interpret mode.

Usage: python tools/tpu_gather_probe.py [--scale 17]
       (worker mode: --worker --variant V, spawned internally)
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _env import env_int as _env_int  # noqa: E402 — jax-free twin of utils.config.env_int

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

VARIANTS = ("flat", "tala0", "tala1", "ptala0", "ptala1", "route",
            "pstream")


def _fit(xs, ys):
    n = len(xs)
    mx, my = sum(xs) / n, sum(ys) / n
    num = sum((x - mx) * (y - my) for x, y in zip(xs, ys))
    den = sum((x - mx) ** 2 for x in xs)
    return num / den, my - (num / den) * mx


def _pallas_tala(axis: int, rb: int, interpret: bool = False):
    """Block-local take_along_axis kernel: grid over row-blocks, idx
    values local to the block (axis 0: [0, rb); axis 1: [0, 128))."""
    import functools

    import jax
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    import jax.numpy as jnp

    def kernel(x_ref, i_ref, o_ref):
        o_ref[:] = jnp.take_along_axis(
            x_ref[:], i_ref[:], axis=axis, mode="promise_in_bounds"
        )

    @jax.jit
    def run(x, idx):
        r, c = x.shape
        grid = (r // rb,)
        spec = pl.BlockSpec((rb, c), lambda i: (i, 0))
        return pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[spec, spec],
            out_specs=spec,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("arbitrary",)
            ),
            interpret=interpret,
        )(x, idx)

    return run


def _pallas_stream(rb_out: int, rb_in: int, interpret: bool = False):
    """Arbitrary whole-column gather: out[r, c] = x[idx[r, c], c] with
    idx in [0, R).  Grid (out_blocks, in_blocks); every in-block streams
    past every out-block (consecutive revisits keep the out block in
    VMEM); in-range hits are selected in.  One pass of the 3-stage
    permutation network costs exactly this."""
    import jax
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    import jax.numpy as jnp

    def kernel(x_ref, i_ref, o_ref):
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _():
            o_ref[:] = jnp.zeros_like(o_ref)

        base = j * rb_in
        local = i_ref[:] - base
        valid = (local >= 0) & (local < rb_in)
        g = jnp.take_along_axis(
            x_ref[:],
            jnp.clip(local, 0, rb_in - 1),
            axis=0,
            mode="promise_in_bounds",
        )
        o_ref[:] = jnp.where(valid, g, o_ref[:])

    @jax.jit
    def run(x, idx):
        r, c = x.shape
        grid = (r // rb_out, r // rb_in)
        return pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((rb_in, c), lambda o, j: (j, 0)),
                pl.BlockSpec((rb_out, c), lambda o, j: (o, 0)),
            ],
            out_specs=pl.BlockSpec((rb_out, c), lambda o, j: (o, 0)),
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("arbitrary", "arbitrary")
            ),
            interpret=interpret,
        )(x, idx)

    return run


def worker_main(args) -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np

    t_setup = time.perf_counter()
    n = 1 << args.scale  # elements moved per rep (matches rmat edges/8)
    cols = 128
    rows = n // cols
    rb = min(args.rb, rows)
    interp = bool(_env_int("LUX_GP_INTERPRET", 0))
    rng = np.random.default_rng(0)
    x_np = rng.random((rows, cols)).astype(np.float32)
    v = args.variant
    if v == "flat":
        idx_np = rng.integers(0, n, n, dtype=np.int32)
        x = jnp.asarray(x_np.reshape(-1))
        idx = jnp.asarray(idx_np)
        want = x_np.reshape(-1)[idx_np]

        def f(x):
            return x[idx]

        run1 = jax.jit(f)
    elif v in ("tala0", "ptala0", "pstream"):
        hi = rb if v == "ptala0" else rows
        idx_np = rng.integers(0, hi, (rows, cols), dtype=np.int32)
        if v == "ptala0":  # rows random WITHIN each block (primitive rate)
            blk = np.arange(rows, dtype=np.int32)[:, None] // rb * rb
            idx_np = (rng.integers(0, rb, (rows, cols), dtype=np.int32)
                      + blk).astype(np.int32)
            want = np.take_along_axis(x_np, idx_np, axis=0)
            idx_np = idx_np - blk  # kernel sees block-local
        else:
            want = np.take_along_axis(x_np, idx_np, axis=0)
        x = jnp.asarray(x_np)
        idx = jnp.asarray(idx_np)
        if v == "tala0":
            run1 = jax.jit(
                lambda x: jnp.take_along_axis(
                    x, idx, axis=0, mode="promise_in_bounds"))
        elif v == "ptala0":
            pk = _pallas_tala(0, rb, interp)
            run1 = lambda x: pk(x, idx)
        else:
            pk = _pallas_stream(rb, rb, interp)
            run1 = lambda x: pk(x, idx)
    elif v == "route":
        # full Benes replay of a random PERMUTATION (the production
        # shape: 2k-1 digit-gather passes + 1 transpose each) — the
        # apples-to-apples rival of `flat` for a fixed edge permutation
        from lux_tpu.ops import pallas_shuffle as S
        from lux_tpu.ops import route as RT

        t_r = time.perf_counter()
        perm = rng.permutation(n)
        plan = S.plan_route(RT.build_route(perm))
        print(f"# route build: {time.perf_counter()-t_r:.1f}s "
              f"dims={plan.dims} passes={len(plan.passes)}", flush=True)
        idx_dev = S.device_indices(plan)
        x = jnp.asarray(x_np.reshape(-1))
        idx = idx_dev  # block_until_ready target
        want = x_np.reshape(-1)[perm]

        def f(xc):
            return S.apply_route(xc, plan, idx_dev=idx_dev, rb=args.rb,
                                 interpret=interp)

        run1 = jax.jit(f)
    elif v in ("tala1", "ptala1"):
        idx_np = rng.integers(0, cols, (rows, cols), dtype=np.int32)
        want = np.take_along_axis(x_np, idx_np, axis=1)
        x = jnp.asarray(x_np)
        idx = jnp.asarray(idx_np)
        if v == "tala1":
            run1 = jax.jit(
                lambda x: jnp.take_along_axis(
                    x, idx, axis=1, mode="promise_in_bounds"))
        else:
            pk = _pallas_tala(1, rb, interp)
            run1 = lambda x: pk(x, idx)
    else:
        raise SystemExit(f"unknown variant {v}")

    jax.block_until_ready((x, idx))
    platform = jax.devices()[0].platform
    print(f"# gather worker: platform={platform} variant={v} n={n} "
          f"rows={rows} rb={rb} setup={time.perf_counter()-t_setup:.1f}s",
          flush=True)

    # numerics first: on-chip result == NumPy oracle, exactly (f32 moves)
    got = np.asarray(jax.device_get(run1(x)))
    ok = bool((got.reshape(want.shape) == want).all())
    print(f"# numerics: {'EXACT' if ok else 'MISMATCH'}", flush=True)

    # x_{k+1} = g(x_k) chaining; scale values so chains stay finite
    @jax.jit
    def run(x0, nrep):
        def body(_, xc):
            return run1(xc).reshape(xc.shape) * jnp.float32(0.999)
        return jax.lax.fori_loop(0, nrep, body, x0)

    t_c = time.perf_counter()
    for r in args.reps:
        float(jax.device_get(run(x, jnp.int32(r)).ravel()[0]))
    compile_s = time.perf_counter() - t_c
    xs, ts = [], []
    for r in args.reps:
        t0 = time.perf_counter()
        float(jax.device_get(run(x, jnp.int32(r)).ravel()[0]))
        ts.append(time.perf_counter() - t0)
        xs.append(r)
    slope, icpt = _fit(xs, ts)
    ns_per_elem = slope / n * 1e9 if slope > 0 else float("nan")
    gbps = 2 * 4 * n / slope / 1e9 if slope > 0 else float("nan")
    print(json.dumps({
        "gather_probe": v, "platform": platform, "n": n,
        "numerics_exact": ok,
        "ms_per_rep": round(slope * 1e3, 4),
        "ns_per_elem": round(ns_per_elem, 3),
        "eff_GBps_rw": round(gbps, 2),
        "intercept_ms": round(icpt * 1e3, 2),
        "compile_s": round(compile_s, 1),
        "raw": {str(r): round(t, 4) for r, t in zip(xs, ts)},
    }), flush=True)
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=int, default=21,
                    help="log2(elements) moved per rep")
    ap.add_argument("--rb", type=int, default=4096,
                    help="Pallas row-block (VMEM budget: 3*rb*128*4B)")
    ap.add_argument("--reps", type=int, nargs="+", default=[1, 8, 32])
    ap.add_argument("--variants", nargs="+", default=list(VARIANTS),
                    help="probe order; riskiest (pstream) belongs last")
    ap.add_argument("--variant", help="(worker mode)")
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--per-variant-s", type=int,
                    default=_env_int("LUX_MICRO_METHOD_S", 300))
    ap.add_argument("--outdir", default="/tmp/lux_gather_probe")
    args = ap.parse_args(argv)
    if args.worker:
        return worker_main(args)

    on_cpu = os.environ.get("JAX_PLATFORMS", "") == "cpu"
    if not on_cpu:
        import socket

        try:
            socket.create_connection(("127.0.0.1", 8083), timeout=3).close()
        except OSError:
            print("relay down (127.0.0.1:8083) — nothing to probe",
                  flush=True)
            return 1
    os.makedirs(args.outdir, exist_ok=True)
    rows: dict[str, dict] = {}
    for v in args.variants:
        out_path = os.path.join(args.outdir, f"gp_{v}.out")
        cmd = [sys.executable, os.path.abspath(__file__), "--worker",
               "--variant", v, "--scale", str(args.scale),
               "--rb", str(args.rb),
               "--reps", *[str(r) for r in args.reps]]
        t0 = time.monotonic()
        # Popen dups the descriptors into the child, so the with block
        # may close ours even when the worker is abandoned mid-write
        with open(out_path, "wb") as out, \
                open(out_path + ".err", "wb") as err:
            proc = subprocess.Popen(cmd, stdout=out, stderr=err,
                                    cwd=os.path.dirname(
                                        os.path.abspath(__file__)),
                                    start_new_session=True)
            while time.monotonic() - t0 < args.per_variant_s:
                if proc.poll() is not None:
                    break
                time.sleep(1)
            abandoned = proc.poll() is None
        with open(out_path, "rb") as f:
            text = f.read().decode("utf8", "replace")
        for line in text.splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    rows[v] = json.loads(line)
                except ValueError:
                    pass
            elif line:
                print(line, flush=True)
        if v in rows:
            print(json.dumps(rows[v]), flush=True)
        if abandoned:
            print(f"# {v} ABANDONED after {args.per_variant_s}s (pid "
                  f"{proc.pid} left to unwind); stopping probe", flush=True)
            break
        if v not in rows:
            print(f"# {v} produced no measurement (rc={proc.returncode}; "
                  f"see {out_path}.err)", flush=True)
    if not rows:
        print("gather probe: no measurements", flush=True)
        return 1
    summary = {v: {"ns_per_elem": r.get("ns_per_elem"),
                   "exact": r.get("numerics_exact")}
               for v, r in rows.items()}
    print(f"# gather probe summary: {json.dumps(summary)}", flush=True)
    platforms = {r.get("platform") for r in rows.values()}
    if platforms & {"tpu", "axon"}:
        from lux_tpu.engine import methods

        methods.record_overlay_entry("tpu:gather_probe", summary)
    return 0


if __name__ == "__main__":
    sys.exit(main())
