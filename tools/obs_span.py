#!/usr/bin/env python
"""obs_span — emit luxtrace span events from shell scripts.

tools/chip_day.sh wraps every battery step with this helper, so a window
that dies mid-step still leaves a complete event log: the begin event is
on disk before the step runs, and an abort simply leaves the span OPEN —
exactly what luxview's post-mortem section renders.

Usage (chip_day.sh idiom):
    sid=$(python tools/obs_span.py begin step.micro_race timeout=3000)
    ...run the step...
    python tools/obs_span.py end "$sid" --rc $?
    python tools/obs_span.py point battery.abort reason=relay_down

All invocations of one run append to ONE shared ``events-shell.jsonl``
in the run dir (single-line O_APPEND writes are atomic on Linux), keyed
by $LUX_OBS_RUN_ID / $LUX_OBS_DIR — export the run id once at the top of
the script and every child process (python workers included, via the
recorder's env contract) lands in the same timeline.  Monotonic
timestamps are CLOCK_MONOTONIC, system-wide on Linux, so shell spans and
worker spans interleave correctly.

Jax-free (luxcheck's bare-package stub): this must work when the tunnel
or the jax install is wedged — that is precisely when the post-mortem
matters.  Failures degrade silently (prints an empty sid); observability
must never fail the battery.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

_TOOLS = os.path.dirname(os.path.abspath(__file__))
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

import _jaxfree  # noqa: E402

REPO = _jaxfree.REPO
_rec = _jaxfree.load("lux_tpu.obs.recorder")


def _parse_attrs(pairs):
    out = {}
    for p in pairs:
        k, _, v = p.partition("=")
        if not _:
            continue
        try:
            out[k] = json.loads(v)
        except ValueError:
            out[k] = v
    return out


def _log_path():
    """The shared shell event file for this run, or None when the dir
    contract fails (degrade silently — same rule as the recorder)."""
    run = os.environ.get(_rec.RUN_ENV)
    if not run or os.environ.get(_rec.ENABLE_ENV, "1") == "0":
        return None
    root = _rec.default_root()
    d = os.path.join(root, run)
    if not (_rec._dir_trusted(root) and _rec._dir_trusted(d)):
        return None
    return os.path.join(d, "events-shell.jsonl")


def _write(ev: dict) -> bool:
    path = _log_path()
    if path is None:
        return False
    try:
        new = not os.path.exists(path)
        with open(path, "a", encoding="utf-8") as f:
            if new:
                f.write(json.dumps({
                    "e": "m", "run": os.environ.get(_rec.RUN_ENV),
                    "pid": os.getpid(), "wall": time.time(),
                    "mono": time.monotonic(), "argv": ["obs_span(shell)"],
                }) + "\n")
            f.write(json.dumps(ev) + "\n")
        return True
    except OSError:
        return False


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="append luxtrace span/point events from shell")
    ap.add_argument("verb", choices=("begin", "end", "point"))
    ap.add_argument("name_or_sid",
                    help="span/point name (begin, point) or the sid "
                         "printed by begin (end)")
    ap.add_argument("attrs", nargs="*", help="k=v attributes")
    ap.add_argument("--rc", type=int, default=0,
                    help="step exit code (end; nonzero = failed span)")
    ap.add_argument("--parent", default=None,
                    help="parent sid (begin; nested shell phases)")
    args = ap.parse_args(argv)

    t = time.monotonic()
    if args.verb == "begin":
        # sid unique across the battery: pid + microsecond monotonic
        sid = f"sh{os.getpid()}-{int(t * 1e6)}"
        ev = {"e": "b", "n": args.name_or_sid, "s": sid,
              "p": args.parent, "t": t}
        a = _parse_attrs(args.attrs)
        if a:
            ev["a"] = a
        # degrade contract: an empty sid tells the script the log dir is
        # unusable, so its [ -n "$sid" ] guards skip the end/point spawns
        print(sid if _write(ev) else "")
        return 0
    if args.verb == "end":
        ev = {"e": "e", "s": args.name_or_sid, "t": t,
              "ok": args.rc == 0}
        a = _parse_attrs(args.attrs)
        if args.rc:
            a["rc"] = args.rc
        if a:
            ev["a"] = a
        _write(ev)
        return 0
    ev = {"e": "p", "n": args.name_or_sid, "t": t}
    a = _parse_attrs(args.attrs)
    if a:
        ev["a"] = a
    _write(ev)
    return 0


if __name__ == "__main__":
    sys.exit(main())
