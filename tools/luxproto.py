#!/usr/bin/env python
"""luxproto — exhaustive protocol model checking for the distributed
fleet (lux_tpu.analysis.proto).

Usage:
    python tools/luxproto.py --all              # every protocol model
    python tools/luxproto.py --protocols election,journal
    python tools/luxproto.py --all --twins      # + broken twins must FAIL
    python tools/luxproto.py --replay SOAK.json # conformance over a log
    python tools/luxproto.py --export election:unfenced  # FaultPlan JSON
    python tools/luxproto.py --list

Exit codes: 0 = clean, 1 = findings, 2 = usage.

What counts as a FINDING (abort-on-findings, like luxcheck):

* a counterexample in a CLEAN protocol model — the protocol (or the
  model of it) is broken; the shortest trace is printed and the
  counterexample exports as a seeded PR-14 FaultPlan
  (``--export <protocol>``) that replays against the real fleet;
* under ``--twins``: a BROKEN twin that checks clean — the deliberately
  de-fenced model no longer fails, so either the model drifted from the
  code or the checker lost the hazard (a silent-pass tripwire);
* an EMPTY or unknown ``--protocols`` filter — a gate that matched
  nothing must not read as coverage;
* under ``--replay``: any recorded soak transition the models would not
  allow (lux_tpu.analysis.proto.conform), or an empty event log.

Runs as step -3c of tools/chip_day.sh (next to luxcheck/-3 and
luxaudit/-3b) and as ci_check's ``proto_smoke`` stage.  Pure stdlib —
the models import the REAL protocol code (StandbyGroup, pubproto,
GenerationGap, deltalog's journal constants) but none of it touches
jax, so the gate costs well under a second.
"""
import argparse
import json
import os
import sys

_TOOLS = os.path.dirname(os.path.abspath(__file__))
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

import _jaxfree  # noqa: E402

REPO = _jaxfree.bare_package()

from lux_tpu.analysis.proto import (  # noqa: E402
    PROTOCOLS, check_broken, check_protocol,
)
from lux_tpu.analysis.proto import conform  # noqa: E402
from lux_tpu.analysis.proto.export import export_json  # noqa: E402


def _parse_protocols(spec):
    """Comma-separated filter -> (names, findings).  Unknown names and
    an empty selection are findings, not silent no-ops."""
    findings = []
    if spec is None:
        return list(PROTOCOLS), findings
    names = [s.strip() for s in spec.split(",") if s.strip()]
    unknown = [n for n in names if n not in PROTOCOLS]
    for n in unknown:
        findings.append(
            f"luxproto: unknown protocol {n!r} in --protocols "
            f"(known: {', '.join(PROTOCOLS)})")
    names = [n for n in names if n in PROTOCOLS]
    if not names:
        findings.append(
            "luxproto: --protocols selected NOTHING — an empty gate "
            "must not read as coverage")
    return names, findings


def _check_models(names, twins, max_states):
    findings = []
    for name in names:
        res = check_protocol(name, max_states=max_states)
        print(res.summary())
        if not res.ok:
            findings.append(f"{name}: counterexample found")
            print(res.violation.format())
            print(f"  replay: python tools/luxproto.py --export {name}")
        if not twins:
            continue
        for twin in PROTOCOLS[name].broken:
            bres = check_broken(name, twin, max_states=max_states)
            if bres.ok:
                findings.append(
                    f"{name}/{twin}: broken twin checks CLEAN — the "
                    "model lost the hazard (or the guard it disables "
                    "is no longer what prevents it)")
                print(f"{name}/{twin}: unexpectedly clean "
                      f"({bres.states} states)")
            else:
                print(f"{name}/{twin}: fails as designed "
                      f"({bres.violation.kind}, "
                      f"{len(bres.violation.trace)}-step trace)")
    return findings


def _load_events(path):
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict):
        doc = doc.get("events", doc)
    if not isinstance(doc, list):
        raise ValueError(
            f"{path}: expected a JSON list of events (or a soak "
            "report with an 'events' key)")
    return doc


def _replay(paths, kind):
    findings = []
    for path in paths:
        try:
            events = _load_events(path)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            findings.append(f"{path}: unreadable event log: {e}")
            continue
        bad = conform.replay(events, kind=kind)
        label = conform.detect_kind(events) if kind == "auto" else kind
        if bad:
            for nc in bad:
                print(f"{path}: {nc.format()}")
            findings.append(
                f"{path}: {len(bad)} model-illegal transition(s)")
        else:
            print(f"{path}: {len(events)} events conform ({label})")
    return findings


def _export(spec):
    """``protocol`` (clean model's counterexample — only exists when
    the gate is failing) or ``protocol:twin`` (the designed
    counterexample)."""
    name, _, twin = spec.partition(":")
    if name not in PROTOCOLS:
        print(f"luxproto: unknown protocol {name!r}", file=sys.stderr)
        return 2
    if twin:
        if twin not in PROTOCOLS[name].broken:
            print(f"luxproto: unknown twin {twin!r} for {name} "
                  f"(known: {', '.join(PROTOCOLS[name].broken)})",
                  file=sys.stderr)
            return 2
        res = check_broken(name, twin)
    else:
        res = check_protocol(name)
    if res.ok:
        print(f"luxproto: {spec} checks clean — no counterexample to "
              "export", file=sys.stderr)
        return 1
    print(export_json(res))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="exhaustive protocol model checking (election "
                    "fencing, two-phase publish, generation line, "
                    "journal crash-atomicity) + trace-replay "
                    "conformance")
    ap.add_argument("--all", action="store_true",
                    help="check every registered protocol model")
    ap.add_argument("--protocols", default=None, metavar="A,B",
                    help="comma-separated subset (empty/unknown "
                         "selection is itself a finding)")
    ap.add_argument("--twins", action="store_true",
                    help="also run the broken twins and REQUIRE them "
                         "to fail (silent-pass tripwire)")
    ap.add_argument("--replay", nargs="+", default=None, metavar="LOG",
                    help="conformance-check recorded soak event logs "
                         "(JSON list, or a soak report with 'events')")
    ap.add_argument("--kind", default="auto",
                    choices=("auto", "chaos_soak", "autopilot_soak"),
                    help="event-log kind for --replay")
    ap.add_argument("--export", default=None, metavar="PROTO[:TWIN]",
                    help="print the counterexample's FaultPlan JSON")
    ap.add_argument("--max-states", type=int, default=1_000_000,
                    help="state-space tripwire (exceeding it is a "
                         "finding, not a silent truncation)")
    ap.add_argument("--list", action="store_true",
                    help="list protocols, their broken twins and "
                         "invariant summaries")
    args = ap.parse_args(argv)

    if args.list:
        for name, p in PROTOCOLS.items():
            twins = ", ".join(p.broken) or "-"
            print(f"{name:10s} twins=[{twins}]  {p.summary}")
        return 0
    if args.export is not None:
        return _export(args.export)

    run_models = args.all or args.protocols is not None
    if not run_models and args.replay is None:
        ap.print_usage(sys.stderr)
        print("error: give --all, --protocols or --replay",
              file=sys.stderr)
        return 2
    findings = []
    names = []
    if run_models:
        names, findings = _parse_protocols(
            None if args.all and args.protocols is None
            else args.protocols)
        findings += _check_models(names, args.twins, args.max_states)
    if args.replay is not None:
        findings += _replay(args.replay, args.kind)

    if findings:
        print(f"\nluxproto: {len(findings)} finding(s):",
              file=sys.stderr)
        for f in findings:
            print(f"  {f}", file=sys.stderr)
        return 1
    done = []
    if names:
        done.append(f"{len(names)} protocol(s) exhaustively clean"
                    + (" (+twins fail as designed)" if args.twins
                       else ""))
    if args.replay is not None:
        done.append(f"{len(args.replay)} log(s) conform")
    print(f"[PASS] luxproto: {'; '.join(done)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
