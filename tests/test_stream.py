"""Host-offload edge streaming (engine/stream.py): results match the
monolithic engine (bitwise for min/max combiners, association-only
drift for sums), the double-buffer knob changes nothing semantically,
and the capacity contract holds — peak resident edge bytes under a
budget the full edge arrays exceed.  ZC-analog of
core/lux_mapper.cc:146-165."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lux_tpu.engine import pull, stream
from lux_tpu.graph import generate
from lux_tpu.graph.shards import build_pull_shards
from lux_tpu.models.components import MaxLabelProgram
from lux_tpu.models.pagerank import PageRankProgram


def _mono(prog, sh, iters, method="scan"):
    s0 = pull.init_state(prog, jax.tree.map(jnp.asarray, sh.arrays))
    return s0, np.asarray(pull.run_pull_fixed(
        prog, sh.spec, sh.arrays, s0, iters, method=method))


@pytest.mark.parametrize("P", [1, 3])
def test_streamed_pagerank_matches(P):
    g = generate.rmat(11, 8, seed=20)
    sh = build_pull_shards(g, P)
    prog = PageRankProgram(nv=g.nv)
    s0, mono = _mono(prog, sh, 4)
    ssh = stream.build_streamed_pull(sh, 1024)
    assert len(ssh.chunks[0]) > 1  # actually multi-chunk
    out = np.asarray(stream.run_pull_fixed_streamed(
        prog, ssh, s0, 4, method="scan"))
    np.testing.assert_allclose(out, mono, rtol=2e-5, atol=1e-9)
    # serial (no double-buffer) path: same math entirely
    out2 = np.asarray(stream.run_pull_fixed_streamed(
        prog, ssh, s0, 4, method="scan", prefetch=False))
    assert (out2 == out).all()


def test_streamed_max_combiner_bitwise():
    """Max-label propagation (CC's pull form): cross-chunk maximum is
    associative AND commutative exactly -> bitwise equality."""
    g = generate.rmat(10, 8, seed=21)
    sh = build_pull_shards(g, 2)
    prog = MaxLabelProgram()
    s0, mono = _mono(prog, sh, 3)
    ssh = stream.build_streamed_pull(sh, 512)
    out = np.asarray(stream.run_pull_fixed_streamed(
        prog, ssh, s0, 3, method="scan"))
    assert (out == mono).all()


def test_streamed_until_cc_bitwise():
    """Convergence-driven streaming (CC max-label): same fixpoint, same
    iteration count, bitwise state vs the monolithic until-engine."""
    from lux_tpu.models import components

    g = generate.rmat(10, 8, seed=25)
    sh = build_pull_shards(g, 2)
    prog = MaxLabelProgram()
    s0 = pull.init_state(prog, jax.tree.map(jnp.asarray, sh.arrays))
    mono, iters = pull.run_pull_until(
        prog, sh.spec, sh.arrays, s0, 64, components.active_count,
        method="scan")
    ssh = stream.build_streamed_pull(sh, 512)
    got, it2 = stream.run_pull_until_streamed(
        prog, ssh, s0, 64, components.active_count, method="scan")
    assert int(iters) == it2
    assert (np.asarray(got) == np.asarray(mono)).all()


def test_streamed_weighted_cf_chunks():
    """Weighted + dst-state programs (CF error term) stream too: the
    chunk carries weights and the dst gather."""
    from lux_tpu.models.colfilter import CFProgram

    g = generate.bipartite_ratings(96, 64, 1024, seed=22)
    sh = build_pull_shards(g, 2)
    prog = CFProgram(gamma=1e-3)
    s0, mono = _mono(prog, sh, 3)
    ssh = stream.build_streamed_pull(sh, 512)
    out = np.asarray(stream.run_pull_fixed_streamed(
        prog, ssh, s0, 3, method="scan"))
    np.testing.assert_allclose(out, mono, rtol=3e-5, atol=1e-7)


def test_capacity_contract():
    """The feature's reason to exist: a budget the monolithic edge
    arrays EXCEED still admits a streamed run whose peak resident edge
    bytes fit it."""
    g = generate.rmat(11, 8, seed=23)
    sh = build_pull_shards(g, 1)
    total = stream.edge_bytes_total(sh.spec)
    # a budget sized for ~1/6 of the edges resident (toy graphs carry a
    # large fixed vertex-side footprint, so size it from the model; the
    # streamed per-edge footprint ~3x the monolithic 14 B/edge means the
    # chunk must stay well under e_pad/3 for budget < total to hold)
    budget = stream.streamed_hbm_bytes(
        sh.spec, sh.spec.e_pad // 6 // 128 * 128)
    assert budget < total
    chunk_e = stream.chunk_edges_for_budget(sh.spec, budget)
    assert 0 < chunk_e < sh.spec.e_pad
    resident = stream.streamed_hbm_bytes(sh.spec, chunk_e)
    assert resident <= budget < total
    ssh = stream.build_streamed_pull(sh, chunk_e)
    prog = PageRankProgram(nv=g.nv)
    s0, mono = _mono(prog, sh, 2)
    out = np.asarray(stream.run_pull_fixed_streamed(prog, ssh, s0, 2))
    np.testing.assert_allclose(out, mono, rtol=2e-5, atol=1e-9)
    # an impossible budget raises instead of silently thrashing
    with pytest.raises(ValueError, match="budget"):
        stream.chunk_edges_for_budget(sh.spec, 1000)


def test_cli_streamed_pagerank():
    """--stream-hbm-gib on the pagerank app: end-to-end under a budget
    forcing multiple chunks, -check verdict, and the combination
    rejections."""
    import subprocess
    import sys

    from conftest import forced_cpu_env

    env = forced_cpu_env()
    r = subprocess.run(
        [sys.executable, "-m", "lux_tpu.apps.pagerank", "--rmat-scale",
         "10", "-ni", "4", "--stream-hbm-gib", "0.002", "-check"],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "[PASS]" in r.stdout
    assert "chunk(s)" in r.stdout
    r2 = subprocess.run(
        [sys.executable, "-m", "lux_tpu.apps.pagerank", "--rmat-scale",
         "10", "--stream-hbm-gib", "0.002", "--compact-gather"],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert r2.returncode != 0
    assert "--stream-hbm-gib" in r2.stderr
    # components streams its pull form to CONVERGENCE (until driver)
    r4 = subprocess.run(
        [sys.executable, "-m", "lux_tpu.apps.components", "--rmat-scale",
         "10", "--stream-hbm-gib", "0.003", "-check"],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert r4.returncode == 0, r4.stderr[-2000:]
    assert "[PASS]" in r4.stdout and "converged in" in r4.stdout
    # colfilter streams its WIDE (V, K) state too (width-aware budget);
    # the budget forces MULTIPLE chunks so the cross-chunk combination
    # of (V, K) partials is actually exercised end-to-end
    r3 = subprocess.run(
        [sys.executable, "-m", "lux_tpu.apps.colfilter", "--rmat-scale",
         "9", "-ni", "3", "--stream-hbm-gib", "0.0005", "-check"],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert r3.returncode == 0, r3.stderr[-2000:]
    assert "[PASS]" in r3.stdout
    import re

    m = re.search(r"streamed: (\d+) chunk", r3.stdout)
    assert m and int(m.group(1)) >= 2, r3.stdout[:400]


def test_chunk_head_flags_rebuilt():
    """A destination segment split across a chunk border gets a fresh
    head at the border (the re-based row_ptr encodes it); padding stays
    sentinel."""
    g = generate.rmat(9, 8, seed=24)
    sh = build_pull_shards(g, 1)
    ssh = stream.build_streamed_pull(sh, 128)
    V = sh.spec.nv_pad
    for c, ch in enumerate(ssh.chunks[0]):
        m = int(min(sh.spec.e_pad - c * 128, 128))
        real = ch.dst_local[:m] < V
        if real.any():
            first = int(np.argmax(real))
            assert ch.head_flag[first]  # local segment start at border
        assert (ch.dst_local[m:] == V).all()
        # head positions == re-based row starts (derived, not stored)
        rp = stream._rebased_row_ptr(ssh.row_ptrs[0], ch.lo, 128)
        starts = rp[:V][rp[:V] < rp[1 : V + 1]]
        want = np.zeros(128, bool)
        want[starts] = True
        assert (ch.head_flag == want).all()
