"""serve/scheduler: coalescing, deadlines, backpressure, cold
degradation — driven deterministically with a fake clock and a fake
engine cache (no jax in the policy path)."""
import numpy as np
import pytest

from lux_tpu.serve.metrics import ServeMetrics
from lux_tpu.serve.scheduler import (
    MicroBatchScheduler,
    RejectedError,
    ServeTimeoutError,
)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class FakeResult:
    def __init__(self, queries):
        self.queries = list(queries)
        self.iters = 3
        self.rounds = np.full(len(queries), 3, np.int32)
        self.traversed = [100] * len(queries)

    def query_state(self, i):
        return np.asarray([self.queries[i]])  # echo the query back


class FakeEngine:
    def __init__(self, q, fail=False):
        self.q = q
        self.fail = fail
        self.calls = []

    def run(self, queries):
        assert len(queries) == self.q
        if self.fail:
            raise RuntimeError("engine exploded")
        self.calls.append(list(queries))
        return FakeResult(queries)


class FakeCache:
    """warm_buckets/get/is_warm shim around FakeEngines."""

    def __init__(self, warm=(4,), fail=False):
        self._warm = tuple(sorted(warm))
        self.engines = {}
        self.fail = fail
        self.cold_traces = 0
        self.warm_hits = 0

    def warm_buckets(self, app):
        return self._warm

    def current_overlay(self):
        return None  # static snapshot: no live overlay, no tags

    def get(self, app, q):
        eng = self.engines.setdefault(q, FakeEngine(q, fail=self.fail))
        warm = q in self._warm
        if warm:
            self.warm_hits += 1
        else:
            self.cold_traces += 1
        return eng, warm

    def stats(self):
        return {"warm_hits": self.warm_hits,
                "cold_traces": self.cold_traces}


def make(warm=(4,), **kw):
    clock = FakeClock()
    cache = FakeCache(warm=warm, fail=kw.pop("fail", False))
    sched = MicroBatchScheduler(cache, app="sssp", clock=clock,
                                metrics=ServeMetrics(), **kw)
    return sched, cache, clock


def test_coalesces_within_wait_window():
    sched, cache, clock = make(warm=(4,), max_wait_ms=10.0)
    futs = [sched.submit(i) for i in range(3)]
    # window not elapsed, bucket not full: nothing dispatches
    assert sched.step() == 0
    assert not futs[0].done()
    clock.t = 0.011  # past max_wait_ms
    assert sched.step() == 3
    # one padded batch in the smallest covering bucket
    assert cache.engines[4].calls == [[0, 1, 2, 0]]
    assert [f.result(timeout=0)[0] for f in futs] == [0, 1, 2]
    b = sched.metrics.batches[0]
    assert (b.q, b.real, b.warm) == (4, 3, True)


def test_full_bucket_dispatches_without_waiting():
    sched, cache, clock = make(warm=(2, 4), max_wait_ms=1e6)
    for i in range(4):
        sched.submit(i)
    assert sched.step() == 4  # t == 0: no window elapsed, bucket full
    assert cache.engines[4].calls == [[0, 1, 2, 3]]


def test_overflow_drains_in_bucket_sized_batches():
    sched, cache, clock = make(warm=(4,), max_wait_ms=0.0)
    futs = [sched.submit(i) for i in range(6)]
    assert sched.step() == 4
    assert sched.pending() == 2
    assert sched.step() == 2  # remainder padded into the same bucket
    assert cache.engines[4].calls == [[0, 1, 2, 3], [4, 5, 4, 4]]
    assert all(f.done() for f in futs)


def test_deadline_expiry_returns_timeout_not_hang():
    sched, cache, clock = make(warm=(4,), max_wait_ms=1e6)
    fut = sched.submit(7, timeout_ms=5.0)
    clock.t = 0.006  # past the deadline while still queued
    assert sched.step() == 1  # resolved AS a timeout
    with pytest.raises(ServeTimeoutError):
        fut.result(timeout=0)
    assert sched.metrics.timeouts == 1
    assert cache.engines == {}  # nothing ever dispatched


def test_result_wall_guard_never_hangs():
    sched, _, _ = make()
    fut = sched.submit(1)
    with pytest.raises(ServeTimeoutError):
        fut.result(timeout=0.01)  # nobody is pumping: guard fires


def test_tight_deadline_forces_early_dispatch():
    sched, cache, clock = make(warm=(4,), max_wait_ms=1000.0)
    fut = sched.submit(3, timeout_ms=50.0)
    # waiting out the 1 s window would blow the 50 ms deadline: dispatch
    assert sched.step() == 1
    assert fut.result(timeout=0)[0] == 3


def test_bounded_queue_rejects_with_retry_after():
    sched, _, clock = make(warm=(4,), max_queue=2, max_wait_ms=1e6)
    sched.submit(0)
    sched.submit(1)
    with pytest.raises(RejectedError) as e:
        sched.submit(2)
    assert e.value.retry_after_ms > 0
    assert sched.metrics.rejected == 1
    assert sched.pending() == 2  # rejected request never queued


def test_cold_shape_degrades_to_q1():
    sched, cache, clock = make(warm=(), max_wait_ms=0.0)
    futs = [sched.submit(i) for i in range(3)]
    sched.drain()
    # nothing warm: served singly through the cold Q=1 engine
    assert cache.engines[1].calls == [[0], [1], [2]]
    assert cache.cold_traces >= 1
    assert [f.result(timeout=0)[0] for f in futs] == [0, 1, 2]
    assert sched.metrics.summary()["warm_batch_ratio"] == 0.0


def test_engine_failure_resolves_requests_with_error():
    sched, cache, clock = make(warm=(2,), max_wait_ms=0.0, fail=True)
    fut = sched.submit(5)
    sched.step()
    with pytest.raises(RuntimeError, match="engine exploded"):
        fut.result(timeout=0)


def test_metrics_summary_shape():
    sched, cache, clock = make(warm=(4,), max_wait_ms=0.0)
    for i in range(4):
        sched.submit(i)
    sched.step()
    s = sched.metrics.summary(elapsed_s=1.0, cache_stats=cache.stats())
    assert s["completed"] == 4
    assert s["qps"] == 4.0
    assert s["batch_occupancy"] == 1.0
    assert set(s["latency_ms"]) == {"p50", "p95", "p99"}
    assert s["engine_cache"]["warm_hits"] == 1


def test_threaded_loop_end_to_end():
    """Background-thread mode with the REAL clock (tiny window)."""
    cache = FakeCache(warm=(4,))
    sched = MicroBatchScheduler(cache, app="sssp", max_wait_ms=2.0,
                                metrics=ServeMetrics()).start()
    try:
        futs = [sched.submit(i) for i in range(3)]
        got = [f.result(timeout=5.0)[0] for f in futs]
        assert got == [0, 1, 2]
    finally:
        sched.stop()
