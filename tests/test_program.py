"""luxprog (ISSUE 13): the declarative vertex-program compiler.

Three claim families:

  1. SPEC-VS-HANDWIRED BITWISE PINS — the four reference apps'
     spec-backed programs against in-test copies of the DELETED
     hand-wired bodies, across the execution surfaces: pull
     fixed/until (direct + routed-pf), push (sparse/dense), mutation
     overlays on both engines, and the serve Q-axis batched step.
  2. ORACLE CHECKS for the four payoff workloads (bfs, kcore,
     labelprop, triangles) — NetworkX-free NumPy oracles — plus the
     generic CLI driver end-to-end.
  3. ZERO-RETRACE: spec-compiled programs hit the SAME jit/lru compile
     caches as any other program dataclass (equal specs ARE one
     program), probed with ``_cache_size`` across fresh instances.
"""
from __future__ import annotations

import dataclasses
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lux_tpu.engine import pull, push
from lux_tpu.graph import generate
from lux_tpu.graph.csc import from_edge_list
from lux_tpu.graph.push_shards import build_push_shards
from lux_tpu.graph.shards import build_pull_shards
from lux_tpu.program import (BatchedSpecProgram, SpecProgram,
                             VertexProgramSpec, active_changed, library)
from lux_tpu.program import expr as expr_mod
from lux_tpu.program import workloads
from lux_tpu.program.spec import bind


# ---------------------------------------------------------------------------
# the deleted hand-wired bodies, preserved here as the bitwise reference
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class _HandPageRank:
    nv: int
    alpha: float = 0.15
    dtype: str = "float32"
    reduce: str = dataclasses.field(default="sum", init=False)

    def init_state(self, global_vid, degree, vtx_mask):
        rank = jnp.float32(1.0 / self.nv)
        deg = degree.astype(jnp.float32)
        state = jnp.where(degree > 0, rank / jnp.maximum(deg, 1.0), rank)
        return jnp.where(vtx_mask, state, 0.0).astype(self.dtype)

    def edge_value(self, src_state, weight, dst_state=None):
        del weight, dst_state
        return src_state.astype(jnp.float32)

    def apply(self, old_local, acc, arrays):
        del old_local
        init_rank = jnp.float32((1.0 - self.alpha) / self.nv)
        pr = init_rank + jnp.float32(self.alpha) * acc
        deg = arrays.degree.astype(jnp.float32)
        pr = jnp.where(arrays.degree > 0, pr / jnp.maximum(deg, 1.0), pr)
        return jnp.where(arrays.vtx_mask, pr, 0.0).astype(self.dtype)


@dataclasses.dataclass(frozen=True)
class _HandPPR(_HandPageRank):
    seed: int = 0

    def init_state(self, global_vid, degree, vtx_mask):
        mass = (global_vid == self.seed).astype(jnp.float32)
        deg = jnp.maximum(degree.astype(jnp.float32), 1.0)
        state = jnp.where(degree > 0, mass / deg, mass)
        return jnp.where(vtx_mask, state, 0.0).astype(self.dtype)

    def apply(self, old_local, acc, arrays):
        del old_local
        mass = (arrays.global_vid == self.seed).astype(jnp.float32)
        pr = jnp.float32(1.0 - self.alpha) * mass \
            + jnp.float32(self.alpha) * acc
        deg = arrays.degree.astype(jnp.float32)
        pr = jnp.where(arrays.degree > 0, pr / jnp.maximum(deg, 1.0), pr)
        return jnp.where(arrays.vtx_mask, pr, 0.0).astype(self.dtype)


@dataclasses.dataclass(frozen=True)
class _HandSSSP:
    nv: int
    start: int = 0
    reduce: str = dataclasses.field(default="min", init=False)

    @property
    def inf(self):
        return self.nv

    def init_state(self, global_vid, degree, vtx_mask):
        del degree
        inf = jnp.int32(self.inf)
        d = jnp.where(global_vid == self.start, jnp.int32(0), inf)
        return jnp.where(vtx_mask, d, inf)

    def init_frontier(self, global_vid, state, vtx_mask):
        del state
        return (global_vid == self.start) & vtx_mask

    def relax(self, src_val, weight):
        del weight
        return src_val + jnp.int32(1)


@dataclasses.dataclass(frozen=True)
class _HandWeightedSSSP(_HandSSSP):
    @property
    def inf(self):
        return 1 << 30

    def relax(self, src_val, weight):
        return src_val + weight.astype(jnp.int32)


@dataclasses.dataclass(frozen=True)
class _HandMaxLabel:
    reduce: str = dataclasses.field(default="max", init=False)

    def init_state(self, global_vid, degree, vtx_mask):
        del degree
        return jnp.where(vtx_mask, global_vid, -1)

    def edge_value(self, src_state, weight, dst_state=None):
        del weight, dst_state
        return src_state

    def apply(self, old_local, acc, arrays):
        new = jnp.maximum(old_local, acc)
        return jnp.where(jnp.asarray(arrays.vtx_mask), new, old_local)

    def init_frontier(self, global_vid, state, vtx_mask):
        del global_vid, state
        return vtx_mask

    def relax(self, src_val, weight):
        del weight
        return src_val


@dataclasses.dataclass(frozen=True)
class _HandCF:
    k: int = 20
    lam: float = 1e-3
    gamma: float = 3.5e-7
    dtype: str = "float32"
    err_dot: str = "vpu"
    reduce: str = dataclasses.field(default="sum", init=False)
    needs_dst_state: bool = dataclasses.field(default=True, init=False)

    def init_state(self, global_vid, degree, vtx_mask):
        del degree
        v0 = jnp.full((global_vid.shape[0], self.k),
                      np.sqrt(1.0 / self.k), jnp.float32)
        return jnp.where(vtx_mask[:, None], v0, 0.0).astype(self.dtype)

    def edge_value(self, src_state, weight, dst_state=None):
        from lux_tpu.models.colfilter import err_dot

        src = src_state.astype(jnp.float32)
        dst = dst_state.astype(jnp.float32)
        err = weight - err_dot(src, dst, self.err_dot)
        return err[..., None] * src

    def apply(self, old_local, acc, arrays):
        old = old_local.astype(jnp.float32)
        new = old + jnp.float32(self.gamma) * (
            acc - jnp.float32(self.lam) * old)
        return jnp.where(
            jnp.asarray(arrays.vtx_mask)[:, None], new, old
        ).astype(self.dtype)


@lru_cache(maxsize=1)
def _fx():
    g = generate.rmat(8, 6, seed=3)
    sh = build_pull_shards(g, 2)
    psh = build_push_shards(g, 2)
    arrays = jax.tree.map(jnp.asarray, sh.arrays)
    return g, sh, psh, arrays


@lru_cache(maxsize=1)
def _fx_w():
    gw = generate.rmat(7, 5, seed=5, weighted=True, max_weight=9)
    return gw, build_push_shards(gw, 2)


def _run_fixed(prog, sh, arrays, n=4, route=None, overlay=None):
    s0 = pull.init_state(prog, arrays)
    return np.asarray(pull.run_pull_fixed(
        prog, sh.spec, arrays, s0, n, "scan", route=route,
        overlay=overlay))


# ---------------------------------------------------------------------------
# 1. spec-vs-handwired bitwise pins
# ---------------------------------------------------------------------------


def test_pagerank_spec_bitwise_pull_direct_and_routed_pf():
    from lux_tpu.models.pagerank import PageRankProgram
    from lux_tpu.ops import expand

    g, sh, _, arrays = _fx()
    spec_p = PageRankProgram(nv=sh.spec.nv)
    hand_p = _HandPageRank(nv=sh.spec.nv)
    assert np.array_equal(_run_fixed(spec_p, sh, arrays),
                          _run_fixed(hand_p, sh, arrays))
    plan = expand.to_pf(expand.plan_expand_shards(sh))
    rt = (plan[0], jax.tree.map(jnp.asarray, plan[1]))
    assert np.array_equal(_run_fixed(spec_p, sh, arrays, route=rt),
                          _run_fixed(hand_p, sh, arrays, route=rt))
    # bf16 storage twin
    assert np.array_equal(
        _run_fixed(PageRankProgram(nv=sh.spec.nv, dtype="bfloat16"), sh,
                   arrays),
        _run_fixed(_HandPageRank(nv=sh.spec.nv, dtype="bfloat16"), sh,
                   arrays))


def test_ppr_spec_bitwise():
    from lux_tpu.models.pagerank import PPRProgram

    g, sh, _, arrays = _fx()
    assert np.array_equal(
        _run_fixed(PPRProgram(nv=sh.spec.nv, seed=17), sh, arrays),
        _run_fixed(_HandPPR(nv=sh.spec.nv, seed=17), sh, arrays))


def test_pagerank_spec_bitwise_overlay():
    """Mutation-overlay surface (PR 10): base tombstones + inserts
    through the spec-compiled program == the hand-wired one, bitwise."""
    from lux_tpu.models.pagerank import PageRankProgram
    from lux_tpu.mutate import OP_DELETE, OP_INSERT, MutableGraph

    g, _, _, _ = _fx()
    rng = np.random.default_rng(0)
    mg = MutableGraph(g, num_parts=2, cap=128)
    dele = rng.choice(g.ne, 16, replace=False)
    mg.apply(g.col_idx[dele], g.dst_of_edges()[dele],
             np.full(16, OP_DELETE, np.int8))
    mg.apply(rng.integers(0, g.nv, 24), rng.integers(0, g.nv, 24),
             np.full(24, OP_INSERT, np.int8))
    sh = mg.pull_shards
    arrays = jax.tree.map(jnp.asarray, sh.arrays)
    ov = mg.pull_overlay()
    assert np.array_equal(
        _run_fixed(PageRankProgram(nv=sh.spec.nv), sh, arrays, overlay=ov),
        _run_fixed(_HandPageRank(nv=sh.spec.nv), sh, arrays, overlay=ov))


def test_sssp_spec_bitwise_push_direct_routed_weighted():
    from lux_tpu.models.sssp import SSSPProgram, WeightedSSSPProgram
    from lux_tpu.ops import expand

    g, sh, psh, _ = _fx()
    start = int(np.argmax(np.bincount(g.col_idx, minlength=g.nv)))
    for spec_p, hand_p, shards in (
        (SSSPProgram(nv=g.nv, start=start),
         _HandSSSP(nv=g.nv, start=start), psh),
        (WeightedSSSPProgram(nv=_fx_w()[0].nv, start=start),
         _HandWeightedSSSP(nv=_fx_w()[0].nv, start=start), _fx_w()[1]),
    ):
        s_s, it_s, e_s = push.run_push(spec_p, shards, 1000, "scan")
        s_h, it_h, e_h = push.run_push(hand_p, shards, 1000, "scan")
        assert np.array_equal(np.asarray(s_s), np.asarray(s_h))
        assert int(it_s) == int(it_h)
        assert np.array_equal(np.asarray(e_s), np.asarray(e_h))
    # routed-pf dense rounds (the push --route-gather expand-pf surface)
    plan = expand.to_pf(expand.plan_expand_shards(psh))
    rt = (plan[0], jax.tree.map(jnp.asarray, plan[1]))
    s_s, _, _ = push.run_push(SSSPProgram(nv=g.nv, start=start), psh,
                              1000, "scan", route=rt)
    s_h, _, _ = push.run_push(_HandSSSP(nv=g.nv, start=start), psh,
                              1000, "scan", route=rt)
    assert np.array_equal(np.asarray(s_s), np.asarray(s_h))


def test_sssp_spec_bitwise_push_overlay():
    """Push-engine overlay surface: churn through the spec program ==
    the hand-wired one (compile_push_chunk overlay twins)."""
    from lux_tpu.models.sssp import SSSPProgram
    from lux_tpu.mutate import OP_INSERT, MutableGraph

    g, _, _, _ = _fx()
    rng = np.random.default_rng(1)
    mg = MutableGraph(g, num_parts=2, cap=128)
    mg.apply(rng.integers(0, g.nv, 24), rng.integers(0, g.nv, 24),
             np.full(24, OP_INSERT, np.int8))
    pshards = mg.push_shards
    ostatic, oarr, parr = mg.push_overlay()
    start = int(np.argmax(np.bincount(g.col_idx, minlength=g.nv)))
    outs = []
    for prog in (SSSPProgram(nv=g.nv, start=start),
                 _HandSSSP(nv=g.nv, start=start)):
        arrays, _, carry0 = push.push_init(prog, pshards)
        loop = push.compile_push_chunk(prog, pshards.pspec, pshards.spec,
                                       "scan", overlay_static=ostatic)
        out = loop(arrays, jax.tree.map(jnp.asarray, parr), carry0,
                   jnp.int32(1000),
                   oarrays=jax.tree.map(jnp.asarray, oarr))
        outs.append(np.asarray(out.state))
    assert np.array_equal(outs[0], outs[1])


def test_components_spec_bitwise_pull_until_and_push():
    from lux_tpu.models.components import MaxLabelProgram

    g, sh, psh, arrays = _fx()
    spec_p, hand_p = MaxLabelProgram(), _HandMaxLabel()
    s_s, it_s = pull.run_pull_until(
        spec_p, sh.spec, arrays, pull.init_state(spec_p, arrays), 100,
        active_changed, "scan")
    s_h, it_h = pull.run_pull_until(
        hand_p, sh.spec, arrays, pull.init_state(hand_p, arrays), 100,
        active_changed, "scan")
    assert np.array_equal(np.asarray(s_s), np.asarray(s_h))
    assert int(it_s) == int(it_h)
    p_s, _, _ = push.run_push(spec_p, psh, 1000, "scan")
    p_h, _, _ = push.run_push(hand_p, psh, 1000, "scan")
    assert np.array_equal(np.asarray(p_s), np.asarray(p_h))


def test_colfilter_spec_bitwise_direct_and_cf_route():
    from lux_tpu.models.colfilter import CFProgram
    from lux_tpu.ops import expand

    gw = generate.bipartite_ratings(128, 128, 1500, seed=1)
    sh = build_pull_shards(gw, 2)
    arrays = jax.tree.map(jnp.asarray, sh.arrays)
    spec_p, hand_p = CFProgram(), _HandCF()
    assert np.array_equal(_run_fixed(spec_p, sh, arrays, n=3),
                          _run_fixed(hand_p, sh, arrays, n=3))
    plan = expand.plan_cf_route_shards(sh)
    rt = (plan[0], jax.tree.map(jnp.asarray, plan[1]))
    assert np.array_equal(_run_fixed(spec_p, sh, arrays, n=3, route=rt),
                          _run_fixed(hand_p, sh, arrays, n=3, route=rt))
    # the mxu error-dot lowering stays a program parameter
    assert np.array_equal(
        _run_fixed(CFProgram(err_dot="mxu"), sh, arrays, n=2),
        _run_fixed(_HandCF(err_dot="mxu"), sh, arrays, n=2))


def test_serve_batched_spec_bitwise():
    """The Q-axis lift: serve's spec-backed MultiSource programs ==
    hand-wired batched bodies, and each column == the single-query
    spec program (one spec, three lowerings)."""
    from lux_tpu.models.pagerank import PPRProgram
    from lux_tpu.serve import batched as sb

    g, sh, _, arrays = _fx()
    queries = jnp.asarray(np.array([0, 9, 40, 177], np.int32))

    @dataclasses.dataclass(frozen=True)
    class _HandMSPPR(sb.QueryProgram):
        nv: int
        alpha: float = 0.15
        reduce: str = dataclasses.field(default="sum", init=False)
        fixpoint: bool = dataclasses.field(default=False, init=False)

        def init_part(self, global_vid, degree, vtx_mask, queries):
            seed = (global_vid[:, None] == queries[None, :]).astype(
                jnp.float32)
            deg = jnp.maximum(degree.astype(jnp.float32), 1.0)[:, None]
            state = jnp.where(degree[:, None] > 0, seed / deg, seed)
            return jnp.where(vtx_mask[:, None], state, 0.0)

        def edge_value(self, src_state, weights):
            del weights
            return src_state.astype(jnp.float32)

        def apply(self, old_local, acc, arr, queries):
            del old_local
            seed = (arr.global_vid[:, None] == queries[None, :]).astype(
                jnp.float32)
            pr = jnp.float32(1.0 - self.alpha) * seed \
                + jnp.float32(self.alpha) * acc
            deg = arr.degree.astype(jnp.float32)[:, None]
            pr = jnp.where(arr.degree[:, None] > 0,
                           pr / jnp.maximum(deg, 1.0), pr)
            return jnp.where(arr.vtx_mask[:, None], pr, 0.0)

    spec_p = sb.MultiSourcePPR(nv=sh.spec.nv)
    hand_p = _HandMSPPR(nv=sh.spec.nv)
    outs = {}
    for name, prog in (("spec", spec_p), ("hand", hand_p)):
        run = sb._compile_batched_fixed(prog, sh.spec, "scan")
        state0 = sb._batched_iteration  # noqa: F841 (doc anchor)
        init = sb._compile_batched_init(prog)
        state, _, _ = run(arrays, queries, init(arrays, queries),
                          jnp.int32(4))
        outs[name] = np.asarray(state)
    assert np.array_equal(outs["spec"], outs["hand"])
    # column q == the single-seed spec program's pull run (two columns:
    # each seed is its own compiled single-query program — lanes are
    # independent, so two pins buy what four would)
    glob = sh.scatter_to_global(outs["spec"])  # (nv, Q)
    for qi in (0, 3):
        seed = int(np.asarray(queries)[qi])
        single = _run_fixed(PPRProgram(nv=sh.spec.nv, seed=seed),
                            sh, arrays, n=4)
        assert np.array_equal(glob[:, qi],
                              sh.scatter_to_global(single)), qi


@pytest.mark.slow
def test_serve_sssp_engine_matches_push():
    """BatchedEngine (spec path end-to-end) vs the one-shot push run.
    Slow tier: tier-1's test_serve_batched already pins the batched
    engines against push/pull bitwise — this is the e2e double-check."""
    from lux_tpu.models.sssp import sssp
    from lux_tpu.serve.batched import BatchedEngine

    g, sh, _, _ = _fx()
    srcs = np.array([3, 50, 120], np.int32)
    eng = BatchedEngine(sh, "sssp", len(srcs), method="scan")
    res = eng.run(srcs)
    for qi, s in enumerate(srcs):
        assert np.array_equal(res.state[qi], sssp(g, start=int(s))), qi


# ---------------------------------------------------------------------------
# 2. the four payoff workloads: oracles + CLI
# ---------------------------------------------------------------------------


def test_bfs_push_pull_routed_match_oracle():
    from lux_tpu.ops import expand

    g, sh, psh, _ = _fx()
    sources = (3, 77, 200)
    ref = workloads.bfs_reference(g, sources)
    d_push, _ = workloads.bfs(psh, sources)
    assert np.array_equal(d_push, ref)
    d_pull, _ = workloads.bfs(sh, sources, engine="pull")
    assert np.array_equal(d_pull, ref)
    plan = expand.plan_expand_shards(psh)
    d_rt, _ = workloads.bfs(psh, sources,
                            route=(plan[0],
                                   jax.tree.map(jnp.asarray, plan[1])))
    assert np.array_equal(d_rt, ref)
    assert workloads.check_bfs(g, ref, sources) == 0
    # the -check gate bounds distances from BOTH sides: an all-zeros
    # answer (sources fine, every edge satisfied) must FAIL the
    # lower-bound/fixpoint leg, and an over-estimate the upper bound
    assert workloads.check_bfs(g, np.zeros(g.nv, np.int32), sources) > 0
    over = ref.copy()
    over[ref == 1] = 3
    assert workloads.check_bfs(g, over, sources) > 0


@pytest.mark.slow
def test_bfs_single_source_matches_sssp():
    """BFS at one source is sssp's unweighted relaxation — the spec
    family's internal consistency check (slow tier: the oracle test
    above already pins bfs on every surface)."""
    from lux_tpu.models.sssp import sssp

    g, _, psh, _ = _fx()
    d, _ = workloads.bfs(psh, (11,))
    assert np.array_equal(d, sssp(g, start=11))


def test_kcore_matches_peel_oracle():
    # a capped peel keeps the tier-1 cost at 5 level compiles; coreness
    # below the cap must still match the (capped) oracle exactly
    g, sh, _, _ = _fx()
    core, kmax, rounds = workloads.kcore(sh, kmax=5)
    ref = workloads.kcore_reference(g, kmax=5)
    assert np.array_equal(core, ref)
    assert kmax == int(ref.max()) == 5 and rounds > kmax
    # the invariant check passes on any capped prefix too: every vertex
    # at level c keeps >= c in-neighbors at its own level
    assert workloads.check_kcore(g, core) == 0


@pytest.mark.slow
def test_kcore_full_peel_and_symmetrized():
    g, sh, _, _ = _fx()
    core, kmax, _ = workloads.kcore(sh)
    ref = workloads.kcore_reference(g)
    assert np.array_equal(core, ref) and kmax == int(ref.max()) >= 2
    gs = workloads.symmetrize(g)
    core_s, _, _ = workloads.kcore(gs, kmax=3)
    assert np.array_equal(core_s, workloads.kcore_reference(gs, kmax=3))


def test_labelprop_matches_float64_oracle():
    g, sh, _, _ = _fx()
    probs = workloads.labelprop(sh, labels=6, stride=8, num_iters=5)
    ref = workloads.labelprop_reference(g, labels=6, stride=8, num_iters=5)
    assert probs.shape == (g.nv, 6)
    np.testing.assert_allclose(probs, ref, rtol=2e-4, atol=1e-6)
    assert workloads.check_labelprop(probs, 6, 8) == 0


def test_triangles_matches_oracle_and_exact_count():
    # K6 complete graph: C(6,3) = 20 triangles, exactly counted
    n = 6
    pairs = [(a, b) for a in range(n) for b in range(n) if a != b]
    es = np.array([p[0] for p in pairs])
    ed = np.array([p[1] for p in pairs])
    g6 = from_edge_list(es, ed, n, weights=np.ones(len(es), np.int32))
    inc, stats = workloads.triangles(g6)
    assert stats["triangles_if_unit"] == 20.0
    assert np.array_equal(inc, workloads.triangles_reference(g6))
    # weighted, on a symmetrized rmat draw
    g = generate.rmat(7, 4, seed=9, weighted=True, max_weight=7)
    gs = workloads.symmetrize(g)
    inc, _ = workloads.triangles(gs, num_parts=2)
    ref = workloads.triangles_reference(gs)
    np.testing.assert_allclose(inc, ref, rtol=1e-5)
    assert workloads.check_triangles(gs, inc) == 0


def test_triangles_guards():
    g, _, _, _ = _fx()
    with pytest.raises(ValueError, match="weighted"):
        workloads.triangles(g)  # unweighted
    big = generate.path_graph(workloads.TRIANGLES_MAX_NV + 1)
    big.weights = np.ones(big.ne, np.int32)
    with pytest.raises(ValueError, match="quadratic"):
        workloads.triangles(big)
    # a MULTIgraph corrupts the sum-as-union bitsets via binary carry:
    # refused loudly, never a silently-wrong count
    dup = from_edge_list(np.array([1, 1, 2]), np.array([0, 0, 0]), 3,
                         weights=np.ones(3, np.int32))
    with pytest.raises(ValueError, match="SIMPLE"):
        workloads.triangles(dup)


def test_integer_sum_strategies_stay_exact():
    """The scan-family refinement must never corrupt INTEGER sum
    programs: matmul_cumsum accumulates f32, so a banked (or forced)
    mxsum downgrades to the bitwise scan for integer values — pinned
    end-to-end on the uint32 bitset workload (a 2^31 bit is not f32-
    representable; the pre-fix run lost high bits and failed -check)."""
    from lux_tpu.ops import segment

    rng = np.random.default_rng(0)
    vals = (np.uint32(1) << rng.integers(0, 32, 64).astype(np.uint32))
    row_ptr = jnp.asarray(np.array([0, 20, 20, 45, 64], np.int32))
    head = np.zeros(64, bool)
    head[[0, 20, 45]] = True
    dst = np.repeat(np.arange(4), np.diff([0, 20, 20, 45, 64]))
    args = (jnp.asarray(vals), row_ptr, jnp.asarray(head),
            jnp.asarray(dst.astype(np.int32)))
    ref = np.asarray(segment.segment_sum_csc(*args, method="scan"))
    for m in ("mxsum", "cumsum", "scatter", "mxscan"):
        got = np.asarray(segment.segment_sum_csc(*args, method=m))
        assert got.dtype == ref.dtype and np.array_equal(got, ref), m
    # end-to-end: the triangles workload under a forced mxsum winner
    gt = workloads.symmetrize(generate.rmat(7, 4, seed=9, weighted=True))
    inc, _ = workloads.triangles(gt, method="mxsum")
    np.testing.assert_allclose(inc, workloads.triangles_reference(gt),
                               rtol=1e-5)


def test_bfs_pull_mesh_refuses_route():
    g, sh, _, _ = _fx()
    if len(jax.devices()) < 2:
        pytest.skip("needs the multi-device CPU harness")
    from lux_tpu.parallel.mesh import make_mesh_for_parts

    with pytest.raises(ValueError, match="route"):
        workloads.bfs(sh, (3,), num_parts=2, engine="pull",
                      mesh=make_mesh_for_parts(2), route=("fake", None))


def test_run_cli_labelprop(capsys):
    """One generic-driver e2e stays in tier-1 (the factored CLI path);
    the full four-program sweep rides the slow tier + the ci_check
    program_smoke stage (bfs + triangles, [PASS]-gated)."""
    from lux_tpu.apps import run as run_app

    small = ["--rmat-scale", "7", "--rmat-ef", "5"]
    assert run_app.main(["labelprop"] + small
                        + ["--labels", "4", "-ni", "2", "-check"]) == 0
    assert "[PASS] labelprop" in capsys.readouterr().out


@pytest.mark.slow
def test_run_cli_all_programs(capsys):
    from lux_tpu.apps import run as run_app

    small = ["--rmat-scale", "7", "--rmat-ef", "5"]
    assert run_app.main(["bfs"] + small + ["--sources", "0,3", "-check"]) == 0
    out = capsys.readouterr().out
    assert "[PASS] bfs" in out and "reached" in out
    assert run_app.main(["kcore"] + small + ["--kmax", "3", "-check"]) == 0
    assert "[PASS] kcore" in capsys.readouterr().out
    assert run_app.main(["triangles"] + small + ["-check"]) == 0
    out = capsys.readouterr().out
    assert "[PASS] triangles" in out and "unit weights, exact" in out


def test_run_cli_rejections(capsys):
    from lux_tpu.apps import run as run_app

    assert run_app.main(["nope"]) == 2
    assert "unknown program" in capsys.readouterr().err
    with pytest.raises(SystemExit):
        run_app.main(["bfs", "--rmat-scale", "7", "--sources", "frog"])
    with pytest.raises(SystemExit):
        run_app.main(["labelprop", "--rmat-scale", "7",
                      "--route-gather", "expand"])


# ---------------------------------------------------------------------------
# 3. cache identity / zero-retrace, language guards
# ---------------------------------------------------------------------------


def test_spec_program_equality_and_zero_retrace():
    """Two freshly-constructed equal programs ARE one program to the
    compile caches: the pull jit cache does not grow on the second
    run, and the push chunk lru returns the identical compiled loop."""
    g, sh, psh, arrays = _fx()

    def fresh():
        return bind(library.KCORE, kk=2)

    assert fresh() == fresh() and hash(fresh()) == hash(fresh())
    s0 = pull.init_state(fresh(), arrays)
    pull.run_pull_fixed(fresh(), sh.spec, arrays, s0, 2, "scan")
    size0 = pull._pull_fixed_jit._cache_size()
    pull.run_pull_fixed(fresh(), sh.spec, arrays, s0, 2, "scan")
    assert pull._pull_fixed_jit._cache_size() == size0
    # model classes are spec-backed dataclasses with the same property
    from lux_tpu.models.sssp import SSSPProgram

    l1 = push.compile_push_chunk(SSSPProgram(nv=g.nv, start=1),
                                 psh.pspec, psh.spec, "scan")
    l2 = push.compile_push_chunk(SSSPProgram(nv=g.nv, start=1),
                                 psh.pspec, psh.spec, "scan")
    assert l1 is l2


def test_spec_program_param_identity_is_static():
    """Different bindings are different programs (kcore's per-level
    compile is honest), equal bindings are not."""
    a, b = bind(library.KCORE, kk=2), bind(library.KCORE, kk=3)
    assert a != b and a == bind(library.KCORE, kk=2)


def test_expr_language_rejects_out_of_vocabulary():
    for bad in (
        "__import__('os').system('x')",
        "src.dtype",
        "src[0]",
        "[x for x in src]",
        "lambda x: x",
        "src if weight else dst",
        "a = 1",  # no final expression
        "f = exec",
    ):
        with pytest.raises(expr_mod.SpecSyntaxError):
            expr_mod.check(bad)
    with pytest.raises(expr_mod.SpecSyntaxError, match="unknown name"):
        expr_mod.run("nope + 1", {"x": 1})
    with pytest.raises(expr_mod.SpecSyntaxError, match="unknown function"):
        expr_mod.run("frobnicate(x)", {"x": 1})


def test_spec_validation_at_definition():
    with pytest.raises(ValueError, match="monoid"):
        VertexProgramSpec(name="bad", reduce="mean", init="vid", edge="src")
    with pytest.raises(ValueError, match="convergence"):
        VertexProgramSpec(name="bad", reduce="sum", init="vid",
                          edge="src", convergence="whenever")
    with pytest.raises(expr_mod.SpecSyntaxError, match="bad.*init"):
        VertexProgramSpec(name="bad", reduce="sum", init="vid ++", edge="s")


def test_lowering_guards():
    """Reduce-only phases refuse update loops; pull-only specs refuse
    the push contract; dst-reading specs refuse the push relax; specs
    without a query param refuse the serve lift."""
    g, sh, _, arrays = _fx()
    tri = bind(library.TRI_COUNT)
    with pytest.raises(ValueError, match="reduce-only"):
        tri.apply(None, None, sh.arrays)
    with pytest.raises(ValueError, match="no frontier"):
        bind(library.KCORE, kk=1).init_frontier(None, None, None)
    with pytest.raises(ValueError, match="destination state"):
        bind(library.COLFILTER, k=20, lam=0.0, gamma=0.0,
             dtype="float32", err_dot="vpu").relax(None, None)
    with pytest.raises(ValueError, match="query_param"):
        BatchedSpecProgram(library.COMPONENTS).init_part(
            None, None, None, None)


def test_registry_covers_all_shipped_programs():
    assert set(library.REGISTRY) == {
        "pagerank", "ppr", "sssp", "sssp_weighted", "components",
        "colfilter", "bfs", "kcore", "labelprop", "tri_neighbors",
        "tri_count"}
    for s in library.REGISTRY.values():
        assert isinstance(s, VertexProgramSpec)


@pytest.mark.slow
def test_spec_programs_on_virtual_mesh():
    """Dist-engine surface: spec programs run the shard_map engines on
    the virtual mesh unchanged (pull fixed + push dist).  Slow tier:
    tier-1's test_dist/test_ring/test_scatter already drive the dist
    engines through the (now spec-backed) model programs every run."""
    from lux_tpu.models.pagerank import PageRankProgram
    from lux_tpu.parallel import dist
    from lux_tpu.parallel.mesh import make_mesh_for_parts

    if len(jax.devices()) < 4:
        pytest.skip("needs the 8-device CPU harness")
    g, sh4, psh4, _ = None, None, None, None
    g = generate.rmat(8, 6, seed=3)
    sh4 = build_pull_shards(g, 4)
    psh4 = build_push_shards(g, 4)
    mesh = make_mesh_for_parts(4)
    prog = PageRankProgram(nv=sh4.spec.nv)
    s0 = pull.init_state(prog, jax.tree.map(jnp.asarray, sh4.arrays))
    out = dist.run_pull_fixed_dist(prog, sh4.spec, sh4.arrays, s0, 3,
                                   mesh, "scan")
    ref = _run_fixed(_HandPageRank(nv=sh4.spec.nv), sh4,
                     jax.tree.map(jnp.asarray, sh4.arrays), n=3)
    assert np.array_equal(np.asarray(out), ref)
    # push-dist with a spec-only workload (bfs)
    d_dist, _ = workloads.bfs(psh4, (3, 77), mesh=mesh)
    assert np.array_equal(d_dist, workloads.bfs_reference(g, (3, 77)))
