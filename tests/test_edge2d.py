"""2-D (parts x edge) parallelism: edge-sharded partial reductions must be
exact for sum/min/max programs."""
import numpy as np
import pytest

from lux_tpu.engine import pull
from lux_tpu.graph import generate
from lux_tpu.models import pagerank as pr
from lux_tpu.parallel import edge2d


def _state0(prog, shards):
    return pull.init_state(prog, shards.arrays)


@pytest.mark.parametrize("shape", [(4, 2), (2, 4)])
def test_edge2d_pagerank_matches_oracle(shape):
    P, EP = shape
    g = generate.rmat(9, 8, seed=130)
    shards = edge2d.build_edge2d_shards(g, P, EP)
    mesh = edge2d.make_mesh2d(P, EP)
    prog = pr.PageRankProgram(nv=shards.spec.nv)
    out = edge2d.run_pull_fixed_2d(prog, shards, _state0(prog, shards), 5, mesh)
    got = shards.scatter_to_global(np.asarray(out))
    np.testing.assert_allclose(got, pr.pagerank_reference(g, 5), rtol=3e-5)
    assert len(out.sharding.device_set) >= P


def test_edge2d_win_condition():
    """The layout's reason to exist (reference limitation: one part ==
    one GPU, core/graph.h:31): a synthetic per-device budget the 1-D
    part CANNOT fit — preflight rejects it, suggest_edge_shards names
    the smallest EP that fits, and THAT 2-D run executes correctly.
    (VERDICT r4 weak #4: no prior test constructed the win condition.)"""
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.utils import preflight

    g = generate.rmat(10, 16, seed=133)
    P = 2
    sh1 = build_pull_shards(g, P)
    est1 = preflight.estimate_pull(sh1.spec)
    # budget between the 2-D floor and the 1-D footprint: the edge
    # arrays dominate at ef=16, so halving them via EP=2 must fit
    budget = est1.total_bytes - (sh1.spec.e_pad * 13) // 3
    assert not preflight.check_fits(est1, hbm_bytes=budget, spec=sh1.spec)
    ep = preflight.suggest_edge_shards(sh1.spec, budget)
    assert ep is not None and ep >= 2
    e2 = edge2d.build_edge2d_shards(g, P, ep)
    est2 = preflight.estimate_edge2d(e2.spec, e2.e2_pad)
    assert est2.total_bytes <= budget < est1.total_bytes
    # the suggested 2-D config RUNS and is exact
    mesh = edge2d.make_mesh2d(P, ep)
    prog = pr.PageRankProgram(nv=e2.spec.nv)
    out = edge2d.run_pull_fixed_2d(prog, e2, _state0(prog, e2), 4, mesh)
    got = e2.scatter_to_global(np.asarray(out))
    np.testing.assert_allclose(got, pr.pagerank_reference(g, 4), rtol=3e-5)


def test_suggest_edge_shards_floor():
    """The gathered-state replica is the irreducible floor: a budget
    below it gets None (no EP helps), and the hint text names the flag."""
    import io
    from contextlib import redirect_stdout

    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.utils import preflight

    g = generate.rmat(9, 8, seed=134)
    sh = build_pull_shards(g, 2)
    floor = preflight.estimate_edge2d(sh.spec, 128).total_bytes
    assert preflight.suggest_edge_shards(sh.spec, floor - 1) is None
    est = preflight.estimate_pull(sh.spec)
    buf = io.StringIO()
    with redirect_stdout(buf):
        ok = preflight.check_fits(
            est, hbm_bytes=est.total_bytes - 1, spec=sh.spec)
    assert not ok
    out = buf.getvalue()
    assert "--edge-shards" in out  # the actionable hint
    buf2 = io.StringIO()
    with redirect_stdout(buf2):
        preflight.check_fits(est, hbm_bytes=floor - 1, spec=sh.spec)
    assert "--edge-shards" not in buf2.getvalue()  # nothing would fit
    # too few devices for even EP=2 part-columns: hint suppressed (the
    # suggested config must be RUNNABLE, apps/common.report_preflight)
    buf3 = io.StringIO()
    with redirect_stdout(buf3):
        preflight.check_fits(est, hbm_bytes=est.total_bytes - 1,
                             spec=sh.spec, max_edge_shards=1,
                             stream_hint=True)
    assert "--edge-shards" not in buf3.getvalue()
    # ... and points at host-offload streaming instead (more parts on
    # the same single device cannot help a pull-layout overflow)
    assert "--stream-hbm-gib" in buf3.getvalue()
    # apps without the flag (colfilter) must NOT advertise it
    buf4 = io.StringIO()
    with redirect_stdout(buf4):
        preflight.check_fits(est, hbm_bytes=est.total_bytes - 1,
                             spec=sh.spec, max_edge_shards=1)
    assert "--stream-hbm-gib" not in buf4.getvalue()


def test_edge2d_roofline_model():
    """utils/roofline.edge2d_iter_model: EP=1 degenerates to the 1-D
    model + allgather term; ICI volume grows with EP (the modeled cost
    of replication) while useful FLOPs stay fixed."""
    from lux_tpu.utils import roofline

    ne, nv, P = 1 << 16, 1 << 12, 4
    base = roofline.pull_iter_model(ne, nv, "scan")
    m1 = roofline.edge2d_iter_model(ne, nv, P, 1)
    assert m1["hbm"].bytes_moved == base.bytes_moved
    assert m1["hbm"].flops == base.flops
    prev = None
    for ep in (1, 2, 4, 8):
        m = roofline.edge2d_iter_model(ne, nv, P, ep)
        assert m["hbm"].flops == base.flops  # useful work never scales
        assert m["hbm"].bytes_moved >= base.bytes_moved
        if prev is not None:
            assert m["ici_bytes"] > prev["ici_bytes"]
            assert m["hbm"].device_flops > prev["hbm"].device_flops
        prev = m


def test_edge2d_chunks_cover_all_edges():
    g = generate.rmat(8, 6, seed=131)
    shards = edge2d.build_edge2d_shards(g, 2, 4)
    V = shards.spec.nv_pad
    assert int((shards.arrays2d.dst_local < V).sum()) == g.ne
    # chunk boundaries may split a destination across edge-shards: partial
    # reductions must still combine exactly (covered by the oracle test)


def test_edge2d_maxlabel_pmax():
    """min/max programs combine edge-shard partials with pmin/pmax."""
    from lux_tpu.models import components

    g = generate.uniform_random(300, 2400, seed=132)
    shards = edge2d.build_edge2d_shards(g, 4, 2)
    mesh = edge2d.make_mesh2d(4, 2)
    prog = components.MaxLabelProgram()
    out = edge2d.run_pull_fixed_2d(prog, shards, _state0(prog, shards), 30, mesh)
    labels = shards.scatter_to_global(np.asarray(out))
    assert components.check_labels(g, labels) == 0


def test_edge2d_cf_weighted():
    from lux_tpu.models import colfilter as cf

    g = generate.bipartite_ratings(100, 60, 1200, seed=133)
    shards = edge2d.build_edge2d_shards(g, 2, 4)
    mesh = edge2d.make_mesh2d(2, 4)
    prog = cf.CFProgram(gamma=1e-3)
    out = edge2d.run_pull_fixed_2d(prog, shards, _state0(prog, shards), 3, mesh)
    got = shards.scatter_to_global(np.asarray(out))
    want = cf.colfilter_reference(g, 3, gamma=1e-3)
    np.testing.assert_allclose(got, want, rtol=3e-5, atol=1e-7)


def test_edge2d_bitwise_deterministic():
    g = generate.rmat(8, 8, seed=134)
    shards = edge2d.build_edge2d_shards(g, 2, 4)
    mesh = edge2d.make_mesh2d(2, 4)
    prog = pr.PageRankProgram(nv=shards.spec.nv)
    s0 = _state0(prog, shards)
    a = edge2d.run_pull_fixed_2d(prog, shards, s0, 4, mesh)
    b = edge2d.run_pull_fixed_2d(prog, shards, s0, 4, mesh)
    assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


def test_edge2d_until_cc():
    """Convergence-driven 2-D driver (CC label propagation to fixpoint)."""
    from lux_tpu.models import components

    g = generate.uniform_random(400, 3000, seed=135)
    shards = edge2d.build_edge2d_shards(g, 4, 2)
    mesh = edge2d.make_mesh2d(4, 2)
    prog = components.MaxLabelProgram()
    out, iters = edge2d.run_pull_until_2d(
        prog, shards, _state0(prog, shards), 200, components.active_count,
        mesh,
    )
    labels = shards.scatter_to_global(np.asarray(out))
    assert components.check_labels(g, labels) == 0
    assert 1 <= int(iters) < 200
