"""2-D (parts x edge) parallelism: edge-sharded partial reductions must be
exact for sum/min/max programs."""
import numpy as np
import pytest

from lux_tpu.engine import pull
from lux_tpu.graph import generate
from lux_tpu.models import pagerank as pr
from lux_tpu.parallel import edge2d


def _state0(prog, shards):
    return pull.init_state(prog, shards.arrays)


@pytest.mark.parametrize("shape", [(4, 2), (2, 4)])
def test_edge2d_pagerank_matches_oracle(shape):
    P, EP = shape
    g = generate.rmat(9, 8, seed=130)
    shards = edge2d.build_edge2d_shards(g, P, EP)
    mesh = edge2d.make_mesh2d(P, EP)
    prog = pr.PageRankProgram(nv=shards.spec.nv)
    out = edge2d.run_pull_fixed_2d(prog, shards, _state0(prog, shards), 5, mesh)
    got = shards.scatter_to_global(np.asarray(out))
    np.testing.assert_allclose(got, pr.pagerank_reference(g, 5), rtol=3e-5)
    assert len(out.sharding.device_set) >= P


def test_edge2d_chunks_cover_all_edges():
    g = generate.rmat(8, 6, seed=131)
    shards = edge2d.build_edge2d_shards(g, 2, 4)
    V = shards.spec.nv_pad
    assert int((shards.arrays2d.dst_local < V).sum()) == g.ne
    # chunk boundaries may split a destination across edge-shards: partial
    # reductions must still combine exactly (covered by the oracle test)


def test_edge2d_maxlabel_pmax():
    """min/max programs combine edge-shard partials with pmin/pmax."""
    from lux_tpu.models import components

    g = generate.uniform_random(300, 2400, seed=132)
    shards = edge2d.build_edge2d_shards(g, 4, 2)
    mesh = edge2d.make_mesh2d(4, 2)
    prog = components.MaxLabelProgram()
    out = edge2d.run_pull_fixed_2d(prog, shards, _state0(prog, shards), 30, mesh)
    labels = shards.scatter_to_global(np.asarray(out))
    assert components.check_labels(g, labels) == 0


def test_edge2d_cf_weighted():
    from lux_tpu.models import colfilter as cf

    g = generate.bipartite_ratings(100, 60, 1200, seed=133)
    shards = edge2d.build_edge2d_shards(g, 2, 4)
    mesh = edge2d.make_mesh2d(2, 4)
    prog = cf.CFProgram(gamma=1e-3)
    out = edge2d.run_pull_fixed_2d(prog, shards, _state0(prog, shards), 3, mesh)
    got = shards.scatter_to_global(np.asarray(out))
    want = cf.colfilter_reference(g, 3, gamma=1e-3)
    np.testing.assert_allclose(got, want, rtol=3e-5, atol=1e-7)


def test_edge2d_bitwise_deterministic():
    g = generate.rmat(8, 8, seed=134)
    shards = edge2d.build_edge2d_shards(g, 2, 4)
    mesh = edge2d.make_mesh2d(2, 4)
    prog = pr.PageRankProgram(nv=shards.spec.nv)
    s0 = _state0(prog, shards)
    a = edge2d.run_pull_fixed_2d(prog, shards, s0, 4, mesh)
    b = edge2d.run_pull_fixed_2d(prog, shards, s0, 4, mesh)
    assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


def test_edge2d_until_cc():
    """Convergence-driven 2-D driver (CC label propagation to fixpoint)."""
    from lux_tpu.models import components

    g = generate.uniform_random(400, 3000, seed=135)
    shards = edge2d.build_edge2d_shards(g, 4, 2)
    mesh = edge2d.make_mesh2d(4, 2)
    prog = components.MaxLabelProgram()
    out, iters = edge2d.run_pull_until_2d(
        prog, shards, _state0(prog, shards), 200, components.active_count,
        mesh,
    )
    labels = shards.scatter_to_global(np.asarray(out))
    assert components.check_labels(g, labels) == 0
    assert 1 <= int(iters) < 200
