"""CPU virtual-mesh twins of the version-guarded test_multihost trio.

jax 0.4.x XLA:CPU cannot run multi-process collectives, so the three
real two-OS-process tests in test_multihost.py skip on this pin (see
its version guard).  These twins run the SAME engine programs — the
same graphs, parts, iteration counts, and oracles as tests/mh_worker.py
— on the suite's single-process 8-device virtual mesh, with the host
split simulated through the PlacementTree a real launch uses: per-host
partial file loads and subset bucket builds (``placement=tree,
host=h``), stitched in part order, driven through the same
dist/ring/scatter/feat/push entry points.  A twin cannot exercise a
real process boundary; what it DOES pin is every piece of host-local
arithmetic the multihost path composes (the tree split, the partial
loads, the subset builds, the per-host carry init), so when the jax pin
moves past 0.5 the guarded tests come back to host-split logic that
never rotted.
"""
import jax
import jax.numpy as jnp
import numpy as np

from lux_tpu.engine import pull
from lux_tpu.graph import generate, sharded_load
from lux_tpu.graph.format import write_lux
from lux_tpu.graph.shards import build_pull_shards
from lux_tpu.models.pagerank import PageRankProgram, pagerank_reference
from lux_tpu.parallel import dist, ring
from lux_tpu.parallel import scatter as scatter_mod
from lux_tpu.parallel.mesh import make_mesh_for_parts, shard_stacked
from lux_tpu.parallel.placement import PlacementTree
from lux_tpu.parallel.ring import bucket_counts

P = 8       # parts = virtual devices, like the 2 x 4-device real pair
HOSTS = 2   # the simulated host count


def _check_parts(out, cuts, want, assert_fn):
    """Validate a (P, V)-stacked result part by part against the global
    oracle (the single-process analog of mh_worker.check_local)."""
    got = np.asarray(out)
    for p in range(got.shape[0]):
        lo, hi = int(cuts[p]), int(cuts[p + 1])
        assert_fn(got[p][: hi - lo], want[lo:hi])


def _stitch(parts_arrays, cls):
    """Concatenate per-host stacked arrays in host (= part) order into
    the full (P, ...) layout — the np twin of multihost.assemble_global.
    """
    return cls(*(
        np.concatenate([np.asarray(getattr(a, n)) for a in parts_arrays])
        for n in cls._fields))


def test_twin_pull_sharded_load_dist_ring_scatter(tmp_path):
    """Twin of test_two_process_distributed_pagerank: per-host partial
    .lux loads + tree-placed subset bucket builds, then the dist
    (all_gather), ring (ppermute) and scatter (psum_scatter) engines on
    the stitched arrays, each vs the pagerank reference."""
    g = generate.rmat(9, 8, seed=55)
    shards = build_pull_shards(g, P)
    tree = PlacementTree.build(P, HOSTS)
    mesh = make_mesh_for_parts(P)
    prog = PageRankProgram(nv=shards.spec.nv)
    close = lambda a, b: np.testing.assert_allclose(a, b, rtol=5e-5)  # noqa: E731
    want = pagerank_reference(g, 5)

    lux_path = str(tmp_path / "mh.lux")
    write_lux(lux_path, g)
    # per-host PARTIAL file load: host h reads only its parts' byte
    # ranges, and the streamed subset equals the in-memory build's rows
    locals_ = [
        sharded_load.load_pull_shards(
            lux_path, P, parts_subset=list(tree.parts_of(h)))
        for h in range(HOSTS)
    ]
    for h, loc in enumerate(locals_):
        mine = list(tree.parts_of(h))
        for name in loc.arrays._fields:
            np.testing.assert_array_equal(
                getattr(loc.arrays, name),
                getattr(shards.arrays, name)[mine], err_msg=name)
    arrays_np = _stitch([loc.arrays for loc in locals_],
                        type(shards.arrays))
    # per-host state init on the loaded subset, stitched in part order
    state0 = np.concatenate([
        np.asarray(pull.init_state(prog, loc.arrays))
        for loc in locals_
    ])
    np.testing.assert_array_equal(
        state0, np.asarray(pull.init_state(prog, shards.arrays)))

    arrays = shard_stacked(mesh, jax.tree.map(jnp.asarray, arrays_np))
    st0 = shard_stacked(mesh, jnp.asarray(state0))
    out = dist.run_pull_fixed_dist(prog, shards.spec, arrays, st0, 5,
                                   mesh)
    _check_parts(out, shards.cuts, want, close)

    # ring + scatter bucket exchanges from PER-HOST placement-derived
    # subset builds (each host materializes only its rows), stitched
    counts = bucket_counts(g, shards.cuts, P)

    def stitched(build, field, cls):
        per_host = [build(g, P, pull=shards, counts=counts,
                          placement=tree, host=h) for h in range(HOSTS)]
        assert len({hb.e_bucket_pad for hb in per_host}) == 1
        arrs = _stitch([getattr(hb, field) for hb in per_host],
                       type(getattr(per_host[0], field)))
        return cls(pull=shards, e_bucket_pad=per_host[0].e_bucket_pad,
                   parts_subset=list(range(P)), **{field: arrs})

    full_ring = stitched(ring.build_ring_shards, "rarrays",
                         ring.RingShards)
    r_out = ring.run_pull_fixed_ring(prog, full_ring, st0, 5, mesh)
    _check_parts(r_out, shards.cuts, want, close)

    full_scatter = stitched(scatter_mod.build_scatter_shards, "sarrays",
                            scatter_mod.ScatterShards)
    s_out = scatter_mod.run_pull_fixed_scatter(prog, full_scatter, st0,
                                               5, mesh)
    _check_parts(s_out, shards.cuts, want, close)


def test_twin_feat_cf_two_meshes_and_ring():
    """Twin of test_two_process_feat_cf: the 2-D (parts x feat) CF
    engine on the default and interleaved mesh layouts, plus ring-feat
    with tree-placed subset bucket builds."""
    from jax.sharding import Mesh

    from lux_tpu.models import colfilter as cf_model
    from lux_tpu.parallel import feat
    from lux_tpu.parallel.mesh import FEAT_AXIS, PARTS_AXIS

    gw = generate.bipartite_ratings(96, 64, 800, seed=5)
    fsh = build_pull_shards(gw, 4)
    fmesh = feat.make_mesh_feat(4, 2)
    cfp = cf_model.CFProgram(gamma=1e-3)
    want = cf_model.colfilter_reference(gw, 3, gamma=1e-3)

    def check_feat(out):
        got = np.asarray(out)
        for p in range(got.shape[0]):
            lo, hi = int(fsh.cuts[p]), int(fsh.cuts[p + 1])
            np.testing.assert_allclose(got[p][: hi - lo], want[lo:hi],
                                       rtol=5e-4, atol=1e-6)

    s0 = feat.init_state_feat(cfp, fsh.arrays, fmesh)
    check_feat(feat.run_cf_feat_dist(cfp, fsh.spec, fsh.arrays, s0, 3,
                                     fmesh))
    # interleaved mesh: each feat column pairs device i with device
    # i+4 — the layout that puts the cross-feat psum on DCN for real
    devs = np.asarray(jax.devices())
    imesh = Mesh(np.stack([devs[:4], devs[4:]], axis=1),
                 (PARTS_AXIS, FEAT_AXIS))
    i_s0 = feat.init_state_feat(cfp, fsh.arrays, imesh)
    check_feat(feat.run_cf_feat_dist(cfp, fsh.spec, fsh.arrays, i_s0, 3,
                                     imesh))
    # ring x feat from per-host placement-derived subset builds
    tree = PlacementTree.build(4, HOSTS)
    per_host = [ring.build_ring_shards(gw, 4, pull=fsh, placement=tree,
                                       host=h) for h in range(HOSTS)]
    assert len({hb.e_bucket_pad for hb in per_host}) == 1
    frs = ring.RingShards(
        pull=fsh, e_bucket_pad=per_host[0].e_bucket_pad,
        parts_subset=list(range(4)),
        rarrays=_stitch([hb.rarrays for hb in per_host],
                        type(per_host[0].rarrays)))
    check_feat(feat.run_cf_feat_ring(cfp, frs, s0, 3, fmesh))


def test_twin_push_dist_phase_split_delta():
    """Twin of test_two_process_distributed_push: push to convergence
    from a STITCHED per-host carry init, the 3-phase fenced split, and
    distributed delta-stepping vs the single-device bucket run."""
    from lux_tpu.engine import delta as delta_mod
    from lux_tpu.engine import push
    from lux_tpu.graph.push_shards import build_push_shards
    from lux_tpu.models.sssp import (
        SSSPProgram,
        WeightedSSSPProgram,
        bfs_reference,
    )

    g = generate.rmat(9, 8, seed=55)
    mesh = make_mesh_for_parts(P)
    tree = PlacementTree.build(P, HOSTS)
    psh = build_push_shards(g, P)
    sp = SSSPProgram(nv=psh.spec.nv, start=0)
    want = bfs_reference(g, 0)

    # per-host carry init on each host's tree slice, stitched in part
    # order: must equal the full init bitwise (the assemble_carry
    # contract a real multihost launch relies on)
    full_carry = push._init_carry(
        sp, psh.pspec, jax.tree.map(jnp.asarray, psh.arrays))
    host_carries = [
        push._init_carry(sp, psh.pspec, jax.tree.map(
            lambda a, _m=list(tree.parts_of(h)): jnp.asarray(a[_m]),
            push.vertex_view(psh.arrays)))
        for h in range(HOSTS)
    ]
    # the sharded/replicated field split assemble_carry keeps in one
    # place: per-part arrays concatenate, scalar fields (it, active,
    # edges, dense_rounds) are process-identical by construction
    sharded = {"state", "q_vid", "q_val", "count", "sp_work"}
    stitched = push.PushCarry(*(
        np.concatenate([np.asarray(getattr(c, f)) for c in host_carries])
        if f in sharded else np.asarray(getattr(host_carries[0], f))
        for f in push.PushCarry._fields))
    for f in push.PushCarry._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(stitched, f)),
            np.asarray(getattr(full_carry, f)), err_msg=f)

    arrays = shard_stacked(mesh, jax.tree.map(jnp.asarray, psh.arrays))
    parrays = shard_stacked(mesh,
                            jax.tree.map(jnp.asarray, psh.parrays))
    run = push._compile_push_dist(sp, mesh, psh.pspec, psh.spec, "scan")
    out = run(arrays, parrays,
              push.shard_carry(mesh, jax.tree.map(jnp.asarray,
                                                  stitched)),
              jnp.int32(1000))
    _check_parts(out.state, psh.cuts, want,
                 np.testing.assert_array_equal)

    # the 3-phase fenced split converges to the same fixpoint
    pl, pc, pu = push.compile_push_phases_dist(sp, mesh, psh.pspec,
                                               psh.spec, "scan")
    carry2 = push.shard_carry(
        mesh, push._init_carry(sp, psh.pspec,
                               jax.tree.map(jnp.asarray, psh.arrays)))
    it = 0
    while int(carry2.active) > 0 and it < 64:
        plan = pl(parrays, carry2)
        carry2 = pu(arrays, carry2, pc(arrays, parrays, carry2, plan),
                    plan)
        it += 1
    _check_parts(carry2.state, psh.cuts, want,
                 np.testing.assert_array_equal)

    # distributed delta-stepping vs the single-device bucket run
    gd = generate.rmat(9, 8, seed=57, weighted=True, max_weight=15)
    dsh = build_push_shards(gd, P)
    dp = WeightedSSSPProgram(nv=dsh.spec.nv, start=1)
    d_state, _it, d_edges = delta_mod.run_push_delta_dist(
        dp, dsh, 4, mesh, method="scan")
    st_s, _, e_s = delta_mod.run_push_delta(dp, dsh, 4, method="scan")
    _check_parts(d_state, dsh.cuts,
                 dsh.scatter_to_global(np.asarray(st_s)),
                 np.testing.assert_array_equal)
    assert push.edges_total(d_edges) == push.edges_total(e_s)
