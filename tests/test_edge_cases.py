"""Degenerate and adversarial graph shapes."""
import numpy as np
import pytest

from lux_tpu.graph.csc import from_edge_list
from lux_tpu.graph.partition import edge_balanced_cuts
from lux_tpu.graph.push_shards import build_push_shards
from lux_tpu.graph.shards import build_pull_shards
from lux_tpu.models import components, pagerank as pr, sssp


def test_single_vertex_no_edges():
    g = from_edge_list(np.array([], np.int64), np.array([], np.int64), 1)
    ranks = pr.pagerank(g, num_iters=3)
    # no edges: rank = initRank each iteration (deg 0, undivided)
    np.testing.assert_allclose(ranks, [(1 - 0.15) / 1], rtol=1e-6)
    labels = components.connected_components(g)
    np.testing.assert_array_equal(labels, [0])


def test_edgeless_many_vertices():
    g = from_edge_list(np.array([], np.int64), np.array([], np.int64), 500)
    d = sssp.sssp(g, start=3)
    assert d[3] == 0 and np.all(np.delete(d, 3) == 500)


def test_self_loops_and_duplicates():
    src = np.array([0, 0, 1, 1, 1, 2])
    dst = np.array([0, 0, 1, 2, 2, 2])  # self loops + parallel edges
    g = from_edge_list(src, dst, 3)
    d = sssp.sssp(g, start=1)
    np.testing.assert_array_equal(d, [3, 0, 1])
    labels = components.connected_components(g)
    assert components.check_labels(g, labels) == 0


def test_more_parts_than_vertices():
    g = from_edge_list(np.array([0, 1]), np.array([1, 2]), 3)
    cuts = edge_balanced_cuts(g.row_ptr, 8)
    assert cuts[-1] == 3 and np.all(np.diff(cuts) >= 0)
    sh = build_pull_shards(g, 8)
    assert int(sh.arrays.vtx_mask.sum()) == 3
    ranks = pr.pagerank(g, num_iters=2, num_parts=8)
    want = pr.pagerank_reference(g, 2)
    np.testing.assert_allclose(ranks, want, rtol=1e-5)


def test_hub_vertex_skew():
    """One vertex receives almost all edges (extreme power-law)."""
    n = 256
    src = np.arange(1, n)
    dst = np.zeros(n - 1, np.int64)  # everyone -> 0
    g = from_edge_list(np.concatenate([src, [0]]), np.concatenate([dst, [1]]), n)
    sh = build_push_shards(g, 4)
    d = sssp.sssp(g, start=5, num_parts=4)
    assert d[5] == 0 and d[0] == 1 and d[1] == 2
    ranks = pr.pagerank(g, num_iters=3, num_parts=4)
    np.testing.assert_allclose(ranks, pr.pagerank_reference(g, 3), rtol=1e-5)


def test_lazy_subpackage_access():
    import lux_tpu

    assert hasattr(lux_tpu.models, "__path__")
    with pytest.raises(AttributeError):
        lux_tpu.nonexistent_thing