"""Streaming sharded loader == in-memory shard builder, including subsets."""
import numpy as np
import pytest

from lux_tpu.graph import generate, sharded_load
from lux_tpu.graph.format import write_lux
from lux_tpu.graph.shards import build_pull_shards


@pytest.fixture(scope="module")
def lux_file(tmp_path_factory):
    g = generate.rmat(9, 8, seed=130, weighted=True)
    p = str(tmp_path_factory.mktemp("g") / "g.lux")
    write_lux(p, g)
    return p, g


def test_streaming_degrees(lux_file):
    path, g = lux_file
    np.testing.assert_array_equal(
        sharded_load.out_degrees_from_file(path, chunk_edges=1000),
        g.out_degrees(),
    )


def test_load_matches_memory_build(lux_file):
    path, g = lux_file
    mem = build_pull_shards(g, 4)
    fil = sharded_load.load_pull_shards(path, 4)
    assert fil.spec == mem.spec
    np.testing.assert_array_equal(fil.cuts, mem.cuts)
    for name in mem.arrays._fields:
        np.testing.assert_array_equal(
            getattr(fil.arrays, name), getattr(mem.arrays, name), err_msg=name
        )


def test_load_subset(lux_file):
    path, g = lux_file
    mem = build_pull_shards(g, 4)
    sub = sharded_load.load_pull_shards(path, 4, parts_subset=[1, 3])
    for name in mem.arrays._fields:
        np.testing.assert_array_equal(
            getattr(sub.arrays, name)[0], getattr(mem.arrays, name)[1], err_msg=name
        )
        np.testing.assert_array_equal(
            getattr(sub.arrays, name)[1], getattr(mem.arrays, name)[3], err_msg=name
        )


def test_loaded_shards_run_pagerank(lux_file):
    path, g = lux_file
    from lux_tpu.models import pagerank as pr

    shards = sharded_load.load_pull_shards(path, 2)
    got = pr.pagerank(shards, num_iters=5)
    np.testing.assert_allclose(got, pr.pagerank_reference(g, 5), rtol=3e-5)

def test_subset_load_is_o_local_edges(lux_file, monkeypatch):
    """VERDICT r3 #4: a parts_subset load must be O(local edges) resident —
    it allocates only the subset's stacked rows AND reads only the
    subset's byte ranges from the file (the reference's per-node partial
    reads, core/pull_model.inl:253-320).  Pinned by (a) exact allocation
    accounting and (b) spying the range reads; mmap keeps the header
    column array unmaterialized."""
    from lux_tpu.graph import format as fmt

    path, g = lux_file
    P, subset = 8, [2, 5]
    full = sharded_load.load_pull_shards(path, P)
    calls = []
    real = fmt.read_lux_range

    def spy(path_, vlo, vhi, **kw):
        calls.append((vlo, vhi))
        return real(path_, vlo, vhi, **kw)

    monkeypatch.setattr(fmt, "read_lux_range", spy)
    sub = sharded_load.load_pull_shards(path, P, parts_subset=subset)
    # (a) allocation: exactly len(subset)/P of the full stacked bytes
    sub_b = sum(a.nbytes for a in sub.arrays)
    full_b = sum(a.nbytes for a in full.arrays)
    assert sub_b * P == full_b * len(subset)
    # (b) file reads: exactly the subset parts' vertex ranges, no more
    cuts = full.cuts
    assert calls == [(int(cuts[p]), int(cuts[p + 1])) for p in subset]
    # the shared header/offset pass stays file-backed (a zero-copy view
    # chain ending in the memmap — never an O(ne) materialization)
    hdr = fmt.read_lux(path, mmap=True)
    b = hdr.col_idx
    assert not b.flags.owndata
    while isinstance(b, np.ndarray) and b.base is not None:
        b = b.base
    import mmap as _mmap

    assert isinstance(b, (np.memmap, _mmap.mmap))
    # and the subset rows equal the full build's same-part rows
    for name in sub.arrays._fields:
        np.testing.assert_array_equal(
            getattr(sub.arrays, name),
            getattr(full.arrays, name)[subset],
            err_msg=name,
        )
