"""Streaming sharded loader == in-memory shard builder, including subsets."""
import numpy as np
import pytest

from lux_tpu.graph import generate, sharded_load
from lux_tpu.graph.format import write_lux
from lux_tpu.graph.shards import build_pull_shards


@pytest.fixture(scope="module")
def lux_file(tmp_path_factory):
    g = generate.rmat(9, 8, seed=130, weighted=True)
    p = str(tmp_path_factory.mktemp("g") / "g.lux")
    write_lux(p, g)
    return p, g


def test_streaming_degrees(lux_file):
    path, g = lux_file
    np.testing.assert_array_equal(
        sharded_load.out_degrees_from_file(path, chunk_edges=1000),
        g.out_degrees(),
    )


def test_load_matches_memory_build(lux_file):
    path, g = lux_file
    mem = build_pull_shards(g, 4)
    fil = sharded_load.load_pull_shards(path, 4)
    assert fil.spec == mem.spec
    np.testing.assert_array_equal(fil.cuts, mem.cuts)
    for name in mem.arrays._fields:
        np.testing.assert_array_equal(
            getattr(fil.arrays, name), getattr(mem.arrays, name), err_msg=name
        )


def test_load_subset(lux_file):
    path, g = lux_file
    mem = build_pull_shards(g, 4)
    sub = sharded_load.load_pull_shards(path, 4, parts_subset=[1, 3])
    for name in mem.arrays._fields:
        np.testing.assert_array_equal(
            getattr(sub.arrays, name)[0], getattr(mem.arrays, name)[1], err_msg=name
        )
        np.testing.assert_array_equal(
            getattr(sub.arrays, name)[1], getattr(mem.arrays, name)[3], err_msg=name
        )


def test_loaded_shards_run_pagerank(lux_file):
    path, g = lux_file
    from lux_tpu.models import pagerank as pr

    shards = sharded_load.load_pull_shards(path, 2)
    got = pr.pagerank(shards, num_iters=5)
    np.testing.assert_allclose(got, pr.pagerank_reference(g, 5), rtol=3e-5)