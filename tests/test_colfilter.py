"""Collaborative filtering vs the numpy recurrence oracle."""
import numpy as np
import pytest

from lux_tpu.graph import generate
from lux_tpu.models import colfilter as cf
from lux_tpu.parallel import mesh as mesh_lib


@pytest.mark.parametrize("num_parts", [1, 4])
def test_cf_matches_oracle(num_parts):
    g = generate.bipartite_ratings(60, 40, 800, seed=50)
    got = cf.colfilter(g, num_iters=5, num_parts=num_parts, gamma=1e-3)
    want = cf.colfilter_reference(g, 5, gamma=1e-3)
    np.testing.assert_allclose(got, want, rtol=3e-5, atol=1e-7)


def test_cf_training_reduces_rmse():
    g = generate.bipartite_ratings(80, 50, 1500, seed=51, max_rating=5)
    v0 = cf.colfilter(g, num_iters=0, gamma=2e-3)
    v = cf.colfilter(g, num_iters=60, gamma=2e-3)
    assert cf.rmse(g, v) < cf.rmse(g, v0) * 0.9


def test_cf_distributed_matches_single():
    g = generate.bipartite_ratings(100, 60, 1200, seed=52)
    single = cf.colfilter(g, num_iters=4, num_parts=1, gamma=1e-3)
    multi = cf.colfilter(
        g, num_iters=4, num_parts=8, mesh=mesh_lib.make_mesh(8), gamma=1e-3
    )
    np.testing.assert_allclose(multi, single, rtol=2e-5, atol=1e-7)


def test_cf_requires_weights():
    g = generate.uniform_random(50, 200, seed=53)
    with pytest.raises(AssertionError):
        cf.colfilter(g, num_iters=1)