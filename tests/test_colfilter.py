"""Collaborative filtering vs the numpy recurrence oracle."""
import jax.numpy as jnp
import numpy as np
import pytest

from lux_tpu.graph import generate
from lux_tpu.models import colfilter as cf
from lux_tpu.parallel import mesh as mesh_lib


@pytest.mark.parametrize("num_parts", [1, 4])
def test_cf_matches_oracle(num_parts):
    g = generate.bipartite_ratings(60, 40, 800, seed=50)
    got = cf.colfilter(g, num_iters=5, num_parts=num_parts, gamma=1e-3)
    want = cf.colfilter_reference(g, 5, gamma=1e-3)
    np.testing.assert_allclose(got, want, rtol=3e-5, atol=1e-7)


def test_cf_training_reduces_rmse():
    g = generate.bipartite_ratings(80, 50, 1500, seed=51, max_rating=5)
    v0 = cf.colfilter(g, num_iters=0, gamma=2e-3)
    v = cf.colfilter(g, num_iters=60, gamma=2e-3)
    assert cf.rmse(g, v) < cf.rmse(g, v0) * 0.9


def test_cf_distributed_matches_single():
    g = generate.bipartite_ratings(100, 60, 1200, seed=52)
    single = cf.colfilter(g, num_iters=4, num_parts=1, gamma=1e-3)
    multi = cf.colfilter(
        g, num_iters=4, num_parts=8, mesh=mesh_lib.make_mesh(8), gamma=1e-3
    )
    np.testing.assert_allclose(multi, single, rtol=2e-5, atol=1e-7)


def test_cf_requires_weights():
    g = generate.uniform_random(50, 200, seed=53)
    with pytest.raises(AssertionError):
        cf.colfilter(g, num_iters=1)

def test_cf_bfloat16_state():
    """bf16 storage dtype: runs end-to-end, tracks the f32 result within
    bf16 resolution, and training still reduces RMSE (the SURVEY.md §7.3
    wide-state memory case)."""
    g = generate.bipartite_ratings(60, 40, 900, seed=54, max_rating=5)
    f32 = cf.colfilter(g, num_iters=5, gamma=1e-3)
    bf16 = cf.colfilter(g, num_iters=5, gamma=1e-3, dtype="bfloat16")
    assert bf16.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        bf16.astype(np.float32), f32, rtol=2e-2, atol=2e-3
    )
    v0 = cf.colfilter(g, num_iters=0, gamma=2e-3, dtype="bfloat16")
    v = cf.colfilter(g, num_iters=60, gamma=2e-3, dtype="bfloat16")
    assert cf.rmse(g, v.astype(np.float32)) < cf.rmse(g, v0.astype(np.float32)) * 0.9


def test_cf_bf16_accumulates_in_f32():
    """The per-edge error products and their segmented reduction must be
    float32 even when the state is stored bf16."""
    prog = cf.CFProgram(dtype="bfloat16")
    src = jnp.ones((6, cf.K), jnp.bfloat16)
    dst = jnp.ones((6, cf.K), jnp.bfloat16)
    w = jnp.ones((6,), jnp.float32)
    assert prog.edge_value(src, w, dst).dtype == jnp.float32


def test_cf_bf16_deterministic():
    g = generate.bipartite_ratings(50, 30, 600, seed=55)
    a = cf.colfilter(g, num_iters=4, gamma=1e-3, dtype="bfloat16")
    b = cf.colfilter(g, num_iters=4, gamma=1e-3, dtype="bfloat16")
    assert np.asarray(a).tobytes() == np.asarray(b).tobytes()
