"""Device replay of routed permutations (interpret-mode Pallas).

Covers the two gather kernels directly, then full Benes replays against
the NumPy oracle and the raw permutation, f32 and int32, across digit
mixes (pure-lane, lane+sublane, and tiny sub-8 digits).
"""
import numpy as np
import jax.numpy as jnp
import pytest

from lux_tpu.ops import pallas_shuffle as S
from lux_tpu.ops import route as R


def test_lane_gather_kernel(rng):
    x = rng.random((256, 128)).astype(np.float32)
    idx = rng.integers(0, 128, (256, 128), dtype=np.int32)
    got = np.asarray(
        S.lane_gather(jnp.asarray(x), jnp.asarray(idx), rb=64,
                      interpret=True))
    np.testing.assert_array_equal(got, np.take_along_axis(x, idx, axis=1))


@pytest.mark.parametrize("d", [2, 4, 8])
def test_sublane_gather_kernel(d, rng):
    x = rng.random((d, 512)).astype(np.float32)
    idx = rng.integers(0, d, (d, 512), dtype=np.int32)
    got = np.asarray(
        S.sublane_gather(jnp.asarray(x), jnp.asarray(idx), lb=256,
                         interpret=True))
    np.testing.assert_array_equal(got, np.take_along_axis(x, idx, axis=0))


@pytest.mark.parametrize("n", [1024, 2048, 16384, 1 << 17])
def test_apply_route_matches_perm(n, rng):
    perm = rng.permutation(n)
    rt = R.build_route(perm)
    plan = S.plan_route(rt)
    x = rng.random(n).astype(np.float32)
    got = np.asarray(
        S.apply_route(jnp.asarray(x), plan, rb=256, lb=512,
                      interpret=True))
    np.testing.assert_array_equal(got, x[perm])
    np.testing.assert_array_equal(R.apply_route_np(rt, x), x[perm])


def test_apply_route_int32(rng):
    n = 4096
    perm = rng.permutation(n)
    plan = S.plan_route(R.build_route(perm))
    x = rng.integers(-(2**31), 2**31 - 1, n, dtype=np.int32)
    got = np.asarray(
        S.apply_route(jnp.asarray(x), plan, rb=256, lb=512,
                      interpret=True))
    np.testing.assert_array_equal(got, x[perm])


def test_apply_route_composes_under_jit(rng):
    """apply_route must trace cleanly inside a larger jitted program
    (it is destined for the pull engine's iteration body)."""
    import jax

    n = 2048
    perm = rng.permutation(n)
    plan = S.plan_route(R.build_route(perm))
    idx_dev = S.device_indices(plan)
    x = rng.random(n).astype(np.float32)

    @jax.jit
    def step(v):
        moved = S.apply_route(v, plan, idx_dev=idx_dev, rb=256, lb=512,
                              interpret=True)
        return moved * 2.0

    np.testing.assert_allclose(
        np.asarray(step(jnp.asarray(x))), x[perm] * 2.0, rtol=1e-6)


def test_plan_route_nondividing_digit_takes_sublane():
    """A digit that does not divide 128 must NOT ride the widened lane
    path (its (lane//d)*d fixup would cross block boundaries and gather
    garbage under promise_in_bounds): it falls through to the sublane
    kernel, whose d <= 8 assert fails loudly for oversized digits."""
    # d=96: > 8 and 128 % 96 != 0; n = 96*128 >= LANE would have taken
    # the lane branch before the guard
    shape = (96, 128)
    idx = np.zeros(shape, np.int32)
    r = R.Route(n=96 * 128, dims=shape,
                passes=[R.Pass(shape=shape, axis=0, idx=idx)])
    plan = S.plan_route(r)
    assert plan.passes[0].kind == "sublane"
    with pytest.raises(AssertionError):  # loud, not garbage
        S.sublane_gather(jnp.zeros(plan.passes[0].kshape, jnp.float32),
                            jnp.asarray(plan.passes[0].idx), interpret=True)


def test_plan_route_dividing_small_digit_still_rides_lane():
    shape = (4, 128)
    idx = np.zeros(shape, np.int32)
    r = R.Route(n=4 * 128, dims=shape,
                passes=[R.Pass(shape=shape, axis=0, idx=idx)])
    plan = S.plan_route(r)
    assert plan.passes[0].kind == "lane"
