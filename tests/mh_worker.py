import os, sys
pid = int(sys.argv[1]); nproc = int(sys.argv[2])
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from lux_tpu.parallel import multihost
me = multihost.initialize("127.0.0.1:29517", nproc, pid)
import jax
import numpy as np
assert jax.process_count() == nproc, jax.process_count()
assert jax.device_count() == 4 * nproc
from lux_tpu.graph import generate
from lux_tpu.graph.shards import build_pull_shards
from lux_tpu.engine import pull
from lux_tpu.models.pagerank import PageRankProgram, pagerank_reference
from lux_tpu.parallel import multihost as mh, dist
mesh = mh.global_parts_mesh()
P = jax.device_count()
g = generate.rmat(9, 8, seed=55)
shards = build_pull_shards(g, P)
prog = PageRankProgram(nv=shards.spec.nv)
# host-sharded load: this host materializes only its own parts
mine = list(mh.local_part_range(P))
assert len(mine) == 4
state0_local = np.stack([
    np.asarray(prog.init_state(
        shards.arrays.global_vid[p], shards.arrays.degree[p], shards.arrays.vtx_mask[p]
    )) for p in mine
])
state0 = mh.assemble_global(mesh, state0_local, P)
arrays = jax.tree.map(
    lambda a: mh.assemble_global(mesh, a[mine], P), shards.arrays
)
out = dist.run_pull_fixed_dist(prog, shards.spec, arrays, state0, 5, mesh)
# addressable_shards order is not guaranteed to follow the parts axis
shards_sorted = sorted(out.addressable_shards, key=lambda s: s.index[0].start)
local = np.concatenate([np.asarray(s.data)[0][None] for s in shards_sorted])
# verify my local parts against the oracle
want = pagerank_reference(g, 5)
for i, p in enumerate(mine):
    lo, hi = int(shards.cuts[p]), int(shards.cuts[p + 1])
    np.testing.assert_allclose(local[i][: hi - lo], want[lo:hi], rtol=5e-5)
print(f"process {pid}: multihost pagerank OK over {P} devices / {nproc} procs", flush=True)

# --- ring exchange with PER-HOST SUBSET bucket builds: each process
# materializes only its parts' (P, B) bucket rows (the RMAT27 load plan,
# SURVEY.md §7.3) and assemble_global stitches the global stacked arrays
from lux_tpu.parallel import ring

rs_local = ring.build_ring_shards(g, P, parts_subset=mine, pull=shards)
rarr_global = jax.tree.map(
    lambda a: mh.assemble_global(mesh, a, P), rs_local.rarrays
)
rs = ring.RingShards(
    pull=shards, rarrays=rarr_global,
    e_bucket_pad=rs_local.e_bucket_pad, parts_subset=list(range(P)),
)
ring_out = ring.run_pull_fixed_ring(prog, rs, state0, 5, mesh)
rshards_sorted = sorted(
    ring_out.addressable_shards, key=lambda s: s.index[0].start
)
rlocal = np.concatenate([np.asarray(s.data)[0][None] for s in rshards_sorted])
for i, p in enumerate(mine):
    lo, hi = int(shards.cuts[p]), int(shards.cuts[p + 1])
    np.testing.assert_allclose(rlocal[i][: hi - lo], want[lo:hi], rtol=5e-5)
print(f"process {pid}: multihost ring OK (subset-built buckets)", flush=True)
