import os, sys
pid = int(sys.argv[1]); nproc = int(sys.argv[2])
mode = sys.argv[3] if len(sys.argv) > 3 else "pull"
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from lux_tpu.parallel import multihost
# distinct coordinator port per mode: the pull and push tests may run
# back-to-back and a lingering TIME_WAIT port would wedge the second
port = {"pull": 29517, "push": 29518, "feat": 29519}[mode]
me = multihost.initialize(f"127.0.0.1:{port}", nproc, pid)
import jax

# share the suite's persistent compile cache (tests/conftest.py): the
# pair's engine compiles dominate its 300+ s budget on the 1-core host
jax.config.update("jax_compilation_cache_dir",
                  os.environ.get("LUX_JAX_CACHE", "/tmp/lux_jax_cache"))
import numpy as np
assert jax.process_count() == nproc, jax.process_count()
assert jax.device_count() == 4 * nproc
from lux_tpu.graph import generate
from lux_tpu.graph.shards import build_pull_shards
from lux_tpu.engine import pull
from lux_tpu.models.pagerank import PageRankProgram, pagerank_reference
from lux_tpu.parallel import multihost as mh, dist
mesh = mh.global_parts_mesh()
P = jax.device_count()
g = generate.rmat(9, 8, seed=55)


def check_local(arr, cuts, mine, want, assert_fn):
    """Validate THIS process's parts of a (P, V)-sharded result against
    the global oracle (addressable shard order is not the parts order)."""
    got = sorted(arr.addressable_shards, key=lambda s: s.index[0].start)
    for i, p in enumerate(mine):
        lo, hi = int(cuts[p]), int(cuts[p + 1])
        assert_fn(np.asarray(got[i].data)[0][: hi - lo], want[lo:hi])


if mode == "feat":
    # --- 2-D (parts x feat) CF across REAL processes.  Two meshes, so
    # that BOTH composed collectives get a process boundary: in the
    # default layout (feat minor) the feat columns are intra-process and
    # the parts-axis all_gather/ppermute crosses hosts; the interleaved
    # layout pairs device i of process 0 with device i of process 1 in
    # each feat column, so the cross-feat error-dot psum crosses hosts.
    from jax.sharding import Mesh

    from lux_tpu.models import colfilter as cf_model
    from lux_tpu.parallel import feat
    from lux_tpu.parallel.mesh import FEAT_AXIS, PARTS_AXIS

    gw = generate.bipartite_ratings(96, 64, 800, seed=5)
    fsh = build_pull_shards(gw, 4)
    fmesh = feat.make_mesh_feat(4, 2)

    def check_feat_shards(out, want):
        """Validate THIS process's (part, feat) shards of a (P, V, K)
        result against the global oracle."""
        for shard in out.addressable_shards:
            p = shard.index[0].start
            ks = shard.index[2]
            lo, hi = int(fsh.cuts[p]), int(fsh.cuts[p + 1])
            np.testing.assert_allclose(
                np.asarray(shard.data)[0][: hi - lo], want[lo:hi, ks],
                rtol=5e-4, atol=1e-6,
            )
    # gamma=1e-3 (not the app default 3.5e-7) so the 3-iteration signal
    # exceeds the comparison tolerance — same convention as every CF
    # oracle test; at the default gamma the unmodified initial state
    # would pass rtol=5e-4
    cfp = cf_model.CFProgram(gamma=1e-3)
    s0 = feat.init_state_feat(cfp, fsh.arrays, fmesh)
    out = feat.run_cf_feat_dist(
        cfp, fsh.spec, fsh.arrays, s0, 3, fmesh
    )
    want = cf_model.colfilter_reference(gw, 3, gamma=1e-3)
    check_feat_shards(out, want)
    print(f"process {pid}: multihost feat-CF OK ({len(out.addressable_shards)}"
          f" local shards)", flush=True)
    # interleaved mesh: feat pairs (dev i of proc 0, dev i of proc 1) —
    # the cross-feat psum now crosses the process boundary
    devs = np.asarray(jax.devices())
    imesh = Mesh(
        np.stack([devs[:4], devs[4:]], axis=1), (PARTS_AXIS, FEAT_AXIS)
    )
    i_s0 = feat.init_state_feat(cfp, fsh.arrays, imesh)
    i_out = feat.run_cf_feat_dist(
        cfp, fsh.spec, fsh.arrays, i_s0, 3, imesh
    )
    check_feat_shards(i_out, want)
    print(f"process {pid}: multihost feat-CF cross-host-psum OK", flush=True)
    # ring x feat on the default mesh: the parts-axis ppermute ring
    # crosses hosts under the composed engine
    from lux_tpu.parallel import ring as ring_mod

    frs = ring_mod.build_ring_shards(gw, 4, pull=fsh)
    r_out = feat.run_cf_feat_ring(cfp, frs, s0, 3, fmesh)
    check_feat_shards(r_out, want)
    print(f"process {pid}: multihost ring-feat-CF OK", flush=True)
    sys.exit(0)

if mode == "push":
    # --- push engine across REAL processes: frontier (vid, value) queue
    # all_gathers, the psum'd direction-switch flags, and the dense-branch
    # state all_gather inside lax.cond — the riskiest collective pattern
    # in the framework, here exercised over an actual process boundary
    import jax.numpy as jnp

    from lux_tpu.engine import push
    from lux_tpu.graph.push_shards import build_push_shards
    from lux_tpu.models.sssp import SSSPProgram, bfs_reference

    psh = build_push_shards(g, P)
    sp = SSSPProgram(nv=psh.spec.nv, start=0)
    mine = list(mh.local_part_range(P))
    arrays_p = jax.tree.map(
        lambda a: mh.assemble_global(mesh, a[mine], P), psh.arrays
    )
    parrays_p = jax.tree.map(
        lambda a: mh.assemble_global(mesh, a[mine], P), psh.parrays
    )
    # per-host carry init on the local parts, stitched like the arrays
    view_local = jax.tree.map(
        lambda a: jnp.asarray(a[mine]), push.vertex_view(psh.arrays)
    )
    c_local = push._init_carry(sp, psh.pspec, view_local)
    carry = push.assemble_carry(
        c_local, lambda a: mh.assemble_global(mesh, a, P)
    )
    run = push._compile_push_dist(sp, mesh, psh.pspec, psh.spec, "scan")
    out = run(arrays_p, parrays_p, carry, jnp.int32(1000))
    check_local(out.state, psh.cuts, mine, bfs_reference(g, 0),
                np.testing.assert_array_equal)
    print(f"process {pid}: multihost push OK over {P} devices", flush=True)
    # --- the 3-phase -verbose split across processes: the same
    # load/comp/update shard_map programs the CLI fences must converge to
    # the same BFS fixpoint when every collective (queue all_gather,
    # direction psums, dense-branch state all_gather) crosses a real
    # process boundary
    c_local2 = push._init_carry(sp, psh.pspec, view_local)
    carry2 = push.assemble_carry(
        c_local2, lambda a: mh.assemble_global(mesh, a, P)
    )
    pl, pc, pu = push.compile_push_phases_dist(
        sp, mesh, psh.pspec, psh.spec, "scan"
    )
    it = 0
    while int(carry2.active) > 0 and it < 64:
        plan = pl(parrays_p, carry2)
        carry2 = pu(arrays_p, carry2, pc(arrays_p, parrays_p, carry2, plan),
                    plan)
        it += 1
    check_local(carry2.state, psh.cuts, mine, bfs_reference(g, 0),
                np.testing.assert_array_equal)
    print(f"process {pid}: multihost push phase-split OK ({it} its)",
          flush=True)
    # --- distributed delta-stepping across processes: the bucket
    # occupancy psum and the pmin threshold advance each cross a real
    # process boundary; validated against the single-device bucket run
    from lux_tpu.engine import delta as delta_mod
    from lux_tpu.models.sssp import WeightedSSSPProgram

    DW = 4
    gd = generate.rmat(9, 8, seed=57, weighted=True, max_weight=15)
    dsh = build_push_shards(gd, P)
    dp = WeightedSSSPProgram(nv=dsh.spec.nv, start=1)
    d_arrays = jax.tree.map(
        lambda a: mh.assemble_global(mesh, a[mine], P), dsh.arrays
    )
    d_parrays = jax.tree.map(
        lambda a: mh.assemble_global(mesh, a[mine], P), dsh.parrays
    )
    c_loc = delta_mod._init_carry(
        dp, dsh.pspec,
        jax.tree.map(lambda a: jnp.asarray(a[mine]), dsh.arrays), DW,
    )
    # global pending count from a full-arrays init (what
    # run_push_delta_dist does) — never a hardcoded constant
    c_full = delta_mod._init_carry(
        dp, dsh.pspec, jax.tree.map(jnp.asarray, dsh.arrays), DW
    )
    d_carry = delta_mod.DeltaCarry(
        mh.assemble_global(mesh, np.asarray(c_loc.state), P),
        mh.assemble_global(mesh, np.asarray(c_loc.pending), P),
        c_loc.thr, c_loc.it, c_full.active, c_loc.edges,
    )
    d_run = delta_mod._compile_delta_dist(
        dp, mesh, dsh.pspec, dsh.spec, "scan", DW
    )
    d_out = d_run(d_arrays, d_parrays, d_carry, jnp.int32(100000))
    st_s, _, e_s = delta_mod.run_push_delta(dp, dsh, DW, method="scan")
    check_local(
        d_out.state, dsh.cuts, mine,
        dsh.scatter_to_global(np.asarray(st_s)),
        np.testing.assert_array_equal,
    )
    assert push.edges_total(d_out.edges) == push.edges_total(e_s)
    print(f"process {pid}: multihost delta-stepping OK", flush=True)
    sys.exit(0)

shards = build_pull_shards(g, P)
prog = PageRankProgram(nv=shards.spec.nv)
mine = list(mh.local_part_range(P))
assert len(mine) == 4

# host-sharded FILE load: every process reads ONLY its parts' byte
# ranges from the SHARED .lux — the reference's per-node partial reads
# (pull_load_task_impl, core/pull_model.inl:253-320) across real OS
# processes.  Process 0 publishes the file atomically; the graph is
# deterministic so a pre-existing file from an earlier run is identical.
import time as _time

from lux_tpu.graph import format as fmt
from lux_tpu.graph import sharded_load

import hashlib

# content-keyed path: a layout/generator change produces a new file
# instead of poisoning runs with a stale cache
tag = hashlib.md5(
    np.ascontiguousarray(g.col_idx).tobytes()
    + np.ascontiguousarray(g.row_ptr).tobytes()
).hexdigest()[:10]
lux_path = f"/tmp/lux_mh_pull_{tag}_{nproc}.lux"
if pid == 0 and not os.path.exists(lux_path):
    tmp = f"{lux_path}.tmp{os.getpid()}"
    fmt.write_lux(tmp, g)
    os.replace(tmp, lux_path)
for _ in range(150):
    if os.path.exists(lux_path):
        break
    _time.sleep(0.2)
else:
    raise AssertionError(
        f"timed out waiting for pid 0 to publish {lux_path}"
    )
pull_local = sharded_load.load_pull_shards(lux_path, P, parts_subset=mine)
# the streamed subset must equal the in-memory build's same-part rows
for name in pull_local.arrays._fields:
    np.testing.assert_array_equal(
        getattr(pull_local.arrays, name),
        getattr(shards.arrays, name)[mine], err_msg=name,
    )
state0_local = np.stack([
    np.asarray(prog.init_state(
        pull_local.arrays.global_vid[i], pull_local.arrays.degree[i],
        pull_local.arrays.vtx_mask[i],
    )) for i in range(len(mine))
])
state0 = mh.assemble_global(mesh, state0_local, P)
arrays = jax.tree.map(
    lambda a: mh.assemble_global(mesh, a, P), pull_local.arrays
)
out = dist.run_pull_fixed_dist(prog, shards.spec, arrays, state0, 5, mesh)
import functools

close = functools.partial(np.testing.assert_allclose, rtol=5e-5)
want = pagerank_reference(g, 5)
check_local(out, shards.cuts, mine, want, close)
print(f"process {pid}: multihost pagerank OK over {P} devices / {nproc} procs", flush=True)

# --- routed expand across the REAL process boundary: the Benes
# lane-shuffle LOAD phase (ops/expand.py) under the same two-process
# mesh, bitwise-equal to the direct distributed result shard by shard
from lux_tpu.ops import expand as _expand

# plan ONLY this process's parts (per-host O(local parts) work, like
# the sharded file load above); statics are size-derived so the two
# processes' statics agree without coordination
_r_plans = [
    _expand.plan_expand(np.asarray(shards.arrays.src_pos[i]),
                        int(np.count_nonzero(shards.arrays.edge_mask[i])),
                        shards.spec.gathered_size)
    for i in mine
]
r_static = _r_plans[0][0]
assert all(st == r_static for st, _ in _r_plans[1:])
r_local = tuple(
    np.stack([_r_plans[j][1][a] for j in range(len(mine))])
    for a in range(len(_r_plans[0][1]))
)
r_dev = jax.tree.map(lambda a: mh.assemble_global(mesh, a, P), r_local)
r_out = dist.run_pull_fixed_dist(
    prog, shards.spec, arrays, state0, 5, mesh, route=(r_static, r_dev)
)


def _local_shards(x):
    return {tuple(map(str, sh.index)): np.asarray(sh.data)
            for sh in x.addressable_shards}


ld, lr = _local_shards(out), _local_shards(r_out)
assert ld.keys() == lr.keys()
for key in ld:
    np.testing.assert_array_equal(ld[key], lr[key])
print(f"process {pid}: multihost ROUTED pagerank bitwise OK", flush=True)

# --- bucket exchanges (ring, reduce_scatter) with PER-HOST SUBSET
# builds: each process materializes only its parts' bucket rows (the
# RMAT27 load plan, SURVEY.md §7.3); assemble_global stitches the
# global stacked arrays; ring's ppermute and scatter's fused
# psum_scatter each cross the real process boundary
from lux_tpu.parallel import ring
from lux_tpu.parallel import scatter as scatter_mod
from lux_tpu.parallel.ring import bucket_counts

counts = bucket_counts(g, shards.cuts, P)  # shared O(ne) pass


def run_bucket_exchange(build, shards_cls, field, run):
    """Subset-build -> assemble -> reconstruct-global -> run -> check,
    identical for every bucket-layout exchange."""
    local = build(g, P, parts_subset=mine, pull=shards, counts=counts)
    arr_global = jax.tree.map(
        lambda a: mh.assemble_global(mesh, a, P), getattr(local, field)
    )
    full = shards_cls(
        pull=shards, e_bucket_pad=local.e_bucket_pad,
        parts_subset=list(range(P)), **{field: arr_global},
    )
    out = run(prog, full, state0, 5, mesh)
    check_local(out, shards.cuts, mine, want, close)


run_bucket_exchange(
    ring.build_ring_shards, ring.RingShards, "rarrays",
    ring.run_pull_fixed_ring,
)
print(f"process {pid}: multihost ring OK (subset-built buckets)", flush=True)
run_bucket_exchange(
    scatter_mod.build_scatter_shards, scatter_mod.ScatterShards, "sarrays",
    scatter_mod.run_pull_fixed_scatter,
)
print(f"process {pid}: multihost scatter OK (cross-host psum_scatter)",
      flush=True)
