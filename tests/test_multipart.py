"""Parts decoupled from devices (VERDICT r2 #5): num_parts = k x mesh
size, with k parts resident per device and the per-part step vmapped over
the resident lanes — the reference mapper's slicing analog
(core/lux_mapper.cc:102-122, MAX_NUM_PARTS=64 over fewer processors).

P=16 on the 8-device virtual mesh (k=2) must be bitwise equal to the
same-P single-device run (identical per-part reductions; distribution
changes placement, not math), and equal to the P=8 run globally (bitwise
for min/max confluence; allclose for float sums, whose reduction order
depends on the cuts).
"""
import numpy as np
import pytest

from lux_tpu.engine import pull, push
from lux_tpu.graph import generate
from lux_tpu.graph.push_shards import build_push_shards
from lux_tpu.graph.shards import build_pull_shards
from lux_tpu.models import components
from lux_tpu.models.pagerank import PageRankProgram
from lux_tpu.models.sssp import SSSPProgram, bfs_reference
from lux_tpu.parallel import dist, ring
from lux_tpu.parallel.mesh import make_mesh, make_mesh_for_parts


@pytest.fixture(scope="module")
def g():
    return generate.rmat(10, 8, seed=21)


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(8)


def test_make_mesh_for_parts_picks_largest_divisor():
    assert make_mesh_for_parts(16).devices.size == 8
    assert make_mesh_for_parts(8).devices.size == 8
    assert make_mesh_for_parts(6).devices.size == 6
    assert make_mesh_for_parts(12).devices.size == 6  # 12 % 8 != 0
    assert make_mesh_for_parts(1).devices.size == 1


def test_pull_fixed_p16_on_8_devices(g, mesh8):
    shards = build_pull_shards(g, 16)
    prog = PageRankProgram(nv=shards.spec.nv)
    s0 = pull.init_state(prog, shards.arrays)
    out = dist.run_pull_fixed_dist(
        prog, shards.spec, shards.arrays, s0, 4, mesh8, method="scan"
    )
    # bitwise vs the SAME-P single-device run (identical math per part)
    want = pull.run_pull_fixed(
        prog, shards.spec, shards.arrays, s0, 4, method="scan"
    )
    np.testing.assert_array_equal(np.asarray(out), np.asarray(want))
    # and allclose vs the P=8 cuts (different reduction grouping)
    sh8 = build_pull_shards(g, 8)
    p8 = dist.run_pull_fixed_dist(
        PageRankProgram(nv=sh8.spec.nv), sh8.spec, sh8.arrays,
        pull.init_state(PageRankProgram(nv=sh8.spec.nv), sh8.arrays),
        4, mesh8, method="scan",
    )
    np.testing.assert_allclose(
        shards.scatter_to_global(np.asarray(out)),
        sh8.scatter_to_global(np.asarray(p8)),
        rtol=5e-5,
    )


def test_pull_until_p16_bitwise_vs_p8(g, mesh8):
    prog = components.MaxLabelProgram()
    outs = {}
    for p in (8, 16):
        sh = build_pull_shards(g, p)
        s0 = pull.init_state(prog, sh.arrays)
        st, iters = dist.run_pull_until_dist(
            prog, sh.spec, sh.arrays, s0, 64,
            components.active_count, mesh8, method="scan",
        )
        assert int(iters) >= 1
        outs[p] = sh.scatter_to_global(np.asarray(st))
    np.testing.assert_array_equal(outs[8], outs[16])


def test_push_dist_p16_on_8_devices(g, mesh8):
    sh16 = build_push_shards(g, 16)
    sp = SSSPProgram(nv=sh16.spec.nv, start=0)
    st, iters, edges = push.run_push_dist(
        sp, sh16, mesh8, max_iters=1000, method="scan"
    )
    np.testing.assert_array_equal(
        sh16.scatter_to_global(np.asarray(st)), bfs_reference(g, 0)
    )
    # same schedule + exact edge accounting as the SAME-P single-device run
    st1, it1, e1 = push.run_push(
        SSSPProgram(nv=sh16.spec.nv, start=0), sh16, 1000, method="scan"
    )
    assert int(iters) == int(it1)
    assert push.edges_total(edges) == push.edges_total(e1)
    np.testing.assert_array_equal(np.asarray(st), np.asarray(st1))


def test_push_ring_p16_on_8_devices(g, mesh8):
    prs = ring.build_push_ring_shards(g, 16)
    sp = SSSPProgram(nv=prs.spec.nv, start=0)
    st, _, _ = push.run_push_ring(sp, prs, mesh8, max_iters=1000, method="scan")
    np.testing.assert_array_equal(
        prs.scatter_to_global(np.asarray(st)), bfs_reference(g, 0)
    )


def test_pull_ring_p16_on_8_devices(g, mesh8):
    rs = ring.build_ring_shards(g, 16)
    prog = PageRankProgram(nv=rs.spec.nv)
    s0 = pull.init_state(prog, rs.arrays)
    out = ring.run_pull_fixed_ring(prog, rs, s0, 4, mesh8, method="scan")
    # the ring fold is bucket-by-source-owner: compare to the same-P
    # allgather engine within float tolerance
    sh16 = build_pull_shards(g, 16)
    want = dist.run_pull_fixed_dist(
        prog, sh16.spec, sh16.arrays,
        pull.init_state(prog, sh16.arrays), 4, mesh8, method="scan",
    )
    np.testing.assert_allclose(
        rs.scatter_to_global(np.asarray(out)),
        sh16.scatter_to_global(np.asarray(want)),
        rtol=5e-5,
    )


def test_adaptive_repartition_p16_on_8_devices(g, mesh8):
    from lux_tpu.engine import repartition

    res = repartition.run_push_adaptive(
        SSSPProgram(nv=g.nv, start=0), g, 16, chunk=2, threshold=1.01,
        mesh=mesh8, method="scan",
    )
    np.testing.assert_array_equal(res.state, bfs_reference(g, 0))


def test_cli_p16_on_8_devices(capsys):
    from lux_tpu.apps import sssp as app

    rc = app.main(
        ["--rmat-scale", "9", "-ng", "16", "--distributed", "-check"]
    )
    assert rc == 0
    assert "[PASS]" in capsys.readouterr().out
