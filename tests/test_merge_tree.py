"""luxmerge: the asynchronous cross-part merge and the
frontier-tolerance refresh (ISSUE 17).

Pins the exactness contracts of ops/merge_tree.py — the static
reduction-tree schedule is bitwise-identical to the bulk left-fold for
the min/max/integer monoids at EVERY arity (byes included), the push
engine's tree mode lands on the bulk answer bitwise at every part
count, and the LUX_MERGE_MODE knob resolves exactly like the other
banked method knobs.  Plus the tolerance-refresh contract: a declared
served-error bound is HONORED against a float64 oracle of the merged
graph's fixpoint across churn sequences, tolerance=0 degrades to the
bitwise exact path (same probe function object, same compiled
program), the bound rides every standing read through the fleet as a
served-read tag (the luxmerge twin of PR 14's stale tag), and the
fused-overlay refresh route re-enters ONE compiled program across
delta occupancies.
"""
import numpy as np
import pytest

from lux_tpu.engine import methods, pull, push
from lux_tpu.graph import generate
from lux_tpu.graph.push_shards import build_push_shards
from lux_tpu.models.pagerank import ALPHA, _host_iteration
from lux_tpu.models.sssp import SSSPProgram, bfs_reference
from lux_tpu.mutate import MutableGraph, OP_DELETE, OP_INSERT
from lux_tpu.mutate import refresh as refresh_mod
from lux_tpu.ops import merge_tree


# ----------------------------------------------------------------------
# the static schedule (host-side plan)
# ----------------------------------------------------------------------


def test_plan_tree_schedule_shape():
    """Every arity's plan is a legal tournament: ceil(log2) levels,
    exactly arity-1 combines total, no index touched twice per level,
    and the byes keep non-powers-of-two balanced."""
    import math

    for arity in range(0, 18):
        levels = merge_tree.plan_tree(arity)
        want_depth = 0 if arity <= 1 else math.ceil(math.log2(arity))
        assert len(levels) == want_depth, arity
        assert merge_tree.tree_depth(arity) == want_depth
        total = 0
        for lvl in levels:
            touched = [i for pair in lvl for i in pair]
            assert len(touched) == len(set(touched)), (arity, lvl)
            total += len(lvl)
        assert total == max(arity - 1, 0), arity
    with pytest.raises(ValueError, match="arity"):
        merge_tree.plan_tree(-1)
    with pytest.raises(ValueError, match="num_dev"):
        merge_tree.bruck_schedule(0)
    # doubling offsets, ceil(log2 D) rounds
    assert merge_tree.bruck_schedule(1) == ()
    assert merge_tree.bruck_schedule(5) == (1, 2, 4)
    assert merge_tree.bruck_schedule(8) == (1, 2, 4)


def test_tree_combine_bitwise_monoids():
    """tree_combine == the bulk left-fold BITWISE for min/max (int and
    float) and integer sum at every arity 1..9 — the reassociation-free
    monoids the push engine ships tree mode for.  Float sum is checked
    only to float tolerance: it genuinely reassociates, which is why it
    stays behind the oracle-gated A/B race."""
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    ops = {"min": jnp.minimum, "max": jnp.maximum, "sum": jnp.add}
    for arity in range(1, 10):
        for dtype in (np.int32, np.float32):
            vals = rng.integers(-1000, 1000,
                                size=(arity, 33)).astype(dtype)
            for name, op in ops.items():
                got = np.asarray(
                    merge_tree.tree_combine(jnp.asarray(vals), op))
                bulk = vals[0]
                for i in range(1, arity):
                    bulk = np.asarray(op(bulk, vals[i]))
                if name == "sum" and dtype is np.float32:
                    np.testing.assert_allclose(got, bulk, rtol=1e-6)
                else:
                    assert np.array_equal(got, bulk), (arity, name,
                                                       dtype)
                # the neutral really is a combiner identity (bitwise)
                n = merge_tree.neutral(name, dtype)
                assert np.array_equal(
                    np.asarray(op(jnp.asarray(vals[0]), n)), vals[0])


# ----------------------------------------------------------------------
# the engine contract: tree merge == bulk merge at every part count
# ----------------------------------------------------------------------


def test_push_tree_merge_bitwise_vs_bulk():
    """run_push with merge="tree" lands on the bulk answer BITWISE at
    parts 1/2/4 (arity 1, even, and power-of-two paths through the
    schedule) and both match the BFS oracle."""
    g = generate.rmat(9, 8, seed=11)
    want = bfs_reference(g, 0)
    for parts in (1, 2, 4):
        shm = build_push_shards(g, parts)
        prog = SSSPProgram(nv=g.nv, start=0)
        outs = {}
        for mode in ("bulk", "tree"):
            st, _, _ = push.run_push(prog, shm, merge=mode)
            d = shm.scatter_to_global(np.asarray(st))
            assert np.array_equal(
                np.where(d >= prog.inf, g.nv, d), want), (parts, mode)
            outs[mode] = d
        assert np.array_equal(outs["bulk"], outs["tree"]), parts


def test_push_dist_tree_merge_bitwise_vs_bulk():
    """The virtual-mesh dist engine: the staged-ppermute Bruck queue
    exchange + tree combine lands on the bulk all_gather's answer
    BITWISE — the per-device rotation never reaches the carry (every
    downstream consumer is order-independent)."""
    from lux_tpu.parallel import mesh as mesh_lib

    g = generate.rmat(9, 8, seed=17)
    shm = build_push_shards(g, 4)
    prog = SSSPProgram(nv=g.nv, start=0)
    mesh = mesh_lib.make_mesh_for_parts(4)
    outs = {}
    for mode in ("bulk", "tree"):
        st, _, _ = push.run_push_dist(prog, shm, mesh, merge=mode)
        outs[mode] = np.asarray(st)
    assert outs["bulk"].tobytes() == outs["tree"].tobytes()
    d = shm.scatter_to_global(outs["tree"])
    assert np.array_equal(np.where(d >= prog.inf, g.nv, d),
                          bfs_reference(g, 0))


def test_merge_mode_knob(monkeypatch):
    """LUX_MERGE_MODE resolves like the other banked knobs: explicit
    env wins on any platform, invalid values raise naming the choices,
    and the CPU default stays the shipped bulk merge."""
    monkeypatch.delenv("LUX_MERGE_MODE", raising=False)
    assert methods.merge_mode("cpu") == "bulk"
    monkeypatch.setenv("LUX_MERGE_MODE", "tree")
    assert methods.merge_mode("cpu") == "tree"
    assert push._resolve_merge(None) == "tree"
    monkeypatch.setenv("LUX_MERGE_MODE", "chaotic")
    with pytest.raises(ValueError, match="LUX_MERGE_MODE"):
        methods.merge_mode("cpu")
    monkeypatch.delenv("LUX_MERGE_MODE", raising=False)
    with pytest.raises(ValueError, match="merge"):
        push._resolve_merge("bogus")


# ----------------------------------------------------------------------
# frontier-tolerance refresh: the declared-error contract
# ----------------------------------------------------------------------


def _oracle_fixpoint(merged):
    """float64 fixpoint of the merged graph's recurrence — 200 exact
    host iterations (contraction ~ALPHA per step; 0.15^200 is far
    below f64 resolution)."""
    deg = merged.out_degrees().astype(np.float64)
    st = np.where(deg > 0, (1.0 / merged.nv) / np.maximum(deg, 1.0),
                  1.0 / merged.nv)
    for _ in range(200):
        st = _host_iteration(merged, st, deg)
    return st


def _churn(mg, g, rng, ndel=20, nins=30):
    if ndel:
        dele = rng.choice(g.ne, ndel, replace=False)
        mg.apply(g.col_idx[dele], g.dst_of_edges()[dele],
                 np.full(ndel, OP_DELETE, np.int8))
    mg.apply(rng.integers(0, g.nv, nins), rng.integers(0, g.nv, nins),
             np.full(nins, OP_INSERT, np.int8))


def test_tolerance_threshold_and_probe_identity():
    """The sizing formula and the compile-cache identity: the probe for
    a tolerance is ONE function object (one compiled loop per declared
    bound, zero retrace across refreshes), and tolerance<=0 returns the
    exact residual probe ITSELF."""
    t = refresh_mod.pagerank_tolerance_threshold(1e-4)
    assert t == pytest.approx(1e-4 * (1.0 - ALPHA))
    with pytest.raises(ValueError, match="tolerance"):
        refresh_mod.pagerank_tolerance_threshold(-1e-6)
    assert refresh_mod.pagerank_probe(0.0) is refresh_mod._changed_count
    assert refresh_mod.pagerank_probe(1e-4) is \
        refresh_mod.pagerank_probe(1e-4)
    assert refresh_mod.pagerank_probe(1e-4) is not \
        refresh_mod.pagerank_probe(1e-5)


def test_tolerance_contract_vs_f64_oracle():
    """The promise itself: across a churn sequence of warm refreshes,
    the max observed served error vs the float64 fixpoint of the merged
    graph stays <= the DECLARED tolerance — while the band buys fewer
    warm iterations than the exact path."""
    g = generate.rmat(9, 8, seed=21)
    exact_iters = {}
    for tol in (0.0, 1e-4, 1e-6):
        rng = np.random.default_rng(3)
        mg = MutableGraph(g, num_parts=2, cap=2048)
        pr, _ = refresh_mod.converge_pagerank(mg.pull_shards,
                                              tolerance=tol)
        iters = []
        for b in range(3):
            _churn(mg, g, rng)
            pr, it = refresh_mod.refresh_pagerank(mg, pr, tolerance=tol)
            iters.append(it)
            want = _oracle_fixpoint(mg.log.merged_graph())
            got = mg.pull_shards.scatter_to_global(np.asarray(pr))
            err = float(np.max(np.abs(got.astype(np.float64) - want)))
            if tol > 0:
                assert err <= tol, (tol, b, err)
            else:
                # exact path: f32 fixpoint noise only, orders below
                # any tolerance a caller would declare
                assert err <= 1e-8, (b, err)
        if tol == 0.0:
            exact_iters = dict(enumerate(iters))
        else:
            assert all(iters[b] <= exact_iters[b] for b in range(3)), (
                tol, iters, exact_iters)


def test_tolerance_zero_bitwise_exact_path():
    """tolerance=0 IS the exact refresh: same converged bits as the
    default call on the same churn — the degrade-to-exact leg of the
    contract."""
    g = generate.rmat(9, 8, seed=23)
    rng = np.random.default_rng(5)
    mg = MutableGraph(g, num_parts=2, cap=1024)
    pr0, _ = refresh_mod.converge_pagerank(mg.pull_shards)
    _churn(mg, g, rng)
    a, ita = refresh_mod.refresh_pagerank(mg, pr0)
    b, itb = refresh_mod.refresh_pagerank(mg, pr0, tolerance=0.0)
    assert ita == itb
    assert np.array_equal(np.asarray(a), np.asarray(b))


def test_tolerance_refresh_zero_retrace_fused_route():
    """Warm tolerance refreshes on the fused-pf route across delta
    occupancies re-enter ONE compiled program — the serving-config
    composition (fastest plan family + tolerance band) of
    test_mutate.py's zero-retrace pin."""
    from lux_tpu.ops import expand

    g = generate.rmat(9, 8, seed=7)
    rng = np.random.default_rng(0)
    mg = MutableGraph(g, num_parts=2, cap=512)
    route = expand.plan_fused_shards_cached(mg.pull_shards, "sum",
                                            pf=True, mx=False)
    pr, _ = refresh_mod.converge_pagerank(mg.pull_shards, route=route,
                                          tolerance=1e-5)
    sizes = []
    for lvl in (4, 60, 180):
        _churn(mg, g, rng, ndel=0, nins=lvl)
        pr, _ = refresh_mod.refresh_pagerank(mg, pr, route=route,
                                             tolerance=1e-5)
        sizes.append(pull._pull_until_jit._cache_size())
    assert sizes[0] == sizes[1] == sizes[2], sizes


# ----------------------------------------------------------------------
# the served-read tag: tolerance rides every standing read
# ----------------------------------------------------------------------


def test_tolerance_tag_through_fleet():
    """A fleet started with a declared tolerance serves the bound on
    EVERY standing pagerank read — the tag a client needs to interpret
    an approximate answer, exactly like the stale tag; apps refreshed
    exactly tag 0.0."""
    from lux_tpu.serve.live.controller import start_live_fleet

    g = generate.rmat(8, 8, seed=4)
    tol = 2e-4
    fleet = start_live_fleet(
        2, g, parts=2, cap=512,
        standing=(("sssp", 0), ("pagerank", None)), tolerance=tol)
    ctl = fleet.controller
    try:
        rng = np.random.default_rng(1)
        src = rng.integers(0, g.nv, 16)
        dst = rng.integers(0, g.nv, 16)
        ctl.admit_writes(src, dst, np.ones(16, np.int8))
        ctl.refresh_fleet()
        ent = ctl.read_standing("pagerank")
        assert ent["tolerance"] == pytest.approx(tol)
        assert ent["generation"] >= 1
        # every replica tags, not just the routed one
        for wid, e in ctl.read_standing_all("pagerank").items():
            assert e["tolerance"] == pytest.approx(tol), wid
        # the exact app's tag is 0.0 — absence of a band is declared too
        assert ctl.read_standing("sssp")["tolerance"] == 0.0
    finally:
        fleet.close()


def test_tolerance_tag_default_zero_single_host():
    """The default serving config declares tolerance 0.0 on its
    standing entries (the LiveReplica knob surface, no fleet)."""
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.serve.live.replica import LiveReplica

    g = generate.rmat(8, 8, seed=4)
    solo = LiveReplica(g, build_pull_shards(g, 2), cap=256,
                       standing=(("pagerank", None),))
    solo.refresh()
    ent = solo.standing("pagerank")
    assert ent.get("tolerance", 0.0) == 0.0
    assert solo.tolerance == 0.0
