"""Delta-stepping weighted SSSP (engine/delta.py): exact distances
(Dijkstra-validated), traversed-edge counts strictly below the chaotic
relaxation baseline, and the CLI/validation surface.  No reference code
to match (its SSSP is BFS, sssp_gpu.cu:122); BASELINE.json's config
list names the frontier delta-stepping kernel as the target framing."""
import os
import subprocess
import sys

import numpy as np
import pytest

from lux_tpu.engine import delta as delta_mod
from lux_tpu.engine import push
from lux_tpu.graph import generate
from lux_tpu.graph.push_shards import build_push_shards
from lux_tpu.models import sssp as sssp_model


def _chaotic_and_delta(g, P, start, delta, method="scan"):
    shards = build_push_shards(g, P)
    prog = sssp_model.WeightedSSSPProgram(nv=shards.spec.nv, start=start)
    st_c, _, e_c = push.run_push(prog, shards, method=method)
    st_d, _, e_d = delta_mod.run_push_delta(
        prog, shards, delta, method=method)
    return (shards.scatter_to_global(np.asarray(st_c)),
            shards.scatter_to_global(np.asarray(st_d)),
            push.edges_total(e_c), push.edges_total(e_d))


@pytest.mark.parametrize("delta", [1, 5, 20])
def test_delta_matches_chaotic_and_cuts_edges(delta):
    g = generate.rmat(11, 8, seed=5, weighted=True, max_weight=20)
    base, got, e_c, e_d = _chaotic_and_delta(g, 4, 1, delta)
    assert (base == got).all()
    # the whole point: bucket ordering expands most vertices once, with
    # their final distance — strictly fewer relaxed edges
    assert e_d < e_c, (delta, e_d, e_c)


def test_delta_vs_dijkstra_oracle():
    scipy_sparse = pytest.importorskip("scipy.sparse")
    from scipy.sparse.csgraph import dijkstra

    g = generate.uniform_random(256, 2048, seed=44, weighted=True,
                                max_weight=9)
    got = sssp_model.sssp(g, start=0, weighted=True, delta=3, num_parts=2)
    dst = g.dst_of_edges()
    order = np.lexsort((g.weights, g.col_idx, dst))
    s, d, w = g.col_idx[order], dst[order], g.weights[order]
    first = np.ones(g.ne, bool)
    first[1:] = (s[1:] != s[:-1]) | (d[1:] != d[:-1])
    A = scipy_sparse.csr_matrix(
        (w[first], (s[first], d[first])), shape=(g.nv, g.nv))
    want = dijkstra(A, directed=True, indices=0, unweighted=False)
    finite = np.isfinite(want)
    np.testing.assert_array_equal(got[finite], want[finite].astype(np.int64))
    assert np.all(got[~finite] == sssp_model.inf_value(g.nv, weighted=True))
    assert sssp_model.check_distances(g, got, weighted=True) == 0


def test_delta_bucket_width_tradeoff():
    """Smaller Δ -> fewer edges, more rounds (the Meyer-Sanders knob);
    a Δ above the weight diameter degenerates to chaotic behavior."""
    g = generate.rmat(10, 8, seed=6, weighted=True, max_weight=20)
    shards = build_push_shards(g, 2)
    prog = sssp_model.WeightedSSSPProgram(nv=shards.spec.nv, start=1)
    rows = {}
    for delta in (1, 20, 10**6):
        st, it, ed = delta_mod.run_push_delta(prog, shards, delta)
        rows[delta] = (int(it), push.edges_total(ed),
                       shards.scatter_to_global(np.asarray(st)))
    assert (rows[1][2] == rows[20][2]).all()
    assert (rows[1][2] == rows[10**6][2]).all()
    assert rows[1][1] <= rows[20][1] <= rows[10**6][1]
    assert rows[1][0] >= rows[20][0]
    # huge Δ: every pending vertex is always in the bucket == chaotic
    _, _, e_c = push.run_push(prog, shards)
    assert rows[10**6][1] == push.edges_total(e_c)


def test_delta_zero_weight_edges_settle():
    """0-weight edges re-enter the same bucket (within-bucket fixpoint)
    and still converge to exact distances."""
    edges = np.array([
        [0, 1, 0], [1, 2, 0], [2, 3, 4], [0, 3, 5], [3, 4, 1],
    ], np.int64)
    from lux_tpu.graph.csc import from_edge_list

    g = from_edge_list(edges[:, 0], edges[:, 1], nv=5,
                       weights=edges[:, 2])
    got = sssp_model.sssp(g, start=0, weighted=True, delta=2)
    assert got.tolist() == [0, 0, 0, 4, 5]


def test_delta_validation():
    g = generate.rmat(9, 4, seed=7, weighted=True)
    gu = generate.rmat(9, 4, seed=7)
    with pytest.raises(ValueError, match="WEIGHTED"):
        sssp_model.sssp(gu, weighted=False, delta=2)
    with pytest.raises(ValueError, match="delta must be positive"):
        shards = build_push_shards(g, 1)
        prog = sssp_model.WeightedSSSPProgram(nv=shards.spec.nv)
        delta_mod.run_push_delta(prog, shards, 0)
    with pytest.raises(ValueError, match="min-relaxation"):
        shards = build_push_shards(g, 1)
        from lux_tpu.models.components import MaxLabelProgram

        delta_mod.run_push_delta(MaxLabelProgram(), shards, 2)
    with pytest.raises(ValueError, match="allgather"):
        sssp_model.sssp(g, weighted=True, delta=2, exchange="ring")


def test_delta_distributed_matches_single():
    """run_push_delta_dist: same bucket discipline over the mesh (one
    psum vote + one pmin advance), bitwise-equal states AND identical
    round/edge counts, including k-resident parts (P=16 on 8 devices)."""
    from lux_tpu.parallel import mesh as mesh_lib

    g = generate.rmat(10, 8, seed=9, weighted=True, max_weight=15)
    for P in (8, 16):
        shards = build_push_shards(g, P)
        prog = sssp_model.WeightedSSSPProgram(nv=shards.spec.nv, start=1)
        st_s, it_s, e_s = delta_mod.run_push_delta(prog, shards, 4)
        msh = mesh_lib.make_mesh_for_parts(P)
        st_d, it_d, e_d = delta_mod.run_push_delta_dist(
            prog, shards, 4, msh)
        assert (np.asarray(st_s) == np.asarray(st_d)).all()
        assert int(it_s) == int(it_d)
        assert push.edges_total(e_s) == push.edges_total(e_d)
    # model-level dispatch reaches the distributed driver
    got = sssp_model.sssp(g, start=1, weighted=True, delta=4,
                          num_parts=8, mesh=mesh_lib.make_mesh_for_parts(8))
    base = sssp_model.sssp(g, start=1, weighted=True, delta=4, num_parts=8)
    assert (got == base).all()


def test_delta_composes_with_compact_gather():
    """Delta's dense rounds carry the compact mirror (dense_part_step);
    results and edge counts are bitwise-unchanged by the relayout."""
    g = generate.rmat(10, 8, seed=11, weighted=True, max_weight=15)
    a = build_push_shards(g, 2)
    b = build_push_shards(g, 2, compact_gather=True)
    prog = sssp_model.WeightedSSSPProgram(nv=a.spec.nv, start=1)
    # a large bucket forces at least one dense round through the mirror
    st_a, it_a, e_a = delta_mod.run_push_delta(prog, a, 10**6)
    st_b, it_b, e_b = delta_mod.run_push_delta(prog, b, 10**6)
    assert (np.asarray(st_a) == np.asarray(st_b)).all()
    assert (int(it_a), push.edges_total(e_a)) == (
        int(it_b), push.edges_total(e_b))


def test_delta_rerun_bitwise():
    """Two runs of the same delta program are bitwise identical (the
    determinism contract every engine carries, tests/test_determinism)."""
    g = generate.rmat(10, 8, seed=12, weighted=True, max_weight=15)
    shards = build_push_shards(g, 3)
    prog = sssp_model.WeightedSSSPProgram(nv=shards.spec.nv, start=1)
    outs = [delta_mod.run_push_delta(prog, shards, 4) for _ in range(2)]
    assert (np.asarray(outs[0][0]) == np.asarray(outs[1][0])).all()
    assert push.edges_total(outs[0][2]) == push.edges_total(outs[1][2])


def test_delta_checkpoint_resume(tmp_path):
    """Windowed delta checkpointing: an interrupted run (last save
    deleted) resumes mid-buckets and re-converges to the uninterrupted
    distances; the single-device save resumes ELASTICALLY on a
    different part count.  Saves carry state + pending + thr + the
    exact edge counter (utils/checkpoint.save_delta)."""
    import dataclasses
    import os

    from lux_tpu.apps import sssp as sssp_app
    from lux_tpu.utils.config import RunConfig

    # seed 5 runs 24 bucket rounds from start=1 — plenty of windows
    g = generate.rmat(10, 8, seed=5, weighted=True, max_weight=20)
    base = sssp_model.sssp(g, start=1, weighted=True, delta=4)
    d = str(tmp_path / "ck")
    cfg = RunConfig(
        file=None, num_parts=2, num_iters=10, start=1, weighted=True,
        delta=4, ckpt_dir=d, ckpt_every=3, max_iters=100000,
        method="scan",
    )
    shards = build_push_shards(g, 2)
    prog = sssp_model.WeightedSSSPProgram(nv=shards.spec.nv, start=1)
    st, it, edges, _ = sssp_app.run_delta_checkpointed(
        prog, shards, cfg, None, "sssp")
    got = shards.scatter_to_global(np.asarray(st))[: g.nv]
    assert (got == base).all()
    full_edges = push.edges_total(edges)
    # interrupt: drop the final checkpoint, resume, re-converge
    saves = sorted(os.listdir(d), key=lambda s: int(s[5:-4]))
    assert len(saves) >= 2
    os.remove(os.path.join(d, saves[-1]))
    st2, it2, edges2, _ = sssp_app.run_delta_checkpointed(
        prog, shards, cfg, None, "sssp")
    assert (shards.scatter_to_global(np.asarray(st2))[: g.nv] == base).all()
    assert push.edges_total(edges2) == full_edges  # exact counter carried
    # elastic: resume the 2-part save on a 4-part layout
    saves = sorted(os.listdir(d), key=lambda s: int(s[5:-4]))
    os.remove(os.path.join(d, saves[-1]))
    sh4 = build_push_shards(g, 4)
    prog4 = sssp_model.WeightedSSSPProgram(nv=sh4.spec.nv, start=1)
    cfg4 = dataclasses.replace(cfg, num_parts=4)
    st3, _, _, _ = sssp_app.run_delta_checkpointed(
        prog4, sh4, cfg4, None, "sssp")
    assert (sh4.scatter_to_global(np.asarray(st3))[: g.nv] == base).all()


def test_cli_delta():
    from conftest import forced_cpu_env

    env = forced_cpu_env()
    r = subprocess.run(
        [sys.executable, "-m", "lux_tpu.apps.sssp", "--rmat-scale", "9",
         "--weighted", "--delta", "4", "-start", "1", "-check"],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "[PASS]" in r.stdout
    # --delta without --weighted is an error, not a silent BFS run
    r2 = subprocess.run(
        [sys.executable, "-m", "lux_tpu.apps.sssp", "--rmat-scale", "9",
         "--delta", "4"],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert r2.returncode != 0
    assert "--weighted" in r2.stderr
