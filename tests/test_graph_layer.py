"""Graph layer: .lux round-trip, converter semantics, partitioner, shards."""
import numpy as np
import pytest

from lux_tpu.graph import generate
from lux_tpu.graph.csc import from_edge_list
from lux_tpu.graph.format import read_lux, write_lux
from lux_tpu.graph.partition import edge_balanced_cuts, part_of_vertex
from lux_tpu.graph.shards import build_pull_shards


def tiny_graph():
    # 0->1, 0->2, 1->2, 2->0, 3->2  (nv=4)
    src = np.array([0, 0, 1, 2, 3])
    dst = np.array([1, 2, 2, 0, 2])
    return from_edge_list(src, dst, 4)


def test_from_edge_list_csc():
    g = tiny_graph()
    assert g.nv == 4 and g.ne == 5
    np.testing.assert_array_equal(g.row_ptr, [0, 1, 2, 5, 5])
    # in-neighbors of 2 are {0, 1, 3} in stable input order
    np.testing.assert_array_equal(np.sort(g.col_idx[2:5]), [0, 1, 3])
    np.testing.assert_array_equal(g.col_idx[0:1], [2])  # in-nbr of 0
    np.testing.assert_array_equal(g.out_degrees(), [2, 1, 1, 1])
    g.validate()


def test_lux_roundtrip(tmp_path):
    g = generate.uniform_random(100, 500, seed=3)
    p = str(tmp_path / "g.lux")
    write_lux(p, g)
    g2 = read_lux(p)
    assert g2.nv == g.nv and g2.ne == g.ne
    np.testing.assert_array_equal(g2.row_ptr, g.row_ptr)
    np.testing.assert_array_equal(g2.col_idx, g.col_idx)
    assert g2.weights is None


def test_lux_roundtrip_weighted(tmp_path):
    g = generate.uniform_random(50, 300, seed=4, weighted=True)
    p = str(tmp_path / "gw.lux")
    write_lux(p, g)
    g2 = read_lux(p)
    assert g2.weighted
    np.testing.assert_array_equal(g2.weights, g.weights)
    # explicit weighted=False must ignore the weight block
    g3 = read_lux(p, weighted=False)
    assert g3.weights is None


def test_csr_roundtrip():
    g = generate.uniform_random(64, 400, seed=5, weighted=True)
    csr_row_ptr, csr_dst, perm = g.to_csr()
    assert csr_row_ptr[-1] == g.ne
    # every CSR edge (s, d) must exist in CSC
    dst_of = g.dst_of_edges()
    for s in [0, 7, 31]:
        outs = np.sort(csr_dst[csr_row_ptr[s] : csr_row_ptr[s + 1]])
        ins = np.sort(dst_of[g.col_idx == s])
        np.testing.assert_array_equal(outs, ins)
    # perm maps CSR slots to CSC edge ids: src must match
    srcs_via_perm = g.col_idx[perm]
    expect = np.repeat(np.arange(g.nv), np.diff(csr_row_ptr))
    np.testing.assert_array_equal(srcs_via_perm, expect)


@pytest.mark.parametrize("num_parts", [1, 2, 3, 8])
def test_edge_balanced_cuts(num_parts):
    g = generate.rmat(10, 8, seed=7)
    cuts = edge_balanced_cuts(g.row_ptr, num_parts)
    assert cuts[0] == 0 and cuts[-1] == g.nv
    assert np.all(np.diff(cuts) >= 0)
    e_cap = -(-g.ne // num_parts)
    max_deg = int(np.diff(g.row_ptr).max())
    e_counts = g.row_ptr[cuts[1:]] - g.row_ptr[cuts[:-1]]
    assert e_counts.sum() == g.ne
    # each part's edges bounded by cap + one vertex's worth of slack
    assert np.all(e_counts <= e_cap + max_deg)


def test_part_of_vertex():
    g = generate.uniform_random(1000, 8000, seed=8)
    cuts = edge_balanced_cuts(g.row_ptr, 4)
    vids = np.arange(g.nv)
    parts = part_of_vertex(cuts, vids)
    for p in range(4):
        sel = (vids >= cuts[p]) & (vids < cuts[p + 1])
        assert np.all(parts[sel] == p)


@pytest.mark.parametrize("num_parts", [1, 4])
def test_build_pull_shards(num_parts):
    g = generate.rmat(9, 8, seed=9, weighted=True)
    sh = build_pull_shards(g, num_parts)
    spec, arr = sh.spec, sh.arrays
    assert arr.src_pos.shape == (num_parts, spec.e_pad)
    assert int(arr.edge_mask.sum()) == g.ne
    assert int(arr.vtx_mask.sum()) == g.nv
    # Reconstruct every edge (src, dst) from the shards and compare.
    got = []
    dst_of = g.dst_of_edges()
    for p in range(num_parts):
        m = int(arr.edge_mask[p].sum())
        rp = arr.row_ptr[p]
        # dst_local from row_ptr must match stored dst_local
        dl = np.repeat(np.arange(spec.nv_pad), np.diff(rp))
        np.testing.assert_array_equal(dl[:m], arr.dst_local[p, :m])
        assert np.all(arr.dst_local[p, m:] == spec.nv_pad)
        # src_pos decodes back to the global src id
        pos = arr.src_pos[p, :m]
        owner = pos // spec.nv_pad
        src_global = sh.cuts[owner] + pos % spec.nv_pad
        dst_global = arr.dst_local[p, :m] + int(sh.cuts[p])
        got.append(np.stack([src_global, dst_global], 1))
    got = np.concatenate(got)
    expect = np.stack([g.col_idx, dst_of], 1)
    np.testing.assert_array_equal(
        got[np.lexsort(got.T)], expect[np.lexsort(expect.T)]
    )
    # degrees land on the right global vertices
    deg_global = sh.scatter_to_global(arr.degree)
    np.testing.assert_array_equal(deg_global, g.out_degrees())
    # weights preserved
    total_w = sum(arr.weights[p, arr.edge_mask[p]].sum() for p in range(num_parts))
    assert total_w == pytest.approx(g.weights.sum())


def test_stacked_global_roundtrip():
    g = generate.uniform_random(777, 5000, seed=11)
    sh = build_pull_shards(g, 4)
    x = np.random.default_rng(0).random(g.nv).astype(np.float32)
    stacked = sh.global_to_stacked(x)
    np.testing.assert_array_equal(sh.scatter_to_global(stacked), x)


def test_sort_segments_layout_invariants():
    """The gather-locality relayout moves ONLY src_pos/weights: dst
    sequence, head flags, masks, row_ptr are untouched; within every
    segment the (src, weight) multiset is preserved and src_pos is
    nondecreasing."""
    from lux_tpu.graph import generate
    from lux_tpu.graph.shards import build_pull_shards

    g = generate.rmat(10, 8, seed=77, weighted=True)
    a = build_pull_shards(g, 4)
    b = build_pull_shards(g, 4, sort_segments=True)
    for name in ("row_ptr", "dst_local", "head_flag", "edge_mask",
                 "vtx_mask", "degree", "global_vid"):
        np.testing.assert_array_equal(
            getattr(a.arrays, name), getattr(b.arrays, name), err_msg=name
        )
    for p in range(4):
        dl = a.arrays.dst_local[p]
        for seg in np.unique(dl):
            m = dl == seg
            sp = b.arrays.src_pos[p][m]
            assert (np.diff(sp) >= 0).all()  # sorted within the segment
            pairs_a = sorted(zip(a.arrays.src_pos[p][m],
                                 a.arrays.weights[p][m]))
            pairs_b = sorted(zip(sp, b.arrays.weights[p][m]))
            assert pairs_a == pairs_b  # same (src, weight) multiset


def test_sort_segments_engine_equivalence():
    """Sorted layout computes the same fixed points: pagerank within
    float-rounding tolerance, CC labels bitwise (min/max order-free)."""
    import jax

    from lux_tpu.engine import pull
    from lux_tpu.graph import generate
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.models import components as cc
    from lux_tpu.models.pagerank import PageRankProgram

    g = generate.rmat(10, 8, seed=78)
    outs = {}
    for sort in (False, True):
        sh = build_pull_shards(g, 2, sort_segments=sort)
        prog = PageRankProgram(nv=sh.spec.nv)
        arr = jax.tree.map(np.asarray, sh.arrays)
        s0 = pull.init_state(prog, arr)
        outs[sort] = sh.scatter_to_global(
            np.asarray(pull.run_pull_fixed(prog, sh.spec, arr, s0, 5))
        )
    np.testing.assert_allclose(outs[True], outs[False], rtol=1e-6)
    labels = {}
    for sort in (False, True):
        sh = build_pull_shards(g, 2, sort_segments=sort)
        mp = cc.MaxLabelProgram()
        arr = jax.tree.map(np.asarray, sh.arrays)
        s0 = pull.init_state(mp, arr)
        out, _ = pull.run_pull_until(
            mp, sh.spec, arr, s0, 64, cc.active_count
        )
        labels[sort] = sh.scatter_to_global(np.asarray(out))
    np.testing.assert_array_equal(labels[True], labels[False])


def test_sort_segments_cli(capsys):
    """--sort-segments runs end-to-end; bucket layouts reject it."""
    import pytest

    from lux_tpu.apps import pagerank as pr_app

    args = ["--rmat-scale", "9", "--rmat-ef", "4", "-ni", "3"]
    assert pr_app.main(args + ["--sort-segments"]) == 0
    assert "top-5" in capsys.readouterr().out
    with pytest.raises(SystemExit, match="sort-segments"):
        pr_app.main(args + ["--sort-segments", "-ng", "8", "--distributed",
                            "--exchange", "ring"])


def test_sort_segments_push_bitwise():
    """Push apps (min/max relaxation) are BITWISE invariant under the
    relayout — sssp distances identical, sorted vs not, incl. -check."""
    from lux_tpu.apps import sssp as sssp_app
    from lux_tpu.graph import generate
    from lux_tpu.graph.push_shards import build_push_shards
    from lux_tpu.models.sssp import sssp

    g = generate.rmat(10, 8, seed=79)
    plain = sssp(build_push_shards(g, 4), start=1)
    sorted_ = sssp(build_push_shards(g, 4, sort_segments=True), start=1)
    np.testing.assert_array_equal(np.asarray(plain), np.asarray(sorted_))
    args = ["--rmat-scale", "9", "--rmat-ef", "4", "-start", "1", "-check"]
    assert sssp_app.main(args + ["--sort-segments"]) == 0
    with pytest.raises(SystemExit, match="sort-segments"):
        sssp_app.main(args + ["--sort-segments", "--method", "pallas",
                              "-ng", "2", "--distributed"])
