"""Test harness: force an 8-device virtual CPU mesh (SURVEY.md §4 — the
substitute for the reference's missing multi-node fake backend; multi-chip
logic is exercised without TPU hardware).

Note: the environment may pre-import jax and point JAX_PLATFORMS at a real
accelerator plugin; we override BOTH the env var and the live jax config here,
before any backend is initialized, so tests never tunnel to hardware.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
# hermetic method resolution: a TPU bench run records .lux_winners.json
# at the repo root (by design — engine/methods overlay); the suite's
# expectations are about the STATIC table, so point the overlay at a
# path that never exists (tests that exercise the overlay monkeypatch
# this env var themselves)
os.environ.setdefault("LUX_METHOD_WINNERS", "/nonexistent-lux-winners")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Persistent compilation cache: the suite is compile-dominated on this
# 1-core host (multihost engine programs take minutes); caching programs
# that cost >1 s to build makes repeat runs cheap.  Same-machine only
# (/tmp), atomic writes, load errors degrade to a recompile.
jax.config.update("jax_compilation_cache_dir",
                  os.environ.get("LUX_JAX_CACHE", "/tmp/lux_jax_cache"))

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    assert jax.devices()[0].platform == "cpu", jax.devices()
    assert jax.device_count() == 8, jax.devices()


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(1234)


def forced_cpu_env() -> dict:
    """Child-process env for CLI subprocess tests: PYTHONPATH pinned to
    the repo root (NOT the inherited path — the axon sitecustomize would
    register the TPU plugin at interpreter start and hang every child
    when the relay is wedged) + JAX_PLATFORMS=cpu.  ONE implementation
    for every subprocess-spawning test."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    env["PYTHONPATH"] = repo
    env["JAX_PLATFORMS"] = "cpu"
    return env


def hub_vertex(g) -> int:
    """Max-out-degree start vertex for frontier-app tests: a fixed start
    (e.g. 0) can have zero out-edges on an RMAT draw and converge
    instantly, leaving nothing to exercise."""
    return int(np.argmax(np.bincount(g.col_idx, minlength=g.nv)))
