"""The chip-window insurance micro race (tools/tpu_micro_race.py),
driven as a real process on CPU: both method rows must appear, the
winner must be announced, and the overlay must NOT be written off-TPU
(only a chip measurement may change TPU defaults)."""
import json
import os
import subprocess
import sys

TOOL = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "tools", "tpu_micro_race.py")


def test_micro_race_cpu(tmp_path):
    from conftest import forced_cpu_env

    env = forced_cpu_env()
    env["LUX_METHOD_WINNERS"] = str(tmp_path / "w.json")
    r = subprocess.run(
        [sys.executable, TOOL, "--scale", "10", "--reps", "1", "2", "4",
         "--outdir", str(tmp_path / "out")],
        env=env, capture_output=True, text=True, timeout=540,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    rows = [json.loads(s) for s in r.stdout.splitlines()
            if s.startswith("{")]
    # the default race is the three-way scan family (ISSUE 11): the
    # banked tpu:sum winner requires ALL of them to measure
    assert {row["method"] for row in rows} == {"mxsum", "mxscan", "scan"}
    for row in rows:
        assert row["micro"] == "segment_sum"
        # toy scale: slope noise may go negative; the field must exist
        assert isinstance(row["ms_per_rep"], float)
    assert "# micro race winner:" in r.stdout
    # off-TPU: the tpu:micro_sum overlay entry must not be recorded
    assert "not on tpu" in r.stdout
    assert not (tmp_path / "w.json").exists()


def test_micro_race_gather_modes(tmp_path):
    """The gather-half workers (direct vs compact mirror) produce rows
    but never the method winner (they inform layout, not method)."""
    from conftest import forced_cpu_env

    env = forced_cpu_env()
    env["LUX_METHOD_WINNERS"] = str(tmp_path / "w.json")
    r = subprocess.run(
        [sys.executable, TOOL, "--scale", "10", "--reps", "1", "2", "4",
         "--methods", "gather", "gatherc", "mxsum",
         "--outdir", str(tmp_path / "out")],
        env=env, capture_output=True, text=True, timeout=540,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    rows = {json.loads(s)["method"]: json.loads(s)
            for s in r.stdout.splitlines() if s.startswith("{")}
    assert set(rows) == {"gather", "gatherc", "mxsum"}
    assert rows["gather"]["micro"] == "gather"
    assert "# compact mirror: U=" in r.stdout
    # gather rows are excluded from the method decision (at toy scale
    # the mxsum slope may be noise-negative -> winner None; either way
    # a gather mode must never win)
    assert "winner: gather" not in r.stdout
    assert "winner: mxsum" in r.stdout or "winner: None" in r.stdout
