"""Segment reductions vs numpy oracles, across all strategies."""
import numpy as np
import jax.numpy as jnp
import pytest

from lux_tpu.graph import generate
from lux_tpu.graph.shards import build_pull_shards
from lux_tpu.ops import segment


def _oracle(g, vals, op, neutral):
    out = np.full(g.nv, neutral, dtype=vals.dtype)
    dst = g.dst_of_edges()
    for e in range(g.ne):
        out[dst[e]] = op(out[dst[e]], vals[e])
    return out


@pytest.mark.parametrize("method", ["scan", "cumsum", "scatter"])
def test_segment_sum(method):
    g = generate.rmat(8, 8, seed=1)
    sh = build_pull_shards(g, 1)
    arr = sh.arrays
    rng = np.random.default_rng(2)
    vals = np.zeros(sh.spec.e_pad, np.float32)
    vals[: g.ne] = rng.random(g.ne)
    out = segment.segment_sum_csc(
        jnp.asarray(vals), jnp.asarray(arr.row_ptr[0]),
        jnp.asarray(arr.head_flag[0]), jnp.asarray(arr.dst_local[0]),
        method=method,
    )
    expect = _oracle(g, vals[: g.ne], np.add, 0.0)
    # cumsum pays float32 prefix-cancellation error — documented tradeoff,
    # which is exactly why "scan" is the default strategy.
    rtol = 5e-3 if method == "cumsum" else 2e-5
    np.testing.assert_allclose(np.asarray(out)[: g.nv], expect, rtol=rtol)


@pytest.mark.parametrize("method", ["scan", "scatter"])
@pytest.mark.parametrize("kind", ["min", "max"])
def test_segment_minmax(method, kind):
    g = generate.rmat(8, 4, seed=3)
    sh = build_pull_shards(g, 1)
    arr = sh.arrays
    rng = np.random.default_rng(4)
    # Padding tail holds arbitrary junk: dst_local sentinels must drop it.
    vals = np.full(sh.spec.e_pad, 12345, np.int32)
    vals[: g.ne] = rng.integers(0, 1 << 20, g.ne)
    if kind == "min":
        fn, op, neutral = segment.segment_min_csc, min, np.iinfo(np.int32).max
    else:
        fn, op, neutral = segment.segment_max_csc, max, np.iinfo(np.int32).min
    out = fn(
        jnp.asarray(vals), jnp.asarray(arr.row_ptr[0]),
        jnp.asarray(arr.head_flag[0]), jnp.asarray(arr.dst_local[0]),
        method=method,
    )
    expect = _oracle(g, vals[: g.ne], op, neutral)
    np.testing.assert_array_equal(np.asarray(out)[: g.nv], expect)


def test_segment_sum_2d():
    """(E, K) values — the CF latent-vector accumulation shape."""
    g = generate.uniform_random(60, 400, seed=5)
    sh = build_pull_shards(g, 1)
    arr = sh.arrays
    K = 8
    rng = np.random.default_rng(6)
    vals = np.zeros((sh.spec.e_pad, K), np.float32)
    vals[: g.ne] = rng.random((g.ne, K))
    out = segment.segment_sum_csc(
        jnp.asarray(vals), jnp.asarray(arr.row_ptr[0]),
        jnp.asarray(arr.head_flag[0]),
    )
    dst = g.dst_of_edges()
    expect = np.zeros((g.nv, K), np.float32)
    np.add.at(expect, dst, vals[: g.ne])
    np.testing.assert_allclose(np.asarray(out)[: g.nv], expect, rtol=2e-5)
