"""Segment reductions vs numpy oracles, across all strategies."""
import numpy as np
import jax.numpy as jnp
import pytest

from lux_tpu.graph import generate
from lux_tpu.graph.shards import build_pull_shards
from lux_tpu.ops import segment


def _oracle(g, vals, op, neutral):
    out = np.full(g.nv, neutral, dtype=vals.dtype)
    dst = g.dst_of_edges()
    for e in range(g.ne):
        out[dst[e]] = op(out[dst[e]], vals[e])
    return out


@pytest.mark.parametrize("method", ["scan", "cumsum", "scatter"])
def test_segment_sum(method):
    g = generate.rmat(8, 8, seed=1)
    sh = build_pull_shards(g, 1)
    arr = sh.arrays
    rng = np.random.default_rng(2)
    vals = np.zeros(sh.spec.e_pad, np.float32)
    vals[: g.ne] = rng.random(g.ne)
    out = segment.segment_sum_csc(
        jnp.asarray(vals), jnp.asarray(arr.row_ptr[0]),
        jnp.asarray(arr.head_flag[0]), jnp.asarray(arr.dst_local[0]),
        method=method,
    )
    expect = _oracle(g, vals[: g.ne], np.add, 0.0)
    # cumsum pays float32 prefix-cancellation error — documented tradeoff,
    # which is exactly why "scan" is the default strategy.
    rtol = 5e-3 if method == "cumsum" else 2e-5
    np.testing.assert_allclose(np.asarray(out)[: g.nv], expect, rtol=rtol)


@pytest.mark.parametrize("method", ["scan", "scatter"])
@pytest.mark.parametrize("kind", ["min", "max"])
def test_segment_minmax(method, kind):
    g = generate.rmat(8, 4, seed=3)
    sh = build_pull_shards(g, 1)
    arr = sh.arrays
    rng = np.random.default_rng(4)
    # Padding tail holds arbitrary junk: dst_local sentinels must drop it.
    vals = np.full(sh.spec.e_pad, 12345, np.int32)
    vals[: g.ne] = rng.integers(0, 1 << 20, g.ne)
    if kind == "min":
        fn, op, neutral = segment.segment_min_csc, min, np.iinfo(np.int32).max
    else:
        fn, op, neutral = segment.segment_max_csc, max, np.iinfo(np.int32).min
    out = fn(
        jnp.asarray(vals), jnp.asarray(arr.row_ptr[0]),
        jnp.asarray(arr.head_flag[0]), jnp.asarray(arr.dst_local[0]),
        method=method,
    )
    expect = _oracle(g, vals[: g.ne], op, neutral)
    np.testing.assert_array_equal(np.asarray(out)[: g.nv], expect)


def test_segment_sum_2d():
    """(E, K) values — the CF latent-vector accumulation shape."""
    g = generate.uniform_random(60, 400, seed=5)
    sh = build_pull_shards(g, 1)
    arr = sh.arrays
    K = 8
    rng = np.random.default_rng(6)
    vals = np.zeros((sh.spec.e_pad, K), np.float32)
    vals[: g.ne] = rng.random((g.ne, K))
    out = segment.segment_sum_csc(
        jnp.asarray(vals), jnp.asarray(arr.row_ptr[0]),
        jnp.asarray(arr.head_flag[0]),
    )
    dst = g.dst_of_edges()
    expect = np.zeros((g.nv, K), np.float32)
    np.add.at(expect, dst, vals[: g.ne])
    np.testing.assert_allclose(np.asarray(out)[: g.nv], expect, rtol=2e-5)


@pytest.mark.parametrize("method", ["scan", "scatter"])
@pytest.mark.parametrize("reduce", ["sum", "min", "max"])
def test_segment_reduce_by_ends(method, reduce):
    """Row_ptr-free bucketed reduction (ring/scatter layouts) vs oracle,
    including empty rows, padding slots, and a wide (E, K) value axis."""
    from lux_tpu.parallel.ring import mark_bucket_heads

    rng = np.random.default_rng(7)
    V, m, B = 37, 60, 128
    dl = np.sort(rng.integers(0, V, size=m)).astype(np.int32)
    dst = np.full(B, V, np.int32)
    dst[:m] = dl
    head = np.zeros(B, bool)
    mark_bucket_heads(head, dl)
    vals = np.zeros(B, np.float32)
    vals[:m] = rng.random(m).astype(np.float32) + 0.5

    ops = {"sum": np.add, "min": np.minimum, "max": np.maximum}
    neutral = {"sum": 0.0, "min": np.inf, "max": -np.inf}[reduce]
    want = np.full(V, neutral, np.float32)
    for j in range(m):
        want[dl[j]] = ops[reduce](want[dl[j]], vals[j])

    got = segment.segment_reduce_by_ends(
        jnp.asarray(vals), jnp.asarray(head), jnp.asarray(dst), V,
        reduce=reduce, method=method,
    )
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)

    if reduce == "sum":  # wide value axis (CF's (E, K) case)
        vk = np.zeros((B, 3), np.float32)
        vk[:m] = rng.random((m, 3)).astype(np.float32)
        want_k = np.zeros((V, 3), np.float32)
        np.add.at(want_k, dl, vk[:m])
        got_k = segment.segment_reduce_by_ends(
            jnp.asarray(vk), jnp.asarray(head), jnp.asarray(dst), V,
            reduce="sum", method=method,
        )
        np.testing.assert_allclose(np.asarray(got_k), want_k, rtol=1e-5)


def test_segment_reduce_by_ends_full_bucket():
    """No padding slot after the last edge: the appended end flag must
    close the final segment."""
    from lux_tpu.parallel.ring import mark_bucket_heads

    V, B = 5, 8
    dl = np.array([0, 0, 1, 1, 1, 3, 4, 4], np.int32)  # m == B
    head = np.zeros(B, bool)
    mark_bucket_heads(head, dl)
    vals = np.arange(1, 9, dtype=np.float32)
    got = segment.segment_reduce_by_ends(
        jnp.asarray(vals), jnp.asarray(head), jnp.asarray(dl), V,
        reduce="sum", method="scan",
    )
    np.testing.assert_allclose(np.asarray(got), [3, 12, 0, 6, 15])


def test_mxsum_matches_cumsum():
    import numpy as np
    import jax.numpy as jnp
    from lux_tpu.ops.segment import matmul_cumsum, segment_sum_csc
    rng = np.random.default_rng(11)
    for n in (1, 7, 512, 513, 5000, 300_000):
        x = jnp.asarray(rng.random(n, np.float32))
        got = np.asarray(matmul_cumsum(x))
        want = np.cumsum(np.asarray(x, np.float64))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-4)


def test_mxsum_segment_matches_scan():
    import numpy as np
    import jax.numpy as jnp
    from lux_tpu.graph import generate
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.ops import segment
    g = generate.rmat(9, 8, seed=13)
    sh = build_pull_shards(g, 1)
    a = sh.arrays
    rng = np.random.default_rng(3)
    vals = jnp.asarray(rng.random(a.src_pos.shape[1], np.float32))
    rp = jnp.asarray(a.row_ptr[0])
    hf = jnp.asarray(a.head_flag[0])
    dl = jnp.asarray(a.dst_local[0])
    want = np.asarray(segment.segment_sum_csc(vals, rp, hf, dl, method="scan"))
    got = np.asarray(segment.segment_sum_csc(vals, rp, hf, dl, method="mxsum"))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_pagerank_mxsum_method():
    import numpy as np
    from lux_tpu.graph import generate
    from lux_tpu.models import pagerank as pr
    g = generate.rmat(8, 8, seed=15)
    base = pr.pagerank(g, num_iters=5, method="scan")
    got = pr.pagerank(g, num_iters=5, method="mxsum")
    np.testing.assert_allclose(
        np.asarray(got, np.float64), np.asarray(base, np.float64),
        rtol=1e-4, atol=1e-7,
    )


def test_pagerank_mxsum_multipart():
    """mxsum under vmap (multi-part single device)."""
    import numpy as np
    from lux_tpu.graph import generate
    from lux_tpu.models import pagerank as pr
    g = generate.rmat(8, 8, seed=15)
    base = pr.pagerank(g, num_iters=5, method="scan", num_parts=3)
    got = pr.pagerank(g, num_iters=5, method="mxsum", num_parts=3)
    np.testing.assert_allclose(
        np.asarray(got, np.float64), np.asarray(base, np.float64),
        rtol=1e-4, atol=1e-7,
    )
