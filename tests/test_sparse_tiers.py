"""Two-tier sparse edge buffer: small frontiers walk O(e_sp_small), not
O(e_sp) (VERDICT r1 weak #3 — a 10-vertex frontier must not pay a full
e_pad/4 scan).  The tier choice is an execution detail: results must be
bitwise identical with the tier disabled, on every engine path."""
from __future__ import annotations

import dataclasses

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from lux_tpu.graph import generate
from lux_tpu.graph.push_shards import build_push_shards
from lux_tpu.models import components as cc
from lux_tpu.models import sssp as ss
from lux_tpu.parallel.mesh import make_mesh


def _untiered(shards):
    return dataclasses.replace(
        shards, pspec=dataclasses.replace(shards.pspec, e_sp_small=0)
    )


def test_pspec_has_small_tier():
    g = generate.rmat(10, 8, seed=0)
    sh = build_push_shards(g, 2)
    assert 0 < sh.pspec.e_sp_small < sh.pspec.e_sp


def test_sssp_tiered_bitwise_single():
    # long sparse tail: BFS from one vertex on a sparse-ish graph
    g = generate.rmat(10, 4, seed=2)
    sh = build_push_shards(g, 2)
    a = ss.sssp(sh, start=0)
    b = ss.sssp(_untiered(sh), start=0)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_cc_tiered_bitwise_single():
    g = generate.rmat(9, 4, seed=4)
    sh = build_push_shards(g, 3)
    a = cc.connected_components_push(sh)
    b = cc.connected_components_push(_untiered(sh))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sssp_tiered_bitwise_distributed():
    g = generate.rmat(10, 4, seed=6)
    mesh = make_mesh(4)
    sh = build_push_shards(g, 4)
    a = ss.sssp(sh, start=0, mesh=mesh)
    b = ss.sssp(_untiered(sh), start=0, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sssp_tiered_bitwise_ring():
    from lux_tpu.parallel.ring import build_push_ring_shards

    g = generate.rmat(10, 4, seed=8)
    mesh = make_mesh(4)
    rs = build_push_ring_shards(g, 4)
    a = ss.sssp(rs, start=0, mesh=mesh, exchange="ring")
    rs2 = dataclasses.replace(
        rs, push=_untiered(rs.push)
    )
    b = ss.sssp(rs2, start=0, mesh=mesh, exchange="ring")
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
