"""Randomized cross-engine consistency: for a batch of random graphs, every
engine/path must agree with the host oracle and with each other."""
import numpy as np
import pytest

from lux_tpu.graph import generate
from lux_tpu.graph.push_shards import build_push_shards
from lux_tpu.models import components, pagerank as pr, sssp

SEEDS = [7, 21, 99, 123, 4242]


@pytest.mark.parametrize("seed", SEEDS)
def test_fuzz_pagerank(seed):
    rng = np.random.default_rng(seed)
    scale = int(rng.integers(6, 10))
    ef = int(rng.integers(2, 12))
    parts = int(rng.integers(1, 5))
    g = generate.rmat(scale, ef, seed=seed)
    got = pr.pagerank(g, num_iters=4, num_parts=parts)
    np.testing.assert_allclose(got, pr.pagerank_reference(g, 4), rtol=5e-5)


@pytest.mark.parametrize("seed", SEEDS)
def test_fuzz_sssp(seed):
    rng = np.random.default_rng(seed + 1000)
    nv = int(rng.integers(50, 800))
    ne = int(rng.integers(nv, nv * 8))
    parts = int(rng.integers(1, 5))
    start = int(rng.integers(0, nv))
    g = generate.uniform_random(nv, ne, seed=seed)
    got = sssp.sssp(g, start=start, num_parts=parts)
    np.testing.assert_array_equal(got, sssp.bfs_reference(g, start))
    assert sssp.check_distances(g, got) == 0


@pytest.mark.parametrize("seed", SEEDS)
def test_fuzz_cc_push_vs_pull(seed):
    rng = np.random.default_rng(seed + 2000)
    nv = int(rng.integers(50, 600))
    ne = int(rng.integers(nv // 2, nv * 6))
    g = generate.uniform_random(nv, ne, seed=seed)
    a = components.connected_components(g)
    b = components.connected_components_push(g, num_parts=int(rng.integers(1, 4)))
    np.testing.assert_array_equal(a, b)
    assert components.check_labels(g, a) == 0

@pytest.mark.parametrize("seed", SEEDS[:3])
def test_fuzz_push_ring_vs_allgather(seed):
    """Randomized cross-exchange agreement for the frontier engine: the
    ring-dense driver must match the all_gather driver BITWISE (min/max
    folds are exact) on random graphs across the 8-device mesh."""
    from lux_tpu.engine import push
    from lux_tpu.parallel import ring
    from lux_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(seed + 3000)
    nv = int(rng.integers(64, 600))
    ne = int(rng.integers(nv, nv * 6))
    start = int(rng.integers(0, nv))
    g = generate.uniform_random(nv, ne, seed=seed)
    mesh = make_mesh(8)
    prs = ring.build_push_ring_shards(g, 8)
    prog = sssp.SSSPProgram(nv=prs.spec.nv, start=start)
    a, _, _ = push.run_push_ring(prog, prs, mesh)
    b, _, _ = push.run_push_dist(prog, build_push_shards(g, 8), mesh)
    assert np.asarray(a).tobytes() == np.asarray(b).tobytes()
    got = prs.scatter_to_global(np.asarray(a))
    np.testing.assert_array_equal(got, sssp.bfs_reference(g, start))


@pytest.mark.parametrize("seed", SEEDS[:3])
def test_fuzz_pallas_dist_pagerank(seed):
    """Randomized: the distributed Pallas engine agrees with the oracle
    across graph shapes / part counts / tile sizes (interpret mode)."""
    from lux_tpu.models.pagerank import PageRankProgram
    from lux_tpu.parallel import pallas_dist as pd
    from lux_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(seed + 3000)
    scale = int(rng.integers(6, 9))
    ef = int(rng.integers(2, 10))
    parts = int(rng.choice([2, 4]))
    v_blk = int(rng.choice([128, 256]))
    g = generate.rmat(scale, ef, seed=seed)
    pp = pd.build_pallas_parts(g, parts, v_blk=v_blk, t_chunk=128)
    prog = PageRankProgram(nv=pp.spec.nv)
    s0 = pd.init_state_pallas(prog, pp)
    out = pd.run_pull_fixed_pallas_dist(
        prog, pp, s0, 4, make_mesh(parts), interpret=True
    )
    got = pp.scatter_to_global(np.asarray(out))
    np.testing.assert_allclose(got, pr.pagerank_reference(g, 4), rtol=5e-5)


@pytest.mark.parametrize("seed", SEEDS[:3])
def test_fuzz_adaptive_repartition(seed):
    """Random graphs/windows/thresholds: the adaptive driver must reach
    the static fixpoint exactly, whatever recut schedule it takes."""
    from lux_tpu.engine import repartition

    rng = np.random.default_rng(seed + 5000)
    nv = int(rng.integers(100, 700))
    ne = int(rng.integers(nv, nv * 8))
    parts = int(rng.integers(2, 5))
    chunk = int(rng.integers(1, 4))
    threshold = float(rng.uniform(1.0, 1.3))
    start = int(rng.integers(0, nv))
    g = generate.uniform_random(nv, ne, seed=seed)
    prog = sssp.SSSPProgram(nv=g.nv, start=start)
    res = repartition.run_push_adaptive(
        prog, g, parts, chunk=chunk, threshold=threshold
    )
    np.testing.assert_array_equal(res.state, sssp.bfs_reference(g, start))
    assert sssp.check_distances(g, res.state) == 0


@pytest.mark.parametrize("seed", SEEDS[:3])
def test_fuzz_frontier_ckpt_elastic(seed, tmp_path):
    """Randomized kill-and-resume: interrupt SSSP at a random iteration
    on a random layout, resume on ANOTHER random layout; the global
    state, total iteration count, and exact traversed-edge counter must
    match the uninterrupted run bitwise."""
    import dataclasses

    from lux_tpu.apps import sssp as sssp_app
    from lux_tpu.engine import push
    from lux_tpu.utils.config import RunConfig

    rng = np.random.default_rng(seed + 7000)
    g = generate.rmat(int(rng.integers(8, 10)), int(rng.integers(4, 10)),
                      seed=seed)
    from conftest import hub_vertex

    start = hub_vertex(g)
    p1 = int(rng.integers(1, 5))
    p2 = p1 % 4 + 1  # always a DIFFERENT part count: cross-layout resume
    sh1 = build_push_shards(g, p1)
    prog = sssp.SSSPProgram(nv=sh1.spec.nv, start=start)
    want_st, want_it, want_e = push.run_push(prog, sh1, 1000, method="scan")
    if int(want_it) < 2:
        pytest.skip("instant convergence — nothing to interrupt")

    cut = int(rng.integers(1, int(want_it)))
    cfg = RunConfig(ckpt_dir=str(tmp_path), ckpt_every=1, max_iters=cut,
                    method="scan")
    sssp_app.run_push_checkpointed(prog, sh1, cfg, None, "sssp")

    sh2 = build_push_shards(g, p2)
    cfg2 = dataclasses.replace(
        cfg, max_iters=10_000,
        ckpt_every=int(rng.integers(1, 4)),
    )
    st, it, e, _ = sssp_app.run_push_checkpointed(
        prog, sh2, cfg2, None, "sssp"
    )
    assert it == int(want_it)
    np.testing.assert_array_equal(
        sh2.scatter_to_global(np.asarray(st)),
        sh1.scatter_to_global(np.asarray(want_st)),
    )
    assert push.edges_total(e) == push.edges_total(want_e)


@pytest.mark.parametrize("seed,compact,dist", [
    # explicit (compact, distributed) grid — random branch draws with
    # the fixed seed list left both interesting branches uncovered
    (SEEDS[0], False, False),
    (SEEDS[1], True, False),
    (SEEDS[2], False, True),
    (SEEDS[3], True, True),
])
def test_fuzz_delta_vs_chaotic(seed, compact, dist):
    """Random weighted graph and bucket width through the delta driver
    (compact layout on/off x single-device/distributed, per the
    explicit grid): delta-stepping must reproduce the chaotic fixpoint
    bitwise and never traverse MORE edges."""
    from lux_tpu.engine import delta as delta_mod
    from lux_tpu.engine import push
    from lux_tpu.parallel.mesh import make_mesh_for_parts

    rng = np.random.default_rng(seed + 9000)
    g = generate.rmat(int(rng.integers(8, 11)), int(rng.integers(4, 10)),
                      seed=seed, weighted=True,
                      max_weight=int(rng.integers(2, 60)))
    from conftest import hub_vertex

    start = hub_vertex(g)
    P = 8 if dist else int(rng.choice([2, 4]))
    sh = build_push_shards(g, P, compact_gather=compact)
    prog = sssp.WeightedSSSPProgram(nv=sh.spec.nv, start=start)
    st_c, _, e_c = push.run_push(prog, sh, 100000, method="scan")
    width = int(rng.integers(1, 80))
    if dist:
        mesh = make_mesh_for_parts(P)
        st_d, _, e_d = delta_mod.run_push_delta_dist(
            prog, sh, width, mesh, method="scan")
    else:
        st_d, _, e_d = delta_mod.run_push_delta(
            prog, sh, width, method="scan")
    np.testing.assert_array_equal(np.asarray(st_c), np.asarray(st_d))
    assert push.edges_total(e_d) <= push.edges_total(e_c)


@pytest.mark.parametrize("seed", SEEDS[:3])
def test_fuzz_all_pull_exchanges_agree(seed):
    """One random graph through EVERY pull exchange layout — allgather
    (random k residency + random sort-segments relayout), ring,
    reduce_scatter, and the 2-D edge-sharded mesh — all within float
    tolerance of the host oracle, hence of each other."""
    import jax

    from lux_tpu.engine import pull
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.parallel import dist, edge2d, ring, scatter
    from lux_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(seed + 4000)
    scale = int(rng.integers(7, 10))
    ef = int(rng.integers(2, 8))
    P = int(rng.choice([8, 16]))  # k = 1 or 2 on the 8-device mesh
    iters = int(rng.integers(2, 6))
    g = generate.rmat(scale, ef, seed=seed)
    want = pr.pagerank_reference(g, iters)
    mesh = make_mesh(8)

    sh = build_pull_shards(g, P, sort_segments=bool(rng.integers(2)),
                           compact_gather=bool(rng.integers(2)))
    prog = pr.PageRankProgram(nv=sh.spec.nv)
    s0 = pull.init_state(prog, sh.arrays)
    route = None
    if rng.integers(2):  # randomize the routed-expand load too
        from lux_tpu.ops import expand

        route = expand.plan_expand_shards(sh)
    outs = {
        "allgather": sh.scatter_to_global(np.asarray(
            dist.run_pull_fixed_dist(prog, sh.spec, sh.arrays, s0, iters,
                                     mesh, route=route)
        )),
    }
    rs = ring.build_ring_shards(g, P, pull=sh)
    outs["ring"] = rs.scatter_to_global(np.asarray(
        ring.run_pull_fixed_ring(prog, rs, pull.init_state(prog, sh.arrays),
                                 iters, mesh)
    ))
    ss = scatter.build_scatter_shards(g, P, pull=sh)
    outs["scatter"] = ss.scatter_to_global(np.asarray(
        scatter.run_pull_fixed_scatter(
            prog, ss, pull.init_state(prog, sh.arrays), iters, mesh
        )
    ))
    e2 = edge2d.build_edge2d_shards(g, 4, 2)
    p2 = pr.PageRankProgram(nv=e2.spec.nv)
    outs["edge2d"] = e2.scatter_to_global(np.asarray(
        edge2d.run_pull_fixed_2d(
            p2, e2, pull.init_state(p2, e2.arrays), iters,
            edge2d.make_mesh2d(4, 2),
        )
    ))
    for name, got in outs.items():
        np.testing.assert_allclose(got, want, rtol=5e-5, err_msg=name)
