"""luxguard (ISSUE 20): the guarded-by (LUX-G) and resource-lifecycle
(LUX-R) checker families.

Three layers, mirroring how the families are gated in CI:

* inference units — the field→lock guard map, its exemptions (init
  window, ``Condition(self._lock)`` aliasing, the ``*_locked`` caller-
  holds-lock naming convention), and thread-entry reachability
  (including targets bound through loop variables, the ReplicaWorker
  ``start()`` shape);
* the synthetic-positive twins — every known-bad snippet MUST fire
  (``tools/luxcheck.py --twins``; a clean twin means the checker
  rotted), plus the named pre-fix fixtures for the PR 16 socket stall
  and the PR 19 dial-under-lock wedge;
* regressions for the real findings this family's first sweep caught
  (launcher tmpdir reclaim on exception exits, subscribe dispatcher
  leak on hub rebind).
"""
import io
import os
import subprocess
import sys
import textwrap
import threading

import pytest

from lux_tpu.analysis.core import Module, check_module
from lux_tpu.analysis.guards import GuardedByChecker
from lux_tpu.analysis.locks import LockOrderChecker
from lux_tpu.analysis.resources import ResourceLifecycleChecker

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(source, checkers, relpath="lux_tpu/serve/fleet/fixture.py"):
    mod = Module(path=f"<{relpath}>", relpath=relpath,
                 source=textwrap.dedent(source))
    return check_module(mod, checkers)


def _guard(source):
    return [f.code for f in _run(source, (GuardedByChecker(),))]


def _res(source):
    return [f.code for f in _run(source, (ResourceLifecycleChecker(),))]


# ---------------------------------------------------------------------------
# guard-map inference
# ---------------------------------------------------------------------------


def test_g001_guarded_field_read_outside_lock():
    codes = _guard("""\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def start(self):
                threading.Thread(target=self._loop, daemon=True).start()

            def _loop(self):
                with self._lock:
                    self._n += 1

            def peek(self):
                threading.Thread(target=self.peek).start()
                return self._n
        """)
    assert "LUX-G001" in codes


def test_locked_reads_and_unguarded_fields_are_clean():
    codes = _guard("""\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0
                self._free = 0  # never written under a lock: unguarded

            def start(self):
                threading.Thread(target=self._loop, daemon=True).start()

            def _loop(self):
                with self._lock:
                    self._n += 1
                self._free += 1

            def peek(self):
                threading.Thread(target=self.peek).start()
                with self._lock:
                    n = self._n
                return n + self._free
        """)
    assert codes == []


def test_init_window_exemption():
    """``__init__`` writes neither establish a guard nor violate one —
    no second thread can exist before construction finishes."""
    codes = _guard("""\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0  # unlocked write: the init window

            def start(self):
                threading.Thread(target=self._loop, daemon=True).start()

            def _loop(self):
                with self._lock:
                    self._n += 1
        """)
    assert codes == []


def test_condition_alias_is_the_same_guard():
    """``Condition(self._lock)`` shares the underlying lock: holding
    either side guards the field — no G001, no G002 mixed-guard."""
    codes = _guard("""\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._wake = threading.Condition(self._lock)
                self._n = 0

            def start(self):
                threading.Thread(target=self._loop, daemon=True).start()

            def _loop(self):
                with self._wake:
                    self._n += 1
                    self._wake.notify_all()

            def drain(self):
                threading.Thread(target=self.drain).start()
                with self._lock:
                    return self._n
        """)
    assert codes == []


def test_locked_suffix_convention_means_caller_holds():
    """A ``*_locked`` method accesses guarded fields bare — the suffix
    IS the contract that every caller already holds the lock (the
    lexical inference cannot see callers' frames)."""
    codes = _guard("""\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def start(self):
                threading.Thread(target=self._loop, daemon=True).start()

            def _loop(self):
                with self._lock:
                    self._bump_locked()
                    self._n += 1

            def _bump_locked(self):
                self._n += 1
        """)
    assert codes == []


def test_unreachable_method_is_not_flagged():
    """Reachability gates G001: a method no thread entry can reach only
    ever runs on the constructing thread."""
    codes = _guard("""\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def _locked_write(self):
                with self._lock:
                    self._n += 1

            def main_thread_only(self):
                return self._n
        """)
    assert codes == []


def test_loop_variable_thread_target_seeds_reachability():
    """The ReplicaWorker ``start()`` shape: targets bound through a
    loop variable over ``(self._a, self._b)`` tuples still seed the
    reachable set (the spawner's self-method references are taken when
    the target Name cannot be resolved)."""
    codes = _guard("""\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def start(self):
                for fn, name in ((self._loop_a, "a"),
                                 (self._loop_b, "b")):
                    threading.Thread(target=fn, name=name,
                                     daemon=True).start()

            def _loop_a(self):
                with self._lock:
                    self._n += 1

            def _loop_b(self):
                return self._n  # unlocked read on a second thread
        """)
    assert codes == ["LUX-G001"]


def test_g002_mixed_guards():
    codes = _guard("""\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._other = threading.Lock()
                self._n = 0

            def start(self):
                threading.Thread(target=self._loop, daemon=True).start()

            def _loop(self):
                with self._lock:
                    self._n += 1
                with self._other:
                    self._n += 1
        """)
    assert "LUX-G002" in codes


def test_g003_requires_separate_acquisitions():
    """Check-then-act across two ``with`` blocks fires; the same
    decide-and-write inside ONE acquisition is the fix shape and is
    clean."""
    bad = _guard("""\
        import threading

        class Bank:
            def __init__(self):
                self._lock = threading.Lock()
                self._bal = 0

            def start(self):
                threading.Thread(target=self.withdraw).start()

            def withdraw(self, amount=1):
                with self._lock:
                    ok = self._bal >= amount
                if ok:
                    with self._lock:
                        self._bal -= amount
                return ok
        """)
    good = _guard("""\
        import threading

        class Bank:
            def __init__(self):
                self._lock = threading.Lock()
                self._bal = 0

            def start(self):
                threading.Thread(target=self.withdraw).start()

            def withdraw(self, amount=1):
                with self._lock:
                    ok = self._bal >= amount
                    if ok:
                        self._bal -= amount
                return ok
        """)
    assert "LUX-G003" in bad
    assert good == []


# ---------------------------------------------------------------------------
# resource-lifecycle units
# ---------------------------------------------------------------------------


def test_r001_joined_stop_path_with_timeout_is_clean():
    codes = _res("""\
        import threading

        class Svc:
            def start(self):
                self._t = threading.Thread(target=self._loop)
                self._t.start()

            def _loop(self):
                pass

            def stop(self):
                self._t.join(timeout=5.0)
        """)
    assert codes == []


def test_r001_unbounded_join_in_stop_path():
    codes = _res("""\
        import threading

        class Svc:
            def start(self):
                self._t = threading.Thread(target=self._loop)
                self._t.start()

            def _loop(self):
                pass

            def stop(self):
                self._t.join()
        """)
    assert "LUX-R001" in codes


def test_r002_shutdown_before_close_is_clean():
    codes = _res("""\
        import socket

        class Srv:
            def start(self):
                self._srv = socket.socket()
                self._srv.listen(8)

            def _accept_loop(self):
                conn, _ = self._srv.accept()

            def stop(self):
                try:
                    self._srv.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                self._srv.close()
        """)
    assert codes == []


def test_r003_ownership_transfer_is_clean():
    """Returning the tmpdir or handing it to a constructor transfers
    reclaim responsibility — no finding at the mkdtemp site."""
    codes = _res("""\
        import shutil
        import tempfile

        class Handle:
            def __init__(self, tmpdir):
                self.tmpdir = tmpdir

            def close(self):
                shutil.rmtree(self.tmpdir, ignore_errors=True)

        def launch():
            d = tempfile.mkdtemp(prefix="x-")
            return Handle(d)
        """)
    assert codes == []


def test_r004_with_and_class_managed_are_clean():
    codes = _res("""\
        class Sink:
            def __init__(self, path):
                self._f = open(path, "wb")

            def close(self):
                self._f.close()

        def read_all(path):
            with open(path, "rb") as f:
                return f.read()
        """)
    assert codes == []


# ---------------------------------------------------------------------------
# the twins: known-bad snippets MUST fire
# ---------------------------------------------------------------------------


def test_every_twin_fires():
    from lux_tpu.analysis.twins import run_twins

    results = run_twins()
    assert results, "no twins registered"
    silent = [(name, expected, sorted(fired))
              for name, expected, fired, ok in results if not ok]
    assert silent == [], f"twins came back CLEAN: {silent}"


def test_silent_twin_is_reported_as_failure(monkeypatch):
    """The harness itself: a twin whose expected code does not fire
    must come back ok=False (this is the tripwire that makes checker
    rot visible — see luxproto's broken twins)."""
    import lux_tpu.analysis.twins as tw

    monkeypatch.setattr(tw, "ALL_TWINS",
                        (("clean_decoy", "x = 1\n", ("LUX-G001",)),))
    (name, expected, fired, ok), = tw.run_twins()
    assert name == "clean_decoy" and not ok and not fired


def test_pr16_fixture_close_without_shutdown():
    """The PR 16 stall, as a checker finding: ``close()`` alone does
    not wake a thread parked in ``accept()`` on Linux, so the pre-fix
    ``stop()`` burned the full join timeout.  This is the exact shape
    pod.py/controller.py shipped with before this PR's fix."""
    codes = _res("""\
        import socket
        import threading

        class PodWorker:
            def start(self):
                self._srv = socket.socket()
                self._srv.listen(8)
                self._t = threading.Thread(target=self._accept_loop,
                                           daemon=True)
                self._t.start()

            def _accept_loop(self):
                while self._running:
                    conn, _ = self._srv.accept()

            def stop(self):
                self._running = False
                self._srv.close()  # pre-fix: no shutdown() first
                self._t.join(timeout=5.0)
        """)
    assert "LUX-R002" in codes


def test_pr19_fixture_dial_under_lock():
    """The PR 19 wedge (caught then by LUX-L003, pinned here forever):
    dialing the incumbent while holding the probe lock let a hung
    connect() to a dead address wedge ``close()`` behind it."""
    findings = _run("""\
        import threading

        class WireIncumbent:
            def __init__(self):
                self._lock = threading.Lock()
                self._conn = None

            def ping(self):
                from lux_tpu.serve.fleet.wire import Conn

                with self._lock:
                    if self._conn is None:
                        self._conn = Conn.connect("h", 1)  # pre-fix
                    self._conn.send({"op": "lease"})
        """, (LockOrderChecker(),),
        relpath="lux_tpu/serve/autopilot/fixture.py")
    assert "LUX-L003" in [f.code for f in findings]


# ---------------------------------------------------------------------------
# suppression round-trip
# ---------------------------------------------------------------------------


def test_inline_suppression_with_reason_silences():
    codes = _guard("""\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def start(self):
                threading.Thread(target=self._loop, daemon=True).start()

            def _loop(self):
                with self._lock:
                    self._n += 1

            def peek(self):
                threading.Thread(target=self.peek).start()
                # luxcheck: disable=LUX-G001 -- monotonic counter, a stale read is fine here
                return self._n
        """)
    assert codes == []


def test_inline_suppression_without_reason_is_a_finding():
    codes = _guard("""\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def start(self):
                threading.Thread(target=self._loop, daemon=True).start()

            def _loop(self):
                with self._lock:
                    self._n += 1

            def peek(self):
                threading.Thread(target=self.peek).start()
                return self._n  # luxcheck: disable=LUX-G001
        """)
    assert "LUX-X001" in codes


# ---------------------------------------------------------------------------
# the CLI gates, jax-free
# ---------------------------------------------------------------------------


def _run_cli_jax_free(flag, must_print):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    code = (
        "import builtins, runpy, sys\n"
        "real = builtins.__import__\n"
        "def guard(name, *a, **k):\n"
        "    assert not name.startswith('jax'), 'luxcheck imported jax'\n"
        "    return real(name, *a, **k)\n"
        "builtins.__import__ = guard\n"
        "sys.argv = ['luxcheck.py', %r]\n"
        "try:\n"
        "    runpy.run_path(%r, run_name='__main__')\n"
        "except SystemExit as e:\n"
        "    sys.exit(e.code)\n"
        % (flag, os.path.join(REPO, "tools", "luxcheck.py"))
    )
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, cwd=REPO,
                          timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert must_print in proc.stdout, proc.stdout


def test_cli_twins_jax_free():
    _run_cli_jax_free("--twins", "[PASS] luxcheck twins")


def test_cli_check_baselines_jax_free():
    _run_cli_jax_free("--check-baselines", "[PASS] baselines")


# ---------------------------------------------------------------------------
# regressions for the findings the first sweep caught
# ---------------------------------------------------------------------------


class _FakeProc:
    """subprocess.Popen stand-in for launcher teardown paths."""

    def __init__(self, wait_raises=0):
        self.killed = False
        self.terminated = False
        self.returncode = None
        self._wait_raises = wait_raises

    def poll(self):
        return self.returncode

    def kill(self):
        self.killed = True

    def terminate(self):
        self.terminated = True

    def wait(self, timeout=None):
        if self._wait_raises > 0:
            self._wait_raises -= 1
            raise subprocess.TimeoutExpired(cmd="worker", timeout=timeout)
        self.returncode = 0
        return 0


def test_launcher_kill_reclaims_tmpdir_on_wait_timeout(tmp_path):
    """ProcHandle.kill(): an unreapable child (wait() raising
    TimeoutExpired) must not leak the private tmpdir on top of the
    stuck process — the reclaim runs on the exception path too."""
    from lux_tpu.serve.fleet.launcher import ProcHandle

    d = tmp_path / "scratch"
    d.mkdir()
    h = ProcHandle(_FakeProc(wait_raises=1), "w0", 1, 2, str(d), {})
    with pytest.raises(subprocess.TimeoutExpired):
        h.kill()
    assert not d.exists()
    assert h.tmpdir is None


def test_launcher_terminate_reclaims_tmpdir_on_wait_timeout(tmp_path):
    """terminate(): both waits timing out (TERM ignored, then the
    post-KILL reap hanging) still reclaims the tmpdir."""
    from lux_tpu.serve.fleet.launcher import ProcHandle

    d = tmp_path / "scratch"
    d.mkdir()
    proc = _FakeProc(wait_raises=2)
    h = ProcHandle(proc, "w0", 1, 2, str(d), {})
    with pytest.raises(subprocess.TimeoutExpired):
        h.terminate(timeout_s=0.01)
    assert proc.terminated and proc.killed
    assert not d.exists()


def test_launch_malformed_ready_reclaims_tmpdir_and_child(monkeypatch,
                                                          tmp_path):
    """_launch_argv: a READY line missing a required key raises while
    building the ProcHandle — the pre-fix code only reclaimed on
    LaunchError, orphaning both the child and its tmpdir."""
    from lux_tpu.serve.fleet import launcher

    spawned = []

    class _ReadyProc(_FakeProc):
        def __init__(self, *a, **k):
            super().__init__()
            # ready, but no "port": ProcHandle construction raises
            self.stdout = io.StringIO(
                '{"ready": true, "worker_id": "w9", "pid": 7}\n')
            spawned.append(self)

    made = []
    real_mkdtemp = launcher.tempfile.mkdtemp

    def _mkdtemp(prefix=""):
        d = real_mkdtemp(prefix=prefix, dir=str(tmp_path))
        made.append(d)
        return d

    monkeypatch.setattr(launcher.subprocess, "Popen", _ReadyProc)
    monkeypatch.setattr(launcher.tempfile, "mkdtemp", _mkdtemp)
    with pytest.raises(KeyError):
        launcher.launch("lux_tpu.serve.fleet.pod", [],
                        ready_timeout_s=5.0)
    assert made and not os.path.exists(made[0])
    assert spawned and spawned[0].killed


def test_rebind_closes_displaced_hub():
    """SubscriptionHub.rebind: adopting a hub onto a successor that
    already built its OWN hub must close the displaced one — pre-fix,
    its dispatcher thread idled forever and its subscribers hung with
    nothing left to notify them."""
    from lux_tpu.serve.autopilot.subscribe import (
        SubscriptionClosed, SubscriptionHub,
    )

    class _Ctl:
        def __init__(self):
            self._lock = threading.Lock()
            self._sub_hub = None

        def generation(self):
            return 0

        def _pilot_count(self, key, n=1):
            pass

    a, b = _Ctl(), _Ctl()
    hub_b = SubscriptionHub(b)
    b._sub_hub = hub_b
    sub = hub_b.subscribe("pr")  # starts hub_b's dispatcher thread
    assert hub_b._thread is not None and hub_b._thread.is_alive()

    hub_a = SubscriptionHub(a)
    a._sub_hub = hub_a
    hub_a.rebind(b)

    assert b._sub_hub is hub_a
    hub_b._thread.join(timeout=5.0)
    assert not hub_b._thread.is_alive()
    with pytest.raises(SubscriptionClosed):
        sub.get(timeout_s=1.0)
