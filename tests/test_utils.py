"""Aux subsystems: config parsing, preflight estimates, checkpointing."""
import numpy as np

from lux_tpu.graph import generate
from lux_tpu.graph.push_shards import build_push_shards
from lux_tpu.graph.shards import build_pull_shards
from lux_tpu.utils import checkpoint, preflight
from lux_tpu.utils.config import parse_args


def test_parse_args_reference_flags():
    cfg = parse_args(
        ["-file", "g.lux", "-ng", "4", "-ni", "20", "-verbose", "-check",
         "-start", "7"],
        sssp=True,
    )
    assert cfg.file == "g.lux"
    assert cfg.num_parts == 4
    assert cfg.num_iters == 20
    assert cfg.start == 7
    assert cfg.verbose and cfg.check


def test_preflight_counts_real_bytes():
    g = generate.rmat(10, 8, seed=70)
    sh = build_pull_shards(g, 2)
    est = preflight.estimate_pull(sh.spec)
    # the estimate must at least cover the actual shard array bytes
    actual = sum(a.nbytes for a in sh.arrays) / sh.spec.num_parts
    assert est.shard_bytes >= 0.9 * actual
    assert est.total_bytes > est.shard_bytes
    psh = build_push_shards(g, 2)
    pest = preflight.estimate_push(psh.spec, psh.pspec)
    assert pest.total_bytes > est.total_bytes


def test_checkpoint_roundtrip(tmp_path):
    state = np.random.default_rng(0).random((4, 128)).astype(np.float32)
    p = str(tmp_path / "ckpt_5.npz")
    checkpoint.save(p, state, 5, {"app": "pagerank"})
    s2, it, meta = checkpoint.load(p)
    np.testing.assert_array_equal(s2, state)
    assert it == 5 and meta["app"] == "pagerank"


def test_checkpoint_latest(tmp_path):
    for it in [3, 10, 7]:
        checkpoint.save(
            str(tmp_path / f"ckpt_{it}.npz"),
            np.zeros((1, 8), np.float32), it, {},
        )
    assert checkpoint.latest(str(tmp_path)).endswith("ckpt_10.npz")
    assert checkpoint.latest(str(tmp_path / "missing")) is None


def test_pagerank_app_checkpoint_resume(tmp_path):
    """End-to-end: run 6 iters with checkpointing, resume from 4, and the
    result must equal an uninterrupted run."""
    from lux_tpu.apps import pagerank as app
    from lux_tpu.models.pagerank import pagerank as pr_run

    g_args = ["--rmat-scale", "8", "--rmat-ef", "4", "--seed", "3"]
    ck = str(tmp_path / "ck")
    rc = app.main(g_args + ["-ni", "6", "--ckpt-dir", ck, "--ckpt-every", "2"])
    assert rc == 0
    assert checkpoint.latest(ck).endswith("ckpt_6.npz")
    # checkpoints store the GLOBAL (nv,) state (elastic layout)
    state, it, _ = checkpoint.load(checkpoint.latest(ck))
    from lux_tpu.graph import generate as gen

    g = gen.rmat(8, 4, seed=3)
    want = pr_run(g, num_iters=6)
    assert state.shape == (g.nv,)
    np.testing.assert_allclose(state, want, rtol=1e-6)

def test_checkpoint_elastic_meta_and_bf16(tmp_path):
    """save_iteration stores the global layout + app/nv/dtype meta;
    load_resume validates it and round-trips bf16 through the widened
    on-disk f32."""
    import ml_dtypes

    d = str(tmp_path / "ck")
    g16 = np.arange(64, dtype=np.float32).astype(ml_dtypes.bfloat16)
    checkpoint.save_iteration(d, 3, g16, "pagerank")
    state, it, prev = checkpoint.load_resume(d, "pagerank", 64)
    assert it == 3 and prev.endswith("ckpt_3.npz")
    assert state.dtype == ml_dtypes.bfloat16
    np.testing.assert_array_equal(state, g16)
    # wrong app / wrong nv refuse
    import pytest

    with pytest.raises(SystemExit):
        checkpoint.load_resume(d, "colfilter", 64)
    with pytest.raises(SystemExit):
        checkpoint.load_resume(d, "pagerank", 128)
    # empty dir resumes from scratch
    assert checkpoint.load_resume(str(tmp_path / "none"), "x", 1)[0] is None
    # legacy (layout-less) checkpoints are refused, not misread
    import os

    os.makedirs(str(tmp_path / "ck2"))
    checkpoint.save(
        str(tmp_path / "ck2" / "ckpt_1.npz"), g16.astype(np.float32), 1, {}
    )
    with pytest.raises(SystemExit):
        checkpoint.load_resume(str(tmp_path / "ck2"), "pagerank", 64)


def test_residency_single_device_counts_all_parts():
    """ADVICE r3: a non-distributed -ng N run holds all N parts on the one
    device — the preflight residency factor must be N, not 1 (otherwise
    estimate_exchange underestimates by ~N x and could pass a run that
    OOMs on a real chip)."""
    from lux_tpu.apps.common import _residency
    from lux_tpu.utils.config import RunConfig

    assert _residency(RunConfig(num_parts=4)) == 4
    assert _residency(RunConfig(num_parts=1)) == 1
    # edge2d's estimate already counts the whole footprint: stays 1
    assert _residency(RunConfig(num_parts=4, edge_shards=2)) == 1
    # distributed on the 8-device test mesh: 16 parts -> k = 2
    assert _residency(RunConfig(num_parts=16, distributed=True)) == 2
    assert _residency(RunConfig(num_parts=8, distributed=True)) == 1


def test_preflight_ring_k_resident_exact():
    """VERDICT r3 weak #6: pin the k-resident ring estimate against the
    EXACT per-device array bytes.  The ring driver with k = P/D resident
    parts per device holds k parts' bucket arrays + vertex views and
    circulates (k, V)-blocks (4 state-block terms: local, in-flight,
    accumulator, new — parallel/ring.py run()).  scale_residency must
    cover that footprint, with zero gathered term (the ring's point)."""
    from lux_tpu.graph import generate
    from lux_tpu.parallel.ring import build_ring_shards

    g = generate.rmat(10, 8, seed=71)
    P, k = 4, 2  # e.g. 4 parts on 2 devices
    rs = build_ring_shards(g, P)
    est = preflight.scale_residency(
        preflight.estimate_ring(rs.spec, rs.e_bucket_pad), k
    )
    V, B = rs.spec.nv_pad, rs.e_bucket_pad
    # exact per-part bytes, from the shapes the driver actually places:
    per_part_buckets = sum(
        a.nbytes // a.shape[0] for a in rs.rarrays
    )  # (R, P, B) arrays -> P*B*(4+4+1+4) bytes per part
    assert per_part_buckets == P * B * 13
    per_part_view = V * (1 + 4)  # vtx_mask uint8 + degree int32
    per_part_state = 4 * V * 4  # 4 f32 (V,) blocks per resident part
    actual = k * (per_part_buckets + per_part_view + per_part_state)
    assert est.gathered_bytes == 0
    assert est.total_bytes >= actual  # no underestimate at k > 1
    assert est.total_bytes <= 1.05 * actual  # and stays tight


def test_iterstats_and_report(capsys):
    """Metrics/logging subsystem (SURVEY.md §5): the verbose line formats
    (reference parity: activeNodes/loadTime/compTime/updateTime,
    sssp_gpu.cu:513-518), phase totals, and the GTEPS derivation."""
    from lux_tpu.utils.timing import IterStats, report_elapsed

    st = IterStats(verbose=True)
    st.record(0, 42, 0.002)
    st.record_phases(1, 7, 0.001, 0.003, 0.0005)
    out = capsys.readouterr().out
    assert "activeNodes(42) time(2.000 ms)" in out
    assert "loadTime(1.000 ms)" in out and "updateTime(0.500 ms)" in out
    assert st.total_active == 49
    lt, ct, ut = st.phase_totals()
    assert (lt, ct, ut) == (0.001, 0.003, 0.0005)
    # fixed-iteration GTEPS: iters * ne / s / 1e9
    g = report_elapsed(2.0, 1_000_000, 10)
    assert abs(g - 0.005) < 1e-12
    # frontier apps: traversed-edge count wins over iters * ne
    g2 = report_elapsed(1.0, 1_000_000, 10, traversed=3_000_000)
    assert abs(g2 - 0.003) < 1e-12
    out = capsys.readouterr().out
    assert "ELAPSED TIME" in out and "GTEPS" in out


def test_timer_fences_device_values(capsys):
    import jax.numpy as jnp

    from lux_tpu.utils.timing import Timer

    t = Timer()
    x = jnp.arange(8) * 2
    dt = t.stop(x)
    assert dt >= 0.0 and t.elapsed == dt
