"""Roofline traffic/FLOP model sanity (VERDICT r3 weak #5)."""
import pytest

from lux_tpu.utils import roofline


def test_pull_iter_model_pagerank_shape():
    """rmat18/ef16-like: per-edge bytes dominated by the gather + reduce;
    the model is linear in ne and counts the scan floor correctly."""
    ne, nv = 1 << 22, 1 << 18
    m = roofline.pull_iter_model(ne, nv, "scan")
    # per edge: src_pos 4 + state 4 (no dst gather for pagerank) +
    # scan 2 passes 8 + flag 1 = 17; per vertex: 2*4 + degree 4 = 12
    assert m.bytes_moved == ne * 17 + nv * 12
    assert m.flops == ne + 3 * nv
    assert m.device_flops == m.flops  # element-wise reduce: no redundancy
    m2 = roofline.pull_iter_model(2 * ne, nv, "scan")
    assert m2.bytes_moved - m.bytes_moved == ne * 17


def test_pull_iter_model_methods_ordering():
    """VMEM-resident pallas moves the least HBM bytes but issues the most
    device FLOPs (the one-hot redundancy, ops/pallas_spmv.py); scatter
    moves the most bytes; useful FLOPs identical across methods."""
    ne, nv = 1 << 20, 1 << 16
    ms = {
        k: roofline.pull_iter_model(ne, nv, k)
        for k in ("scan", "scatter", "cumsum", "mxsum", "pallas")
    }
    assert ms["pallas"].bytes_moved < ms["mxsum"].bytes_moved
    assert ms["mxsum"].bytes_moved <= ms["cumsum"].bytes_moved
    assert ms["scan"].bytes_moved < ms["scatter"].bytes_moved
    assert len({m.flops for m in ms.values()}) == 1
    assert ms["pallas"].device_flops == ne * 2 * roofline.PALLAS_V_BLK + (
        ms["scan"].device_flops - ne
    )
    assert ms["mxsum"].device_flops > ms["scan"].device_flops


def test_pull_iter_model_cf_width():
    """CF: K-wide state, weighted, dst gather; bytes scale ~K."""
    ne, nv, K = 1 << 20, 1 << 16, 20
    m1 = roofline.pull_iter_model(ne, nv, "scan", width=1,
                                  weighted=True, needs_dst=True)
    mk = roofline.pull_iter_model(ne, nv, "scan", width=K,
                                  weighted=True, needs_dst=True)
    assert mk.bytes_moved > 10 * m1.bytes_moved  # ~K x the state traffic
    assert mk.flops == ne * 4 * K + nv * 3 * K


def test_push_run_model_dense_sparse_split():
    """The run model matches the engine's exact accounting: dense rounds
    walk every edge at pull-iteration cost, the sparse remainder pays the
    per-frontier-edge scatter cost."""
    ne, nv = 1 << 20, 1 << 16
    dense_only = roofline.push_run_model(ne, nv, 3 * ne, 3, "scan")
    per_dense = roofline.pull_iter_model(ne, nv, "scan", 4, 1, False, False, 1)
    assert dense_only.bytes_moved == 3 * per_dense.bytes_moved + 3 * nv * 5
    mixed = roofline.push_run_model(ne, nv, 3 * ne + 1000, 3, "scan")
    assert (
        mixed.bytes_moved - dense_only.bytes_moved
        == 1000 * roofline.push_sparse_edge_model().bytes_moved + nv * 5
    )
    # traversed < dense_rounds*ne cannot go negative
    assert roofline.push_run_model(ne, nv, ne, 2, "scan").bytes_moved > 0


def test_summarize_fields_and_roof_frac(monkeypatch):
    m = roofline.TrafficModel(bytes_moved=10**9, flops=10**8,
                              device_flops=10**8)
    out = roofline.summarize(m, 0.5, 10**7)
    assert out["achieved_GBps"] == 2.0
    assert out["bytes_per_edge"] == 100.0
    assert "frac_bw_roof" not in out
    monkeypatch.setenv("LUX_PEAK_GBPS", "819")
    out2 = roofline.summarize(m, 0.5, 10**7)
    assert out2["frac_bw_roof"] == round(2.0 / 819, 4)


def test_unknown_method_raises():
    with pytest.raises(ValueError):
        roofline.pull_iter_model(10, 10, "nope")
