"""PlacementTree + snapshot streaming units (ISSUE 19).

The tree is the ONE partition->host map shared by the dist engines and
the fleet wire layer; these tests pin (a) the balanced split against the
historical ``multihost.local_part_range`` arithmetic for every small
(parts x hosts) shape, (b) wire roundtrip + construction validation so a
tree received over TCP cannot describe gapped/overlapping ownership,
(c) the two halo collective legs against plain numpy on the virtual
8-device mesh, and (d) the stream.py reassembly contract (ordering,
overflow, digest — errors latch, never a silent half-file).
"""
import hashlib
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lux_tpu.parallel.placement import (
    HostSlice,
    PlacementTree,
    halo_all_gather,
    halo_reduce_scatter,
    local_tree,
)
from lux_tpu.serve.fleet.stream import (
    FRAME_SLACK,
    MIN_CHUNK,
    StreamSink,
    StreamTable,
    file_chunks,
    negotiate_chunk_bytes,
    stream_file,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------- tree


def _legacy_local_part_range(num_parts, num_hosts, h):
    """The arithmetic multihost.local_part_range always used — now
    defined once in PlacementTree.build; this copy is the oracle."""
    base, extra = divmod(num_parts, num_hosts)
    lo = h * base + min(h, extra)
    hi = lo + base + (1 if h < extra else 0)
    return lo, hi


def test_build_matches_historical_split_exhaustive():
    for P in range(1, 33):
        for H in range(1, 9):
            tree = PlacementTree.build(P, H)
            assert tree.num_hosts == H and tree.num_parts == P
            covered = []
            for h in range(H):
                lo, hi = _legacy_local_part_range(P, H, h)
                s = tree.slice_of(h)
                assert (s.lo, s.hi) == (lo, hi), (P, H, h)
                assert list(tree.parts_of(h)) == list(range(lo, hi))
                covered.extend(tree.parts_of(h))
            assert covered == list(range(P)), (P, H)
            for p in range(P):
                h = tree.host_of(p)
                assert p in tree.parts_of(h), (P, H, p, h)


def test_build_small_graph_on_big_fleet_leaves_empty_slices():
    tree = PlacementTree.build(2, 5)
    assert [s.num_parts for s in tree.slices] == [1, 1, 0, 0, 0]
    assert tree.host_of(1) == 1


def test_single_host_and_local_tree():
    tree = PlacementTree.single_host(8, devices=8)
    assert tree.num_hosts == 1
    assert tree.parts_of(0) == range(0, 8)
    # no jax.distributed in the suite: the runtime tree IS single-host
    lt = local_tree(8)
    assert lt.num_hosts == jax.process_count() == 1
    assert lt.slices[0].devices == jax.local_device_count()


def test_wire_roundtrip_through_json():
    tree = PlacementTree.build(13, 4, devices_per_host=8)
    wired = json.loads(json.dumps(tree.to_wire()))
    assert PlacementTree.from_wire(wired) == tree
    wired["version"] = 99
    with pytest.raises(ValueError, match="wire version"):
        PlacementTree.from_wire(wired)


def test_construction_rejects_bad_trees():
    with pytest.raises(ValueError, match="bad part range"):
        HostSlice(host=0, lo=3, hi=1)
    with pytest.raises(ValueError, match="num_parts"):
        PlacementTree.build(0, 1)
    with pytest.raises(ValueError, match="num_hosts"):
        PlacementTree.build(4, 0)
    with pytest.raises(ValueError, match="at least one host"):
        PlacementTree(num_parts=4, slices=())
    # gap: [0,2) then [3,4)
    with pytest.raises(ValueError, match="contiguously"):
        PlacementTree(num_parts=4, slices=(
            HostSlice(0, 0, 2), HostSlice(1, 3, 4)))
    # overlap: [0,2) then [1,4)
    with pytest.raises(ValueError, match="contiguously"):
        PlacementTree(num_parts=4, slices=(
            HostSlice(0, 0, 2), HostSlice(1, 1, 4)))
    # under-coverage
    with pytest.raises(ValueError, match="num_parts=4"):
        PlacementTree(num_parts=4, slices=(HostSlice(0, 0, 3),))
    # non-dense host ids
    with pytest.raises(ValueError, match="dense"):
        PlacementTree(num_parts=4, slices=(
            HostSlice(1, 0, 4),))
    with pytest.raises(IndexError):
        PlacementTree.build(4, 2).host_of(4)


def test_placement_and_stream_are_jax_free():
    """The fleet side holds and ships trees without an accelerator
    runtime: placement/stream/launcher import under the bare-package
    stub with a jax import tripwire armed."""
    code = (
        "import builtins, sys\n"
        "sys.path.insert(0, %r)\n"
        "real = builtins.__import__\n"
        "def guard(name, *a, **k):\n"
        "    assert not name.startswith('jax'), name\n"
        "    return real(name, *a, **k)\n"
        "builtins.__import__ = guard\n"
        "import _jaxfree\n"
        "pl = _jaxfree.load('lux_tpu.parallel.placement')\n"
        "st = _jaxfree.load('lux_tpu.serve.fleet.stream')\n"
        "_jaxfree.load('lux_tpu.serve.fleet.launcher')\n"
        "t = pl.PlacementTree.build(13, 4)\n"
        "assert pl.PlacementTree.from_wire(t.to_wire()) == t\n"
        "assert st.negotiate_chunk_bytes(2**24, None) > 0\n"
        "print('JAXFREE-OK')\n" % os.path.join(REPO, "tools")
    )
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "JAXFREE-OK" in proc.stdout


# ---------------------------------------------------------------- halo


def _parts_mesh(n):
    from lux_tpu.parallel.mesh import make_mesh_for_parts

    return make_mesh_for_parts(n)


@pytest.mark.parametrize("P", [8, 16])  # k = 1 and k = 2 per device
def test_halo_all_gather_flattens_in_global_part_order(P):
    from jax.sharding import PartitionSpec as Ps

    from lux_tpu.parallel.mesh import PARTS_AXIS, shard_stacked

    mesh = _parts_mesh(P)
    V, F = 4, 3
    x = jnp.arange(P * V * F, dtype=jnp.float32).reshape(P, V, F)

    run = jax.jit(jax.shard_map(
        halo_all_gather, mesh=mesh,
        in_specs=(Ps(PARTS_AXIS),), out_specs=Ps()))
    out = np.asarray(run(shard_stacked(mesh, x)))
    np.testing.assert_array_equal(out, np.asarray(x).reshape(P * V, F))


@pytest.mark.parametrize("k", [1, 2])
def test_halo_reduce_scatter_sums_per_destination(k):
    """Each device contributes a full (P, V) partials matrix; device d
    must come back with the summed columns of ITS k resident parts —
    i.e. the global result is x.sum(over contributors) in part order."""
    from jax.sharding import PartitionSpec as Ps

    from lux_tpu.parallel.mesh import PARTS_AXIS, shard_stacked

    D = 8
    P, V = D * k, 4
    mesh = _parts_mesh(P)
    rng = np.random.default_rng(7)
    # axis 0 = contributing device (sharded), then that device's (P, V)
    x = jnp.asarray(rng.integers(0, 100, (D, P, V)).astype(np.float32))

    run = jax.jit(jax.shard_map(
        lambda blk: halo_reduce_scatter(blk[0], k),
        mesh=mesh, in_specs=(Ps(PARTS_AXIS),),
        out_specs=Ps(PARTS_AXIS)))
    out = np.asarray(run(shard_stacked(mesh, x)))
    np.testing.assert_array_equal(out, np.asarray(x).sum(axis=0))


# -------------------------------------------------------------- stream


def test_negotiate_chunk_bytes():
    mb = 1024 * 1024
    assert negotiate_chunk_bytes(64 * mb, None) == 64 * mb - FRAME_SLACK
    assert negotiate_chunk_bytes(64 * mb, 8 * mb) == 8 * mb - FRAME_SLACK
    assert negotiate_chunk_bytes(8 * mb, 64 * mb) == 8 * mb - FRAME_SLACK
    # a pathological bound cannot degrade below the chunk floor
    assert negotiate_chunk_bytes(1024, 512) == MIN_CHUNK


def _spool(tmp_path, nbytes, seed=0):
    data = np.random.default_rng(seed).integers(
        0, 256, nbytes).astype(np.uint8).tobytes()
    path = tmp_path / "snap.lux"
    path.write_bytes(data)
    return str(path), data


def test_file_chunks_and_sink_roundtrip(tmp_path):
    path, data = _spool(tmp_path, 700 * 1024)
    chunk = 256 * 1024
    nbytes, nchunks, it = file_chunks(path, chunk)
    assert nbytes == len(data) and nchunks == 3
    sink = StreamSink("t0", str(tmp_path), nbytes, nchunks)
    for seq, arr in enumerate(it):
        sink.add(seq, arr)
    out = sink.finalize(hashlib.sha256(data).hexdigest())
    assert open(out, "rb").read() == data


def test_sink_errors_latch_and_surface_at_finalize(tmp_path):
    path, data = _spool(tmp_path, 300 * 1024, seed=1)
    sha = hashlib.sha256(data).hexdigest()
    chunks = list(file_chunks(path, 128 * 1024)[2])

    # reordered frames
    sink = StreamSink("t1", str(tmp_path), len(data), len(chunks))
    sink.add(1, chunks[1])
    assert "out of order" in sink.error
    sink.add(0, chunks[0])  # latched: later good frames don't unlatch
    with pytest.raises(ValueError, match="out of order"):
        sink.finalize(sha)
    sink.abort()

    # overflow past the announced byte count
    sink = StreamSink("t2", str(tmp_path), 10, len(chunks))
    sink.add(0, chunks[0])
    with pytest.raises(ValueError, match="overflow"):
        sink.finalize(sha)
    sink.abort()

    # digest mismatch on an otherwise perfect stream
    sink = StreamSink("t3", str(tmp_path), len(data), len(chunks))
    for seq, arr in enumerate(chunks):
        sink.add(seq, arr)
    with pytest.raises(ValueError, match="digest mismatch"):
        sink.finalize("0" * 64)

    # truncated stream (a chunk never arrived)
    sink = StreamSink("t4", str(tmp_path), len(data), len(chunks))
    sink.add(0, chunks[0])
    with pytest.raises(ValueError, match="incomplete"):
        sink.finalize(sha)
    sink.abort()

    # non-uint8 payload
    sink = StreamSink("t5", str(tmp_path), len(data), len(chunks))
    sink.add(0, np.zeros(4, np.float32))
    assert "no uint8 payload" in sink.error
    sink.abort()


def test_stream_table_supersede_and_unknown_token():
    tbl = StreamTable(prefix="lux-test-stream-")
    try:
        first = tbl.begin("tok", 8, 1)
        second = tbl.begin("tok", 8, 1)  # restart supersedes
        # the superseded sink was aborted (closed); the restarted stream
        # owns the token's spool file from byte 0
        assert first._f.closed
        tbl.chunk("nope", 0, np.zeros(4, np.uint8))  # dropped, no raise
        tbl.chunk("tok", 0, np.arange(8, dtype=np.uint8))
        assert tbl.pop("tok") is second and second.received == 8
        assert tbl.pop("tok") is None
    finally:
        tbl.clear()
    assert tbl._dir is None


def test_stream_file_end_to_end(tmp_path):
    """Sender (stream_file) against a receiver StreamTable wired through
    a fake conn — the exact op sequence the pod/fleet receivers run."""
    path, data = _spool(tmp_path, 600 * 1024, seed=2)
    tbl = StreamTable(prefix="lux-test-stream-")

    class FakeConn:
        def send(self, msg, arr=None):
            assert msg["op"] == "stream_chunk"
            tbl.chunk(msg["token"], msg["seq"], arr)

    def rpc(msg):
        assert msg["op"] == "stream_begin"
        tbl.begin(msg["token"], msg["nbytes"], msg["chunks"])
        return {"ok": True}

    try:
        meta = stream_file(FakeConn(), path, "tok", 256 * 1024, rpc=rpc)
        assert meta["nbytes"] == len(data) and meta["chunks"] == 3
        assert meta["sha256"] == hashlib.sha256(data).hexdigest()
        sink = tbl.pop("tok")
        out = sink.finalize(meta["sha256"])
        assert open(out, "rb").read() == data
    finally:
        tbl.clear()
