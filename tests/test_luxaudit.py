"""luxaudit (lux_tpu.analysis.ir): each LUX-J family catches its seeded
broken fixture AND passes its clean twin, the audited repo engines are
clean (the chip-day step -3b gate in tier-1 form), and the baseline
machinery round-trips — mirroring tests/test_luxcheck.py for the layer
below the AST."""
import dataclasses
import os
import subprocess
import sys
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lux_tpu.analysis.ir import aot, donation, hbm, retrace, run_audit, vmem
from lux_tpu.analysis.ir.collectives import check_shard_map_bodies
from tests.conftest import forced_cpu_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _codes(findings):
    return [f.code for f in findings]


# ---------------------------------------------------------------------------
# LUX-J1 retrace stability
# ---------------------------------------------------------------------------


def _unrolled(n):
    """A config-dependent Python unroll — the retrace bug class: every
    config value is a new program."""

    @jax.jit
    def f(x, idx):
        for _ in range(n):
            x = jnp.take(x, idx) * 2
        return x

    return f


def test_j101_unroll_across_variants_fails():
    x = jnp.arange(8.0)
    idx = jnp.arange(8, dtype=jnp.int32)
    variants = [_unrolled(2).trace(x, idx), _unrolled(3).trace(x, idx)]
    fs = retrace.check_variants(variants, "lux_tpu/engine/pull.py",
                                "fixture/unroll")
    assert "LUX-J101" in _codes(fs)
    # the coarse (shape-varying-family) signature catches it too: the
    # unroll duplicates GATHERS, not just elementwise ops
    fs = retrace.check_variants(variants, "lux_tpu/engine/pull.py",
                                "fixture/unroll", strict=False)
    assert "LUX-J101" in _codes(fs)


def test_j101_clean_twin_fori_loop():
    def make(n):
        @jax.jit
        def f(x, idx):
            return jax.lax.fori_loop(
                0, n, lambda _, s: jnp.take(s, idx) * 2, x)

        return f

    x = jnp.arange(8.0)
    idx = jnp.arange(8, dtype=jnp.int32)
    fs = retrace.check_variants(
        [make(2).trace(x, idx), make(3).trace(x, idx)],
        "lux_tpu/engine/pull.py", "fixture/fori")
    assert fs == []


def test_j101_coarse_tolerates_broadcast_idioms():
    """The Q-bucket contract: a degenerate Q=1 broadcast may trace
    differently (slice vs broadcast_in_dim) without being drift."""

    @jax.jit
    def f(x, q):
        return x[:, None] * q[None, :]

    a = f.trace(jnp.arange(8.0), jnp.arange(1.0))
    b = f.trace(jnp.arange(8.0), jnp.arange(4.0))
    assert retrace.check_variants([a, b], "p", "fixture/q",
                                  strict=False) == []


def test_j102_unhashable_static():
    fs = retrace.check_statics([("ok",), [1, 2]], "p", "fixture/statics")
    assert _codes(fs) == ["LUX-J102"]


def test_j103_dynamic_recall():
    @jax.jit
    def f(x):
        return x * 2

    # clean: same shape, different values — one compile
    fs = retrace.check_dynamic_recall(
        f, lambda: f(jnp.arange(4.0)), lambda: f(jnp.ones(4)),
        "p", "fixture/dyn")
    assert fs == []
    # broken: the knob leaks into the shape — a recompile per value
    fs = retrace.check_dynamic_recall(
        f, lambda: f(jnp.arange(4.0)), lambda: f(jnp.arange(5.0)),
        "p", "fixture/dyn")
    assert _codes(fs) == ["LUX-J103"]


def test_j101_same_config_double_trace_stable():
    fs = retrace.trace_twice_stable(
        lambda: _unrolled(2).trace(jnp.arange(8.0),
                                   jnp.arange(8, dtype=jnp.int32)),
        "p", "fixture/stable", statics=((1, 2),))
    assert fs == []


# ---------------------------------------------------------------------------
# LUX-J2 donation
# ---------------------------------------------------------------------------


def test_j201_dropped_donation_fails():
    """x is donated AND read, but no output matches its shape: XLA
    silently drops the donation — the exact bug class."""

    @partial(jax.jit, donate_argnums=0)
    def f(x, y):
        return jnp.sum(x) + y

    x, y = jnp.arange(8.0), jnp.arange(4.0)
    # jax itself only WARNS about the drop (the failure mode: a warning
    # scrolled past in a log); the checker turns it into a finding
    with pytest.warns(UserWarning, match="donated buffers were not"):
        fs = donation.check_donation(f.trace(x, y), (x, y), (0,),
                                     "p", "fixture/dropped")
    assert _codes(fs) == ["LUX-J201"]


def test_j201_clean_twin_aliases_land():
    @partial(jax.jit, donate_argnums=0)
    def f(x, y):
        return x * 2 + jnp.sum(y)

    x, y = jnp.arange(8.0), jnp.arange(4.0)
    fs = donation.check_donation(f.trace(x, y), (x, y), (0,),
                                 "p", "fixture/aliased")
    assert fs == []


def test_j201_pruned_unused_leaf_exempt():
    """A donated leaf DCE'd out of the lowered module holds no runtime
    buffer: nothing to alias, nothing resident — must not fire."""

    @partial(jax.jit, donate_argnums=0)
    def f(c, y):
        state, unused = c
        del unused  # never read: DCE'd out of the lowered main
        return state * 2, y + 1

    c = (jnp.arange(8.0), jnp.arange(3.0))
    y = jnp.arange(4.0)
    fs = donation.check_donation(f.trace(c, y), (c, y), (0,),
                                 "p", "fixture/pruned")
    assert fs == []


def test_j2_pull_and_push_aliases_land_from_lowered_hlo():
    """The acceptance claim: donation aliases asserted from lowered HLO
    for BOTH the pull and push engine paths, on CPU."""
    from lux_tpu.analysis.ir import targets

    assert targets._donation_pull_fixed() == []
    assert targets._donation_push_chunk() == []
    assert targets._donation_push_step() == []
    assert targets._donation_serve("sssp") == []


# ---------------------------------------------------------------------------
# LUX-J3 collective order
# ---------------------------------------------------------------------------


def _mesh2():
    return jax.sharding.Mesh(np.array(jax.devices()[:2]), ("parts",))


def test_j301_mismatched_psum_arm_fails():
    """A cond whose arms disagree on collectives under a LOCAL (per-
    device) predicate: participants can take different arms and the
    psum deadlocks the mesh."""
    from jax.sharding import PartitionSpec as P

    mesh = _mesh2()

    @jax.jit
    @partial(jax.shard_map, mesh=mesh, in_specs=(P("parts"),),
             out_specs=P("parts"))
    def f(x):
        return jax.lax.cond(
            jnp.sum(x) > 0,  # local value: not mesh-agreed
            lambda: x + jax.lax.psum(jnp.sum(x), "parts"),
            lambda: x * 2,
        )

    fs = check_shard_map_bodies(
        aot.traced_jaxpr(f.trace(jnp.arange(8.0))), "p", "fixture/cond")
    assert "LUX-J301" in _codes(fs)


def test_j301_clean_twin_psum_predicate():
    from jax.sharding import PartitionSpec as P

    mesh = _mesh2()

    @jax.jit
    @partial(jax.shard_map, mesh=mesh, in_specs=(P("parts"),),
             out_specs=P("parts"))
    def f(x):
        return jax.lax.cond(
            jax.lax.psum(jnp.sum(x), "parts") > 0,  # mesh-agreed
            lambda: x + jax.lax.psum(jnp.sum(x), "parts"),
            lambda: x * 2,
        )

    fs = check_shard_map_bodies(
        aot.traced_jaxpr(f.trace(jnp.arange(8.0))), "p", "fixture/cond")
    assert fs == []


def test_j302_local_while_predicate_fails():
    from jax.sharding import PartitionSpec as P

    mesh = _mesh2()

    @jax.jit
    @partial(jax.shard_map, mesh=mesh, in_specs=(P("parts"),),
             out_specs=P("parts"))
    def f(x):
        def body(c):
            s, it = c
            return s + jax.lax.psum(jnp.sum(s), "parts"), it + 1

        def cond(c):
            s, it = c
            # stop depends on the LOCAL shard: devices disagree on the
            # trip count, one exits while the rest block in the psum
            return (jnp.sum(s) < 100.0) & (it < 5)

        return jax.lax.while_loop(cond, body, (x, jnp.int32(0)))[0]

    fs = check_shard_map_bodies(
        aot.traced_jaxpr(f.trace(jnp.arange(4.0))), "p", "fixture/while")
    assert "LUX-J302" in _codes(fs)


def test_j302_clean_twin_psum_carried_predicate():
    """The push engine's shape: the stop predicate reads a psum'd carry
    slot — agreed through the while fixpoint."""
    from jax.sharding import PartitionSpec as P

    mesh = _mesh2()

    @jax.jit
    @partial(jax.shard_map, mesh=mesh, in_specs=(P("parts"), P()),
             out_specs=P("parts"))
    def f(x, stop):
        def body(c):
            s, it, _ = c
            new = s + jax.lax.all_gather(s, "parts", tiled=True).sum()
            active = jax.lax.psum(
                (jnp.sum(new - s) > 0).astype(jnp.int32), "parts")
            return new, it + 1, active

        def cond(c):
            _, it, active = c
            return (active > 0) & (it < stop)

        return jax.lax.while_loop(
            cond, body, (x, jnp.int32(0), jnp.int32(1)))[0]

    fs = check_shard_map_bodies(
        aot.traced_jaxpr(f.trace(jnp.arange(4.0), jnp.int32(3))),
        "p", "fixture/while-clean")
    assert fs == []


def test_j3_real_push_engines_clean():
    """The direction-optimized engines' cond/while predicates are
    provably mesh-agreed — the property five rounds of comments assert."""
    from lux_tpu.analysis.ir import targets

    assert targets._collective_push_dist() == []
    assert targets._collective_push_ring() == []


# ---------------------------------------------------------------------------
# LUX-J4 VMEM budget
# ---------------------------------------------------------------------------


def _pf_plan():
    from lux_tpu.analysis.ir.targets import fixture

    return fixture()["plan_pf"]


def test_j401_over_budget_group_fails():
    rs, ra = _pf_plan()
    from lux_tpu.ops.pallas_shuffle import StaticRoutePF

    assert isinstance(rs.r1, StaticRoutePF)
    # seed the bug: a group whose tile claims 64x the planned rows —
    # the shape of a planner regression a cached plan would replay
    big = dataclasses.replace(
        rs.r1, groups=tuple(
            dataclasses.replace(g, block_rows=g.block_rows * 64)
            for g in rs.r1.groups))
    broken = dataclasses.replace(rs, r1=big)
    fs = vmem.check_vmem(broken, ra, "p", "fixture/overbudget",
                         budget_bytes=1 << 20)
    assert "LUX-J401" in _codes(fs)


def test_j4_real_pf_plans_within_budget():
    rs, ra = _pf_plan()
    assert vmem.check_vmem(rs, ra, "p", "expand-pf") == []


def test_j4_residency_uses_real_index_dtypes():
    """The recomputation reads the ACTUAL narrowed dtypes: a u8 plan's
    residency is below the planner's conservative int32 model."""
    rs, ra = _pf_plan()
    from lux_tpu.analysis.ir.vmem import group_residency_bytes

    g = rs.r1.groups[0]
    idx = [np.zeros((4, 128), np.uint8)] * len(g.steps)
    narrow = group_residency_bytes(g, idx)
    wide = group_residency_bytes(
        g, [a.astype(np.int32) for a in idx])
    assert narrow < wide


# ---------------------------------------------------------------------------
# LUX-J5 HBM-pass accounting
# ---------------------------------------------------------------------------


def test_j501_direct_gather_vs_routed_claim_fails():
    """Replay the plan's role with a FLAT gather (zero pallas kernels):
    the kernel count no longer matches the static — the 'a pass fell
    off the Pallas path' regression."""
    rs, _ = _pf_plan()

    @jax.jit
    def direct(x, idx):
        return x[idx]

    traced = direct.trace(jnp.arange(256.0),
                          jnp.arange(256, dtype=jnp.int32))
    fs = hbm.check_hbm(traced, rs, "p", "fixture/direct")
    assert "LUX-J501" in _codes(fs)


def test_j502_off_by_one_claim_fails():
    from lux_tpu.analysis.ir.targets import _expand_traced, fixture

    traced, rs = _expand_traced(fixture()["plan_pf"])
    from lux_tpu.utils import roofline

    claimed = roofline.routed_hbm_passes(rs)
    claimed["r1"] += 1  # the seeded metric drift
    fs = hbm.check_hbm(traced, rs, "p", "fixture/offbyone",
                       claimed=claimed)
    assert _codes(fs) == ["LUX-J502"]


def test_j5_real_replays_match_accounting():
    from lux_tpu.analysis.ir import targets

    assert targets._hbm_expand(False) == []
    assert targets._hbm_expand(True) == []
    assert targets._hbm_fused_pf() == []


def test_j503_kernel_delta_fails():
    """Seeded broken twin: a 'telemetry' variant that launches pallas
    kernels the base config does not — the ring-adds-HBM-passes bug
    class the parity check exists for."""
    from lux_tpu.analysis.ir.targets import _expand_traced, fixture

    @jax.jit
    def base(x, idx):
        return x[idx]

    traced_base = base.trace(jnp.arange(256.0),
                             jnp.arange(256, dtype=jnp.int32))
    traced_twin, _ = _expand_traced(fixture()["plan_pf"])
    fs = hbm.check_kernel_parity(traced_base, traced_twin, "p",
                                 "fixture/delta")
    assert _codes(fs) == ["LUX-J503"]


def test_j_ring_units_clean():
    """The luxtrace telemetry ring's three audited legs (retrace,
    donation, kernel parity) are clean on the real engines — the
    static proof behind docs/OBSERVABILITY.md's claims."""
    from lux_tpu.analysis.ir import targets

    assert targets._retrace_pull_fixed_ring() == []
    assert targets._donation_pull_fixed_ring() == []
    assert targets._donation_push_chunk_ring() == []
    assert targets._hbm_ring_neutral() == []


# ---------------------------------------------------------------------------
# the gate + baseline machinery
# ---------------------------------------------------------------------------


def test_repo_is_luxaudit_clean_fast_tier():
    """The ci_check tier of the acceptance gate, in-process."""
    findings, report = run_audit(fast=True)
    assert findings == [], [f.format() for f in findings]
    assert report["clean"] and len(report["units"]) >= 5


def test_run_audit_crash_is_a_finding(monkeypatch):
    """An audit unit that CRASHES must fail the gate (LUX-J000), never
    pass as clean — the luxcheck LUX-X000 policy one layer down."""
    from lux_tpu.analysis.ir import targets as tmod

    def boom_units(fast=False):
        return [tmod.AuditUnit("retrace", "boom", "lux_tpu/engine/pull.py",
                               True, lambda: 1 / 0)]

    monkeypatch.setattr(tmod, "audit_units", boom_units)
    findings, report = run_audit(fast=True)
    assert _codes(findings) == ["LUX-J000"]
    assert not report["clean"]


def test_baseline_suppresses_and_stales(monkeypatch, tmp_path):
    """A justified baseline entry suppresses exactly its finding; a
    stale entry is itself a finding — luxcheck's machinery, shared."""
    from lux_tpu.analysis.core import Finding
    from lux_tpu.analysis.ir import targets as tmod

    seeded = Finding(path="lux_tpu/engine/pull.py", line=1, col=0,
                     code="LUX-J201", message="seeded", text="unit/x")

    def units(fast=False):
        return [tmod.AuditUnit("donation", "unit/x",
                               "lux_tpu/engine/pull.py", True,
                               lambda: [seeded])]

    monkeypatch.setattr(tmod, "audit_units", units)
    base = tmp_path / "baseline.txt"
    base.write_text(f"{seeded.path}:{seeded.code}:{seeded.fingerprint()}"
                    "  # fixture justification\n")
    findings, _ = run_audit(fast=True, baseline_path=str(base))
    assert findings == []
    # stale entry: nothing matches -> LUX-X003
    base.write_text("lux_tpu/engine/pull.py:LUX-J201:000000000000"
                    "  # fixture justification\n")
    findings, _ = run_audit(fast=True, baseline_path=str(base))
    codes = _codes(findings)
    assert "LUX-J201" in codes and "LUX-X003" in codes


@pytest.mark.slow
def test_luxaudit_cli_all_clean():
    """The full acceptance gate: `tools/luxaudit.py --all` exits 0 on
    the repo with the shipped (empty) baseline, writing the AUDIT json."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "luxaudit.py"),
         "--all", "--json", "/tmp/lux_audit_test.json"],
        capture_output=True, text=True, timeout=560, env=forced_cpu_env(),
        cwd=REPO)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "luxaudit: clean" in out.stdout
    import json

    with open("/tmp/lux_audit_test.json") as f:
        rec = json.load(f)
    assert rec["clean"] and rec["tier"] == "all"
    fams = {u["family"] for u in rec["units"]}
    assert fams == {"retrace", "donation", "collective", "vmem", "hbm"}


def test_luxaudit_cli_usage_error():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "luxaudit.py")],
        capture_output=True, text=True, timeout=60, env=forced_cpu_env(),
        cwd=REPO)
    assert out.returncode == 2


def test_j301_nested_in_while_found_once():
    """A broken cond NESTED in a while loop: the carry fixpoint
    re-evaluates the body, but each distinct finding reports once."""
    from jax.sharding import PartitionSpec as P

    mesh = _mesh2()

    @jax.jit
    @partial(jax.shard_map, mesh=mesh, in_specs=(P("parts"),),
             out_specs=P("parts"))
    def f(x):
        def body(c):
            s, it = c
            s = jax.lax.cond(
                jnp.sum(s) > 0,  # local predicate: broken
                lambda: s + jax.lax.psum(jnp.sum(s), "parts"),
                lambda: s * 2)
            return s, it + 1

        def cond(c):
            return c[1] < 3  # pure index math: agreed, no LUX-J302

        return jax.lax.while_loop(cond, body, (x, jnp.int32(0)))[0]

    fs = check_shard_map_bodies(
        aot.traced_jaxpr(f.trace(jnp.arange(4.0))), "p", "fixture/nested")
    assert _codes(fs) == ["LUX-J301"]


def test_j302_collective_in_cond_jaxpr_fails():
    """Code-review regression: a psum that lives only in the while COND
    jaxpr deadlocks the same way a body collective does (one device
    exits, stragglers re-enter the cond's psum) — J302 must fire when
    the predicate has a locally-divergent conjunct."""
    from jax.sharding import PartitionSpec as P

    mesh = _mesh2()

    @jax.jit
    @partial(jax.shard_map, mesh=mesh, in_specs=(P("parts"),),
             out_specs=P("parts"))
    def f(x):
        def body(c):
            s, it = c
            return s * 2, it + 1  # pure-local body

        def cond(c):
            s, it = c
            # local conjunct: devices disagree on the trip count while
            # the psum synchronizes the mesh every evaluation
            return ((jnp.sum(s) < 100.0)
                    & (jax.lax.psum(jnp.sum(s), "parts") < 1e9)
                    & (it < 5))

        return jax.lax.while_loop(cond, body, (x, jnp.int32(0)))[0]

    fs = check_shard_map_bodies(
        aot.traced_jaxpr(f.trace(jnp.arange(4.0))), "p", "fixture/condpsum")
    assert _codes(fs) == ["LUX-J302"]


def test_empty_family_filter_is_a_finding():
    """Code-review regression: a typo'd --families value must FAIL the
    gate (LUX-J000), never audit zero units and report clean."""
    findings, report = run_audit(fast=True, families=("donate",))
    assert not report["clean"]
    assert "LUX-J000" in _codes(findings)
    # a valid subset still works
    findings, report = run_audit(fast=True, families=("donation",))
    assert findings == [] and report["clean"]
