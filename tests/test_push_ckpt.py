"""Frontier-app (push engine) checkpoint/resume (VERDICT r2 #6): the
carry's state + frontier + exact edge counter survive interruption, and
the checkpoint is ELASTIC — any part count / exchange / mesh resumes any
other's save (queues rebuild from the global changed mask)."""
import dataclasses

import numpy as np
import pytest

from lux_tpu.apps import sssp as app
from lux_tpu.engine import push
from lux_tpu.graph import generate
from lux_tpu.graph.push_shards import build_push_shards
from lux_tpu.models.sssp import SSSPProgram, bfs_reference
from lux_tpu.parallel import ring
from lux_tpu.parallel.mesh import make_mesh
from lux_tpu.utils.config import RunConfig


@pytest.fixture(scope="module")
def g():
    return generate.rmat(9, 8, seed=5)


@pytest.fixture(scope="module")
def start(g):
    from conftest import hub_vertex

    return hub_vertex(g)


def test_interrupt_and_resume_matches_uninterrupted(g, start, tmp_path):
    shards = build_push_shards(g, 2)
    prog = SSSPProgram(nv=shards.spec.nv, start=start)
    want_st, want_it, want_e = push.run_push(prog, shards, 1000, method="scan")
    assert int(want_it) > 3, "graph must take >3 rounds for this test"

    # "kill" mid-run: the driver stops at max_iters=3 with a checkpoint
    cfg = RunConfig(
        ckpt_dir=str(tmp_path), ckpt_every=2, max_iters=3, method="scan"
    )
    _, it, _, _ = app.run_push_checkpointed(prog, shards, cfg, None, "sssp")
    assert it == 3

    # resume on a FRESH layout build; must land exactly where the
    # uninterrupted run did — global state, iteration count, and edge
    # counter (stacked padding slots are inert and round-trip as zeros,
    # so the comparison is on the de-padded global vector)
    cfg2 = dataclasses.replace(cfg, max_iters=10_000)
    sh2b = build_push_shards(g, 2)
    st2, it2, e2, _ = app.run_push_checkpointed(prog, sh2b, cfg2, None, "sssp")
    assert it2 == int(want_it)
    np.testing.assert_array_equal(
        sh2b.scatter_to_global(np.asarray(st2)),
        shards.scatter_to_global(np.asarray(want_st)),
    )
    assert push.edges_total(e2) == push.edges_total(want_e)


def test_elastic_resume_across_parts_and_exchange(g, start, tmp_path):
    # save from a P=2 single-device run, interrupted after 3 iterations
    sh2 = build_push_shards(g, 2)
    prog = SSSPProgram(nv=sh2.spec.nv, start=start)
    cfg = RunConfig(
        ckpt_dir=str(tmp_path), ckpt_every=3, max_iters=3, method="scan"
    )
    app.run_push_checkpointed(prog, sh2, cfg, None, "sssp")

    # resume on P=8 ring-dense over the 8-device mesh
    mesh8 = make_mesh(8)
    prs = ring.build_push_ring_shards(g, 8)
    cfg2 = RunConfig(
        ckpt_dir=str(tmp_path), ckpt_every=4, method="scan",
        exchange="ring", distributed=True, num_parts=8,
    )
    st, it, edges, _ = app.run_push_checkpointed(
        prog, prs, cfg2, mesh8, "sssp"
    )
    np.testing.assert_array_equal(
        prs.scatter_to_global(np.asarray(st)), bfs_reference(g, start)
    )
    # layout-independent engine semantics: same total iteration count and
    # exact traversed-edge counter as an uninterrupted run
    _, want_it, want_e = push.run_push(prog, sh2, 1000, method="scan")
    assert it == int(want_it)
    assert push.edges_total(edges) == push.edges_total(want_e)


def test_elastic_resume_k_resident_parts(g, start, tmp_path):
    """Resume a P=2 save on P=16 over the 8-device mesh: two parts
    RESIDENT per device (the mapper-slicing analog) through the
    checkpointed windowed driver."""
    sh2 = build_push_shards(g, 2)
    prog = SSSPProgram(nv=sh2.spec.nv, start=start)
    cfg = RunConfig(
        ckpt_dir=str(tmp_path), ckpt_every=3, max_iters=3, method="scan"
    )
    app.run_push_checkpointed(prog, sh2, cfg, None, "sssp")

    mesh8 = make_mesh(8)
    sh16 = build_push_shards(g, 16)
    cfg2 = RunConfig(
        ckpt_dir=str(tmp_path), ckpt_every=5, method="scan",
        distributed=True, num_parts=16,
    )
    st, it, edges, _ = app.run_push_checkpointed(
        prog, sh16, cfg2, mesh8, "sssp"
    )
    np.testing.assert_array_equal(
        sh16.scatter_to_global(np.asarray(st)), bfs_reference(g, start)
    )
    _, want_it, want_e = push.run_push(prog, sh2, 1000, method="scan")
    assert it == int(want_it)
    assert push.edges_total(edges) == push.edges_total(want_e)


def test_cli_ckpt_and_resume(g, tmp_path, capsys):
    args = [
        "--rmat-scale", "9", "--rmat-ef", "8", "--seed", "7",
        "--ckpt-dir", str(tmp_path), "--ckpt-every", "2",
    ]
    assert app.main(args) == 0
    out1 = capsys.readouterr().out
    assert "resumed" not in out1
    # second invocation resumes at the converged checkpoint: zero windows
    assert app.main(args) == 0
    out2 = capsys.readouterr().out
    assert "resumed from" in out2
    # both report the same reach
    r1 = [ln for ln in out1.splitlines() if ln.startswith("reached")]
    r2 = [ln for ln in out2.splitlines() if ln.startswith("reached")]
    assert r1 == r2


def test_cli_gate_needs_both_flags(tmp_path):
    with pytest.raises(SystemExit):
        app.main(
            ["--rmat-scale", "8", "--ckpt-dir", str(tmp_path)]
        )  # no --ckpt-every


def test_components_cli_ckpt(tmp_path, capsys):
    from lux_tpu.apps import components as cc_app

    args = [
        "--rmat-scale", "8", "--ckpt-dir", str(tmp_path),
        "--ckpt-every", "2", "-check",
    ]
    assert cc_app.main(args) == 0
    assert "[PASS]" in capsys.readouterr().out
    assert cc_app.main(args) == 0
    out = capsys.readouterr().out
    assert "resumed from" in out and "[PASS]" in out
