"""serve/live: mutation-aware serving — the write path through the
fleet (ISSUE 12).

Pins the acceptance surface: a write admitted at the controller is
readable from EVERY replica with a generation tag >= its commit
generation; fleet-wide warm-refresh answers are bitwise-equal (SSSP/CC;
PageRank <= 1 ulp) to a single-host apply+refresh of the same batch
sequence — including under a mid-replication worker kill, where the
killed worker recovers its exact committed journal prefix and catches
up.  Plus the satellites: the overlay-twin batched engines (bitwise vs
the merged reference, zero retrace across occupancies), the
LUX_FLEET_MAX_FRAME_MB wire knob, and the fused/CF overlay rejection
naming its escape hatches.
"""
import os
import time

import numpy as np
import pytest

from lux_tpu.graph import generate
from lux_tpu.graph.format import read_lux
from lux_tpu.graph.shards import build_pull_shards
from lux_tpu.models.sssp import bfs_reference
from lux_tpu.mutate import overlay as ovl
from lux_tpu.mutate.deltalog import DeltaLog, DeltaOverflow
from lux_tpu.serve.fleet.controller import FleetError, StaleReadError
from lux_tpu.serve.fleet.worker import ReplicaWorker
from lux_tpu.serve.live.controller import (
    LiveFleetController,
    start_live_fleet,
)
from lux_tpu.serve.live.journal import (
    LiveJournal,
    pack_batch,
    unpack_batch,
)
from lux_tpu.serve.live.replica import (
    GenerationGap,
    LiveReplica,
    parse_standing,
)


@pytest.fixture(scope="module")
def small():
    g = generate.rmat(8, 8, seed=4)
    return g, build_pull_shards(g, 2)


def _churned_log(g, k=15, seed=0):
    rng = np.random.default_rng(seed)
    dlog = DeltaLog(g)
    dele = rng.choice(g.ne, k, replace=False)
    dlog.apply(g.col_idx[dele], g.dst_of_edges()[dele],
               np.zeros(k, np.int8))
    dlog.apply(rng.integers(0, g.nv, k), rng.integers(0, g.nv, k),
               np.ones(k, np.int8))
    return dlog


def _batches(g, n, rows=12, seed=1):
    """n random insert/delete batches against ``g`` (deletes target
    distinct base edges so every batch resolves)."""
    rng = np.random.default_rng(seed)
    dele_pool = rng.permutation(g.ne)
    out = []
    lo = 0
    for i in range(n):
        ndel = rows // 2
        dele = dele_pool[lo:lo + ndel]
        lo += ndel
        src = np.concatenate([np.asarray(g.col_idx, np.int64)[dele],
                              rng.integers(0, g.nv, rows - ndel)])
        dst = np.concatenate([np.asarray(g.dst_of_edges(),
                                         np.int64)[dele],
                              rng.integers(0, g.nv, rows - ndel)])
        op = np.concatenate([np.zeros(ndel, np.int8),
                             np.ones(rows - ndel, np.int8)])
        out.append((src, dst, op))
    return out


# ----------------------------------------------------------------------
# overlay-twin batched engines
# ----------------------------------------------------------------------


def test_batched_overlay_matches_merged_reference(small):
    import jax
    import jax.numpy as jnp

    from lux_tpu.serve.batched import BatchedEngine

    g, sh = small
    dlog = _churned_log(g)
    ostatic = ovl.OverlayStatic(cap=ovl.delta_cap(256),
                                weighted=sh.spec.weighted)
    _, oarr = ovl.build_pull_overlay(sh, dlog, cap=256)
    eng = BatchedEngine(sh, "sssp", 4, overlay_static=ostatic).warm()
    merged = dlog.merged_graph()
    srcs = [0, 3, 7, 11]
    out = eng.run(srcs, oarrays=jax.tree.map(jnp.asarray, oarr))
    for i, s in enumerate(srcs):
        assert np.array_equal(out.query_state(i),
                              bfs_reference(merged, s)), s
    # the zero-churn overlay is BITWISE the plain engine
    plain = BatchedEngine(sh, "sssp", 4).warm().run(srcs)
    empty = eng.run(srcs, oarrays=jax.tree.map(
        jnp.asarray, ovl.empty_overlay_arrays(sh, 256)))
    assert np.array_equal(empty.state, plain.state)


def test_batched_overlay_ppr_lane_independence(small):
    import jax
    import jax.numpy as jnp

    from lux_tpu.serve.batched import BatchedEngine

    g, sh = small
    dlog = _churned_log(g)
    ostatic = ovl.OverlayStatic(cap=ovl.delta_cap(256),
                                weighted=sh.spec.weighted)
    _, oarr = ovl.build_pull_overlay(sh, dlog, cap=256)
    deg = ovl.merged_degree_stacked(sh, dlog)
    oarr_d = jax.tree.map(jnp.asarray, oarr)
    e4 = BatchedEngine(sh, "ppr", 4, overlay_static=ostatic)
    e1 = BatchedEngine(sh, "ppr", 1, overlay_static=ostatic)
    srcs = [0, 3, 7, 11]
    o4 = e4.run(srcs, oarrays=oarr_d, degree=deg)
    for i, s in enumerate(srcs):
        o1 = e1.run([s], oarrays=oarr_d, degree=deg)
        assert np.array_equal(o4.query_state(i), o1.query_state(0)), s


def test_batched_overlay_zero_retrace(small):
    import jax
    import jax.numpy as jnp

    from lux_tpu.serve import batched as B

    g, sh = small
    dlog = _churned_log(g)
    ostatic = ovl.OverlayStatic(cap=ovl.delta_cap(256),
                                weighted=sh.spec.weighted)
    prog = B.make_program("sssp", sh.spec.nv)
    run = B._compile_batched_fixpoint(prog, sh.spec, "scan", ostatic)
    arrs = jax.tree.map(jnp.asarray, sh.arrays)
    sizes = []
    for occ in (ovl.build_pull_overlay(sh, dlog, cap=256)[1],
                ovl.empty_overlay_arrays(sh, 256)):
        q = jnp.zeros((2,), jnp.int32)
        st = B._compile_batched_init(prog)(arrs, q)
        run(arrs, q, st, jnp.int32(2), jax.tree.map(jnp.asarray, occ))
        sizes.append(run._cache_size())
    assert sizes == [1, 1]  # occupancy is data, never a trace


def test_batched_overlay_pairing_guard(small):
    from lux_tpu.serve.batched import BatchedEngine

    g, sh = small
    ostatic = ovl.OverlayStatic(cap=ovl.delta_cap(128),
                                weighted=sh.spec.weighted)
    live_eng = BatchedEngine(sh, "sssp", 1, overlay_static=ostatic)
    with pytest.raises(ValueError, match="passed together"):
        live_eng.run([0])
    plain = BatchedEngine(sh, "sssp", 1)
    with pytest.raises(ValueError, match="passed together"):
        plain.run([0], oarrays=ovl.empty_overlay_arrays(sh, 128))


def test_cf_overlay_rejection_names_escape_hatch(small):
    """Satellite (rescoped by luxmerge): the overlay rejection now
    covers ONLY the CF route — the fused families tombstone in group
    space and must RUN under an overlay.  The CF raise must still name
    the escape hatches (compact, or route_base=\"expand\") and the
    knobs — not just say 'not supported'."""
    import jax
    import jax.numpy as jnp

    from lux_tpu.engine import pull
    from lux_tpu.models.pagerank import PageRankProgram
    from lux_tpu.ops import expand

    g, sh = small
    dlog = _churned_log(g)
    ostatic, oarr = ovl.build_pull_overlay(sh, dlog, cap=256)
    prog = PageRankProgram(nv=sh.spec.nv)
    arrs = jax.tree.map(jnp.asarray, sh.arrays)
    st, fa = expand.plan_cf_route_shards(sh)
    with pytest.raises(ValueError) as ei:
        pull.run_pull_fixed(
            prog, sh.spec, arrs, pull.init_state(prog, arrs), 2,
            method="scan", route=(st, fa), overlay=(ostatic, oarr))
    msg = str(ei.value)
    assert "route_base=\"expand\"" in msg
    assert "compact()" in msg
    assert "LUX_ROUTE_MODE" in msg and "LUX_DELTA_CAP" in msg
    # the fused family is no longer rejected: the same overlay runs on
    # the fused route (group-space tombstones via the plan's gslot)
    fst, ffa = expand.plan_fused_shards(sh)
    out = pull.run_pull_fixed(
        prog, sh.spec, arrs, pull.init_state(prog, arrs), 2,
        method="scan", route=(fst, ffa), overlay=(ostatic, oarr))
    assert np.isfinite(np.asarray(out)).all()


def test_wire_max_frame_env_knob(monkeypatch):
    """Satellite: LUX_FLEET_MAX_FRAME_MB bounds the payload both ways
    (send refuses before the bytes move; recv refuses a hostile length
    prefix)."""
    from lux_tpu.serve.fleet import wire

    assert wire.max_frame_bytes() == wire.MAX_PAYLOAD
    monkeypatch.setenv("LUX_FLEET_MAX_FRAME_MB", "1")
    assert wire.max_frame_bytes() == 1024 * 1024
    # 0 disables the in-flight deadline (no select on the fake socket)
    monkeypatch.setenv("LUX_FLEET_TIMEOUT_S", "0")
    assert wire.frame_timeout_s() is None

    class _Sock:
        def send(self, b):
            raise AssertionError("oversized frame must not hit the wire")

    conn = wire.Conn.__new__(wire.Conn)
    conn._sock = _Sock()
    import threading

    conn._send_lock = threading.Lock()
    conn._closed = False
    with pytest.raises(wire.WireError, match="LUX_FLEET_MAX_FRAME_MB"):
        conn.send({"op": "x"}, arr=np.zeros(1024 * 1024, np.int64))
    monkeypatch.setenv("LUX_FLEET_MAX_FRAME_MB", "64")
    conn2 = wire.Conn.__new__(wire.Conn)
    sent = []

    class _Sock2:
        def send(self, b):
            # the chunked sender consumes the memoryview via send()
            sent.append(len(b))
            return len(b)

    conn2._sock = _Sock2()
    conn2._send_lock = threading.Lock()
    conn2._closed = False
    conn2.send({"op": "x"}, arr=np.zeros(1024 * 1024, np.int64))
    assert sent
    monkeypatch.setenv("LUX_FLEET_MAX_FRAME_MB", "junk")
    with pytest.raises(ValueError, match="LUX_FLEET_MAX_FRAME_MB"):
        wire.max_frame_bytes()


# ----------------------------------------------------------------------
# journal + replica
# ----------------------------------------------------------------------


def test_live_journal_sequencing_reload_and_epoch(small, tmp_path):
    g, _sh = small
    jd = str(tmp_path / "ctl")
    J = LiveJournal(g, journal_dir=jd)
    gens = [J.admit(s, d, o) for s, d, o in _batches(g, 3)]
    assert gens == [1, 2, 3]
    assert [gen for gen, _ in J.batches_since(1)] == [2, 3]
    with pytest.raises(KeyError):
        J.payload(4)
    # wire pack round-trip
    s, d, o = _batches(g, 1)[0]
    arr = pack_batch(s, d, o)
    s2, d2, o2, w2 = unpack_batch(arr)
    assert np.array_equal(s, s2) and np.array_equal(d, d2)
    assert np.array_equal(o.astype(np.int8), o2)
    # reload: same generation line, same catch-up stream
    J2 = LiveJournal(g, journal_dir=jd)
    assert J2.generation() == 3
    assert np.array_equal(J2.payload(2), J.payload(2))
    # compaction epoch: base advances, old batches gone, line continues
    snap = str(tmp_path / "snap.lux")
    merged = J.compact(snap)
    assert J.base_generation == 3 and J.generation() == 3
    assert not J.batches_since(3)
    with pytest.raises(KeyError, match="compacted"):
        J.batches_since(0)
    assert J.admit([1], [2], [1]) == 4
    J3 = LiveJournal(read_lux(snap), journal_dir=jd)
    assert J3.base_generation == 3 and J3.generation() == 4
    assert merged.ne == read_lux(snap).ne
    # a journaled sequencer refuses to compact without a snapshot
    with pytest.raises(ValueError, match="snapshot path"):
        J3.compact()


def test_replica_kill_between_receipt_and_marker_recovers_prefix(
        small, tmp_path, monkeypatch):
    """Satellite: a worker killed between delta receipt and the .ok
    marker must recover to the EXACT committed prefix and, after
    catch-up, answer bitwise-equal to a never-killed replica."""
    g, sh = small
    J = LiveJournal(g)
    batches = _batches(g, 3)
    for s, d, o in batches:
        J.admit(s, d, o)
    wd = str(tmp_path / "w")
    rep = LiveReplica(g, sh, cap=256, journal_dir=wd,
                      standing=(("sssp", 0),))
    rep.apply_batch(J.payload(1), 1)
    # the crash window: batch npz lands, the marker never does
    monkeypatch.setattr(
        DeltaLog, "_journal_mark",
        lambda self, seq: (_ for _ in ()).throw(
            KeyboardInterrupt("killed before marker")))
    with pytest.raises(KeyboardInterrupt):
        rep.apply_batch(J.payload(2), 2)
    monkeypatch.undo()
    # recover: replay stops at the first missing marker — generation 1,
    # not 2 (the torn batch is gone), never a half-applied state
    rec = LiveReplica(g, sh, cap=256, journal_dir=wd,
                      standing=(("sssp", 0),))
    assert rec.generation() == 1 == rec.servable_generation()
    # catch up to the committed prefix of the AUTHORITATIVE journal
    for gen, arr in J.batches_since(rec.generation()):
        rec.apply_batch(arr, gen)
    assert rec.generation() == 3
    # answers bitwise-equal to a never-killed replica
    clean = LiveReplica(g, sh, cap=256, standing=(("sssp", 0),))
    for gen, arr in J.batches_since(0):
        clean.apply_batch(arr, gen)
    rec.refresh()
    clean.refresh()
    assert np.array_equal(rec.standing("sssp")["state"],
                          clean.standing("sssp")["state"])
    assert np.array_equal(rec.standing("sssp")["state"],
                          bfs_reference(J.log.merged_graph(), 0))


def test_replica_generation_gap_and_overflow(small, tmp_path):
    g, sh = small
    J = LiveJournal(g)
    for s, d, o in _batches(g, 2):
        J.admit(s, d, o)
    rep = LiveReplica(g, sh, cap=128, standing=())
    with pytest.raises(GenerationGap) as ei:
        rep.apply_batch(J.payload(2), 2)  # skipped generation 1
    assert ei.value.have == 0 and ei.value.want == 2
    rep.apply_batch(J.payload(1), 1)
    # one batch past the per-part capacity: journaled but not servable
    rng = np.random.default_rng(7)
    big = pack_batch(rng.integers(0, g.nv, 400),
                     rng.integers(0, g.nv, 400), np.ones(400, np.int8))
    with pytest.raises(DeltaOverflow):
        rep.apply_batch(big, 2)
    assert rep.generation() == 2  # durable...
    assert rep.servable_generation() == 1  # ...but the overlay lags


def test_parse_standing():
    assert parse_standing("sssp:7,pagerank") == (("sssp", 7),
                                                ("pagerank", None))
    with pytest.raises(ValueError, match="unknown standing app"):
        parse_standing("bfsish")


# ----------------------------------------------------------------------
# the fleet write path (acceptance pins)
# ----------------------------------------------------------------------


def _close(fleet):
    fleet.close()


def test_live_fleet_read_your_writes(small, tmp_path):
    """Acceptance: a write admitted at the controller is readable from
    EVERY replica with a generation tag >= its commit generation."""
    g, _sh = small
    fleet = start_live_fleet(2, g, parts=2, cap=256,
                             standing=(("sssp", 0),))
    ctl = fleet.controller
    try:
        f = ctl.submit(3)
        assert np.array_equal(f.result(timeout=60), bfs_reference(g, 3))
        assert f.generation == 0
        for s, d, o in _batches(g, 2):
            rep = ctl.admit_writes(s, d, o)
        assert rep["generation"] == 2
        assert set(rep["acked"]) == {"w0", "w1"}
        merged = ctl.journal.log.merged_graph()
        # route around the ring: every source key lands somewhere —
        # check BOTH replicas answer with the write visible, by asking
        # each one directly through the standing read AND via routed
        # queries with the read-your-writes bound
        seen = set()
        for s in (0, 3, 7, 11, 20, 33, 40, 41):
            f = ctl.submit(s, min_generation=2)
            assert np.array_equal(f.result(timeout=60),
                                  bfs_reference(merged, s)), s
            assert f.generation >= 2
            seen.add(f.worker_id)
        assert seen == {"w0", "w1"}  # both replicas served tagged reads
        assert ctl.worker_generations() == {"w0": 2, "w1": 2}
        # stale bound: nobody has generation 99
        with pytest.raises(StaleReadError):
            ctl.submit(0, min_generation=99)
    finally:
        _close(fleet)


def test_live_fleet_refresh_bitwise_vs_single_host(small, tmp_path):
    """Acceptance: fleet-wide warm-refresh answers are bitwise-equal
    (SSSP/CC; PageRank <= 1 ulp) to a single-host apply+refresh of the
    same batch sequence."""
    g, sh = small
    standing = (("sssp", 0), ("components", None), ("pagerank", None))
    fleet = start_live_fleet(2, g, parts=2, cap=256, standing=standing)
    ctl = fleet.controller
    try:
        batches = _batches(g, 2)
        for s, d, o in batches:
            ctl.admit_writes(s, d, o)
        ctl.refresh_fleet()
        # more churn, refresh again: the WARM path (prior states), not
        # just the cold first convergence
        for s, d, o in _batches(g, 2, seed=9):
            gen = ctl.admit_writes(s, d, o)["generation"]
        res = ctl.refresh_fleet()
        assert all(w["generation"] == gen
                   for w in res["workers"].values())
        # single host: same batch sequence through apply + refresh
        solo = LiveReplica(g, build_pull_shards(g, 2), cap=256,
                           standing=standing)
        for gg, arr in ctl.journal.batches_since(0):
            solo.apply_batch(arr, gg)
        solo.refresh()
        merged = ctl.journal.log.merged_graph()
        for app in ("sssp", "components", "pagerank"):
            allr = ctl.read_standing_all(app)
            assert set(allr) == {"w0", "w1"}
            ref = solo.standing(app)["state"]
            for wid, ent in allr.items():
                assert ent["generation"] >= gen, (app, wid)
                if app == "pagerank":
                    a = ent["state"].view(np.int32).astype(np.int64)
                    b = ref.view(np.int32).astype(np.int64)
                    assert np.abs(a - b).max() <= 1, (app, wid)
                else:
                    assert np.array_equal(ent["state"], ref), (app, wid)
        # and sssp is the merged graph's true answer, not just agreement
        assert np.array_equal(ctl.read_standing("sssp")["state"],
                              bfs_reference(merged, 0))
    finally:
        _close(fleet)


def test_live_fleet_mid_replication_kill_and_rejoin(
        small, tmp_path, monkeypatch):
    """Acceptance under faults: a worker killed mid-replication (after
    the delta npz, before the .ok marker) recovers its exact committed
    prefix from its journal, rejoins, catches up through the
    controller, and its reads/refresh answers are bitwise-equal to the
    survivor's."""
    g, sh = small
    jroot = str(tmp_path / "j")
    fleet = start_live_fleet(2, g, parts=2, cap=256,
                             journal_root=jroot,
                             standing=(("sssp", 0),))
    ctl = fleet.controller
    try:
        batches = _batches(g, 4)
        s, d, o = batches[0]
        ctl.admit_writes(s, d, o)
        # arm the crash on w1's NEXT journal mark, then vanish —
        # the delta npz is on disk, the marker never lands
        w1 = fleet.thread_workers[1]
        orig_mark = DeltaLog._journal_mark

        def boom(self_log, seq):
            if self_log is w1._live.mg.log:
                w1.kill()
                raise OSError("killed between receipt and marker")
            return orig_mark(self_log, seq)

        monkeypatch.setattr(DeltaLog, "_journal_mark", boom)
        s, d, o = batches[1]
        rep = ctl.admit_writes(s, d, o)
        monkeypatch.undo()
        assert rep["acked"] == ["w0"]
        deadline = time.monotonic() + 10
        while ctl.live_workers() != ["w0"]:
            assert time.monotonic() < deadline
            time.sleep(0.05)
        # fleet keeps admitting + serving while w1 is down
        s, d, o = batches[2]
        rep = ctl.admit_writes(s, d, o)
        assert rep["generation"] == 3 and rep["acked"] == ["w0"]
        f = ctl.submit(3, min_generation=3)
        merged = ctl.journal.log.merged_graph()
        assert np.array_equal(f.result(timeout=60),
                              bfs_reference(merged, 3))
        # recover w1 from its journal: the committed prefix is EXACTLY
        # generation 1 (batch 2's marker never landed)
        live2 = LiveReplica(g, sh, cap=256,
                            journal_dir=os.path.join(jroot, "w1"),
                            standing=(("sssp", 0),))
        assert live2.generation() == 1
        w1b = ReplicaWorker(sh, "w1", graph_id="live",
                            q_buckets=(1, 4), live=live2).start()
        fleet.thread_workers.append(w1b)
        ctl.add_worker("127.0.0.1", w1b.port)
        # catch-up ran inside add_worker: w1 is current again
        assert ctl.worker_generations() == {"w0": 3, "w1": 3}
        ctl.refresh_fleet()
        allr = ctl.read_standing_all("sssp")
        assert set(allr) == {"w0", "w1"}
        assert np.array_equal(allr["w0"]["state"], allr["w1"]["state"])
        assert np.array_equal(allr["w1"]["state"],
                              bfs_reference(merged, 0))
        assert allr["w1"]["generation"] == 3
        # and routed reads hit the recovered replica too
        seen = set()
        for srcv in (0, 3, 7, 11, 20, 33):
            fq = ctl.submit(srcv, min_generation=3)
            assert np.array_equal(fq.result(timeout=60),
                                  bfs_reference(merged, srcv))
            seen.add(fq.worker_id)
        assert "w1" in seen
    finally:
        _close(fleet)


def test_overflow_escalates_to_fleet_compaction(small, tmp_path):
    from lux_tpu import obs
    from lux_tpu.obs.recorder import Recorder

    g, _sh = small
    snap = str(tmp_path / "snap.lux")
    rec = Recorder(run_id="tovf", root=str(tmp_path / "obs"),
                   enabled=True)
    old_rec = obs.install(rec)
    fleet = start_live_fleet(2, g, parts=2, cap=128,
                             snapshot_path=snap,
                             journal_root=str(tmp_path / "j"),
                             standing=(("sssp", 0),))
    ctl = fleet.controller
    rng = np.random.default_rng(1)
    try:
        rep = None
        for i in range(3):
            rep = ctl.admit_writes(rng.integers(0, g.nv, 120),
                                   rng.integers(0, g.nv, 120),
                                   np.ones(120, np.int8))
            if rep["compacted"]:
                break
        assert rep["compacted"], "cap=128 never overflowed"
        gen = rep["generation"]
        assert ctl.journal.base_generation == gen
        assert os.path.exists(snap)
        # ISSUE 14 satellite: the overflow-triggered compaction is no
        # longer silent in the flight recorder — its own counter, an
        # escalation point event, and a span a chaos post-mortem can
        # attribute the latency spike to
        assert ctl.stats()["overflow_compactions"] == 1
        import json as _json

        evs = []
        for fn in sorted(os.listdir(rec.run_dir())):
            if fn.startswith("events-") and fn.endswith(".jsonl"):
                with open(os.path.join(rec.run_dir(), fn)) as fh:
                    evs += [_json.loads(ln) for ln in fh if ln.strip()]
        names = [e.get("n") for e in evs]
        assert "live.overflow.escalated" in names  # the point event
        assert "live.overflow.compact" in names  # the span
        # post-compaction: the whole fleet serves the new epoch, the
        # write that triggered the escalation included
        merged = ctl.journal.log.merged_graph()
        f = ctl.submit(3, min_generation=gen)
        assert np.array_equal(f.result(timeout=60),
                              bfs_reference(merged, 3))
        assert f.generation >= gen
        assert ctl.worker_generations() == {"w0": gen, "w1": gen}
        # the generation line continues across the epoch
        s, d, o = _batches(g, 1)[0]
        del_live = ctl.journal.log  # deletes must target the NEW base
        live_edges = np.flatnonzero(~del_live.del_base)[:4]
        base = del_live.base
        rep2 = ctl.admit_writes(
            np.asarray(base.col_idx, np.int64)[live_edges],
            np.asarray(base.dst_of_edges(), np.int64)[live_edges],
            np.zeros(4, np.int8))
        assert rep2["generation"] == gen + 1
    finally:
        obs.install(old_rec)
        _close(fleet)


def test_standing_state_not_stale_across_compaction(small, tmp_path):
    """A standing state refreshed BEFORE later batches must not carry
    across the compaction epoch (the new base embeds those batches; a
    carried-over prior would be re-tagged current by the fresh-epoch
    refresh without recomputing).  Only epoch-boundary states inherit
    warm."""
    g, _sh = small
    snap = str(tmp_path / "snap.lux")
    fleet = start_live_fleet(2, g, parts=2, cap=256,
                             snapshot_path=snap,
                             standing=(("sssp", 0),))
    ctl = fleet.controller
    try:
        batches = _batches(g, 2)
        s, d, o = batches[0]
        ctl.admit_writes(s, d, o)
        ctl.refresh_fleet()  # standing converges at generation 1
        s, d, o = batches[1]
        ctl.admit_writes(s, d, o)  # generation 2, NOT refreshed
        ctl.compact_fleet()  # epoch base := 2
        ctl.refresh_fleet()
        merged = ctl.journal.log.merged_graph()
        for wid, ent in ctl.read_standing_all("sssp").items():
            assert ent["generation"] == 2, wid
            assert np.array_equal(ent["state"],
                                  bfs_reference(merged, 0)), wid
        # piggyback (same fleet, snapshot on disk): a live worker must
        # refuse a prepare with no base_generation — a snapshot swap
        # that abandons the epoch would serve wrong answers under the
        # same generation line — and the abort leaves it serving
        with pytest.raises(FleetError, match="base_generation"):
            ctl.republish(snap, graph_id="live")
        f = ctl.submit(3)
        assert np.array_equal(f.result(timeout=60),
                              bfs_reference(merged, 3))
    finally:
        _close(fleet)


def test_live_controller_refuses_static_worker(small):
    g, sh = small
    # prewarm=False: the refusal happens at the hello handshake — no
    # engine is ever exercised, so don't pay the compile
    w = ReplicaWorker(sh, "ws", graph_id="live",
                      q_buckets=(1,)).start(prewarm=False)
    ctl = LiveFleetController(g)
    try:
        with pytest.raises(FleetError, match="not live"):
            ctl.add_worker("127.0.0.1", w.port)
        assert ctl.live_workers() == []
    finally:
        ctl.close()
        if w._running:
            w.stop()


@pytest.mark.slow
def test_live_bench_row_shape():
    """Slow tier: tier-1 already exercises the live row end-to-end
    through test_bench's happy path (the real bench.py run asserts its
    fields); this is the direct harness-shape check."""
    from lux_tpu.serve.live.bench import measure_live_mixed

    row = measure_live_mixed(scale=8, ef=8, workers=2, batch_rows=16,
                             write_batches=3, reader_threads=1,
                             min_window_s=0.5)
    assert row["metric"] == "sssp_live_w2_rmat8_cpu"
    assert row["unit"] == "QPS" and row["value"] > 0
    assert row["write_batches_per_s"] > 0
    assert row["final_generation"] == 3
    assert set(row["worker_generations"].values()) == {3}
    assert row["fleet_refresh_s"] > 0
    assert row["read_errors"] == 0
    for k in ("staleness_gen_p50", "staleness_gen_p99", "read_p50_ms",
              "read_p99_ms", "write_rows_per_s", "compactions"):
        assert k in row


def test_shared_pull_layout_determinism(small):
    """The overlay contract LiveReplica leans on: the push-embedded
    pull layout is BITWISE the standalone pull layout, so overlays
    built from the serving shards address the refresh engines' slots
    too."""
    import jax

    g, sh = small
    from lux_tpu.graph.push_shards import build_push_shards

    other = build_push_shards(g, 2).pull
    for a, b in zip(jax.tree_util.tree_leaves(sh.arrays),
                    jax.tree_util.tree_leaves(other.arrays)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
