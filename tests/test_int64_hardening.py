"""64-bit edge-count hardening (VERDICT r3 #5).

The reference keeps E_ID = uint64 / V_ID = uint32 (pagerank/app.h:21-22):
graphs can hold more than 2^31 (or 2^32) edges as long as no single part
does.  These tests pin that contract on the host-side geometry (fabricated
int64 row_ptr offsets — no giant allocations) and on the device-side
[hi, lo] uint32 traversal counter.
"""
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lux_tpu.engine.push import _acc_edges, _zero_edges, edges_total
from lux_tpu.graph.partition import edge_balanced_cuts
from lux_tpu.graph.shards import LANE, shard_geometry


def _fake_row_ptr(nv: int, ne: int) -> np.ndarray:
    """(nv+1,) int64 monotone offsets from 0 to ne — uniform degrees."""
    return np.linspace(0, ne, nv + 1, dtype=np.int64)


def test_shard_geometry_ne_past_2_32():
    """A 5e9-edge graph (> 2^32) passes as long as every PART stays under
    2^31 — global E_ID is int64 on host, per-part offsets are int32."""
    nv, ne, P = 1024, 5_000_000_000, 8
    rp = _fake_row_ptr(nv, ne)
    cuts, nv_pad, e_pad = shard_geometry(rp, P, nv)
    assert cuts.dtype == np.int64
    e_counts = rp[cuts[1:]] - rp[cuts[:-1]]
    assert e_counts.dtype == np.int64
    assert int(e_counts.sum()) == ne  # no edge lost to 32-bit wrap
    assert int(e_counts.max()) < 2**31
    assert e_pad >= int(e_counts.max())
    assert e_pad % LANE == 0 and nv_pad % LANE == 0


def test_shard_geometry_part_over_2_31_raises():
    """One part >= 2^31 edges breaks the int32 per-part edge indexing —
    must refuse with the 'increase num_parts' guard, not wrap silently."""
    rp = _fake_row_ptr(64, 3_000_000_000)
    with pytest.raises(ValueError, match="increase num_parts"):
        shard_geometry(rp, 1, 64)
    # the same graph at P=2 is fine (1.5e9 per part)
    cuts, _, e_pad = shard_geometry(rp, 2, 64)
    assert int(rp[cuts[1]]) >= 1_500_000_000
    assert e_pad < 2**31


def test_shard_geometry_int32_gather_guard():
    """num_parts * nv_pad is an int32 gather index (src_pos = own * nv_pad
    + local); a skewed cut pushing it past 2^31 must refuse.  Built from a
    4096-part graph whose zero-degree tail lands ~525k vertices in the
    last part: P * nv_pad ~ 2.15e9 — only a ~4 MB row_ptr is allocated."""
    P, heads = 4096, 4095
    nv = 530_000
    rp = np.zeros(nv + 1, np.int64)
    rp[1 : heads + 1] = np.arange(1, heads + 1)  # 1 edge each
    rp[heads + 1 :] = heads  # zero-degree tail
    with pytest.raises(ValueError, match="int32 gather range"):
        shard_geometry(rp, P, nv)


def test_edge_balanced_cuts_int64_targets():
    """The bounds sweep's cumulative targets (p * edge_cap) exceed 2^32 on
    big graphs; the sweep must hit them exactly in int64."""
    nv, ne, P = 4096, 6_000_000_000, 16
    rp = _fake_row_ptr(nv, ne)
    cuts = edge_balanced_cuts(rp, P)
    assert cuts[0] == 0 and cuts[-1] == nv
    assert (np.diff(cuts) >= 0).all()
    e_counts = rp[cuts[1:]] - rp[cuts[:-1]]
    cap = -(-ne // P)
    # each part holds at most cap + one vertex's degree (the contract)
    max_deg = int(np.diff(rp).max())
    assert int(e_counts.max()) <= cap + max_deg


class _VirtualColIdx:
    """col_idx stand-in for offsets past 2^31: serves slice requests from a
    tiny backing array, recording the requested int64 byte ranges — the
    shape of an np.memmap on a >16 GiB .lux file."""

    def __init__(self, serve: dict):
        self.serve = serve  # (lo, hi) -> np.ndarray
        self.requests = []

    def __getitem__(self, sl):
        assert isinstance(sl, slice) and sl.step is None
        self.requests.append((sl.start, sl.stop))
        return self.serve[(sl.start, sl.stop)]


def test_ring_bucket_counts_int64_offsets():
    """ring.bucket_counts on a graph whose edge offsets cross 2^31: the
    per-part slices must be requested at exact int64 bounds (the mmap
    path) and tallied into int64 counts."""
    from lux_tpu.parallel.ring import bucket_counts

    big = 2**31
    rp = np.array([0, big + 6, big + 10], np.int64)
    cuts = np.array([0, 1, 2], np.int64)
    col = _VirtualColIdx({
        (0, big + 6): np.array([0, 0, 1, 1, 1, 1], np.int32),
        (big + 6, big + 10): np.array([0, 0, 0, 1], np.int32),
    })
    g = types.SimpleNamespace(row_ptr=rp, col_idx=col)
    counts = bucket_counts(g, cuts, 2)
    assert counts.dtype == np.int64
    np.testing.assert_array_equal(counts, [[2, 4], [3, 1]])
    assert col.requests == [(0, big + 6), (big + 6, big + 10)]


def test_acc_edges_lo_carry_crosses_2_32():
    """The uint32 lo lane wraps and must carry into hi exactly once."""
    acc = jax.jit(_acc_edges, static_argnums=1)
    edges = jnp.array([0, 0xFFFF_FFF0], jnp.uint32)
    out = acc(edges, 0, jnp.uint32(0x20), jnp.bool_(False))
    assert edges_total(out) == 0x1_0000_0010
    # no carry when lo does not wrap
    out2 = acc(edges, 0, jnp.uint32(0x0F), jnp.bool_(False))
    assert edges_total(out2) == 0xFFFF_FFFF


def test_acc_edges_dense_ne_past_2_32():
    """dense_ne > 2^32 is split [hi, lo] at trace time; repeated dense
    rounds accumulate exactly."""
    dense_ne = (1 << 33) + 5
    acc = jax.jit(_acc_edges, static_argnums=1)
    e = _zero_edges()
    for _ in range(3):
        e = acc(e, dense_ne, jnp.uint32(0), jnp.bool_(True))
    assert edges_total(e) == 3 * dense_ne


def test_acc_edges_mixed_rounds_match_python_int():
    """A fuzzed dense/sparse round mix tracks an exact Python-int oracle
    across several 2^32 boundaries."""
    rng = np.random.default_rng(7)
    dense_ne = 3_000_000_001  # > 2^31, not a power of two
    acc = jax.jit(_acc_edges, static_argnums=1)
    e, want = _zero_edges(), 0
    for _ in range(40):
        use_dense = bool(rng.integers(2))
        sparse = int(rng.integers(0, 2**31))
        e = acc(e, dense_ne, jnp.uint32(sparse), jnp.bool_(use_dense))
        want += dense_ne if use_dense else sparse
    assert edges_total(e) == want
    assert want > 2**32  # the oracle actually crossed the boundary
