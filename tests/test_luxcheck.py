"""luxcheck (lux_tpu.analysis): each checker family catches its seeded
violation, suppressions round-trip (inline + baseline, justification
mandatory), and the shipped package is luxcheck-clean — the tier-1 form
of the chip-day step -3 gate."""
import os
import subprocess
import sys
import textwrap

import pytest

from lux_tpu.analysis import ALL_CHECKERS, check_paths
from lux_tpu.analysis.core import DEFAULT_TARGETS, Finding, Module

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _check_snippet(tmp_path, relpath, source):
    """Write ``source`` at ``relpath`` under a scratch repo root and run
    the full checker set on it (checker scopes key off the relpath)."""
    full = tmp_path / relpath
    full.parent.mkdir(parents=True, exist_ok=True)
    full.write_text(textwrap.dedent(source))
    return check_paths([relpath], str(tmp_path))


def _codes(findings):
    return [f.code for f in findings]


# ---------------------------------------------------------------------------
# tracing-safety fixtures
# ---------------------------------------------------------------------------


def test_tracing_if_on_traced_value(tmp_path):
    fs = _check_snippet(tmp_path, "lux_tpu/engine/bad_jit.py", """\
        import jax

        @jax.jit
        def step(state, frontier):
            if frontier:
                return state + 1
            return state
        """)
    assert "LUX-T001" in _codes(fs)


def test_tracing_while_and_item(tmp_path):
    fs = _check_snippet(tmp_path, "lux_tpu/engine/bad_loop.py", """\
        import jax

        @jax.jit
        def run(active, state):
            while active:
                state = state * 2
            return state.sum()

        @jax.jit
        def pick(dist):
            return dist.item()
        """)
    assert "LUX-T002" in _codes(fs)
    assert "LUX-T004" in _codes(fs)


def test_tracing_cast_in_scan_body(tmp_path):
    """A local def handed to lax.scan is a traced context even without a
    jit decorator."""
    fs = _check_snippet(tmp_path, "lux_tpu/engine/bad_scan.py", """\
        import jax
        from jax import lax

        def driver(xs):
            def body(carry, x):
                flag = bool(x)
                return carry + int(flag), x
            return lax.scan(body, 0, xs)
        """)
    assert "LUX-T003" in _codes(fs)


def test_tracing_statics_and_none_checks_exempt(tmp_path):
    """static_argnames branching is the supported recompile-by-design
    path; `x is None` is a trace-time constant; `.shape` access is
    static — none may fire."""
    fs = _check_snippet(tmp_path, "lux_tpu/engine/good_jit.py", """\
        import functools
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnames=("num_iters",))
        def run(state, num_iters, mask=None):
            if num_iters > 3:
                state = state * 2
            if mask is None:
                mask = jnp.ones_like(state)
            if state.shape[0] > 128:
                state = state[:128]
            return jnp.where(mask > 0, state, 0.0)
        """)
    assert _codes(fs) == []


# ---------------------------------------------------------------------------
# determinism fixtures
# ---------------------------------------------------------------------------


def test_determinism_set_iteration(tmp_path):
    fs = _check_snippet(tmp_path, "lux_tpu/graph/bad_set.py", """\
        import numpy as np

        def owners(edges):
            uniq = set(int(e) for e in edges)
            return np.array([x for x in uniq if x > 0])

        def cuts(parts):
            return list({p.lo for p in parts})
        """)
    # the comprehension over `uniq` is an aliased set (untracked —
    # precision over recall), but the literal/list(set) forms must fire
    assert "LUX-D001" in _codes(fs)


def test_determinism_set_sorted_is_clean(tmp_path):
    fs = _check_snippet(tmp_path, "lux_tpu/graph/good_set.py", """\
        def owners(edges):
            return sorted(set(edges))

        def count(edges):
            return len(set(edges))
        """)
    assert _codes(fs) == []


def test_determinism_wall_clock_and_rng(tmp_path):
    fs = _check_snippet(tmp_path, "lux_tpu/engine/bad_entropy.py", """\
        import time
        import numpy as np

        def stamp_plan(plan):
            plan["built_at"] = time.time()
            return plan

        def jitter(n):
            return np.random.rand(n)
        """)
    codes = _codes(fs)
    assert "LUX-D002" in codes
    assert "LUX-D003" in codes


def test_determinism_perf_counter_clean(tmp_path):
    """perf_counter/monotonic are timing, not calendar — exempt."""
    fs = _check_snippet(tmp_path, "lux_tpu/engine/good_timing.py", """\
        import time

        def timed(fn):
            t0 = time.perf_counter()
            out = fn()
            return out, time.perf_counter() - t0
        """)
    assert _codes(fs) == []


# ---------------------------------------------------------------------------
# thread-safety fixtures
# ---------------------------------------------------------------------------


def test_threads_unlocked_global_and_container(tmp_path):
    fs = _check_snippet(tmp_path, "lux_tpu/ops/bad_state.py", """\
        _CACHE = None
        _STATS = {"built": 0}

        def get_cache():
            global _CACHE
            if _CACHE is None:
                _CACHE = {"x": 1}
            return _CACHE

        def bump():
            _STATS["built"] += 1
        """)
    codes = _codes(fs)
    assert "LUX-C001" in codes
    assert "LUX-C002" in codes


def test_threads_locked_is_clean(tmp_path):
    fs = _check_snippet(tmp_path, "lux_tpu/ops/good_state.py", """\
        import threading

        _LOCK = threading.Lock()
        _CACHE = None
        _STATS = {"built": 0}

        def get_cache():
            global _CACHE
            with _LOCK:
                if _CACHE is None:
                    _CACHE = {"x": 1}
                return _CACHE

        def bump():
            with _LOCK:
                _STATS["built"] += 1
        """)
    assert _codes(fs) == []


def test_threads_env_read_in_thread_target_and_env_write(tmp_path):
    fs = _check_snippet(tmp_path, "lux_tpu/ops/bad_threads.py", """\
        import os
        import threading

        def spawn():
            def work():
                width = os.environ.get("LUX_WIDTH", "1")
                return int(width)
            t = threading.Thread(target=work)
            t.start()
            return t

        def force_cpu():
            os.environ["JAX_PLATFORMS"] = "cpu"
        """)
    codes = _codes(fs)
    assert "LUX-C003" in codes
    assert "LUX-C004" in codes


# ---------------------------------------------------------------------------
# policy fixtures
# ---------------------------------------------------------------------------


def test_policy_pickle_and_env_cast(tmp_path):
    fs = _check_snippet(tmp_path, "lux_tpu/ops/bad_policy.py", """\
        import os
        import pickle
        import numpy as np

        def load_plan(path):
            with open(path, "rb") as f:
                return pickle.load(f)

        def load_npz(path):
            return np.load(path, allow_pickle=True)

        def threads():
            return int(os.environ.get("LUX_THREADS", "1"))
        """)
    codes = _codes(fs)
    assert codes.count("LUX-P001") >= 2  # import + allow_pickle=True
    assert "LUX-P002" in codes


def test_policy_uint8_narrowing_outside_narrow_idx(tmp_path):
    fs = _check_snippet(tmp_path, "lux_tpu/ops/bad_narrow.py", """\
        import numpy as np

        def shrink(idx):
            return idx.astype(np.uint8)

        def _narrow_idx(a):
            assert a.max() < 128
            return a.astype(np.uint8)
        """)
    # `shrink` fires; the blessed _narrow_idx home does not
    assert _codes(fs) == ["LUX-P003"]


# ---------------------------------------------------------------------------
# lock-order fixtures
# ---------------------------------------------------------------------------


def test_lockorder_ab_ba_inversion(tmp_path):
    fs = _check_snippet(tmp_path, "lux_tpu/serve/bad_order.py", """\
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self._live_lock = threading.Lock()

            def op_delta(self):
                with self._live_lock:
                    with self._lock:
                        pass

            def op_commit(self):
                with self._lock:
                    with self._live_lock:
                        pass
        """)
    assert "LUX-L002" in _codes(fs)


def test_lockorder_consistent_order_clean(tmp_path):
    fs = _check_snippet(tmp_path, "lux_tpu/serve/good_order.py", """\
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self._live_lock = threading.Lock()

            def op_delta(self):
                with self._live_lock:
                    with self._lock:
                        pass

            def op_commit(self):
                with self._live_lock:
                    self._commit_locked()

            def _commit_locked(self):
                with self._lock:
                    pass
        """)
    assert not [c for c in _codes(fs) if c.startswith("LUX-L")]


def test_lockorder_reentrant_self_deadlock(tmp_path):
    fs = _check_snippet(tmp_path, "lux_tpu/serve/bad_reentry.py", """\
        import threading

        class Group:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    pass
        """)
    assert "LUX-L001" in _codes(fs)


def test_lockorder_rlock_reentry_clean(tmp_path):
    fs = _check_snippet(tmp_path, "lux_tpu/serve/good_reentry.py", """\
        import threading

        class Group:
            def __init__(self):
                self._lock = threading.RLock()

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    pass
        """)
    assert "LUX-L001" not in _codes(fs)


def test_lockorder_blocking_under_lock(tmp_path):
    fs = _check_snippet(tmp_path, "lux_tpu/serve/bad_block.py", """\
        import threading
        import time

        class Ctl:
            def __init__(self):
                self._lock = threading.Lock()

            def slow(self, thread, fut):
                with self._lock:
                    time.sleep(1.0)
                    thread.join()
                    fut.result(timeout=5)
        """)
    assert _codes(fs).count("LUX-L003") == 3


def test_lockorder_condition_wait_and_unheld_clean(tmp_path):
    fs = _check_snippet(tmp_path, "lux_tpu/serve/good_block.py", """\
        import threading
        import time

        class Ctl:
            def __init__(self):
                self._lock = threading.Lock()
                self._wake_cond = threading.Condition(self._lock)

            def wait_for_work(self):
                with self._wake_cond:
                    self._wake_cond.wait(1.0)

            def slow_outside(self, thread):
                with self._lock:
                    n = 1
                time.sleep(0.1)
                thread.join()
        """)
    assert "LUX-L003" not in _codes(fs)


def test_lockorder_unbalanced_acquire_release(tmp_path):
    fs = _check_snippet(tmp_path, "lux_tpu/serve/bad_split.py", """\
        import threading

        class Ctl:
            def __init__(self):
                self._lock = threading.Lock()

            def grab(self):
                self._lock.acquire()

            def drop(self):
                self._lock.release()
        """)
    assert _codes(fs).count("LUX-L004") == 2


def test_lockorder_ctx_manager_pair_exempt(tmp_path):
    fs = _check_snippet(tmp_path, "lux_tpu/serve/good_split.py", """\
        class Guard:
            def __init__(self, lock):
                self._inner_lock = lock

            def __enter__(self):
                self._inner_lock.acquire()
                return self

            def __exit__(self, *exc):
                self._inner_lock.release()
        """)
    assert "LUX-L004" not in _codes(fs)


# ---------------------------------------------------------------------------
# suppression machinery
# ---------------------------------------------------------------------------


def test_inline_suppression_roundtrip(tmp_path):
    src = """\
        import pickle  # luxcheck: disable=LUX-P001 -- fixture: legacy tool kept for migration
        """
    assert _codes(_check_snippet(tmp_path, "lux_tpu/a.py", src)) == []


def test_inline_suppression_previous_line(tmp_path):
    src = """\
        # luxcheck: disable=LUX-P001 -- fixture: legacy tool kept for migration
        import pickle
        """
    assert _codes(_check_snippet(tmp_path, "lux_tpu/b.py", src)) == []


def test_inline_suppression_requires_justification(tmp_path):
    src = """\
        import pickle  # luxcheck: disable=LUX-P001
        """
    codes = _codes(_check_snippet(tmp_path, "lux_tpu/c.py", src))
    # unjustified: the original finding SURVIVES and the bare
    # suppression is itself flagged
    assert "LUX-P001" in codes
    assert "LUX-X001" in codes


def test_inline_suppression_wrong_code_does_not_cover(tmp_path):
    src = """\
        import pickle  # luxcheck: disable=LUX-D001 -- wrong code entirely here
        """
    codes = _codes(_check_snippet(tmp_path, "lux_tpu/d.py", src))
    assert "LUX-P001" in codes


def test_threads_submit_data_args_not_targets(tmp_path):
    """Only the CALLABLE position of submit/map marks a thread target —
    a data argument sharing a function's name must not make that
    function's env reads LUX-C003 (a lint FP aborts the chip gate)."""
    fs = _check_snippet(tmp_path, "lux_tpu/ops/submit_args.py", """\
        import os
        from concurrent import futures

        def work(x):
            return x + 1

        def helper():
            return os.environ.get("LUX_MODE", "a")

        def spawn(executor):
            return executor.submit(work, helper)
        """)
    assert _codes(fs) == []


def test_suppression_in_docstring_is_inert(tmp_path):
    """The suppression syntax QUOTED in a docstring (docs showing the
    feature) must neither register a live suppression nor emit a
    phantom LUX-X001 — only real comments count (tokenize-based scan)."""
    fs = _check_snippet(tmp_path, "lux_tpu/doc_sup.py", '''\
        """Docs: suppress with  # luxcheck: disable=LUX-P001
        or with a reason:  # luxcheck: disable=LUX-P001 -- why it is safe
        """
        import pickle
        ''')
    # the docstring registers nothing: no X001, and the real finding on
    # line 4 survives (the line-2 example must not cover line 3's next
    # line either)
    assert _codes(fs) == ["LUX-P001"]


def test_overlapping_targets_scan_once(tmp_path):
    """--all plus an explicit subpath must not double-report (duplicates
    would also break one-shot baseline consumption)."""
    rel = "lux_tpu/dup.py"
    (tmp_path / "lux_tpu").mkdir(parents=True, exist_ok=True)
    (tmp_path / rel).write_text("import pickle\n")
    findings = check_paths(["lux_tpu", rel, "lux_tpu"], str(tmp_path))
    assert _codes(findings) == ["LUX-P001"]


def test_baseline_roundtrip(tmp_path):
    rel = "lux_tpu/base.py"
    full = tmp_path / rel
    full.parent.mkdir(parents=True, exist_ok=True)
    full.write_text("import pickle\n")
    findings = check_paths([rel], str(tmp_path))
    assert _codes(findings) == ["LUX-P001"]
    fp = findings[0].fingerprint()
    baseline = tmp_path / "baseline.txt"
    baseline.write_text(
        f"{rel}:LUX-P001:{fp}  # fixture: justified baseline entry\n")
    assert check_paths([rel], str(tmp_path),
                       baseline_path=str(baseline)) == []
    # unjustified entry: does not suppress, and is flagged itself
    baseline.write_text(f"{rel}:LUX-P001:{fp}\n")
    codes = _codes(check_paths([rel], str(tmp_path),
                               baseline_path=str(baseline)))
    assert "LUX-P001" in codes and "LUX-X002" in codes
    # stale entry (code fixed, entry left behind) is a finding
    full.write_text("x = 1\n")
    baseline.write_text(
        f"{rel}:LUX-P001:{fp}  # fixture: now-stale baseline entry\n")
    codes = _codes(check_paths([rel], str(tmp_path),
                               baseline_path=str(baseline)))
    assert codes == ["LUX-X003"]


def test_fingerprint_tracks_text_not_line(tmp_path):
    """Adding lines above a finding must not invalidate its baseline
    entry (fingerprints hash the line TEXT, not the number)."""
    a = Finding(path="p.py", line=5, code="LUX-P001", col=0,
                message="m", text="import pickle")
    b = Finding(path="p.py", line=50, code="LUX-P001", col=0,
                message="m", text="import pickle")
    assert a.fingerprint() == b.fingerprint()
    c = Finding(path="p.py", line=5, code="LUX-P001", col=0,
                message="m", text="import dill")
    assert a.fingerprint() != c.fingerprint()


def test_unparsable_file_is_a_finding(tmp_path):
    codes = _codes(_check_snippet(tmp_path, "lux_tpu/broken.py",
                                  "def broken(:\n"))
    assert codes == ["LUX-X000"]


def test_missing_target_is_a_finding(tmp_path):
    """A typo'd/renamed target must FAIL the gate — 'clean' after
    scanning zero files is how a preflight silently stops
    preflighting."""
    findings = check_paths(["lux_tpu/nonexistent_dir", "typo.py"],
                           str(tmp_path))
    assert _codes(findings) == ["LUX-X000", "LUX-X000"]
    assert "does not exist" in findings[0].message


def test_baseline_entry_is_one_shot(tmp_path):
    """Fingerprints hash line TEXT, so identical lines collide: one
    justified entry must suppress exactly ONE occurrence, never a
    second (possibly future) identical line."""
    rel = "lux_tpu/twice.py"
    full = tmp_path / rel
    full.parent.mkdir(parents=True, exist_ok=True)
    full.write_text("import pickle\nimport pickle\n")
    findings = check_paths([rel], str(tmp_path))
    assert _codes(findings) == ["LUX-P001", "LUX-P001"]
    fp = findings[0].fingerprint()
    assert fp == findings[1].fingerprint()  # the collision being guarded
    baseline = tmp_path / "baseline.txt"
    baseline.write_text(
        f"{rel}:LUX-P001:{fp}  # fixture: covers only one occurrence\n")
    codes = _codes(check_paths([rel], str(tmp_path),
                               baseline_path=str(baseline)))
    assert codes == ["LUX-P001"]
    # two entries cover both; a third is stale
    baseline.write_text(
        f"{rel}:LUX-P001:{fp}  # fixture: first occurrence justified\n"
        f"{rel}:LUX-P001:{fp}  # fixture: second occurrence justified\n"
        f"{rel}:LUX-P001:{fp}  # fixture: third entry must go stale\n")
    codes = _codes(check_paths([rel], str(tmp_path),
                               baseline_path=str(baseline)))
    assert codes == ["LUX-X003"]


# ---------------------------------------------------------------------------
# the repo gate
# ---------------------------------------------------------------------------


def test_repo_is_luxcheck_clean():
    """The shipped package/tools/bench are clean under the full checker
    set + the checked-in baseline — the tier-1 twin of chip_day's
    step -3 preflight.  A finding here means: fix it, or suppress it
    WITH a justification (docs/ANALYSIS.md)."""
    baseline = os.path.join(REPO, "tools", "luxcheck_baseline.txt")
    findings = check_paths(list(DEFAULT_TARGETS), REPO,
                           baseline_path=baseline)
    assert findings == [], "\n".join(f.format() for f in findings)


def test_luxcheck_cli_clean_and_jax_free():
    """`python tools/luxcheck.py --all` exits 0 on the repo, and the
    preflight never imports jax (it must run on a host whose jax/tunnel
    is wedged) — asserted via an import tripwire."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    code = (
        "import builtins, runpy, sys\n"
        "real = builtins.__import__\n"
        "def guard(name, *a, **k):\n"
        "    assert not name.startswith('jax'), 'luxcheck imported jax'\n"
        "    return real(name, *a, **k)\n"
        "builtins.__import__ = guard\n"
        "sys.argv = ['luxcheck.py', '--all']\n"
        "try:\n"
        "    runpy.run_path(%r, run_name='__main__')\n"
        "except SystemExit as e:\n"
        "    sys.exit(e.code)\n" % os.path.join(REPO, "tools", "luxcheck.py")
    )
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, cwd=REPO,
                          timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "clean" in proc.stdout


def test_every_family_has_a_checker():
    fams = {c.family for c in ALL_CHECKERS}
    assert fams == {"tracing-safety", "determinism", "thread-safety",
                    "policy", "observability", "lock-order",
                    "guarded-by", "resource-lifecycle"}


# ---------------------------------------------------------------------------
# env_int (the LUX-P002 contract)
# ---------------------------------------------------------------------------


def test_env_int(monkeypatch):
    from lux_tpu.utils.config import env_int

    monkeypatch.delenv("LUX_TEST_KNOB", raising=False)
    assert env_int("LUX_TEST_KNOB") is None
    assert env_int("LUX_TEST_KNOB", 7) == 7
    monkeypatch.setenv("LUX_TEST_KNOB", " 12 ")
    assert env_int("LUX_TEST_KNOB", 7) == 12
    monkeypatch.setenv("LUX_TEST_KNOB", "")
    assert env_int("LUX_TEST_KNOB", 7) == 7
    monkeypatch.setenv("LUX_TEST_KNOB", "twelve")
    with pytest.raises(ValueError, match="LUX_TEST_KNOB"):
        env_int("LUX_TEST_KNOB")
    monkeypatch.setenv("LUX_TEST_KNOB", "0")
    with pytest.raises(ValueError, match=">= 1"):
        env_int("LUX_TEST_KNOB", minimum=1)
    monkeypatch.setenv("LUX_TEST_KNOB", "999")
    with pytest.raises(ValueError, match="<= 256"):
        env_int("LUX_TEST_KNOB", maximum=256)
