"""Feature-dimension (tensor-parallel) CF sharding: the 2-D
(parts × feat) mesh engine (parallel/feat.py) — the FEAT_AXIS promised
in parallel/mesh.py.  Parity vs the 1-D engines, k resident parts,
bf16 state, CLI routing, and flag validation."""
import numpy as np
import pytest

import jax

from lux_tpu.apps import colfilter as cf_app
from lux_tpu.engine import pull
from lux_tpu.graph import generate
from lux_tpu.graph.shards import build_pull_shards
from lux_tpu.models import colfilter as cf
from lux_tpu.parallel import feat


@pytest.fixture(scope="module")
def g():
    return generate.rmat(10, 8, seed=9, weighted=True)


@pytest.fixture(scope="module")
def setup(g):
    shards = build_pull_shards(g, 4)
    prog = cf.CFProgram()
    s0 = pull.init_state(prog, jax.tree.map(np.asarray, shards.arrays))
    ref = shards.scatter_to_global(
        np.asarray(
            pull.run_pull_fixed(
                prog, shards.spec, shards.arrays, s0, 5, method="scan"
            )
        )
    )
    return shards, prog, s0, ref


def test_feat_matches_single_device(setup):
    shards, prog, s0, ref = setup
    mesh = feat.make_mesh_feat(4, 2)
    out = feat.run_cf_feat_dist(
        prog, shards.spec, shards.arrays, s0, 5, mesh, method="scan"
    )
    np.testing.assert_allclose(
        shards.scatter_to_global(np.asarray(out)), ref, rtol=1e-6, atol=1e-7
    )


def test_feat_resident_parts(setup):
    """P=4 parts on a 2x2 mesh: k=2 resident parts per device."""
    shards, prog, s0, ref = setup
    mesh = feat.make_mesh_feat(2, 2)
    out = feat.run_cf_feat_dist(
        prog, shards.spec, shards.arrays, s0, 5, mesh, method="scan"
    )
    np.testing.assert_allclose(
        shards.scatter_to_global(np.asarray(out)), ref, rtol=1e-6, atol=1e-7
    )


def test_feat_four_way_split(setup):
    """K=20 over 4 feat shards (Kf=5), 2 parts."""
    shards2 = build_pull_shards(
        generate.rmat(10, 8, seed=9, weighted=True), 2
    )
    prog = cf.CFProgram()
    s0 = pull.init_state(prog, jax.tree.map(np.asarray, shards2.arrays))
    ref = shards2.scatter_to_global(
        np.asarray(
            pull.run_pull_fixed(
                prog, shards2.spec, shards2.arrays, s0, 4, method="scan"
            )
        )
    )
    mesh = feat.make_mesh_feat(2, 4)
    out = feat.run_cf_feat_dist(
        prog, shards2.spec, shards2.arrays, s0, 4, mesh, method="scan"
    )
    np.testing.assert_allclose(
        shards2.scatter_to_global(np.asarray(out)), ref, rtol=1e-6,
        atol=1e-7,
    )


def test_feat_bf16_state(setup):
    """bf16 storage composes with feat sharding (f32 error math)."""
    shards, _, _, _ = setup
    prog = cf.CFProgram(dtype="bfloat16")
    s0 = pull.init_state(prog, jax.tree.map(np.asarray, shards.arrays))
    mesh = feat.make_mesh_feat(4, 2)
    out = feat.run_cf_feat_dist(
        prog, shards.spec, shards.arrays, s0, 5, mesh, method="scan"
    )
    ref = pull.run_pull_fixed(
        prog, shards.spec, shards.arrays, s0, 5, method="scan"
    )
    np.testing.assert_array_equal(
        np.asarray(out, np.float32), np.asarray(ref, np.float32)
    )


def test_feat_rerun_bitwise(setup):
    shards, prog, s0, _ = setup
    mesh = feat.make_mesh_feat(4, 2)
    a = feat.run_cf_feat_dist(
        prog, shards.spec, shards.arrays, s0, 5, mesh, method="scan"
    )
    b = feat.run_cf_feat_dist(
        prog, shards.spec, shards.arrays, s0, 5, mesh, method="scan"
    )
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_feat_ring_matches_allgather_feat():
    """The ring × feat composition (both big axes sharded): bitwise-level
    agreement with the 1-D engine on a ratings graph."""
    from lux_tpu.parallel import ring

    g = generate.bipartite_ratings(256, 256, 4096, seed=9)
    shards = build_pull_shards(g, 4)
    rs = ring.build_ring_shards(g, 4, pull=shards)
    prog = cf.CFProgram(gamma=1e-3)
    s0 = pull.init_state(prog, jax.tree.map(np.asarray, shards.arrays))
    ref = shards.scatter_to_global(
        np.asarray(
            pull.run_pull_fixed(
                prog, shards.spec, shards.arrays, s0, 4, method="scan"
            )
        )
    )
    # signal guard: the recurrence must move the state beyond tolerance
    assert np.abs(ref - np.sqrt(1 / 20)).max() > 1e-3
    for mesh in (feat.make_mesh_feat(4, 2), feat.make_mesh_feat(2, 2)):
        out = feat.run_cf_feat_ring(prog, rs, s0, 4, mesh, method="scan")
        np.testing.assert_allclose(
            shards.scatter_to_global(np.asarray(out)), ref,
            rtol=1e-5, atol=1e-6,
        )


def test_feat_ring_bf16_matches_single_device():
    from lux_tpu.parallel import ring

    g = generate.bipartite_ratings(256, 256, 4096, seed=9)
    shards = build_pull_shards(g, 4)
    rs = ring.build_ring_shards(g, 4, pull=shards)
    prog = cf.CFProgram(gamma=1e-3, dtype="bfloat16")
    s0 = pull.init_state(prog, jax.tree.map(np.asarray, shards.arrays))
    out = feat.run_cf_feat_ring(
        prog, rs, s0, 3, feat.make_mesh_feat(4, 2), method="scan"
    )
    ref = pull.run_pull_fixed(
        prog, shards.spec, shards.arrays, s0, 3, method="scan"
    )
    np.testing.assert_array_equal(
        np.asarray(out, np.float32), np.asarray(ref, np.float32)
    )


CLI = ["--rmat-scale", "9", "--seed", "4", "-ni", "4"]


def test_cli_feat_matches_1d(capsys):
    assert cf_app.main(CLI + ["-ng", "4", "--distributed",
                              "--feat-shards", "2"]) == 0
    rmse_2d = [ln for ln in capsys.readouterr().out.splitlines()
               if "RMSE" in ln]
    assert cf_app.main(CLI + ["-ng", "4", "--distributed"]) == 0
    rmse_1d = [ln for ln in capsys.readouterr().out.splitlines()
               if "RMSE" in ln]
    assert rmse_2d == rmse_1d
    # ring x feat from the CLI reports the same training metric
    assert cf_app.main(CLI + ["-ng", "4", "--distributed",
                              "--feat-shards", "2",
                              "--exchange", "ring"]) == 0
    rmse_ring = [ln for ln in capsys.readouterr().out.splitlines()
                 if "RMSE" in ln]
    assert rmse_ring == rmse_1d


@pytest.mark.parametrize(
    "extra,match",
    [
        (["--feat-shards", "2"], "requires --distributed"),
        (["--feat-shards", "2", "--distributed", "--exchange", "scatter"],
         "--exchange scatter"),
        (["--feat-shards", "3", "--distributed"], "must divide"),
        (["--feat-shards", "10", "-ng", "4", "--distributed"],
         "at least that many devices"),
    ],
)
def test_cli_feat_rejections(extra, match):
    with pytest.raises(SystemExit, match=match):
        cf_app.main(CLI + extra)


def test_cli_feat_k_resident_parts(capsys):
    """-ng 8 --feat-shards 2 on 8 devices: 4 parts slots x 2 feat, two
    parts resident per device — same RMSE as the 1-D run."""
    assert cf_app.main(CLI + ["-ng", "8", "--distributed",
                              "--feat-shards", "2"]) == 0
    rmse_k = [ln for ln in capsys.readouterr().out.splitlines()
              if "RMSE" in ln]
    assert cf_app.main(CLI + ["-ng", "8", "--distributed"]) == 0
    rmse_1d = [ln for ln in capsys.readouterr().out.splitlines()
               if "RMSE" in ln]
    assert rmse_k == rmse_1d


def test_cli_feat_rejected_for_scalar_state_apps():
    from lux_tpu.apps import pagerank as pr_app

    with pytest.raises(SystemExit, match="colfilter only"):
        pr_app.main(["--rmat-scale", "8", "-ng", "2", "--distributed",
                     "--feat-shards", "2"])
