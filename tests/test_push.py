"""Push engine: SSSP/CC vs host oracles, sparse/dense mode equivalence,
overflow fallback, distributed equivalence."""
import dataclasses

import numpy as np
import pytest

from lux_tpu.engine import push
from lux_tpu.graph import generate
from lux_tpu.graph.csc import from_edge_list
from lux_tpu.graph.push_shards import build_push_shards
from lux_tpu.models import components, sssp
from lux_tpu.parallel import mesh as mesh_lib


def test_push_shards_csr_consistent():
    g = generate.rmat(8, 6, seed=30, weighted=True)
    sh = build_push_shards(g, 4)
    dst_of = g.dst_of_edges()
    for p in range(4):
        vlo, vhi = int(sh.cuts[p]), int(sh.cuts[p + 1])
        # every real CSR edge (uniq_src[row], dst) must be a real CSC edge
        uniq = sh.parrays.uniq_src[p]
        rp = sh.parrays.csr_row_ptr[p]
        got = []
        for r in range(sh.pspec.u_pad):
            if uniq[r] == np.iinfo(np.int32).max:
                continue
            for e in range(rp[r], rp[r + 1]):
                got.append((uniq[r], sh.parrays.csr_dst_local[p, e] + vlo))
        sel = (dst_of >= vlo) & (dst_of < vhi)
        expect = sorted(zip(g.col_idx[sel].tolist(), dst_of[sel].tolist()))
        assert sorted(got) == expect


@pytest.mark.parametrize("num_parts", [1, 3])
def test_sssp_matches_bfs(num_parts):
    g = generate.rmat(9, 8, seed=31)
    got = sssp.sssp(g, start=0, num_parts=num_parts)
    want = sssp.bfs_reference(g, 0)
    np.testing.assert_array_equal(got, want)
    assert sssp.check_distances(g, got) == 0


def test_sssp_path_graph():
    g = generate.path_graph(300)
    got = sssp.sssp(g, start=0)
    np.testing.assert_array_equal(got, np.arange(300))


def test_sssp_unreachable():
    # two disjoint chains; start in first — second stays INF (== nv)
    n = 64
    src = np.concatenate([np.arange(0, 31), np.arange(32, 63)])
    dst = src + 1
    g = from_edge_list(src, dst, n)
    got = sssp.sssp(g, start=0)
    np.testing.assert_array_equal(got[:32], np.arange(32))
    assert np.all(got[32:] == n)


def test_sssp_forced_sparse_and_dense_agree():
    g = generate.rmat(9, 8, seed=33)
    want = sssp.bfs_reference(g, 5)
    # force-dense: threshold denominator so large frontier always > nv/den
    sh_dense = build_push_shards(g, 1)
    sh_dense.pspec = dataclasses.replace(sh_dense.pspec, pull_threshold_den=g.nv + 1)
    prog = sssp.SSSPProgram(nv=g.nv, start=5)
    dense_final, _, _ = push.run_push(prog, sh_dense)
    np.testing.assert_array_equal(sh_dense.scatter_to_global(np.asarray(dense_final)), want)
    # force-sparse: huge threshold denominator -> frontier never > nv/1;
    # big queue and edge buffer so no overflow fallback
    sh_sparse = build_push_shards(g, 1, f_cap=sh_dense.spec.nv_pad,
                                  e_sp=sh_dense.spec.e_pad)
    sh_sparse.pspec = dataclasses.replace(sh_sparse.pspec, pull_threshold_den=1)
    sparse_final, _, _ = push.run_push(prog, sh_sparse)
    np.testing.assert_array_equal(
        sh_sparse.scatter_to_global(np.asarray(sparse_final)), want
    )


def test_sssp_overflow_falls_back_dense():
    """Tiny queue capacity: frontier overflows, engine must stay correct."""
    g = generate.rmat(9, 8, seed=34)
    sh = build_push_shards(g, 1, f_cap=128, e_sp=256)
    prog = sssp.SSSPProgram(nv=g.nv, start=0)
    final, _, _ = push.run_push(prog, sh)
    np.testing.assert_array_equal(
        sh.scatter_to_global(np.asarray(final)), sssp.bfs_reference(g, 0)
    )


def test_cc_push_matches_pull():
    g = generate.rmat(9, 6, seed=35)
    pull_labels = components.connected_components(g)
    push_labels = components.connected_components_push(g)
    np.testing.assert_array_equal(push_labels, pull_labels)
    assert components.check_labels(g, push_labels) == 0


def test_cc_fixpoint_oracle():
    """Labels must be the max-label fixpoint: label[v] = max(v, labels of
    in-neighbors) iterated to convergence on the host."""
    g = generate.uniform_random(200, 1500, seed=36)
    labels = components.connected_components_push(g)
    want = np.arange(g.nv)
    dst = g.dst_of_edges()
    while True:
        new = want.copy()
        np.maximum.at(new, dst, want[g.col_idx])
        if np.array_equal(new, want):
            break
        want = new
    np.testing.assert_array_equal(labels, want)


def test_sssp_dist_matches_single():
    g = generate.rmat(9, 8, seed=37)
    mesh8 = mesh_lib.make_mesh(8)
    single = sssp.sssp(g, start=0, num_parts=1)
    multi = sssp.sssp(g, start=0, num_parts=8, mesh=mesh8)
    np.testing.assert_array_equal(multi, single)


def test_weighted_sssp_extension():
    scipy_sparse = pytest.importorskip("scipy.sparse")
    from scipy.sparse.csgraph import dijkstra

    g = generate.uniform_random(128, 1024, seed=38, weighted=True, max_weight=9)
    got = sssp.sssp(g, start=0, weighted=True)
    # scipy sums duplicate (src, dst) entries; the engine relaxes each
    # parallel edge independently (min wins) — dedupe to min for the oracle
    dst = g.dst_of_edges()
    order = np.lexsort((g.weights, g.col_idx, dst))
    s, d, w = g.col_idx[order], dst[order], g.weights[order]
    first = np.ones(g.ne, bool)
    first[1:] = (s[1:] != s[:-1]) | (d[1:] != d[:-1])
    A = scipy_sparse.csr_matrix(
        (w[first], (s[first], d[first])), shape=(g.nv, g.nv)
    )  # rows=src for dijkstra's directed traversal
    want = dijkstra(A, directed=True, indices=0, unweighted=False)
    finite = np.isfinite(want)
    np.testing.assert_array_equal(got[finite], want[finite].astype(np.int64))
    assert np.all(got[~finite] == sssp.inf_value(g.nv, weighted=True))
    assert sssp.check_distances(g, got, weighted=True) == 0

def test_run_push_donate_twin():
    """The push-side ``donate=`` contract (pull parity, luxaudit LUX-J2):
    the donating loop is bitwise-identical to the default, consumes the
    carry it is handed, and raises no donation warnings on this backend."""
    import warnings

    import jax
    import jax.numpy as jnp

    g = generate.rmat(8, 8, seed=31)
    sh = build_push_shards(g, 2)
    prog = sssp.SSSPProgram(nv=g.nv, start=0)
    ref_state, ref_it, ref_edges = push.run_push(prog, sh)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        state, it, edges = push.run_push(prog, sh, donate=True)
        jax.block_until_ready(state)
        donation_warnings = [str(i.message) for i in w
                             if "donat" in str(i.message).lower()]
    assert donation_warnings == [], donation_warnings
    np.testing.assert_array_equal(np.asarray(ref_state), np.asarray(state))
    assert int(it) == int(ref_it)
    assert push.edges_total(edges) == push.edges_total(ref_edges)
    # the donating loop really consumes its carry (single copy in HBM)
    loop = push.compile_push_chunk(prog, sh.pspec, sh.spec, "scan",
                                   donate=True)
    arrays, parrays, carry0 = push.push_init(prog, sh)
    out = loop(arrays, parrays, carry0, jnp.int32(50))
    jax.block_until_ready(out.state)
    with pytest.raises((RuntimeError, ValueError)):
        jnp.sum(carry0.state).block_until_ready()
