"""Routed expand (ops/expand.py): the pull LOAD phase as lane shuffles.

Pins (1) the fill-forward hierarchy against its oracle, (2) the full
expand against the direct gather BITWISE on real-slot values, (3) the
engine integration: run_pull_fixed with route= must be bitwise equal to
the direct-gather engine on every app/reduce combination tried, at P=1
and vmapped P>1.
"""
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from lux_tpu.ops import expand as E


def _dev(arrays):
    return tuple(jnp.asarray(a) for a in arrays)


@pytest.mark.parametrize("n", [128, 1024, 4096, 1 << 15])
def test_ff_oracle(n, rng):
    # random run structure: heads at random ascending slots
    nheads = max(1, n // 7)
    heads = np.unique(
        np.concatenate([[0], rng.integers(0, n, nheads)])
    ).astype(np.int64)
    h = heads[np.searchsorted(heads, np.arange(n), side="right") - 1]
    static, arrays = E.plan_ff(h)
    x = rng.standard_normal(n).astype(np.float32)
    got = np.asarray(
        E.apply_ff(jnp.asarray(x), static, _dev(arrays), interpret=True))
    np.testing.assert_array_equal(got, E.apply_ff_np(x, h))


@pytest.mark.parametrize(
    "e_pad,m,state_size",
    [(512, 400, 300), (1024, 1024, 128), (2048, 1500, 2048),
     (256, 0, 100), (16384, 12000, 4096)],
)
def test_expand_matches_gather(e_pad, m, state_size, rng):
    src_pos = np.zeros(e_pad, np.int32)
    src_pos[:m] = rng.integers(0, state_size, m)
    static, arrays = E.plan_expand(src_pos, m, state_size)
    state = rng.standard_normal(state_size).astype(np.float32)
    got = np.asarray(
        E.apply_expand(jnp.asarray(state), static, _dev(arrays),
                       interpret=True))
    # real slots must match the direct gather bitwise; padding slots
    # carry junk by contract (the engine only reads them through
    # row_ptr / the dst_local sentinel, same as the direct layout)
    np.testing.assert_array_equal(got[:m], state[src_pos[:m]])
    assert got.shape == (e_pad,)


def test_expand_statics_shared_across_parts(rng):
    """Parts of one graph share e_pad and state size, so their
    ExpandStatic must be identical — the vmapped engine relies on it."""
    e_pad, S = 1024, 512
    statics = []
    for _ in range(3):
        m = int(rng.integers(1, e_pad))
        src_pos = np.zeros(e_pad, np.int32)
        src_pos[:m] = rng.integers(0, S, m)
        s, _ = E.plan_expand(src_pos, m, S)
        statics.append(s)
    assert statics[0] == statics[1] == statics[2]


def _pull_both_ways(graph, parts, prog_cls, iters, **prog_kw):
    from lux_tpu.engine import pull
    from lux_tpu.graph.shards import build_pull_shards

    shards = build_pull_shards(graph, parts)
    prog = prog_cls(**prog_kw) if prog_kw.pop("_no_nv", False) else \
        prog_cls(nv=shards.spec.nv, **prog_kw)
    arrays = jax.tree.map(jnp.asarray, shards.arrays)
    s0 = pull.init_state(prog, arrays)
    direct = pull.run_pull_fixed(prog, shards.spec, arrays, s0, iters,
                                 method="scan")
    route = E.plan_expand_shards(shards)
    routed = pull.run_pull_fixed(prog, shards.spec, arrays, s0, iters,
                                 method="scan", route=route)
    return np.asarray(direct), np.asarray(routed)


@pytest.mark.parametrize("parts", [1, 3])
def test_engine_pagerank_bitwise(parts):
    from lux_tpu.graph import generate
    from lux_tpu.models.pagerank import PageRankProgram

    g = generate.rmat(8, 8, seed=3)
    direct, routed = _pull_both_ways(g, parts, PageRankProgram, 5)
    np.testing.assert_array_equal(direct, routed)


def test_engine_components_max_reduce_bitwise():
    """int32 state + max reduce through the routed load (the routed
    passes are dtype-agnostic moves)."""
    from lux_tpu.graph import generate
    from lux_tpu.models.components import MaxLabelProgram

    g = generate.rmat(8, 8, seed=4)
    direct, routed = _pull_both_ways(g, 2, MaxLabelProgram, 8, _no_nv=True)
    np.testing.assert_array_equal(direct, routed)


def test_engine_fused_pagerank_close():
    """Fused routed pull (load + reduce replaced): sum association is
    method-specific, so compare against the direct engine numerically."""
    from lux_tpu.graph import generate
    from lux_tpu.engine import pull
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.models.pagerank import PageRankProgram

    g = generate.rmat(9, 8, seed=5)
    shards = build_pull_shards(g, 1)
    prog = PageRankProgram(nv=shards.spec.nv)
    arrays = jax.tree.map(jnp.asarray, shards.arrays)
    s0 = pull.init_state(prog, arrays)
    direct = pull.run_pull_fixed(prog, shards.spec, arrays, s0, 6,
                                 method="scan")
    fused = E.plan_fused_shards(shards, "sum")
    routed = pull.run_pull_fixed(prog, shards.spec, arrays, s0, 6,
                                 method="scan", route=fused)
    np.testing.assert_allclose(np.asarray(routed), np.asarray(direct),
                               rtol=1e-5, atol=1e-7)
    # determinism: same program reruns bitwise
    again = pull.run_pull_fixed(prog, shards.spec, arrays, s0, 6,
                                method="scan", route=fused)
    np.testing.assert_array_equal(np.asarray(routed), np.asarray(again))


def test_engine_fused_components_bitwise():
    """max is associative-commutative exactly — fused must be BITWISE
    equal to the direct engine."""
    from lux_tpu.graph import generate
    from lux_tpu.engine import pull
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.models.components import MaxLabelProgram

    g = generate.rmat(9, 8, seed=6)
    shards = build_pull_shards(g, 1)
    prog = MaxLabelProgram()
    arrays = jax.tree.map(jnp.asarray, shards.arrays)
    s0 = pull.init_state(prog, arrays)
    direct = pull.run_pull_fixed(prog, shards.spec, arrays, s0, 8,
                                 method="scan")
    fused = E.plan_fused_shards(shards, "max")
    routed = pull.run_pull_fixed(prog, shards.spec, arrays, s0, 8,
                                 method="scan", route=fused)
    np.testing.assert_array_equal(np.asarray(direct), np.asarray(routed))


def test_fused_multipart_template():
    """Parts share one FusedStatic via the group template; the vmapped
    engine batches them and matches the direct engine."""
    from lux_tpu.graph import generate
    from lux_tpu.engine import pull
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.models.pagerank import PageRankProgram

    g = generate.rmat(8, 8, seed=7)
    shards = build_pull_shards(g, 2)
    static, arrays = E.plan_fused_shards(shards, "sum")
    assert arrays[0].shape[0] == 2
    prog = PageRankProgram(nv=shards.spec.nv)
    dev = jax.tree.map(jnp.asarray, shards.arrays)
    s0 = pull.init_state(prog, dev)
    direct = pull.run_pull_fixed(prog, shards.spec, dev, s0, 5,
                                 method="scan")
    routed = pull.run_pull_fixed(prog, shards.spec, dev, s0, 5,
                                 method="scan", route=(static, arrays))
    np.testing.assert_allclose(np.asarray(routed), np.asarray(direct),
                               rtol=1e-5, atol=1e-7)


def test_fused_distributed_bitwise_vs_single():
    """Fused routed pull under shard_map (8 virtual devices) matches the
    single-device fused engine bitwise (same plans, same association)."""
    from lux_tpu.graph import generate
    from lux_tpu.engine import pull
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.models.pagerank import PageRankProgram
    from lux_tpu.parallel import dist, mesh as mesh_lib

    g = generate.rmat(10, 8, seed=11)
    shards = build_pull_shards(g, 8)
    prog = PageRankProgram(nv=shards.spec.nv)
    dev = jax.tree.map(jnp.asarray, shards.arrays)
    s0 = pull.init_state(prog, dev)
    fused = E.plan_fused_shards(shards, "sum")
    single = pull.run_pull_fixed(prog, shards.spec, dev, s0, 5,
                                 method="scan", route=fused)
    mesh = mesh_lib.make_mesh(8)
    dist_out = dist.run_pull_fixed_dist(
        prog, shards.spec, shards.arrays, s0, 5, mesh, method="scan",
        route=fused)
    np.testing.assert_array_equal(np.asarray(single), np.asarray(dist_out))


def test_cli_route_gather():
    """--route-gather on the pagerank CLI: expand is bitwise vs direct
    (same top ranks), fused passes -check, and the misuse guards fire."""
    import subprocess, sys
    from tests.conftest import forced_cpu_env

    env = forced_cpu_env()
    base = [sys.executable, "-m", "lux_tpu.apps.pagerank",
            "--rmat-scale", "8", "-ni", "4", "-check"]
    for extra in ([], ["--route-gather"], ["--route-gather", "fused"]):
        r = subprocess.run(base + extra, capture_output=True, text=True,
                           env=env, timeout=300)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "[PASS]" in r.stdout
    # both modes run --distributed on the allgather exchange
    for mode in ([], ["fused"]):
        ok_dist = subprocess.run(
            base + ["--route-gather", *mode, "--distributed", "-ng", "2"],
            capture_output=True, text=True, env=env, timeout=300)
        assert ok_dist.returncode == 0, ok_dist.stdout + ok_dist.stderr
    # every pull layout routes in expand mode now (allgather, ring,
    # scatter buckets, edge-sharded chunks); fused stays allgather-only
    for extra2 in (["--exchange", "ring"], ["--exchange", "scatter"]):
        ok = subprocess.run(
            base + ["--route-gather", "--distributed", "-ng", "2",
                    *extra2],
            capture_output=True, text=True, env=env, timeout=300)
        assert ok.returncode == 0, ok.stdout + ok.stderr
    ok2 = subprocess.run(
        base + ["--route-gather", "--distributed", "-ng", "4",
                "--edge-shards", "2"],
        capture_output=True, text=True, env=env, timeout=300)
    assert ok2.returncode == 0, ok2.stdout + ok2.stderr
    bad = subprocess.run(
        base + ["--route-gather", "fused", "--distributed", "-ng", "2",
                "--exchange", "ring"],
        capture_output=True, text=True, env=env, timeout=300)
    assert bad.returncode != 0


@pytest.mark.parametrize("devices", [8, 4])
def test_distributed_routed_expand_bitwise(devices):
    """Routed expand under shard_map: bitwise vs the direct distributed
    gather at P == D (8) and k-resident P > D (8 parts on 4)."""
    from lux_tpu.engine import pull
    from lux_tpu.graph import generate
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.models.pagerank import PageRankProgram
    from lux_tpu.parallel import dist, mesh as mesh_lib

    mesh = mesh_lib.make_mesh(devices)
    g = generate.rmat(10, 8, seed=9)
    shards = build_pull_shards(g, 8)
    prog = PageRankProgram(nv=shards.spec.nv)
    arrays = jax.tree.map(jnp.asarray, shards.arrays)
    s0 = pull.init_state(prog, arrays)
    route = E.plan_expand_shards(shards)
    direct = dist.run_pull_fixed_dist(
        prog, shards.spec, shards.arrays, s0, 5, mesh, method="scan")
    routed = dist.run_pull_fixed_dist(
        prog, shards.spec, shards.arrays, s0, 5, mesh, method="scan",
        route=route)
    np.testing.assert_array_equal(np.asarray(direct), np.asarray(routed))


def test_push_dense_rounds_routed_bitwise():
    """Routed expand in the push engine's dense rounds: bitwise state,
    identical round and exact-edge counters, on SSSP and CC."""
    from lux_tpu.engine import push
    from lux_tpu.graph import generate
    from lux_tpu.graph.push_shards import build_push_shards
    from lux_tpu.models.sssp import SSSPProgram
    from lux_tpu.models.components import MaxLabelProgram

    g = generate.rmat(9, 8, seed=3)
    shards = build_push_shards(g, 2)
    route = E.plan_expand_shards(shards)
    for prog in (SSSPProgram(nv=g.nv, start=1), MaxLabelProgram()):
        st, it, ed = push.run_push(prog, shards, method="scan")
        st2, it2, ed2 = push.run_push(prog, shards, method="scan",
                                      route=route)
        np.testing.assert_array_equal(np.asarray(st), np.asarray(st2))
        assert int(it) == int(it2)
        assert push.edges_total(ed) == push.edges_total(ed2)


def test_routed_until_and_bf16():
    """run_pull_until with route= (convergence driver) and bfloat16
    state through the routed load — moves are dtype-agnostic, so both
    stay bitwise vs the direct engine."""
    from lux_tpu.graph import generate
    from lux_tpu.engine import pull
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.models.components import MaxLabelProgram
    from lux_tpu.models.pagerank import PageRankProgram
    from lux_tpu.models import components as cc_model

    g = generate.rmat(8, 8, seed=12)
    shards = build_pull_shards(g, 2)
    route = E.plan_expand_shards(shards)
    dev = jax.tree.map(jnp.asarray, shards.arrays)

    prog = MaxLabelProgram()
    s0 = pull.init_state(prog, dev)
    d, it_d = pull.run_pull_until(prog, shards.spec, dev, s0, 50,
                                  cc_model.active_count, method="scan")
    r, it_r = pull.run_pull_until(prog, shards.spec, dev, s0, 50,
                                  cc_model.active_count, method="scan",
                                  route=route)
    np.testing.assert_array_equal(np.asarray(d), np.asarray(r))
    assert int(it_d) == int(it_r)

    pr = PageRankProgram(nv=shards.spec.nv, dtype="bfloat16")
    s0 = pull.init_state(pr, dev)
    d = pull.run_pull_fixed(pr, shards.spec, dev, s0, 5, method="scan")
    r = pull.run_pull_fixed(pr, shards.spec, dev, s0, 5, method="scan",
                            route=route)
    np.testing.assert_array_equal(np.asarray(d), np.asarray(r))


def test_cf_routed_bitwise():
    """Wide dst-dependent load (colfilter): per-column src + dst routed
    expands, bitwise vs the direct engine at P=2."""
    from lux_tpu.graph import generate
    from lux_tpu.engine import pull
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.models.colfilter import CFProgram

    g = generate.rmat(8, 8, seed=13)
    shards = build_pull_shards(g, 2)
    prog = CFProgram(k=8)
    dev = jax.tree.map(jnp.asarray, shards.arrays)
    s0 = pull.init_state(prog, dev)
    direct = pull.run_pull_fixed(prog, shards.spec, dev, s0, 4,
                                 method="scan")
    route = E.plan_cf_route_shards(shards)
    routed = pull.run_pull_fixed(prog, shards.spec, dev, s0, 4,
                                 method="scan", route=route)
    np.testing.assert_array_equal(np.asarray(direct), np.asarray(routed))


def test_cf_routed_distributed():
    """Distributed wide routed load (per-column vmapped kernels under
    shard_map) matches the single-device routed CF engine bitwise."""
    from lux_tpu.graph import generate
    from lux_tpu.engine import pull
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.models.colfilter import CFProgram
    from lux_tpu.parallel import dist, mesh as mesh_lib

    g = generate.rmat(7, 6, seed=14)
    shards = build_pull_shards(g, 4)
    prog = CFProgram(k=4)
    dev = jax.tree.map(jnp.asarray, shards.arrays)
    s0 = pull.init_state(prog, dev)
    route = E.plan_cf_route_shards(shards)
    single = pull.run_pull_fixed(prog, shards.spec, dev, s0, 3,
                                 method="scan", route=route)
    mesh = mesh_lib.make_mesh(4)
    out = dist.run_pull_fixed_dist(prog, shards.spec, shards.arrays, s0, 3,
                                   mesh, method="scan", route=route)
    np.testing.assert_array_equal(np.asarray(single), np.asarray(out))


def test_delta_routed_bitwise():
    """Delta-stepping with routed dense rounds: bitwise state, same
    rounds, same exact edge counter."""
    from lux_tpu.engine import delta as dmod, push
    from lux_tpu.graph import generate
    from lux_tpu.graph.push_shards import build_push_shards
    from lux_tpu.models.sssp import WeightedSSSPProgram

    g = generate.rmat(9, 8, seed=4, weighted=True, max_weight=50)
    outdeg = np.zeros(g.nv, np.int64)
    np.add.at(outdeg, np.asarray(g.col_idx), 1)
    prog = WeightedSSSPProgram(nv=g.nv, start=int(np.argmax(outdeg)))
    shards = build_push_shards(g, 2)
    st, it, ed = dmod.run_push_delta(prog, shards, 4, method="scan")
    route = E.plan_expand_shards(shards)
    st2, it2, ed2 = dmod.run_push_delta(prog, shards, 4, method="scan",
                                        route=route)
    np.testing.assert_array_equal(np.asarray(st), np.asarray(st2))
    assert int(it) == int(it2)
    assert push.edges_total(ed) == push.edges_total(ed2)


def test_preflight_routed_terms():
    """Preflight charges the routed plan's device arrays: the exact and
    analytic estimates agree with the built plan's actual bytes."""
    from lux_tpu.graph import generate
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.utils import preflight

    sh = build_pull_shards(generate.rmat(10, 8, seed=1), 1)
    static, arrays = E.plan_expand_shards(sh)
    actual = sum(a.nbytes for a in arrays)
    assert preflight.routed_plan_bytes(static) == actual
    analytic = preflight.routed_plan_bytes_analytic(sh.spec, "expand")
    assert 0.8 * actual < analytic < 1.5 * actual
    base = preflight.estimate_pull(sh.spec)
    routed = preflight.add_routed(base, static)
    assert routed.total_bytes == base.total_bytes + actual
    # fused: exact match (weighted and unweighted) + analytic bound
    fstatic, farrays = E.plan_fused_shards(sh, "sum")
    factual = sum(a.nbytes for a in farrays)
    assert preflight.routed_plan_bytes(fstatic) == factual
    m = int(np.count_nonzero(sh.arrays.edge_mask[0]))
    fs_unw, fa_unw = E.plan_fused(
        np.asarray(sh.arrays.src_pos[0]), np.asarray(sh.arrays.dst_local[0]),
        m, sh.spec.gathered_size, sh.arrays.row_ptr.shape[1] - 1, "sum")
    assert preflight.routed_plan_bytes(fs_unw) == sum(a.nbytes for a in fa_unw)
    fanalytic = preflight.routed_plan_bytes_analytic(sh.spec, "fused")
    assert 0.7 * factual < fanalytic < 2.0 * factual


def test_push_dist_routed_bitwise():
    """Routed dense rounds in the DISTRIBUTED push engine (virtual
    8-mesh): bitwise state, same rounds, same exact edge counters."""
    from lux_tpu.engine import push
    from lux_tpu.graph import generate
    from lux_tpu.graph.push_shards import build_push_shards
    from lux_tpu.models.components import MaxLabelProgram
    from lux_tpu.parallel.mesh import make_mesh

    g = generate.rmat(9, 8, seed=6)
    shards = build_push_shards(g, 8)
    prog = MaxLabelProgram()
    mesh = make_mesh(8)
    st, it, ed = push.run_push_dist(prog, shards, mesh, method="scan")
    route = E.plan_expand_shards(shards)
    st2, it2, ed2 = push.run_push_dist(prog, shards, mesh, method="scan",
                                       route=route)
    np.testing.assert_array_equal(np.asarray(st), np.asarray(st2))
    assert int(it) == int(it2)
    assert push.edges_total(ed) == push.edges_total(ed2)


def test_ring_routed_bitwise():
    """Routed per-bucket expands in the RING exchange: bitwise vs the
    direct ring fold on the virtual 8-mesh."""
    from lux_tpu.engine import pull
    from lux_tpu.graph import generate
    from lux_tpu.parallel import ring
    from lux_tpu.parallel.mesh import make_mesh
    from lux_tpu.models.pagerank import PageRankProgram

    g = generate.rmat(9, 8, seed=15)
    rs = ring.build_ring_shards(g, 8)
    prog = PageRankProgram(nv=rs.spec.nv)
    s0 = pull.init_state(prog, rs.arrays)
    mesh = make_mesh(8)
    direct = ring.run_pull_fixed_ring(prog, rs, s0, 4, mesh, method="scan")
    route = E.plan_ring_route_shards(rs)
    routed = ring.run_pull_fixed_ring(prog, rs, s0, 4, mesh, method="scan",
                                      route=route)
    np.testing.assert_array_equal(np.asarray(direct), np.asarray(routed))


def test_scatter_routed_bitwise():
    """Routed per-bucket expands in the reduce_scatter exchange: bitwise
    vs the direct fold on the virtual 8-mesh."""
    from lux_tpu.engine import pull
    from lux_tpu.graph import generate
    from lux_tpu.parallel import scatter as sc
    from lux_tpu.parallel.mesh import make_mesh
    from lux_tpu.models.pagerank import PageRankProgram

    g = generate.rmat(9, 8, seed=16)
    ss = sc.build_scatter_shards(g, 8)
    prog = PageRankProgram(nv=ss.spec.nv)
    s0 = pull.init_state(prog, ss.arrays)
    mesh = make_mesh(8)
    direct = sc.run_pull_fixed_scatter(prog, ss, s0, 4, mesh, method="scan")
    route = E.plan_scatter_route_shards(ss)
    routed = sc.run_pull_fixed_scatter(prog, ss, s0, 4, mesh, method="scan",
                                       route=route)
    np.testing.assert_array_equal(np.asarray(direct), np.asarray(routed))


def test_feat_sharded_cf_routed_bitwise():
    """Routed per-column CF load on the 2-D (parts x feat) mesh: plans
    shard over parts, replicate over feat; bitwise vs the direct feat
    engine."""
    from jax.sharding import Mesh
    from lux_tpu.engine import pull
    from lux_tpu.graph import generate
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.models.colfilter import CFProgram
    from lux_tpu.parallel import feat
    from lux_tpu.parallel.mesh import PARTS_AXIS
    from lux_tpu.parallel.feat import FEAT_AXIS

    gw = generate.bipartite_ratings(256, 256, 4096, seed=0)
    shards = build_pull_shards(gw, 4)
    prog = CFProgram(k=8)
    dev = jax.tree.map(jnp.asarray, shards.arrays)
    s0 = pull.init_state(prog, dev)
    mesh = Mesh(np.array(jax.devices()).reshape(4, 2),
                (PARTS_AXIS, FEAT_AXIS))
    direct = feat.run_cf_feat_dist(prog, shards.spec, shards.arrays, s0, 3,
                                   mesh, method="scan")
    route = E.plan_cf_route_shards(shards)
    routed = feat.run_cf_feat_dist(prog, shards.spec, shards.arrays, s0, 3,
                                   mesh, method="scan", route=route)
    np.testing.assert_array_equal(np.asarray(direct), np.asarray(routed))


def test_edge2d_routed_bitwise():
    """Routed per-chunk expands on the 2-D (parts x edge) mesh: bitwise
    vs the direct chunked gather."""
    from lux_tpu.engine import pull
    from lux_tpu.graph import generate
    from lux_tpu.parallel import edge2d
    from lux_tpu.models.pagerank import PageRankProgram

    g = generate.rmat(9, 8, seed=17)
    es = edge2d.build_edge2d_shards(g, 4, 2)
    prog = PageRankProgram(nv=es.spec.nv)
    mesh = edge2d.make_mesh2d(4, 2)
    s0 = pull.init_state(prog, es.arrays)
    direct = edge2d.run_pull_fixed_2d(prog, es, s0, 4, mesh, method="scan")
    route = E.plan_edge2d_route_shards(es)
    routed = edge2d.run_pull_fixed_2d(prog, es, s0, 4, mesh, method="scan",
                                      route=route)
    np.testing.assert_array_equal(np.asarray(direct), np.asarray(routed))


@pytest.mark.parametrize("parts", [8, 16])
def test_push_ring_routed_bitwise(parts):
    """Routed streamed-block gathers in the push engine's RING dense
    rounds: bitwise state, rounds, and exact edge counters — at k=1
    (parts == devices) AND k=2 resident lanes (the plan slice indexing
    q = dev*k + j is the subtle part)."""
    from lux_tpu.engine import push
    from lux_tpu.graph import generate
    from lux_tpu.parallel.ring import build_push_ring_shards
    from lux_tpu.parallel.mesh import make_mesh
    from lux_tpu.models.components import MaxLabelProgram

    g = generate.rmat(9, 8, seed=18)
    prs = build_push_ring_shards(g, parts)
    prog = MaxLabelProgram()
    mesh = make_mesh(8)
    st, it, ed = push.run_push_ring(prog, prs, mesh, method="scan")
    route = E.plan_ring_route_shards(prs)
    st2, it2, ed2 = push.run_push_ring(prog, prs, mesh, method="scan",
                                       route=route)
    np.testing.assert_array_equal(np.asarray(st), np.asarray(st2))
    assert int(it) == int(it2)
    assert push.edges_total(ed) == push.edges_total(ed2)


def test_routed_on_heavy_tail_ba():
    """Routed expand AND fused on a Barabasi-Albert heavy-tail graph
    (hub in-degree ~n/10 stresses the widest fused group classes):
    expand bitwise, fused within tolerance, vs the direct engine."""
    from lux_tpu.engine import pull
    from lux_tpu.graph import generate
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.models.pagerank import PageRankProgram

    g = generate.barabasi_albert(4096, m=8, seed=2)
    shards = build_pull_shards(g, 1)
    prog = PageRankProgram(nv=shards.spec.nv)
    dev = jax.tree.map(jnp.asarray, shards.arrays)
    s0 = pull.init_state(prog, dev)
    direct = pull.run_pull_fixed(prog, shards.spec, dev, s0, 5,
                                 method="scan")
    routed = pull.run_pull_fixed(
        prog, shards.spec, dev, s0, 5, method="scan",
        route=E.plan_expand_shards(shards))
    np.testing.assert_array_equal(np.asarray(direct), np.asarray(routed))
    fused = pull.run_pull_fixed(
        prog, shards.spec, dev, s0, 5, method="scan",
        route=E.plan_fused_shards(shards, "sum"))
    np.testing.assert_allclose(np.asarray(fused), np.asarray(direct),
                               rtol=1e-5, atol=1e-7)


def test_narrow_idx_rejects_above_lane():
    """u8 narrowing admits ONLY digit-local values (< 128): [128, 256)
    fits a uint8 but would gather out of bounds under promise_in_bounds."""
    ok = E._narrow_idx(np.arange(128, dtype=np.int64).reshape(8, 16))
    assert ok.dtype == np.uint8
    with pytest.raises(AssertionError):
        E._narrow_idx(np.array([128], np.int64))
    # bool ff masks pass through untouched
    m = np.array([True, False])
    assert E._narrow_idx(m) is m


def test_cache_key_folds_shape_and_dtype():
    """Byte-identical arrays with different layouts must key differently
    (replaying a plan across layouts would gather garbage)."""
    import hashlib

    a = np.arange(16, dtype=np.int32)

    def key(arr):
        h = hashlib.sha1()
        E._hash_array(h, arr)
        return h.hexdigest()

    assert key(a) != key(a.reshape(4, 4))
    assert key(a) != key(a.view(np.float32))
    assert key(a) == key(a.copy())


def test_plan_cache_npz_roundtrip(tmp_path, rng):
    """The disk cache stores npz+json (no pickle): a second build loads
    the identical plan — equal statics (jit-static equality) and equal
    arrays — and the file parses with allow_pickle=False."""
    from lux_tpu.graph import generate
    from lux_tpu.graph.shards import build_pull_shards

    g = generate.rmat(7, 4, seed=9)
    shards = build_pull_shards(g, 2)
    cdir = str(tmp_path / "cache")
    s1, a1 = E.plan_expand_shards_cached(shards, cache_dir=cdir)
    files = [f for f in os.listdir(cdir)]
    assert files and all(f.endswith(".npz") for f in files)
    with np.load(os.path.join(cdir, files[0]), allow_pickle=False) as z:
        assert "__static__" in z.files  # loads without pickle at all
    s2, a2 = E.plan_expand_shards_cached(shards, cache_dir=cdir)
    assert s1 == s2 and hash(s1) == hash(s2)
    assert len(a1) == len(a2)
    for x, y in zip(a1, a2):
        assert x.dtype == y.dtype and np.array_equal(x, y)
    # the loaded plan replays bitwise on real edge slots, like the built
    # one (padding slots are junk by contract)
    full = rng.standard_normal(shards.spec.gathered_size).astype(np.float32)
    for p in range(2):
        got = jax.jit(
            lambda v: E.apply_expand(
                v, s2, tuple(jnp.asarray(a[p]) for a in a2), interpret=True
            )
        )(jnp.asarray(full))
        want = E.apply_expand_np(shards.arrays.src_pos[p], full)
        mask = shards.arrays.edge_mask[p]
        np.testing.assert_array_equal(np.asarray(got)[mask], want[mask])


def test_fused_and_cf_statics_roundtrip_json():
    """Every static vocabulary member survives the JSON codec with
    equality (FusedStatic carries nested groups; CFRouteStatic nests two
    ExpandStatics)."""
    from lux_tpu.graph import generate
    from lux_tpu.graph.shards import build_pull_shards

    g = generate.rmat(6, 4, seed=3, weighted=True)
    shards = build_pull_shards(g, 1)
    fs, _ = E.plan_fused_shards(shards, "sum")
    assert E._static_from_obj(E._static_to_obj(fs)) == fs
    cs, _ = E.plan_cf_route_shards(shards)
    assert E._static_from_obj(E._static_to_obj(cs)) == cs


def test_untrusted_cache_dir_degrades_to_build(tmp_path):
    """A symlinked or world-writable cache dir is never read OR written —
    plans still build correctly, just uncached."""
    from lux_tpu.graph import generate
    from lux_tpu.graph.shards import build_pull_shards

    g = generate.rmat(6, 4, seed=2)
    shards = build_pull_shards(g, 1)
    loose = tmp_path / "loose"
    loose.mkdir()
    os.chmod(loose, 0o777)
    assert not E._cache_dir_trusted(str(loose))
    s1, _ = E.plan_expand_shards_cached(shards, cache_dir=str(loose))
    assert list(loose.iterdir()) == []  # nothing written into it
    link = tmp_path / "link"
    os.symlink(loose, link)
    assert not E._cache_dir_trusted(str(link))
    tight = tmp_path / "tight"
    s2, _ = E.plan_expand_shards_cached(shards, cache_dir=str(tight))
    assert E._cache_dir_trusted(str(tight))
    assert (os.stat(tight).st_mode & 0o777) == 0o700
    assert s1 == s2


def test_corrupt_cache_file_rebuilds(tmp_path):
    from lux_tpu.graph import generate
    from lux_tpu.graph.shards import build_pull_shards

    g = generate.rmat(6, 4, seed=2)
    shards = build_pull_shards(g, 1)
    cdir = tmp_path / "c"
    s1, _ = E.plan_expand_shards_cached(shards, cache_dir=str(cdir))
    (path,) = list(cdir.iterdir())
    path.write_bytes(b"not an npz")
    s2, _ = E.plan_expand_shards_cached(shards, cache_dir=str(cdir))
    assert s1 == s2  # rebuilt (and re-cached) rather than crashed


def _fake_shards(parts_src, parts_mask, gathered):
    """Minimal PullShards stand-in for the planner APIs (arrays.src_pos /
    arrays.edge_mask + spec.gathered_size)."""
    import types

    return types.SimpleNamespace(
        arrays=types.SimpleNamespace(
            src_pos=np.stack(parts_src), edge_mask=np.stack(parts_mask)
        ),
        spec=types.SimpleNamespace(gathered_size=gathered),
    )


def test_parallel_plan_build_matches_serial(monkeypatch):
    """The executor fan-out over parts (and the threaded native colorer
    underneath) is BITWISE identical to the serial build — the planning
    layer's half of the tentpole contract."""
    from lux_tpu.graph import generate
    from lux_tpu.graph.shards import build_pull_shards

    g = generate.rmat(9, 8, seed=21)
    shards = build_pull_shards(g, 4)
    monkeypatch.setenv("LUX_PLAN_THREADS", "1")
    monkeypatch.setenv("LUX_ROUTE_THREADS", "1")
    s1, a1 = E.plan_expand_shards(shards)
    f1, fa1 = E.plan_fused_shards(shards, "sum")
    monkeypatch.setenv("LUX_PLAN_THREADS", "4")
    monkeypatch.setenv("LUX_ROUTE_THREADS", "4")
    s2, a2 = E.plan_expand_shards(shards)
    f2, fa2 = E.plan_fused_shards(shards, "sum")
    assert s1 == s2 and f1 == f2
    for x, y in zip(a1 + fa1, a2 + fa2):
        assert x.dtype == y.dtype and np.array_equal(x, y)


def test_incremental_cache_rebuilds_only_changed_parts(tmp_path, rng):
    """Per-part cache entries are keyed on each part's OWN arrays: a
    second layout sharing part 0 reloads its entry and builds only the
    changed part — the repartition-recut amortization contract."""
    e_pad, S = 512, 256
    def mk_part(seed):
        r = np.random.default_rng(seed)
        m = 400
        sp = np.zeros(e_pad, np.int32)
        sp[:m] = r.integers(0, S, m)
        mask = np.zeros(e_pad, bool)
        mask[:m] = True
        return sp, mask

    p0, p1, p2 = mk_part(1), mk_part(2), mk_part(3)
    cdir = str(tmp_path / "c")
    sh_a = _fake_shards([p0[0], p1[0]], [p0[1], p1[1]], S)
    sh_b = _fake_shards([p0[0], p2[0]], [p0[1], p2[1]], S)

    E.reset_plan_stats()
    sa, aa = E.plan_expand_shards_cached(sh_a, cache_dir=cdir)
    st = E.plan_stats_snapshot()
    assert st["built"] == 2 and st["loaded"] == 0
    E.reset_plan_stats()
    sb, ab = E.plan_expand_shards_cached(sh_b, cache_dir=cdir)
    st = E.plan_stats_snapshot()
    assert st["loaded"] == 1 and st["built"] == 1, st  # p0 reused, p2 built
    assert sa == sb
    # the reused entry replays the identical plan bytes for part 0
    for x, y in zip(aa, ab):
        np.testing.assert_array_equal(x[0], y[0])
    # a full rerun of EITHER layout is pure cache
    E.reset_plan_stats()
    E.plan_expand_shards_cached(sh_a, cache_dir=cdir)
    st = E.plan_stats_snapshot()
    assert st["built"] == 0 and st["loaded"] == 2


def test_bucket_cache_incremental_ring(tmp_path):
    """Ring per-bucket entries: a warm rerun loads every bucket; the
    cached plan equals the uncached one bitwise."""
    from lux_tpu.graph import generate
    from lux_tpu.parallel import ring

    g = generate.rmat(8, 8, seed=22)
    rs = ring.build_ring_shards(g, 4)
    cdir = str(tmp_path / "c")
    E.reset_plan_stats()
    s1, a1 = E.plan_ring_route_shards_cached(rs, cache_dir=cdir)
    st = E.plan_stats_snapshot()
    assert st["built"] == 16 and st["loaded"] == 0  # (R=4) x (P=4) buckets
    E.reset_plan_stats()
    s2, a2 = E.plan_ring_route_shards_cached(rs, cache_dir=cdir)
    st = E.plan_stats_snapshot()
    assert st["built"] == 0 and st["loaded"] == 16
    sd, ad = E.plan_ring_route_shards(rs)
    assert s1 == s2 == sd
    for x, y, z in zip(a1, a2, ad):
        assert np.array_equal(x, y) and np.array_equal(x, z)
        assert x.shape[:2] == (4, 4)  # (R, P) bucket axes restored


def test_fused_cached_matches_uncached(tmp_path):
    """Per-part fused entries (template-salted keys) replay the exact
    uncached plan; cf likewise."""
    from lux_tpu.graph import generate
    from lux_tpu.graph.shards import build_pull_shards

    g = generate.rmat(8, 8, seed=23, weighted=True)
    shards = build_pull_shards(g, 2)
    cdir = str(tmp_path / "c")
    fs_c, fa_c = E.plan_fused_shards_cached(shards, "sum", cache_dir=cdir)
    fs_u, fa_u = E.plan_fused_shards(shards, "sum")
    assert fs_c == fs_u
    for x, y in zip(fa_c, fa_u):
        np.testing.assert_array_equal(x, y)
    cs_c, ca_c = E.plan_cf_route_shards_cached(shards, cache_dir=cdir)
    cs_u, ca_u = E.plan_cf_route_shards(shards)
    assert cs_c == cs_u
    for x, y in zip(ca_c, ca_u):
        np.testing.assert_array_equal(x, y)


def test_plan_async_future_and_overlapped_engine():
    """plan_async + run_pull_fixed_overlapped: direct-gather chunks run
    while the plan future builds, the handover is bitwise-invisible, a
    resolved future routes every iteration, and fused futures are
    rejected (mid-run association change)."""
    import time as _time

    from lux_tpu.engine import pull
    from lux_tpu.graph import generate
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.models.pagerank import PageRankProgram

    g = generate.rmat(8, 8, seed=24)
    shards = build_pull_shards(g, 2)
    prog = PageRankProgram(nv=shards.spec.nv)
    dev = jax.tree.map(jnp.asarray, shards.arrays)
    s0 = pull.init_state(prog, dev)
    direct = pull.run_pull_fixed(prog, shards.spec, dev, s0, 6,
                                 method="scan")

    def slow_build():
        _time.sleep(0.3)
        return E.plan_expand_shards(shards)

    fut = E.plan_async(slow_build)
    assert isinstance(fut, E.PlanFuture)
    out, routed = pull.run_pull_fixed_overlapped(
        prog, shards.spec, dev, s0, 6, method="scan", route_future=fut)
    np.testing.assert_array_equal(np.asarray(direct), np.asarray(out))
    assert 0 <= routed <= 6

    ready = E.plan_async(lambda: E.plan_expand_shards(shards))
    ready.result()
    out2, routed2 = pull.run_pull_fixed_overlapped(
        prog, shards.spec, dev, s0, 6, method="scan", route_future=ready)
    np.testing.assert_array_equal(np.asarray(direct), np.asarray(out2))
    assert routed2 == 6  # resolved future -> routed from iteration 0
    # no future at all degrades to the plain driver
    out3, routed3 = pull.run_pull_fixed_overlapped(
        prog, shards.spec, dev, s0, 6, method="scan")
    np.testing.assert_array_equal(np.asarray(direct), np.asarray(out3))
    assert routed3 == 0

    # fused futures: resolved at entry -> run fused from iteration 0
    # (normal fused semantics, association differs so allclose not
    # bitwise); resolving mid-run would finish DIRECT (routed == 0)
    # rather than mix associations or discard completed iterations
    fused = E.plan_async(lambda: E.plan_fused_shards(shards, "sum"))
    fused.result()
    out4, routed4 = pull.run_pull_fixed_overlapped(
        prog, shards.spec, dev, s0, 6, method="scan", route_future=fused)
    assert routed4 == 6
    np.testing.assert_allclose(np.asarray(out4), np.asarray(direct),
                               rtol=1e-5, atol=1e-7)

    def slow_fused():
        _time.sleep(0.3)
        return E.plan_fused_shards(shards, "sum")

    out5, routed5 = pull.run_pull_fixed_overlapped(
        prog, shards.spec, dev, s0, 6, method="scan",
        route_future=E.plan_async(slow_fused))
    assert routed5 in (0, 6)  # mid-run -> finished direct; entry -> fused
    if routed5 == 0:
        np.testing.assert_array_equal(np.asarray(out5), np.asarray(direct))
    else:
        np.testing.assert_allclose(np.asarray(out5), np.asarray(direct),
                                   rtol=1e-5, atol=1e-7)


def test_plan_stats_accounting(tmp_path):
    """cold_s/warm_s + built/loaded counts track cache behavior — the
    source of bench.py's plan_build_seconds field."""
    from lux_tpu.graph import generate
    from lux_tpu.graph.shards import build_pull_shards

    g = generate.rmat(7, 4, seed=25)
    shards = build_pull_shards(g, 2)
    cdir = str(tmp_path / "c")
    E.reset_plan_stats()
    E.plan_expand_shards_cached(shards, cache_dir=cdir)
    st = E.plan_stats_snapshot()
    assert st["built"] == 2 and st["cold_s"] > 0 and st["warm_s"] == 0
    E.plan_expand_shards_cached(shards, cache_dir=cdir)
    st2 = E.plan_stats_snapshot()
    assert st2["loaded"] == 2 and st2["warm_s"] > 0
    assert st2["cold_s"] == st["cold_s"]  # warm pass added no build time
