"""luxpod: fleet workers that ARE mesh slices (ISSUE 19 tentpole).

A pod is N worker processes holding ONE PlacementTree-sharded graph;
the snapshot reaches each worker as a wire byte stream (no shared
filesystem), each worker partial-loads only its own parts, and per
round every worker runs the pull engine's exact per-part step.  The
acceptance bar these tests pin: pod answers are BITWISE equal to the
single-host engine for every tested (parts x hosts) shape — including
the uneven H=3 split of P=8 and under live mutation overlays.

The in-process tests (PodWorker threads over loopback) are tier-1; the
real-subprocess tests — private-tmpdir isolation and the process-mode
lease failover drill — are ``slow`` (they fork python+jax processes)
and also run in the ci_check ``pod_smoke`` stage.
"""
import os
import tempfile
import textwrap
import time

import numpy as np
import pytest

from lux_tpu.engine import pull
from lux_tpu.graph import generate
from lux_tpu.graph.format import write_lux
from lux_tpu.graph.shards import build_pull_shards
from lux_tpu.models.sssp import SSSPProgram
from lux_tpu.parallel.placement import PlacementTree
from lux_tpu.program.spec import active_changed
from lux_tpu.serve.fleet.pod import (
    PodError,
    PodWorker,
    _rpc,
    run_pull_pod,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
P = 8  # parts — H=3 exercises the uneven 3/3/2 slice split


@pytest.fixture(scope="module")
def pod_graph(tmp_path_factory):
    """Graph + snapshot + single-host sssp oracle, built once: start at
    the hub vertex so convergence takes several rounds (a fixed start 0
    can be isolated on an RMAT draw and converge instantly)."""
    g = generate.rmat(10, 8, seed=3)
    snap = str(tmp_path_factory.mktemp("pod") / "g.lux")
    write_lux(snap, g)
    shards = build_pull_shards(g, P)
    start = int(np.argmax(g.out_degrees()))
    prog = SSSPProgram(nv=shards.spec.nv, start=start)
    s0 = pull.init_state(prog, shards.arrays)
    oracle, iters = pull.run_pull_until(
        prog, shards.spec, shards.arrays, s0, 10_000, active_changed,
        method="auto")
    return {"g": g, "snap": snap, "shards": shards, "start": start,
            "oracle": np.asarray(oracle), "iters": int(iters)}


def _pod(n):
    return [PodWorker(f"p{i}").start() for i in range(n)]


@pytest.mark.parametrize("hosts", [1, 2, 3])
def test_pod_bitwise_matches_single_host(pod_graph, hosts):
    ws = _pod(hosts)
    res = run_pull_pod([(w.host, w.port) for w in ws],
                       pod_graph["snap"], P, app="sssp",
                       start=pod_graph["start"])
    assert res["iters"] == pod_graph["iters"]
    np.testing.assert_array_equal(res["state"], pod_graph["oracle"])
    # every worker owns exactly its tree slice, tiling [0, P)
    tree = PlacementTree.build(P, hosts)
    spans = sorted((w["lo"], w["hi"]) for w in res["workers"].values())
    assert spans == [(s.lo, s.hi) for s in tree.slices]
    # the standard phase attribution is present and sane
    assert set(res["phases"]) == {"plan", "exchange", "converge"}
    assert all(v >= 0.0 for v in res["phases"].values())


def test_pod_overlay_bitwise(pod_graph):
    """Live-mutation overlays ride the wire: rows sliced per worker by
    the same tree, answers bitwise vs the single-host overlay run."""
    from lux_tpu.mutate import overlay as ovl
    from lux_tpu.mutate.graph import DeltaLog

    g, shards = pod_graph["g"], pod_graph["shards"]
    rng = np.random.default_rng(0)
    dlog = DeltaLog(g)
    dele = rng.choice(g.ne, 25, replace=False)
    dlog.apply(g.col_idx[dele], g.dst_of_edges()[dele],
               np.zeros(25, np.int8))
    dlog.apply(rng.integers(0, g.nv, 25), rng.integers(0, g.nv, 25),
               np.ones(25, np.int8))
    ostatic = ovl.OverlayStatic(cap=ovl.delta_cap(256),
                                weighted=shards.spec.weighted)
    _, oarr = ovl.build_pull_overlay(shards, dlog, cap=256)

    prog = SSSPProgram(nv=shards.spec.nv, start=pod_graph["start"])
    s0 = pull.init_state(prog, shards.arrays)
    oracle, iters = pull.run_pull_until(
        prog, shards.spec, shards.arrays, s0, 10_000, active_changed,
        overlay=(ostatic, oarr))

    ws = _pod(2)
    res = run_pull_pod([(w.host, w.port) for w in ws],
                       pod_graph["snap"], P, app="sssp",
                       start=pod_graph["start"],
                       overlay=(ostatic, oarr))
    assert res["iters"] == int(iters)
    np.testing.assert_array_equal(res["state"], np.asarray(oracle))


def test_pod_pagerank_fixed_iters(pod_graph):
    """Non-quiescent app: pagerank runs exactly num_iters rounds and is
    bitwise equal to the single-host fixed driver."""
    from lux_tpu.models.pagerank import PageRankProgram

    shards = pod_graph["shards"]
    prog = PageRankProgram(nv=shards.spec.nv)
    s0 = pull.init_state(prog, shards.arrays)
    oracle = pull.run_pull_fixed(prog, shards.spec, shards.arrays, s0, 3)

    ws = _pod(2)
    res = run_pull_pod([(w.host, w.port) for w in ws],
                       pod_graph["snap"], P, app="pagerank",
                       num_iters=3)
    assert res["iters"] == 3
    np.testing.assert_array_equal(res["state"], np.asarray(oracle))


def test_pod_rejects_corrupt_stream_then_recovers(pod_graph):
    """A digest mismatch can never be staged: pod_build errors loudly,
    and a re-stream on the SAME connection (token supersede) succeeds."""
    from lux_tpu.serve.fleet.stream import stream_file
    from lux_tpu.serve.fleet.wire import Conn

    w = PodWorker("px").start()
    try:
        conn = Conn.connect(w.host, w.port, timeout_s=10.0,
                            peer="pod", owner="test")
        try:
            def rpc(m):
                return _rpc(conn, m)[0]

            meta = stream_file(conn, pod_graph["snap"], "t", 256 * 1024,
                               rpc=rpc)
            build = {"op": "pod_build", "token": "t",
                     "num_parts": P,
                     "placement": PlacementTree.build(P, 1).to_wire(),
                     "host": 0, "app": "sssp",
                     "start": pod_graph["start"]}
            with pytest.raises(PodError, match="digest mismatch"):
                _rpc(conn, {**build, "sha256": "0" * 64})
            # the sink is consumed either way — a second build without
            # a fresh stream must say so, not stage garbage
            with pytest.raises(PodError, match="no snapshot stream"):
                _rpc(conn, {**build, "sha256": meta["sha256"]})
            meta = stream_file(conn, pod_graph["snap"], "t", 256 * 1024,
                               rpc=rpc)
            reply, state0 = _rpc(conn, {**build,
                                        "sha256": meta["sha256"]})
            assert (reply["lo"], reply["hi"]) == (0, P)
            assert state0.shape[0] == P
        finally:
            conn.close()
    finally:
        w.stop()


def test_pod_tree_shape_mismatches_error(pod_graph):
    ws = _pod(2)
    try:
        with pytest.raises(PodError, match="names 1 hosts"):
            run_pull_pod([(w.host, w.port) for w in ws],
                         pod_graph["snap"], P,
                         tree=PlacementTree.build(P, 1), shutdown=False)
    finally:
        for w in ws:
            w.stop()
    # a tree that disagrees with the graph's cut count is refused by
    # the WORKER (the tree travels on the wire; the check is remote)
    ws = _pod(1)
    with pytest.raises(PodError, match="covers 4 parts"):
        run_pull_pod([(w.host, w.port) for w in ws],
                     pod_graph["snap"], P,
                     tree=PlacementTree.build(4, 1))


# ----------------------------------------------------------------------
# real processes (slow tier + ci_check pod_smoke stage)
# ----------------------------------------------------------------------


def _child_env():
    from conftest import forced_cpu_env

    return forced_cpu_env()


@pytest.mark.slow
def test_pod_subprocess_private_tmpdirs(pod_graph):
    """2 real worker processes, DISJOINT private tmpdirs (the launcher
    enforces no-shared-filesystem by construction), snapshot over the
    wire, answers bitwise — and each spool lived under its own tmpdir."""
    from lux_tpu.serve.fleet.launcher import launch_pod_worker

    hs = [launch_pod_worker(f"pp{i}", env=_child_env())
          for i in range(2)]
    try:
        tmps = [h.tmpdir for h in hs]
        assert len(set(tmps)) == 2 and all(tmps)
        res = run_pull_pod([("127.0.0.1", h.port) for h in hs],
                           pod_graph["snap"], P, app="sssp",
                           start=pod_graph["start"])
        assert res["iters"] == pod_graph["iters"]
        np.testing.assert_array_equal(res["state"],
                                      pod_graph["oracle"])
        # the driver's shutdown op makes each worker self-exit cleanly
        for h in hs:
            assert h.proc.wait(timeout=30.0) == 0
    finally:
        for h in hs:
            h.terminate()
    # teardown reclaimed both private tmpdirs
    assert not any(os.path.exists(t) for t in tmps)


@pytest.mark.slow
def test_process_mode_lease_failover():
    """The ISSUE 19 failover drill, all real processes: a fleet worker
    and an incumbent controller each in their own process; a standby in
    THIS process renews the lease over the wire; SIGKILL the incumbent
    — silence on the lease port IS the death signal — and the standby
    wins the fenced election and SERVES through the surviving worker."""
    from lux_tpu.serve.autopilot.election import (
        Standby,
        StandbyGroup,
        WireIncumbent,
    )
    from lux_tpu.serve.fleet.launcher import (
        launch_fleet_worker,
        launch_script,
    )

    env = _child_env()
    w = launch_fleet_worker(
        "fw0", extra_args=["--rmat", "9,8", "--parts", "2"], env=env)
    ctl_proc = sb = inc = ctl2 = None
    try:
        script = os.path.join(tempfile.mkdtemp(prefix="lux-failover-"),
                              "incumbent.py")
        with open(script, "w") as f:
            f.write(textwrap.dedent(f"""
                import json, os, time
                os.environ.setdefault("JAX_PLATFORMS", "cpu")
                from lux_tpu.serve.fleet.controller import FleetController
                ctl = FleetController(hb_interval_s=0.05,
                                      hb_timeout_s=0.5)
                ctl.add_worker("127.0.0.1", {w.port})
                lease = ctl.serve_lease()
                print(json.dumps({{"ready": True, "worker_id": "ctl0",
                                   "port": lease, "pid": os.getpid(),
                                   "incarnation": ctl.incarnation}}),
                      flush=True)
                while True:
                    time.sleep(0.2)
            """))
        ctl_proc = launch_script(script, env=env)

        inc = WireIncumbent("127.0.0.1", ctl_proc.port)
        assert inc.incarnation == ctl_proc.ready["incarnation"]
        # the lease grant carried the incumbent's heartbeat terms
        assert inc.hb_interval_s == pytest.approx(0.05)
        assert inc.hb_timeout_s == pytest.approx(0.5)

        group = StandbyGroup()

        def _promote(tc=None):
            from lux_tpu.serve.fleet.controller import FleetController

            c2 = FleetController(hb_interval_s=0.05, hb_timeout_s=1.0)
            wid = c2.add_worker("127.0.0.1", w.port)
            return c2, {"joined": [wid]}

        sb = Standby(group, 0, inc, _promote, hb_interval_s=0.05,
                     death_after_s=0.4, seed=0).start()
        deadline = time.monotonic() + 10.0
        while sb.probes_ok == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert sb.probes_ok > 0, "standby never renewed the lease"

        ctl_proc.kill()  # SIGKILL: no goodbye, the port just goes dark
        got = group.wait_promoted(timeout_s=60.0)
        assert got is not None, "standby never promoted"
        ctl2, rep = got
        assert sb.outcome == "won"
        assert rep["joined"] == ["fw0"]
        assert ctl2.incarnation != inc.incarnation

        out = ctl2.submit(0, app="sssp").result(timeout=120.0)
        assert isinstance(out, np.ndarray) and out.size > 0
    finally:
        if sb is not None:
            sb.stop()
        if inc is not None:
            inc.close()
        if ctl2 is not None:
            ctl2.close(shutdown_workers=False)
        if ctl_proc is not None and ctl_proc.alive():
            ctl_proc.kill()
        w.terminate()
