"""Weighted cuts + custom-cuts shard builds (the dynamic-repartitioning
mechanism).

The Lux paper describes repartitioning from per-part runtimes; the
reference code never shipped it (no repartition path anywhere under
/root/reference).  In a lockstep SPMD engine every part executes the same
static-shape program, so rebalancing pays off only when it changes the
static shapes themselves (e_pad = max part edges) or evens out measured
per-vertex work across chips; the framework therefore exposes the
*mechanism* — partition.weighted_cuts + build_*_shards(cuts=...) — and the
driver chooses the policy.
"""
from __future__ import annotations

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from lux_tpu.graph import generate
from lux_tpu.graph.partition import edge_balanced_cuts, weighted_cuts
from lux_tpu.graph.push_shards import build_push_shards
from lux_tpu.graph.shards import build_pull_shards
from lux_tpu.models import pagerank as pr
from lux_tpu.models import sssp as ss


def test_weighted_cuts_matches_edge_balanced_on_degree():
    g = generate.rmat(8, 8, seed=3)
    indeg = np.diff(g.row_ptr)
    wc = weighted_cuts(indeg, 4)
    eb = edge_balanced_cuts(g.row_ptr, 4)
    # same work model -> same bounds (both sweep the cumulative in-degree)
    np.testing.assert_array_equal(wc, eb)


def test_weighted_cuts_balances_skewed_work():
    nv = 1024
    w = np.zeros(nv)
    w[:128] = 100.0  # all work concentrated in the first eighth
    w[128:] = 1.0
    cuts = weighted_cuts(w, 4)
    per_part = [w[cuts[p]:cuts[p + 1]].sum() for p in range(4)]
    assert max(per_part) <= 2.0 * (w.sum() / 4)
    # the hot region is spread over multiple parts
    assert cuts[1] < 128


def test_weighted_cuts_degenerate():
    cuts = weighted_cuts(np.zeros(100), 4)
    assert cuts[0] == 0 and cuts[-1] == 100
    assert np.all(np.diff(cuts) >= 0)
    one = weighted_cuts(np.ones(3), 8)  # more parts than vertices
    assert one[-1] == 3 and np.all(np.diff(one) >= 0)


def test_custom_cuts_pull_same_result():
    """PageRank on a deliberately different (weighted) partition must agree
    with the default edge-balanced run — the partition is an execution
    detail, not a semantic one."""
    g = generate.rmat(8, 8, seed=5)
    base = pr.pagerank(g, num_iters=5)
    rng = np.random.default_rng(0)
    w = np.diff(g.row_ptr) + rng.integers(0, 50, g.nv)  # skewed custom work
    cuts = weighted_cuts(w, 3)
    shards = build_pull_shards(g, 3, cuts=cuts)
    assert not np.array_equal(shards.cuts, build_pull_shards(g, 3).cuts)
    custom = pr.pagerank(shards, num_iters=5)
    np.testing.assert_allclose(
        np.asarray(base, np.float64), np.asarray(custom, np.float64),
        rtol=1e-5, atol=1e-7,
    )


def test_custom_cuts_push_same_result():
    g = generate.rmat(8, 8, seed=7)
    base = ss.sssp(g, start=0)
    w = np.linspace(1, 10, g.nv)
    shards = build_push_shards(g, 3, cuts=weighted_cuts(w, 3))
    custom = ss.sssp(shards, start=0)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(custom))


# ---------------------------------------------------------------------------
# Adaptive driver (the POLICY on top of the mechanism): the engine's carry
# accumulates per-part load (sp_work/dense_rounds); run_push_adaptive recuts
# between windows and remaps the in-flight state + frontier.

from lux_tpu.engine import push, repartition
from lux_tpu.parallel.mesh import make_mesh


def _static_global(prog, g, num_parts, mesh=None):
    shards = build_push_shards(g, num_parts)
    if mesh is None:
        st, _, e = push.run_push(prog, shards)
    else:
        st, _, e = push.run_push_dist(prog, shards, mesh)
    return shards.scatter_to_global(np.asarray(st)), e


def test_adaptive_sssp_matches_static():
    g = generate.rmat(11, 8, seed=3)
    prog = ss.SSSPProgram(nv=g.nv, start=0)
    ref, _ = _static_global(prog, g, 4)
    events = []
    res = repartition.run_push_adaptive(
        prog, g, 4, chunk=2, threshold=1.01,
        on_repartition=lambda it, oc, nc, w: events.append((it, oc, nc)),
    )
    np.testing.assert_array_equal(res.state, ref)
    # the tight threshold + sparse BFS tail must actually trigger recuts
    assert res.reparts >= 1 and res.reparts == len(events)
    for _, old_cuts, new_cuts in events:
        assert not np.array_equal(old_cuts, new_cuts)
        assert np.all(np.diff(new_cuts) >= 0)
        assert new_cuts[0] == 0 and new_cuts[-1] == g.nv


def test_adaptive_distributed_matches_static():
    g = generate.rmat(11, 8, seed=5)
    prog = ss.SSSPProgram(nv=g.nv, start=0)
    mesh = make_mesh(8)
    ref, _ = _static_global(prog, g, 8, mesh)
    res = repartition.run_push_adaptive(
        prog, g, 8, chunk=2, threshold=1.01, mesh=mesh
    )
    np.testing.assert_array_equal(res.state, ref)
    assert res.iters > 0


def test_adaptive_cc_overflow_defers_then_matches():
    """CC starts with EVERY vertex in the frontier — counts far beyond
    f_cap at the first window boundary, exercising the truncated-queue
    deferral path — and must still reach the static fixpoint."""
    from lux_tpu.models.components import MaxLabelProgram

    g = generate.rmat(10, 8, seed=9)
    prog = MaxLabelProgram()
    ref, _ = _static_global(prog, g, 4)
    res = repartition.run_push_adaptive(prog, g, 4, chunk=1, threshold=1.0)
    np.testing.assert_array_equal(res.state, ref)


def test_adaptive_rerun_deterministic():
    g = generate.rmat(10, 8, seed=11)
    prog = ss.SSSPProgram(nv=g.nv, start=2)
    a = repartition.run_push_adaptive(prog, g, 4, chunk=2, threshold=1.05)
    b = repartition.run_push_adaptive(prog, g, 4, chunk=2, threshold=1.05)
    np.testing.assert_array_equal(a.state, b.state)
    assert a.reparts == b.reparts and a.iters == b.iters
    assert push.edges_total(a.edges) == push.edges_total(b.edges)


def test_part_work_and_weights():
    row_ptr = np.array([0, 4, 6, 6, 10], np.int64)  # nv=4
    cuts = np.array([0, 2, 4], np.int64)  # 2 parts: edges [6, 4]
    work = repartition.part_work(
        np.array([10.0, 0.0], np.float32), 2, cuts, row_ptr
    )
    np.testing.assert_allclose(work, [10.0 + 2 * 6, 2 * 4])
    assert repartition.imbalance(np.array([1.0, 1.0])) == 1.0
    assert repartition.imbalance(np.array([3.0, 1.0])) == 1.5
    w = repartition.vertex_weights(work, cuts, row_ptr)
    assert w.shape == (4,) and np.all(w > 0)
    # part 0 is hotter per edge -> its vertices weigh more per unit degree
    assert w[0] / 4 > w[3] / 4


def test_sparse_work_accumulates_in_carry():
    """Window stats: sparse rounds add per-part walked totals; dense
    rounds bump the round counter."""
    import jax

    g = generate.rmat(9, 6, seed=13)
    shards = build_push_shards(g, 4)
    prog = ss.SSSPProgram(nv=g.nv, start=0)
    arrays, parrays, carry = push.push_init(prog, shards)
    loop = push.compile_push_chunk(prog, shards.pspec, shards.spec, "scan")
    out = loop(arrays, parrays, carry, jnp.int32(1000))
    sp = np.asarray(out.sp_work)
    dr = int(out.dense_rounds)
    assert sp.shape == (4,) and np.all(sp >= 0)
    assert 0 <= dr <= int(out.it)
    # a BFS from a single source must have at least one sparse round, and
    # its walked totals land in sp_work
    assert sp.sum() > 0


def test_library_wrappers_adaptive():
    """sssp()/connected_components_push() expose the policy."""
    from lux_tpu.models.components import connected_components_push

    g = generate.rmat(10, 8, seed=4)
    base = ss.sssp(g, start=0, num_parts=4)
    adapt = ss.sssp(
        g, start=0, num_parts=4, repartition_every=2,
        repartition_threshold=1.01,
    )
    np.testing.assert_array_equal(base, adapt)
    base_cc = connected_components_push(g, num_parts=4)
    adapt_cc = connected_components_push(
        g, num_parts=4, repartition_every=2, repartition_threshold=1.01
    )
    np.testing.assert_array_equal(base_cc, adapt_cc)
    with pytest.raises(ValueError):
        ss.sssp(g, start=0, repartition_every=2, exchange="ring")


def test_adaptive_ring_matches_static():
    """Ring-exchange adaptive run: recuts rebuild the ring buckets AND
    the frontier CSR; fixpoint equals the static all-gather run."""
    from lux_tpu.parallel.ring import build_push_ring_shards

    g = generate.rmat(11, 8, seed=3)
    prog = ss.SSSPProgram(nv=g.nv, start=0)
    mesh = make_mesh(8)
    ref, _ = _static_global(prog, g, 8, mesh)
    events = []
    res = repartition.run_push_adaptive(
        prog, g, 8, chunk=2, threshold=1.01, mesh=mesh, exchange="ring",
        on_repartition=lambda it, oc, nc, w: events.append(it),
    )
    np.testing.assert_array_equal(res.state, ref)
    assert res.reparts >= 1
    # the final layout is a ring layout on the recut partition
    assert hasattr(res.shards, "rarrays")
    assert not np.array_equal(
        res.shards.cuts, build_push_ring_shards(g, 8).cuts
    )


def test_adaptive_ring_requires_mesh():
    g = generate.rmat(8, 6, seed=1)
    prog = ss.SSSPProgram(nv=g.nv, start=0)
    with pytest.raises(ValueError):
        repartition.run_push_adaptive(prog, g, 4, exchange="ring")
    with pytest.raises(ValueError):
        repartition.run_push_adaptive(prog, g, 4, exchange="scatter")


def test_sp_work_saturates_instead_of_wrapping():
    """VERDICT r3 weak #6: the per-part load accumulator near its 2^32
    ceiling must SATURATE (hot stays hot), never wrap to small (hot reads
    cold and the recut inverts).  Drives _acc_load directly with window
    totals that cross the ceiling."""
    import jax
    import jax.numpy as jnp

    from lux_tpu.engine.push import PushCarry, _acc_load

    def carry_with(sp):
        return PushCarry(None, None, None, None, None, None, None,
                         jnp.asarray(sp, jnp.uint32), jnp.int32(0))

    step = jax.jit(
        lambda sp, t, d: _acc_load(carry_with(sp), t, d)[0]
    )
    near = np.uint32(0xFFFF_FF00)
    # part 0 crosses the ceiling, part 1 stays small
    out = np.asarray(step(np.array([near, 1000], np.uint32),
                          jnp.int32(0x200), jnp.bool_(False)))
    assert out[0] == 0xFFFF_FFFF  # saturated, not wrapped to ~0x100
    assert out[1] == 1000 + 0x200
    # saturation is absorbing
    out2 = np.asarray(step(out, jnp.int32(12345), jnp.bool_(False)))
    assert out2[0] == 0xFFFF_FFFF
    # dense rounds add nothing to sp_work
    out3 = np.asarray(step(out, jnp.int32(777), jnp.bool_(True)))
    assert out3[1] == out[1]
    # the policy input stays exact far past float32's 2^24 absorb point
    big = np.uint32(20_000_000)
    out4 = np.asarray(step(np.array([big, 0], np.uint32),
                           jnp.int32(3), jnp.bool_(False)))
    assert out4[0] == 20_000_003  # float32 would have absorbed the +3


def test_adaptive_recut_keeps_sort_segments():
    """run_push_adaptive(sort_segments=True): the recut rebuild keeps the
    gather-locality relayout (per-segment nondecreasing src_pos in the
    rebuilt pull layout) and still converges to the BFS fixpoint."""
    from lux_tpu.engine import repartition
    from lux_tpu.models import sssp as ss

    g = generate.rmat(9, 6, seed=14)
    res = repartition.run_push_adaptive(
        ss.SSSPProgram(nv=g.nv, start=0), g, 4, chunk=2, threshold=1.01,
        sort_segments=True,
    )
    assert res.reparts >= 1  # a recut actually happened
    np.testing.assert_array_equal(res.state, ss.bfs_reference(g, 0))
    arr = res.shards.arrays
    for p in range(arr.src_pos.shape[0]):
        dl = arr.dst_local[p]
        sp = arr.src_pos[p]
        # within every dst segment the gather indices are nondecreasing
        same_seg = dl[1:] == dl[:-1]
        assert (sp[1:][same_seg] >= sp[:-1][same_seg]).all()
    import pytest

    with pytest.raises(ValueError, match="sort_segments"):
        repartition.run_push_adaptive(
            ss.SSSPProgram(nv=g.nv, start=0), g, 4, exchange="ring",
            mesh=None, sort_segments=True,
        )
