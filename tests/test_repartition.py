"""Weighted cuts + custom-cuts shard builds (the dynamic-repartitioning
mechanism).

The Lux paper describes repartitioning from per-part runtimes; the
reference code never shipped it (no repartition path anywhere under
/root/reference).  In a lockstep SPMD engine every part executes the same
static-shape program, so rebalancing pays off only when it changes the
static shapes themselves (e_pad = max part edges) or evens out measured
per-vertex work across chips; the framework therefore exposes the
*mechanism* — partition.weighted_cuts + build_*_shards(cuts=...) — and the
driver chooses the policy.
"""
from __future__ import annotations

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from lux_tpu.graph import generate
from lux_tpu.graph.partition import edge_balanced_cuts, weighted_cuts
from lux_tpu.graph.push_shards import build_push_shards
from lux_tpu.graph.shards import build_pull_shards
from lux_tpu.models import pagerank as pr
from lux_tpu.models import sssp as ss


def test_weighted_cuts_matches_edge_balanced_on_degree():
    g = generate.rmat(8, 8, seed=3)
    indeg = np.diff(g.row_ptr)
    wc = weighted_cuts(indeg, 4)
    eb = edge_balanced_cuts(g.row_ptr, 4)
    # same work model -> same bounds (both sweep the cumulative in-degree)
    np.testing.assert_array_equal(wc, eb)


def test_weighted_cuts_balances_skewed_work():
    nv = 1024
    w = np.zeros(nv)
    w[:128] = 100.0  # all work concentrated in the first eighth
    w[128:] = 1.0
    cuts = weighted_cuts(w, 4)
    per_part = [w[cuts[p]:cuts[p + 1]].sum() for p in range(4)]
    assert max(per_part) <= 2.0 * (w.sum() / 4)
    # the hot region is spread over multiple parts
    assert cuts[1] < 128


def test_weighted_cuts_degenerate():
    cuts = weighted_cuts(np.zeros(100), 4)
    assert cuts[0] == 0 and cuts[-1] == 100
    assert np.all(np.diff(cuts) >= 0)
    one = weighted_cuts(np.ones(3), 8)  # more parts than vertices
    assert one[-1] == 3 and np.all(np.diff(one) >= 0)


def test_custom_cuts_pull_same_result():
    """PageRank on a deliberately different (weighted) partition must agree
    with the default edge-balanced run — the partition is an execution
    detail, not a semantic one."""
    g = generate.rmat(8, 8, seed=5)
    base = pr.pagerank(g, num_iters=5)
    rng = np.random.default_rng(0)
    w = np.diff(g.row_ptr) + rng.integers(0, 50, g.nv)  # skewed custom work
    cuts = weighted_cuts(w, 3)
    shards = build_pull_shards(g, 3, cuts=cuts)
    assert not np.array_equal(shards.cuts, build_pull_shards(g, 3).cuts)
    custom = pr.pagerank(shards, num_iters=5)
    np.testing.assert_allclose(
        np.asarray(base, np.float64), np.asarray(custom, np.float64),
        rtol=1e-5, atol=1e-7,
    )


def test_custom_cuts_push_same_result():
    g = generate.rmat(8, 8, seed=7)
    base = ss.sssp(g, start=0)
    w = np.linspace(1, 10, g.nv)
    shards = build_push_shards(g, 3, cuts=weighted_cuts(w, 3))
    custom = ss.sssp(shards, start=0)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(custom))
