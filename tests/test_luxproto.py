"""luxproto (lux_tpu.analysis.proto): every clean protocol model checks
EXHAUSTIVELY clean, every broken twin produces its designed shortest
counterexample, recorded soak logs replay conformant through the
models' legality rules, and an election counterexample round-trips to a
REAL split brain through the exported FaultPlan — the tier-1 form of
chip-day step -3c / ci_check's proto_smoke.
"""
import importlib.util
import json
import os
import sys

import pytest

from lux_tpu.analysis.proto import (
    PROTOCOLS,
    check_all,
    check_broken,
    check_protocol,
)
from lux_tpu.analysis.proto import conform
from lux_tpu.analysis.proto.export import (
    export_faultplan,
    export_json,
    trace_seed,
)
from lux_tpu.fault.plan import FaultPlan

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DATA = os.path.join(REPO, "tests", "data")


def _fixture(name):
    with open(os.path.join(DATA, name)) as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# the clean models: exhaustively clean, real state spaces
# ---------------------------------------------------------------------------


def test_all_protocols_check_clean():
    results = check_all()
    assert [r.protocol for r in results] == list(PROTOCOLS)
    for r in results:
        assert r.ok, r.violation.format()
        # exhaustive means a real state space was walked, not a stub
        assert r.states > 10 and r.transitions > r.states / 2, r
        assert r.depth > 3, r


def test_state_spaces_are_not_degenerate():
    """Floors (not exact pins — models may legitimately grow): the
    docs/ANALYSIS.md state-space table stays honest if these move."""
    floors = {"election": 100, "publish": 1000, "genline": 10000,
              "journal": 50}
    for r in check_all():
        assert r.states >= floors[r.protocol], r.summary()


# ---------------------------------------------------------------------------
# broken twins: each must fail, with its DESIGNED counterexample
# ---------------------------------------------------------------------------


def test_every_broken_twin_fails():
    for name, proto in PROTOCOLS.items():
        for twin in proto.broken:
            r = check_broken(name, twin)
            assert not r.ok, f"{name}/{twin} unexpectedly clean"
            assert r.violation.kind == "invariant", (name, twin)
            assert r.violation.trace, (name, twin)


def test_election_unfenced_is_split_brain():
    v = check_broken("election", "unfenced").violation
    assert "split brain" in v.message
    assert "incarnation fence" in v.message
    # the shortest schedule: winner promotes, stops, late detector
    # claims the SAME incarnation and promotes again
    assert v.trace[:2] == ("detect(s0)", "claim_win(s0)")
    assert sum(a.startswith("claim_win") for a in v.trace) == 2


def test_publish_unchecked_tokens_installs_wrong_cache():
    v = check_broken("publish", "unchecked_tokens").violation
    # the refusal string is the REAL pubproto.token_mismatch spelling
    from lux_tpu.serve.fleet.pubproto import token_mismatch
    assert token_mismatch("pub-A-1", "pub-B-1") in v.message
    assert any(a.startswith("crash(c0)") for a in v.trace)


def test_genline_twins():
    v = check_broken("genline", "stale_heartbeat").violation
    assert "read-your-writes" in v.message
    assert "view 1 -> 0" in v.message
    v = check_broken("genline", "optimistic_send").violation
    assert "leads its applied gen" in v.message
    assert v.trace == ("write(gen=1)",)  # a 1-step counterexample


def test_journal_marker_first_loses_atomicity():
    v = check_broken("journal", "marker_first").violation
    assert "batch-before-marker" in v.message
    assert any(a.startswith("crash(") for a in v.trace)
    assert v.trace[0] == "mark(seq=0)"


# ---------------------------------------------------------------------------
# counterexample -> FaultPlan export
# ---------------------------------------------------------------------------


def test_export_clean_result_raises():
    with pytest.raises(ValueError, match="no counterexample"):
        export_faultplan(check_protocol("journal"))


def test_election_export_is_deterministic_and_round_trips():
    r = check_broken("election", "unfenced")
    plan = export_faultplan(r)
    assert plan.seed == trace_seed(r.violation)
    points = {rule.point for rule in plan.rules}
    assert points == {"election.promote", "election.detect"}
    # the schedule holds the FIRST winner's promotion open and stalls
    # the OTHER standby's detection (owners from the trace)
    owners = {rule.point: rule.owner for rule in plan.rules}
    assert owners["election.promote"] == "standby-0"
    assert owners["election.detect"] == "standby-1"
    # bit-stable: the JSON is the reproduction recipe
    assert export_json(r) == export_json(
        check_broken("election", "unfenced"))
    back = FaultPlan.from_json(export_json(r))
    assert back.seed == plan.seed
    assert [ru.point for ru in back.rules] == [
        ru.point for ru in plan.rules]


def test_journal_export_kills_the_marker_window():
    plan = export_faultplan(check_broken("journal", "marker_first"))
    assert all(ru.point == "journal.before_marker" for ru in plan.rules)
    assert all(ru.action == "kill" for ru in plan.rules)


# ---------------------------------------------------------------------------
# the model -> implementation round-trip (the ISSUE-18 acceptance pin):
# the exported schedule reproduces a REAL split brain on the unfenced
# group, and the REAL fence absorbs the exact same schedule
# ---------------------------------------------------------------------------


def test_exported_plan_reproduces_split_brain_unfenced():
    from lux_tpu.fault.chaos import election_drill
    plan = export_faultplan(check_broken("election", "unfenced"))
    rep = election_drill(plan, fenced=False)
    assert rep["elections"] == 2, rep  # the model's violation, live
    assert sorted(rep["outcomes"].values()) == ["won", "won"], rep
    assert rep["fired"] > 0, "the exported schedule never injected"


def test_fence_absorbs_the_same_schedule():
    from lux_tpu.fault.chaos import election_drill
    plan = export_faultplan(check_broken("election", "unfenced"))
    rep = election_drill(plan, fenced=True)
    assert rep["elections"] == 1, rep
    assert sorted(rep["outcomes"].values()) == ["adopted", "won"], rep


# ---------------------------------------------------------------------------
# trace-replay conformance
# ---------------------------------------------------------------------------


def test_recorded_chaos_logs_conform():
    for name in ("chaos_soak_seed0.json",
                 "chaos_soak_failover_seed3.json"):
        events = _fixture(name)
        assert conform.detect_kind(events) == "chaos_soak"
        assert conform.replay(events) == [], name


def test_recorded_autopilot_log_conforms():
    events = _fixture("autopilot_soak_seed0.json")
    assert conform.detect_kind(events) == "autopilot_soak"
    assert conform.replay(events) == []


def test_live_chaos_soak_conforms():
    """The fixture logs must not drift from live behavior: a fresh
    soak's events replay conformant too."""
    from lux_tpu.fault.chaos import chaos_soak
    rep = chaos_soak(seed=0, steps=10)
    bad = conform.replay(rep["events"])
    assert bad == [], [nc.format() for nc in bad]


def test_live_autopilot_soak_conforms():
    from lux_tpu.fault.chaos import autopilot_soak
    rep = autopilot_soak(0, steps=3, scale=6, cap=32, rows=8)
    bad = conform.replay(rep["events"], kind="autopilot_soak")
    assert bad == [], [nc.format() for nc in bad]


def test_conformance_catches_doctored_transitions():
    events = _fixture("chaos_soak_failover_seed3.json")

    def rules_for(mutate):
        evs = [dict(e) for e in events]
        mutate(evs)
        return {nc.rule for nc in conform.replay(evs)}

    def gen_jump(evs):
        w = next(e for e in evs if e["ev"] == "write")
        w["gen"] = 99

    def stale_on_fresh(evs):
        r = next(e for e in evs if e["ev"] == "read")
        r["stale"] = True

    def second_failover(evs):
        f = next(e for e in evs if e["ev"] == "failover")
        evs.append(dict(f))

    def double_kill(evs):
        k = next(e for e in evs if e["ev"] == "kill")
        evs.insert(evs.index(k) + 1, dict(k))

    def lost_writes_promotion(evs):
        f = next(e for e in evs if e["ev"] == "failover")
        f["gen"] = 0

    assert "genline.gen_gap" in rules_for(gen_jump)
    assert "genline.fresh_required" in rules_for(stale_on_fresh)
    assert "election.refenced" in rules_for(second_failover)
    assert "fleet.double_kill" in rules_for(double_kill)
    assert "journal.promotion_lost_writes" in rules_for(
        lost_writes_promotion)


def test_conformance_empty_log_is_a_finding():
    bad = conform.replay([])
    assert [nc.rule for nc in bad] == ["trace.empty"]
    assert bad[0].index == -1


def test_conformance_unknown_kind_and_event():
    assert [nc.rule for nc in conform.replay([{"ev": "write"}],
                                             kind="nope")] \
        == ["trace.unknown_kind"]
    rules = {nc.rule for nc in conform.replay(
        [{"i": 0, "ev": "teleport"}])}
    assert "trace.unknown_event" in rules


# ---------------------------------------------------------------------------
# the CLI: jax-free gate semantics (exit codes, filter-as-finding)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def luxproto_main():
    tools = os.path.join(REPO, "tools")
    if tools not in sys.path:
        sys.path.insert(0, tools)
    spec = importlib.util.spec_from_file_location(
        "luxproto", os.path.join(tools, "luxproto.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.main


def test_cli_all_twins_is_clean(luxproto_main, capsys):
    assert luxproto_main(["--all", "--twins"]) == 0
    out = capsys.readouterr().out
    assert "[PASS] luxproto" in out
    assert "fails as designed" in out


def test_cli_empty_filter_is_a_finding(luxproto_main, capsys):
    assert luxproto_main(["--protocols", ","]) == 1
    assert "selected NOTHING" in capsys.readouterr().err


def test_cli_unknown_protocol_is_a_finding(luxproto_main, capsys):
    assert luxproto_main(["--protocols", "election,bogus"]) == 1
    err = capsys.readouterr().err
    assert "unknown protocol 'bogus'" in err


def test_cli_replay_fixtures(luxproto_main, capsys):
    logs = [os.path.join(DATA, n) for n in (
        "chaos_soak_seed0.json", "autopilot_soak_seed0.json")]
    assert luxproto_main(["--replay"] + logs) == 0
    assert "2 log(s) conform" in capsys.readouterr().out


def test_cli_replay_flags_doctored_log(luxproto_main, tmp_path,
                                       capsys):
    events = _fixture("chaos_soak_seed0.json")
    events[0]["gen"] = 50
    bad = tmp_path / "doctored.json"
    bad.write_text(json.dumps(events))
    assert luxproto_main(["--replay", str(bad)]) == 1
    assert "genline.gen_gap" in capsys.readouterr().out


def test_cli_export_twin_prints_plan_json(luxproto_main, capsys):
    assert luxproto_main(["--export", "election:unfenced"]) == 0
    plan = FaultPlan.from_json(capsys.readouterr().out)
    assert {r.point for r in plan.rules} == {
        "election.promote", "election.detect"}
