"""mxscan: the MXU-resident blocked segmented scan (ISSUE 11).

Pins, all in interpret mode on CPU (correctness never waits on a chip
window):

1. the kernel (ops/pallas_scan.mxscan_segmented) matches a pure-Python
   segmented-scan reference — carry across rows AND tiles, padding
   neutralized in-kernel, vmapped over parts;
2. the ``segment_*_csc`` method="mxscan" path is BITWISE equal to the
   VPU ladder for int32 sums and min/max (f32 and bf16 included) across
   segment geometries — empty segments, single-element segments, one
   all-covering segment, hubs, ragged tails vs the tile size — and
   within the documented tolerance for f32/bf16 float sums (the MXU
   contraction owns its deterministic association, like mxsum vs scan);
3. (E, K) values fall back to the VPU scan bitwise; the bucketed
   row_ptr-free path (segment_reduce_by_ends) runs mxscan for 1-D
   values, downgrades prefix-diff strategies to 'scan', and its
   validator names the accepted set and env knob;
4. the mxsum 1-D-only restriction is LIFTED: matmul_cumsum handles
   (E, K) values (the former silent degrade to a plain cumsum is gone);
5. engine-vs-direct parity through pull (pagerank, tolerance) and push
   (sssp, bitwise — min never touches the MXU), plus the zero-retrace
   contract: segment geometry is data, one compile serves every census;
6. ``sum_mode()``/``resolve_sum()`` resolution: env override, the
   banked ``tpu:sum`` overlay winner followed on TPU only (CPU runs
   bitwise-unchanged), explicit methods passing through untouched;
7. roofline + audit: mxscan is accounted (REDUCE_HBM_PASSES/byte/flop
   models), the LUX-J4 residency ledger and LUX-J501 one-kernel
   accounting run clean, and a seeded over-budget geometry is a
   finding.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from lux_tpu.engine import methods
from lux_tpu.ops import pallas_scan as PS
from lux_tpu.ops import segment


def _ref_scan(vals, heads, op):
    """Pure-Python inclusive segmented scan (the oracle)."""
    out = np.empty_like(vals)
    fn = {"sum": np.add, "min": np.minimum, "max": np.maximum}[op]
    acc = None
    for i in range(len(vals)):
        acc = vals[i] if (heads[i] or acc is None) else fn(acc, vals[i])
        out[i] = acc
    return out


def _csc(widths, pad=0, pad_value=0):
    """(row_ptr, head_flag, dst_local, e_pad) for explicit segment
    widths — the geometry knob every bitwise test turns."""
    widths = np.asarray(widths, np.int64)
    rp = np.concatenate([[0], np.cumsum(widths)]).astype(np.int32)
    ne = int(rp[-1])
    e_pad = ne + pad
    head = np.zeros(e_pad, bool)
    starts = rp[:-1][rp[1:] > rp[:-1]]
    head[starts] = True
    dst = np.full(e_pad, len(widths), np.int32)
    dst[:ne] = np.repeat(np.arange(len(widths), dtype=np.int32), widths)
    return rp, head, dst, e_pad


def _seg_oracle(widths, vals, op, dtype):
    neutral = {"sum": 0,
               "min": (np.inf if np.issubdtype(dtype, np.floating)
                       else np.iinfo(dtype).max),
               "max": (-np.inf if np.issubdtype(dtype, np.floating)
                       else np.iinfo(dtype).min)}[op]
    out = np.full(len(widths), neutral,
                  np.float64 if np.issubdtype(dtype, np.floating)
                  else np.int64)
    fn = {"sum": np.add, "min": np.minimum, "max": np.maximum}[op]
    e = 0
    for i, w in enumerate(widths):
        for _ in range(int(w)):
            out[i] = fn(out[i], vals[e])
            e += 1
    return out


#: the geometry matrix of the ISSUE: empty segments, single-element
#: segments, one all-covering segment, a hub, ragged tails vs the
#: (8, 128) default tile, and widths spanning row/tile boundaries
GEOMETRIES = [
    ("empties", [0, 3, 0, 0, 5, 0, 2, 0]),
    ("singles", [1] * 70),
    ("one_segment", [517]),
    ("hub", [600, 1, 0, 7, 1]),
    ("ragged_tail", [100, 100, 100, 29]),  # 329: not a lane multiple
    ("tile_spanning", [90, 300, 700, 41]),  # crosses rows AND tiles
]


# ---------------------------------------------------------------------------
# the kernel, against the pure-Python oracle
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("op", ["sum", "min", "max"])
@pytest.mark.parametrize("n", [5, 128, 1024, 5000])
def test_kernel_matches_reference(op, n, rng):
    heads = rng.random(n) < 0.1
    heads[0] = True
    vals = rng.standard_normal(n).astype(np.float32)
    inv = np.zeros(n, bool)
    got = np.asarray(PS.mxscan_segmented(
        jnp.asarray(vals), jnp.asarray(heads), jnp.asarray(inv), op=op))
    want = _ref_scan(vals, heads, op)
    if op == "sum":
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    else:
        np.testing.assert_array_equal(got, want)


def test_kernel_int32_bitwise(rng):
    n = 3000
    heads = rng.random(n) < 0.05
    heads[0] = True
    vals = rng.integers(-10_000, 10_000, n).astype(np.int32)
    got = np.asarray(PS.mxscan_segmented(
        jnp.asarray(vals), jnp.asarray(heads),
        jnp.asarray(np.zeros(n, bool)), op="sum"))
    assert got.dtype == np.int32
    np.testing.assert_array_equal(got, _ref_scan(vals, heads, "sum"))


def test_kernel_carry_spans_tiles(rng):
    """ONE segment covering many (8, 128) tiles: the scratch carry must
    thread every row and tile boundary."""
    n = 5 * 8 * 128 + 77
    heads = np.zeros(n, bool)
    heads[0] = True
    vals = np.ones(n, np.float32)
    got = np.asarray(PS.mxscan_segmented(
        jnp.asarray(vals), jnp.asarray(heads),
        jnp.asarray(np.zeros(n, bool)), op="sum"))
    # integer-valued f32: exact under any association
    np.testing.assert_array_equal(got, np.arange(1, n + 1, dtype=np.float32))


def test_kernel_masks_nonfinite_padding(rng):
    """NaN/Inf junk in PADDING slots must not poison real outputs (the
    0 * NaN = NaN matmul hazard, docs/PERF.md precision caveat)."""
    n = 400
    heads = rng.random(n) < 0.1
    heads[0] = True
    vals = rng.standard_normal(n).astype(np.float32)
    vals[-20:] = np.nan
    vals[-21] = np.inf
    inv = np.zeros(n, bool)
    inv[-21:] = True
    got = np.asarray(PS.mxscan_segmented(
        jnp.asarray(vals), jnp.asarray(heads), jnp.asarray(inv),
        op="sum"))
    want = _ref_scan(np.where(inv, 0, vals), heads, "sum")
    np.testing.assert_allclose(got[:-21], want[:-21], rtol=1e-5,
                               atol=1e-5)
    assert np.isfinite(got[:-21]).all()


def test_kernel_vmapped_parts_isolated(rng):
    """vmap over parts: the sequential carry resets at tile 0 of every
    batch element (the engine's multi-part dispatch)."""
    P, n = 3, 700
    vals = rng.standard_normal((P, n)).astype(np.float32)
    heads = rng.random((P, n)) < 0.08
    heads[:, 0] = True
    inv = np.zeros((P, n), bool)
    got = np.asarray(jax.vmap(
        lambda v, h, i: PS.mxscan_segmented(v, h, i, op="sum"))(
            jnp.asarray(vals), jnp.asarray(heads), jnp.asarray(inv)))
    want = np.stack([_ref_scan(vals[p], heads[p], "sum")
                     for p in range(P)])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_kernel_validators():
    v = jnp.ones((4, 2), jnp.float32)
    with pytest.raises(ValueError, match="1-D"):
        PS.mxscan_segmented(v, jnp.ones((4, 2), bool),
                            jnp.zeros((4, 2), bool))
    with pytest.raises(ValueError, match="sum"):
        PS.mxscan_segmented(jnp.ones(4), jnp.ones(4, bool),
                            jnp.zeros(4, bool), op="prod")
    with pytest.raises(ValueError, match="LUX_MXSCAN_TILE_ROWS"):
        PS._mxscan_defaults(3)


# ---------------------------------------------------------------------------
# segment_*_csc: the bitwise matrix across geometries
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name,widths", GEOMETRIES)
@pytest.mark.parametrize("op", ["sum", "min", "max"])
def test_csc_int32_bitwise_across_geometries(name, widths, op, rng):
    """int32 across the geometry matrix: mxscan == scan BITWISE (and
    both == the oracle) — integer combines are order-insensitive."""
    rp, head, dst, e_pad = _csc(widths, pad=rng.integers(0, 40))
    vals = np.full(e_pad, 123456, np.int32)  # junk pad, masked in-kernel
    ne = int(rp[-1])
    vals[:ne] = rng.integers(-50_000, 50_000, ne)
    fn = {"sum": segment.segment_sum_csc, "min": segment.segment_min_csc,
          "max": segment.segment_max_csc}[op]
    args = (jnp.asarray(vals), jnp.asarray(rp), jnp.asarray(head),
            jnp.asarray(dst))
    ref = np.asarray(fn(*args, method="scan"))
    got = np.asarray(fn(*args, method="mxscan"))
    assert got.dtype == np.int32
    np.testing.assert_array_equal(ref, got, err_msg=name)
    oracle = _seg_oracle(widths, vals[:ne], op, np.int32)
    np.testing.assert_array_equal(got, oracle.astype(np.int32))


@pytest.mark.parametrize("op", ["min", "max"])
@pytest.mark.parametrize("dtype", [np.float32, "bfloat16"])
def test_csc_float_minmax_bitwise(op, dtype, rng):
    """min/max never touch the MXU: dtype-preserving, bitwise vs the
    ladder — Inf sentinels included (the sssp shape)."""
    rp, head, dst, e_pad = _csc([5, 0, 900, 1, 33, 0, 7], pad=13)
    vals_np = rng.standard_normal(e_pad).astype(np.float32)
    vals_np[3] = np.inf
    vals = jnp.asarray(vals_np)
    if dtype == "bfloat16":
        vals = vals.astype(jnp.bfloat16)
    fn = (segment.segment_min_csc if op == "min"
          else segment.segment_max_csc)
    args = (vals, jnp.asarray(rp), jnp.asarray(head), jnp.asarray(dst))
    ref = fn(*args, method="scan")
    got = fn(*args, method="mxscan")
    assert got.dtype == vals.dtype
    np.testing.assert_array_equal(
        np.asarray(ref.astype(jnp.float32)),
        np.asarray(got.astype(jnp.float32)))


@pytest.mark.parametrize("name,widths", GEOMETRIES)
def test_csc_f32_sum_tolerance(name, widths, rng):
    """General f32 sums: mxscan's own deterministic association, equal
    to the f64 oracle within the documented tolerance (rtol 1e-5 —
    accumulation stays WITHIN a segment, in f32, so there is no
    global-prefix caveat) and run-to-run deterministic."""
    rp, head, dst, e_pad = _csc(widths, pad=7)
    vals = np.zeros(e_pad, np.float32)
    ne = int(rp[-1])
    vals[:ne] = rng.standard_normal(ne)
    args = (jnp.asarray(vals), jnp.asarray(rp), jnp.asarray(head),
            jnp.asarray(dst))
    got = np.asarray(segment.segment_sum_csc(*args, method="mxscan"))
    oracle = _seg_oracle(widths, vals[:ne], "sum", np.float32)
    np.testing.assert_allclose(got, oracle, rtol=1e-5, atol=1e-5,
                               err_msg=name)
    np.testing.assert_array_equal(
        got, np.asarray(segment.segment_sum_csc(*args, method="mxscan")))


def test_csc_bf16_sum_tolerance(rng):
    """bf16 sums: bf16 operands (already the storage precision), f32
    accumulation in-kernel, ONE rounding back to bf16 per tile row —
    strictly tighter than the ladder's per-element bf16 rounding, so
    the pin is against the f32 oracle at bf16 input resolution."""
    rp, head, dst, e_pad = _csc([40, 0, 300, 9, 1], pad=5)
    ne = int(rp[-1])
    vals_np = rng.standard_normal(e_pad).astype(np.float32)
    vals = jnp.asarray(vals_np).astype(jnp.bfloat16)
    got = segment.segment_sum_csc(
        vals, jnp.asarray(rp), jnp.asarray(head), jnp.asarray(dst),
        method="mxscan")
    assert got.dtype == jnp.bfloat16
    oracle = _seg_oracle(
        [40, 0, 300, 9, 1],
        np.asarray(vals.astype(jnp.float32))[:ne], "sum", np.float32)
    np.testing.assert_allclose(
        np.asarray(got.astype(jnp.float32)), oracle, rtol=2e-2,
        atol=2e-2)


def test_csc_f32_exact_case_bitwise(rng):
    """Integer-valued f32 sums are exact under ANY association: mxscan
    must equal the ladder bit for bit."""
    rp, head, dst, e_pad = _csc([3, 200, 0, 57, 1000, 1], pad=11)
    vals = np.zeros(e_pad, np.float32)
    ne = int(rp[-1])
    vals[:ne] = rng.integers(-1000, 1000, ne).astype(np.float32)
    args = (jnp.asarray(vals), jnp.asarray(rp), jnp.asarray(head),
            jnp.asarray(dst))
    ref = np.asarray(segment.segment_sum_csc(*args, method="scan"))
    got = np.asarray(segment.segment_sum_csc(*args, method="mxscan"))
    np.testing.assert_array_equal(ref, got)


def test_csc_2d_falls_back_to_scan_bitwise(rng):
    """(E, K) values: the blocked kernel is 1-D, so method='mxscan'
    must produce EXACTLY the ladder scan's bits (the engine-safety
    contract — a banked winner can never crash the CF/feat shapes)."""
    rp, head, dst, e_pad = _csc([10, 0, 25, 3], pad=4)
    vals = rng.standard_normal((e_pad, 5)).astype(np.float32)
    args = (jnp.asarray(vals), jnp.asarray(rp), jnp.asarray(head),
            jnp.asarray(dst))
    for fn in (segment.segment_sum_csc, segment.segment_min_csc):
        ref = np.asarray(fn(*args, method="scan"))
        got = np.asarray(fn(*args, method="mxscan"))
        np.testing.assert_array_equal(ref, got)


@pytest.mark.parametrize("tile_rows", [1, 2, 32])
def test_tile_rows_knob_geometries_bitwise(tile_rows, rng):
    """Every legal tile geometry lands identical bits for the exact
    cases — the knob shapes the kernel, never the math."""
    n = 1000
    heads = rng.random(n) < 0.07
    heads[0] = True
    vals = rng.integers(-500, 500, n).astype(np.int32)
    inv = np.zeros(n, bool)
    base = np.asarray(PS.mxscan_segmented(
        jnp.asarray(vals), jnp.asarray(heads), jnp.asarray(inv),
        op="sum"))
    got = np.asarray(PS.mxscan_segmented(
        jnp.asarray(vals), jnp.asarray(heads), jnp.asarray(inv),
        op="sum", tile_rows=tile_rows))
    np.testing.assert_array_equal(base, got)


# ---------------------------------------------------------------------------
# the bucketed (row_ptr-free) path
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("reduce", ["sum", "min", "max"])
def test_by_ends_mxscan(reduce, rng):
    from lux_tpu.parallel.ring import mark_bucket_heads

    V, m, B = 37, 60, 128
    dl = np.sort(rng.integers(0, V, size=m)).astype(np.int32)
    dst = np.full(B, V, np.int32)
    dst[:m] = dl
    head = np.zeros(B, bool)
    mark_bucket_heads(head, dl)
    vals = np.full(B, np.nan, np.float32)  # junk pads, sentinel-masked
    vals[:m] = rng.random(m).astype(np.float32) + 0.5
    args = (jnp.asarray(vals), jnp.asarray(head), jnp.asarray(dst), V)
    ref = np.asarray(segment.segment_reduce_by_ends(
        *args, reduce=reduce, method="scan"))
    got = np.asarray(segment.segment_reduce_by_ends(
        *args, reduce=reduce, method="mxscan"))
    if reduce == "sum":
        np.testing.assert_allclose(ref, got, rtol=1e-5, atol=1e-6)
    else:
        np.testing.assert_array_equal(ref, got)


def test_by_ends_mxscan_full_bucket():
    """m == B: the appended end flag must close the final segment (the
    ladder contract, now through the kernel)."""
    V, B = 5, 8
    dl = np.array([0, 0, 1, 1, 1, 3, 4, 4], np.int32)
    from lux_tpu.parallel.ring import mark_bucket_heads

    head = np.zeros(B, bool)
    mark_bucket_heads(head, dl)
    vals = np.arange(1, 9, dtype=np.float32)
    got = segment.segment_reduce_by_ends(
        jnp.asarray(vals), jnp.asarray(head), jnp.asarray(dl), V,
        reduce="sum", method="mxscan")
    np.testing.assert_allclose(np.asarray(got), [3, 12, 0, 6, 15])


def test_by_ends_downgrades_and_validator(rng):
    """cumsum/mxsum (and mxscan on (E, K)) downgrade to the shipped
    'scan' BITWISE; an unknown method's error names the accepted set
    and the env knob (the ISSUE's validator satellite)."""
    from lux_tpu.parallel.ring import mark_bucket_heads

    V, m, B = 11, 30, 64
    dl = np.sort(rng.integers(0, V, size=m)).astype(np.int32)
    dst = np.full(B, V, np.int32)
    dst[:m] = dl
    head = np.zeros(B, bool)
    mark_bucket_heads(head, dl)
    vals = np.zeros(B, np.float32)
    vals[:m] = rng.random(m).astype(np.float32)
    args = (jnp.asarray(vals), jnp.asarray(head), jnp.asarray(dst), V)
    ref = np.asarray(segment.segment_reduce_by_ends(
        *args, reduce="sum", method="scan"))
    for m_ in ("cumsum", "mxsum"):
        got = np.asarray(segment.segment_reduce_by_ends(
            *args, reduce="sum", method=m_))
        np.testing.assert_array_equal(ref, got)
    vk = jnp.asarray(rng.random((B, 3)).astype(np.float32))
    ref_k = np.asarray(segment.segment_reduce_by_ends(
        vk, jnp.asarray(head), jnp.asarray(dst), V, reduce="sum",
        method="scan"))
    got_k = np.asarray(segment.segment_reduce_by_ends(
        vk, jnp.asarray(head), jnp.asarray(dst), V, reduce="sum",
        method="mxscan"))
    np.testing.assert_array_equal(ref_k, got_k)
    with pytest.raises(ValueError, match="LUX_SUM_MODE"):
        segment.segment_reduce_by_ends(*args, reduce="sum",
                                       method="bogus")


def test_csc_validators_name_set_and_knob():
    v = jnp.ones(8, jnp.float32)
    rp = jnp.asarray(np.array([0, 8], np.int32))
    hf = jnp.asarray(np.array([True] + [False] * 7))
    with pytest.raises(ValueError, match="LUX_SUM_MODE"):
        segment.segment_sum_csc(v, rp, hf, method="bogus")
    with pytest.raises(ValueError, match="LUX_SUM_MODE"):
        segment.segment_min_csc(v, rp, hf, method="mxsum")


# ---------------------------------------------------------------------------
# the lifted mxsum restriction
# ---------------------------------------------------------------------------


def test_matmul_cumsum_2d_lifted(rng):
    """matmul_cumsum now handles (E, K) values (the former silent
    degrade to a plain cumsum is gone — ISSUE 11 satellite)."""
    for shape in ((7, 3), (513, 4), (5000, 2)):
        x = rng.random(shape).astype(np.float32)
        got = np.asarray(segment.matmul_cumsum(jnp.asarray(x)))
        want = np.cumsum(x.astype(np.float64), axis=0)
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-4)


def test_segment_sum_2d_mxsum_rides_matmul(rng):
    """(E, K) mxsum goes through the triangular-matmul cumsum and still
    matches the oracle within the documented global-prefix tolerance."""
    from lux_tpu.graph import generate
    from lux_tpu.graph.shards import build_pull_shards

    g = generate.uniform_random(60, 400, seed=5)
    sh = build_pull_shards(g, 1)
    arr = sh.arrays
    K = 8
    vals = np.zeros((sh.spec.e_pad, K), np.float32)
    vals[: g.ne] = rng.random((g.ne, K))
    out = segment.segment_sum_csc(
        jnp.asarray(vals), jnp.asarray(arr.row_ptr[0]),
        jnp.asarray(arr.head_flag[0]), method="mxsum")
    dst = g.dst_of_edges()
    expect = np.zeros((g.nv, K), np.float32)
    np.add.at(expect, dst, vals[: g.ne])
    np.testing.assert_allclose(np.asarray(out)[: g.nv], expect,
                               rtol=5e-3, atol=1e-4)


# ---------------------------------------------------------------------------
# engine parity + zero-retrace
# ---------------------------------------------------------------------------


def test_pull_engine_mxscan_matches_scan():
    from lux_tpu.graph import generate
    from lux_tpu.models import pagerank as pr

    g = generate.rmat(8, 8, seed=15)
    for parts in (1, 3):
        base = pr.pagerank(g, num_iters=5, method="scan",
                           num_parts=parts)
        got = pr.pagerank(g, num_iters=5, method="mxscan",
                          num_parts=parts)
        np.testing.assert_allclose(
            np.asarray(got, np.float64), np.asarray(base, np.float64),
            rtol=1e-4, atol=1e-7)


def test_push_engine_mxscan_bitwise():
    """Push (sssp, reduce=min): mxscan's min path never touches the
    MXU, so the whole frontier run is BITWISE the scan engine's."""
    from lux_tpu.graph import generate
    from lux_tpu.models import sssp as ss

    g = generate.rmat(8, 8, seed=5)
    a = ss.sssp(g, 0, method="scan")
    b = ss.sssp(g, 0, method="mxscan")
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_auto_dispatch_through_sum_mode(monkeypatch):
    """The end-to-end wiring: with a forced scan-family winner,
    method='auto' on a (virtual) TPU platform must produce EXACTLY the
    explicit method='mxscan' run — the resolver the engines consult."""
    from lux_tpu.graph import generate
    from lux_tpu.models import pagerank as pr

    monkeypatch.setenv("LUX_METHOD_PLATFORM", "tpu")
    monkeypatch.setenv("LUX_SUM_MODE", "mxscan")
    g = generate.rmat(8, 4, seed=3)
    auto = pr.pagerank(g, num_iters=4, method="auto")
    explicit = pr.pagerank(g, num_iters=4, method="mxscan")
    np.testing.assert_array_equal(np.asarray(auto), np.asarray(explicit))


def test_zero_retrace_across_geometries(rng):
    """Segment geometry is DATA: one compile serves every census (the
    LUX-J1 contract the audit unit also pins)."""
    n = 800

    @jax.jit
    def run(v, rp, hf, dl):
        return segment.segment_sum_csc(v, rp, hf, dl, method="mxscan")

    for widths in ([100, 300, 390], [1] * 79, [779]):
        rp, head, dst, e_pad = _csc(widths, pad=n - sum(widths) - 1)
        # pad out to ONE shared shape so only the geometry values vary
        vals = np.zeros(n, np.float32)
        rp_fix = np.zeros(80 + 1, np.int32)
        rp_fix[1:len(rp)] = rp[1:]
        rp_fix[len(rp):] = rp[-1]
        head_fix = np.zeros(n, bool)
        head_fix[:len(head)] = head
        dst_fix = np.full(n, 80, np.int32)
        dst_fix[:len(dst)] = dst
        run(jnp.asarray(vals), jnp.asarray(rp_fix),
            jnp.asarray(head_fix), jnp.asarray(dst_fix))
    assert run._cache_size() == 1


# ---------------------------------------------------------------------------
# sum_mode / resolve_sum resolution
# ---------------------------------------------------------------------------


def _reset_overlay_caches(monkeypatch):
    monkeypatch.setattr(methods, "_overlay_raw_cache", None)
    monkeypatch.setattr(methods, "_file_winners_cache", None)
    monkeypatch.setattr(methods, "_tiles_cache", None)


def test_sum_mode_default_and_env(monkeypatch):
    monkeypatch.delenv("LUX_SUM_MODE", raising=False)
    assert methods.sum_mode("tpu") == "scan"
    assert methods.sum_mode("cpu") == "scan"
    monkeypatch.setenv("LUX_SUM_MODE", "mxscan")
    assert methods.sum_mode("cpu") == "mxscan"  # env = explicit choice
    # the env choice wins under auto EVERYWHERE — including platforms
    # whose blanket winner is not "scan" (the review fix: 'LUX_SUM_MODE
    # forces a flavor anywhere' must hold on the CPU scatter default)
    assert methods.resolve_sum("auto", "sum", "cpu") == "mxscan"
    assert methods.resolve_sum("auto", "sum", "tpu") == "mxscan"
    assert methods.resolve_sum("auto", "min", "cpu") == "scatter"
    assert methods.resolve_sum("scatter", "sum", "cpu") == "scatter"
    monkeypatch.setenv("LUX_SUM_MODE", "bogus")
    with pytest.raises(ValueError, match="LUX_SUM_MODE"):
        methods.sum_mode("tpu")


def test_sum_mode_follows_banked_winner_tpu_only(monkeypatch, tmp_path):
    """The acceptance contract: a banked tpu:sum winner retires the VPU
    default ON TPU ONLY — CPU resolution is bitwise-unchanged."""
    import json

    f = tmp_path / "w.json"
    f.write_text(json.dumps({"tpu:sum": "mxscan"}))
    monkeypatch.setenv("LUX_METHOD_WINNERS", str(f))
    _reset_overlay_caches(monkeypatch)
    assert methods.sum_mode("tpu") == "mxscan"
    assert methods.sum_mode("axon") == "mxscan"  # the tunneled chip
    assert methods.sum_mode("cpu") == "scan"
    assert methods.resolve_sum("auto", "sum", "tpu") == "mxscan"
    # min/max rows and CPU rows untouched; explicit choice wins
    assert methods.resolve_sum("auto", "min", "tpu") == "scan"
    assert methods.resolve_sum("auto", "sum", "cpu") == "scatter"
    assert methods.resolve_sum("scan", "sum", "tpu") == "scan"
    assert methods.resolve_sum("mxsum", "sum", "tpu") == "mxsum"
    # blanket resolve() is UNCHANGED by a scan-family entry (the
    # bucketed layouts' contract): mxscan is not a blanket winner
    assert methods.resolve("auto", "sum", "tpu") == "scan"
    _reset_overlay_caches(monkeypatch)


def test_sum_mode_ignores_non_family_entries(monkeypatch, tmp_path):
    """tpu:sum may also hold the app-race's blanket winner ('scatter'):
    sum_mode ignores it (resolve() already followed it) and a garbage
    entry reads as the default."""
    import json

    f = tmp_path / "w.json"
    f.write_text(json.dumps({"tpu:sum": "scatter"}))
    monkeypatch.setenv("LUX_METHOD_WINNERS", str(f))
    _reset_overlay_caches(monkeypatch)
    assert methods.sum_mode("tpu") == "scan"
    assert methods.resolve_sum("auto", "sum", "tpu") == "scatter"
    f.write_text(json.dumps({"tpu:sum": "pallas"}))
    _reset_overlay_caches(monkeypatch)
    assert methods.sum_mode("tpu") == "scan"
    assert methods.resolve_sum("auto", "sum", "tpu") == "scan"
    _reset_overlay_caches(monkeypatch)


def test_mxsum_banked_follows_on_csc_paths(monkeypatch, tmp_path):
    """mxsum banked under tpu:sum (possible: it is in the three-way
    race) flows to the csc engines through the SAME refinement."""
    import json

    f = tmp_path / "w.json"
    f.write_text(json.dumps({"tpu:sum": "mxsum"}))
    monkeypatch.setenv("LUX_METHOD_WINNERS", str(f))
    _reset_overlay_caches(monkeypatch)
    assert methods.resolve_sum("auto", "sum", "tpu") == "mxsum"
    assert methods.resolve("auto", "sum", "tpu") == "scan"
    _reset_overlay_caches(monkeypatch)


def test_cli_auto_reaches_banked_winner(monkeypatch, capsys):
    """The review fix: the app CLIs pre-resolve --method auto through
    resolve_sum, so a banked/forced scan-family winner actually reaches
    the engines from `python -m lux_tpu.apps.*` — and downgrades (with
    a note) before the bucketed exchanges, where an EXPLICIT choice
    still fails loudly."""
    from lux_tpu.apps import common
    from lux_tpu.models.pagerank import PageRankProgram
    from lux_tpu.utils.config import parse_args

    monkeypatch.setenv("LUX_METHOD_PLATFORM", "tpu")
    monkeypatch.setenv("LUX_SUM_MODE", "mxscan")
    prog = PageRankProgram(nv=16)
    cfg = parse_args([], pull=True)
    common.validate_exchange(cfg, prog)
    assert cfg.method == "mxscan"
    cfg = parse_args(["--distributed", "--exchange", "ring"], pull=True)
    common.validate_exchange(cfg, prog)
    assert cfg.method == "scan"  # blanket winner, with a stderr note
    assert "downgraded" in capsys.readouterr().err
    cfg = parse_args(["--distributed", "--exchange", "ring",
                      "--method", "mxscan"], pull=True)
    with pytest.raises(SystemExit, match="scan or scatter"):
        common.validate_exchange(cfg, prog)


def test_record_sum_family_winner_preserves_scatter(monkeypatch,
                                                    tmp_path):
    """The review fix: a scan-family race (which never times scatter)
    must not clobber a measured blanket 'scatter' tpu:sum winner; any
    other prior value may be overwritten (last full measurement
    wins)."""
    import json

    f = tmp_path / "w.json"
    monkeypatch.setenv("LUX_METHOD_WINNERS", str(f))
    _reset_overlay_caches(monkeypatch)
    assert methods.record_sum_family_winner("mxscan") is True
    assert json.loads(f.read_text())["tpu:sum"] == "mxscan"
    methods.record_overlay_entry("tpu:sum", "scatter")
    assert methods.record_sum_family_winner("mxsum") is False
    assert json.loads(f.read_text())["tpu:sum"] == "scatter"
    methods.record_overlay_entry("tpu:sum", "scan")
    assert methods.record_sum_family_winner("mxsum") is True
    assert json.loads(f.read_text())["tpu:sum"] == "mxsum"
    _reset_overlay_caches(monkeypatch)


def test_concrete_set_includes_mxscan():
    assert "mxscan" in methods.CONCRETE
    assert methods.SUM_MODES == ("scan", "mxsum", "mxscan")
    assert methods.SUM_MODE_KEY == "tpu:sum"


# ---------------------------------------------------------------------------
# accounting + audit
# ---------------------------------------------------------------------------


def test_roofline_accounts_mxscan():
    from lux_tpu.utils import roofline

    assert roofline.REDUCE_HBM_PASSES["mxscan"] == 2
    passes = roofline.pull_hbm_passes("mxscan")
    assert passes["total"] == roofline.pull_hbm_passes("scan")["total"]
    # bytes: the packed head/pad byte costs +2 B/edge over the ladder's
    # optimistic floor; flops: 2 contractions x T MACs per value
    b_scan = roofline._reduce_bytes_per_edge("scan", 4, 1)
    b_mx = roofline._reduce_bytes_per_edge("mxscan", 4, 1)
    assert b_mx == b_scan + 2
    assert (roofline._reduce_device_flops_per_edge("mxscan", 1)
            == 4 * roofline.MXSCAN_T)
    m = roofline.pull_iter_model(1000, 100, "mxscan")
    assert m.bytes_moved > 0 and m.device_flops > m.flops


def test_audit_units_clean_and_seeded():
    from lux_tpu.analysis.ir import targets, vmem

    assert targets._retrace_pull_fixed_mxscan() == []
    assert targets._vmem_mxscan() == []
    assert targets._hbm_mxscan() == []
    assert targets._hbm_mxscan_ring_neutral() == []
    findings = vmem.check_vmem_mxscan("p", "t", budget_bytes=1)
    assert len(findings) == 1 and findings[0].code == "LUX-J401"
    assert findings[0].text == "t:mxscan"
    labels = {u.label for u in targets.audit_units()}
    assert {"pull-fixed/mxscan", "mxscan", "segment/mxscan",
            "pull-fixed/mxscan/ring-neutral"} <= labels


def test_mxscan_kernel_count_is_one(rng):
    """The LUX-J501 claim behind the exact '2 sweeps' accounting: one
    csc segment sum on method='mxscan' launches exactly ONE kernel."""
    from lux_tpu.analysis.ir import aot

    rp, head, dst, e_pad = _csc([30, 100, 5], pad=9)
    vals = jnp.zeros(e_pad, jnp.float32)

    traced = jax.jit(
        lambda v: segment.segment_sum_csc(
            v, jnp.asarray(rp), jnp.asarray(head), jnp.asarray(dst),
            method="mxscan")).trace(vals)
    assert aot.count_primitive(aot.traced_jaxpr(traced),
                               "pallas_call") == 1


def test_residency_model_positive():
    assert PS.mxscan_residency_bytes(8) > 0
    assert (PS.mxscan_residency_bytes(16)
            > PS.mxscan_residency_bytes(1))
