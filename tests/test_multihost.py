"""REAL multi-process distributed execution: two OS processes, 4 virtual
CPU devices each, jax.distributed over a local coordinator — the closest
CI-able analog of a 2-host DCN deployment (the reference's GASNet
multi-node mode, README.md:33-37, which it cannot test without a cluster;
SURVEY.md §4 point 4)."""
import os
import subprocess
import sys

import jax
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
WORKER = os.path.join(HERE, "mh_worker.py")

# jax 0.4.37's CPU backend cannot run multi-process collectives at all:
# every cross-process computation fails with "INVALID_ARGUMENT:
# Multiprocess computations aren't implemented on the CPU backend"
# (XLA:CPU grew that support in the jax 0.5.x line).  All three tests in
# this file are two-OS-process by design, so on 0.4.37 they are a KNOWN
# environment limitation, not a regression — version-guard them
# explicitly so tier-1 reports 0 failures instead of a memorized trio
# (docs/ANALYSIS.md "Known skips").  Remove the guard when the pinned
# jax moves past 0.4.x.
pytestmark = pytest.mark.skipif(
    jax.__version__.startswith("0.4."),
    reason="jax 0.4.x XLA:CPU lacks multi-process collectives "
           "('Multiprocess computations aren't implemented on the CPU "
           "backend'); real multihost coverage needs jax >= 0.5 or "
           "hardware",
)


def _run_pair(mode: str, timeout: int = 320):
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    env["JAX_PLATFORMS"] = "cpu"
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(pid), "2", mode],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd="/tmp",
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        # never leak workers: a deadlocked pair would keep the coordinator
        # port bound and wedge every later run
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-3000:]}"
    return outs


def test_two_process_distributed_pagerank():
    # 420 s: three compiled engines (dist + ring + scatter) on the
    # 1-core host are compile-dominated on a cold cache, like push
    outs = _run_pair("pull", timeout=420)
    for pid, out in enumerate(outs):
        assert f"process {pid}: multihost pagerank OK" in out
        assert f"process {pid}: multihost ring OK" in out
        assert f"process {pid}: multihost scatter OK" in out


def test_two_process_feat_cf():
    """The 2-D (parts x feat) CF engine across two real OS processes, on
    two mesh layouts so BOTH composed collectives get a process
    boundary: parts all_gather/ppermute (default feat-minor mesh) and
    the cross-feat error-dot psum (interleaved mesh)."""
    outs = _run_pair("feat")
    for pid, out in enumerate(outs):
        assert f"process {pid}: multihost feat-CF OK" in out
        assert f"process {pid}: multihost feat-CF cross-host-psum OK" in out
        assert f"process {pid}: multihost ring-feat-CF OK" in out


def test_two_process_distributed_push():
    """The direction-optimizing push engine (queue all_gathers + psum'd
    switch flags + dense all_gather inside lax.cond) over two real OS
    processes — SSSP to convergence, validated against the BFS oracle."""
    outs = _run_pair("push", timeout=480)
    for pid, out in enumerate(outs):
        assert f"process {pid}: multihost push OK" in out
        assert f"process {pid}: multihost push phase-split OK" in out
        assert f"process {pid}: multihost delta-stepping OK" in out