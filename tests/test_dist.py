"""Multi-chip execution on the virtual 8-device CPU mesh: distributed
results must be bitwise-identical in math to the single-device engine."""
import numpy as np
import pytest

from lux_tpu.engine import pull
from lux_tpu.graph import generate
from lux_tpu.graph.shards import build_pull_shards
from lux_tpu.models import pagerank as pr
from lux_tpu.parallel import dist, mesh as mesh_lib


@pytest.fixture(scope="module")
def mesh8():
    return mesh_lib.make_mesh(8)


def test_dist_pagerank_matches_single(mesh8):
    g = generate.rmat(9, 8, seed=21)
    shards = build_pull_shards(g, 8)
    prog = pr.PageRankProgram(nv=shards.spec.nv)
    state0 = pull.init_state(prog, shards.arrays)

    single = pull.run_pull_fixed(prog, shards.spec, shards.arrays, state0, 8)
    multi = dist.run_pull_fixed_dist(
        prog, shards.spec, shards.arrays, state0, 8, mesh8
    )
    np.testing.assert_allclose(
        np.asarray(multi), np.asarray(single), rtol=1e-6, atol=1e-12
    )
    # and against the host oracle
    got = shards.scatter_to_global(np.asarray(multi))
    np.testing.assert_allclose(got, pr.pagerank_reference(g, 8), rtol=3e-5)


def test_dist_sharding_is_real(mesh8):
    """The state must actually be sharded over the 8 devices, one part each."""
    g = generate.uniform_random(4096, 32768, seed=22)
    shards = build_pull_shards(g, 8)
    prog = pr.PageRankProgram(nv=shards.spec.nv)
    state0 = pull.init_state(prog, shards.arrays)
    out = dist.run_pull_fixed_dist(prog, shards.spec, shards.arrays, state0, 2, mesh8)
    assert len(out.sharding.device_set) == 8
    shard_shapes = {s.data.shape for s in out.addressable_shards}
    assert shard_shapes == {(1, shards.spec.nv_pad)}


def test_dist_until_convergence(mesh8):
    """while_loop + psum convergence path (used by CC/SSSP) on the mesh."""
    from lux_tpu.graph.csc import from_edge_list
    from lux_tpu.models import components

    # Reversed path 63 -> 62 -> ... -> 0: the max label must walk the whole
    # chain, so convergence genuinely takes ~nv iterations of psum'd loop.
    n = 64
    g = from_edge_list(np.arange(1, n), np.arange(0, n - 1), n)
    shards = build_pull_shards(g, 8)

    prog = components.MaxLabelProgram()
    state0 = pull.init_state(prog, shards.arrays)
    final, iters = dist.run_pull_until_dist(
        prog, shards.spec, shards.arrays, state0, 200,
        components.active_count, mesh8,
    )
    labels = shards.scatter_to_global(np.asarray(final))
    np.testing.assert_array_equal(labels, np.full(n, n - 1))
    assert n - 1 <= int(iters) <= n + 1
