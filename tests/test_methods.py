"""Platform-aware method resolution (engine.methods): defaults follow
measured winners (PERF.md), explicit choices pass through untouched."""
import numpy as np

from lux_tpu.engine import methods


def test_explicit_method_passes_through():
    assert methods.resolve("scatter", "sum", "tpu") == "scatter"
    assert methods.resolve("mxsum", "sum", "cpu") == "mxsum"
    assert methods.resolve("scan", "min", "cpu") == "scan"


def test_measured_winners():
    # CPU: scatter beats scan ~2x on the comp phase (BASELINE.md r2 table)
    assert methods.resolve("auto", "sum", "cpu") == "scatter"
    assert methods.resolve("auto", "min", "cpu") == "scatter"
    assert methods.resolve("auto", "max", "cpu") == "scatter"
    # TPU: XLA scatter serializes on-chip (PERF.md r2: 0.06 GTEPS)
    assert methods.resolve("auto", "sum", "tpu") == "scan"
    assert methods.resolve("auto", "min", "tpu") == "scan"
    assert methods.resolve("auto", "max", "tpu") == "scan"


def test_unknown_platform_falls_back_portable():
    assert methods.resolve("auto", "sum", "gpu") == methods.FALLBACK


def test_resolution_is_always_concrete_and_universally_valid():
    # the winner set must stay within {scan, scatter}: cumsum/mxsum are
    # sum-only and pallas needs the block-CSR layout
    for plat in ("cpu", "tpu", "gpu", "weird"):
        for red in ("sum", "min", "max"):
            m = methods.resolve("auto", red, plat)
            assert m in methods.CONCRETE
            assert m in ("scan", "scatter")


def test_axon_platform_takes_tpu_rows():
    # 'axon' is the tunneled-TPU plugin: it must resolve exactly like tpu
    for red in ("sum", "min", "max"):
        assert methods.resolve("auto", red, "axon") == methods.resolve(
            "auto", red, "tpu"
        )


def test_platform_env_override(monkeypatch):
    monkeypatch.setenv("LUX_METHOD_PLATFORM", "tpu")
    assert methods.resolve("auto") == "scan"
    monkeypatch.setenv("LUX_METHOD_PLATFORM", "cpu")
    assert methods.resolve("auto") == "scatter"


def test_default_platform_detects_cpu_harness(monkeypatch):
    monkeypatch.delenv("LUX_METHOD_PLATFORM", raising=False)
    # the test harness pins JAX_PLATFORMS=cpu (conftest)
    assert methods.default_platform() == "cpu"
    assert methods.resolve("auto") == "scatter"


def test_cli_default_is_auto():
    from lux_tpu.utils.config import parse_args

    cfg = parse_args([])
    assert cfg.method == "auto"


def test_auto_runs_and_matches_resolved_concrete():
    # engine-level: method="auto" must produce bitwise the same result as
    # passing the resolved concrete method explicitly
    from lux_tpu.models import pagerank as pr
    from lux_tpu.graph import generate

    g = generate.rmat(8, 4, seed=3)
    concrete = methods.resolve("auto", "sum")
    a = pr.pagerank(g, num_iters=4, method="auto")
    b = pr.pagerank(g, num_iters=4, method=concrete)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_winners_file_overlay(monkeypatch, tmp_path):
    """A measured-winners file (written by the TPU bench race) overrides
    the hard-coded table; malformed entries are ignored."""
    import json

    path = tmp_path / "winners.json"
    path.write_text(json.dumps({"tpu:sum": "scatter", "tpu:min": "pallas"}))
    monkeypatch.setenv("LUX_METHOD_WINNERS", str(path))
    monkeypatch.setattr(methods, "_overlay_raw_cache", None)
    monkeypatch.setattr(methods, "_file_winners_cache", None)
    monkeypatch.setattr(methods, "_tiles_cache", None)
    assert methods.resolve("auto", "sum", platform="tpu") == "scatter"
    # "pallas" is not a safe blanket default: entry dropped
    assert methods.resolve("auto", "min", platform="tpu") == "scan"
    # untouched rows still come from the static table
    assert methods.resolve("auto", "sum", platform="cpu") == "scatter"
    monkeypatch.setattr(methods, "_overlay_raw_cache", None)
    monkeypatch.setattr(methods, "_file_winners_cache", None)
    monkeypatch.setattr(methods, "_tiles_cache", None)


def test_winners_file_malformed_is_noop(monkeypatch, tmp_path):
    path = tmp_path / "bad.json"
    path.write_text("{not json")
    monkeypatch.setenv("LUX_METHOD_WINNERS", str(path))
    monkeypatch.setattr(methods, "_overlay_raw_cache", None)
    monkeypatch.setattr(methods, "_file_winners_cache", None)
    monkeypatch.setattr(methods, "_tiles_cache", None)
    assert methods.resolve("auto", "sum", platform="tpu") == "scan"
    monkeypatch.setattr(methods, "_overlay_raw_cache", None)
    monkeypatch.setattr(methods, "_file_winners_cache", None)
    monkeypatch.setattr(methods, "_tiles_cache", None)


def test_winners_file_non_dict_and_sum_only_guard(monkeypatch, tmp_path):
    import json

    # valid JSON but not a dict: ignored, never raises
    bad = tmp_path / "list.json"
    bad.write_text(json.dumps(["tpu:sum"]))
    monkeypatch.setenv("LUX_METHOD_WINNERS", str(bad))
    monkeypatch.setattr(methods, "_overlay_raw_cache", None)
    monkeypatch.setattr(methods, "_file_winners_cache", None)
    monkeypatch.setattr(methods, "_tiles_cache", None)
    assert methods.resolve("auto", "sum", platform="tpu") == "scan"
    # prefix-diff strategies cannot become blanket defaults for ANY row
    # (the bucketed ring/edge2d layouts only run scan/scatter)
    mix = tmp_path / "mix.json"
    mix.write_text(json.dumps({"tpu:sum": "mxsum", "tpu:max": "scatter"}))
    monkeypatch.setenv("LUX_METHOD_WINNERS", str(mix))
    monkeypatch.setattr(methods, "_overlay_raw_cache", None)
    monkeypatch.setattr(methods, "_file_winners_cache", None)
    monkeypatch.setattr(methods, "_tiles_cache", None)
    assert methods.resolve("auto", "sum", platform="tpu") == "scan"
    assert methods.resolve("auto", "max", platform="tpu") == "scatter"
    monkeypatch.setattr(methods, "_overlay_raw_cache", None)
    monkeypatch.setattr(methods, "_file_winners_cache", None)
    monkeypatch.setattr(methods, "_tiles_cache", None)


def test_pallas_tiles_overlay(tmp_path, monkeypatch):
    """The sweep-recorded tile winner flows into build_blockcsr defaults;
    malformed/misaligned entries are ignored; explicit args always win."""
    import json

    import lux_tpu.engine.methods as methods
    from lux_tpu.graph import generate
    from lux_tpu.ops import pallas_spmv as ps

    g = generate.rmat(8, 4, seed=90)
    f = tmp_path / "w.json"
    f.write_text(json.dumps(
        {"tpu:pallas_tiles": {"v_blk": 256, "t_chunk": 1024}}
    ))
    monkeypatch.setenv("LUX_METHOD_WINNERS", str(f))
    monkeypatch.setattr(methods, "_overlay_raw_cache", None)
    monkeypatch.setattr(methods, "_tiles_cache", None)
    assert methods.pallas_tiles() == (256, 1024)
    bc = ps.build_blockcsr(g)
    assert (bc.v_blk, bc.t_chunk) == (256, 1024)
    # explicit args override the overlay
    bc2 = ps.build_blockcsr(g, v_blk=128, t_chunk=128)
    assert (bc2.v_blk, bc2.t_chunk) == (128, 128)
    # misaligned v_blk (not a lane multiple) is ignored
    f.write_text(json.dumps({"tpu:pallas_tiles": {"v_blk": 100,
                                                  "t_chunk": 512}}))
    monkeypatch.setattr(methods, "_overlay_raw_cache", None)
    monkeypatch.setattr(methods, "_tiles_cache", None)
    assert methods.pallas_tiles() is None
    bc3 = ps.build_blockcsr(g)
    assert (bc3.v_blk, bc3.t_chunk) == (ps.V_BLK, ps.T_CHUNK)


def test_record_overlay_entry_survives_corrupt_file(monkeypatch, tmp_path):
    """The single overlay writer replaces a corrupt file instead of
    dropping an expensive chip measurement, honors LUX_METHOD_WINNERS,
    and round-trips through the readers."""
    import json

    f = tmp_path / "w.json"
    f.write_text("{ not json !!")
    monkeypatch.setenv("LUX_METHOD_WINNERS", str(f))
    methods.record_overlay_entry("tpu:sum", "scatter")
    methods.record_overlay_entry(
        "tpu:pallas_tiles", {"v_blk": 128, "t_chunk": 256}
    )
    saved = json.loads(f.read_text())
    assert saved == {"tpu:sum": "scatter",
                     "tpu:pallas_tiles": {"v_blk": 128, "t_chunk": 256}}
    monkeypatch.setattr(methods, "_overlay_raw_cache", None)
    monkeypatch.setattr(methods, "_file_winners_cache", None)
    monkeypatch.setattr(methods, "_tiles_cache", None)
    assert methods.resolve("auto", "sum", platform="tpu") == "scatter"
    assert methods.pallas_tiles() == (128, 256)


def test_record_overlay_entry_invalidates_caches(monkeypatch, tmp_path):
    """A process that records then reads must see its own write (ADVICE
    r4: the old writer left _overlay_raw_cache/_file_winners_cache/
    _tiles_cache stale) — no manual cache resets here on purpose."""
    f = tmp_path / "w.json"
    monkeypatch.setenv("LUX_METHOD_WINNERS", str(f))
    monkeypatch.setattr(methods, "_overlay_raw_cache", None)
    monkeypatch.setattr(methods, "_file_winners_cache", None)
    monkeypatch.setattr(methods, "_tiles_cache", None)
    # prime the caches with the (empty) pre-write state
    assert methods.resolve("auto", "sum", platform="tpu") == "scan"
    assert methods.pallas_tiles() is None
    methods.record_overlay_entry("tpu:sum", "scatter")
    assert methods.resolve("auto", "sum", platform="tpu") == "scatter"
    methods.record_overlay_entry(
        "tpu:pallas_tiles", {"v_blk": 256, "t_chunk": 512})
    assert methods.pallas_tiles() == (256, 512)


def test_record_overlay_merges_dict_entries(monkeypatch, tmp_path):
    """A recorded measurement must survive subsequent records — both of a
    DIFFERENT method's sub-row under the same key (the round-5 clobber:
    a later micro-race write dropped the banked mxsum/gather rows) and of
    a different key entirely (the race winner)."""
    import json

    path = tmp_path / "winners.json"
    monkeypatch.setenv("LUX_METHOD_WINNERS", str(path))
    monkeypatch.setattr(methods, "_overlay_raw_cache", None)
    monkeypatch.setattr(methods, "_file_winners_cache", None)
    monkeypatch.setattr(methods, "_tiles_cache", None)

    methods.record_overlay_entry("tpu:sum", "scatter")
    methods.record_overlay_entry(
        "tpu:micro_sum", {"scale": 17, "ms_per_rep": {"mxsum": 2.0}}
    )
    # a later record for a DIFFERENT method merges, never overwrites
    methods.record_overlay_entry(
        "tpu:micro_sum", {"ms_per_rep": {"route": 0.3}}
    )
    data = json.loads(path.read_text())
    assert data["tpu:micro_sum"]["ms_per_rep"] == {
        "mxsum": 2.0, "route": 0.3
    }
    assert data["tpu:micro_sum"]["scale"] == 17
    # the race winner recorded first survived the micro-row records
    assert data["tpu:sum"] == "scatter"
    assert methods.resolve("auto", "sum", platform="tpu") == "scatter"
    # scalar re-records still overwrite (a winner is a decision)
    methods.record_overlay_entry("tpu:sum", "scan")
    assert json.loads(path.read_text())["tpu:sum"] == "scan"
    monkeypatch.setattr(methods, "_overlay_raw_cache", None)
    monkeypatch.setattr(methods, "_file_winners_cache", None)
    monkeypatch.setattr(methods, "_tiles_cache", None)


def test_shipped_winners_overlay_has_no_quarantined_sum_row():
    """Regression for the VERDICT r5 contradiction: the repo's shipped
    overlay must never record scan — the documented tunnel-wedger,
    quarantined to last place in docs/PERF.md — as a measured tpu:sum
    winner, and the round-5 micro rows must stay banked."""
    import json
    import os

    repo_overlay = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".lux_winners.json",
    )
    data = json.loads(open(repo_overlay).read())
    assert data.get("tpu:sum") != "scan"
    micro = data.get("tpu:micro_sum", {}).get("ms_per_rep", {})
    assert "mxsum" in micro and "route" in micro and "gather" in micro
