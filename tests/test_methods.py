"""Platform-aware method resolution (engine.methods): defaults follow
measured winners (PERF.md), explicit choices pass through untouched."""
import numpy as np

from lux_tpu.engine import methods


def test_explicit_method_passes_through():
    assert methods.resolve("scatter", "sum", "tpu") == "scatter"
    assert methods.resolve("mxsum", "sum", "cpu") == "mxsum"
    assert methods.resolve("scan", "min", "cpu") == "scan"


def test_measured_winners():
    # CPU: scatter beats scan ~2x on the comp phase (BASELINE.md r2 table)
    assert methods.resolve("auto", "sum", "cpu") == "scatter"
    assert methods.resolve("auto", "min", "cpu") == "scatter"
    assert methods.resolve("auto", "max", "cpu") == "scatter"
    # TPU: XLA scatter serializes on-chip (PERF.md r2: 0.06 GTEPS)
    assert methods.resolve("auto", "sum", "tpu") == "scan"
    assert methods.resolve("auto", "min", "tpu") == "scan"
    assert methods.resolve("auto", "max", "tpu") == "scan"


def test_unknown_platform_falls_back_portable():
    assert methods.resolve("auto", "sum", "gpu") == methods.FALLBACK


def test_resolution_is_always_concrete_and_universally_valid():
    # the winner set must stay within {scan, scatter}: cumsum/mxsum are
    # sum-only and pallas needs the block-CSR layout
    for plat in ("cpu", "tpu", "gpu", "weird"):
        for red in ("sum", "min", "max"):
            m = methods.resolve("auto", red, plat)
            assert m in methods.CONCRETE
            assert m in ("scan", "scatter")


def test_axon_platform_takes_tpu_rows():
    # 'axon' is the tunneled-TPU plugin: it must resolve exactly like tpu
    for red in ("sum", "min", "max"):
        assert methods.resolve("auto", red, "axon") == methods.resolve(
            "auto", red, "tpu"
        )


def test_platform_env_override(monkeypatch):
    monkeypatch.setenv("LUX_METHOD_PLATFORM", "tpu")
    assert methods.resolve("auto") == "scan"
    monkeypatch.setenv("LUX_METHOD_PLATFORM", "cpu")
    assert methods.resolve("auto") == "scatter"


def test_default_platform_detects_cpu_harness(monkeypatch):
    monkeypatch.delenv("LUX_METHOD_PLATFORM", raising=False)
    # the test harness pins JAX_PLATFORMS=cpu (conftest)
    assert methods.default_platform() == "cpu"
    assert methods.resolve("auto") == "scatter"


def test_cli_default_is_auto():
    from lux_tpu.utils.config import parse_args

    cfg = parse_args([])
    assert cfg.method == "auto"


def test_auto_runs_and_matches_resolved_concrete():
    # engine-level: method="auto" must produce bitwise the same result as
    # passing the resolved concrete method explicitly
    from lux_tpu.models import pagerank as pr
    from lux_tpu.graph import generate

    g = generate.rmat(8, 4, seed=3)
    concrete = methods.resolve("auto", "sum")
    a = pr.pagerank(g, num_iters=4, method="auto")
    b = pr.pagerank(g, num_iters=4, method=concrete)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_winners_file_overlay(monkeypatch, tmp_path):
    """A measured-winners file (written by the TPU bench race) overrides
    the hard-coded table; malformed entries are ignored."""
    import json

    path = tmp_path / "winners.json"
    path.write_text(json.dumps({"tpu:sum": "scatter", "tpu:min": "pallas"}))
    monkeypatch.setenv("LUX_METHOD_WINNERS", str(path))
    monkeypatch.setattr(methods, "_file_winners_cache", None)
    assert methods.resolve("auto", "sum", platform="tpu") == "scatter"
    # "pallas" is not a safe blanket default: entry dropped
    assert methods.resolve("auto", "min", platform="tpu") == "scan"
    # untouched rows still come from the static table
    assert methods.resolve("auto", "sum", platform="cpu") == "scatter"
    monkeypatch.setattr(methods, "_file_winners_cache", None)


def test_winners_file_malformed_is_noop(monkeypatch, tmp_path):
    path = tmp_path / "bad.json"
    path.write_text("{not json")
    monkeypatch.setenv("LUX_METHOD_WINNERS", str(path))
    monkeypatch.setattr(methods, "_file_winners_cache", None)
    assert methods.resolve("auto", "sum", platform="tpu") == "scan"
    monkeypatch.setattr(methods, "_file_winners_cache", None)


def test_winners_file_non_dict_and_sum_only_guard(monkeypatch, tmp_path):
    import json

    # valid JSON but not a dict: ignored, never raises
    bad = tmp_path / "list.json"
    bad.write_text(json.dumps(["tpu:sum"]))
    monkeypatch.setenv("LUX_METHOD_WINNERS", str(bad))
    monkeypatch.setattr(methods, "_file_winners_cache", None)
    assert methods.resolve("auto", "sum", platform="tpu") == "scan"
    # prefix-diff strategies cannot become blanket defaults for ANY row
    # (the bucketed ring/edge2d layouts only run scan/scatter)
    mix = tmp_path / "mix.json"
    mix.write_text(json.dumps({"tpu:sum": "mxsum", "tpu:max": "scatter"}))
    monkeypatch.setenv("LUX_METHOD_WINNERS", str(mix))
    monkeypatch.setattr(methods, "_file_winners_cache", None)
    assert methods.resolve("auto", "sum", platform="tpu") == "scan"
    assert methods.resolve("auto", "max", platform="tpu") == "scatter"
    monkeypatch.setattr(methods, "_file_winners_cache", None)
