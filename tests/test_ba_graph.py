"""Barabási–Albert preferential attachment: a SECOND heavy-tail family
(independent of RMAT) at beyond-fixture scale — VERDICT r4 weak #5
asked for power-law structure above toy size exercising the adaptive
thresholds, in a zero-egress environment (so generated, not fetched)."""
import numpy as np
import pytest

from lux_tpu.graph import generate
from lux_tpu.graph.push_shards import build_push_shards
from lux_tpu.models import pagerank as pr
from lux_tpu.models import sssp as sssp_model


@pytest.fixture(scope="module")
def ba():
    # 32k vertices / ~256k edges: ~1000x the karate fixture
    return generate.barabasi_albert(1 << 15, 8, seed=3)


def test_ba_is_heavy_tailed(ba):
    """The generator must actually produce hubs: max in-degree orders of
    magnitude above the mean (early vertices accumulate degree ~sqrt)."""
    deg = np.bincount(ba.dst_of_edges(), minlength=ba.nv)
    assert deg.mean() < 8
    assert deg.max() > 50 * deg.mean(), (deg.max(), deg.mean())
    # every edge points new -> old (citation orientation)
    assert (ba.col_idx > ba.dst_of_edges()).all()


def test_ba_pagerank_vs_oracle(ba):
    got = pr.pagerank(ba, num_iters=5, num_parts=4)
    np.testing.assert_allclose(
        got, pr.pagerank_reference(ba, 5), rtol=3e-5, atol=1e-10)


def test_ba_sssp_adaptivity_and_oracle():
    """Direction-optimized SSSP from a hub on the UNDIRECTED BA graph
    (hub in-mass becomes out-edges, so the frontier genuinely explodes):
    correct vs BFS, most of the graph reached, AND at least one dense
    round actually triggered — the thresholds were tuned on RMAT; this
    pins them on the second heavy-tail family at 32k scale."""
    from lux_tpu.engine import push

    g = generate.barabasi_albert(1 << 15, 8, seed=3, directed=False)
    deg_out = np.bincount(g.col_idx, minlength=g.nv)
    start = int(np.argmax(deg_out))  # a real hub now has out-edges
    assert deg_out[start] > 50 * deg_out.mean()
    shards = build_push_shards(g, 4)
    prog = sssp_model.SSSPProgram(nv=shards.spec.nv, start=start)
    st, it, edges = push.run_push(prog, shards, 10000, method="scan")
    got = shards.scatter_to_global(np.asarray(st))[: g.nv]
    want = sssp_model.bfs_reference(g, start)
    assert (got == want).all()
    assert (want < g.nv).mean() > 0.95  # the component spans the graph
    # the hub flood must cross nv/16 -> at least one dense (all-edge)
    # round, so the exact counter exceeds one full edge sweep
    assert push.edges_total(edges) >= g.ne
    assert int(it) >= 2


@pytest.mark.slow
def test_ba_2_20_converter_lux_routed_pull_push(tmp_path):
    """Heavy-tail coverage at plan-padding scale (VERDICT r5 #7): a
    2^20-vertex Barabási–Albert graph through converter→`.lux`→ROUTED
    pull AND push, bitwise vs the direct engines.  This is the scale
    band where routed-plan padding and hub skew actually bite (the
    expand space is 2^23 — the 32k fixture above never leaves one lane
    row of the ff recursion), and the threaded plan build is what makes
    it affordable as a test at all."""
    import jax
    import jax.numpy as jnp

    from lux_tpu.engine import pull, push
    from lux_tpu.graph.format import read_lux, write_lux
    from lux_tpu.graph.push_shards import build_push_shards
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.models.components import MaxLabelProgram
    from lux_tpu.ops import expand as E

    g0 = generate.barabasi_albert(1 << 20, 4, seed=5)
    deg = np.bincount(g0.dst_of_edges(), minlength=g0.nv)
    assert deg.max() > 100 * deg.mean()  # hubs at scale, not fixture noise

    # converter layer: .lux round-trip must reproduce the graph exactly
    path = str(tmp_path / "ba20.lux")
    write_lux(path, g0)
    g = read_lux(path)
    assert (g.nv, g.ne) == (g0.nv, g0.ne)
    np.testing.assert_array_equal(np.asarray(g.row_ptr),
                                  np.asarray(g0.row_ptr))
    np.testing.assert_array_equal(np.asarray(g.col_idx),
                                  np.asarray(g0.col_idx))

    # routed pull (pagerank, 2 iters) bitwise vs direct at P=2 — the
    # per-part executor fan-out and the threaded colorer both engage
    shards = build_pull_shards(g, 2)
    route = E.plan_expand_shards(shards)
    prog = pr.PageRankProgram(nv=shards.spec.nv)
    dev = jax.tree.map(jnp.asarray, shards.arrays)
    s0 = pull.init_state(prog, dev)
    direct = pull.run_pull_fixed(prog, shards.spec, dev, s0, 2,
                                 method="scan")
    routed = pull.run_pull_fixed(prog, shards.spec, dev, s0, 2,
                                 method="scan", route=route)
    np.testing.assert_array_equal(np.asarray(direct), np.asarray(routed))
    del route, direct, routed, dev, s0, shards

    # routed push dense rounds (max-label CC starts all-active = dense)
    # bitwise + identical exact edge counters, bounded rounds
    pshards = build_push_shards(g, 2)
    proute = E.plan_expand_shards(pshards)
    cc = MaxLabelProgram()
    st, it, ed = push.run_push(cc, pshards, 3, method="scan")
    st2, it2, ed2 = push.run_push(cc, pshards, 3, method="scan",
                                  route=proute)
    np.testing.assert_array_equal(np.asarray(st), np.asarray(st2))
    assert int(it) == int(it2)
    assert push.edges_total(ed) == push.edges_total(ed2)
