"""Barabási–Albert preferential attachment: a SECOND heavy-tail family
(independent of RMAT) at beyond-fixture scale — VERDICT r4 weak #5
asked for power-law structure above toy size exercising the adaptive
thresholds, in a zero-egress environment (so generated, not fetched)."""
import numpy as np
import pytest

from lux_tpu.graph import generate
from lux_tpu.graph.push_shards import build_push_shards
from lux_tpu.models import pagerank as pr
from lux_tpu.models import sssp as sssp_model


@pytest.fixture(scope="module")
def ba():
    # 32k vertices / ~256k edges: ~1000x the karate fixture
    return generate.barabasi_albert(1 << 15, 8, seed=3)


def test_ba_is_heavy_tailed(ba):
    """The generator must actually produce hubs: max in-degree orders of
    magnitude above the mean (early vertices accumulate degree ~sqrt)."""
    deg = np.bincount(ba.dst_of_edges(), minlength=ba.nv)
    assert deg.mean() < 8
    assert deg.max() > 50 * deg.mean(), (deg.max(), deg.mean())
    # every edge points new -> old (citation orientation)
    assert (ba.col_idx > ba.dst_of_edges()).all()


def test_ba_pagerank_vs_oracle(ba):
    got = pr.pagerank(ba, num_iters=5, num_parts=4)
    np.testing.assert_allclose(
        got, pr.pagerank_reference(ba, 5), rtol=3e-5, atol=1e-10)


def test_ba_sssp_adaptivity_and_oracle():
    """Direction-optimized SSSP from a hub on the UNDIRECTED BA graph
    (hub in-mass becomes out-edges, so the frontier genuinely explodes):
    correct vs BFS, most of the graph reached, AND at least one dense
    round actually triggered — the thresholds were tuned on RMAT; this
    pins them on the second heavy-tail family at 32k scale."""
    from lux_tpu.engine import push

    g = generate.barabasi_albert(1 << 15, 8, seed=3, directed=False)
    deg_out = np.bincount(g.col_idx, minlength=g.nv)
    start = int(np.argmax(deg_out))  # a real hub now has out-edges
    assert deg_out[start] > 50 * deg_out.mean()
    shards = build_push_shards(g, 4)
    prog = sssp_model.SSSPProgram(nv=shards.spec.nv, start=start)
    st, it, edges = push.run_push(prog, shards, 10000, method="scan")
    got = shards.scatter_to_global(np.asarray(st))[: g.nv]
    want = sssp_model.bfs_reference(g, start)
    assert (got == want).all()
    assert (want < g.nv).mean() > 0.95  # the component spans the graph
    # the hub flood must cross nv/16 -> at least one dense (all-edge)
    # round, so the exact counter exceeds one full edge sweep
    assert push.edges_total(edges) >= g.ne
    assert int(it) >= 2
