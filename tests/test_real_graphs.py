"""Non-synthetic graphs end-to-end (VERDICT r3 #8).

The checked-in data/ fixtures are real public-domain graphs (Zachary's
karate club; Les Misérables coappearances — see data/README.md for
provenance and the no-egress note).  These tests drive the FULL
reference pipeline on them: text edge list -> tools/converter.py ->
`.lux` -> each app, validated against independent NetworkX oracles —
the role the reference's six README datasets play
(/root/reference/README.md:77-86), at fixture scale.
"""
import os

import numpy as np
import pytest

networkx = pytest.importorskip("networkx")

from lux_tpu.graph.format import read_lux
from tools import converter

DATA = os.path.join(os.path.dirname(__file__), "..", "data")


@pytest.fixture(scope="module")
def karate_lux(tmp_path_factory):
    out = str(tmp_path_factory.mktemp("real") / "karate.lux")
    assert converter.main([
        "-nv", "34", "-ne", "156",
        "-input", os.path.join(DATA, "karate.el"), "-output", out,
    ]) in (0, None)
    return out


@pytest.fixture(scope="module")
def lesmis_lux(tmp_path_factory):
    out = str(tmp_path_factory.mktemp("real") / "lesmis.lux")
    assert converter.main([
        "-nv", "77", "-ne", "508",
        "-input", os.path.join(DATA, "lesmis.el"), "-output", out,
        "-weighted",
    ]) in (0, None)
    return out


def _karate_nx():
    return networkx.karate_club_graph()


def test_karate_lux_roundtrip(karate_lux):
    g = read_lux(karate_lux)
    assert g.nv == 34 and g.ne == 156
    # in-degree == networkx degree (both directions were emitted)
    nxg = _karate_nx()
    indeg = np.diff(np.asarray(g.row_ptr))
    for v in range(34):
        assert indeg[v] == nxg.degree(v)


def test_karate_pagerank_vs_networkx(karate_lux):
    """The app math (pre-divided ranks, ALPHA=0.15 on the sum,
    pagerank_gpu.cu:97-100) is the standard damping-0.15 recurrence;
    rank*outdeg must match networkx.pagerank(alpha=0.15)."""
    from lux_tpu.models.pagerank import pagerank

    g = read_lux(karate_lux)
    stored = np.asarray(pagerank(g, num_iters=40), np.float64)
    outdeg = np.bincount(np.asarray(g.col_idx), minlength=g.nv)
    rank = stored * outdeg
    # weight=None: networkx's karate graph carries interaction-count edge
    # weights and pagerank would use them by default; the .el fixture (and
    # the reference's unweighted datasets) are topology-only
    want = networkx.pagerank(_karate_nx(), alpha=0.15, tol=1e-12, weight=None)
    np.testing.assert_allclose(
        rank, [want[v] for v in range(34)], rtol=1e-6
    )


def test_karate_components_single(karate_lux):
    """Karate club is connected: max-label propagation must converge to
    the single label 33 everywhere."""
    from lux_tpu.models.components import connected_components_push

    g = read_lux(karate_lux)
    labels = connected_components_push(g)
    assert (np.asarray(labels) == 33).all()


def test_karate_bfs_vs_networkx(karate_lux):
    """Unweighted SSSP (BFS labels, sssp_gpu.cu:122 parity) against
    networkx shortest_path_length from the club president (v33)."""
    from lux_tpu.models.sssp import sssp

    g = read_lux(karate_lux)
    dist = sssp(g, start=33)
    want = networkx.shortest_path_length(_karate_nx(), source=33)
    np.testing.assert_array_equal(
        np.asarray(dist), [want[v] for v in range(34)]
    )


def test_lesmis_weighted_sssp_vs_dijkstra(lesmis_lux):
    """TRUE weighted SSSP (the extension the reference paper promises
    but its code never shipped) against networkx Dijkstra on the real
    coappearance weights."""
    from lux_tpu.models.sssp import sssp

    g = read_lux(lesmis_lux)
    assert g.weights is not None and g.ne == 508
    dist = sssp(g, start=0, weighted=True)
    lm = networkx.les_miserables_graph()
    names = sorted(lm.nodes())
    src = names[0]
    want = networkx.single_source_dijkstra_path_length(lm, src)
    got = np.asarray(dist)
    for i, n in enumerate(names):
        assert got[i] == int(want[n]), (i, n)


def test_lesmis_delta_stepping(lesmis_lux):
    """Delta-stepping on the real coappearance weights: identical
    distances, strictly fewer traversed edges than chaotic relaxation
    (VERDICT r4 #4 done-criterion on a non-synthetic graph)."""
    from lux_tpu.engine import delta as delta_mod
    from lux_tpu.engine import push
    from lux_tpu.graph.push_shards import build_push_shards
    from lux_tpu.models.sssp import WeightedSSSPProgram

    g = read_lux(lesmis_lux)
    shards = build_push_shards(g, 2)
    prog = WeightedSSSPProgram(nv=shards.spec.nv, start=0)
    st_c, _, e_c = push.run_push(prog, shards)
    st_d, _, e_d = delta_mod.run_push_delta(prog, shards, delta=2)
    assert (np.asarray(st_c) == np.asarray(st_d)).all()
    assert push.edges_total(e_d) < push.edges_total(e_c)


def test_lesmis_cli_apps_with_check(lesmis_lux, karate_lux, capsys):
    """The four app CLIs on real files: -check passes where the
    reference ships a checker (sssp/components), and the weighted CF
    epoch runs on the real integer weights without diverging."""
    from lux_tpu.apps import colfilter as cf_app
    from lux_tpu.apps import components as cc_app
    from lux_tpu.apps import pagerank as pr_app
    from lux_tpu.apps import sssp as sssp_app

    assert sssp_app.main(["-file", karate_lux, "-start", "0", "-check"]) == 0
    assert "[PASS] sssp" in capsys.readouterr().out
    assert cc_app.main(["-file", karate_lux, "-check"]) == 0
    assert "[PASS] components" in capsys.readouterr().out
    assert pr_app.main(["-file", karate_lux, "-ni", "10"]) == 0
    assert "top-5" in capsys.readouterr().out
    assert sssp_app.main(
        ["-file", lesmis_lux, "--weighted", "-start", "0", "-check"]
    ) == 0
    assert "[PASS] sssp" in capsys.readouterr().out
    assert cf_app.main(["-file", lesmis_lux, "-ni", "3"]) == 0
    out = capsys.readouterr().out
    assert "RMSE" in out
