"""End-to-end CLI driver coverage: every app's main() on tiny graphs,
exercising the flag surface the reference exposes (pagerank.cc:121-148
parse_input_args parity) plus the exchange/dtype extensions."""
import numpy as np
import pytest

from lux_tpu.apps import colfilter as cf_app, components as cc_app, \
    pagerank as pr_app, sssp as sssp_app

SMALL = ["--rmat-scale", "8", "--rmat-ef", "6"]


def test_pagerank_cli_basic(capsys):
    assert pr_app.main(SMALL + ["-ni", "3"]) == 0
    out = capsys.readouterr().out
    assert "ELAPSED TIME" in out and "top-5" in out


def test_pagerank_cli_verbose_phases(capsys):
    assert pr_app.main(SMALL + ["-ni", "2", "-verbose"]) == 0
    out = capsys.readouterr().out
    assert "loadTime(" in out and "compTime(" in out and "updateTime(" in out


def _parse_top5(out):
    line = [ln for ln in out.splitlines() if ln.startswith("top-5")][0]
    pairs = line.split(": ", 1)[1].split(", ")
    return {p.split("=")[0]: float(p.split("=")[1]) for p in pairs}


def test_pagerank_cli_exchanges_agree(capsys):
    """All three exchange strategies compute the same ranks (within f32
    reduction-order noise — they sum edge contributions in different
    orders)."""
    tops = {}
    for exchange in ["allgather", "ring", "scatter"]:
        args = SMALL + ["-ni", "3", "-ng", "8", "--distributed",
                        "--exchange", exchange]
        assert pr_app.main(args) == 0
        tops[exchange] = _parse_top5(capsys.readouterr().out)
    ref = tops["allgather"]
    for exchange in ["ring", "scatter"]:
        common_vids = set(ref) & set(tops[exchange])
        assert len(common_vids) >= 4, (ref, tops[exchange])
        for vid in common_vids:
            np.testing.assert_allclose(
                tops[exchange][vid], ref[vid], rtol=1e-4, err_msg=exchange
            )


def test_pagerank_cli_ring_requires_distributed():
    with pytest.raises(SystemExit):
        pr_app.main(SMALL + ["--exchange", "ring"])


def test_pagerank_cli_bf16(capsys):
    assert pr_app.main(SMALL + ["-ni", "2", "--dtype", "bfloat16"]) == 0
    assert "top-5" in capsys.readouterr().out


def test_sssp_cli_check(capsys):
    assert sssp_app.main(SMALL + ["-start", "0", "-check"]) == 0
    assert "[PASS] sssp" in capsys.readouterr().out


def test_sssp_cli_weighted_check(capsys):
    assert sssp_app.main(SMALL + ["--weighted", "-check"]) == 0
    assert "[PASS] sssp" in capsys.readouterr().out


def test_sssp_cli_distributed_device_check(capsys):
    args = SMALL + ["-ng", "8", "--distributed", "-check"]
    assert sssp_app.main(args) == 0
    assert "[PASS] sssp" in capsys.readouterr().out


def test_components_cli_distributed_device_check(capsys):
    args = SMALL + ["-ng", "8", "--distributed", "-check"]
    assert cc_app.main(args) == 0
    assert "[PASS] components" in capsys.readouterr().out


def test_components_cli_verbose_phases(capsys):
    # phase-fenced stats are a single-device observability mode; the
    # distributed loop stays fused on device
    assert cc_app.main(SMALL + ["-verbose"]) == 0
    out = capsys.readouterr().out
    assert "loadTime(" in out and "compTime(" in out


def test_colfilter_cli_ring_bf16(capsys):
    args = SMALL + ["-ni", "2", "-ng", "8", "--distributed",
                    "--exchange", "ring", "--dtype", "bfloat16"]
    assert cf_app.main(args) == 0
    assert "training RMSE" in capsys.readouterr().out


def test_pagerank_cli_ckpt_resume(tmp_path, capsys):
    d = str(tmp_path / "ck")
    assert pr_app.main(SMALL + ["-ni", "4", "--ckpt-dir", d,
                                "--ckpt-every", "2"]) == 0
    out1 = capsys.readouterr().out
    line1 = [ln for ln in out1.splitlines() if ln.startswith("top-5")][0]
    # resume from iteration 2 and finish; final ranks must match
    assert pr_app.main(SMALL + ["-ni", "4", "--ckpt-dir", d]) == 0
    out2 = capsys.readouterr().out
    assert "resumed from" in out2
    line2 = [ln for ln in out2.splitlines() if ln.startswith("top-5")][0]
    assert np.array_equal(line1, line2)


def test_push_apps_flag_gating():
    """Push apps take --exchange {allgather,ring} (ring needs
    --distributed); scatter and --dtype are pull-only and rejected."""
    with pytest.raises(SystemExit):
        sssp_app.main(SMALL + ["--exchange", "scatter"])
    with pytest.raises(SystemExit):
        cc_app.main(SMALL + ["--dtype", "bfloat16"])
    with pytest.raises(SystemExit, match="requires --distributed"):
        sssp_app.main(SMALL + ["--exchange", "ring"])


def test_sssp_cli_ring_exchange(capsys):
    """Frontier app with ring-streamed dense rounds + on-device -check."""
    args = SMALL + ["-ng", "8", "--distributed", "--exchange", "ring",
                    "-check"]
    assert sssp_app.main(args) == 0
    assert "[PASS] sssp" in capsys.readouterr().out


def test_components_cli_ring_exchange(capsys):
    args = SMALL + ["-ng", "8", "--distributed", "--exchange", "ring",
                    "-check"]
    assert cc_app.main(args) == 0
    assert "[PASS] components" in capsys.readouterr().out


def test_colfilter_rejects_scatter_exchange_upfront():
    """CF reads destination state per edge — incompatible with the
    pre-combined reduce_scatter; rejected before the shard build."""
    with pytest.raises(SystemExit, match="sum-reducible"):
        cf_app.main(SMALL + ["-ng", "8", "--distributed",
                             "--exchange", "scatter"])


def test_pagerank_rejects_cumsum_with_ring():
    with pytest.raises(SystemExit, match="scan or scatter"):
        pr_app.main(SMALL + ["-ng", "8", "--distributed",
                             "--exchange", "ring", "--method", "cumsum"])


def test_pagerank_cli_distributed_ckpt_resume(tmp_path, capsys):
    """Distributed runs checkpoint in on-device chunks and resume."""
    d = str(tmp_path / "ckd")
    base = SMALL + ["-ng", "8", "--distributed", "-ni", "4",
                    "--ckpt-dir", d]
    assert pr_app.main(base + ["--ckpt-every", "2"]) == 0
    line1 = [ln for ln in capsys.readouterr().out.splitlines()
             if ln.startswith("top-5")][0]
    import os

    assert sorted(os.listdir(d)) == ["ckpt_2.npz", "ckpt_4.npz"]
    # wipe the final checkpoint; resume from iteration 2
    os.remove(os.path.join(d, "ckpt_4.npz"))
    assert pr_app.main(base) == 0
    out2 = capsys.readouterr().out
    assert "resumed from" in out2
    line2 = [ln for ln in out2.splitlines() if ln.startswith("top-5")][0]
    assert line1 == line2


def test_colfilter_cli_distributed_ckpt_resume(tmp_path, capsys):
    d = str(tmp_path / "cfck")
    base = SMALL + ["-ng", "8", "--distributed", "-ni", "4",
                    "--ckpt-dir", d]
    assert cf_app.main(base + ["--ckpt-every", "2"]) == 0
    out1 = capsys.readouterr().out
    rmse1 = [ln for ln in out1.splitlines() if "RMSE" in ln][0]
    import os

    os.remove(os.path.join(d, "ckpt_4.npz"))
    assert cf_app.main(base) == 0
    out2 = capsys.readouterr().out
    assert "resumed from" in out2
    rmse2 = [ln for ln in out2.splitlines() if "RMSE" in ln][0]
    assert rmse1 == rmse2


def test_push_apps_require_both_ckpt_flags(tmp_path):
    # frontier apps checkpoint in windows: --ckpt-dir alone is rejected
    # (tests/test_push_ckpt.py covers the working dir+every combination)
    with pytest.raises(SystemExit, match="BOTH"):
        sssp_app.main(SMALL + ["--ckpt-dir", str(tmp_path)])


def test_pagerank_cli_edge_shards(capsys):
    """2-D (parts x edge) mesh from the CLI: 4 parts x 2 edge-shards on
    the 8-device test mesh; ranks must match the 1-D distributed run."""
    args = SMALL + ["-ni", "3", "-ng", "4", "--distributed",
                    "--edge-shards", "2"]
    assert pr_app.main(args) == 0
    t2d = _parse_top5(capsys.readouterr().out)
    assert pr_app.main(SMALL + ["-ni", "3", "-ng", "8", "--distributed"]) == 0
    t1d = _parse_top5(capsys.readouterr().out)
    shared = set(t2d) & set(t1d)
    assert shared, (t2d, t1d)  # disjoint top-5s would make this vacuous
    for vid in shared:
        np.testing.assert_allclose(t2d[vid], t1d[vid], rtol=1e-4)


def test_edge_shards_flag_gating():
    with pytest.raises(SystemExit, match="requires --distributed"):
        pr_app.main(SMALL + ["--edge-shards", "2"])
    with pytest.raises(SystemExit, match="own exchange"):
        pr_app.main(SMALL + ["-ng", "4", "--distributed",
                             "--edge-shards", "2", "--exchange", "ring"])


def test_sssp_cli_distributed_verbose(capsys):
    """Distributed -verbose: the SAME 3-phase load/comp/update breakdown
    as single-device (the reference prints per-GPU
    loadTime/compTime/updateTime on multi-GPU runs, sssp_gpu.cu:513-518),
    and the result still validates (-check)."""
    args = SMALL + ["-ng", "8", "--distributed", "-verbose", "-check"]
    assert sssp_app.main(args) == 0
    out = capsys.readouterr().out
    assert "activeNodes(" in out and "[PASS] sssp" in out
    assert "loadTime(" in out and "compTime(" in out and "updateTime(" in out


def test_pagerank_cli_distributed_verbose(capsys):
    args = SMALL + ["-ni", "3", "-ng", "8", "--distributed", "-verbose"]
    assert pr_app.main(args) == 0
    out = capsys.readouterr().out
    assert out.count("activeNodes(") == 3 and "top-5" in out
    assert out.count("loadTime(") == 3 and out.count("updateTime(") == 3


def test_colfilter_cli_distributed_verbose(capsys):
    args = SMALL + ["-ni", "2", "-ng", "8", "--distributed", "-verbose"]
    assert cf_app.main(args) == 0
    out = capsys.readouterr().out
    assert out.count("activeNodes(") == 2 and "training RMSE" in out


def test_pagerank_cli_distributed_verbose_with_ckpt(tmp_path, capsys):
    """-verbose --distributed composes with --ckpt-every (on_iter hook)."""
    d = str(tmp_path / "vck")
    args = SMALL + ["-ni", "4", "-ng", "8", "--distributed", "-verbose",
                    "--ckpt-dir", d, "--ckpt-every", "2"]
    assert pr_app.main(args) == 0
    out = capsys.readouterr().out
    assert out.count("activeNodes(") == 4
    import os

    assert sorted(os.listdir(d)) == ["ckpt_2.npz", "ckpt_4.npz"]


def test_sssp_cli_repartition(capsys):
    """--repartition-every with a tight threshold: at least one recut
    actually fires end-to-end, and the result still validates (-check)."""
    # scale 10: the SMALL graph's BFS from 0 dies after one hop, leaving
    # no window for the policy to act on
    args = ["--rmat-scale", "10", "--rmat-ef", "8", "-ng", "4",
            "-start", "0", "-check", "--repartition-every", "2",
            "--repartition-threshold", "1.01"]
    assert sssp_app.main(args) == 0
    out = capsys.readouterr().out
    assert "[PASS]" in out
    n_line = [ln for ln in out.splitlines() if "repartition(s)" in ln][0]
    assert int(n_line.split()[0]) >= 1, out
    assert "iter " in out and "imbalance" in out


def test_cc_cli_repartition_distributed(capsys):
    args = SMALL + ["-ng", "8", "--distributed", "-check",
                    "--repartition-every", "2"]
    assert cc_app.main(args) == 0
    out = capsys.readouterr().out
    assert "repartition(s)" in out and "[PASS]" in out


def test_repartition_flag_rejections(capsys):
    with pytest.raises(SystemExit):
        sssp_app.main(SMALL + ["--repartition-every", "2", "-verbose"])
    with pytest.raises(SystemExit):
        sssp_app.main(SMALL + ["--repartition-every", "-3"])


def test_sssp_cli_repartition_ring(capsys):
    """Adaptive repartitioning composed with the ring dense exchange —
    the big-AND-skewed configuration."""
    args = ["--rmat-scale", "10", "--rmat-ef", "8", "-ng", "8",
            "--distributed", "--exchange", "ring", "-start", "0", "-check",
            "--repartition-every", "2", "--repartition-threshold", "1.01"]
    assert sssp_app.main(args) == 0
    out = capsys.readouterr().out
    assert "[PASS]" in out


def test_elastic_resume_across_part_counts(tmp_path, capsys):
    """Elastic restart: checkpoints are global-layout, so a run saved at
    -ng 2 single-device resumes at -ng 8 --distributed (different part
    count, padding, AND exchange) and matches the uninterrupted run."""
    d = str(tmp_path / "ck")
    assert pr_app.main(SMALL + ["-ni", "6"]) == 0
    ref = _parse_top5(capsys.readouterr().out)
    assert pr_app.main(SMALL + ["-ni", "4", "-ng", "2", "--ckpt-dir", d,
                                "--ckpt-every", "2"]) == 0
    capsys.readouterr()
    assert pr_app.main(SMALL + ["-ni", "6", "-ng", "8", "--distributed",
                                "--exchange", "ring", "--ckpt-dir", d]) == 0
    out = capsys.readouterr().out
    assert "resumed from" in out and "iteration 4" in out
    got = _parse_top5(out)
    shared = set(ref) & set(got)
    assert len(shared) >= 4, (ref, got)
    for vid in shared:
        np.testing.assert_allclose(got[vid], ref[vid], rtol=1e-4)


def test_elastic_resume_rejects_wrong_app(tmp_path, capsys):
    d = str(tmp_path / "ck")
    assert pr_app.main(SMALL + ["-ni", "2", "--ckpt-dir", d,
                                "--ckpt-every", "2"]) == 0
    capsys.readouterr()
    with pytest.raises(SystemExit):
        cf_app.main(SMALL + ["-ni", "4", "--ckpt-dir", d])


def test_cli_file_loading_end_to_end(tmp_path, capsys):
    """-file: the reference's primary input path (-file graph.lux) driven
    end-to-end — write a .lux, run sssp -check and distributed pagerank
    from it, and confirm results match the in-memory graph."""
    from lux_tpu.graph import generate
    from lux_tpu.graph.format import write_lux
    from lux_tpu.models import sssp as sssp_model

    g = generate.rmat(8, 6, seed=11)
    path = str(tmp_path / "g.lux")
    write_lux(path, g)

    from conftest import hub_vertex

    start = hub_vertex(g)
    assert sssp_app.main(["-file", path, "-start", str(start),
                          "-check"]) == 0
    out = capsys.readouterr().out
    assert "[PASS]" in out
    want = sssp_model.bfs_reference(g, start)
    reached = [ln for ln in out.splitlines() if ln.startswith("reached")][0]
    assert reached.startswith(f"reached {int((want < g.nv).sum())}/")

    assert pr_app.main(["-file", path, "-ni", "3", "-ng", "4",
                        "--distributed"]) == 0
    assert "top-5" in capsys.readouterr().out


def test_cli_file_errors(tmp_path):
    with pytest.raises(SystemExit, match="cannot read"):
        sssp_app.main(["-file", str(tmp_path / "missing.lux")])
    # an unweighted file refuses apps that need ratings/weights
    from lux_tpu.graph import generate
    from lux_tpu.graph.format import write_lux

    path = str(tmp_path / "unweighted.lux")
    write_lux(path, generate.rmat(7, 4, seed=2))
    with pytest.raises(SystemExit, match="no edge weights"):
        cf_app.main(["-file", path, "-ni", "2"])


def test_pagerank_cli_check_extension(capsys):
    """-check on pagerank: the fixed-point residual validator (extension
    — the reference ships no pull-app check task) passes on a healthy
    run, and the unit validator rejects a corrupted state."""
    import numpy as np

    from lux_tpu.graph import generate
    from lux_tpu.models.pagerank import check_ranks, pagerank

    assert pr_app.main(SMALL + ["-ni", "12", "-check"]) == 0
    out = capsys.readouterr().out
    assert "[PASS] pagerank" in out
    g = generate.rmat(9, 4, seed=11)
    good = np.asarray(pagerank(g, num_iters=15))
    assert check_ranks(g, good) == 0
    bad = good.copy()
    bad[::7] *= 3.0  # a broken engine's ranks violate the fixed point
    assert check_ranks(g, bad) > 0
    nan = good.copy()
    nan[3] = np.nan
    assert check_ranks(g, nan) > 0


def test_colfilter_cli_check_extension(capsys):
    """-check on colfilter: training-progress validator (extension)."""
    import numpy as np

    from lux_tpu.graph import generate
    from lux_tpu.models.colfilter import check_training

    assert cf_app.main(SMALL + ["-ni", "2", "-check"]) == 0
    out = capsys.readouterr().out
    assert "[PASS] colfilter" in out
    gw = generate.bipartite_ratings(60, 40, 300, seed=12)
    diverged = np.full((gw.nv, 20), 1e6, np.float32)
    assert check_training(gw, diverged) > 0


def test_pagerank_cli_profile_trace(tmp_path, capsys):
    """--profile-dir captures a jax.profiler trace around the run (the
    tracing aux subsystem, SURVEY.md §5 — Legion Prof's role)."""
    import os

    d = str(tmp_path / "trace")
    assert pr_app.main(SMALL + ["-ni", "2", "--profile-dir", d]) == 0
    assert "profiler trace written" in capsys.readouterr().out
    found = [os.path.join(r, f) for r, _, fs in os.walk(d) for f in fs]
    assert found, "no trace files written"


def test_sssp_cli_serve(capsys):
    """--serve: warm buckets, serve a burst through the scheduler, emit
    the JSON metrics line, and -check validates every answer."""
    import json

    args = SMALL + ["--serve", "--serve-queries", "5",
                    "--serve-buckets", "1,4", "-check"]
    assert sssp_app.main(args) == 0
    out = capsys.readouterr().out
    line = [ln for ln in out.splitlines() if ln.startswith('{"metric"')][0]
    stats = json.loads(line)
    assert stats["metric"] == "sssp_serve"
    assert stats["completed"] == 5 and stats["timeouts"] == 0
    assert set(stats["latency_ms"]) == {"p50", "p95", "p99"}
    assert stats["engine_cache"]["engines_warm"] == 2
    assert "[PASS] sssp serve check" in out


def test_pagerank_cli_serve(capsys):
    import json

    args = SMALL + ["-ni", "4", "--serve", "--serve-queries", "3",
                    "--serve-buckets", "4", "-check"]
    assert pr_app.main(args) == 0
    out = capsys.readouterr().out
    line = [ln for ln in out.splitlines() if ln.startswith('{"metric"')][0]
    stats = json.loads(line)
    assert stats["metric"] == "ppr_serve" and stats["completed"] == 3
    assert stats["batch_occupancy"] == 0.75  # 3 real queries padded to 4
    assert "[PASS] ppr serve check" in out


def test_serve_cli_rejects_bad_combinations():
    with pytest.raises(SystemExit, match="does not combine"):
        sssp_app.main(SMALL + ["--serve", "--distributed"])
    with pytest.raises(SystemExit, match="does not combine"):
        sssp_app.main(SMALL + ["--serve", "--weighted"])
    with pytest.raises(SystemExit, match="bad vertex list"):
        sssp_app.main(SMALL + ["--serve", "--serve-sources", "1,x"])
    with pytest.raises(SystemExit, match="must be in"):
        sssp_app.main(SMALL + ["--serve", "--serve-sources", "999999"])
    with pytest.raises(SystemExit, match="buckets must be"):
        sssp_app.main(SMALL + ["--serve", "--serve-buckets", "0,4"])


def test_serve_cli_explicit_sources(capsys):
    assert sssp_app.main(
        SMALL + ["--serve", "--serve-sources", "3,9", "--serve-buckets", "2"]
    ) == 0
    out = capsys.readouterr().out
    assert '"completed": 2' in out
