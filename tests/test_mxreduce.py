"""mxreduce: the MXU-resident segmented reduction fused into the
routed-pf hot loop (ISSUE 7).

Pins, all in interpret mode on CPU (correctness never waits on a chip
window):

1. the mx fusion grouping (ops/route.plan_mx_fusion_groups) bounds the
   final group's distinct-digit block and still covers every pass;
2. the MXREDUCE replay (ops/expand.plan_fused mx=True -> apply_fused ->
   ops/pallas_shuffle.mxreduce_pass_gather) matches the NumPy segment
   oracle BITWISE for every f32-exact case — min/max and integer sums
   across dtypes, and float sums whose terms are exactly representable
   small integers (any association is exact there) — and to the
   documented tolerance for general f32 / bf16-operand sums (the MXU
   contraction owns its deterministic association, like mxsum vs scan;
   bf16 state accumulates in f32 per the StaticMXGroup precision
   contract);
3. the contract holds across reduce ops, group-width censuses (narrow
   sub-lane segments, lane-wide segments, a hub), weighted plans, and
   forced mx tile/v_blk/suffix-block knobs;
4. the engine path (run_pull_fixed route=fused-mx, vmapped parts) agrees
   with the plain fused path and the direct gather;
5. the "fused-mx-<reduce>" plan-cache family round-trips, is guarded
   against foreign entries, and resolves mx=None via the banked
   ``tpu:reduce_mode`` winner;
6. roofline accounting: the mx kernel is charged 0.5 sweeps, the
   separate reduce sweep is gone, the fused-mx total drops below the
   fused-pf total, and LUX-J4/J5 audit the new form clean;
7. colfilter's error-dot MXU tile (models/colfilter.err_dot mode="mxu")
   equals the reference error-dot, through both the pull engine and the
   single-chip Pallas runner.
"""
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from lux_tpu.ops import expand as E
from lux_tpu.ops import pallas_shuffle as S
from lux_tpu.ops import route as R


def _dev(arrays):
    return tuple(jnp.asarray(a) for a in arrays)


def _make_csc(rng, m, nseg, ss, hub=False):
    """CSC-order (src_pos, dst_local) with a mixed width census: most
    segments small (sub-lane widths), optionally one hub destination
    (lane-wide class) — both group layouts of the template."""
    p = np.ones(nseg)
    if hub:
        p[0] = nseg  # ~half the edges land on dst 0
    p /= p.sum()
    dst = np.repeat(np.arange(nseg), rng.multinomial(m, p))
    src = rng.integers(0, ss, m)
    order = np.argsort(dst, kind="stable")
    return src[order].astype(np.int64), dst[order].astype(np.int64)


def _oracle(src_pos, dst_local, x, nseg, op, weights=None):
    vals = np.asarray(x, np.float64)[src_pos]
    if weights is not None:
        vals = vals * np.asarray(weights, np.float64)
    out = np.full(
        nseg,
        0.0 if op == "sum" else (np.inf if op == "min" else -np.inf))
    ufunc = {"sum": np.add, "min": np.minimum, "max": np.maximum}[op]
    ufunc.at(out, dst_local, vals)
    return out


def _apply(static, arrays, x, **kw):
    return np.asarray(E.apply_fused(jnp.asarray(x), static, _dev(arrays),
                                    interpret=True, **kw))


# ---------------------------------------------------------------------------
# mx fusion grouping + physical order
# ---------------------------------------------------------------------------


def test_mx_fusion_groups_bound_suffix_block():
    # dims (128, 128, 8): passes gather axes 0,1,2,1,0; suffix {1,0}
    # blocks 128*128 > 1024, so the suffix is the single final 0-pass
    gs, sfx = R.plan_mx_fusion_groups((128, 128, 8), 1 << 17, 3, 1024)
    assert gs[-1] == sfx and sum(gs) == 5
    blk = 1
    for a in set(R.benes_axes(3)[-sfx:]):
        blk *= (128, 128, 8)[a]
    assert blk <= 1024
    # a wide-open bound lets the whole tail fuse
    gs2, sfx2 = R.plan_mx_fusion_groups((128, 8), 1 << 17, 3, 1 << 20)
    assert sum(gs2) == 3 and sfx2 >= 1
    with pytest.raises(ValueError):
        R.plan_mx_fusion_groups((128, 8), mx_max_block=64)


def test_mx_fusion_groups_cover_every_pass():
    for dims in [(128,), (128, 8), (128, 128, 2), (128, 128, 128, 8)]:
        gs, sfx = R.plan_mx_fusion_groups(dims)
        assert sum(gs) == 2 * len(dims) - 1
        assert 1 <= sfx == gs[-1]


def test_mx_physical_order_is_permutation():
    for dims in [(128, 8), (128, 128, 8)]:
        n = int(np.prod(dims))
        gs, _ = R.plan_mx_fusion_groups(dims)
        sigma = S.mx_physical_order(n, dims, gs)
        assert sorted(sigma.tolist()) == list(range(n))


# ---------------------------------------------------------------------------
# the precision contract, across ops / widths / dtypes
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("op", ["sum", "min", "max"])
@pytest.mark.parametrize("hub", [False, True])
def test_mx_f32_exact_cases_bitwise(op, hub, rng):
    """f32-exact cases are BITWISE: min/max pick elements (no
    arithmetic), and integer-valued f32 sums are exact under ANY
    association — so mx must equal the plain fused path bit for bit."""
    m, nseg, ss = 900, 41, 600
    src_pos, dst_local = _make_csc(rng, m, nseg, ss, hub=hub)
    st, arr = E.plan_fused(src_pos, dst_local, m, ss, 64, op)
    stm, arrm = E.plan_fused(src_pos, dst_local, m, ss, 64, op, mx=True)
    assert stm.mx is not None and st.mx is None
    x = rng.integers(-1000, 1000, ss).astype(np.float32)
    ref = _apply(st, arr, x)
    got = _apply(stm, arrm, x)
    np.testing.assert_array_equal(ref[:nseg], got[:nseg])
    oracle = _oracle(src_pos, dst_local, x, nseg, op)
    np.testing.assert_array_equal(got[:nseg], oracle.astype(np.float32))


@pytest.mark.parametrize("op", ["sum", "min", "max"])
def test_mx_int32_bitwise(op, rng):
    m, nseg, ss = 700, 37, 500
    src_pos, dst_local = _make_csc(rng, m, nseg, ss)
    st, arr = E.plan_fused(src_pos, dst_local, m, ss, 64, op)
    stm, arrm = E.plan_fused(src_pos, dst_local, m, ss, 64, op, mx=True)
    x = rng.integers(-10_000, 10_000, ss).astype(np.int32)
    ref = _apply(st, arr, x)
    got = _apply(stm, arrm, x)
    # integer ops never touch the MXU: dtype-preserving, bitwise
    assert got.dtype == np.int32
    np.testing.assert_array_equal(ref, got)


def test_mx_general_f32_sum_tolerance(rng):
    """General f32 sums: the MXU contraction's own deterministic
    association, equal to the f64 oracle within documented f32
    tolerance, and run-to-run deterministic."""
    m, nseg, ss = 1100, 29, 700
    src_pos, dst_local = _make_csc(rng, m, nseg, ss, hub=True)
    stm, arrm = E.plan_fused(src_pos, dst_local, m, ss, 32, "sum", mx=True)
    x = rng.standard_normal(ss).astype(np.float32)
    got = _apply(stm, arrm, x)
    oracle = _oracle(src_pos, dst_local, x, nseg, "sum")
    np.testing.assert_allclose(got[:nseg], oracle, rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(got, _apply(stm, arrm, x))


def test_mx_bf16_operand_sum_tolerance(rng):
    """bf16 state: operands enter the contraction as bf16 (already the
    storage precision — no further quantization), accumulation is f32
    (StaticMXGroup contract), totals return f32.  Documented tolerance:
    bf16's ~8-bit mantissa on the inputs, NOT on the accumulator."""
    m, nseg, ss = 800, 31, 512
    src_pos, dst_local = _make_csc(rng, m, nseg, ss)
    stm, arrm = E.plan_fused(src_pos, dst_local, m, ss, 64, "sum", mx=True)
    x = rng.standard_normal(ss).astype(np.float32)
    xb = jnp.asarray(x).astype(jnp.bfloat16)
    got = np.asarray(E.apply_fused(xb, stm, _dev(arrm), interpret=True))
    assert got.dtype == np.float32  # float-sum totals are f32
    oracle = _oracle(src_pos, dst_local,
                     np.asarray(xb.astype(jnp.float32)), nseg, "sum")
    np.testing.assert_allclose(got[:nseg], oracle, rtol=2e-2, atol=2e-2)


@pytest.mark.parametrize("op", ["min", "max"])
def test_mx_bf16_minmax_bitwise(op, rng):
    """min/max never touch the MXU: bf16 in, bf16 out, bitwise equal to
    the plain fused path."""
    m, nseg, ss = 600, 23, 400
    src_pos, dst_local = _make_csc(rng, m, nseg, ss)
    st, arr = E.plan_fused(src_pos, dst_local, m, ss, 32, op)
    stm, arrm = E.plan_fused(src_pos, dst_local, m, ss, 32, op, mx=True)
    xb = jnp.asarray(rng.standard_normal(ss).astype(np.float32)).astype(
        jnp.bfloat16)
    ref = E.apply_fused(xb, st, _dev(arr), interpret=True)
    got = E.apply_fused(xb, stm, _dev(arrm), interpret=True)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(ref.astype(jnp.float32)),
        np.asarray(got.astype(jnp.float32)))


def test_mx_weighted_sum(rng):
    """Pre-routed f32 weights ride the mx kernel's tile (the plan's
    gweights array in the final physical layout) and feed edge_value
    exactly like the plain fused path."""
    m, nseg, ss = 750, 27, 480
    src_pos, dst_local = _make_csc(rng, m, nseg, ss)
    w = rng.random(m).astype(np.float32)
    stm, arrm = E.plan_fused(src_pos, dst_local, m, ss, 32, "sum",
                             weights=w, mx=True)
    assert stm.weighted
    x = rng.integers(1, 64, ss).astype(np.float32)
    wq = np.round(w * 8) / 8  # keep products exactly representable
    stq, arrq = E.plan_fused(src_pos, dst_local, m, ss, 32, "sum",
                             weights=wq, mx=True)
    got = _apply(stq, arrq, x, edge_value=lambda v, ww: v * ww)
    oracle = _oracle(src_pos, dst_local, x, nseg, "sum", weights=wq)
    np.testing.assert_allclose(got[:nseg], oracle, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize(
    "knobs", [{"LUX_MX_TILE_ROWS": "1", "LUX_MX_MAX_BLOCK": "128"},
              {"LUX_MX_TILE_ROWS": "16", "LUX_MX_MAX_BLOCK": "2048"},
              {"LUX_MX_VBLK": "8"},
              {"LUX_MX_VBLK": "248"},
              {"LUX_MX_MAX_BLOCK": "128"}]
)
def test_mx_knob_geometries_bitwise(knobs, monkeypatch, rng):
    """Every legal tile/v_blk/suffix-block geometry lands the identical
    f32-exact bits — the knobs shape the plan, never the math."""
    for k, v in knobs.items():
        monkeypatch.setenv(k, v)
    m, nseg, ss = 640, 19, 400
    src_pos, dst_local = _make_csc(rng, m, nseg, ss, hub=True)
    stm, arrm = E.plan_fused(src_pos, dst_local, m, ss, 32, "sum", mx=True)
    x = rng.integers(-500, 500, ss).astype(np.float32)
    got = _apply(stm, arrm, x)
    oracle = _oracle(src_pos, dst_local, x, nseg, "sum")
    np.testing.assert_array_equal(got[:nseg], oracle.astype(np.float32))


def test_mx_knob_validation():
    with pytest.raises(ValueError):
        S._mx_defaults(v_blk=100)  # not a multiple of 8
    with pytest.raises(ValueError):
        S._mx_defaults(tile_rows=3)  # not a power of two
    with pytest.raises(ValueError):
        S._mx_defaults(mx_max_block=4096, tile_rows=8)  # block > tile


def test_mx_rank_tiles_narrow_u8(rng):
    """The segment-boundary rank tile is u8 under the default
    LUX_ROUTE_IDX8 layout (the ISSUE's u8-narrowable requirement), with
    the v_blk sentinel marking every non-edge slot."""
    m, nseg, ss = 500, 17, 300
    src_pos, dst_local = _make_csc(rng, m, nseg, ss)
    stm, arrm = E.plan_fused(src_pos, dst_local, m, ss, 32, "sum", mx=True)
    _, _, _, _, _, _, _, mxa = E.split_fused_arrays(stm, arrm, stm.weighted)
    dst_rel = mxa[len(stm.mx.steps)]
    assert dst_rel.dtype == np.uint8
    assert dst_rel.max() == stm.mx.v_blk  # sentinel present (padding)
    assert (np.asarray(dst_rel) <= stm.mx.v_blk).all()
    tile_block, tile_first = mxa[-2], mxa[-1]
    assert tile_first[0] == 1 and tile_block.dtype == np.int32


def test_mx_split_arrays_round_trip(rng):
    m, nseg, ss = 400, 13, 256
    src_pos, dst_local = _make_csc(rng, m, nseg, ss)
    stm, arrm = E.plan_fused(src_pos, dst_local, m, ss, 16, "sum", mx=True)
    r1a, ffa, r2a, gmask, gweights, gslot, vra, mxa = E.split_fused_arrays(
        stm, arrm, stm.weighted)
    assert gmask is None and gweights is None
    assert gslot.shape == (len(src_pos),) and gslot.dtype == np.int32
    assert len(mxa) == len(stm.mx.steps) + 3
    total = (len(r1a) + len(ffa) + len(r2a) + len(mxa) + 1 + len(vra))
    assert total == len(arrm)
    with pytest.raises(TypeError):
        E.to_pf((stm, arrm))  # mx plans are already pass-fused


# ---------------------------------------------------------------------------
# engine + cache + resolution
# ---------------------------------------------------------------------------


def _engine_fixture(scale=8, parts=2):
    from lux_tpu.engine import pull
    from lux_tpu.graph import generate
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.models.pagerank import PageRankProgram

    g = generate.rmat(scale, 8, seed=7)
    shards = build_pull_shards(g, parts)
    prog = PageRankProgram(nv=shards.spec.nv)
    arrays = jax.tree.map(jnp.asarray, shards.arrays)
    s0 = pull.init_state(prog, arrays)
    return pull, shards, prog, arrays, s0


def test_engine_fused_mx_matches_fused_and_direct(monkeypatch):
    """The vmapped multi-part engine hot loop on an mx plan: numerically
    the plain fused path's (and the direct engine's) PageRank."""
    monkeypatch.setenv("LUX_ROUTE_INTERPRET", "1")
    pull, shards, prog, arrays, s0 = _engine_fixture()
    fz = E.plan_fused_shards(shards, "sum")
    fzmx = E.plan_fused_shards(shards, "sum", mx=True)
    assert fzmx[0].mx is not None
    a = pull.run_pull_fixed(prog, shards.spec, arrays, s0, 3,
                            method="scan", route=_dev_plan(fz))
    b = pull.run_pull_fixed(prog, shards.spec, arrays, s0, 3,
                            method="scan", route=_dev_plan(fzmx))
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=3e-6)
    d = pull.run_pull_fixed(prog, shards.spec, arrays, s0, 3,
                            method="scan")
    np.testing.assert_allclose(np.asarray(b), np.asarray(d), rtol=3e-6)


def _dev_plan(plan):
    return plan[0], jax.tree.map(jnp.asarray, plan[1])


def test_mx_cache_round_trip(tmp_path, rng):
    """fused-mx-<reduce> family: reload == fresh build, and the family
    guard rejects foreign (plain-pf) entries instead of replaying the
    wrong layout."""
    _, shards, _, _, _ = _engine_fixture(parts=1)
    cdir = str(tmp_path / "plans")
    st_c, arr_c = E.plan_fused_shards_cached(shards, "sum", cache_dir=cdir,
                                             mx=True)
    st_u, arr_u = E.plan_fused_shards(shards, "sum", mx=True)
    assert st_c == st_u
    for a, b in zip(arr_c, arr_u):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    st_r, arr_r = E.plan_fused_shards_cached(shards, "sum", cache_dir=cdir,
                                             mx=True)
    assert st_r == st_u
    for a, b in zip(arr_r, arr_u):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert E.has_cached_fused_plan(shards, "sum", cache_dir=cdir,
                                   mx=True) is not None
    # the pf family is a DIFFERENT tag: no cross-contamination
    assert E.has_cached_fused_plan(shards, "sum", cache_dir=cdir,
                                   pf=True) is None


def test_mx_resolution_follows_reduce_mode(monkeypatch):
    """mx=None follows the banked tpu:reduce_mode winner (the
    unattended-window contract); explicit False always wins."""
    from lux_tpu.engine import methods

    monkeypatch.setenv("LUX_REDUCE_MODE", "mxreduce")
    assert methods.reduce_mode() == "mxreduce"
    assert E.resolve_fused_mx(None) is True
    assert E.resolve_fused_mx(False) is False
    monkeypatch.setenv("LUX_REDUCE_MODE", "group")
    assert E.resolve_fused_mx(None) is False
    monkeypatch.setenv("LUX_REDUCE_MODE", "bogus")
    with pytest.raises(ValueError):
        methods.reduce_mode()


def test_route_mx_helper():
    from lux_tpu.apps import common

    assert common.route_mx("fused-mx") is True
    assert common.route_mx("fused-pf") is None
    assert common.route_mx("fused") is False
    assert common.route_base("fused-mx") == "fused"
    assert common.route_is_pf("fused-mx")


# ---------------------------------------------------------------------------
# accounting + audit
# ---------------------------------------------------------------------------


def test_mx_hbm_passes_drop_below_fused_pf():
    """The acceptance metric: the accounted sweeps of one fused-mx
    iteration drop below the fused-pf accounting for the SAME graph —
    the separate reduce sweep is gone and the final group is charged
    half a sweep."""
    from lux_tpu.utils import roofline

    _, shards, _, _, _ = _engine_fixture(parts=1)
    st_pf, _ = E.plan_fused_shards(shards, "sum", pf=True)
    st_mx, _ = E.plan_fused_shards(shards, "sum", mx=True)
    pf = roofline.routed_hbm_passes(st_pf)
    mx = roofline.routed_hbm_passes(st_mx)
    assert "mx" in mx and mx["reduce"] == 0.0
    assert mx["mx"] == pytest.approx(0.5 * st_mx.n2 / st_mx.n, abs=0.01)
    assert mx["total"] < pf["total"]


def test_mx_routed_plan_bytes_exact():
    """preflight.routed_plan_bytes models an mx plan's device residency
    EXACTLY (same `== sum(nbytes)` contract the plain families pin in
    test_expand): step tiles + rank tile replace the group mask, plus
    the per-tile routing words."""
    from lux_tpu.utils import preflight

    _, shards, _, _, _ = _engine_fixture(parts=1)
    for kw in ({"pf": True}, {"mx": True}):
        st, arr = E.plan_fused_shards(shards, "sum", **kw)
        assert preflight.routed_plan_bytes(st) == sum(
            np.asarray(a).nbytes for a in arr), kw


def test_mx_byte_model_below_fused_pf():
    from lux_tpu.utils import roofline

    _, shards, _, _, _ = _engine_fixture(parts=1)
    st_pf, _ = E.plan_fused_shards(shards, "sum", pf=True)
    st_mx, _ = E.plan_fused_shards(shards, "sum", mx=True)
    ne, nv = 2048, 256
    b_pf = roofline.routed_pull_iter_model(st_pf, ne, nv).bytes_moved
    b_mx = roofline.routed_pull_iter_model(st_mx, ne, nv).bytes_moved
    assert b_mx < b_pf


def test_mx_kernel_count_and_claim_agree(rng):
    """LUX-J501/J502 on the mx replay: the traced pallas_call count
    equals the static derivation (prefix groups + ONE mx kernel), and
    the 0.5-sweep claim un-scales back to that same count."""
    from lux_tpu.analysis.ir import hbm

    m, nseg, ss = 500, 17, 300
    src_pos, dst_local = _make_csc(rng, m, nseg, ss)
    stm, arrm = E.plan_fused(src_pos, dst_local, m, ss, 32, "sum", mx=True)
    ra = _dev(arrm)
    x = jnp.asarray(rng.random(ss).astype(np.float32))

    def replay(xx, arrs):
        return E.apply_fused(xx, stm, arrs, interpret=True)

    traced = jax.jit(replay).trace(x, ra)
    assert hbm.check_hbm(traced, stm, "lux_tpu/ops/expand.py",
                         "fused-mx-test") == []


def test_mx_vmem_audit(rng):
    """LUX-J4: the mx group's one-hot/accumulator tiles join the
    residency ledger — clean under the real budget, a finding under an
    impossible one."""
    from lux_tpu.analysis.ir import vmem

    m, nseg, ss = 500, 17, 300
    src_pos, dst_local = _make_csc(rng, m, nseg, ss)
    stm, arrm = E.plan_fused(src_pos, dst_local, m, ss, 32, "sum", mx=True)
    assert vmem.check_vmem(stm, arrm, "p", "mx-test") == []
    findings = vmem.check_vmem(stm, arrm, "p", "mx-test", budget_bytes=1)
    assert any(f.code == "LUX-J401" and f.text.endswith(":mx")
               for f in findings)
    need = vmem.mx_residency_bytes(
        stm.mx, E.split_fused_arrays(stm, arrm, stm.weighted)[7],
        stm.weighted)
    assert need > 0


# ---------------------------------------------------------------------------
# colfilter error-dot MXU tile
# ---------------------------------------------------------------------------


def test_cf_err_dot_modes_agree(rng):
    from lux_tpu.models.colfilter import err_dot

    src = jnp.asarray(rng.standard_normal((64, 20)).astype(np.float32))
    dst = jnp.asarray(rng.standard_normal((64, 20)).astype(np.float32))
    a = np.asarray(err_dot(src, dst, "vpu"))
    b = np.asarray(err_dot(src, dst, "mxu"))
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
    # 3-D chunk shape (the Pallas runner's (C, T, K) tiles)
    s3 = src.reshape(4, 16, 20)
    np.testing.assert_allclose(
        np.asarray(err_dot(s3, dst.reshape(4, 16, 20), "mxu")),
        b.reshape(4, 16), rtol=1e-5, atol=1e-6)
    with pytest.raises(ValueError):
        err_dot(src, dst, "tpu")


def test_cf_mxu_tile_matches_reference():
    """The acceptance pin: colfilter with the MXU error-dot tile ==
    the NumPy reference recurrence, through the pull engine AND the
    single-chip Pallas runner."""
    from lux_tpu.graph import generate
    from lux_tpu.models import colfilter as cf

    g = generate.rmat(8, 8, seed=3, weighted=True)
    ref = cf.colfilter_reference(g, 3)
    v = cf.colfilter(g, 3, err_dot="mxu")
    np.testing.assert_allclose(v, ref, rtol=1e-4, atol=1e-6)
    p = cf.colfilter_pallas(g, 3, interpret=True, err_dot_mode="mxu")
    np.testing.assert_allclose(p, ref, rtol=1e-4, atol=1e-6)


def test_cf_err_dot_mode_resolution(monkeypatch):
    from lux_tpu.engine import methods
    from lux_tpu.models.colfilter import _resolve_err_dot

    monkeypatch.setenv("LUX_CF_ERR_DOT", "mxu")
    assert methods.cf_err_dot_mode() == "mxu"
    assert _resolve_err_dot(None) == "mxu"
    assert _resolve_err_dot("vpu") == "vpu"
    monkeypatch.setenv("LUX_CF_ERR_DOT", "bogus")
    with pytest.raises(ValueError):
        methods.cf_err_dot_mode()


def test_cf_program_default_unchanged():
    """The CFProgram default stays the shipped VPU form — existing
    callers are bitwise-unchanged until a measurement flips the mode."""
    from lux_tpu.models.colfilter import CFProgram

    assert CFProgram().err_dot == "vpu"
