"""luxpilot (ISSUE 16): the self-driving fleet.

Pins the acceptance surface: (a) AdmissionPolicy is JSON-round-trip
DATA — ordered first-match rules over SLO verdicts, unknown fields
refused — and the installed policy's mode actually gates ``_dispatch``
(shed rejects at admission, stale_degrade serves bounded reads with
the explicit stale tag); (b) ``rebalance_preview`` is a bitwise
dry-run: its movement report matches a real join/leave table diff
exactly; (c) the Autoscaler's hysteresis/cooldown/move-budget gates
fire deterministically under a fake clock, and scale actions emit
keyed ``pilot.scale`` incident spans; (d) the ELECTION DRILL — a
seeded FaultPlan kills the controller at a heartbeat sweep and a
STANDBY (not the harness) detects the silence, wins the
incarnation-fenced election, and promotes with zero acked-write loss,
one stitched incident trace, and split-brain refused in both
directions; (e) subscriptions push generation-tagged standing answers,
coalesce bursts, and survive the election via hub rebind; (f) the
full autonomous loop (``autopilot_soak``) holds under a fixed seed.
"""
import json
import os
import threading
import time

import numpy as np
import pytest

from lux_tpu import fault, obs
from lux_tpu.fault import drills
from lux_tpu.fault.chaos import autopilot_soak
from lux_tpu.graph import generate
from lux_tpu.graph.shards import build_pull_shards
from lux_tpu.models.sssp import bfs_reference
from lux_tpu.obs import dtrace
from lux_tpu.obs.dtrace import _hex_hash
from lux_tpu.obs.recorder import Recorder
from lux_tpu.obs.slo import worst_verdict
from lux_tpu.serve.autopilot import (
    MODES,
    AdmissionPolicy,
    Autoscaler,
    AutoscalerConfig,
    PolicyError,
    PolicyRule,
    Standby,
    StandbyGroup,
    SubscriptionClosed,
    default_fleet_policy,
)
from lux_tpu.serve.fleet.controller import (
    _POLICY_MODE_CODE,
    FleetController,
    FleetRejectedError,
    WorkerRefusedError,
)
from lux_tpu.serve.fleet.hashring import HashRing
from lux_tpu.serve.fleet.worker import ReplicaWorker
from lux_tpu.serve.live.controller import (
    LiveFleetController,
    promote_live_controller,
    start_live_fleet,
)
from tests.test_dtrace import prom_parse, read_events, spans_by_name


@pytest.fixture(autouse=True)
def _clean_state():
    yield
    dtrace.set_enabled(None)
    fault.uninstall()


@pytest.fixture(scope="module")
def small():
    g = generate.rmat(8, 6, seed=9)
    return g, build_pull_shards(g, 2)


@pytest.fixture()
def rec(tmp_path):
    r = Recorder(run_id="pilot", root=str(tmp_path), enabled=True)
    old = obs.install(r)
    yield r
    r.close()
    obs.install(old)


def _batches(g, n, rows=12, seed=1):
    rng = np.random.default_rng(seed)
    dele_pool = rng.permutation(g.ne)
    out, lo = [], 0
    for _ in range(n):
        ndel = rows // 2
        dele = dele_pool[lo:lo + ndel]
        lo += ndel
        src = np.concatenate([np.asarray(g.col_idx, np.int64)[dele],
                              rng.integers(0, g.nv, rows - ndel)])
        dst = np.concatenate([np.asarray(g.dst_of_edges(),
                                         np.int64)[dele],
                              rng.integers(0, g.nv, rows - ndel)])
        op = np.concatenate([np.zeros(ndel, np.int8),
                             np.ones(rows - ndel, np.int8)])
        out.append((src, dst, op))
    return out


# ----------------------------------------------------------------------
# admission policy as data
# ----------------------------------------------------------------------


def test_policy_rule_and_bounds_validation():
    with pytest.raises(PolicyError, match="unknown mode"):
        PolicyRule(mode="panic")
    with pytest.raises(PolicyError, match="unknown verdict"):
        PolicyRule(verdict="meltdown")
    with pytest.raises(PolicyError, match="default_mode"):
        AdmissionPolicy(default_mode="panic")
    with pytest.raises(PolicyError, match="max_shed_frac"):
        AdmissionPolicy(max_shed_frac=1.5)


def test_policy_json_round_trip_and_unknown_fields():
    pol = default_fleet_policy(max_shed_frac=0.25)
    back = AdmissionPolicy.from_json(pol.to_json())
    assert back.to_dict() == pol.to_dict()
    assert back.max_shed_frac == 0.25
    assert back.name == "default_fleet_policy"
    # unknown fields are refused at BOTH levels, like FaultPlan/SLOSpec
    with pytest.raises(PolicyError, match="unknown policy fields"):
        AdmissionPolicy.from_dict({"rules": [], "surprise": 1})
    with pytest.raises(PolicyError, match="unknown rule fields"):
        AdmissionPolicy.from_dict(
            {"rules": [{"slo": "*", "verdict": "warn", "mode": "queue",
                        "extra": True}]})
    with pytest.raises(PolicyError, match="bad policy JSON"):
        AdmissionPolicy.from_json("{nope")
    with pytest.raises(PolicyError, match="'rules'"):
        AdmissionPolicy.from_dict({"default_mode": "serve"})


def test_policy_decide_first_match_glob_and_default():
    pol = AdmissionPolicy([
        PolicyRule(slo="read_freshness", verdict="burning",
                   mode="stale_degrade", note="stale beats absent"),
        PolicyRule(slo="read_*", verdict="burning", mode="shed"),
        PolicyRule(slo="*", verdict="warn", mode="queue"),
    ])
    rows = [{"name": "read_latency", "verdict": "ok"},
            {"name": "read_freshness", "verdict": "ok"}]
    assert pol.decide(rows) == ("serve", "default")
    rows[0]["verdict"] = "warn"
    assert pol.decide(rows) == ("queue", "read_latency=warn")
    # order wins over row position: freshness burning beats the
    # earlier-listed latency row matching the broader glob rule
    rows[0]["verdict"] = "burning"
    rows[1]["verdict"] = "burning"
    mode, reason = pol.decide(rows)
    assert mode == "stale_degrade"
    assert reason.startswith("read_freshness=burning")
    assert "stale beats absent" in reason
    rows[1]["verdict"] = "ok"
    assert pol.decide(rows)[0] == "shed"


def test_default_fleet_policy_ladder_and_mode_code_pin():
    pol = default_fleet_policy()
    assert pol.decide([{"name": "read_availability",
                        "verdict": "burning"}])[0] == "shed"
    assert pol.decide([{"name": "read_freshness",
                        "verdict": "burning"}])[0] == "stale_degrade"
    assert pol.decide([{"name": "journal_lag",
                        "verdict": "warn"}])[0] == "queue"
    # the prom gauge coding must track MODES ordinally (dashboards
    # key on the numbers)
    assert _POLICY_MODE_CODE == {m: i for i, m in enumerate(MODES)}
    assert worst_verdict([]) == "no_data"


# ----------------------------------------------------------------------
# rebalance preview (satellite 1)
# ----------------------------------------------------------------------


def test_rebalance_preview_matches_actual_membership_change():
    keys = [f"sssp|g|q{i}" for i in range(256)]
    ring = HashRing()
    for w in ("w0", "w1", "w2"):
        ring.add(w)
    before = ring.table(keys)

    prev = ring.rebalance_preview(keys, add=["w3"])
    ring.add("w3")
    after = ring.table(keys)
    moved = [k for k in keys if before[k] != after[k]]
    assert prev["moved"] == len(moved)
    assert prev["moved_frac"] == pytest.approx(len(moved) / len(keys))
    # a join moves keys ONLY to the joiner, ~1/(R+1) of the space
    assert set(prev["gained"]) == {"w3"}
    assert prev["gained"]["w3"] == len(moved)
    assert sum(prev["lost"].values()) == len(moved)
    assert 0.05 < prev["moved_frac"] < 0.5

    prev2 = ring.rebalance_preview(keys, remove=["w1"])
    ring.remove("w1")
    after2 = ring.table(keys)
    moved2 = [k for k in keys if after[k] != after2[k]]
    assert prev2["moved"] == len(moved2)
    assert set(prev2["lost"]) == {"w1"}
    # the leaver yields exactly its share; nobody else's keys move
    assert prev2["lost"]["w1"] == len(
        [k for k in keys if after[k] == "w1"])


def test_rebalance_preview_validation_and_empty_ring():
    keys = ["a", "b", "c"]
    ring = HashRing()
    ring.add("w0")
    with pytest.raises(ValueError, match="already on the ring"):
        ring.rebalance_preview(keys, add=["w0"])
    with pytest.raises(ValueError, match="not on the ring"):
        ring.rebalance_preview(keys, remove=["ghost"])
    with pytest.raises(ValueError, match="both added and removed"):
        ring.rebalance_preview(keys, add=["w0x"], remove=["w0x"])
    # retiring the last worker routes everything to nowhere — the
    # preview reports total movement instead of crashing
    prev = ring.rebalance_preview(keys, remove=["w0"])
    assert prev["moved"] == 3 and prev["gained"] == {}
    assert prev["lost"] == {"w0": 3}


# ----------------------------------------------------------------------
# fleet timing knobs (satellite 6)
# ----------------------------------------------------------------------


def test_fleet_timing_env_knobs(monkeypatch):
    monkeypatch.setenv("LUX_FLEET_HEARTBEAT_S", "0.07")
    monkeypatch.setenv("LUX_FLEET_DEATH_S", "0.9")
    ctl = FleetController()
    try:
        assert ctl.hb_interval_s == pytest.approx(0.07)
        assert ctl.hb_timeout_s == pytest.approx(0.9)
    finally:
        ctl.close()
    # explicit ctor args beat the environment
    ctl = FleetController(hb_interval_s=0.5, hb_timeout_s=2.0)
    try:
        assert ctl.hb_interval_s == 0.5 and ctl.hb_timeout_s == 2.0
    finally:
        ctl.close()
    # garbage env fails loudly, NAMING the knob
    monkeypatch.setenv("LUX_FLEET_DEATH_S", "soon")
    with pytest.raises(ValueError, match="LUX_FLEET_DEATH_S"):
        FleetController()
    monkeypatch.setenv("LUX_FLEET_DEATH_S", "9999")
    with pytest.raises(ValueError, match="LUX_FLEET_DEATH_S"):
        FleetController()


def test_autoscaler_config_env_knobs(monkeypatch):
    monkeypatch.setenv("LUX_PILOT_UP_OCC", "0.8")
    monkeypatch.setenv("LUX_PILOT_COOLDOWN_S", "7")
    cfg = AutoscalerConfig()
    assert cfg.up_occupancy == pytest.approx(0.8)
    assert cfg.cooldown_s == pytest.approx(7.0)
    assert AutoscalerConfig(up_occupancy=0.9).up_occupancy == 0.9
    monkeypatch.setenv("LUX_PILOT_UP_OCC", "hot")
    with pytest.raises(ValueError, match="LUX_PILOT_UP_OCC"):
        AutoscalerConfig()
    monkeypatch.delenv("LUX_PILOT_UP_OCC")
    with pytest.raises(ValueError, match="min_workers"):
        AutoscalerConfig(min_workers=3, max_workers=1)
    with pytest.raises(ValueError, match="flap"):
        AutoscalerConfig(up_occupancy=0.3, down_occupancy=0.3)


# ----------------------------------------------------------------------
# autoscaler control loop (fakes + fake clock: fully deterministic)
# ----------------------------------------------------------------------


class _FakeWorker:
    def __init__(self, wid):
        self.worker_id = wid
        self.port = 0


class _FakeCtl:
    incarnation = "fake-inc"

    def __init__(self, occ=0.0, alive=1, moved_frac=0.1):
        self.occ = occ
        self.n_alive = alive
        self.moved_frac = moved_frac
        self.slo_rows = []
        self.added, self.removed = [], []
        self.counts = {}

    def workers(self):
        return {f"w{i}": {"alive": True, "saturated": False,
                          "last_hb": {"occupancy": self.occ}}
                for i in range(self.n_alive)}

    def slo_status(self):
        return list(self.slo_rows)

    def rebalance_preview(self, add=(), remove=(), app="sssp"):
        return {"total": 256, "moved": int(256 * self.moved_frac),
                "moved_frac": self.moved_frac, "gained": {},
                "lost": {}, "add": list(add), "remove": list(remove)}

    def add_worker(self, host, port, tc=None):
        self.added.append(port)
        self.n_alive += 1

    def remove_worker(self, wid, shutdown=True):
        self.removed.append(wid)
        self.n_alive -= 1

    def _pilot_count(self, key, n=1):
        self.counts[key] = self.counts.get(key, 0) + n


def _scaler(ctl, **cfg_kw):
    cfg_kw.setdefault("min_workers", 1)
    cfg_kw.setdefault("max_workers", 3)
    cfg_kw.setdefault("up_occupancy", 0.6)
    cfg_kw.setdefault("down_occupancy", 0.15)
    cfg_kw.setdefault("up_consecutive", 2)
    cfg_kw.setdefault("down_consecutive", 2)
    cfg_kw.setdefault("cooldown_s", 10.0)
    reaped = []
    spawned = []

    def spawn(i):
        w = _FakeWorker(f"s{i}")
        spawned.append(w)
        return w

    sc = Autoscaler(ctl, spawn, reap=reaped.append,
                    config=AutoscalerConfig(**cfg_kw))
    return sc, spawned, reaped


def test_autoscaler_hysteresis_cooldown_and_bounds():
    ctl = _FakeCtl(occ=0.9, alive=1)
    sc, spawned, _ = _scaler(ctl)
    # hot, but one tick is not a trend (up_consecutive=2)
    assert sc.tick(now=0.0) is None
    act = sc.tick(now=1.0)
    assert act["action"] == "scale_up" and act["worker"] == "s0"
    assert ctl.n_alive == 2 and ctl.counts["scale_up"] == 1
    # cooldown gates actions; a signal held hot THROUGH the window
    # keeps its streak, so the first post-cooldown tick may fire
    assert sc.tick(now=2.0) is None          # cooling (10s window)
    assert sc.tick(now=5.0) is None          # still cooling
    act2 = sc.tick(now=12.0)
    assert act2["action"] == "scale_up" and ctl.n_alive == 3
    # max_workers bound: hot forever, but the fleet stays at 3
    assert sc.tick(now=23.0) is None and sc.tick(now=24.0) is None
    assert ctl.n_alive == 3
    assert [a["seq"] for a in sc.actions()] == [1, 2]


def test_autoscaler_scale_down_lifo_and_floor():
    ctl = _FakeCtl(occ=0.9, alive=1)
    sc, spawned, reaped = _scaler(ctl, cooldown_s=0.0)
    sc.tick(now=0.0)
    sc.tick(now=1.0)   # spawn s0
    sc.tick(now=2.0)
    sc.tick(now=3.0)   # spawn s1
    assert ctl.n_alive == 3
    ctl.occ = 0.0      # now idle
    assert sc.tick(now=4.0) is None
    act = sc.tick(now=5.0)
    # LIFO: the NEWEST spawned worker retires first
    assert act["action"] == "scale_down" and act["worker"] == "s1"
    assert ctl.removed == ["s1"] and reaped == [spawned[1]]
    sc.tick(now=6.0)
    assert sc.tick(now=7.0)["worker"] == "s0"
    # floor: nothing spawned remains -> the operator's baseline
    # worker is never reaped, no matter how idle
    assert sc.tick(now=8.0) is None and sc.tick(now=9.0) is None
    assert ctl.n_alive == 1


def test_autoscaler_burning_verdict_and_knee_trigger():
    # occupancy calm, but a burning SLO verdict is hot on its own
    ctl = _FakeCtl(occ=0.1, alive=1)
    ctl.slo_rows = [{"name": "read_latency", "verdict": "burning"}]
    sc, _, _ = _scaler(ctl)
    sc.tick(now=0.0)
    assert sc.tick(now=1.0)["action"] == "scale_up"
    # knee-derived desired count: 130 qps / 50 qps-per-worker -> 3
    ctl2 = _FakeCtl(occ=0.1, alive=1)
    sc2, _, _ = _scaler(ctl2, cooldown_s=0.0)
    sc2.set_capacity(50.0)
    sc2.note_offered_qps(130.0)
    assert sc2.signals()["desired"] == 3
    sc2.tick(now=0.0)
    assert sc2.tick(now=1.0)["action"] == "scale_up"
    sc2.tick(now=2.0)
    assert sc2.tick(now=3.0)["action"] == "scale_up"
    assert sc2.tick(now=4.0) is None  # desired met at 3
    sc2.note_offered_qps(None)        # load note withdrawn: no signal
    assert sc2.signals()["desired"] is None


def test_autoscaler_move_budget_refuses_and_reaps():
    ctl = _FakeCtl(occ=0.9, alive=1, moved_frac=0.8)
    sc, spawned, reaped = _scaler(ctl, max_move_frac=0.5)
    sc.tick(now=0.0)
    # hot and ready — but the previewed rebalance would move 80% of
    # the keyspace: the action is refused and the orphan reaped
    assert sc.tick(now=1.0) is None
    assert ctl.added == [] and reaped == spawned
    assert sc.stats()["refused_moves"] == 1
    assert sc.stats()["actions"] == 0


def test_autoscaler_scale_span_is_keyed_incident(rec):
    dtrace.set_enabled(True)
    ctl = _FakeCtl(occ=0.9, alive=1)
    sc, _, _ = _scaler(ctl)
    sc.tick(now=0.0)
    sc.tick(now=1.0)
    by = spans_by_name(read_events(rec.run_dir()))
    (span,) = by["pilot.scale"]
    a = span["a"]
    assert a["trace"] == _hex_hash("lux:scale:fake-inc:1", 8)
    assert a["direction"] == "up" and a["worker"] == "s0"
    assert a["moved_frac"] == pytest.approx(0.1)
    assert a["verdict"] == "no_data" and a["seq"] == 1


# ----------------------------------------------------------------------
# policy gates dispatch on a live fleet
# ----------------------------------------------------------------------


def test_policy_modes_gate_live_dispatch(small, tmp_path, rec):
    g, _sh = small
    dtrace.set_enabled(True)
    fleet = start_live_fleet(1, g, parts=2, cap=1024,
                             standing=(("sssp", 0),),
                             hb_interval_s=0.1)
    ctl = fleet.controller
    try:
        gen = ctl.admit_writes(*_batches(g, 1)[0])["generation"]
        merged = ctl.journal.log.merged_graph()
        # serve (no policy): a normal query answers bitwise
        f = ctl.submit_retrying(0, deadline_s=60.0)
        assert np.array_equal(f.result(timeout=0),
                              bfs_reference(merged, 0))
        # shed: the installed policy rejects at admission
        ctl.set_policy(AdmissionPolicy(default_mode="shed"))
        assert ctl.policy_mode() == "shed"
        with pytest.raises(FleetRejectedError):
            ctl.submit(0)
        fams = prom_parse(ctl.prom_dump())
        assert float(fams["lux_pilot_policy_mode"]
                     ["samples"][0][2]) == 3
        assert float(fams["lux_fleet_shed_total"]
                     ["samples"][0][2]) == 1
        # stale_degrade: a bounded read ahead of every replica is
        # SERVED with the explicit stale tag instead of erroring
        ctl.set_policy(AdmissionPolicy(default_mode="stale_degrade"))
        f = ctl.submit(0, min_generation=gen + 50)
        assert np.array_equal(f.result(timeout=60.0),
                              bfs_reference(merged, 0))
        assert f.stale is True
        # queue mode admits normally when nothing is saturated
        ctl.set_policy(AdmissionPolicy(default_mode="queue"))
        f = ctl.submit(0)
        assert np.array_equal(f.result(timeout=60.0),
                              bfs_reference(merged, 0))
        # clearing the policy restores plain serving
        ctl.set_policy(None)
        assert ctl.policy_mode() == "serve"
        assert "lux_pilot_policy_mode" not in prom_parse(
            ctl.prom_dump())
        # each mode CHANGE emitted a pilot.policy.switch span on its
        # own keyed incident (serve->shed->stale_degrade->queue)
        by = spans_by_name(read_events(rec.run_dir()))
        switches = by["pilot.policy.switch"]
        assert [s["a"]["mode"] for s in switches] == [
            "shed", "stale_degrade", "queue"]
        assert switches[0]["a"]["prev"] == "serve"
        assert switches[0]["a"]["trace"] == _hex_hash(
            f"lux:policy:{ctl.incarnation}:1", 8)
        assert len({s["a"]["trace"] for s in switches}) == 3
    finally:
        fleet.close()


# ----------------------------------------------------------------------
# standing-query subscriptions
# ----------------------------------------------------------------------


def test_subscription_push_cursor_and_coalescing(small, tmp_path, rec):
    g, _sh = small
    dtrace.set_enabled(True)
    fleet = start_live_fleet(1, g, parts=2, cap=1024,
                             standing=(("sssp", 0),),
                             hb_interval_s=0.1)
    ctl = fleet.controller
    mirror = None
    try:
        sub = ctl.subscribe("sssp")
        b = _batches(g, 3, seed=5)
        ctl.admit_writes(*b[0])
        ctl.refresh_fleet()
        up = sub.get(timeout_s=30.0)
        assert up["app"] == "sssp" and up["generation"] >= 1
        assert sub.cursor == up["generation"]
        merged = ctl.journal.log.merged_graph()
        assert np.array_equal(up["state"], bfs_reference(merged, 0))
        # a burst coalesces: two commits, at most the LATEST answer
        # is delivered (the superseded one is counted, not replayed)
        ctl.admit_writes(*b[1])
        gen3 = ctl.admit_writes(*b[2])["generation"]
        ctl.refresh_fleet()
        while sub.get(timeout_s=30.0)["generation"] < gen3:
            pass
        assert sub.cursor == gen3
        merged = ctl.journal.log.merged_graph()
        fams = prom_parse(ctl.prom_dump())
        assert int(fams["lux_pilot_subscriptions"]
                   ["samples"][0][2]) == 1
        assert int(fams["lux_pilot_subscription_pushes_total"]
                   ["samples"][0][2]) >= 2
        assert int(fams["lux_pilot_subscription_lag"]
                   ["samples"][0][2]) == 0
        assert ctl._sub_hub.max_lag() == 0
        # pushes are traced
        by = spans_by_name(read_events(rec.run_dir()))
        pushes = [s for s in by.get("pilot.subscribe.push", ())
                  if "err" not in s["a"]]
        assert pushes and pushes[-1]["a"]["app"] == "sssp"
        # unsubscribe closes the stream
        ctl.unsubscribe(sub)
        with pytest.raises(SubscriptionClosed):
            sub.get(timeout_s=1.0)
        assert ctl._sub_hub.active() == 0
        # late registration is seeded from the CURRENT generation —
        # register once never means wait-for-the-next-write
        sub2 = ctl.subscribe("sssp")
        up2 = sub2.get(timeout_s=30.0)
        assert up2["generation"] == gen3
        assert np.array_equal(up2["state"], bfs_reference(merged, 0))
    finally:
        fleet.close()


# ----------------------------------------------------------------------
# the election drill (satellite 3)
# ----------------------------------------------------------------------


def test_standby_election_drill(small, tmp_path, rec):
    """Seeded controller-kill chaos plan; a STANDBY — not the test —
    detects the death, wins the fenced election, and promotes: zero
    acked-write loss, one stitched incident trace, split-brain refused
    in both directions."""
    g, _sh = small
    dtrace.set_enabled(True)
    root = str(tmp_path / "fleet")
    snap = os.path.join(root, "snap.lux")
    fleet = start_live_fleet(2, g, parts=2, cap=1024,
                             standing=(("sssp", 0),),
                             journal_root=root, snapshot_path=snap,
                             hb_interval_s=0.05)
    ctl = fleet.controller
    inc0 = ctl.incarnation
    standbys = []
    try:
        acked = {}
        for i, b in enumerate(_batches(g, 3)):
            acked[f"el-{i}"] = ctl.admit_writes(
                *b, write_id=f"el-{i}")["generation"]
        last = max(acked.values())
        # the drill: a SEEDED plan kills the controller at its 2nd
        # heartbeat sweep — the standbys must do all the noticing
        plan = drills.controller_kill_at_heartbeat(nth=2, seed=0)
        plan.bind("kill:controller", ctl.kill)
        group = StandbyGroup()

        def _promote(tc=None):
            endpoints = [("127.0.0.1", w.port)
                         for w in fleet.thread_workers]
            return promote_live_controller(
                g, os.path.join(root, "controller"), snap, endpoints,
                seed=1)

        standbys = [Standby(group, sid, ctl, _promote,
                            hb_interval_s=0.02, death_after_s=0.15,
                            seed=0).start()
                    for sid in (0, 1)]
        with fault.installed(plan):
            got = group.wait_promoted(timeout_s=60.0)
        assert plan.total_fired() == 1
        assert got is not None, "no standby promoted"
        ctl2, rep = got
        fleet.controller = ctl2  # close() tears the successor down
        for s in standbys:
            s.stop()
        # deterministic election: lowest standby id won, fenced on
        # the dead incarnation; the loser adopted
        assert group.claimed_by(inc0) == 0
        assert standbys[0].outcome == "won"
        assert standbys[1].outcome in ("adopted", "won")
        assert group.elections == 1
        assert ctl2.incarnation != inc0
        # a straggler declaring the SAME death later is fenced out
        assert group.claim(1, inc0) is False
        # zero acked-write loss across the unattended promotion
        assert sorted(rep["joined"]) == ["w0", "w1"]
        assert not rep["refused"] and not rep["failed"]
        assert ctl2.generation() >= last
        for wid, gen in acked.items():
            assert ctl2.journal.lookup_write(wid) == gen
        merged = ctl2.journal.log.merged_graph()
        f = ctl2.submit_retrying(0, deadline_s=60.0,
                                 min_generation=last)
        assert np.array_equal(f.result(timeout=0),
                              bfs_reference(merged, 0))
        assert "lux_pilot_elections_total 1" in ctl2.prom_dump()
        # ONE stitched trace: both detects + the winner's elect and
        # promote all mint the trace id from the election key
        tid = _hex_hash(f"lux:election:{inc0}", 8)
        by = spans_by_name(read_events(rec.run_dir()))
        detects = by["pilot.detect"]
        assert len(detects) == 2
        assert {s["a"]["standby"] for s in detects} == {0, 1}
        (elect,) = by["pilot.elect"]
        (promote,) = by["pilot.promote"]
        assert elect["a"]["winner"] == 0
        assert "err" not in promote["a"]
        assert promote["a"]["incarnation"] == ctl2.incarnation
        assert promote["a"]["joined"] == 2
        for s in detects + [elect, promote]:
            assert s["a"]["trace"] == tid
        # split-brain, direction 1: an impostor controller on a WIPED
        # journal is refused by workers holding acked history
        wiped = LiveFleetController(g, journal_dir=str(
            tmp_path / "wiped"))
        with pytest.raises(WorkerRefusedError,
                           match="behind my own journal"):
            wiped.add_worker("127.0.0.1", fleet.thread_workers[0].port)
        wiped.close()
        # split-brain, direction 2: the promoted LIVE controller
        # refuses a static-snapshot worker (no journal lineage)
        ws = ReplicaWorker(_sh, worker_id="ws", graph_id="live",
                           q_buckets=(1, 4)).start()
        try:
            with pytest.raises(WorkerRefusedError) as ei:
                ctl2.add_worker("127.0.0.1", ws.port)
            assert ei.value.kind == "static"
        finally:
            ws.kill()
    finally:
        for s in standbys:
            s.stop()
        fleet.close()


def test_election_fence_and_retry_after_failed_promotion():
    """Unit-level election properties: only the lowest live id may
    claim; a released claim lets the next standby retry; a failed
    promote releases the fence and the SAME standby retries."""
    group = StandbyGroup()
    group.register(2)
    group.register(5)
    assert group.claim(5, "inc-a") is False   # not the lowest
    assert group.claim(2, "inc-a") is True
    assert group.claim(2, "inc-a") is False   # fenced: already claimed
    group.release(2, "inc-a")
    group.deregister(2)
    assert group.claim(5, "inc-a") is True    # next-lowest retries

    class _DeadCtl:
        incarnation = "dead-1"
        hb_interval_s = 0.01
        hb_timeout_s = 0.05

        def ping(self):
            raise RuntimeError("gone")

    calls = []

    def flaky_promote(tc=None):
        calls.append(1)
        if len(calls) == 1:
            raise RuntimeError("promotion interrupted")
        return _FakeCtl(), {"joined": ["w0"]}

    g2 = StandbyGroup()
    sb = Standby(g2, 0, _DeadCtl(), flaky_promote, seed=3).start()
    got = g2.wait_promoted(timeout_s=30.0)
    sb.stop()
    assert got is not None and len(calls) == 2
    assert sb.outcome == "won"
    assert got[1] == {"joined": ["w0"]}


# ----------------------------------------------------------------------
# the full autonomous loop
# ----------------------------------------------------------------------


def test_autopilot_soak_fixed_seed(rec):
    """The acceptance soak: ramp -> previewed scale-up, kill ->
    standby election with the subscription surviving via rebind,
    overflow -> escalated compaction; zero acked loss and bitwise
    reads asserted inside the soak, incident spans asserted here."""
    dtrace.set_enabled(True)
    report = autopilot_soak(0, steps=3, scale=6, cap=32, rows=8)
    assert report["scale_ups"] >= 1
    assert report["elections"] == 1 and report["winner"] == 0
    assert report["compactions"] >= 1
    assert report["writes"] >= 4 and report["reads"] >= 3
    assert report["sub_delivered"], "subscription never delivered"
    by = spans_by_name(read_events(rec.run_dir()))
    # every autonomous action spanned on its keyed incident trace
    keys = report["incident_keys"]
    etid = _hex_hash(f"lux:{keys['election']}", 8)
    assert {s["a"]["trace"] for s in by["pilot.elect"]} == {etid}
    assert {s["a"]["trace"] for s in by["pilot.promote"]} == {etid}
    scale_tids = {s["a"]["trace"] for s in by["pilot.scale"]}
    assert scale_tids == {
        _hex_hash(f"lux:{k}", 8) for k in keys["scale"]}
    assert by.get("pilot.subscribe.push")


@pytest.mark.slow
def test_autopilot_soak_seed_sweep():
    for seed in range(10):
        report = autopilot_soak(seed, steps=3, scale=6, cap=32,
                                rows=8)
        assert report["elections"] == 1, seed
        assert report["scale_ups"] >= 1, seed
        assert report["compactions"] >= 1, seed


@pytest.mark.slow
def test_autoscale_bench_row():
    from lux_tpu.serve.fleet.bench import measure_autoscale
    out = measure_autoscale(scale=8, ef=4, start_qps=16.0,
                            max_levels=6, window_s=0.6)
    (row,) = out["rows"]
    assert row["metric"].startswith("sssp_autoscale_w1to")
    assert row["workers_after"] > row["workers_before"]
    assert len(row["scale_actions"]) >= 1
    assert row["shed_bounded"] is True
    assert row["shed_frac"] <= row["max_shed_frac"]
    assert row["knee_after_qps"] > 0
