"""Compact-gather layout (graph/shards.build_compact_mirror): the
unique-in-source mirror must reconstruct src_pos exactly, so every
engine path (pull fixed, push dense rounds, distributed, adaptive
recuts) is BITWISE identical to the direct layout — only the gather
traffic shape changes.  Reference parity: the per-GPU unique in-vertex
list + load_kernel FB staging (pagerank_gpu.cu:229-240, 34-47)."""
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lux_tpu.engine import pull
from lux_tpu.graph import generate
from lux_tpu.graph.shards import build_compact_mirror, build_pull_shards
from lux_tpu.graph.push_shards import build_push_shards
from lux_tpu.models import sssp as sssp_model
from lux_tpu.models.pagerank import PageRankProgram
from lux_tpu.parallel import mesh as mesh_lib


def _shards_pair(g, P, **kw):
    return (build_pull_shards(g, P, **kw),
            build_pull_shards(g, P, compact_gather=True, **kw))


@pytest.mark.parametrize("P", [1, 4])
def test_mirror_reconstructs_src_pos(P):
    g = generate.rmat(11, 8, seed=7)
    _, sh = _shards_pair(g, P)
    a = sh.arrays
    assert a.mirror_pos.shape[1] % 128 == 0
    for p in range(P):
        m = a.edge_mask[p]
        assert (a.mirror_pos[p][a.mirror_rel[p]][m] == a.src_pos[p][m]).all()
        u = np.unique(a.src_pos[p][m])
        # sorted unique prefix, padded with zeros
        assert (a.mirror_pos[p][: len(u)] == u).all()
        # the whole point: per-part unique in-sources < the gathered size
        assert len(u) < sh.spec.gathered_size


def test_pull_fixed_bitwise_equal():
    g = generate.rmat(11, 8, seed=8)
    for P in (1, 4):
        sh_a, sh_b = _shards_pair(g, P)
        prog = PageRankProgram(nv=g.nv)
        for method in ("scan", "scatter"):
            outs = []
            for sh in (sh_a, sh_b):
                arr = jax.tree.map(jnp.asarray, sh.arrays)
                s0 = pull.init_state(prog, arr)
                outs.append(np.asarray(pull.run_pull_fixed(
                    prog, sh.spec, sh.arrays, s0, 4, method=method)))
            assert (outs[0] == outs[1]).all(), (P, method)


def test_compact_composes_with_sort_segments():
    g = generate.rmat(11, 8, seed=9)
    sh_sorted = build_pull_shards(g, 3, sort_segments=True)
    sh_both = build_pull_shards(g, 3, sort_segments=True,
                                compact_gather=True)
    # the mirror remap is monotone, so the sorted relayout survives
    assert (sh_sorted.arrays.src_pos == sh_both.arrays.src_pos).all()
    prog = PageRankProgram(nv=g.nv)
    outs = []
    for sh in (sh_sorted, sh_both):
        arr = jax.tree.map(jnp.asarray, sh.arrays)
        s0 = pull.init_state(prog, arr)
        outs.append(np.asarray(pull.run_pull_fixed(
            prog, sh.spec, sh.arrays, s0, 4, method="scan")))
    assert (outs[0] == outs[1]).all()


def test_push_dense_rounds_bitwise_equal():
    """SSSP (direction-optimized; dense rounds carry the mirror) agrees
    bitwise with the direct layout and the BFS oracle."""
    g = generate.rmat(10, 8, seed=10)
    sh_a = build_push_shards(g, 3)
    sh_b = build_push_shards(g, 3, compact_gather=True)
    assert sh_b.pull.arrays.mirror_pos.shape[1] > 0
    d_a = sssp_model.sssp(sh_a, start=1)
    d_b = sssp_model.sssp(sh_b, start=1)
    assert (d_a == d_b).all()
    assert (d_b == sssp_model.bfs_reference(g, 1)).all()


def test_pull_dist_bitwise_equal():
    """Distributed pull (shard_map all_gather exchange) with the mirror
    equals the direct distributed run bitwise."""
    from lux_tpu.parallel import dist

    g = generate.rmat(11, 8, seed=11)
    P = 8
    msh = mesh_lib.make_mesh(P)
    prog = PageRankProgram(nv=g.nv)
    outs = []
    for compact in (False, True):
        sh = build_pull_shards(g, P, compact_gather=compact)
        arr = jax.tree.map(jnp.asarray, sh.arrays)
        s0 = pull.init_state(prog, arr)
        outs.append(np.asarray(dist.run_pull_fixed_dist(
            prog, sh.spec, sh.arrays, s0, 4, msh, method="scan")))
    assert (outs[0] == outs[1]).all()


def test_adaptive_recut_keeps_compact():
    """run_push_adaptive(compact_gather=True): recut rebuilds keep the
    mirror; ring exchange rejects it."""
    from lux_tpu.engine import repartition

    g = generate.rmat(10, 8, seed=12)
    prog = sssp_model.SSSPProgram(nv=g.nv, start=1)
    res = repartition.run_push_adaptive(
        prog, g, 2, chunk=2, threshold=1.0, compact_gather=True,
    )
    assert res.shards.pull.arrays.mirror_pos.shape[1] > 0
    base = sssp_model.sssp(g, start=1, num_parts=2)
    got = res.shards.pull.scatter_to_global(np.asarray(res.stacked))
    assert (got[: g.nv] == base).all()
    with pytest.raises(ValueError, match="compact_gather"):
        repartition.run_push_adaptive(
            prog, g, 2, chunk=2, mesh=None, compact_gather=True,
            exchange="ring",
        )


def test_cli_compact_gather():
    """--compact-gather on a pull app (end-to-end CLI) and the ring
    rejection."""
    from conftest import forced_cpu_env

    env = forced_cpu_env()
    r = subprocess.run(
        [sys.executable, "-m", "lux_tpu.apps.pagerank", "--rmat-scale", "9",
         "-ni", "5", "--compact-gather", "-check"],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "[PASS]" in r.stdout
    r2 = subprocess.run(
        [sys.executable, "-m", "lux_tpu.apps.pagerank", "--rmat-scale", "9",
         "-ng", "8", "--distributed", "--exchange", "ring",
         "--compact-gather"],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert r2.returncode != 0
    assert "--compact-gather" in r2.stderr
    # feat-sharded CF has its own layout: the flag must be rejected, not
    # silently dropped
    r3 = subprocess.run(
        [sys.executable, "-m", "lux_tpu.apps.colfilter", "--rmat-scale", "9",
         "-ng", "2", "--distributed", "--feat-shards", "2",
         "--compact-gather"],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert r3.returncode != 0
    assert "--compact-gather" in r3.stderr
    # push apps carry the mirror through their dense rounds: end-to-end
    # distributed frontier run, validated ON DEVICE (the --distributed
    # -check path runs validate.count_violations over the mesh)
    r4 = subprocess.run(
        [sys.executable, "-m", "lux_tpu.apps.components", "--rmat-scale",
         "9", "-ng", "8", "--distributed", "--compact-gather", "-check"],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert r4.returncode == 0, r4.stderr[-2000:]
    assert "[PASS]" in r4.stdout


def test_empty_part_mirror():
    """A part with zero edges gets a valid all-zeros mirror row (clip
    path) and the engine still runs."""
    # star graph: all edges into vertex 0 -> later parts can be edge-free
    edges = np.array([[i, 0] for i in range(1, 64)], np.int64)
    from lux_tpu.graph.csc import from_edge_list

    g = from_edge_list(edges[:, 0], edges[:, 1], nv=64)
    sh = build_pull_shards(g, 4, compact_gather=True)
    empty = [p for p in range(4) if not sh.arrays.edge_mask[p].any()]
    assert empty, "expected at least one edge-free part"
    prog = PageRankProgram(nv=g.nv)
    arr = jax.tree.map(jnp.asarray, sh.arrays)
    s0 = pull.init_state(prog, arr)
    out = pull.run_pull_fixed(prog, sh.spec, sh.arrays, s0, 3)
    assert np.isfinite(np.asarray(out)).all()


def test_loader_compact_matches_inmemory(tmp_path):
    """The streaming file loader's compact/sort relayouts must be
    byte-identical to the in-memory builder's, and a parts_subset load
    with the global width keeps full-load block shapes (the multi-host
    shape contract)."""
    from lux_tpu.graph import format as fmt
    from lux_tpu.graph import sharded_load

    g = generate.rmat(10, 8, seed=14)
    path = str(tmp_path / "g.lux")
    fmt.write_lux(path, g)
    P = 4
    mem = build_pull_shards(g, P, sort_segments=True, compact_gather=True)
    disk = sharded_load.load_pull_shards(
        path, P, sort_segments=True, compact_gather=True)
    for a, b in zip(mem.arrays, disk.arrays):
        assert (np.asarray(a) == np.asarray(b)).all()
    u_pad = sharded_load.compact_width_from_file(path, P)
    assert u_pad == mem.arrays.mirror_pos.shape[1]
    sub = sharded_load.load_pull_shards(
        path, P, parts_subset=[1, 2], compact_gather=True)
    assert sub.arrays.mirror_pos.shape[1] == u_pad
    assert (sub.arrays.mirror_rel ==
            build_pull_shards(g, P, compact_gather=True)
            .arrays.mirror_rel[1:3]).all()
    # an explicit too-small width is an error, not silent corruption
    assert u_pad > 128  # this scale needs more than one lane
    with pytest.raises(ValueError, match="u_pad"):
        sharded_load.load_pull_shards(
            path, P, compact_gather=True, compact_u_pad=128)


def test_build_compact_mirror_idempotent_width():
    """Re-attaching the mirror to already-compact arrays reproduces it
    (unique of src_pos is stable)."""
    g = generate.rmat(10, 6, seed=13)
    sh = build_pull_shards(g, 2, compact_gather=True)
    again = build_compact_mirror(sh.arrays)
    assert (again.mirror_pos == sh.arrays.mirror_pos).all()
    assert (again.mirror_rel == sh.arrays.mirror_rel).all()
