"""Skew stress: heavy-tail graphs must actually TRIGGER the adaptive
transitions (VERDICT r4 weak #7 — the thresholds mirror the reference's
constants, sssp/app.h:19 + sssp_gpu.cu:414, but were only ever
exercised on mild RMAT):

  * direction switch  (frontier > nv/16  -> dense/pull round)
  * queue overflow    (changed > f_cap   -> truncated queue, forced dense)
  * two-tier sparse   (totals <= e_sp_small -> small walk; else big)

The tracer drives the REAL compiled loop one iteration at a time and
classifies each upcoming round exactly like the engine's _push_prep
(same eager math), so the assertions pin engine behavior, not a
reimplementation.  Counters cross-checked on the carry itself
(dense_rounds / sp_work / exact edge total)."""
import numpy as np
import pytest

from lux_tpu.engine import push
from lux_tpu.graph.csc import from_edge_list
from lux_tpu.graph.push_shards import build_push_shards
from lux_tpu.models import sssp as sssp_model


def _trace_modes(prog, shards, max_iters=200, method="scan"):
    """Run step-wise; classify every executed round.  Returns
    (modes list, final carry)."""
    import jax
    import jax.numpy as jnp

    arrays = jax.tree.map(jnp.asarray, shards.arrays)
    parrays = jax.tree.map(jnp.asarray, shards.parrays)
    carry = push._init_carry(prog, shards.pspec, arrays)
    loop = push.compile_push_chunk(prog, shards.pspec, shards.spec, method)
    pspec = shards.pspec
    modes = []
    while int(carry.active) > 0 and int(carry.it) < max_iters:
        _, _, preps, use_dense = push._push_prep(
            pspec, shards.spec, parrays, carry
        )
        overflow = bool(np.any(np.asarray(carry.count) > pspec.f_cap))
        if bool(use_dense):
            modes.append("dense_overflow" if overflow else "dense")
        else:
            tot = int(np.asarray(preps[3]).max())
            small = pspec.e_sp_small
            modes.append("sparse_small" if small and tot <= small
                         else "sparse_big")
        carry = loop(arrays, parrays, carry, jnp.int32(int(carry.it) + 1))
    return modes, carry


def _star_chain_graph():
    """Chain -> hub (out-degree ~nv*0.78, the star) -> tail chain: early
    rounds are tiny sparse frontiers, the hub's relaxation floods BOTH
    parts' queues past f_cap (changed vertices land split across the
    edge-balanced cuts, so the hub degree must exceed 2*f_cap), the
    tail settles sparse again."""
    nv = 768
    edges = []
    for i in range(5):  # chain 0..5
        edges.append((i, i + 1))
    hub = 5
    targets = list(range(6, 606))  # 600 changed > 2*f_cap(=512)
    for t in targets:
        edges.append((hub, t))
    for j in range(3):  # a tail chain off one target
        edges.append((606 + j - 1 if j else 6, 606 + j))
    e = np.asarray(edges, np.int64)
    return from_edge_list(e[:, 0], e[:, 1], nv=nv), nv


def zipf_graph(nv=2048, s=1.5, hub_frac=10, seed=42):
    """Zipf(s) out-degrees with an explicit hub of degree nv/hub_frac."""
    rng = np.random.default_rng(seed)
    deg = np.minimum(rng.zipf(s, size=nv), nv // 4)
    deg[0] = nv // hub_frac  # the hub the VERDICT asks for
    src = np.repeat(np.arange(nv), deg)
    dst = rng.integers(0, nv, size=src.size)
    keep = src != dst
    return from_edge_list(src[keep], dst[keep], nv=nv)


def test_star_hub_overflow_then_dense():
    g, nv = _star_chain_graph()
    shards = build_push_shards(g, 2)
    assert 2 * shards.pspec.f_cap < 600  # the hub MUST overflow queues
    prog = sssp_model.SSSPProgram(nv=shards.spec.nv, start=0)
    modes, carry = _trace_modes(prog, shards)
    # early chain rounds: tiny sparse frontiers on the small tier
    assert modes[0] == "sparse_small"
    # the hub's 400 changed vertices overflow f_cap -> forced dense
    assert "dense_overflow" in modes
    # and the engine recovers to sparse afterwards (adaptivity is
    # bidirectional, sssp_gpu.cu:414)
    assert modes[-1].startswith("sparse")
    assert int(carry.dense_rounds) == modes.count("dense") + modes.count(
        "dense_overflow")
    dist = shards.scatter_to_global(np.asarray(carry.state))[: g.nv]
    assert (dist == sssp_model.bfs_reference(g, 0)).all()


def test_zipf_triggers_all_transitions():
    """A Zipf(1.5) heavy tail with an nv/10 hub drives every adaptive
    mode in ONE natural run (no synthetic caps): small sparse tail
    rounds, at least one big-tier or dense round, and a queue overflow
    from the hub's neighborhood."""
    g = zipf_graph()
    shards = build_push_shards(g, 4)
    prog = sssp_model.SSSPProgram(nv=shards.spec.nv, start=1)
    modes, carry = _trace_modes(prog, shards)
    seen = set(modes)
    assert "sparse_small" in seen, modes
    assert "dense" in seen, modes            # direction switch
    assert "dense_overflow" in seen, modes   # the hub floods f_cap
    # exact work accounting survives the skew: dense rounds walk every
    # edge, sparse rounds the frontier's out-edges
    total = push.edges_total(carry.edges)
    assert total >= int(carry.dense_rounds) * g.ne
    assert int(np.asarray(carry.sp_work).sum()) > 0  # sparse work logged
    dist = shards.scatter_to_global(np.asarray(carry.state))[: g.nv]
    assert (dist == sssp_model.bfs_reference(g, 1)).all()


@pytest.mark.parametrize("extra,want", [(0, "sparse_small"),
                                        (1, "sparse_big")])
def test_two_tier_boundary_exact(extra, want):
    """The tier decision pinned AT the boundary: a 2-vertex frontier
    (below the nv/16 direction switch) whose combined out-edges exactly
    fill e_sp_small takes the small walk; ONE edge more takes the big
    walk.  The round after (the 128-vertex flood) is a plain
    direction-switch dense round with no queue overflow — pinning that
    trigger in isolation too."""
    nv = 512
    edges = [(0, 1), (1, 2), (1, 3)]
    # frontier {2,3}: 64 + (64|65) out-edges == 128 (+extra)
    for t in range(4, 68):
        edges.append((2, t))
    for t in range(68, 132 + extra):
        edges.append((3, t))
    e = np.asarray(edges, np.int64)
    g = from_edge_list(e[:, 0], e[:, 1], nv=nv)
    shards = build_push_shards(g, 1, f_cap=2048, e_sp=2048)
    pspec = shards.pspec
    assert pspec.e_sp_small == 128
    prog = sssp_model.SSSPProgram(nv=shards.spec.nv, start=0)
    modes, carry = _trace_modes(prog, shards)
    # r0 {0}: small; r1 {1}: small; r2 {2,3}: 128(+extra) edges at the
    # boundary; r3: 128+ changed > nv/16 -> plain dense, under f_cap
    assert modes[0] == "sparse_small"
    assert modes[1] == "sparse_small"
    assert modes[2] == want, modes
    assert modes[3] == "dense", modes  # switch w/o overflow
    dist = shards.scatter_to_global(np.asarray(carry.state))[: g.nv]
    assert (dist == sssp_model.bfs_reference(g, 0)).all()
