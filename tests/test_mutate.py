"""Dynamic-graph mutation subsystem (lux_tpu.mutate, ISSUE 10).

The load-bearing claims, each pinned here:
  * delta-log then compact == building the merged graph from scratch,
    BITWISE (graph arrays and converged app results) — property test
    over random insert/delete batch sequences;
  * the overlay-aware hot loops are bitwise-equal to a cold rebuild
    per iteration for the exactly-associative (min/max int) reduces,
    and converge to the same exact f32 fixpoint for PageRank;
  * churn across delta occupancy levels causes ZERO retraces (the
    jit-cache probe twin of luxaudit's LUX-J1 unit);
  * overflow triggers compaction (never a reshape), the journal
    replays committed batches only (kill between append and marker
    loses exactly the uncommitted batch), and compaction invalidates
    ONLY the plan-cache buckets whose index arrays changed.
"""
import os
import tempfile

import numpy as np
import pytest

from lux_tpu.engine import pull, push
from lux_tpu.graph import generate
from lux_tpu.graph.csc import from_edge_list
from lux_tpu.graph.format import read_lux
from lux_tpu.graph.shards import build_pull_shards
from lux_tpu.models import components as comp
from lux_tpu.models.sssp import SSSPProgram, bfs_reference
from lux_tpu.mutate import (
    DeltaLog,
    MutableGraph,
    OP_DELETE,
    OP_INSERT,
)
from lux_tpu.mutate import refresh as refresh_mod
from lux_tpu.mutate.deltalog import DeltaOverflow


def _churn_batches(g, rng, n_batches, k, oracle):
    """Yield random mixed batches, mutating the python ``oracle`` edge
    list (delete-newest-match rule — the documented log semantic)."""
    for _ in range(n_batches):
        srcs, dsts, ops, ws = [], [], [], []
        for _ in range(k):
            if rng.random() < 0.45 and oracle:
                u, v, w = oracle[rng.integers(len(oracle))]
                for i in range(len(oracle) - 1, -1, -1):
                    if oracle[i][0] == u and oracle[i][1] == v:
                        del oracle[i]
                        break
                srcs.append(u)
                dsts.append(v)
                ops.append(OP_DELETE)
                ws.append(0)
            else:
                u = int(rng.integers(g.nv))
                v = int(rng.integers(g.nv))
                w = int(rng.integers(1, 9))
                oracle.append((u, v, w))
                srcs.append(u)
                dsts.append(v)
                ops.append(OP_INSERT)
                ws.append(w)
        yield srcs, dsts, ops, ws


@pytest.mark.parametrize("seed", [3, 11])
def test_property_compact_bitwise_vs_scratch(seed, tmp_path):
    """ANY random insert/delete batch sequence, applied via delta-log
    then compacted, equals building the merged graph from scratch —
    bitwise, including the .lux round trip."""
    g = generate.rmat(9, 8, seed=seed, weighted=True, max_weight=9)
    rng = np.random.default_rng(seed)
    oracle = list(zip(g.col_idx.tolist(), g.dst_of_edges().tolist(),
                      np.asarray(g.weights).tolist()))
    mg = MutableGraph(g, num_parts=3)
    for batch in _churn_batches(g, rng, 4, 50, oracle):
        mg.apply(*batch)
    snap = str(tmp_path / "merged.lux")
    mg.compact(path=snap)
    got = read_lux(snap)
    es = np.array([e[0] for e in oracle])
    ed = np.array([e[1] for e in oracle])
    ew = np.array([e[2] for e in oracle], np.int32)
    want = from_edge_list(es, ed, g.nv, weights=ew)
    assert np.array_equal(got.row_ptr, want.row_ptr)
    assert np.array_equal(got.col_idx, want.col_idx)
    assert np.array_equal(got.weights, want.weights)
    # the in-place compacted base IS the snapshot
    assert np.array_equal(mg.base.col_idx, want.col_idx)


@pytest.mark.parametrize("seed", [4, 9])
def test_property_refresh_converged_bitwise(seed):
    """Converged app results after churn+refresh equal a cold run on
    the merged graph: bitwise for the unique-int-fixpoint apps
    (SSSP/CC), and the exact f32 fixpoint for PageRank."""
    g = generate.rmat(9, 8, seed=seed)
    rng = np.random.default_rng(seed)
    mg = MutableGraph(g, num_parts=3)
    start = int(np.argmax(np.bincount(g.col_idx, minlength=g.nv)))
    prog = SSSPProgram(nv=g.nv, start=start)
    st, _, _ = push.run_push(prog, mg.push_shards)
    dist = mg.push_shards.scatter_to_global(np.asarray(st))
    labels = comp.connected_components_push(g, num_parts=3)
    pr, _ = refresh_mod.converge_pagerank(mg.pull_shards)

    oracle = list(zip(g.col_idx.tolist(), g.dst_of_edges().tolist(),
                      [0] * g.ne))
    for batch in _churn_batches(g, rng, 3, 40, oracle):
        # unweighted base: deletes of not-present pairs can happen when
        # the oracle drew an edge the log already tombstoned — skip
        # row-by-row like a driver would
        for u, v, o, w in zip(*batch):
            try:
                mg.apply([u], [v], [o], [w])
            except KeyError:
                pass
        dist, _ = refresh_mod.refresh_sssp(mg, dist, start)
        labels, _ = refresh_mod.refresh_components(mg, labels)
        pr, _ = refresh_mod.refresh_pagerank(mg, pr)
        merged = mg.log.merged_graph()
        assert np.array_equal(dist, bfs_reference(merged, start))
        assert np.array_equal(
            labels, comp.connected_components_push(merged, num_parts=3))
    # pagerank: exact fixpoint, bitwise-equal to a cold fixpoint on the
    # merged graph at matched cuts
    merged = mg.log.merged_graph()
    sh_cold = build_pull_shards(merged, 3,
                                cuts=np.asarray(mg.pull_shards.cuts))
    pr_cold, _ = refresh_mod.converge_pagerank(sh_cold)
    assert np.array_equal(np.asarray(pr), np.asarray(pr_cold))


def test_overlay_step_bitwise_minmax():
    """Per-ITERATION bitwise equality for the exactly-associative
    combiner: the overlay pull step (max-label CC) equals the step on
    cold-rebuilt merged shards, iteration by iteration."""
    g = generate.rmat(9, 8, seed=2)
    rng = np.random.default_rng(0)
    mg = MutableGraph(g, num_parts=2)
    dele = rng.choice(g.ne, 30, replace=False)
    mg.apply(g.col_idx[dele], g.dst_of_edges()[dele],
             np.full(30, OP_DELETE, np.int8))
    mg.apply(rng.integers(0, g.nv, 40), rng.integers(0, g.nv, 40),
             np.full(40, OP_INSERT, np.int8))
    prog = comp.MaxLabelProgram()
    sh = mg.pull_shards
    merged = mg.log.merged_graph()
    sh_m = build_pull_shards(merged, 2, cuts=np.asarray(sh.cuts))
    s0 = pull.init_state(prog, sh.arrays)
    s0_m = pull.init_state(prog, sh_m.arrays)
    ov = mg.pull_overlay()
    for n in (1, 2, 4):
        a = pull.run_pull_fixed(prog, sh.spec, sh.arrays, s0, n,
                                method="scan", overlay=ov)
        b = pull.run_pull_fixed(prog, sh_m.spec, sh_m.arrays, s0_m, n,
                                method="scan")
        assert np.array_equal(sh.scatter_to_global(np.asarray(a)),
                              sh_m.scatter_to_global(np.asarray(b))), n


def test_overlay_routed_pf_bitwise():
    """The overlay composes with a BASE-graph routed(-pf) expand plan
    bitwise (the routed gather is movement-only), and since luxmerge
    also RUNS on fused plans (group-space tombstones) instead of
    rejecting them."""
    from lux_tpu.ops import expand

    g = generate.rmat(9, 8, seed=13)
    rng = np.random.default_rng(2)
    mg = MutableGraph(g, num_parts=2)
    pr0, _ = refresh_mod.converge_pagerank(mg.pull_shards)
    mg.apply(rng.integers(0, g.nv, 30), rng.integers(0, g.nv, 30),
             np.full(30, OP_INSERT, np.int8))
    dele = rng.choice(g.ne, 20, replace=False)
    mg.apply(g.col_idx[dele], g.dst_of_edges()[dele],
             np.full(20, OP_DELETE, np.int8))
    plan = expand.plan_expand_shards(mg.pull_shards, pf=True)
    a, _ = refresh_mod.refresh_pagerank(mg, pr0)
    b, _ = refresh_mod.refresh_pagerank(mg, pr0, route=plan)
    assert np.array_equal(np.asarray(a), np.asarray(b))
    # fused sum: a different (group-layout) association, same contract
    # as the fused engines — it serves the refresh without raising and
    # lands on the same fixpoint to float tolerance
    fused = expand.plan_fused_shards(mg.pull_shards, reduce="sum")
    c, _ = refresh_mod.refresh_pagerank(mg, pr0, route=fused)
    np.testing.assert_allclose(np.asarray(c), np.asarray(a),
                               rtol=0, atol=1e-6)


def test_overlay_fused_families_bitwise():
    """overlay∘fused, overlay∘fused-pf and overlay∘fused-mx are BITWISE
    equal to overlay∘expand (and to the cold merged-graph step) for the
    exactly-associative max reduce — the luxmerge acceptance claim that
    live mutation runs on the fastest plan families undowngraded."""
    from lux_tpu.ops import expand

    g = generate.rmat(9, 8, seed=13)
    rng = np.random.default_rng(2)
    mg = MutableGraph(g, num_parts=2)
    dele = rng.choice(g.ne, 25, replace=False)
    mg.apply(g.col_idx[dele], g.dst_of_edges()[dele],
             np.full(25, OP_DELETE, np.int8))
    mg.apply(rng.integers(0, g.nv, 40), rng.integers(0, g.nv, 40),
             np.full(40, OP_INSERT, np.int8))
    prog = comp.MaxLabelProgram()
    sh = mg.pull_shards
    merged = mg.log.merged_graph()
    sh_m = build_pull_shards(merged, 2, cuts=np.asarray(sh.cuts))
    s0 = pull.init_state(prog, sh.arrays)
    s0_m = pull.init_state(prog, sh_m.arrays)
    ov = mg.pull_overlay()
    plan_exp = expand.plan_expand_shards(sh, pf=True)
    plan_f = expand.plan_fused_shards(sh, reduce="max")
    plans = (("fused", plan_f), ("fused-pf", expand.to_pf(plan_f)),
             ("fused-mx", expand.plan_fused_shards(sh, reduce="max",
                                                   mx=True)))
    for n in (1, 3):
        ref = pull.run_pull_fixed(prog, sh_m.spec, sh_m.arrays, s0_m, n,
                                  method="scan")
        a = pull.run_pull_fixed(prog, sh.spec, sh.arrays, s0, n,
                                method="scan", overlay=ov, route=plan_exp)
        for name, pl in plans:
            b = pull.run_pull_fixed(prog, sh.spec, sh.arrays, s0, n,
                                    method="scan", overlay=ov, route=pl)
            assert np.array_equal(np.asarray(a), np.asarray(b)), (name, n)
            assert np.array_equal(
                sh.scatter_to_global(np.asarray(b)),
                sh_m.scatter_to_global(np.asarray(ref))), (name, n)


def test_zero_retrace_across_occupancy():
    """Churn at empty/half/full delta occupancy re-enters ONE compiled
    program — the dynamic twin of luxaudit's LUX-J1 overlay unit."""
    g = generate.rmat(9, 8, seed=7)
    rng = np.random.default_rng(0)
    mg = MutableGraph(g, num_parts=2, cap=256)
    pr, _ = refresh_mod.converge_pagerank(mg.pull_shards)
    start = 1
    prog = SSSPProgram(nv=g.nv, start=start)
    st, _, _ = push.run_push(prog, mg.push_shards)
    dist = mg.push_shards.scatter_to_global(np.asarray(st))
    sizes = []
    for lvl in (4, 60, 180):
        mg.apply(rng.integers(0, g.nv, lvl),
                 rng.integers(0, g.nv, lvl),
                 np.full(lvl, OP_INSERT, np.int8))
        pr, _ = refresh_mod.refresh_pagerank(mg, pr)
        dist, _ = refresh_mod.refresh_sssp(mg, dist, start)
        sizes.append(pull._pull_until_jit._cache_size())
    assert sizes[0] == sizes[1] == sizes[2], sizes


def test_overflow_triggers_compaction():
    """A batch that would overflow any part's delta capacity compacts
    the standing log FIRST and then applies — the new batch stays in
    the log (warm refresh from a prior converged state remains sound),
    shapes never change.  A batch that alone exceeds the capacity
    raises instead of silently invalidating caller-held priors."""
    g = generate.rmat(9, 8, seed=5)
    rng = np.random.default_rng(1)
    mg = MutableGraph(g, num_parts=2, cap=128)
    _ = mg.pull_shards
    old_ne = g.ne
    st = mg.apply(rng.integers(0, g.nv, 100), np.full(100, 3),
                  np.full(100, OP_INSERT, np.int8))
    assert not st["compacted"]
    st = mg.apply(rng.integers(0, g.nv, 100), np.full(100, 3),
                  np.full(100, OP_INSERT, np.int8))
    assert st["compacted"] and mg.compactions == 1
    # the FIRST batch folded into the base; the second is still live
    assert mg.base.ne == old_ne + 100
    assert mg.log.stats()["inserts_live"] == 100
    # one batch alone past the capacity: a hard error, never a silent
    # fold (and never a reshape)
    with pytest.raises(DeltaOverflow, match="on its own"):
        mg.apply(rng.integers(0, g.nv, 200), np.full(200, 3),
                 np.full(200, OP_INSERT, np.int8))
    # the raw builder raises rather than reshaping
    mg2 = MutableGraph(g, num_parts=2, cap=128)
    log = DeltaLog(g)
    log.apply(rng.integers(0, g.nv, 200), np.full(200, 3),
              np.full(200, OP_INSERT, np.int8))
    from lux_tpu.mutate import build_pull_overlay

    with pytest.raises(DeltaOverflow):
        build_pull_overlay(mg2.pull_shards, log, cap=128)


def test_apply_batch_atomicity():
    """A batch with an invalid row leaves the in-memory state AND the
    journal exactly as before — never half a batch in either, and the
    journal stays replayable (a committed poisoned batch would make
    every reopen raise)."""
    g = generate.rmat(8, 4, seed=3)
    jd = tempfile.mkdtemp()
    log = DeltaLog(g, journal_dir=jd)
    log.apply([1], [2], [OP_INSERT], [5])
    before = log.stats()
    # row 2 is valid, row 3 deletes a non-existent edge
    with pytest.raises(KeyError):
        log.apply([3, 1], [4, 3], [OP_INSERT, OP_DELETE], [6, 0])
    assert log.stats() == before
    log.apply([7], [8], [OP_INSERT], [9])
    # reopen replays BOTH committed batches and nothing else
    log2 = DeltaLog(g, journal_dir=jd)
    assert log2.stats()["batches"] == 2
    assert np.array_equal(log2.live_inserts()[0], log.live_inserts()[0])


def test_journal_roundtrip_and_crash_replay():
    """Committed batches replay on reopen; a batch whose npz landed but
    whose fsync MARKER did not (kill in the append window) is ignored
    AND cleaned up — exactly one batch lost, never a torn state."""
    g = generate.rmat(8, 4, seed=3)
    jd = tempfile.mkdtemp()
    log = DeltaLog(g, journal_dir=jd)
    log.apply([1], [2], [OP_INSERT], [5])
    log.apply([2, 1], [3, 2], [OP_INSERT, OP_DELETE], [6, 0])
    # simulate the crash: append the npz, die before the marker
    seq = log._journal_write_batch(np.array([7]), np.array([8]),
                                   np.array([OP_INSERT], np.int8),
                                   np.array([9]))
    log2 = DeltaLog(g, journal_dir=jd)
    s = log2.stats()
    assert s == {"inserts_live": 1, "inserts_total": 2,
                 "deletes_base": 0, "batches": 2}
    assert not os.path.exists(log2._batch_path(seq))
    # the replayed log resolves identically to the in-memory one
    assert np.array_equal(log2.live_inserts()[0], log.live_inserts()[0])
    # base mismatch is refused loudly
    g2 = generate.rmat(8, 5, seed=3)
    with pytest.raises(ValueError, match="different base"):
        DeltaLog(g2, journal_dir=jd)
    # a SAME-nv/ne different-content base (edge-count-conserving churn
    # epoch) is caught by the content fingerprint, not just the sizes
    g3 = generate.rmat(8, 4, seed=99)
    assert (g3.nv, g3.ne) == (g.nv, g.ne)
    with pytest.raises(ValueError, match="different base"):
        DeltaLog(g3, journal_dir=jd)


def test_journal_rotates_on_compact(tmp_path):
    g = generate.rmat(8, 4, seed=3)
    jd = str(tmp_path / "jr")
    mg = MutableGraph(g, num_parts=2, journal_dir=jd)
    mg.apply([1, 2], [3, 4], [OP_INSERT, OP_INSERT])
    mg.compact(path=str(tmp_path / "s.lux"))
    # no batches survive; a fresh open on the NEW base sees a clean log
    log = DeltaLog(mg.base, journal_dir=jd)
    assert log.stats()["batches"] == 0 and log.empty


def test_delete_missing_edge_raises():
    g = generate.rmat(8, 4, seed=3)
    log = DeltaLog(g)
    # delete an edge, then delete it again -> second must fail
    u, v = int(g.col_idx[0]), int(g.dst_of_edges()[0])
    n_par = int(np.sum((g.col_idx[g.row_ptr[v]:g.row_ptr[v + 1]] == u)))
    for _ in range(n_par):
        log.apply([u], [v], [OP_DELETE])
    with pytest.raises(KeyError):
        log.apply([u], [v], [OP_DELETE])
    # insert-then-delete within one batch resolves in order
    log.apply([u, u], [v, v], [OP_INSERT, OP_DELETE])
    assert log.stats()["inserts_live"] == 0


def test_bucket_invalidation_is_minimal():
    """Churn confined to one part's destination range (at balanced
    insert/delete counts, so the shared e_pad stays put) invalidates
    EXACTLY that part's plan-cache bucket — PLAN_FORMAT 5's per-bucket
    keys doing their job through the compaction path."""
    g = generate.rmat(10, 8, seed=2)
    mg = MutableGraph(g, num_parts=4)
    cuts = np.asarray(mg.pull_shards.cuts)
    lo, hi = int(cuts[2]), int(cuts[3])
    dsts = g.dst_of_edges()
    in_p2 = np.flatnonzero((dsts >= lo) & (dsts < hi))
    rng = np.random.default_rng(0)
    dele = rng.choice(in_p2, 8, replace=False)
    mg.apply(g.col_idx[dele], dsts[dele], np.full(8, OP_DELETE, np.int8))
    mg.apply(rng.integers(0, g.nv, 8), rng.integers(lo, hi, 8),
             np.full(8, OP_INSERT, np.int8))
    rep = mg.compact()
    assert rep["invalidation"]["changed_parts"] == [2], rep
    assert rep["invalidation"]["fraction"] == 0.25


def test_weighted_refresh_and_zero_weight_guard():
    g = generate.rmat(9, 8, seed=5, weighted=True, max_weight=9)
    rng = np.random.default_rng(3)
    mg = MutableGraph(g, num_parts=2)
    from lux_tpu.models.sssp import WeightedSSSPProgram, sssp

    start = int(np.argmax(np.bincount(g.col_idx, minlength=g.nv)))
    prog = WeightedSSSPProgram(nv=g.nv, start=start)
    st, _, _ = push.run_push(prog, mg.push_shards)
    dist = mg.push_shards.scatter_to_global(np.asarray(st))
    dele = rng.choice(g.ne, 20, replace=False)
    mg.apply(g.col_idx[dele], g.dst_of_edges()[dele],
             np.full(20, OP_DELETE, np.int8))
    mg.apply(rng.integers(0, g.nv, 20), rng.integers(0, g.nv, 20),
             np.full(20, OP_INSERT, np.int8), rng.integers(1, 9, 20))
    dist2, _ = refresh_mod.refresh_sssp(mg, dist, start, weighted=True)
    want = sssp(mg.log.merged_graph(), start=start, num_parts=2,
                weighted=True)
    assert np.array_equal(dist2, want)
    # zero weights break the tight-edge cascade's induction: refuse
    mg0 = MutableGraph(g, num_parts=2)
    mg0.apply([1], [2], [OP_INSERT], [0])
    mg0.log.apply(*([g.col_idx[:1], g.dst_of_edges()[:1],
                     [OP_DELETE], [0]]))
    with pytest.raises(ValueError, match="positive"):
        refresh_mod.sssp_dirty(mg0, dist, start, weighted=True)


def test_compact_republish_to_fleet(tmp_path):
    """The full production loop: serve -> churn -> compact -> publish
    the compacted snapshot to a live 2-worker fleet through the
    token-guarded prepare/commit republish -> answers match the merged
    graph, zero shed."""
    from lux_tpu.graph.format import write_lux
    from lux_tpu.mutate import compact as compact_mod
    from lux_tpu.serve.fleet.bench import start_fleet

    g = generate.rmat(8, 4, seed=4)
    base_snap = str(tmp_path / "base.lux")
    write_lux(base_snap, g)
    mg = MutableGraph(g, num_parts=2)
    fleet = start_fleet(2, shards=mg.pull_shards, graph_id="live",
                        mode="thread", buckets=(1, 4))
    try:
        ctl = fleet.controller
        for s in (0, 3):
            assert np.array_equal(ctl.submit(s).result(timeout=60),
                                  bfs_reference(g, s))
        rng = np.random.default_rng(0)
        mg.apply(rng.integers(0, g.nv, 24), rng.integers(0, g.nv, 24),
                 np.full(24, OP_INSERT, np.int8))
        dele = rng.choice(g.ne, 12, replace=False)
        mg.apply(g.col_idx[dele], g.dst_of_edges()[dele],
                 np.full(12, OP_DELETE, np.int8))
        snap = str(tmp_path / "compacted.lux")
        mg.compact(path=snap)
        rep = compact_mod.publish_to_fleet(ctl, snap, graph_id="live")
        assert set(rep["generations"].values()) == {1}, rep
        merged = mg.base
        for s in (0, 3, 7):
            assert np.array_equal(ctl.submit(s).result(timeout=60),
                                  bfs_reference(merged, s)), s
        assert ctl.stats()["shed"] == 0
    finally:
        fleet.close()
