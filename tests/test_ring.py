"""Ring-streamed exchange must be numerically identical to all_gather."""
import numpy as np
import pytest

from lux_tpu.graph import generate
from lux_tpu.models import pagerank as pr
from lux_tpu.parallel import mesh as mesh_lib, ring


@pytest.fixture(scope="module")
def mesh8():
    return mesh_lib.make_mesh(8)


def test_ring_bucket_layout():
    g = generate.rmat(8, 6, seed=90)
    rs = ring.build_ring_shards(g, 4)
    # every edge appears in exactly one bucket (dst_local < V marks real)
    V = rs.spec.nv_pad
    total = int((rs.rarrays.dst_local < V).sum())
    assert total == g.ne


def test_ring_arrays_have_no_dense_rowptr():
    """The bucket layout must stay O(part edges): no field may carry a
    per-bucket V-sized axis (the O(P^2*V) blowup of SURVEY.md §7.3)."""
    g = generate.rmat(8, 6, seed=96)
    rs = ring.build_ring_shards(g, 4)
    for name, arr in rs.rarrays._asdict().items():
        assert arr.shape == (4, 4, rs.e_bucket_pad), name
    est_bytes = sum(a.nbytes for a in rs.rarrays)
    dense_rowptr_bytes = 4 * 4 * (rs.spec.nv_pad + 1) * 4
    assert est_bytes < dense_rowptr_bytes + 13 * 4 * 4 * rs.e_bucket_pad


def test_ring_subset_build_matches_full():
    """Per-host subset rows must equal the same rows of the full build."""
    g = generate.rmat(8, 6, seed=97, weighted=True)
    full = ring.build_ring_shards(g, 4)
    sub = ring.build_ring_shards(g, 4, parts_subset=[1, 3])
    assert sub.e_bucket_pad == full.e_bucket_pad  # global geometry agrees
    assert sub.parts_subset == [1, 3]
    for name, a_full in full.rarrays._asdict().items():
        a_sub = sub.rarrays._asdict()[name]
        np.testing.assert_array_equal(a_sub[0], a_full[1], err_msg=name)
        np.testing.assert_array_equal(a_sub[1], a_full[3], err_msg=name)


def _state0(prog, rs):
    import jax

    from lux_tpu.engine import pull

    return pull.init_state(prog, jax.tree.map(np.asarray, rs.arrays))


def test_ring_pagerank_matches_allgather(mesh8):
    g = generate.rmat(9, 8, seed=91)
    rs = ring.build_ring_shards(g, 8)
    prog = pr.PageRankProgram(nv=rs.spec.nv)
    out = ring.run_pull_fixed_ring(prog, rs, _state0(prog, rs), 6, mesh8)
    got = rs.scatter_to_global(np.asarray(out))
    want = pr.pagerank_reference(g, 6)
    np.testing.assert_allclose(got, want, rtol=3e-5)


def test_ring_cc(mesh8):
    from lux_tpu.models import components

    g = generate.uniform_random(600, 4000, seed=92)
    rs = ring.build_ring_shards(g, 8)
    prog = components.MaxLabelProgram()
    # fixed iterations sufficient for convergence on this size
    out = ring.run_pull_fixed_ring(prog, rs, _state0(prog, rs), 40, mesh8)
    labels = rs.scatter_to_global(np.asarray(out))
    assert components.check_labels(g, labels) == 0


def test_ring_cf_wide_state(mesh8):
    """CF on the ring: (V, K) blocks streamed by ppermute, dst-state
    gathered locally — the wide-state workload the ring path exists for."""
    from lux_tpu.models import colfilter as cf

    g = generate.bipartite_ratings(120, 80, 1500, seed=93)
    rs = ring.build_ring_shards(g, 8)
    prog = cf.CFProgram(gamma=1e-3)
    out = ring.run_pull_fixed_ring(prog, rs, _state0(prog, rs), 4, mesh8)
    got = rs.scatter_to_global(np.asarray(out))
    want = cf.colfilter_reference(g, 4, gamma=1e-3)
    np.testing.assert_allclose(got, want, rtol=3e-5, atol=1e-7)


def test_ring_bitwise_deterministic(mesh8):
    g = generate.rmat(8, 8, seed=94)
    rs = ring.build_ring_shards(g, 8)
    prog = pr.PageRankProgram(nv=rs.spec.nv)
    s0 = _state0(prog, rs)
    a = ring.run_pull_fixed_ring(prog, rs, s0, 5, mesh8)
    b = ring.run_pull_fixed_ring(prog, rs, s0, 5, mesh8)
    assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


def test_ring_scatter_method(mesh8):
    g = generate.rmat(8, 6, seed=95)
    rs = ring.build_ring_shards(g, 8)
    prog = pr.PageRankProgram(nv=rs.spec.nv)
    s0 = _state0(prog, rs)
    a = ring.run_pull_fixed_ring(prog, rs, s0, 4, mesh8, method="scatter")
    b = ring.run_pull_fixed_ring(prog, rs, s0, 4, mesh8, method="scan")
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)

def test_push_ring_sssp_matches_bfs(mesh8):
    """Direction-optimizing push with the RING dense exchange: same result
    as the all_gather push driver and the host BFS oracle."""
    from lux_tpu.engine import push
    from lux_tpu.models.sssp import SSSPProgram, bfs_reference

    g = generate.rmat(9, 8, seed=98)
    prs = ring.build_push_ring_shards(g, 8)
    prog = SSSPProgram(nv=prs.spec.nv, start=0)
    state, iters, edges = push.run_push_ring(prog, prs, mesh8)
    got = prs.scatter_to_global(np.asarray(state))
    np.testing.assert_array_equal(got, bfs_reference(g, 0))
    assert int(iters) >= 1
    assert push.edges_total(edges) > 0


def test_push_ring_cc_matches_allgather(mesh8):
    from lux_tpu.engine import push
    from lux_tpu.graph.push_shards import build_push_shards
    from lux_tpu.models import components

    g = generate.uniform_random(700, 5000, seed=99)
    prs = ring.build_push_ring_shards(g, 8)
    prog = components.MaxLabelProgram()
    ring_state, _, _ = push.run_push_ring(prog, prs, mesh8)
    ag_state, _, _ = push.run_push_dist(
        prog, build_push_shards(g, 8), mesh8
    )
    # min/max folds are exact: results must agree BITWISE
    assert np.asarray(ring_state).tobytes() == np.asarray(ag_state).tobytes()
    assert components.check_labels(
        g, prs.scatter_to_global(np.asarray(ring_state))
    ) == 0


def test_push_ring_weighted_sssp(mesh8):
    from lux_tpu.engine import push
    from lux_tpu.models import sssp as sssp_model

    g = generate.uniform_random(128, 1024, seed=100, weighted=True, max_weight=9)
    prs = ring.build_push_ring_shards(g, 8)
    prog = sssp_model.WeightedSSSPProgram(nv=prs.spec.nv, start=0)
    state, _, _ = push.run_push_ring(prog, prs, mesh8)
    got = prs.scatter_to_global(np.asarray(state))
    want = sssp_model.sssp(g, start=0, weighted=True)
    np.testing.assert_array_equal(got, want)


def test_model_wrappers_ring_exchange(mesh8):
    """Library-level exchange='ring' on the sssp/CC wrappers (the CLI path
    is tested separately)."""
    from lux_tpu.models import components, sssp as sssp_model

    g = generate.uniform_random(300, 2200, seed=101)
    a = sssp_model.sssp(g, start=0, num_parts=8, mesh=mesh8, exchange="ring")
    np.testing.assert_array_equal(a, sssp_model.bfs_reference(g, 0))
    labels = components.connected_components_push(
        g, num_parts=8, mesh=mesh8, exchange="ring"
    )
    assert components.check_labels(g, labels) == 0
    # pre-built PushRingShards also accepted, incl. on the 1-device path
    prs = ring.build_push_ring_shards(g, 8)
    b = sssp_model.sssp(prs, start=0, mesh=mesh8, exchange="ring")
    np.testing.assert_array_equal(b, a)
