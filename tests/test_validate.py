"""On-device validators agree with the host checkers."""
import numpy as np

from lux_tpu.engine import validate
from lux_tpu.graph import generate
from lux_tpu.graph.push_shards import build_push_shards
from lux_tpu.models import components, sssp


def test_device_sssp_check_clean():
    g = generate.rmat(9, 8, seed=110)
    shards = build_push_shards(g, 2)
    from lux_tpu.engine import push

    prog = sssp.SSSPProgram(nv=g.nv, start=0)
    state, _, _ = push.run_push(prog, shards)
    n = validate.count_violations(
        shards.pull, state, validate.sssp_violation(inf=prog.inf)
    )
    assert n == 0
    host = sssp.check_distances(g, shards.scatter_to_global(np.asarray(state)))
    assert host == 0


def test_device_sssp_check_detects_corruption():
    g = generate.rmat(9, 8, seed=111)
    shards = build_push_shards(g, 2)
    from lux_tpu.engine import push

    prog = sssp.SSSPProgram(nv=g.nv, start=0)
    state, _, _ = push.run_push(prog, shards)
    bad = np.asarray(state).copy()
    # corrupt: claim some far vertex is at distance 0 while its in-nbrs are far
    dist_g = shards.scatter_to_global(bad)
    # corrupt a vertex that provably creates violations: out-degree > 0
    # and far enough that its neighbors sit at distance >= 2
    deg = g.out_degrees()
    cand = np.nonzero((deg > 0) & (dist_g >= 2) & (dist_g < g.nv))[0]
    assert len(cand), "need a corruptible vertex"
    far = int(cand[0])
    p = np.searchsorted(shards.cuts, far, side="right") - 1
    bad[p, far - int(shards.cuts[p])] = 0
    dev = validate.count_violations(
        shards.pull, bad, validate.sssp_violation(inf=prog.inf)
    )
    host = sssp.check_distances(g, shards.scatter_to_global(bad))
    assert dev == host  # exact agreement
    assert dev > 0


def test_device_cc_check():
    g = generate.uniform_random(500, 3000, seed=112)
    shards = build_push_shards(g, 4)
    from lux_tpu.engine import push

    prog = components.MaxLabelProgram()
    state, _, _ = push.run_push(prog, shards)
    assert validate.count_violations(shards.pull, state, validate.cc_violation()) == 0
    # corrupt one label downward -> violations appear and counts match host
    bad = np.asarray(state).copy()
    bad[0, 0] = -1
    labels = shards.scatter_to_global(bad)
    dev = validate.count_violations(shards.pull, bad, validate.cc_violation())
    assert dev == components.check_labels(g, labels)
    assert dev > 0