"""Pass-fused routed replay (routed-pf): the round-6 hot-loop bet.

Pins, all in interpret mode on CPU (correctness never waits on a chip
window):

1. the fusion-group planner (ops/route.plan_fusion_groups) packs the
   Benes pass sequence under the block budget;
2. the pass-fused replay (ops/pallas_shuffle.plan_route_pf /
   pf_from_frozen) is BITWISE equal to the unfused replay and the raw
   permutation, across dtypes and forced group widths;
3. ops/expand.to_pf upgrades expand/fused/CF plans with identical
   results — routed-pf == routed == direct gather bitwise, and fused-pf
   == fused bitwise (same group layout, same association);
4. the pf plan-cache family round-trips (reload == fresh build);
5. the fill-forward base level no longer leaves the Pallas pipeline
   (the (1, 128) XLA fallback is gone);
6. the roofline HBM-pass accounting matches the plan's fusion grouping;
7. the fixed/until loops' opt-in state donation works without warnings.
"""
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from lux_tpu.ops import expand as E
from lux_tpu.ops import pallas_shuffle as S
from lux_tpu.ops import route as R


def _dev(arrays):
    return tuple(jnp.asarray(a) for a in arrays)


# ---------------------------------------------------------------------------
# grouping planner
# ---------------------------------------------------------------------------


def test_fusion_groups_pack_under_block_budget():
    # dims (128, 128, 128, 8): axes 0,1,2,3,2,1,0; {0,1,2} = 2^21 blows
    # a 2^17 budget, so the greedy packing is (2, 3, 2) — the {2,3,2}
    # middle rides one kernel (distinct-digit block 1024)
    assert R.plan_fusion_groups((128, 128, 128, 8), 1 << 17, 3) == (2, 3, 2)
    # dims (128, 128, 8, 8): {0,1,2} = 2^17 fits exactly
    assert R.plan_fusion_groups((128, 128, 8, 8), 1 << 17, 3) == (3, 3, 1)
    # max_group=1 degenerates to singletons
    assert R.plan_fusion_groups((128, 128, 8), 1 << 17, 1) == (1,) * 5
    # single digit: one pass, one group
    assert R.plan_fusion_groups((128,), 1 << 17, 3) == (1,)
    with pytest.raises(ValueError):
        R.plan_fusion_groups((128, 8), 64, 3)  # budget below one row
    with pytest.raises(ValueError):
        R.plan_fusion_groups((128, 8), 1 << 17, 0)


def test_fusion_groups_cover_every_pass():
    for dims in [(128,), (128, 8), (128, 128, 2), (128, 128, 128, 8),
                 (128, 8, 8)]:
        gs = R.plan_fusion_groups(dims)
        assert sum(gs) == 2 * len(dims) - 1


# ---------------------------------------------------------------------------
# pass-fused replay vs oracle / unfused — kernels + planner
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n", [128, 1024, 4096, 1 << 15, 1 << 17])
def test_pf_replay_matches_perm_and_unfused(n, rng):
    perm = rng.permutation(n)
    rt = R.build_route(perm)
    x = rng.random(n).astype(np.float32)
    st, arrs = S.freeze_plan(S.plan_route(rt))
    unf = np.asarray(S.apply_route_frozen(jnp.asarray(x), st, _dev(arrs),
                                          interpret=True))
    pst, parrs = S.plan_route_pf(rt)
    pf = np.asarray(S.apply_route_frozen(jnp.asarray(x), pst, _dev(parrs),
                                         interpret=True))
    np.testing.assert_array_equal(unf, x[perm])
    np.testing.assert_array_equal(pf, x[perm])
    # transforming the FROZEN unfused plan yields the identical pf plan
    pst2, parrs2 = S.pf_from_frozen(st, arrs)
    assert pst2 == pst
    for a, b in zip(parrs, parrs2):
        np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize(
    "group_sizes", [(1,) * 7, (2, 2, 2, 1), (1, 3, 3), (3, 3, 1), None]
)
def test_pf_forced_group_widths_bitwise(group_sizes, rng):
    """Every packing of the 7-pass Benes sequence replays the same bits
    — singletons, pairs, and full triples (2^20 = 128*128*8*8)."""
    n = 1 << 20
    perm = rng.permutation(n)
    rt = R.build_route(perm)
    pst, parrs = S.plan_route_pf(rt, group_sizes=group_sizes)
    if group_sizes is not None:
        assert tuple(len(g.steps) for g in pst.groups) == group_sizes
    x = rng.random(n).astype(np.float32)
    got = np.asarray(S.apply_route_frozen(jnp.asarray(x), pst,
                                          _dev(parrs), interpret=True))
    np.testing.assert_array_equal(got, x[perm])


@pytest.mark.parametrize("dtype", ["float32", "int32", "bfloat16"])
def test_pf_replay_dtypes(dtype, rng):
    n = 1 << 14
    perm = rng.permutation(n)
    pst, parrs = S.plan_route_pf(R.build_route(perm))
    if dtype == "int32":
        x = rng.integers(-(2**31), 2**31 - 1, n, dtype=np.int32)
        xj = jnp.asarray(x)
    else:
        x = rng.random(n).astype(np.float32)
        xj = jnp.asarray(x).astype(dtype)
        x = np.asarray(xj.astype(jnp.float32))
    got = S.apply_route_frozen(xj, pst, _dev(parrs), interpret=True)
    np.testing.assert_array_equal(
        np.asarray(got.astype(jnp.float32) if dtype == "bfloat16" else got),
        x[perm])


def test_pf_u8_indices_replay(rng):
    """The uint8-narrowed index tiles (the 4x traffic lever) feed the
    fused kernels exactly like the unfused ones."""
    n = 1 << 15
    perm = rng.permutation(n)
    pst, parrs = S.plan_route_pf(R.build_route(perm))
    for a in parrs:
        assert a.min() >= 0 and a.max() < 128  # u8-narrowable lanes
    dev8 = tuple(jnp.asarray(a.astype(np.uint8)) for a in parrs)
    x = rng.random(n).astype(np.float32)
    got = np.asarray(S.apply_route_frozen(jnp.asarray(x), pst, dev8,
                                          interpret=True))
    np.testing.assert_array_equal(got, x[perm])


def test_pf_rejects_non_lane_routes():
    """Sub-lane digits (d > 8 not dividing 128) and sub-128 spaces fall
    back loudly rather than gather garbage."""
    shape = (96, 128)
    rt = R.Route(n=96 * 128, dims=shape,
                 passes=[R.Pass(shape=shape, axis=0,
                                idx=np.zeros(shape, np.int32))])
    with pytest.raises(ValueError):
        S._pf_plan(96 * 128, shape, [np.zeros(shape, np.int32)], (1,),
                   8 << 20)
    del rt


def test_pf_vmem_budget_caps_tile_rows():
    """A tiny VMEM budget shrinks block_rows (but never below one block
    unit); a huge one caps at the whole array; a block unit that cannot
    fit the budget at all fails AT PLAN TIME naming the knobs (not as a
    Mosaic VMEM blow-up on chip)."""
    assert S._pf_block_rows(1 << 12, 128, 3, 1 << 20) >= 128
    small = S._pf_block_rows(1 << 12, 1, 2, 64 << 10)
    big = S._pf_block_rows(1 << 12, 1, 2, 1 << 30)
    assert small < big
    assert big <= 1 << 12
    with pytest.raises(ValueError, match="LUX_PF_MAX_BLOCK"):
        S._pf_block_rows(1 << 13, 1 << 13, 3, 1 << 20)


# ---------------------------------------------------------------------------
# expand-level: routed-pf vs routed vs direct, engine integration
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "e_pad,m,state_size",
    # non-power-of-two real-edge counts and sub-128 sizes: the pf space
    # is the pow2 envelope, real slots must stay bitwise
    [(512, 400, 300), (512, 100, 90), (2048, 1500, 2048),
     (256, 0, 100), (16384, 12000, 4096)],
)
def test_expand_pf_matches_gather(e_pad, m, state_size, rng):
    src_pos = np.zeros(e_pad, np.int32)
    src_pos[:m] = rng.integers(0, state_size, m)
    base = E.plan_expand(src_pos, m, state_size)
    static, arrays = E.to_pf(base)
    state = rng.standard_normal(state_size).astype(np.float32)
    got = np.asarray(
        E.apply_expand(jnp.asarray(state), static, _dev(arrays),
                       interpret=True))
    np.testing.assert_array_equal(got[:m], state[src_pos[:m]])
    # and bitwise equal to the unfused routed expand on EVERY slot
    # (identical permutations move identical padding junk too)
    unf = np.asarray(
        E.apply_expand(jnp.asarray(state), base[0], _dev(base[1]),
                       interpret=True))
    np.testing.assert_array_equal(got, unf)


def _pull_three_ways(graph, parts, prog_cls, iters, reduce="sum", **kw):
    from lux_tpu.engine import pull
    from lux_tpu.graph.shards import build_pull_shards

    shards = build_pull_shards(graph, parts)
    prog = prog_cls(**kw) if kw.pop("_no_nv", False) else \
        prog_cls(nv=shards.spec.nv, **kw)
    arrays = jax.tree.map(jnp.asarray, shards.arrays)
    s0 = pull.init_state(prog, arrays)
    direct = pull.run_pull_fixed(prog, shards.spec, arrays, s0, iters,
                                 method="scan")
    route = E.plan_expand_shards(shards)
    routed = pull.run_pull_fixed(prog, shards.spec, arrays, s0, iters,
                                 method="scan", route=route)
    pf = E.to_pf(route)
    routed_pf = pull.run_pull_fixed(prog, shards.spec, arrays, s0, iters,
                                    method="scan", route=pf)
    return np.asarray(direct), np.asarray(routed), np.asarray(routed_pf)


@pytest.mark.parametrize("parts", [1, 3])
def test_engine_pagerank_pf_bitwise(parts):
    from lux_tpu.graph import generate
    from lux_tpu.models.pagerank import PageRankProgram

    g = generate.rmat(8, 8, seed=3)
    direct, routed, routed_pf = _pull_three_ways(g, parts,
                                                 PageRankProgram, 5)
    np.testing.assert_array_equal(direct, routed)
    np.testing.assert_array_equal(direct, routed_pf)


def test_engine_components_max_reduce_pf_bitwise():
    """int32 state + max reduce through the pass-fused load (the fused
    kernels are dtype-agnostic moves, like the unfused ones)."""
    from lux_tpu.graph import generate
    from lux_tpu.models.components import MaxLabelProgram

    g = generate.rmat(8, 8, seed=4)
    direct, routed, routed_pf = _pull_three_ways(
        g, 2, MaxLabelProgram, 8, _no_nv=True)
    np.testing.assert_array_equal(direct, routed)
    np.testing.assert_array_equal(direct, routed_pf)


def test_engine_fused_pf_bitwise_vs_fused():
    """fused-pf lands the identical group layout, so its sum is BITWISE
    the unfused fused path's (the plan-deterministic association of the
    ISSUE contract), and numerically the direct engine's."""
    from lux_tpu.engine import pull
    from lux_tpu.graph import generate
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.models.pagerank import PageRankProgram

    g = generate.rmat(9, 8, seed=5)
    shards = build_pull_shards(g, 1)
    prog = PageRankProgram(nv=shards.spec.nv)
    arrays = jax.tree.map(jnp.asarray, shards.arrays)
    s0 = pull.init_state(prog, arrays)
    fz = E.plan_fused_shards(shards, "sum")
    fzpf = E.to_pf(fz)
    a = pull.run_pull_fixed(prog, shards.spec, arrays, s0, 3,
                            method="scan", route=fz)
    b = pull.run_pull_fixed(prog, shards.spec, arrays, s0, 3,
                            method="scan", route=fzpf)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    d = pull.run_pull_fixed(prog, shards.spec, arrays, s0, 3,
                            method="scan")
    np.testing.assert_allclose(np.asarray(b), np.asarray(d), rtol=3e-6)


def test_push_dense_rounds_pf_bitwise():
    """Routed-pf through the push engine's dense rounds (max-label CC:
    all-active start = dense) — bitwise state + identical counters."""
    from lux_tpu.engine import push
    from lux_tpu.graph import generate
    from lux_tpu.graph.push_shards import build_push_shards
    from lux_tpu.models.components import MaxLabelProgram

    g = generate.rmat(8, 8, seed=6)
    pshards = build_push_shards(g, 2)
    cc = MaxLabelProgram()
    st, it, ed = push.run_push(cc, pshards, 3, method="scan")
    proute = E.plan_expand_shards(pshards, pf=True)
    st2, it2, ed2 = push.run_push(cc, pshards, 3, method="scan",
                                  route=proute)
    np.testing.assert_array_equal(np.asarray(st), np.asarray(st2))
    assert int(it) == int(it2)
    assert push.edges_total(ed) == push.edges_total(ed2)


def test_cf_route_pf_bitwise(rng):
    """The CF (wide dst-dependent) route plan pass-fuses both sub-plans;
    src/dst reads stay bitwise equal to the direct gathers."""
    e_pad, m, S_, v_pad, k = 512, 400, 300, 256, 4
    src_pos = np.zeros(e_pad, np.int32)
    src_pos[:m] = rng.integers(0, S_, m)
    dst_local = np.full(e_pad, v_pad, np.int32)
    dst_local[:m] = np.sort(rng.integers(0, v_pad, m))
    s_src, a_src = E.plan_expand(src_pos, m, S_)
    s_dst, a_dst = E.plan_expand(dst_local, m, v_pad + 1)
    cf = (E.CFRouteStatic(src=s_src, dst=s_dst),
          tuple(a_src) + tuple(a_dst))
    cfpf = E.to_pf(cf)
    full = rng.standard_normal((S_, k)).astype(np.float32)
    local = rng.standard_normal((v_pad + 1, k)).astype(np.float32)
    got_s, got_d = E.apply_cf_route(jnp.asarray(full), jnp.asarray(local),
                                    cfpf[0], _dev(cfpf[1]), interpret=True)
    np.testing.assert_array_equal(np.asarray(got_s)[:m],
                                  full[src_pos[:m]])
    np.testing.assert_array_equal(np.asarray(got_d)[:m],
                                  local[dst_local[:m]])


# ---------------------------------------------------------------------------
# plan cache round-trip
# ---------------------------------------------------------------------------


def test_pf_plan_cache_roundtrip(tmp_path):
    """Grouped plan reload == fresh build: statics (incl. relayout
    specs and tile geometry) and every index array survive the
    npz+json codec."""
    from lux_tpu.graph import generate
    from lux_tpu.graph.shards import build_pull_shards

    g = generate.rmat(8, 8, seed=7)
    shards = build_pull_shards(g, 2)
    cdir = str(tmp_path / "cache")
    s1, a1 = E.plan_expand_shards_cached(shards, cache_dir=cdir, pf=True)
    s2, a2 = E.plan_expand_shards_cached(shards, cache_dir=cdir, pf=True)
    assert s1 == s2
    assert isinstance(s1.r1, S.StaticRoutePF)
    for x, y in zip(a1, a2):
        assert x.dtype == y.dtype
        np.testing.assert_array_equal(x, y)
    # the pf miss also warmed the UNFUSED family (its build input)
    assert E.has_cached_expand_plan(shards, cache_dir=cdir) is not None
    assert E.has_cached_expand_plan(shards, cache_dir=cdir,
                                    pf=True) is not None


def test_pf_cache_rejects_wrong_form_entries(tmp_path):
    """The pf family guard: handing UNFUSED-family paths to the pf
    planner (the cache_path misuse) must rebuild real pf plans, never
    silently replay unfused kernels under the pf label."""
    from lux_tpu.graph import generate
    from lux_tpu.graph.shards import build_pull_shards

    g = generate.rmat(8, 8, seed=7)
    shards = build_pull_shards(g, 1)
    cdir = str(tmp_path / "cache")
    E.plan_expand_shards_cached(shards, cache_dir=cdir)  # unfused only
    unfused_paths = E.has_cached_expand_plan(shards, cache_dir=cdir)
    assert unfused_paths is not None
    s, _ = E.plan_expand_shards_cached(shards, cache_dir=cdir, pf=True,
                                       cache_path=unfused_paths)
    assert isinstance(s.r1, S.StaticRoutePF)  # rebuilt, not mislabeled


def test_pf_cache_key_folds_fusion_knobs(tmp_path, monkeypatch):
    """Two processes with different fusion knobs must not share pf
    entries: the knob salt changes every pf path."""
    from lux_tpu.graph import generate
    from lux_tpu.graph.shards import build_pull_shards

    g = generate.rmat(8, 8, seed=7)
    shards = build_pull_shards(g, 1)
    cdir = str(tmp_path / "cache")
    E.plan_expand_shards_cached(shards, cache_dir=cdir, pf=True)
    before = sorted(os.listdir(cdir))
    monkeypatch.setenv("LUX_PF_MAX_GROUP", "1")
    s2, _ = E.plan_expand_shards_cached(shards, cache_dir=cdir, pf=True)
    after = sorted(os.listdir(cdir))
    assert len(after) > len(before)  # new entries, no collision
    assert all(len(gr.steps) == 1 for gr in s2.r1.groups)


# ---------------------------------------------------------------------------
# ff base level: no out-of-band XLA pass left
# ---------------------------------------------------------------------------


def test_lane_gather_sub_tile_rows_via_pallas(rng):
    """The (1, 128) ff base level (and any sub-8-row operand) now rides
    the Pallas kernel — Mosaic's 'Shape mismatch' rejection of sub-tile
    operands is dodged by row tiling, and the plain-XLA fallback is
    gone from the routed pipeline."""
    for r in (1, 2, 4):
        x = rng.random((r, 128)).astype(np.float32)
        idx = rng.integers(0, 128, (r, 128)).astype(np.int32)
        got = np.asarray(S.lane_gather(jnp.asarray(x), jnp.asarray(idx),
                                       interpret=True))
        np.testing.assert_array_equal(
            got, np.take_along_axis(x, idx, axis=1))
        jaxpr = str(jax.make_jaxpr(
            lambda a, b: S.lane_gather(a, b, interpret=True)
        )(jnp.asarray(x), jnp.asarray(idx)))
        assert "pallas_call" in jaxpr, f"r={r} fell back to XLA"


def test_ff_replay_still_exact_with_pallas_base(rng):
    """plan_ff end-to-end after the base-level change (regression for
    the satellite: zero out-of-band passes, same bits)."""
    n = 1 << 14
    nheads = n // 7
    heads = np.unique(np.concatenate([[0],
                                      rng.integers(0, n, nheads)]))
    h = heads[np.searchsorted(heads, np.arange(n), side="right") - 1]
    static, arrays = E.plan_ff(h)
    x = rng.standard_normal(n).astype(np.float32)
    got = np.asarray(E.apply_ff(jnp.asarray(x), static, _dev(arrays),
                                interpret=True))
    np.testing.assert_array_equal(got, E.apply_ff_np(x, h))


# ---------------------------------------------------------------------------
# roofline accounting
# ---------------------------------------------------------------------------


def test_hbm_pass_accounting_matches_grouping():
    from lux_tpu.graph import generate
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.utils import roofline

    g = generate.rmat(10, 8, seed=2)
    shards = build_pull_shards(g, 1)
    base = E.plan_expand_shards(shards)
    pf = E.to_pf(base)
    pb = roofline.routed_hbm_passes(base[0], "scan")
    pp = roofline.routed_hbm_passes(pf[0], "scan")
    assert pb["r1"] == len(base[0].r1.passes)
    assert pp["r1"] == len(pf[0].r1.groups)
    assert pp["reduce"] == pb["reduce"] == 2.0  # method term unchanged
    # the acceptance bound: >= 40% fewer accounted HBM passes
    assert pp["total"] <= 0.6 * pb["total"], (pp, pb)
    # byte model shrinks accordingly (data sweeps collapse, idx reads
    # stay), and the index-byte footprint is unchanged
    mb = roofline.routed_pull_iter_model(base[0], g.ne, g.nv)
    mp = roofline.routed_pull_iter_model(pf[0], g.ne, g.nv)
    assert mp.bytes_moved < 0.75 * mb.bytes_moved
    from lux_tpu.utils import preflight
    assert (preflight.routed_plan_bytes(pf[0])
            == preflight.routed_plan_bytes(base[0]))
    # fused statics report the group-space + accumulator terms too
    fz = E.plan_fused_shards(shards, "sum")
    fp = roofline.routed_hbm_passes(E.to_pf(fz)[0])
    assert {"r1", "ff", "r2", "reduce", "vr", "total"} <= set(fp)
    assert fp["total"] <= 0.6 * roofline.routed_hbm_passes(fz[0])["total"]


def test_direct_hbm_passes_field():
    from lux_tpu.utils import roofline

    d = roofline.pull_hbm_passes("scan")
    assert d == {"gather": 1.0, "reduce": 2.0, "total": 3.0}
    with pytest.raises(KeyError):
        roofline.pull_hbm_passes("nope")


# ---------------------------------------------------------------------------
# donation
# ---------------------------------------------------------------------------


def test_run_pull_fixed_donation(rng):
    """donate=True consumes state0 (single HBM copy in the hot loop)
    with NO donation warnings on this backend; the default keeps state0
    alive for benchmark-style reuse."""
    import warnings

    from lux_tpu.engine import pull
    from lux_tpu.graph import generate
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.models.pagerank import PageRankProgram

    g = generate.rmat(8, 8, seed=1)
    shards = build_pull_shards(g, 1)
    prog = PageRankProgram(nv=shards.spec.nv)
    arrays = jax.tree.map(jnp.asarray, shards.arrays)
    s0 = pull.init_state(prog, arrays)
    ref = np.asarray(pull.run_pull_fixed(prog, shards.spec, arrays, s0, 3,
                                         method="scan"))
    # default: s0 reusable
    again = pull.run_pull_fixed(prog, shards.spec, arrays, s0, 3,
                                method="scan")
    np.testing.assert_array_equal(ref, np.asarray(again))
    s1 = pull.init_state(prog, arrays)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        out = pull.run_pull_fixed(prog, shards.spec, arrays, s1, 3,
                                  method="scan", donate=True)
        jax.block_until_ready(out)
        donation_warnings = [str(i.message) for i in w
                             if "donat" in str(i.message).lower()]
    assert donation_warnings == [], donation_warnings
    np.testing.assert_array_equal(ref, np.asarray(out))
    with pytest.raises(RuntimeError):
        jnp.sum(s1).block_until_ready()  # actually donated


def test_run_pull_until_donation():
    import warnings

    from lux_tpu.engine import pull
    from lux_tpu.graph import generate
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.models.pagerank import PageRankProgram

    def active(old, new):
        return jnp.sum(jnp.abs(new - old) > 1e-7, axis=tuple(
            range(1, old.ndim))).astype(jnp.int32)

    g = generate.rmat(8, 8, seed=2)
    shards = build_pull_shards(g, 1)
    prog = PageRankProgram(nv=shards.spec.nv)
    arrays = jax.tree.map(jnp.asarray, shards.arrays)
    s0 = pull.init_state(prog, arrays)
    ref, it_ref = pull.run_pull_until(prog, shards.spec, arrays, s0, 5,
                                      active)
    s1 = pull.init_state(prog, arrays)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        out, it = pull.run_pull_until(prog, shards.spec, arrays, s1, 5,
                                      active, donate=True)
        jax.block_until_ready(out)
        donation_warnings = [str(i.message) for i in w
                             if "donat" in str(i.message).lower()]
    assert donation_warnings == [], donation_warnings
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))
    assert int(it) == int(it_ref)
    with pytest.raises(RuntimeError):
        jnp.sum(s1).block_until_ready()


# ---------------------------------------------------------------------------
# route-mode overlay
# ---------------------------------------------------------------------------


def test_route_mode_default_env_and_overlay(tmp_path, monkeypatch):
    import json

    from lux_tpu.engine import methods

    # hermetic: no overlay file -> the design-bet default
    monkeypatch.setenv("LUX_METHOD_WINNERS",
                       str(tmp_path / "nonexistent.json"))
    methods._overlay_raw_cache = None
    assert methods.route_mode() == "routed-pf"
    # env override wins and is validated
    monkeypatch.setenv("LUX_ROUTE_MODE", "routed")
    assert methods.route_mode() == "routed"
    monkeypatch.setenv("LUX_ROUTE_MODE", "bogus")
    with pytest.raises(ValueError):
        methods.route_mode()
    monkeypatch.delenv("LUX_ROUTE_MODE")
    # a recorded overlay entry is followed; junk entries are ignored
    f = tmp_path / "w.json"
    f.write_text(json.dumps({methods.ROUTE_MODE_KEY: "routed"}))
    monkeypatch.setenv("LUX_METHOD_WINNERS", str(f))
    methods._overlay_raw_cache = None
    assert methods.route_mode() == "routed"
    f.write_text(json.dumps({methods.ROUTE_MODE_KEY: "garbage"}))
    methods._overlay_raw_cache = None
    assert methods.route_mode() == "routed-pf"
    methods._overlay_raw_cache = None


def test_bare_route_gather_follows_route_mode(monkeypatch):
    """The bare --route-gather flag ('auto') is the overlay's consumer:
    a banked tpu:route_mode winner changes which plan family the next
    app run builds — no code edit, like the method winners."""
    from types import SimpleNamespace

    from lux_tpu.apps import common

    monkeypatch.setenv("LUX_ROUTE_MODE", "routed")
    cfg = SimpleNamespace(route_gather="auto")
    common.resolve_route_auto(cfg)
    assert cfg.route_gather == "expand"
    monkeypatch.setenv("LUX_ROUTE_MODE", "routed-pf")
    cfg = SimpleNamespace(route_gather="auto")
    common.resolve_route_auto(cfg)
    assert cfg.route_gather == "expand-pf"
    # explicit modes pass through untouched
    cfg = SimpleNamespace(route_gather="expand")
    common.resolve_route_auto(cfg)
    assert cfg.route_gather == "expand"


def test_bench_records_route_mode_winner(tmp_path, monkeypatch):
    import json
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench

    f = tmp_path / "w.json"
    monkeypatch.setenv("LUX_METHOD_WINNERS", str(f))
    bench._record_route_mode({"_route": 2.0})  # one flavor: no record
    assert not f.exists()
    bench._record_route_mode({"_route": 2.0, "_routepf": 1.0})
    assert json.loads(f.read_text())["tpu:route_mode"] == "routed-pf"
    bench._record_route_mode({"_route": 1.0, "_routepf": 2.0})
    assert json.loads(f.read_text())["tpu:route_mode"] == "routed"
