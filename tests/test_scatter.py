"""reduce_scatter exchange must match the all_gather result exactly."""
import numpy as np
import pytest

from lux_tpu.engine import pull
from lux_tpu.graph import generate
from lux_tpu.models import pagerank as pr
from lux_tpu.parallel import mesh as mesh_lib, scatter


@pytest.fixture(scope="module")
def mesh8():
    return mesh_lib.make_mesh(8)


def _state0(prog, ss):
    return pull.init_state(prog, ss.arrays)


def test_scatter_bucket_layout():
    g = generate.rmat(8, 6, seed=120)
    ss = scatter.build_scatter_shards(g, 4)
    V = ss.spec.nv_pad
    assert int((ss.sarrays.dst_local < V).sum()) == g.ne
    for name, arr in ss.sarrays._asdict().items():
        assert arr.shape == (4, 4, ss.e_bucket_pad), name  # no V-sized axis


def test_scatter_subset_build_matches_full():
    g = generate.rmat(8, 6, seed=124, weighted=True)
    full = scatter.build_scatter_shards(g, 4)
    sub = scatter.build_scatter_shards(g, 4, parts_subset=[0, 2])
    assert sub.e_bucket_pad == full.e_bucket_pad
    for name, a_full in full.sarrays._asdict().items():
        a_sub = sub.sarrays._asdict()[name]
        np.testing.assert_array_equal(a_sub[0], a_full[0], err_msg=name)
        np.testing.assert_array_equal(a_sub[1], a_full[2], err_msg=name)


def test_scatter_pagerank_matches_oracle(mesh8):
    g = generate.rmat(9, 8, seed=121)
    ss = scatter.build_scatter_shards(g, 8)
    prog = pr.PageRankProgram(nv=ss.spec.nv)
    out = scatter.run_pull_fixed_scatter(prog, ss, _state0(prog, ss), 6, mesh8)
    got = ss.scatter_to_global(np.asarray(out))
    np.testing.assert_allclose(got, pr.pagerank_reference(g, 6), rtol=3e-5)


def test_scatter_rejects_cf(mesh8):
    """CF needs per-edge dst state — incompatible with pre-combination."""
    from lux_tpu.models import colfilter as cf

    g = generate.bipartite_ratings(50, 40, 400, seed=122)
    ss = scatter.build_scatter_shards(g, 8)
    prog = cf.CFProgram()
    with pytest.raises(AssertionError, match="destination state"):
        scatter.run_pull_fixed_scatter(prog, ss, _state0(prog, ss), 2, mesh8)


def test_scatter_rejects_minmax(mesh8):
    from lux_tpu.models import components

    g = generate.rmat(8, 4, seed=123)
    ss = scatter.build_scatter_shards(g, 8)
    prog = components.MaxLabelProgram()
    with pytest.raises(AssertionError, match="sum-reducible"):
        scatter.run_pull_fixed_scatter(prog, ss, _state0(prog, ss), 2, mesh8)

def test_scatter_k_resident_parts(mesh8):
    """P=16 parts on the 8-device mesh (k=2 resident source parts per
    chip): lane partials pre-sum before the psum_scatter, and the tiled
    scatter hands each device its two parts back — same fixed point as
    the single-device engine."""
    from lux_tpu.models import pagerank as pr

    g = generate.rmat(10, 8, seed=124)
    ss = scatter.build_scatter_shards(g, 16)
    prog = pr.PageRankProgram(nv=ss.spec.nv)
    out = scatter.run_pull_fixed_scatter(prog, ss, _state0(prog, ss), 6, mesh8)
    got = ss.scatter_to_global(np.asarray(out))
    np.testing.assert_allclose(got, pr.pagerank_reference(g, 6), rtol=3e-5)
