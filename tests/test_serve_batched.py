"""serve/batched: bitwise parity with the one-shot engines, per-query
convergence masking, and the query-axis contract.

The headline acceptance pin: batched multi-source SSSP on rmat16 equals
Q independent single-source engine/push.py runs BITWISE for
Q in {1, 8, 64}, including early-converging queries in a mixed batch.
"""
import dataclasses

import numpy as np
import pytest

from lux_tpu.engine import push
from lux_tpu.graph import generate
from lux_tpu.graph.push_shards import build_push_shards
from lux_tpu.graph.shards import build_pull_shards
from lux_tpu.models import sssp as sssp_model
from lux_tpu.serve.batched import (
    BatchedEngine,
    MultiSourcePPR,
    MultiSourceSSSP,
)


@pytest.fixture(scope="module")
def rmat16():
    g = generate.rmat(16, 16, seed=7)
    return g, build_push_shards(g, 1)


def _push_reference(pshards, sources):
    """Independent single-source engine/push.py runs (ONE compiled loop,
    the engine's own compile cache) -> (len(sources), nv) distances."""
    import jax
    import jax.numpy as jnp

    proto = sssp_model.SSSPProgram(nv=pshards.spec.nv, start=0)
    loop = push.compile_push_chunk(proto, pshards.pspec, pshards.spec)
    arrays = jax.tree.map(jnp.asarray, pshards.arrays)
    parrays = jax.tree.map(jnp.asarray, pshards.parrays)
    out = []
    for s in sources:
        prog = dataclasses.replace(proto, start=int(s))
        carry = push._init_carry(prog, pshards.pspec, arrays)
        res = loop(arrays, parrays, carry, jnp.int32(10_000))
        out.append(pshards.scatter_to_global(np.asarray(res.state)))
    return np.stack(out)


def _mixed_sources(g, n):
    """n distinct sources, a MIXED convergence profile: the hub (deepest
    run), a zero-out-degree vertex when one exists (converges in one
    round), plus low- and mid-degree vertices."""
    deg = np.bincount(g.col_idx, minlength=g.nv)
    order = np.argsort(deg)
    picks = [int(np.argmax(deg))]
    if deg[order[0]] == 0:
        picks.append(int(order[0]))  # early-converging: no out-edges
    lo = order[deg[order] > 0]
    picks.extend(int(v) for v in lo[: n])
    picks.extend(int(v) for v in order[::-1][1: n])
    uniq = list(dict.fromkeys(picks))[:n]
    assert len(uniq) == n
    return np.asarray(uniq, np.int32)


def test_batched_sssp_bitwise_vs_push_rmat16(rmat16):
    g, pshards = rmat16
    refs16 = _mixed_sources(g, 16)
    want = _push_reference(pshards, refs16)

    shards = pshards.pull
    # Q = 1 and Q = 8: direct slices of the reference set
    got1 = BatchedEngine(shards, "sssp", 1).run(refs16[:1]).state
    assert np.array_equal(got1, want[:1])
    got8 = BatchedEngine(shards, "sssp", 8).run(refs16[:8]).state
    assert np.array_equal(got8, want[:8])
    # Q = 64: the 16 reference sources tiled — every one of the 64
    # queries is checked against its own independent push run, and the
    # batch mixes early-converging with deep queries
    q64 = np.tile(refs16, 4)
    out = BatchedEngine(shards, "sssp", 64).run(q64)
    assert np.array_equal(out.state, want[np.tile(np.arange(16), 4)])
    # per-query masking: rounds differ across the mixed batch, and a
    # finished query stopped contributing traversed edges
    assert out.rounds.min() < out.rounds.max()
    assert min(out.traversed) < max(out.traversed)
    assert out.iters == int(out.rounds.max())


def test_batched_sssp_small_vs_bfs_oracle():
    g = generate.rmat(10, 8, seed=3)
    shards = build_pull_shards(g, 4)  # multi-part stacking too
    srcs = _mixed_sources(g, 6)
    out = BatchedEngine(shards, "sssp", 6).run(srcs)
    for i, s in enumerate(srcs):
        assert np.array_equal(out.state[i], sssp_model.bfs_reference(g, int(s)))


def test_sssp_batched_library_helper():
    g = generate.rmat(9, 8, seed=5)
    srcs = _mixed_sources(g, 3)
    got = sssp_model.sssp_batched(g, srcs, num_parts=2)
    for i, s in enumerate(srcs):
        assert np.array_equal(got[i], sssp_model.sssp(g, start=int(s),
                                                      num_parts=2))


def test_batched_ppr_matches_single_seed_pull():
    """Each batched PPR column equals the single-seed PPRProgram pull run
    BITWISE (lane-independent reducers), and approximates the float64
    host oracle."""
    from lux_tpu.engine import pull
    from lux_tpu.models.pagerank import PPRProgram, ppr_reference

    g = generate.rmat(10, 8, seed=11)
    shards = build_pull_shards(g, 2)
    seeds = _mixed_sources(g, 4)
    out = BatchedEngine(shards, "ppr", 4, num_iters=8).run(seeds)
    for i, s in enumerate(seeds):
        prog = PPRProgram(nv=g.nv, seed=int(s))
        s0 = pull.init_state(prog, shards.arrays)
        single = pull.run_pull_fixed(prog, shards.spec, shards.arrays, s0, 8)
        assert np.array_equal(out.state[i],
                              shards.scatter_to_global(np.asarray(single)))
        want = ppr_reference(g, int(s), 8)
        np.testing.assert_allclose(out.state[i], want, rtol=2e-4, atol=1e-7)


def test_ppr_mass_concentrates_at_seed():
    g = generate.rmat(9, 8, seed=2)
    shards = build_pull_shards(g, 1)
    deg = np.bincount(g.col_idx, minlength=g.nv)
    seed = int(np.argmax(deg))
    out = BatchedEngine(shards, "ppr", 1, num_iters=10).run([seed])
    ranks = out.state[0] * np.maximum(deg, 1)  # undo the pre-division
    assert int(np.argmax(ranks)) == seed  # teleport mass pins the seed


def test_engine_validates_inputs():
    g = generate.rmat(8, 4, seed=1)
    shards = build_pull_shards(g, 1)
    eng = BatchedEngine(shards, "sssp", 2)
    with pytest.raises(ValueError, match="compiled for Q=2"):
        eng.run([1, 2, 3])
    with pytest.raises(ValueError, match="out of range"):
        eng.run([0, g.nv])
    with pytest.raises(ValueError, match="unknown served app"):
        BatchedEngine(shards, "nope", 1)
    with pytest.raises(ValueError, match="q must be"):
        BatchedEngine(shards, "sssp", 0)


def test_programs_are_hashable_statics():
    # the compile caches key on the program dataclasses
    assert hash(MultiSourceSSSP(nv=10)) == hash(MultiSourceSSSP(nv=10))
    assert MultiSourcePPR(nv=10) == MultiSourcePPR(nv=10)
