"""lux_tpu.serve.fleet: consistent-hash router properties (bounded key
movement, cross-process determinism), wire framing, controller/worker
end-to-end (routing affinity, backpressure, kill-a-worker mid-burst,
zero-downtime republish bitwise under load), and the PR's satellites
(warm-cache LRU eviction, replica-labelled Prometheus dump, the
--verbose validate message)."""
import collections
import json
import os
import socket
import struct
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from lux_tpu.graph import generate
from lux_tpu.graph.format import write_lux
from lux_tpu.graph.shards import build_pull_shards
from lux_tpu.models.sssp import bfs_reference
from lux_tpu.serve.fleet.controller import (
    FleetController,
    FleetError,
    FleetRejectedError,
)
from lux_tpu.serve.fleet.hashring import (
    DEFAULT_SLOTS,
    HashRing,
    h64,
    route_key,
)
from lux_tpu.serve.fleet.wire import Conn, WireError
from lux_tpu.serve.fleet.worker import ReplicaWorker

HASHRING_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "lux_tpu", "serve", "fleet", "hashring.py")


# ----------------------------------------------------------------------
# hashring properties
# ----------------------------------------------------------------------


def _slot_keys():
    return [f"sssp|g|q{i}" for i in range(DEFAULT_SLOTS)]


def test_ring_balance_reasonable():
    r = HashRing()
    for i in range(4):
        r.add(f"w{i}")
    loads = collections.Counter(r.table(_slot_keys()).values())
    assert set(loads) == {"w0", "w1", "w2", "w3"}
    # 64 vnodes x 4 workers over 512 slots: no worker above 2x fair share
    assert max(loads.values()) <= 2 * DEFAULT_SLOTS // 4


@pytest.mark.parametrize("n_before", [2, 4, 7])
def test_join_moves_at_most_about_one_over_r(n_before):
    r = HashRing()
    for i in range(n_before):
        r.add(f"w{i}")
    keys = _slot_keys()
    before = r.table(keys)
    r.add("wNEW")
    after = r.table(keys)
    moved = [k for k in keys if before[k] != after[k]]
    # every moved key lands ON the joiner — consistent hashing's contract
    assert moved and all(after[k] == "wNEW" for k in moved)
    # and the moved fraction is ~1/(R+1) (2x slack for vnode variance)
    assert len(moved) <= 2 * len(keys) // (n_before + 1)


def test_leave_moves_only_the_leavers_keys():
    r = HashRing()
    for i in range(4):
        r.add(f"w{i}")
    keys = _slot_keys()
    before = r.table(keys)
    r.remove("w2")
    after = r.table(keys)
    for k in keys:
        if before[k] == "w2":
            assert after[k] != "w2"
        else:  # a key w2 never owned must not move at all
            assert after[k] == before[k]
    r.add("w2")
    assert r.table(keys) == before  # re-join restores the exact table


def test_successors_distinct_and_start_with_owner():
    r = HashRing()
    for i in range(3):
        r.add(f"w{i}")
    for k in _slot_keys()[:32]:
        walk = r.successors(k, 3)
        assert walk[0] == r.route(k)
        assert len(walk) == len(set(walk)) == 3


def test_routing_deterministic_across_processes():
    """The route table must not depend on interpreter state (hash seed):
    a fresh process loading hashring.py STANDALONE (no lux_tpu import)
    derives the identical table."""
    r = HashRing()
    for i in range(4):
        r.add(f"w{i}")
    here = [r.route(route_key("sssp", "g", s)) for s in range(200)]
    code = (
        "import importlib.util, json, sys\n"
        "spec = importlib.util.spec_from_file_location('hr', sys.argv[1])\n"
        "m = importlib.util.module_from_spec(spec)\n"
        "spec.loader.exec_module(m)\n"
        "r = m.HashRing()\n"
        "for i in range(4): r.add(f'w{i}')\n"
        "print(json.dumps([r.route(m.route_key('sssp', 'g', s))"
        " for s in range(200)]))\n"
    )
    env = dict(os.environ, PYTHONHASHSEED="12345")
    out = subprocess.run([sys.executable, "-c", code, HASHRING_PATH],
                         capture_output=True, text=True, env=env,
                         timeout=60)
    assert out.returncode == 0, out.stderr
    assert json.loads(out.stdout) == here


def test_route_key_folds_to_bounded_slots():
    keys = {route_key("sssp", "g", s, slots=16) for s in range(5000)}
    assert len(keys) == 16  # every slot hit, none outside
    assert route_key("sssp", "g", 7) == route_key("sssp", "g", 7)
    assert route_key("sssp", "g", 7) != route_key("ppr", "g", 7)
    assert h64("x") == h64("x") and h64("x") != h64("y")


# ----------------------------------------------------------------------
# wire framing
# ----------------------------------------------------------------------


def test_wire_roundtrip_json_and_arrays():
    a, b = socket.socketpair()
    ca, cb = Conn(a), Conn(b)
    ca.send({"op": "hello", "n": 3})
    msg, arr = cb.recv()
    assert msg == {"op": "hello", "n": 3} and arr is None
    for dt in (np.int32, np.float32, np.float64, np.uint8):
        want = np.arange(37, dtype=dt).reshape(1, 37)
        cb.send({"req_id": "r1", "ok": True}, arr=want)
        msg, got = ca.recv()
        assert msg["ok"] and got.dtype == want.dtype
        assert np.array_equal(got, want)
    ca.close(), cb.close()


def test_wire_rejects_oversized_and_bad_frames():
    a, b = socket.socketpair()
    ca, cb = Conn(a), Conn(b)
    with pytest.raises(WireError):
        ca.send({"x": "y" * (20 * 1024 * 1024)})
    # a corrupt length prefix fails loudly on the reader (12 bytes:
    # header_len / payload_len / payload_crc32)
    a.sendall(b"\xff" * 12)
    with pytest.raises(WireError):
        cb.recv()
    ca.close(), cb.close()
    # flipped bits inside the npy DATA region are caught by the crc —
    # they would otherwise parse as a valid, WRONG array
    a, b = socket.socketpair()
    ca, cb = Conn(a), Conn(b)
    ca.send({"op": "ans"}, arr=np.arange(64))
    hdr = _recv12(b)
    body = bytearray()
    while len(body) < hdr[0] + hdr[1]:
        body.extend(b.recv(65536))
    body[hdr[0] + hdr[1] // 2] ^= 0xFF  # corrupt mid-payload
    b2a, b2b = socket.socketpair()
    c2 = Conn(b2b)
    b2a.sendall(struct.pack("!III", *hdr) + bytes(body))
    with pytest.raises(WireError, match="crc"):
        c2.recv()
    ca.close(), cb.close(), c2.close(), b2a.close()


def _recv12(sock):
    buf = b""
    while len(buf) < 12:
        buf += sock.recv(12 - len(buf))
    return struct.unpack("!III", buf)


# ----------------------------------------------------------------------
# controller/worker end-to-end (thread-mode workers, real sockets)
# ----------------------------------------------------------------------


@pytest.fixture(scope="module")
def small():
    g = generate.rmat(8, 4, seed=4)
    return g, build_pull_shards(g, 2)


def _mk_fleet(shards, n=2, graph_id="g", **worker_kw):
    buckets = worker_kw.pop("q_buckets", (1, 4))
    workers = [
        ReplicaWorker(shards, f"w{i}", graph_id=graph_id,
                      q_buckets=buckets, **worker_kw).start()
        for i in range(n)
    ]
    ctl = FleetController(hb_interval_s=0.1)
    for w in workers:
        ctl.add_worker("127.0.0.1", w.port)
    return ctl, workers


def _teardown(ctl, workers):
    ctl.close()
    for w in workers:
        if w._running:
            w.stop()


def test_fleet_answers_match_reference_and_route_affinity(small):
    g, shards = small
    ctl, workers = _mk_fleet(shards, 2)
    try:
        srcs = [0, 3, 7, 11, 20, 33, 40, 41]
        futs = [ctl.submit(s) for s in srcs]
        for s, f in zip(srcs, futs):
            assert np.array_equal(f.result(timeout=60),
                                  bfs_reference(g, s)), s
            # unsaturated fleet: the answering worker IS the ring owner
            assert f.worker_id == ctl.route(s)
        # affinity: resubmitting lands on the same worker every time
        again = [ctl.submit(s) for s in srcs]
        for s, f in zip(srcs, again):
            f.result(timeout=60)
            assert f.worker_id == ctl.route(s)
        st = ctl.stats()
        assert st["completed"] == 16 and st["errors"] == 0
        # hello carried the layout; both workers visible with heartbeats
        time.sleep(0.3)
        ws = ctl.workers()
        assert set(ws) == {"w0", "w1"}
        assert all(w["alive"] for w in ws.values())
    finally:
        _teardown(ctl, workers)


def test_worker_heartbeat_and_prom_replica_label(small):
    g, shards = small
    ctl, workers = _mk_fleet(shards, 1)
    try:
        for f in [ctl.submit(s) for s in (0, 3)]:
            f.result(timeout=60)
        hb = workers[0].heartbeat()
        assert hb["max_queue"] == 256 and hb["generation"] == 0
        assert hb["warm_buckets"] == {"sssp": [1, 4]}
        assert hb["completed"] >= 2 and hb["shed_total"] == 0
        text = ctl.prom_dump()
        line = next(l for l in text.splitlines()
                    if l.startswith("lux_serve_requests_completed_total"))
        assert '{replica="w0"}' in line
        assert int(line.rsplit(" ", 1)[1]) >= 2
        # histogram samples merge the replica label ahead of le
        assert 'lux_serve_request_latency_seconds_bucket{replica="w0",le=' \
            in text
    finally:
        _teardown(ctl, workers)


def test_backpressure_sheds_and_recovers(small):
    g, shards = small
    # tiny queues + a long coalescing window: floods must overrun
    ctl, workers = _mk_fleet(shards, 2, max_queue=2, max_wait_ms=50.0)
    try:
        shed = 0
        futs = []
        for i in range(120):
            try:
                futs.append(ctl.submit(int(i % g.nv)))
            except FleetRejectedError as e:
                shed += 1
                assert e.retry_after_ms > 0
        assert shed > 0, "flood past 2x2-deep queues must shed"
        # degraded, never wrong: whatever was admitted resolves correctly
        ok = 0
        for f in futs:
            try:
                a = f.result(timeout=60)
            except FleetError:
                continue
            assert np.array_equal(a, bfs_reference(g, f.source))
            ok += 1
        assert ok > 0
        st = ctl.stats()
        assert st["shed"] + st["rerouted"] > 0
        # after the flood drains the fleet answers normally again — the
        # saturated flags clear on the next heartbeat, so honor the
        # retry-after contract like a real client
        deadline = time.monotonic() + 30
        while True:
            try:
                f = ctl.submit(3)
                break
            except FleetRejectedError as e:
                assert time.monotonic() < deadline, "never unsaturated"
                time.sleep(min(e.retry_after_ms / 1e3, 0.2))
        assert np.array_equal(f.result(timeout=60), bfs_reference(g, 3))
    finally:
        _teardown(ctl, workers)


def test_kill_worker_mid_burst_redistributes(small):
    g, shards = small
    ctl, workers = _mk_fleet(shards, 2)
    try:
        srcs = [int(s) % g.nv for s in range(40)]
        futs = [ctl.submit(s) for s in srcs]
        # kill the worker that owns the most in-flight keys, mid-burst
        victim = collections.Counter(
            ctl.route(s) for s in srcs).most_common(1)[0][0]
        next(w for w in workers if w.worker_id == victim).kill()
        for s, f in zip(srcs, futs):
            # every answer that arrives is CORRECT (some orphans may
            # exhaust retries during the death window — degraded is
            # allowed, wrong is not)
            try:
                a = f.result(timeout=60)
            except FleetError:
                continue
            assert np.array_equal(a, bfs_reference(g, s)), s
        st = ctl.stats()
        assert st["worker_deaths"] == 1
        assert ctl.live_workers() == sorted(
            w.worker_id for w in workers if w.worker_id != victim)
        # the ring healed: every key routes to the survivor, answers flow
        futs = [ctl.submit(s) for s in srcs[:8]]
        for s, f in zip(srcs[:8], futs):
            assert np.array_equal(f.result(timeout=60),
                                  bfs_reference(g, s))
            assert f.worker_id != victim
    finally:
        _teardown(ctl, workers)


def test_republish_under_load_bitwise_and_zero_shed(small, tmp_path):
    """The acceptance test: answers bitwise-equal to a cold
    single-process run BEFORE and AFTER the swap, with zero
    rejected-due-to-swap requests."""
    from lux_tpu.serve.batched import BatchedEngine

    g, shards = small
    snap = str(tmp_path / "snap.lux")
    write_lux(snap, g)
    # the cold single-process oracle: one engine, no fleet
    cold = BatchedEngine(shards, "sssp", 1)
    oracle = {s: cold.run([s]).query_state(0) for s in (0, 3, 7, 11)}

    ctl, workers = _mk_fleet(shards, 2, graph_id="snap.lux")
    try:
        stop = threading.Event()
        results = []

        def pump():
            i = 0
            while not stop.is_set():
                s = (0, 3, 7, 11)[i % 4]
                try:
                    results.append((s, ctl.submit(s)))
                except Exception as e:  # noqa: BLE001 — a swap-caused
                    # reject would land here and fail the zero-shed gate
                    results.append((s, e))
                i += 1
                time.sleep(0.005)

        t = threading.Thread(target=pump)
        t.start()
        time.sleep(0.2)
        rep = ctl.republish(snap, graph_id="snap.lux")
        time.sleep(0.2)
        stop.set()
        t.join()
        assert rep["generations"] == {"w0": 1, "w1": 1}
        assert len(results) > 20
        for s, f in results:
            assert not isinstance(f, Exception), f
            assert np.array_equal(f.result(timeout=60), oracle[s]), s
        st = ctl.stats()
        assert st["shed"] == 0 and st["errors"] == 0
        assert st["republishes"] == 1
        for w in workers:
            hb = w.heartbeat()
            assert hb["generation"] == 1 and not hb["staged"]
        # and the fleet still answers bitwise-correct after the swap
        f = ctl.submit(7)
        assert np.array_equal(f.result(timeout=60), oracle[7])
    finally:
        _teardown(ctl, workers)


def test_republish_prepare_failure_aborts_safely(small, tmp_path):
    g, shards = small
    ctl, workers = _mk_fleet(shards, 2)
    try:
        with pytest.raises(FleetError):
            ctl.republish(str(tmp_path / "missing.lux"))
        # abort left the old generation serving everywhere
        for w in workers:
            hb = w.heartbeat()
            assert hb["generation"] == 0 and not hb["staged"]
        f = ctl.submit(3)
        assert np.array_equal(f.result(timeout=60), bfs_reference(g, 3))
    finally:
        _teardown(ctl, workers)


def test_republish_mixed_prepare_failure_discards_staged(
        small, tmp_path, monkeypatch):
    """One worker's prepare succeeds, another's fails: the abort must
    DISCARD the successful worker's staged cache (a fully-prewarmed
    second engine set must not sit resident forever)."""
    g, shards = small
    snap = str(tmp_path / "snap.lux")
    write_lux(snap, g)
    ctl, workers = _mk_fleet(shards, 2, graph_id="snap.lux")
    try:
        real_send = ctl._send

        def crooked_send(handle, msg, pending):
            if msg.get("op") == "prepare" and handle.wid == "w1":
                # snapshots stream over the wire now: corrupt the
                # announced digest so w1's reassembly verification (and
                # therefore its prepare) fails while w0's succeeds
                msg = {**msg, "sha256": "0" * 64}
            return real_send(handle, msg, pending)

        monkeypatch.setattr(ctl, "_send", crooked_send)
        with pytest.raises(FleetError, match="aborted"):
            ctl.republish(snap, graph_id="snap.lux")
        for w in workers:  # w0 prepared successfully — and was told to drop it
            hb = w.heartbeat()
            assert hb["generation"] == 0 and not hb["staged"], w.worker_id
        f = ctl.submit(3)
        assert np.array_equal(f.result(timeout=60), bfs_reference(g, 3))
    finally:
        _teardown(ctl, workers)


def test_republish_commit_failure_retires_uncommitted(
        small, tmp_path, monkeypatch):
    """A commit failure after the point of no return must never leave
    the fleet mixed-generation: the worker that could not commit is
    retired (its keys move to committed successors), never left serving
    the OLD graph under the new id."""
    g, shards = small
    snap = str(tmp_path / "snap.lux")
    write_lux(snap, g)
    ctl, workers = _mk_fleet(shards, 2, graph_id="snap.lux")
    try:
        real_rpc = ctl._rpc

        def crooked_rpc(handle, msg, timeout_s):
            if msg.get("op") == "commit" and handle.wid == "w1":
                raise FleetError("injected commit failure")
            return real_rpc(handle, msg, timeout_s)

        monkeypatch.setattr(ctl, "_rpc", crooked_rpc)
        rep = ctl.republish(snap, graph_id="snap2.lux")
        assert rep["generations"] == {"w0": 1}
        assert rep["retired"] == ["w1"]
        assert ctl.graph_id == "snap2.lux"
        assert ctl.live_workers() == ["w0"]
        # every subsequent answer comes from the committed replica
        for s in (0, 3, 7):
            f = ctl.submit(s)
            assert np.array_equal(f.result(timeout=60),
                                  bfs_reference(g, s))
            assert f.worker_id == "w0"
    finally:
        _teardown(ctl, workers)


def test_controller_close_is_not_worker_death(small):
    g, shards = small
    ctl, workers = _mk_fleet(shards, 2)
    try:
        for f in [ctl.submit(s) for s in (0, 3)]:
            f.result(timeout=60)
        ctl.close()
        time.sleep(0.2)  # readers observe the closed conns
        assert ctl.stats()["worker_deaths"] == 0
    finally:
        _teardown(ctl, workers)


def test_controller_close_resolves_inflight_futures(small):
    """close() must never leave a waiter hanging: a query still queued
    behind the coalescing window resolves with 'controller closed'."""
    g, shards = small
    # a long coalescing window holds a single query in the worker queue
    # well past close() (teardown's drain still dispatches it after the
    # window, so keep the window test-sized)
    ctl, workers = _mk_fleet(shards, 1, max_wait_ms=4_000.0)
    try:
        fut = ctl.submit(3)
        ctl.close()
        with pytest.raises(FleetError, match="controller closed"):
            fut.result(timeout=10)
    finally:
        _teardown(ctl, workers)


def test_fleet_future_first_resolution_wins():
    from lux_tpu.serve.fleet.controller import FleetFuture

    fut = FleetFuture("sssp", 0, None)
    want = np.arange(4)
    fut._resolve(result=want)
    fut._resolve(error=FleetError("late duplicate"))  # must be inert
    assert np.array_equal(fut.result(timeout=1), want)


def test_prom_dump_merges_families_across_workers(small):
    """The fleet aggregate must be ONE valid exposition: HELP/TYPE once
    per metric family, every family's samples grouped, one labelled
    sample per replica."""
    g, shards = small
    ctl, workers = _mk_fleet(shards, 2)
    try:
        for f in [ctl.submit(s) for s in (0, 3, 7, 11)]:
            f.result(timeout=60)
        text = ctl.prom_dump()
        lines = text.splitlines()
        type_fams = [l.split(" ", 3)[2] for l in lines
                     if l.startswith("# TYPE ")]
        assert len(type_fams) == len(set(type_fams)), "duplicate TYPE"
        comp = [l for l in lines
                if l.startswith("lux_serve_requests_completed_total{")]
        assert sorted(comp)[0].startswith(
            'lux_serve_requests_completed_total{replica="w0"}')
        assert len(comp) == 2  # one series per replica, grouped
        # grouping: both samples directly follow their family's TYPE
        at = lines.index(
            "# TYPE lux_serve_requests_completed_total counter")
        assert set(lines[at + 1:at + 3]) == set(comp)
    finally:
        _teardown(ctl, workers)


def test_mismatched_graph_id_rejected(small):
    g, shards = small
    w0 = ReplicaWorker(shards, "w0", graph_id="gA").start()
    w1 = ReplicaWorker(shards, "w1", graph_id="gB").start()
    ctl = FleetController(hb_interval_s=0.1)
    try:
        ctl.add_worker("127.0.0.1", w0.port)
        with pytest.raises(FleetError):
            ctl.add_worker("127.0.0.1", w1.port)
        assert ctl.live_workers() == ["w0"]
    finally:
        _teardown(ctl, [w0, w1])


class _FakeConn:
    """Collects replies from direct worker-op calls (no socket)."""

    def __init__(self):
        self.sent = []

    def send(self, msg, arr=None):
        self.sent.append((msg, arr))


def test_stale_prepare_cannot_stage_or_commit(small, tmp_path, monkeypatch):
    """The publish-token protocol: a prepare superseded by a newer one
    (or by a discard) must not stage, and a commit only swaps the cache
    staged under ITS OWN token — a slow prepare from an aborted
    republish can never put the wrong graph in service."""
    import lux_tpu.graph.shards as shards_mod

    g, shards = small
    snap = str(tmp_path / "snap.lux")
    write_lux(snap, g)
    w = ReplicaWorker(shards, "w0", graph_id="snap.lux", q_buckets=(1,))
    conn = _FakeConn()
    # a newer republish (t2) claims the worker WHILE t1's build runs:
    # inject the claim mid-build through the shard-build call
    real_build = shards_mod.build_pull_shards

    def build_and_supersede(*a, **kw):
        with w._lock:
            w._publish_token = "t2"  # what a newer _op_prepare entry does
        return real_build(*a, **kw)

    monkeypatch.setattr(shards_mod, "build_pull_shards",
                        build_and_supersede)
    w._op_prepare(conn, {"op": "prepare", "req_id": 1, "path": snap,
                         "graph_id": "snap.lux", "token": "t1"})
    monkeypatch.setattr(shards_mod, "build_pull_shards", real_build)
    assert conn.sent[-1][0]["ok"] is False
    assert "superseded" in conn.sent[-1][0]["err"]
    assert w._staged is None
    # a real t2 prepare stages; a commit carrying a DIFFERENT token is
    # refused and leaves the staged cache alone
    w._op_prepare(conn, {"op": "prepare", "req_id": 2, "path": snap,
                         "graph_id": "snap.lux", "token": "t2"})
    assert conn.sent[-1][0]["ok"] is True and w._staged is not None
    w._op_commit(conn, {"op": "commit", "req_id": 3, "token": "t1"})
    assert conn.sent[-1][0]["ok"] is False
    assert "does not match" in conn.sent[-1][0]["err"]
    assert w._staged is not None and w._generation == 0
    # the matching token commits
    w._op_commit(conn, {"op": "commit", "req_id": 4, "token": "t2"})
    assert conn.sent[-1][0]["ok"] is True and w._generation == 1
    assert w._staged is None and w._publish_token is None


def test_discard_strands_inflight_prepare(small, tmp_path):
    g, shards = small
    snap = str(tmp_path / "snap.lux")
    write_lux(snap, g)
    w = ReplicaWorker(shards, "w0", graph_id="snap.lux", q_buckets=(1,))
    conn = _FakeConn()
    # abort (discard) lands while t1's build is "in flight": clearing
    # the token means the finishing prepare must not stage
    with w._lock:
        w._publish_token = None  # what the discard op does
    w._op_prepare(conn, {"op": "prepare", "req_id": 1, "path": snap,
                         "graph_id": "snap.lux", "token": "t1"})
    # _op_prepare sets the token itself at entry, so drive the discard
    # AFTER entry via the dispatch path instead: stage then discard
    assert conn.sent[-1][0]["ok"] is True
    w._dispatch(conn, {"op": "discard", "req_id": 2})
    assert conn.sent[-1][0]["discarded"] is True
    assert w._staged is None and w._publish_token is None
    w._op_commit(conn, {"op": "commit", "req_id": 3, "token": "t1"})
    assert conn.sent[-1][0]["ok"] is False  # nothing staged anymore


def test_ramp_stops_when_start_rate_is_past_capacity(small):
    from lux_tpu.serve.fleet.bench import ramp_to_knee

    g, shards = small
    ctl, workers = _mk_fleet(shards, 1, max_queue=16)
    try:
        srcs = np.asarray([0, 3, 7, 11], np.int32)
        res = ramp_to_knee(ctl, srcs, start_qps=2000.0, growth=1.6,
                           max_levels=6, window_s=0.2, timeout_ms=500.0,
                           refine_levels=0)
        # hopeless from level 0: two consecutive unsustained levels end
        # the ramp without burning the whole geometric schedule
        assert len(res["levels"]) == 2
        assert not res["knee_sustained"]
    finally:
        _teardown(ctl, workers)


# ----------------------------------------------------------------------
# satellites: warm-cache LRU, metrics counters, driver message
# ----------------------------------------------------------------------


def test_warm_cache_lru_eviction_bounded(small):
    from lux_tpu.serve.metrics import ServeMetrics
    from lux_tpu.serve.warm import WarmEngineCache

    g, shards = small
    metrics = ServeMetrics()
    cache = WarmEngineCache(shards, apps=("sssp",), q_buckets=(1, 2),
                            metrics=metrics, max_engines=2)
    cache.prewarm()
    assert cache.stats()["evictions"] == 0
    # a third shape evicts the least-recently-used (bucket 1)
    cache.get("sssp", 3)
    st = cache.stats()
    assert st["engines"] == 2 and st["evictions"] == 1
    assert st["max_engines"] == 2
    assert metrics.counters()["evictions"] == 1
    assert cache.warm_buckets("sssp") == (2, 3)
    # the evicted shape re-enters as a fresh cold trace (counted)
    cold_before = cache.stats()["cold_traces"]
    _, warm = cache.get("sssp", 1)
    assert not warm and cache.stats()["cold_traces"] == cold_before + 1
    # metrics surface: the eviction counter is in summary and prom text
    assert metrics.summary()["evictions"] >= 1
    assert "lux_serve_engine_evictions_total" in metrics.dump()


def test_warm_cache_cap_env_knob(small, monkeypatch):
    from lux_tpu.serve.warm import WarmEngineCache

    g, shards = small
    monkeypatch.setenv("LUX_SERVE_ENGINE_CAP", "1")
    cache = WarmEngineCache(shards, apps=("sssp",), q_buckets=(1, 2))
    cache.prewarm()
    assert cache.stats()["engines"] == 1 and cache.stats()["evictions"] == 1
    monkeypatch.setenv("LUX_SERVE_ENGINE_CAP", "garbage")
    with pytest.raises(ValueError, match="LUX_SERVE_ENGINE_CAP"):
        WarmEngineCache(shards, apps=("sssp",), q_buckets=(1,))


def test_metrics_dump_without_replica_unchanged():
    from lux_tpu.serve.metrics import ServeMetrics

    m = ServeMetrics()
    m.record_done(latency_s=0.01, wait_s=0.001, traversed=5)
    text = m.dump()
    assert "replica=" not in text
    assert "lux_serve_requests_completed_total 1" in text
    labelled = m.dump(replica="r9")
    assert 'lux_serve_requests_completed_total{replica="r9"} 1' in labelled
    assert 'lux_serve_request_latency_seconds_count{replica="r9"} 1' \
        in labelled


def test_driver_validate_names_verbose_flag():
    from lux_tpu.serve.driver import _validate
    from lux_tpu.utils.config import RunConfig

    with pytest.raises(SystemExit, match="--verbose"):
        _validate(RunConfig(serve=True, verbose=True))


# ----------------------------------------------------------------------
# the saturation harness (cheap shapes; the real ramp is the tool)
# ----------------------------------------------------------------------


def test_offered_level_and_ramp_shapes(small):
    from lux_tpu.serve.fleet.bench import offered_level, ramp_to_knee

    g, shards = small
    ctl, workers = _mk_fleet(shards, 2)
    try:
        srcs = np.asarray([0, 3, 7, 11], np.int32)
        lv = offered_level(ctl, srcs, rate=40.0, window_s=0.3)
        assert lv["submitted"] >= 12 and lv["completed"] == lv["submitted"]
        assert lv["fail_frac"] == 0.0 and lv["p99_ms"] >= lv["p50_ms"] > 0
        res = ramp_to_knee(ctl, srcs, start_qps=30.0, growth=2.0,
                           max_levels=2, window_s=0.25, refine_levels=0)
        assert res["knee_qps"] > 0 and len(res["levels"]) == 2
        assert {"knee_p99_ms", "knee_offered_qps"} <= set(res)
    finally:
        _teardown(ctl, workers)


@pytest.mark.slow
def test_proc_mode_fleet_end_to_end(small, tmp_path):
    """One REAL worker process over the same wire protocol: spawn,
    handshake, answer, clean shutdown (the mode fleet_bench defaults
    to; thread-mode tests cover the protocol, this covers the process
    entry)."""
    from lux_tpu.serve.fleet.bench import start_fleet

    g, shards = small
    snap = str(tmp_path / "snap.lux")
    write_lux(snap, g)
    fleet = start_fleet(1, graph_path=snap, graph_id="snap.lux",
                        mode="proc", buckets=(1, 4))
    try:
        futs = [fleet.controller.submit(s) for s in (0, 7)]
        for s, f in zip((0, 7), futs):
            assert np.array_equal(f.result(timeout=120),
                                  bfs_reference(g, s))
        assert fleet.controller.stats()["completed"] == 2
    finally:
        fleet.close()
    assert fleet.procs[0].wait(timeout=30) is not None


# ----------------------------------------------------------------------
# ISSUE 19 satellites: frame-bound handshake, wire snapshot streaming,
# the lease RPC
# ----------------------------------------------------------------------


def test_worker_refuses_controller_frame_bound_mismatch(
        small, monkeypatch):
    """One direction of the handshake guard: a controller advertising a
    DIFFERENT payload bound is refused by the worker at hello, loudly,
    naming the knob — not dropped mid-protocol on the first big frame."""
    from lux_tpu.serve.fleet.controller import WorkerRefusedError

    g, shards = small
    w = ReplicaWorker(shards, "wf", graph_id="g").start()
    try:
        monkeypatch.setattr(FleetController, "_hello_info",
                            lambda self: {"max_frame_bytes": 1 << 20})
        ctl = FleetController(hb_interval_s=0.1)
        try:
            with pytest.raises(WorkerRefusedError,
                               match="LUX_FLEET_MAX_FRAME_MB"):
                ctl.add_worker("127.0.0.1", w.port)
        finally:
            ctl.close()
    finally:
        w.stop()


def test_controller_refuses_worker_frame_bound_mismatch(
        small, monkeypatch):
    """The other direction: a worker advertising a different bound is
    refused by add_worker (the controller mutes its own advertisement so
    the worker-side guard doesn't fire first)."""
    from lux_tpu.serve.fleet import worker as worker_mod

    g, shards = small
    monkeypatch.setattr(worker_mod, "max_frame_bytes",
                        lambda: 1 << 20)
    monkeypatch.setattr(FleetController, "_hello_info", lambda self: {})
    w = ReplicaWorker(shards, "wf", graph_id="g").start()
    try:
        ctl = FleetController(hb_interval_s=0.1)
        try:
            with pytest.raises(FleetError,
                               match="LUX_FLEET_MAX_FRAME_MB"):
                ctl.add_worker("127.0.0.1", w.port)
        finally:
            ctl.close()
    finally:
        w.stop()


def test_republish_streams_snapshot_no_shared_path(
        small, tmp_path, monkeypatch):
    """The no-shared-filesystem pin: prepare frames carry stream
    metadata (token + sha256), NEVER a path — the snapshot bytes travel
    as stream_begin/stream_chunk frames and each worker stages from its
    own private spool dir."""
    g, shards = small
    snap = str(tmp_path / "snap.lux")
    write_lux(snap, g)
    ctl, workers = _mk_fleet(shards, 2, graph_id="snap.lux")
    try:
        seen = []
        real_send = ctl._send

        def spy(handle, msg, pending):
            seen.append(msg)
            return real_send(handle, msg, pending)

        monkeypatch.setattr(ctl, "_send", spy)
        rep = ctl.republish(snap, graph_id="snap.lux")
        assert rep["generations"] == {"w0": 1, "w1": 1}
        preps = [m for m in seen if m.get("op") == "prepare"]
        assert len(preps) == 2
        for m in preps:
            assert "path" not in m, m
            assert m["stream"] is True
            assert len(m["sha256"]) == 64
        begins = [m for m in seen if m.get("op") == "stream_begin"]
        assert len(begins) == 2 and all(m["chunks"] >= 1
                                        for m in begins)
        # each worker reassembled under its OWN spool dir, disjoint
        spools = {w.worker_id: w._streams.dirpath for w in workers}
        assert len(set(spools.values())) == 2
        for w in workers:
            hb = w.heartbeat()
            assert hb["generation"] == 1 and not hb["staged"]
        f = ctl.submit(3)
        assert np.array_equal(f.result(timeout=60), bfs_reference(g, 3))
    finally:
        _teardown(ctl, workers)


def test_serve_lease_rpc_and_wire_incumbent(small):
    """ping() IS a lease grant: a WireIncumbent dialing serve_lease()
    learns the incarnation and heartbeat terms from the first renewal,
    renews over the wire, and sees controller death as a raised probe
    (the dropped/silent lease port) — the Standby duck type across a
    process boundary."""
    from lux_tpu.serve.autopilot.election import WireIncumbent

    g, shards = small
    ctl, workers = _mk_fleet(shards, 1)
    inc = None
    try:
        port = ctl.serve_lease()
        assert ctl.serve_lease() == port  # idempotent
        inc = WireIncumbent("127.0.0.1", port)
        assert inc.incarnation == ctl.incarnation
        assert inc.hb_interval_s == pytest.approx(ctl.hb_interval_s)
        assert inc.hb_timeout_s == pytest.approx(ctl.hb_timeout_s)
        grant = inc.ping()
        assert grant["workers_alive"] == 1
        ctl.kill()  # fault drill: the lease port goes dark
        with pytest.raises(Exception):
            inc.ping()
            inc.ping()  # first probe may see the close as a reply EOF
    finally:
        if inc is not None:
            inc.close()
        _teardown(ctl, workers)
