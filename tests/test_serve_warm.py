"""serve/warm: engine cache keys, pre-tracing, hit accounting, layout
invalidation, and winners-overlay method resolution reuse."""
import json

import numpy as np
import pytest

from lux_tpu.engine import methods
from lux_tpu.graph import generate
from lux_tpu.graph.shards import build_pull_shards
from lux_tpu.serve.warm import EngineKey, WarmEngineCache, layout_key


@pytest.fixture(scope="module")
def small():
    g = generate.rmat(8, 4, seed=4)
    return g, build_pull_shards(g, 2)


def test_prewarm_and_hit_accounting(small):
    g, shards = small
    cache = WarmEngineCache(shards, apps=("sssp",), q_buckets=(1, 4))
    assert cache.warm_buckets("sssp") == ()
    spent = cache.prewarm()
    assert spent > 0 and cache.warm_buckets("sssp") == (1, 4)
    eng, warm = cache.get("sssp", 4)
    assert warm and eng.q == 4
    assert cache.stats()["warm_hits"] == 1
    # an unwarmed bucket is a cold trace; afterwards it reads warm
    _, warm = cache.get("sssp", 2)
    assert not warm
    _, warm2 = cache.get("sssp", 2)
    assert warm2
    st = cache.stats()
    assert st["cold_traces"] == 1 and st["warm_hits"] == 2
    assert 0 < st["warm_hit_ratio"] < 1
    out = eng.run(np.asarray([0, 1, 2, 3], np.int32))
    assert out.state.shape == (4, g.nv)


def test_engine_key_binds_layout(small):
    g, shards = small
    cache = WarmEngineCache(shards, apps=("sssp",), q_buckets=(2,))
    cache.prewarm()
    assert cache.is_warm("sssp", 2)
    other = build_pull_shards(g, 4)  # different part geometry
    assert layout_key(other) != layout_key(shards)
    cache.install_shards(other)
    # old-layout engines dropped: the compiled shapes no longer match
    assert not cache.is_warm("sssp", 2)
    cache.prewarm()
    eng, _ = cache.get("sssp", 2)
    want = [np.argmax(np.bincount(g.col_idx, minlength=g.nv)), 0]
    out = eng.run(np.asarray(want, np.int32))
    from lux_tpu.models.sssp import bfs_reference

    assert np.array_equal(out.state[0], bfs_reference(g, int(want[0])))


def test_method_resolution_reuses_overlay(small, monkeypatch, tmp_path):
    _, shards = small
    path = tmp_path / "winners.json"
    path.write_text(json.dumps({"cpu:min": "scan"}))
    monkeypatch.setenv("LUX_METHOD_WINNERS", str(path))
    monkeypatch.setattr(methods, "_overlay_raw_cache", None)
    monkeypatch.setattr(methods, "_file_winners_cache", None)
    monkeypatch.setattr(methods, "_tiles_cache", None)
    cache = WarmEngineCache(shards, apps=("sssp", "ppr"), q_buckets=(1,))
    # sssp reduces with min -> the overlay row redirects it; ppr (sum)
    # keeps the static cpu winner
    assert cache.key("sssp", 1).method == "scan"
    assert cache.key("ppr", 1).method == methods.WINNERS[("cpu", "sum")]
    assert isinstance(cache.key("sssp", 1), EngineKey)
