"""Native C++ I/O layer: build, converter equivalence, partial reads."""
import os
import subprocess
import sys

import numpy as np
import pytest

from lux_tpu import native
from lux_tpu.graph import generate
from lux_tpu.graph.csc import from_edge_list
from lux_tpu.graph.format import read_lux, read_lux_range, write_lux

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.skipif(
    native.get_lib() is None, reason="native toolchain unavailable"
)


def test_native_header_and_ranges(tmp_path):
    g = generate.rmat(8, 8, seed=60, weighted=True)
    p = str(tmp_path / "g.lux")
    write_lux(p, g)
    assert native.read_header(p) == (g.nv, g.ne)
    rows, cols, w = native.read_range(
        p, g.nv, g.ne, 10, 20, int(g.row_ptr[10]), int(g.row_ptr[20]), True
    )
    np.testing.assert_array_equal(rows.astype(np.int64), g.row_ptr[11:21])
    np.testing.assert_array_equal(
        cols.astype(np.int32), g.col_idx[g.row_ptr[10] : g.row_ptr[20]]
    )
    np.testing.assert_array_equal(w, g.weights[g.row_ptr[10] : g.row_ptr[20]])


def test_native_write_matches_python(tmp_path):
    rng = np.random.default_rng(61)
    nv, ne = 200, 2000
    src = rng.integers(0, nv, ne).astype(np.uint32)
    dst = rng.integers(0, nv, ne).astype(np.uint32)
    w = rng.integers(1, 100, ne).astype(np.int32)
    py = from_edge_list(src, dst, nv, weights=w)
    p = str(tmp_path / "native.lux")
    assert native.write_from_edges(p, nv, src, dst, w)
    gn = read_lux(p)
    np.testing.assert_array_equal(gn.row_ptr, py.row_ptr)
    np.testing.assert_array_equal(gn.col_idx, py.col_idx)
    np.testing.assert_array_equal(gn.weights, py.weights)


def test_native_degrees():
    g = generate.uniform_random(100, 900, seed=62)
    deg = native.count_degrees(g.col_idx, g.nv)
    np.testing.assert_array_equal(deg, g.out_degrees())


def test_read_lux_range(tmp_path):
    g = generate.rmat(8, 6, seed=63)
    p = str(tmp_path / "r.lux")
    write_lux(p, g)
    row_ptr, cols, w = read_lux_range(p, 30, 70)
    np.testing.assert_array_equal(
        row_ptr, g.row_ptr[30:71] - g.row_ptr[30]
    )
    np.testing.assert_array_equal(cols, g.col_idx[g.row_ptr[30] : g.row_ptr[70]])
    assert w is None


@pytest.mark.parametrize("weighted", [False, True])
@pytest.mark.parametrize("fallback", [False, True])
def test_converter_cli_roundtrip(tmp_path, weighted, fallback):
    """Text edge list -> .lux -> read_lux matches the from_edge_list
    oracle, on BOTH converter paths (native lux-convert and the
    --python NumPy fallback), weighted and not."""
    rng = np.random.default_rng(64)
    nv, ne = 50, 400
    src = rng.integers(0, nv, ne)
    dst = rng.integers(0, nv, ne)
    w = rng.integers(1, 50, ne) if weighted else None
    cols = [src, dst] + ([w] if weighted else [])
    txt = tmp_path / "edges.txt"
    np.savetxt(txt, np.stack(cols, 1), fmt="%d")
    out = str(tmp_path / "cli.lux")
    rc = subprocess.call(
        [sys.executable, os.path.join(REPO, "tools", "converter.py"),
         "-nv", str(nv), "-ne", str(ne), "-input", str(txt), "-output", out]
        + (["-weighted"] if weighted else [])
        + (["--python"] if fallback else []),
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert rc == 0
    g = read_lux(out)
    want = from_edge_list(src, dst, nv, weights=w)
    np.testing.assert_array_equal(g.row_ptr, want.row_ptr)
    np.testing.assert_array_equal(g.col_idx, want.col_idx)
    if weighted:
        np.testing.assert_array_equal(g.weights, want.weights)
    else:
        assert g.weights is None


def test_converter_cli_bad_count(tmp_path):
    txt = tmp_path / "edges.txt"
    txt.write_text("0 1\n1 2\n")
    rc = subprocess.call(
        [sys.executable, os.path.join(REPO, "tools", "converter.py"),
         "-nv", "3", "-ne", "5", "-input", str(txt), "-output",
         str(tmp_path / "x.lux")],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert rc != 0

def test_bucket_split_matches_numpy():
    """Native counting-sort bucketing == the NumPy stable-argsort path."""
    from lux_tpu import native

    rng = np.random.default_rng(60)
    cuts = np.array([0, 7, 7, 20, 33], np.int64)  # includes an empty part
    srcs = rng.integers(0, 33, size=500).astype(np.int64)
    res = native.bucket_split(srcs, cuts)
    if res is None:
        import pytest

        pytest.skip("native lib unavailable")
    order, counts = res
    own = np.searchsorted(cuts, srcs, side="right") - 1
    np.testing.assert_array_equal(counts, np.bincount(own, minlength=4))
    np.testing.assert_array_equal(order, np.argsort(own, kind="stable"))


def _with_fallback(fn):
    """Run fn twice: native lib active, then forced NumPy fallback."""
    a = fn()
    save, saved_tried = native._lib, native._tried
    native._lib, native._tried = None, True
    try:
        b = fn()
    finally:
        native._lib, native._tried = save, saved_tried
    return a, b


@pytest.mark.parametrize("weighted", [False, True])
def test_push_part_build_matches_numpy(weighted):
    """Native counting-sort push-CSR build == the NumPy argsort path,
    bitwise, on every PushArrays field (incl. padding slots)."""
    from lux_tpu.graph.push_shards import build_push_shards

    g = generate.rmat(10, 8, seed=65, weighted=weighted)
    a, b = _with_fallback(lambda: build_push_shards(g, 4))
    assert a.pspec == b.pspec
    for name in a.parrays._fields:
        np.testing.assert_array_equal(
            getattr(a.parrays, name), getattr(b.parrays, name), err_msg=name
        )


def test_push_part_build_float_weights_fall_back():
    """Non-integer weights route to the NumPy path (native is int32-only)
    and still produce the float32 CSR weights."""
    from lux_tpu.graph.push_shards import build_push_shards

    g = generate.rmat(8, 4, seed=66, weighted=True)
    g = type(g)(nv=g.nv, ne=g.ne, row_ptr=g.row_ptr, col_idx=g.col_idx,
                weights=g.weights.astype(np.float64) / 3.0)
    sh = build_push_shards(g, 2)
    assert sh.parrays.csr_weight.dtype == np.float32
    assert sh.parrays.csr_weight.sum() > 0


def test_fill_src_pos_matches_numpy():
    """Native src_pos fill == the searchsorted formula on every pull
    array (the whole fill_part output, weighted)."""
    from lux_tpu.graph.shards import build_pull_shards

    g = generate.rmat(10, 8, seed=67, weighted=True)
    a, b = _with_fallback(lambda: build_pull_shards(g, 4))
    for name in a.arrays._fields:
        np.testing.assert_array_equal(
            getattr(a.arrays, name), getattr(b.arrays, name), err_msg=name
        )


def test_push_part_build_empty_part():
    """A part with zero edges (contrived cuts) survives both paths."""
    from lux_tpu.graph.csc import HostGraph
    from lux_tpu.graph.push_shards import build_push_shards

    # vertices 0..3; all edges point at vertex 3 => parts [0,2) empty
    row_ptr = np.array([0, 0, 0, 0, 3], np.int64)
    col_idx = np.array([0, 1, 2], np.int32)
    g = HostGraph(nv=4, ne=3, row_ptr=row_ptr, col_idx=col_idx)
    a, b = _with_fallback(
        lambda: build_push_shards(g, 2, cuts=np.array([0, 2, 4]))
    )
    for name in a.parrays._fields:
        np.testing.assert_array_equal(
            getattr(a.parrays, name), getattr(b.parrays, name), err_msg=name
        )


@pytest.mark.parametrize("weighted", [False, True])
def test_blockcsr_fill_matches_numpy(weighted):
    """Native block-CSR chunk fill == the NumPy flat-scatter path on every
    array, across non-default tile shapes."""
    from lux_tpu.ops import pallas_spmv as ps

    g = generate.rmat(10, 8, seed=68, weighted=weighted)
    a, b = _with_fallback(lambda: ps.build_blockcsr(g, v_blk=128, t_chunk=256))
    for f in ("e_src_pos", "e_dst_rel", "e_weight", "chunk_block",
              "chunk_first"):
        x, y = getattr(a, f), getattr(b, f)
        if x is None:
            assert y is None
            continue
        np.testing.assert_array_equal(x, y, err_msg=f)


@pytest.mark.parametrize("weighted", [False, True])
def test_bucket_fill_matches_numpy_ring(weighted):
    """Native one-pass bucket fill == the NumPy per-bucket path, bitwise,
    on every RingArrays field (incl. padding and head flags)."""
    from lux_tpu.parallel.ring import build_ring_shards

    g = generate.rmat(10, 8, seed=66, weighted=weighted)
    a, b = _with_fallback(lambda: build_ring_shards(g, 4))
    assert a.e_bucket_pad == b.e_bucket_pad
    for name in a.rarrays._fields:
        np.testing.assert_array_equal(
            getattr(a.rarrays, name), getattr(b.rarrays, name), err_msg=name
        )


@pytest.mark.parametrize("subset", [None, [1, 2]])
def test_bucket_fill_matches_numpy_scatter(subset):
    """Same for the transposed reduce_scatter layout, incl. a parts_subset
    build (row_map skips, per-host residency)."""
    from lux_tpu.parallel.scatter import build_scatter_shards

    g = generate.rmat(10, 8, seed=67, weighted=True)
    a, b = _with_fallback(
        lambda: build_scatter_shards(g, 4, parts_subset=subset)
    )
    assert a.parts_subset == b.parts_subset
    for name in a.sarrays._fields:
        np.testing.assert_array_equal(
            getattr(a.sarrays, name), getattr(b.sarrays, name), err_msg=name
        )


@pytest.mark.skipif(native.get_lib() is None,
                    reason="native toolchain unavailable")
def test_bucket_fill_error_contract():
    """lux_bucket_fill's C error paths: bucket overflow (B too small)
    and out-of-cuts sources raise; row_map -1 skips cleanly."""
    rp = np.array([0, 2, 4], np.int64)       # 2 vertices, 2 edges each
    srcs = np.array([0, 1, 0, 1], np.uint32)  # owners: 0,1,0,1 (cuts 0|1|2)
    cuts = np.array([0, 1, 2], np.uint32)
    P, B = 2, 8
    src_l = np.zeros(P * B, np.int32)
    dst_l = np.full(P * B, 2, np.int32)
    hf = np.zeros(P * B, np.uint8)
    row_map = np.arange(P, dtype=np.int64)
    assert native.bucket_fill(srcs, rp, None, cuts, B, row_map, B,
                              src_l, dst_l, hf, None)
    # owner 0 bucket: edges 0,2 -> dst 0,1 ; heads at 0,1 ; pad head at 2
    assert list(dst_l[:2]) == [0, 1] and list(hf[:3]) == [1, 1, 1]
    # overflow: B=1 cannot hold 2 edges per bucket
    with pytest.raises(ValueError, match="bucket fill failed"):
        native.bucket_fill(srcs, rp, None, cuts, 1, row_map, 1,
                           np.zeros(2, np.int32), np.zeros(2, np.int32),
                           np.zeros(2, np.uint8), None)
    # source beyond the last cut
    with pytest.raises(ValueError, match="bucket fill failed"):
        native.bucket_fill(np.array([5], np.uint32),
                           np.array([0, 1], np.int64), None, cuts, B,
                           row_map, B, src_l, dst_l, hf, None)
    # row_map -1: owner-1 edges dropped, no slots consumed, no error
    src_l2 = np.zeros(P * B, np.int32)
    dst_l2 = np.full(P * B, 2, np.int32)
    hf2 = np.zeros(P * B, np.uint8)
    skip_map = np.array([0, -1], np.int64)
    assert native.bucket_fill(srcs, rp, None, cuts, B, skip_map, B,
                              src_l2, dst_l2, hf2, None)
    assert list(dst_l2[:2]) == [0, 1]          # owner-0 bucket filled
    assert (dst_l2[B:] == 2).all()             # owner-1 row untouched
    # int64 sources >= 2^32 must raise, not truncate into a valid
    # bucket (ADVICE r4: the uint32 cast was silent)
    with pytest.raises(ValueError, match="uint32 range"):
        native.bucket_fill(np.array([2**32], np.int64),
                           np.array([0, 1], np.int64), None, cuts, B,
                           row_map, B, src_l, dst_l, hf, None)
    # negative ids (int64 OR int32) must raise too, not wrap to a
    # plausible bucket
    for dt in (np.int64, np.int32):
        with pytest.raises(ValueError, match="uint32 range"):
            native.bucket_fill(np.array([-(2**32 - 5)], dt)
                               if dt == np.int64 else np.array([-3], dt),
                               np.array([0, 1], np.int64), None, cuts, B,
                               row_map, B, src_l, dst_l, hf, None)
    # int64 sources that DO fit pass through unchanged
    src_l3 = np.zeros(P * B, np.int32)
    dst_l3 = np.full(P * B, 2, np.int32)
    hf3 = np.zeros(P * B, np.uint8)
    assert native.bucket_fill(srcs.astype(np.int64), rp, None, cuts, B,
                              row_map, B, src_l3, dst_l3, hf3, None)
    assert list(dst_l3[:2]) == [0, 1]


def test_route_color_threaded_bitwise():
    """The threaded batched colorer is BITWISE identical to the
    single-thread walk for every thread count (per-B sub-problems are
    independent: disjoint slices, per-thread scratch) — the tentpole
    contract of the parallel plan build."""
    b, nside, deg = 7, 512, 8
    u = np.stack([np.repeat(np.arange(nside, dtype=np.int64), deg)
                  for _ in range(b)])
    v = np.stack([
        np.random.default_rng(100 + i).permutation(
            np.repeat(np.arange(nside, dtype=np.int64), deg))
        for i in range(b)
    ])
    base = native.route_color(u, v, deg, nside, n_threads=1)
    assert base is not None
    for nt in (2, 3, 8, 64):
        got = native.route_color(u, v, deg, nside, n_threads=nt)
        np.testing.assert_array_equal(base, got)
    # validity spot-check: each color class is a perfect matching
    for col in range(deg):
        sel = base[0] == col
        assert np.array_equal(np.sort(u[0][sel]), np.arange(nside))
        assert np.array_equal(np.sort(v[0][sel]), np.arange(nside))


def test_route_color_threaded_error_contract():
    """Out-of-range ids fail with the same error through the threaded
    path (any worker's error wins; never a crash or a silent result)."""
    nside, deg = 64, 2
    u = np.stack([np.repeat(np.arange(nside, dtype=np.int64), deg)] * 4)
    v = u.copy()
    v[2, 5] = nside  # out of range in one batch only
    with pytest.raises(ValueError, match="route color failed"):
        native.route_color(u, v, deg, nside, n_threads=4)


def test_route_threads_env(monkeypatch):
    monkeypatch.setenv("LUX_ROUTE_THREADS", "3")
    assert native.route_threads() == 3
    # garbage / non-positive values now REJECT with an error naming the
    # knob (utils.config.env_int) instead of silently running the old
    # fallback — a typo'd thread count must never quietly serialize a
    # chip window's plan build
    for bad in ("bogus", "0", "-2", "1.5"):
        monkeypatch.setenv("LUX_ROUTE_THREADS", bad)
        with pytest.raises(ValueError, match="LUX_ROUTE_THREADS"):
            native.route_threads()
    monkeypatch.setenv("LUX_ROUTE_THREADS", "")  # empty = unset
    assert native.route_threads() == (os.cpu_count() or 1)
    monkeypatch.delenv("LUX_ROUTE_THREADS")
    assert native.route_threads() == (os.cpu_count() or 1)


def test_plan_threads_env(monkeypatch):
    from lux_tpu.ops import expand

    monkeypatch.setenv("LUX_PLAN_THREADS", "2")
    assert expand._plan_threads() == 2
    for bad in ("garbage", "0", "-1"):
        monkeypatch.setenv("LUX_PLAN_THREADS", bad)
        with pytest.raises(ValueError, match="LUX_PLAN_THREADS"):
            expand._plan_threads()
    monkeypatch.delenv("LUX_PLAN_THREADS")
    assert expand._plan_threads() == (os.cpu_count() or 1)


def test_get_lib_threaded_single_init():
    """get_lib under concurrent first-call pressure returns ONE library
    object (the planner fan-out calls it from worker threads; the old
    unlocked check-then-act could double-build — luxcheck LUX-C001)."""
    import threading

    save_lib, save_tried = native._lib, native._tried
    native._lib, native._tried = None, False
    results = []
    try:
        barrier = threading.Barrier(8)

        def grab():
            barrier.wait()
            results.append(native.get_lib())

        threads = [threading.Thread(target=grab) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        native._lib, native._tried = save_lib, save_tried
    assert len(results) == 8
    assert all(r is results[0] for r in results)


# ---------------------------------------------------------------------------
# sanitizer drivers (docs/ANALYSIS.md "Sanitizer build matrix")
# ---------------------------------------------------------------------------

NATIVE_DIR = os.path.join(REPO, "lux_tpu", "native")


def _sanitizer_run(target: str, binary: str):
    """Build (make <target>) and run one sanitizer check driver; returns
    its stdout+stderr.  Skips when the toolchain lacks the sanitizer
    runtime (the build itself fails then)."""
    build = subprocess.run(
        ["make", "-C", NATIVE_DIR, target],
        capture_output=True, text=True, timeout=300,
    )
    if build.returncode != 0:
        pytest.skip(f"sanitizer build unavailable: "
                    f"{build.stderr.strip()[-200:]}")
    proc = subprocess.run(
        [os.path.join(NATIVE_DIR, "build", binary), "all"],
        capture_output=True, text=True, timeout=600,
    )
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, f"{binary} rc={proc.returncode}:\n{out[-3000:]}"
    return out


@pytest.mark.slow
def test_tsan_threaded_colorer_zero_reports():
    """The PR-2 multithreaded colorer under ThreadSanitizer: bitwise
    output vs serial (asserted inside the driver) and ZERO data-race
    reports (any report fails the exit code; the grep is belt and
    braces).  The level-synchronous frame parallelism claims 'disjoint
    slices, per-thread scratch' — this is the instrumented proof."""
    out = _sanitizer_run("tsan", "lux-tsan-check")
    assert "WARNING: ThreadSanitizer" not in out, out[-3000:]
    assert "bitwise == serial" in out
    assert "all clean" in out


@pytest.mark.slow
def test_asan_ubsan_io_zero_reports():
    """lux_io (+ the colorer) under AddressSanitizer and UBSan: the
    write/read/bucket paths do raw pread64 offset arithmetic — an
    off-by-one reads past a heap buffer exactly here."""
    out = _sanitizer_run("asan", "lux-asan-check")
    assert "ERROR: AddressSanitizer" not in out, out[-3000:]
    assert "all clean" in out
    out = _sanitizer_run("ubsan", "lux-ubsan-check")
    assert "runtime error" not in out, out[-3000:]
    assert "all clean" in out
