"""The bench orchestrator's two survival paths, driven as real processes:
a healthy primary worker, and a primary stuck in (simulated) device-claim
hang — the insurance worker must supply the number and the stuck worker
must be LEFT RUNNING (killing a claim-holder wedges the tunnel relay)."""
import json
import os
import signal
import subprocess
import sys

import pytest

BENCH = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "bench.py")


def _run(env_extra, timeout):
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    env["PYTHONPATH"] = os.path.dirname(BENCH)
    env["JAX_PLATFORMS"] = "cpu"
    env["LUX_BENCH_SCALE"] = "10"
    env["LUX_BENCH_CPU_SCALE"] = "10"
    env.update(env_extra)
    return subprocess.run(
        [sys.executable, BENCH], env=env, capture_output=True, text=True,
        timeout=timeout, cwd="/tmp",
    )


def test_bench_happy_path_multi_app():
    r = _run({}, timeout=540)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [
        json.loads(s) for s in r.stdout.strip().splitlines()
        if s.startswith("{")
    ]
    # >=3 metric lines: one per app family (app + unit stem, so the
    # sssp_gteps engine row and the sssp_qps serving row are distinct
    # families), headline (pagerank) LAST
    fams = [ln["metric"].split("_rmat")[0] for ln in lines]
    assert set(fams) >= {"pagerank_gteps", "sssp_gteps",
                         "colfilter_gteps", "sssp_qps"}, fams
    assert fams[-1] == "pagerank_gteps"
    assert len(fams) == len(set(fams))  # exactly one line per family
    for ln in lines:
        assert ln["unit"] == (
            "QPS" if ("_qps_" in ln["metric"]
                      or "_live_" in ln["metric"])
            else "ms/iter" if ln["metric"].startswith(("reduce_micro",
                                                       "scan_micro"))
            else "ms/run" if ln["metric"].startswith("merge_micro")
            else "x" if "_refresh_" in ln["metric"]
            else "GTEPS")
        assert ln["value"] > 0
    # the standing mxu-vs-vpu reduce micro row (ISSUE 7): both flavors
    # timed, a winner named, present in the DEFAULT output
    micro = next(ln for ln in lines
                 if ln["metric"].startswith("reduce_micro"))
    assert set(micro["flavor_ms"]) == {"group", "mxreduce"}
    assert micro["winner"] in micro["flavor_ms"]
    # the standing scan-family micro row (ISSUE 11): all three flavors
    # timed (each oracle-gated), a winner named, in the DEFAULT output
    smicro = next(ln for ln in lines
                  if ln["metric"].startswith("scan_micro"))
    assert set(smicro["flavor_ms"]) == {"scan", "mxsum", "mxscan"}
    assert smicro["winner"] in smicro["flavor_ms"]
    # the standing tree-vs-bulk merge micro row (ISSUE 17): both merge
    # modes timed behind the double bitwise oracle gate, a winner named
    mmicro = next(ln for ln in lines
                  if ln["metric"].startswith("merge_micro"))
    assert set(mmicro["mode_ms"]) == {"bulk", "tree"}
    assert mmicro["winner"] in mmicro["mode_ms"]
    assert mmicro["bitwise_equal"] is True and mmicro["parts"] > 1
    qps = next(ln for ln in lines if "_qps_" in ln["metric"])
    assert qps["batched_vs_q1"] > 0 and qps["scheduler"]["completed"] > 0
    # the standing mutation-aware serving row (ISSUE 12): mixed
    # read/write window with staleness + fleet-refresh accounting
    lv = next(ln for ln in lines
              if ln["metric"].startswith("sssp_live_w2"))
    assert lv["write_batches_per_s"] > 0 and lv["fleet_refresh_s"] > 0
    assert lv["staleness_gen_p99"] >= lv["staleness_gen_p50"] >= 0
    assert lv["final_generation"] > 0 and lv["read_errors"] == 0
    assert set(lv["worker_generations"].values()) == {
        lv["final_generation"]}
    cf = next(ln for ln in lines if ln["metric"].startswith("colfilter"))
    assert cf["rmse"] > 0 and cf["iter_ms"] > 0
    sp = next(ln for ln in lines if ln["metric"].startswith("sssp_gteps"))
    assert sp["traversed_edges"] > 0 and sp["iters"] > 0
    # the standing dynamic-graph rows (ISSUE 10): refresh-vs-cold
    # speedup with the occupancy/invalidation/bitwise accounting
    for app in ("pagerank", "sssp"):
        rf = next(ln for ln in lines
                  if ln["metric"].startswith(f"{app}_refresh_churn1pct"))
        assert rf["refresh_s"] > 0 and rf["cold_s"] > 0
        assert set(rf["cold_breakdown"]) == {"load", "build", "plan",
                                             "compute"}
        assert 0 < rf["delta_occupancy"]["max"] <= rf["delta_occupancy"]["cap"]
        assert 0 < rf["invalidated_bucket_fraction"] <= 1.0
        assert isinstance(rf["bitwise_equal"], bool)
        assert rf["churn_frac"] > 0
    assert next(ln for ln in lines
                if ln["metric"].startswith("sssp_refresh"))["bitwise_equal"]


def test_bench_insurance_survives_hung_primary():
    r = _run(
        {
            "LUX_BENCH_FAKE_HANG": "1",
            "LUX_BENCH_APPS": "pagerank",
            # primary targets a non-cpu platform so the insurance spawns
            "JAX_PLATFORMS": "bogus_tpu",
            "LUX_BENCH_WATCHDOG_S": "240",
            # the window only has to outlive worker spawn + the first
            # liveness probes — the fake hang never produces; a short
            # window keeps this wall-clock test inside the tier-1 budget
            "LUX_BENCH_TPU_S": "7",
        },
        timeout=300,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    line = json.loads(r.stdout.strip().splitlines()[-1])
    assert line["value"] > 0
    assert "_cpu_fallback" in line["metric"]
    assert "left running, not killed" in r.stderr
    # the hung primary must still be alive (never killed); clean up EXACTLY
    # that pid (it holds no tunnel claim in this simulation) — never a
    # pattern kill, which could hit a real claim-waiting worker
    pid = int(r.stderr.split("TPU worker (pid ")[1].split(")")[0])
    os.kill(pid, 0)  # raises if the orchestrator wrongly killed it
    os.kill(pid, signal.SIGKILL)


def test_bench_harvests_banked_lines_from_wedged_primary():
    """A primary that measured something and THEN wedged (the observed
    scan-method server hang) must have its banked chip number win over
    the CPU insurance, and must still be left running."""
    r = _run(
        {
            "LUX_BENCH_FAKE_HANG": "emit",
            "LUX_BENCH_APPS": "pagerank",
            "JAX_PLATFORMS": "bogus_tpu",
            "LUX_BENCH_WATCHDOG_S": "240",
            # long enough for the primary to import jax and EMIT its
            # banked line before wedging; short enough for tier-1
            "LUX_BENCH_TPU_S": "8",
        },
        timeout=300,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    line = json.loads(r.stdout.strip().splitlines()[-1])
    assert line["metric"] == "pagerank_gteps_fake_banked"
    assert line["value"] == 123.0
    assert "left running, not killed" in r.stderr
    pid = int(r.stderr.split("TPU worker (pid ")[1].split(")")[0])
    os.kill(pid, 0)
    os.kill(pid, signal.SIGKILL)


def test_bench_relay_gate_caps_tpu_wait():
    """A dead relay endpoint (the 55-min jax retry trap) caps the TPU
    wait so the insurance result still lands within budget."""
    r = _run(
        {
            "LUX_BENCH_FAKE_HANG": "1",
            "LUX_BENCH_APPS": "pagerank",
            "JAX_PLATFORMS": "bogus_tpu",
            "LUX_BENCH_WATCHDOG_S": "240",
            "LUX_BENCH_TPU_S": "9999",  # would exceed budget un-capped...
            "LUX_BENCH_ASSUME_RELAY": "down",  # ...but the gate caps it
            "LUX_BENCH_RELAY_CAP_S": "5",
        },
        timeout=300,
    )
    assert "assumed down (test hook)" in r.stderr
    assert "TPU wait capped at 5s" in r.stderr
    # and the insurance number actually lands
    assert r.returncode == 0, r.stderr[-2000:]
    line = json.loads(r.stdout.strip().splitlines()[-1])
    assert line["value"] > 0 and "_cpu_fallback" in line["metric"]
    pid = int(r.stderr.split("TPU worker (pid ")[1].split(")")[0])
    os.kill(pid, 0)
    os.kill(pid, signal.SIGKILL)


def test_bench_worker_scaleup_line():
    """The TPU-path scale-up datapoint (VERDICT r3 weak #4): after the
    headline race banks results, a pagerank line at scale+2 on the
    winning method is emitted with roofline fields (forced on CPU via
    the test hook; gated off when the TPU budget is half-spent)."""
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    env["PYTHONPATH"] = os.path.dirname(BENCH)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "LUX_BENCH_SCALE": "9",
        "LUX_BENCH_APPS": "pagerank",
        "LUX_BENCH_FORCE_SCALEUP": "1",
        "LUX_BENCH_TPU_S": "600",
    })
    r = subprocess.run(
        [sys.executable, "-c",
         "import bench; bench.worker_main()"],
        env=env, capture_output=True, text=True, timeout=420, cwd="/tmp",
    )
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [json.loads(s) for s in r.stdout.strip().splitlines()
             if s.startswith("{")]
    up = [ln for ln in lines
          if ln["metric"] == "pagerank_gteps_rmat11_1chip_cpu_fallback"]
    assert up, [ln["metric"] for ln in lines]
    assert up[0]["achieved_GBps"] > 0 and up[0]["bytes_per_edge"] > 0


@pytest.mark.slow
def test_bench_worker_scaleup_budget_gate():
    """The budget-half-spent gate: no scale-up line when the TPU window
    is exhausted.  Slow tier — a full second worker run whose only
    assertion is the gate message (the positive scale-up line above
    stays tier-1)."""
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    env["PYTHONPATH"] = os.path.dirname(BENCH)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "LUX_BENCH_SCALE": "9",
        "LUX_BENCH_APPS": "pagerank",
        "LUX_BENCH_FORCE_SCALEUP": "1",
        "LUX_BENCH_TPU_S": "0",
    })
    r2 = subprocess.run(
        [sys.executable, "-c", "import bench; bench.worker_main()"],
        env=env, capture_output=True, text=True, timeout=420, cwd="/tmp",
    )
    assert "scale-up skipped" in r2.stderr
    assert "rmat11_1chip" not in r2.stdout  # (any suffix)


def test_relay_passes_scaleup_without_hijacking_headline(tmp_path, capsys):
    """The scale-up line is passed through verbatim and the headline stays
    the best primary-scale pagerank line even when the scale-up GTEPS is
    higher (less dispatch-dominated by design)."""
    sys.path.insert(0, os.path.dirname(BENCH))
    import bench

    out = tmp_path / "w.json"
    lines = [
        {"metric": "pagerank_gteps_rmat20_1chip", "value": 1.0,
         "unit": "GTEPS", "vs_baseline": 1.0, "method": "scatter"},
        {"metric": "pagerank_gteps_rmat22_1chip", "value": 9.9,
         "unit": "GTEPS", "vs_baseline": 9.9, "method": "scatter",
         "scale_up": True},
        {"metric": "sssp_gteps_rmat20_1chip", "value": 0.5,
         "unit": "GTEPS", "vs_baseline": 0.5, "method": "scan"},
    ]
    out.write_text("\n".join(json.dumps(o) for o in lines) + "\n")
    assert bench._relay(str(out))
    got = [json.loads(s) for s in capsys.readouterr().out.strip().splitlines()]
    assert got[-1]["metric"] == "pagerank_gteps_rmat20_1chip"  # headline kept
    assert any(o["metric"] == "pagerank_gteps_rmat22_1chip" for o in got)
    assert any(o["metric"] == "sssp_gteps_rmat20_1chip" for o in got)


def test_record_winner_skips_sortseg_ab(tmp_path, monkeypatch):
    """A sort-segments A/B run must never mutate the default-layout
    tpu:sum winner (ADVICE r4): the overlay would silently change every
    later allgather run's method."""
    sys.path.insert(0, os.path.dirname(BENCH))
    import bench

    f = tmp_path / "w.json"
    monkeypatch.setenv("LUX_METHOD_WINNERS", str(f))
    results = {("scan", "float32"): 1.0, ("scatter", "float32"): 2.0}
    monkeypatch.setenv("LUX_BENCH_SORT_SEGMENTS", "1")
    bench._record_winner(results)
    assert not f.exists()
    monkeypatch.delenv("LUX_BENCH_SORT_SEGMENTS")
    bench._record_winner(results)
    assert json.loads(f.read_text())["tpu:sum"] == "scan"


def test_record_winner_family_requires_micro_gate(tmp_path, monkeypatch):
    """The full-scale race times, it never checks numerics — so a
    scan-family winner (mxsum/mxscan) may be banked as tpu:sum ONLY
    when this machine's oracle-gated micro row already verified it
    (ISSUE 11 review fix: a banked winner is always a verified one)."""
    import json as _json

    sys.path.insert(0, os.path.dirname(BENCH))
    import bench

    from lux_tpu.engine import methods

    f = tmp_path / "w.json"
    monkeypatch.setenv("LUX_METHOD_WINNERS", str(f))
    monkeypatch.setattr(methods, "_overlay_raw_cache", None)
    monkeypatch.setattr(methods, "_file_winners_cache", None)
    monkeypatch.setattr(methods, "_tiles_cache", None)
    results = {("mxscan", "float32"): 0.5, ("scan", "float32"): 1.0,
               ("scatter", "float32"): 2.0}
    bench._record_winner(results)
    # no oracle-gated micro row on this machine: the unverified family
    # winner is NOT trusted; the fastest blanket-safe method is banked
    assert _json.loads(f.read_text())["tpu:sum"] == "scan"
    methods.record_overlay_entry(
        "tpu:micro_scan",
        {"ms_per_iter": {"scan": 1.0, "mxsum": 1.0, "mxscan": 0.5}})
    bench._record_winner(results)
    assert _json.loads(f.read_text())["tpu:sum"] == "mxscan"
    monkeypatch.setattr(methods, "_overlay_raw_cache", None)
    monkeypatch.setattr(methods, "_file_winners_cache", None)
    monkeypatch.setattr(methods, "_tiles_cache", None)


class _StuckProc:
    """poll() forever-None stand-in for a claim-stuck TPU worker."""
    returncode = None

    def poll(self):
        return None


def test_wait_tpu_adaptive_extends_while_relay_alive(monkeypatch, capsys):
    """The adaptive wait (VERDICT r5: the one-shot 240s cap lost a live
    chip day): a relay that comes alive mid-wait extends the deadline to
    the full window; while it stays alive the down_grace cap never
    fires."""
    import time

    sys.path.insert(0, os.path.dirname(BENCH))
    import bench

    probes = iter([True] * 50)  # relay alive on every re-probe
    monkeypatch.setattr(bench, "_relay_listening", lambda: next(probes))
    t0 = time.monotonic()
    # starts DOWN (relay_up0=False) with a tiny grace; probes say alive
    # -> the wait must run out the FULL window, not the grace
    done = bench._wait_tpu(_StuckProc(), t0, wait_full=1.2, down_grace=0.2,
                           relay_up0=False, assume=None, probe_s=0.1)
    elapsed = time.monotonic() - t0
    assert not done
    assert elapsed >= 1.0, elapsed  # not cut at the 0.2s grace
    assert "came alive" in capsys.readouterr().err


def test_wait_tpu_caps_after_relay_dies(monkeypatch, capsys):
    """A relay that stops listening mid-wait caps the remaining wait at
    down_grace past last-alive instead of burning the full window."""
    import time

    sys.path.insert(0, os.path.dirname(BENCH))
    import bench

    monkeypatch.setattr(bench, "_relay_listening", lambda: False)
    t0 = time.monotonic()
    done = bench._wait_tpu(_StuckProc(), t0, wait_full=30.0, down_grace=0.5,
                           relay_up0=True, assume=None, probe_s=0.1)
    elapsed = time.monotonic() - t0
    assert not done
    assert elapsed < 5.0, elapsed  # nowhere near the 30s full window
    assert "stopped listening" in capsys.readouterr().err


def test_wait_tpu_assume_hook_pins_probes(monkeypatch):
    """LUX_BENCH_ASSUME_RELAY pins the re-probes too (test hook parity
    with the spawn-time gate)."""
    import time

    sys.path.insert(0, os.path.dirname(BENCH))
    import bench

    def boom():
        raise AssertionError("probe must not hit the network under assume")

    monkeypatch.setattr(bench, "_relay_listening", boom)
    t0 = time.monotonic()
    done = bench._wait_tpu(_StuckProc(), t0, wait_full=30.0, down_grace=0.3,
                           relay_up0=False, assume="down", probe_s=0.1)
    assert not done and time.monotonic() - t0 < 5.0


def test_bench_worker_routepf_ab_row():
    """LUX_BENCH_ROUTE_PF=1 emits the pass-fused A/B row: _routepf
    metric suffix + the hbm_passes accounting field showing the fused
    sweep count (r1/r2 collapsed to group counts)."""
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    env["PYTHONPATH"] = os.path.dirname(BENCH)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "LUX_BENCH_SCALE": "9",
        "LUX_BENCH_ITERS": "4",
        "LUX_BENCH_APPS": "pagerank",
        "LUX_BENCH_ROUTE_PF": "1",
    })
    r = subprocess.run(
        [sys.executable, "-c", "import bench; bench.worker_main()"],
        env=env, capture_output=True, text=True, timeout=420, cwd="/tmp",
    )
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [json.loads(s) for s in r.stdout.strip().splitlines()
             if s.startswith("{")]
    assert lines and all("_routepf" in ln["metric"] for ln in lines)
    hp = lines[0]["hbm_passes"]
    # pf plans at this scale: r1/r2 in <= 3 kernels each (vs 5+ passes)
    assert hp["r1"] <= 3 and hp["r2"] <= 3
    assert hp["total"] == round(sum(v for k, v in hp.items()
                                    if k != "total"), 2)


def test_bench_worker_ba_row():
    """The standing heavy-tail row: Barabási-Albert through
    generator -> .lux -> routed-pf pull, its own metric family (no
    _rmat in the name), with routed roofline + hbm_passes fields."""
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    env["PYTHONPATH"] = os.path.dirname(BENCH)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "LUX_BENCH_SCALE": "9",
        "LUX_BENCH_ITERS": "4",
        "LUX_BENCH_APPS": "ba",
        "LUX_BENCH_BA_SCALE": "9",
    })
    r = subprocess.run(
        [sys.executable, "-c", "import bench; bench.worker_main()"],
        env=env, capture_output=True, text=True, timeout=420, cwd="/tmp",
    )
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [json.loads(s) for s in r.stdout.strip().splitlines()
             if s.startswith("{")]
    assert len(lines) == 1, lines
    ln = lines[0]
    assert ln["metric"].startswith("pagerank_gteps_ba9_m")
    assert "_routepf" in ln["metric"] and "_rmat" not in ln["metric"]
    assert ln["value"] > 0 and ln["ne"] > 0
    assert ln["hbm_passes"]["total"] > 0
    assert ln["plan_build_seconds"]["cold"] >= 0.0


def test_every_row_carries_plan_build_seconds():
    """CI contract for plan-build amortization reporting: every bench
    row (worker-measured AND the orchestrator's zero row) carries the
    cold/warm plan_build_seconds field."""
    sys.path.insert(0, os.path.dirname(BENCH))
    import bench

    z = bench._zero("pagerank_gteps_rmat20_all_workers_failed")
    assert z["plan_build_seconds"] == {"cold": 0.0, "warm": 0.0}
    f = bench._plan_build_field()
    assert set(f) == {"cold", "warm"}
    assert f["cold"] >= 0.0 and f["warm"] >= 0.0
