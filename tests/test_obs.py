"""luxtrace (lux_tpu.obs) tests: recorder span semantics + thread
safety, on-device telemetry rings (bitwise no-op vs telemetry-off,
donation, retrace/HBM neutrality), the LUX-O checker family, the
luxview/obs_span CLIs on seeded event logs, the Prometheus dump, and
XProf trace parsing."""
import gzip
import importlib.util
import json
import os
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lux_tpu import obs
from lux_tpu.obs import ring as obs_ring
from lux_tpu.obs import xprof
from lux_tpu.obs.recorder import Recorder

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def read_events(run_dir):
    evs = []
    for fn in sorted(os.listdir(run_dir)):
        if fn.startswith("events-") and fn.endswith(".jsonl"):
            with open(os.path.join(run_dir, fn), encoding="utf-8") as f:
                evs.extend(json.loads(ln) for ln in f if ln.strip())
    return evs


@pytest.fixture
def rec(tmp_path):
    r = Recorder(run_id="trun", root=str(tmp_path), enabled=True)
    old = obs.install(r)
    yield r
    r.close()
    obs.install(old)


# ---------------------------------------------------------------------------
# recorder
# ---------------------------------------------------------------------------


def test_span_nesting_and_file(rec, tmp_path):
    with obs.span("outer", a=1) as sp_out:
        with obs.span("inner") as sp_in:
            pass
        sp_out.set(banked=True)
    assert sp_out.dur >= sp_in.dur >= 0.0
    evs = read_events(rec.run_dir())
    assert evs[0]["e"] == "m" and evs[0]["run"] == "trun"
    begins = {e["n"]: e for e in evs if e["e"] == "b"}
    ends = {e["s"]: e for e in evs if e["e"] == "e"}
    # nested span's parent is the outer's sid; attrs land begin/end
    assert begins["inner"]["p"] == begins["outer"]["s"]
    assert begins["outer"]["p"] is None
    assert begins["outer"]["a"] == {"a": 1}
    assert ends[begins["outer"]["s"]]["a"] == {"banked": True}
    assert all(ends[s]["ok"] for s in ends)
    # crash-safety: begin events precede their end events in file order
    order = [e["e"] for e in evs]
    assert order == ["m", "b", "b", "e", "e"]


def test_span_exception_marks_not_ok(rec):
    with pytest.raises(ValueError):
        with obs.span("boom"):
            raise ValueError("x")
    evs = read_events(rec.run_dir())
    (end,) = [e for e in evs if e["e"] == "e"]
    assert end["ok"] is False
    # failed spans stay in the event log but NOT in the aggregate: the
    # totals are the one clock behind plan_build_seconds/phases, and a
    # failed plan.load (rebuilt under plan.build) must not drift them
    assert rec.total_count("boom") == 0


def test_sid_prefix_unique_per_recorder(tmp_path):
    """pid reuse across a battery must not collide sids in the merged
    timeline — two same-pid recorders get distinct per-process tokens."""
    a = Recorder(run_id="r", root=str(tmp_path), enabled=False)
    b = Recorder(run_id="r", root=str(tmp_path), enabled=False)
    with a.span("x") as sa, b.span("x") as sb:
        pass
    assert sa.sid != sb.sid
    assert sa.sid.startswith(f"{os.getpid()}-")


def test_point_and_totals(rec):
    obs.point("marker", k=3)
    with obs.span("plan.build"):
        pass
    with obs.span("plan.build"):
        pass
    assert rec.total_count("plan.build") == 2
    assert rec.total_seconds("plan.build") >= 0.0
    assert set(rec.totals("plan.")) == {"plan.build"}
    rec.reset_totals("plan.")
    assert rec.total_count("plan.build") == 0
    assert any(e["e"] == "p" and e["n"] == "marker"
               for e in read_events(rec.run_dir()))


def test_recorder_thread_safety(rec):
    n_threads, n_spans = 8, 50

    def work(i):
        for k in range(n_spans):
            with obs.span(f"t{i}", k=k):
                with obs.span(f"t{i}.inner"):
                    pass

    threads = [threading.Thread(target=work, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    evs = read_events(rec.run_dir())
    begins = [e for e in evs if e["e"] == "b"]
    ends = [e for e in evs if e["e"] == "e"]
    assert len(begins) == len(ends) == 2 * n_threads * n_spans
    # sids unique; every inner's parent is a same-thread outer (the span
    # stack is thread-local, so cross-thread nesting cannot happen)
    sids = [e["s"] for e in begins]
    assert len(set(sids)) == len(sids)
    name_of = {e["s"]: e["n"] for e in begins}
    for e in begins:
        if e["n"].endswith(".inner"):
            assert name_of[e["p"]] == e["n"][:-len(".inner")]
    for i in range(n_threads):
        assert rec.total_count(f"t{i}") == n_spans


def test_disabled_recorder_still_aggregates(tmp_path):
    r = Recorder(run_id="off", root=str(tmp_path / "x"), enabled=False)
    with r.span("s"):
        pass
    assert r.total_count("s") == 1
    assert r.log_path is None
    assert not (tmp_path / "x").exists()


def test_untrusted_dir_degrades_to_memory(tmp_path):
    target = tmp_path / "occupied"
    target.write_text("not a dir")
    r = Recorder(run_id="deg", root=str(target), enabled=True)
    with r.span("s"):
        pass  # must not raise
    assert r.log_path is None
    assert r.total_count("s") == 1


def test_run_id_env_inheritance(tmp_path, monkeypatch):
    monkeypatch.setenv("LUX_OBS_RUN_ID", "from_env_123")
    r = Recorder(root=str(tmp_path))
    assert r.run_id == "from_env_123"


def test_retention_sweeps_only_old_runs(tmp_path, monkeypatch):
    """The always-on recorder must bound its own disk footprint: keep
    the newest LUX_OBS_KEEP run dirs, never a recently-written one, and
    never the current run."""
    # the package re-exports the recorder() accessor under the module's
    # name, so resolve the MODULE explicitly (obs_span.py idiom)
    rmod = importlib.import_module("lux_tpu.obs.recorder")

    root = tmp_path / "obs"
    root.mkdir(mode=0o700)
    old = time.time() - 2 * rmod.SWEEP_MIN_AGE_S
    for i in range(4):
        d = root / f"run{i}"
        d.mkdir(mode=0o700)
        (d / "events-1.jsonl").write_text("{}\n")
        # run3 is the newest stale dir; run0 the oldest
        os.utime(d / "events-1.jsonl", (old + i, old + i))
        os.utime(d, (old + i, old + i))
    fresh = root / "live"
    fresh.mkdir(mode=0o700)
    (fresh / "events-9.jsonl").write_text("{}\n")  # now-mtime: in-age guard

    monkeypatch.setenv("LUX_OBS_KEEP", "3")
    r = Recorder(run_id="cur", root=str(root), enabled=True)
    with r.span("s"):
        pass
    r.close()
    survivors = sorted(p.name for p in root.iterdir())
    # keep=3 = current + 2 newest others; "live" survives on age alone,
    # so the stale dirs shrink to the single newest one
    assert "cur" in survivors and "live" in survivors
    assert "run3" in survivors
    assert not any(n in survivors for n in ("run0", "run1", "run2"))

    # keep<=0 disables the sweep entirely
    monkeypatch.setenv("LUX_OBS_KEEP", "0")
    r2 = Recorder(run_id="cur2", root=str(root), enabled=True)
    with r2.span("s"):
        pass
    r2.close()
    assert "run3" in {p.name for p in root.iterdir()}


# ---------------------------------------------------------------------------
# on-device telemetry rings
# ---------------------------------------------------------------------------


def _pull_setup(scale=8, parts=2, routed=False):
    from lux_tpu.engine import pull
    from lux_tpu.graph import generate
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.models.pagerank import PageRankProgram
    from lux_tpu.ops import expand as E

    g = generate.rmat(scale, 8, seed=17)
    shards = build_pull_shards(g, parts)
    dev = jax.tree.map(jnp.asarray, shards.arrays)
    prog = PageRankProgram(nv=shards.spec.nv)
    s0 = pull.init_state(prog, dev)
    route = E.plan_expand_shards(shards, pf=True) if routed else None
    return pull, prog, shards, dev, s0, route


@pytest.mark.parametrize("routed", [False, True])
def test_ring_pull_fixed_bitwise_noop(routed):
    """Telemetry-on == telemetry-off BITWISE on the result state, for
    the direct and the routed-pf pull (the ring is pure extra output)."""
    pull, prog, shards, dev, s0, route = _pull_setup(routed=routed)
    ref = pull.run_pull_fixed(prog, shards.spec, dev, s0, 6,
                              method="scan", route=route)
    out, rg = pull.run_pull_fixed(
        prog, shards.spec, dev, s0, 6, method="scan", route=route,
        telemetry=obs_ring.new_ring("pull_fixed"))
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))
    rows, n = obs_ring.ring_rows(rg)
    assert n == 6 and rows.shape == (6, 2)
    # recorded column 0 is the iteration index, in order
    np.testing.assert_array_equal(rows[:, 0], np.arange(6))
    # PageRank's residual curve decreases over the tail
    assert rows[-1, 1] < rows[0, 1]


def test_ring_pull_until_bitwise_noop():
    from lux_tpu.models import components as cc_model
    from lux_tpu.models.components import MaxLabelProgram

    pull, _, shards, dev, _, _ = _pull_setup()
    prog = MaxLabelProgram()
    s0 = pull.init_state(prog, dev)
    ref, it_ref = pull.run_pull_until(prog, shards.spec, dev, s0, 50,
                                      cc_model.active_count, method="scan")
    out, it, rg = pull.run_pull_until(
        prog, shards.spec, dev, s0, 50, cc_model.active_count,
        method="scan", telemetry=obs_ring.new_ring("pull_until"))
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))
    assert int(it) == int(it_ref)
    rows, n = obs_ring.ring_rows(rg)
    assert n == int(it)
    # the loop stops when the active count hits 0 — the ring's last row
    # is that 0 (the recorded convergence event)
    assert rows[-1, 1] == 0
    assert (rows[:-1, 1] > 0).all()


def test_ring_push_bitwise_noop():
    from lux_tpu.engine import push
    from lux_tpu.graph import generate
    from lux_tpu.graph.push_shards import build_push_shards
    from lux_tpu.models import sssp

    g = generate.rmat(8, 8, seed=31)
    sh = build_push_shards(g, 2)
    prog = sssp.SSSPProgram(nv=g.nv, start=0)
    ref_state, ref_it, ref_edges = push.run_push(prog, sh)
    state, it, edges, rg = push.run_push(
        prog, sh, telemetry=obs_ring.new_ring("push"))
    np.testing.assert_array_equal(np.asarray(ref_state), np.asarray(state))
    assert int(it) == int(ref_it)
    assert push.edges_total(edges) == push.edges_total(ref_edges)
    rows, n = obs_ring.ring_rows(rg)
    assert n == int(it) and rows.shape[1] == 4
    # per-round traversed-edge deltas sum to the engine's exact counter
    assert int(rows[:, 2].sum()) == push.edges_total(edges)
    # round 0's frontier is the start vertex alone
    assert rows[0, 1] == 1


def test_ring_wraparound_keeps_tail():
    pull, prog, shards, dev, s0, _ = _pull_setup()
    out, rg = pull.run_pull_fixed(
        prog, shards.spec, dev, s0, 10, method="scan",
        telemetry=obs_ring.new_ring("pull_fixed", cap=4))
    rows, n = obs_ring.ring_rows(rg)
    assert n == 10 and rows.shape == (4, 2)
    # the LAST cap rows, in push order
    np.testing.assert_array_equal(rows[:, 0], np.arange(6, 10))


def test_ring_telemetry_retrace_and_hbm_neutral():
    """The ring adds no accounted HBM pass (plan-derived accounting is
    untouched) and no kernel launches: the telemetry jaxpr contains
    exactly the same pallas_call count as the bare loop, and the routed
    sweep accounting is identical before/after a telemetry run."""
    from lux_tpu.utils import roofline

    pull, prog, shards, dev, s0, route = _pull_setup(routed=True)
    passes_before = roofline.routed_hbm_passes(route[0], "scan")

    def count_pallas(fn, *args, **kw):
        jaxpr = jax.make_jaxpr(fn, static_argnums=())(*args, **kw)
        n = 0
        stack = [jaxpr.jaxpr]
        while stack:
            j = stack.pop()
            for eqn in j.eqns:
                if eqn.primitive.name == "pallas_call":
                    n += 1
                for v in eqn.params.values():
                    if hasattr(v, "jaxpr"):
                        stack.append(v.jaxpr)
                    elif isinstance(v, (list, tuple)):
                        stack.extend(x.jaxpr for x in v
                                     if hasattr(x, "jaxpr"))
            for sub in getattr(j, "jaxprs", ()):
                stack.append(sub)
        return n

    rs, ra = route
    ra_dev = jax.tree.map(jnp.asarray, ra)

    def bare(state):
        return pull._pull_fixed_fn(prog, shards.spec, 3, "scan", dev,
                                   state, None, route_static=rs,
                                   route_arrays=ra_dev, interpret=True)

    def with_ring(state, rg):
        return pull._pull_fixed_fn(prog, shards.spec, 3, "scan", dev,
                                   state, rg, route_static=rs,
                                   route_arrays=ra_dev, interpret=True)

    n_bare = count_pallas(bare, s0)
    n_tel = count_pallas(with_ring, s0, obs_ring.new_ring("pull_fixed"))
    assert n_tel == n_bare > 0
    # and the accounted sweeps did not move
    out, rg = pull.run_pull_fixed(
        prog, shards.spec, dev, s0, 3, method="scan", route=route,
        telemetry=obs_ring.new_ring("pull_fixed"))
    assert roofline.routed_hbm_passes(route[0], "scan") == passes_before


def test_ring_donation_consumes_buffers():
    """donate=True with a telemetry ring: the state AND the ring input
    buffers are consumed (single copy in HBM), results bitwise equal."""
    pull, prog, shards, dev, s0, _ = _pull_setup()
    ref = pull.run_pull_fixed(prog, shards.spec, dev, s0, 4, method="scan")
    s0_d = jnp.array(s0)  # a private copy to donate
    ring_in = jax.tree.map(jnp.asarray, obs_ring.new_ring("pull_fixed"))
    out, rg = pull.run_pull_fixed(prog, shards.spec, dev, s0_d, 4,
                                  method="scan", donate=True,
                                  telemetry=ring_in)
    jax.block_until_ready(out)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))
    assert s0_d.is_deleted()
    assert ring_in.buf.is_deleted()
    rows, n = obs_ring.ring_rows(rg)
    assert n == 4


def test_push_telemetry_donate_consumes():
    from lux_tpu.engine import push
    from lux_tpu.graph import generate
    from lux_tpu.graph.push_shards import build_push_shards
    from lux_tpu.models import sssp

    g = generate.rmat(8, 8, seed=31)
    sh = build_push_shards(g, 2)
    prog = sssp.SSSPProgram(nv=g.nv, start=0)
    ref_state, ref_it, ref_edges = push.run_push(prog, sh)
    loop = push.compile_push_chunk(prog, sh.pspec, sh.spec, "scan",
                                   donate=True, telemetry=True)
    arrays, parrays, carry0 = push.push_init(prog, sh)
    ring_in = jax.tree.map(jnp.asarray, obs_ring.new_ring("push"))
    out, rg = loop(arrays, parrays, carry0, jnp.int32(50), ring_in)
    jax.block_until_ready(out.state)
    np.testing.assert_array_equal(np.asarray(ref_state),
                                  np.asarray(out.state))
    assert carry0.state.is_deleted()
    assert ring_in.buf.is_deleted()


def test_emit_ring_point(rec):
    pull, prog, shards, dev, s0, _ = _pull_setup()
    _, rg = pull.run_pull_fixed(
        prog, shards.spec, dev, s0, 3, method="scan",
        telemetry=obs_ring.new_ring("pull_fixed"))
    obs_ring.emit_ring("pull_fixed", rg, app="pagerank")
    (p,) = [e for e in read_events(rec.run_dir()) if e["e"] == "p"]
    assert p["n"] == "telemetry.ring"
    assert p["a"]["kind"] == "pull_fixed" and p["a"]["n"] == 3
    assert p["a"]["cols"] == ["it", "residual_l1"]
    assert len(p["a"]["rows"]) == 3


# ---------------------------------------------------------------------------
# LUX-O checker family
# ---------------------------------------------------------------------------

_LUXO_BAD = '''
import jax
from lux_tpu import obs
from lux_tpu.obs import ring as obs_ring

@jax.jit
def f(x):
    jax.block_until_ready(x)          # O001
    obs.point("inside", v=1)          # O002
    jax.debug.print("x={}", x)        # O004
    return x + 1

def driver(prog, spec, arrays, state, ring):
    for k in range(10):
        state = run_pull_fixed(prog, spec, arrays, state, k)
        rows, n = obs_ring.ring_rows(ring)   # O003
    return state
'''

_LUXO_CLEAN = '''
import jax
from lux_tpu import obs
from lux_tpu.obs import ring as obs_ring

@jax.jit
def f(x, ring):
    return x + 1, obs_ring.ring_push(ring, 0, x.sum())

def driver(prog, spec, arrays, state, ring):
    with obs.span("pull.chunk", k=10):
        for k in range(10):
            state = run_pull_fixed(prog, spec, arrays, state, k)
        jax.block_until_ready(state)
    rows, n = obs_ring.ring_rows(ring)  # ONE fetch, after the loop
    obs_ring.emit_ring("pull_fixed", ring)
    return state
'''


def _luxo_run(tmp_path, source, name):
    from lux_tpu.analysis import check_paths
    from lux_tpu.analysis.obs import ObsChecker

    p = tmp_path / name
    p.write_text(source)
    return check_paths([str(p)], str(tmp_path), checkers=[ObsChecker()])


def test_luxo_seeded_fixture_fires(tmp_path):
    findings = _luxo_run(tmp_path, _LUXO_BAD, "bad.py")
    codes = sorted(f.code for f in findings)
    assert codes == ["LUX-O001", "LUX-O002", "LUX-O003", "LUX-O004"]


def test_luxo_clean_twin(tmp_path):
    assert _luxo_run(tmp_path, _LUXO_CLEAN, "clean.py") == []


def test_luxo_registered_in_all_checkers():
    from lux_tpu.analysis import ALL_CHECKERS, FAMILIES

    assert "observability" in FAMILIES
    assert any(type(c).__name__ == "ObsChecker" for c in ALL_CHECKERS)


def test_luxo_renamed_import_still_caught(tmp_path):
    src = (
        "import jax\n"
        "from lux_tpu.obs.ring import ring_rows as rr\n\n"
        "def body(c):\n"
        "    return rr(c)\n\n"
        "out = jax.lax.while_loop(lambda c: True, body, 0)\n"
    )
    findings = _luxo_run(tmp_path, src, "renamed.py")
    assert [f.code for f in findings] == ["LUX-O002"]


def test_luxo_compiled_loop_idiom_caught(tmp_path):
    """The repo's dominant push idiom drives the callable returned by a
    compile_* factory, not a run_* entry point — O003 must see it."""
    src = (
        "from lux_tpu.obs import ring as obs_ring\n\n"
        "def driver(push, prog, pspec, spec, arrays, parrays, carry, ring):\n"
        "    loop = push.compile_push_chunk(prog, pspec, spec, 'scan')\n"
        "    while int(carry.active) > 0:\n"
        "        carry, ring = loop(arrays, parrays, carry, 8, ring)\n"
        "        rows, n = obs_ring.ring_rows(ring)   # per-chunk fence\n"
        "    return carry\n"
    )
    findings = _luxo_run(tmp_path, src, "loopidiom.py")
    assert [f.code for f in findings] == ["LUX-O003"]

    clean = (
        "from lux_tpu.obs import ring as obs_ring\n\n"
        "def driver(push, prog, pspec, spec, arrays, parrays, carry, ring):\n"
        "    loop = push.compile_push_chunk(prog, pspec, spec, 'scan')\n"
        "    while int(carry.active) > 0:\n"
        "        carry, ring = loop(arrays, parrays, carry, 8, ring)\n"
        "    rows, n = obs_ring.ring_rows(ring)  # ONE fetch, after\n"
        "    return carry\n"
    )
    assert _luxo_run(tmp_path, clean, "loopidiom_clean.py") == []


# ---------------------------------------------------------------------------
# luxview + obs_span CLIs
# ---------------------------------------------------------------------------


def _seed_event_log(tmp_path):
    """A deterministic multi-section event log (injected clock)."""
    t = iter(float(x) for x in range(100))
    r = Recorder(run_id="golden", root=str(tmp_path),
                 clock=lambda: next(t), enabled=True)
    with r.span("step.micro_race", timeout_s=300) as sp:
        with r.span("compile.warm"):
            pass
        sp.set(rc=0)  # end attrs (Span.set / obs_span --rc) must render
    r.point("telemetry.ring", kind="pull_fixed",
            cols=["it", "residual_l1"], n=3,
            rows=[[0, 0.5], [1, 0.25], [2, 0.125]], app="pagerank")
    r.point("xprof.kernels", trace_dir="/tmp/x", rows=[
        {"name": "fused_pass_gather_3", "class": "routed-pf",
         "total_ms": 12.5, "calls": 30, "frac": 0.62},
        {"name": "gather.17", "class": "gather", "total_ms": 7.5,
         "calls": 10, "frac": 0.38}],
        classes={"routed-pf": 12.5, "gather": 7.5})
    r.point("serve.metrics", completed=64, timeouts=0, rejected=1,
            batches=2, qps=880.0, latency_ms={"p50": 3.1, "p99": 9.7})
    r.point("bench.row", metric="pagerank_gteps_rmat18_1chip",
            value=1.23, unit="GTEPS", method="scan")
    # an OPEN span: the process "died" inside
    r.span("step.bench_race").__enter__()
    r.close()
    return os.path.join(str(tmp_path), "golden")


def test_luxview_golden_report(tmp_path, capsys):
    run_dir = _seed_event_log(tmp_path)
    luxview = _load_tool("luxview")
    rc = luxview.main([run_dir])
    out = capsys.readouterr().out
    assert rc == 0
    assert "# luxtrace report — run golden" in out
    # post-mortem: the OPEN span is called out
    assert "step.bench_race" in out and "OPEN" in out
    # waterfall: nesting + durations on the injected clock (1s ticks)
    assert "step.micro_race" in out and "compile.warm" in out
    assert "[timeout_s=300, rc=0]" in out
    # telemetry curve, kernel table, serve, bench sections all render
    assert "ring: pull_fixed" in out and "residual_l1" in out
    assert "fused_pass_gather_3" in out and "routed-pf" in out
    assert "qps=880.0" in out and "p99=9.7" in out
    assert "pagerank_gteps_rmat18_1chip" in out
    assert out.rstrip().endswith("run_id: golden")


def test_luxview_list_and_missing(tmp_path, capsys):
    luxview = _load_tool("luxview")
    assert luxview.main(["--root", str(tmp_path), "--list"]) == 0
    assert luxview.main(["--root", str(tmp_path), "nope"]) == 2


def test_luxview_out_file(tmp_path, capsys):
    run_dir = _seed_event_log(tmp_path)
    out_md = tmp_path / "window_report.md"
    luxview = _load_tool("luxview")
    assert luxview.main([run_dir, "--out", str(out_md)]) == 0
    assert "run_id: golden" in out_md.read_text()


def test_obs_span_cli_roundtrip(tmp_path, monkeypatch, capsys):
    monkeypatch.setenv("LUX_OBS_DIR", str(tmp_path))
    monkeypatch.setenv("LUX_OBS_RUN_ID", "shellrun")
    obs_span = _load_tool("obs_span")
    assert obs_span.main(["begin", "step.probe", "timeout_s=60"]) == 0
    sid = capsys.readouterr().out.strip()
    assert sid
    assert obs_span.main(["end", sid, "--rc", "0"]) == 0
    assert obs_span.main(["point", "battery.abort", "reason=test"]) == 0
    evs = read_events(str(tmp_path / "shellrun"))
    kinds = [e["e"] for e in evs]
    assert kinds == ["m", "b", "e", "p"]
    assert evs[1]["s"] == sid and evs[1]["a"] == {"timeout_s": 60}
    assert evs[2]["ok"] is True
    # a failed step records rc and ok=False
    assert obs_span.main(["begin", "step.dead"]) == 0
    sid2 = capsys.readouterr().out.strip()
    assert obs_span.main(["end", sid2, "--rc", "124"]) == 0
    evs = read_events(str(tmp_path / "shellrun"))
    assert evs[-1]["ok"] is False and evs[-1]["a"]["rc"] == 124


def test_obs_span_begin_empty_sid_on_degrade(tmp_path, monkeypatch,
                                             capsys):
    """An unusable log dir must print an EMPTY sid (the documented
    degrade contract) so chip_day's [ -n "$sid" ] guards skip the
    end/point spawns instead of appending into the void."""
    bad = tmp_path / "occupied"
    bad.write_text("not a dir")
    monkeypatch.setenv("LUX_OBS_DIR", str(bad))
    monkeypatch.setenv("LUX_OBS_RUN_ID", "degraded")
    obs_span = _load_tool("obs_span")
    assert obs_span.main(["begin", "step.x"]) == 0
    assert capsys.readouterr().out.strip() == ""


# ---------------------------------------------------------------------------
# serve metrics: Prometheus dump + snapshots
# ---------------------------------------------------------------------------


def test_prometheus_dump_format():
    from lux_tpu.serve.metrics import ServeMetrics

    m = ServeMetrics()
    for ms in (1, 2, 5, 50):
        m.record_done(latency_s=ms / 1e3, wait_s=ms / 2e3, traversed=100)
    m.record_batch(q=8, real=4, warm=True, service_s=0.004)
    m.record_rejected()
    m.sample_queue_depth(7)
    text = m.dump(elapsed_s=2.0,
                  cache_stats={"warm_hits": 3, "cold_traces": 1})
    assert "# TYPE lux_serve_requests_completed_total counter" in text
    assert "lux_serve_requests_completed_total 4" in text
    assert "lux_serve_requests_shed_total 1" in text
    assert "lux_serve_queue_depth_max 7" in text
    assert "lux_serve_qps 2.0" in text
    assert "lux_serve_warm_hit_ratio 0.75" in text
    # histogram: cumulative buckets, +Inf == count
    assert 'lux_serve_request_latency_seconds_bucket{le="0.001"} 1' in text
    assert 'lux_serve_request_latency_seconds_bucket{le="0.01"} 3' in text
    assert 'lux_serve_request_latency_seconds_bucket{le="+Inf"} 4' in text
    assert "lux_serve_request_latency_seconds_count 4" in text
    # cumulative monotonicity across all buckets
    counts = [int(ln.rsplit(" ", 1)[1]) for ln in text.splitlines()
              if "latency_seconds_bucket" in ln]
    assert counts == sorted(counts)


def test_metrics_snapshot_point(rec):
    from lux_tpu.serve.metrics import ServeMetrics

    m = ServeMetrics()
    m.record_done(latency_s=0.003, wait_s=0.001, traversed=10)
    m.emit_snapshot(elapsed_s=1.0)
    (p,) = [e for e in read_events(rec.run_dir()) if e["e"] == "p"]
    assert p["n"] == "serve.metrics"
    assert p["a"]["completed"] == 1 and "latency_ms" in p["a"]


def test_scheduler_periodic_snapshot(rec):
    """Fake-clock pumps cross snapshot_every_s -> serve.metrics points
    land in the event log (first pump only arms the timer)."""
    from lux_tpu.serve.scheduler import MicroBatchScheduler

    class _NoCache:
        def warm_buckets(self, app):
            return ()

    sched = MicroBatchScheduler(_NoCache(), app="sssp",
                                clock=lambda: 0.0)
    sched.snapshot_every_s = 10.0
    sched.step(now=0.0)     # arms
    sched.step(now=5.0)     # within the window: no snapshot
    sched.step(now=11.0)    # fires
    sched.step(now=12.0)    # within
    sched.step(now=22.0)    # fires
    snaps = [e for e in read_events(rec.run_dir())
             if e["e"] == "p" and e["n"] == "serve.metrics"]
    assert len(snaps) == 2


# ---------------------------------------------------------------------------
# xprof parsing
# ---------------------------------------------------------------------------


def _write_trace(tmp_path, events, gz=True):
    d = os.path.join(str(tmp_path), "plugins", "profile", "run1")
    os.makedirs(d, exist_ok=True)
    doc = json.dumps({"traceEvents": events}).encode()
    if gz:
        with gzip.open(os.path.join(d, "host.trace.json.gz"), "wb") as f:
            f.write(doc)
    else:
        with open(os.path.join(d, "host.trace.json"), "wb") as f:
            f.write(doc)
    return str(tmp_path)


def test_xprof_kernel_table_classifies_and_filters(tmp_path):
    events = [
        {"ph": "M", "name": "process_name", "pid": 7,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "python host threads"}},
        {"ph": "X", "pid": 7, "name": "fused_pass_gather_2", "dur": 3000},
        {"ph": "X", "pid": 7, "name": "fused_pass_gather_2", "dur": 1000},
        {"ph": "X", "pid": 7, "name": "gather.55", "dur": 2000},
        {"ph": "X", "pid": 7, "name": "all-gather.1", "dur": 1000},
        # host-pid event must be EXCLUDED (device lanes exist)
        {"ph": "X", "pid": 1, "name": "hostloop", "dur": 99999},
    ]
    rows = xprof.kernel_table(_write_trace(tmp_path, events))
    assert [r["name"] for r in rows] == ["fused_pass_gather_2",
                                        "gather.55", "all-gather.1"]
    top = rows[0]
    assert top["class"] == "routed-pf" and top["calls"] == 2
    assert top["total_ms"] == 4.0 and top["frac"] == 0.5714
    assert xprof.class_summary(rows) == {
        "routed-pf": 4.0, "gather": 2.0, "collective": 1.0}


def test_xprof_only_newest_capture_counts(tmp_path):
    """A reused --profile-dir accumulates one plugins/profile/<ts> bundle
    per start_trace; attribution must cover the newest only, never the
    union of history."""
    for run, name, dur, age in (("run_old", "stale.kernel", 9000, 100),
                                ("run_new", "fresh.kernel", 1000, 0)):
        d = os.path.join(str(tmp_path), "plugins", "profile", run)
        os.makedirs(d)
        p = os.path.join(d, "t.trace.json")
        with open(p, "w") as f:
            json.dump({"traceEvents": [
                {"ph": "X", "pid": 1, "name": name, "dur": dur}]}, f)
        old = time.time() - age
        os.utime(p, (old, old))
        os.utime(d, (old, old))
    rows = xprof.kernel_table(str(tmp_path))
    assert [r["name"] for r in rows] == ["fresh.kernel"]


def test_xprof_host_file_excluded_when_device_lanes_exist(tmp_path):
    """The all-pids fallback is bundle-wide: a host-only sibling file
    must contribute nothing (and not flag the table host_only) when any
    file in the bundle has device lanes."""
    d = os.path.join(str(tmp_path), "plugins", "profile", "run1")
    os.makedirs(d)
    with open(os.path.join(d, "dev.trace.json"), "w") as f:
        json.dump({"traceEvents": [
            {"ph": "M", "name": "process_name", "pid": 7,
             "args": {"name": "/device:TPU:0"}},
            {"ph": "X", "pid": 7, "name": "gather.1", "dur": 2000}]}, f)
    with open(os.path.join(d, "host.trace.json"), "w") as f:
        json.dump({"traceEvents": [
            {"ph": "X", "pid": 1, "name": "hostloop", "dur": 99999}]}, f)
    meta = {}
    rows = xprof.kernel_table(str(tmp_path), meta=meta)
    assert [r["name"] for r in rows] == ["gather.1"]
    assert "host_only" not in meta


def test_xprof_no_device_lane_falls_back_to_all(tmp_path):
    events = [{"ph": "X", "pid": 1, "name": "scatter.9", "dur": 500}]
    meta = {}
    rows = xprof.kernel_table(_write_trace(tmp_path, events, gz=False),
                              meta=meta)
    assert len(rows) == 1 and rows[0]["class"] == "scatter"
    # the fallback is LABELED: host wall time must not masquerade as
    # device ms in the emitted event / luxview table
    assert meta.get("host_only") is True


def test_xprof_emit_into_event_log(rec, tmp_path):
    events = [{"ph": "X", "pid": 1, "name": "fusion.3", "dur": 1500}]
    d = _write_trace(tmp_path, events)
    rows = xprof.emit_kernel_table(d, top=5)
    assert rows and rows[0]["class"] == "fusion"
    (p,) = [e for e in read_events(rec.run_dir()) if e["e"] == "p"]
    assert p["n"] == "xprof.kernels" and p["a"]["classes"] == {"fusion": 1.5}
    assert p["a"]["host_only"] is True  # no device lanes in this capture
    # empty dir: no rows, no event, no crash
    assert xprof.emit_kernel_table(str(tmp_path / "empty")) is None
