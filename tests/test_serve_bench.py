"""serve/benchmarks + tools/serve_bench.py: the measurement core and the
bench-parsable emission.  Fast CPU paths are unmarked (tier-1); the
acceptance-scale throughput gate is @slow (timing assertion, bench-scale
graph — run it explicitly, not in the CI lane)."""
import json
import subprocess
import sys

import numpy as np
import pytest

from lux_tpu.graph import generate
from lux_tpu.graph.shards import build_pull_shards
from lux_tpu.serve.benchmarks import measure_serving, pick_sources


def test_pick_sources_avoids_dead_vertices():
    g = generate.rmat(8, 4, seed=6)
    srcs = pick_sources(g, 8, seed=1)
    deg = np.bincount(g.col_idx, minlength=g.nv)
    assert len(srcs) == 8 and (deg[srcs] > 0).all()


def test_measure_serving_fields():
    g = generate.rmat(9, 6, seed=8)
    shards = build_pull_shards(g, 1)
    res = measure_serving(g, shards, app="sssp", q=4, num_seq=2,
                          batched_reps=1)
    for k in ("qps_batched", "qps_q1_sequential", "batched_vs_q1",
              "latency_ms", "traversed_edges", "scheduler", "method"):
        assert k in res, k
    assert res["qps_batched"] > 0 and res["qps_q1_sequential"] > 0
    assert res["scheduler"]["completed"] == 4
    assert res["scheduler"]["timeouts"] == 0
    assert json.dumps(res)  # bench artifact lines must be JSON-clean


def test_serve_bench_tool_emits_parsable_line():
    from tests.conftest import forced_cpu_env

    proc = subprocess.run(
        [sys.executable, "tools/serve_bench.py", "--rmat-scale", "9",
         "--rmat-ef", "6", "--q", "4", "--num-seq", "2", "--reps", "1"],
        capture_output=True, text=True, timeout=300, env=forced_cpu_env(),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = [ln for ln in proc.stdout.splitlines()
            if ln.startswith('{"metric"')][-1]
    row = json.loads(line)
    assert row["metric"] == "sssp_qps_rmat9_1chip_cpu_fallback"
    assert row["unit"] == "QPS" and row["value"] > 0
    assert row["vs_baseline"] == row["batched_vs_q1"]


@pytest.mark.slow
def test_rmat16_batched_speedup_gate():
    """THE acceptance bar: warm Q=64 batched >= 5x warm Q=1 sequential
    on rmat16 sssp (CPU fallback).  Timing assertion at bench scale —
    deliberately outside the tier-1 lane; tools/serve_bench.py
    --min-speedup 5 runs the same gate standalone."""
    g = generate.rmat(16, 16, seed=7)
    shards = build_pull_shards(g, 1)
    res = measure_serving(g, shards, app="sssp", q=64, num_seq=8,
                          batched_reps=1)
    assert res["batched_vs_q1"] >= 5.0, res
