"""luxfault (ISSUE 14): deterministic fault injection, controller
failover, and the retry/backoff-hardened serving envelope.

Pins the acceptance surface: (a) faults are DATA — seeded FaultPlans
fired at the wire layer and named process points, every historical
ad-hoc drill (PR 8 worker kill mid-burst, PR 10 torn journal write,
PR 12 kill between delta receipt and marker) re-expressed as a named
plan and still passing; (b) a controller killed mid-write-load is
replaced by a promoted controller that recovers the ring from
re-hellos and the generation line from journal + live_meta with ZERO
acked-write loss and bitwise-equal answers; (c) the client envelope:
per-call wire deadlines naming peer + knob, jittered-backoff retries
honoring retry_after_ms, idempotent write ids, and the opt-in
bounded-staleness degrade with its explicit tag; (d) the chaos soak's
fixed-seed tier-1 instance (20 seeds ride the slow tier).
"""
import os
import socket
import threading
import time

import numpy as np
import pytest

from lux_tpu import fault
from lux_tpu.fault import drills
from lux_tpu.fault.chaos import chaos_soak
from lux_tpu.fault.plan import FaultPlan, FaultPlanError, FaultRule
from lux_tpu.graph import generate
from lux_tpu.graph.shards import build_pull_shards
from lux_tpu.models.sssp import bfs_reference
from lux_tpu.mutate.deltalog import DeltaLog
from lux_tpu.serve.fleet import wire
from lux_tpu.serve.fleet.controller import (
    FleetController,
    FleetError,
    FleetRejectedError,
    StaleReadError,
    WorkerRefusedError,
)
from lux_tpu.serve.fleet.worker import ReplicaWorker
from lux_tpu.serve.live.controller import (
    LiveFleetController,
    promote_live_controller,
    start_live_fleet,
)
from lux_tpu.serve.live.journal import LiveJournal
from lux_tpu.serve.live.replica import LiveReplica
from lux_tpu.utils.backoff import Backoff, poll_until, retry_call
from lux_tpu.utils.config import env_float


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    yield
    fault.uninstall()


@pytest.fixture(scope="module")
def small():
    g = generate.rmat(8, 6, seed=9)
    return g, build_pull_shards(g, 2)


def _batches(g, n, rows=12, seed=1):
    rng = np.random.default_rng(seed)
    dele_pool = rng.permutation(g.ne)
    out, lo = [], 0
    for _ in range(n):
        ndel = rows // 2
        dele = dele_pool[lo:lo + ndel]
        lo += ndel
        src = np.concatenate([np.asarray(g.col_idx, np.int64)[dele],
                              rng.integers(0, g.nv, rows - ndel)])
        dst = np.concatenate([np.asarray(g.dst_of_edges(),
                                         np.int64)[dele],
                              rng.integers(0, g.nv, rows - ndel)])
        op = np.concatenate([np.zeros(ndel, np.int8),
                             np.ones(rows - ndel, np.int8)])
        out.append((src, dst, op))
    return out


# ----------------------------------------------------------------------
# FaultPlan mechanics
# ----------------------------------------------------------------------


def test_plan_json_roundtrip_and_validation():
    p = drills.wire_chaos(seed=11)
    p2 = FaultPlan.from_json(p.to_json())
    assert [r.to_dict() for r in p2.rules] == [
        r.to_dict() for r in p.rules]
    assert p2.seed == 11
    with pytest.raises(FaultPlanError, match="unknown site"):
        FaultRule("nowhere", "drop")
    with pytest.raises(FaultPlanError, match="not expressible"):
        FaultRule("wire.send", "torn")  # torn is a journal action
    with pytest.raises(FaultPlanError, match="unknown rule fields"):
        FaultRule.from_dict({"site": "proc", "action": "kill",
                             "typo_field": 1})
    with pytest.raises(FaultPlanError, match="bad plan JSON"):
        FaultPlan.from_json("not json")


def test_plan_seeded_prob_is_deterministic():
    def fires(seed):
        p = FaultPlan([FaultRule("wire.send", "drop", prob=0.5)],
                      seed=seed)
        return [p.fire("wire.send", peer="x") is not None
                for _ in range(32)]

    assert fires(3) == fires(3)
    assert fires(3) != fires(4)  # 1/2^32 false-failure odds


def test_plan_after_count_and_alias_gating():
    p = FaultPlan([FaultRule("proc", "kill",
                             point="after_delta_before_marker",
                             after=1, count=1)])
    # the alias resolves to the placed point name
    assert p.rules[0].point == "journal.before_marker"
    fault.install(p)
    assert fault.ppoint("journal.before_marker") is None  # after=1
    with pytest.raises(fault.InjectedKill):
        fault.ppoint("after_delta_before_marker")  # alias at call site
    assert fault.ppoint("journal.before_marker") is None  # count spent
    assert p.total_fired() == 1


def test_plan_env_install(monkeypatch, tmp_path):
    plan_json = FaultPlan([FaultRule("wire.recv", "delay",
                                     delay_ms=1.0)], seed=5,
                          name="envplan").to_json()
    path = tmp_path / "plan.json"
    path.write_text(plan_json)
    monkeypatch.setenv("LUX_FAULT_PLAN", str(path))
    monkeypatch.setattr(fault, "_ENV_CHECKED", False)
    monkeypatch.setattr(fault, "_PLAN", None)
    p = fault.active_plan()
    assert p is not None and p.name == "envplan" and p.seed == 5
    fault.uninstall()
    # inline JSON form
    monkeypatch.setenv("LUX_FAULT_PLAN", plan_json)
    monkeypatch.setattr(fault, "_ENV_CHECKED", False)
    assert fault.active_plan().name == "envplan"


# ----------------------------------------------------------------------
# backoff + env_float satellites
# ----------------------------------------------------------------------


def test_backoff_jitter_seeded_and_capped():
    a = Backoff(base_ms=10, cap_ms=50, seed=7)
    b = Backoff(base_ms=10, cap_ms=50, seed=7)
    da = [a.next_s() for _ in range(8)]
    assert da == [b.next_s() for _ in range(8)]  # seeded replay
    assert all(0.0 <= d <= 0.05 for d in da)  # cap respected
    assert Backoff(base_ms=10, cap_ms=50, seed=8).next_s() != da[0]
    a.reset()
    assert a.attempt == 0


def test_backoff_env_knobs(monkeypatch):
    monkeypatch.setenv("LUX_BACKOFF_BASE_MS", "100")
    monkeypatch.setenv("LUX_BACKOFF_CAP_MS", "200")
    bo = Backoff(seed=0)
    assert bo.base_ms == 100.0 and bo.cap_ms == 200.0
    monkeypatch.setenv("LUX_BACKOFF_BASE_MS", "garbage")
    with pytest.raises(ValueError, match="LUX_BACKOFF_BASE_MS"):
        Backoff(seed=0)


def test_retry_call_honors_retry_after_and_deadline():
    calls = []

    def flaky():
        calls.append(time.monotonic())
        if len(calls) < 3:
            raise FleetRejectedError(retry_after_ms=30.0)
        return "ok"

    t0 = time.monotonic()
    assert retry_call(flaky, retry_on=(FleetRejectedError,),
                      deadline_s=10.0,
                      backoff=Backoff(base_ms=1, cap_ms=2, seed=0)) == "ok"
    assert len(calls) == 3
    # the two retries each slept >= the 30 ms hint (jitter only adds)
    assert time.monotonic() - t0 >= 0.055

    def always():
        raise FleetRejectedError(retry_after_ms=5.0)

    with pytest.raises(FleetRejectedError):  # LAST error re-raises
        retry_call(always, retry_on=(FleetRejectedError,),
                   deadline_s=0.15,
                   backoff=Backoff(base_ms=1, cap_ms=5, seed=0))


def test_poll_until_and_env_float(monkeypatch):
    state = {"n": 0}

    def pred():
        state["n"] += 1
        return state["n"] >= 3

    assert poll_until(pred, timeout_s=5.0)
    assert not poll_until(lambda: False, timeout_s=0.05)
    monkeypatch.setenv("LUX_TEST_FLOAT", "2.5")
    assert env_float("LUX_TEST_FLOAT") == 2.5
    monkeypatch.setenv("LUX_TEST_FLOAT", "nope")
    with pytest.raises(ValueError, match="LUX_TEST_FLOAT"):
        env_float("LUX_TEST_FLOAT")
    monkeypatch.setenv("LUX_TEST_FLOAT", "nan")
    with pytest.raises(ValueError, match="LUX_TEST_FLOAT"):
        env_float("LUX_TEST_FLOAT")
    monkeypatch.setenv("LUX_TEST_FLOAT", "")
    assert env_float("LUX_TEST_FLOAT", 1.5) == 1.5


# ----------------------------------------------------------------------
# wire faults + per-call deadlines
# ----------------------------------------------------------------------


def _sock_pair(owner_a="a", owner_b="b"):
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    out = {}

    def acc():
        s, _ = srv.accept()
        out["conn"] = wire.Conn(s, peer=owner_a, owner=owner_b)

    t = threading.Thread(target=acc)
    t.start()
    ca = wire.Conn.connect("127.0.0.1", srv.getsockname()[1],
                           peer=owner_b, owner=owner_a)
    t.join()
    srv.close()
    return ca, out["conn"]


def test_wire_partial_write_hits_deadline_naming_peer_and_knob(
        monkeypatch):
    monkeypatch.setenv("LUX_FLEET_TIMEOUT_S", "0.25")
    ca, cb = _sock_pair("client", "w0")
    try:
        fault.install(FaultPlan([FaultRule(
            "wire.send", "partial", op="query", count=1,
            trunc_bytes=4)]))
        ca.send({"op": "query", "n": 1})  # only a prefix hits the wire
        t0 = time.monotonic()
        with pytest.raises(wire.WireTimeout) as ei:
            cb.recv()
        assert time.monotonic() - t0 < 5.0
        assert "client" in str(ei.value)  # names the hung peer...
        assert "LUX_FLEET_TIMEOUT_S" in str(ei.value)  # ...and the knob
        assert isinstance(ei.value, wire.ConnectionClosed)
    finally:
        ca.close()
        cb.close()


def test_wire_drop_delay_corrupt_truncate_reset(monkeypatch):
    monkeypatch.setenv("LUX_FLEET_TIMEOUT_S", "5")
    ca, cb = _sock_pair("ctl", "w0")
    try:
        plan = fault.install(FaultPlan([
            FaultRule("wire.send", "drop", op="dropme", count=1),
            FaultRule("wire.recv", "delay", op="slow", delay_ms=30,
                      count=1),
            FaultRule("wire.send", "corrupt", op="garble", count=1),
        ]))
        ca.send({"op": "dropme"})
        ca.send({"op": "slow", "n": 2})
        t0 = time.monotonic()
        msg, _ = cb.recv()  # the dropped frame never arrives
        assert msg["n"] == 2 and time.monotonic() - t0 >= 0.025
        ca.send({"op": "garble"}, arr=np.arange(64))
        # flipped payload bits are caught by the frame crc — without
        # it they parse as a valid, WRONG array
        with pytest.raises(wire.WireError, match="crc"):
            cb.recv()
        assert plan.total_fired() == 3
        # truncate: prefix + EOF mid-frame on a fresh pair
        fault.install(FaultPlan([FaultRule(
            "wire.send", "truncate", count=1, trunc_bytes=2)]))
        cc, cd = _sock_pair("ctl", "w1")
        cc.send({"op": "x"})
        with pytest.raises(wire.ConnectionClosed):
            cd.recv()
        cc.close()
        cd.close()
        # reset: the sender's own socket drops before anything is sent
        fault.install(FaultPlan([FaultRule(
            "wire.send", "reset", count=1)]))
        ce, cf = _sock_pair("ctl", "w2")
        with pytest.raises(wire.ConnectionClosed, match="injected reset"):
            ce.send({"op": "x"})
        with pytest.raises(wire.ConnectionClosed):
            cf.recv()
        cf.close()
    finally:
        ca.close()
        cb.close()


# ----------------------------------------------------------------------
# the re-expressed historical drills (named, seeded plans)
# ----------------------------------------------------------------------


def _mk_fleet(shards, n):
    ctl = FleetController(hb_interval_s=0.1)
    workers = [ReplicaWorker(shards, worker_id=f"w{i}", q_buckets=(1, 4),
                             max_wait_ms=1.0).start() for i in range(n)]
    for w in workers:
        ctl.add_worker("127.0.0.1", w.port)
    return ctl, workers


def _teardown(ctl, workers):
    ctl.close()
    for w in workers:
        if w._running:
            w.stop()


def test_drill_worker_kill_mid_burst_as_plan(small):
    """PR 8's kill-mid-burst drill as a named, seeded FaultPlan: the
    victim dies when its Nth query FRAME arrives (wire.recv site), the
    controller re-dispatches to ring successors — every answer that
    arrives is correct, and the injection shows in the prom surface."""
    g, shards = small
    ctl, workers = _mk_fleet(shards, 2)
    try:
        srcs = list(range(24))
        import collections

        victim = collections.Counter(
            ctl.route(s) for s in srcs).most_common(1)[0][0]
        w = next(x for x in workers if x.worker_id == victim)
        plan = drills.worker_kill_mid_burst(victim, nth_query=3, seed=2)
        plan.bind(f"kill:{victim}", w.kill)
        fault.install(plan)
        futs = [ctl.submit(s) for s in srcs]
        got = 0
        for s, f in zip(srcs, futs):
            try:
                a = f.result(timeout=60)
            except FleetError:
                continue  # degraded is allowed; wrong is not
            got += 1
            assert np.array_equal(a, bfs_reference(g, s)), s
        assert got > 0
        assert plan.total_fired() == 1
        assert ctl.stats()["worker_deaths"] == 1
        assert ctl.live_workers() == sorted(
            x.worker_id for x in workers if x.worker_id != victim)
        dump = ctl.prom_dump()
        assert 'lux_fault_injected_total{site="wire.recv",' in dump
        assert "lux_fleet_retries_total" in dump
    finally:
        _teardown(ctl, workers)


def test_drill_kill_before_marker_as_plan(small, tmp_path):
    """PR 12's kill-between-receipt-and-marker drill as a plan: the
    batch npz lands, the injected crash fires before the marker, and
    recovery replays the EXACT committed prefix then catches up to
    bitwise-equal answers."""
    g, sh = small
    J = LiveJournal(g)
    for s, d, o in _batches(g, 3):
        J.admit(s, d, o)
    wd = str(tmp_path / "w")
    rep = LiveReplica(g, sh, cap=256, journal_dir=wd,
                      standing=(("sssp", 0),))
    rep.apply_batch(J.payload(1), 1)
    fault.install(drills.kill_before_marker(seed=4))
    with pytest.raises(fault.InjectedKill):
        rep.apply_batch(J.payload(2), 2)
    fault.uninstall()
    rec = LiveReplica(g, sh, cap=256, journal_dir=wd,
                      standing=(("sssp", 0),))
    assert rec.generation() == 1 == rec.servable_generation()
    for gen, arr in J.batches_since(rec.generation()):
        rec.apply_batch(arr, gen)
    assert rec.generation() == 3
    rec.refresh()
    assert np.array_equal(rec.standing("sssp")["state"],
                          bfs_reference(J.log.merged_graph(), 0))


def test_drill_torn_journal_write_as_plan(small, tmp_path):
    """PR 10's torn-journal drill as a plan: the batch npz is HALF
    written straight to its final name (no rename, no marker), then
    the injected crash — replay must discard exactly that batch and
    keep the committed prefix."""
    g, _sh = small
    jd = str(tmp_path / "j")
    log = DeltaLog(g, journal_dir=jd)
    b = _batches(g, 2)
    log.apply(*b[0])
    fault.install(drills.torn_journal_write(seed=3))
    with pytest.raises(fault.InjectedKill):
        log.apply(*b[1])
    fault.uninstall()
    # on disk: batch 1's npz exists but is torn and unmarked
    assert os.path.exists(os.path.join(jd, "batch_00000001.npz"))
    assert not os.path.exists(os.path.join(jd, "batch_00000001.ok"))
    rec = DeltaLog(g, journal_dir=jd)  # replay
    assert rec.batches_applied == 1
    # the torn npz was removed so the sequence number is reusable
    assert not os.path.exists(os.path.join(jd, "batch_00000001.npz"))
    rec.apply(*b[1])  # the lost batch re-applies cleanly
    assert rec.batches_applied == 2


def test_worker_kill_at_named_point_live_fleet(small, tmp_path):
    """The issue's API: ``worker.kill_at("after_delta_before_marker")``
    — the worker dies inside the delta window (batch npz journaled, no
    marker, no ack; journaled workers only — the window IS the journal
    protocol's), the write path survives on the other replica, and the
    victim recovers to its exact committed prefix on rejoin."""
    g, sh = small
    root = str(tmp_path / "fleet")
    fleet = start_live_fleet(2, g, parts=2, cap=512,
                             standing=(("sssp", 0),),
                             journal_root=root)
    ctl = fleet.controller
    try:
        b = _batches(g, 2)
        ctl.admit_writes(*b[0])
        victim = fleet.thread_workers[1]
        victim.kill_at("after_delta_before_marker")
        rep = ctl.admit_writes(*b[1])
        # the killed replica cannot have acked; the survivor did
        assert victim.worker_id not in rep["acked"]
        assert rep["acked"], rep
        assert ctl.generation() == 2
        merged = ctl.journal.log.merged_graph()
        f = ctl.submit(3, min_generation=2)
        assert np.array_equal(f.result(timeout=60),
                              bfs_reference(merged, 3))
        plan = fault.active_plan()
        assert plan is not None and plan.total_fired() == 1
        fault.uninstall()
        # the victim's journal holds EXACTLY the committed prefix
        # (generation 1; the killed batch's marker never landed), and
        # the rejoin catch-up brings it to parity
        live2 = LiveReplica(g, sh, cap=512,
                            journal_dir=os.path.join(
                                root, victim.worker_id),
                            standing=(("sssp", 0),))
        assert live2.generation() == 1
        w2 = ReplicaWorker(sh, worker_id=victim.worker_id,
                           graph_id="live", q_buckets=(1, 4),
                           live=live2).start()
        fleet.thread_workers.append(w2)
        ctl.add_worker("127.0.0.1", w2.port)
        assert ctl.worker_generations()[victim.worker_id] == 2
        f = ctl.submit(5, min_generation=2)
        assert np.array_equal(f.result(timeout=60),
                              bfs_reference(merged, 5))
    finally:
        fleet.close()


# ----------------------------------------------------------------------
# the client envelope
# ----------------------------------------------------------------------


@pytest.fixture(scope="module")
def live_fleet(small):
    """ONE shared in-memory live fleet for the read-side envelope
    tests (they only advance generations monotonically)."""
    g, _sh = small
    fleet = start_live_fleet(2, g, parts=2, cap=1024,
                             standing=(("sssp", 0),))
    yield g, fleet
    fleet.close()


def test_submit_retrying_retries_sheds_with_hint(live_fleet):
    g, fleet = live_fleet
    ctl = fleet.controller
    real, calls = ctl.submit, []

    def flaky(*a, **kw):
        calls.append(1)
        if len(calls) < 3:
            raise FleetRejectedError(retry_after_ms=5.0)
        return real(*a, **kw)

    before = ctl.stats()["retries"]
    try:
        ctl.submit = flaky
        fut = ctl.submit_retrying(1, deadline_s=30.0,
                                  backoff=Backoff(base_ms=1, cap_ms=4,
                                                  seed=0))
    finally:
        ctl.submit = real
    assert len(calls) == 3
    assert np.array_equal(fut.result(timeout=0), bfs_reference(
        fleet.controller.journal.log.merged_graph(), 1))
    assert fut.request_id is not None
    assert ctl.stats()["retries"] - before == 2

    def hopeless(*a, **kw):
        raise FleetRejectedError(retry_after_ms=2.0)

    try:
        ctl.submit = hopeless
        with pytest.raises(FleetRejectedError):
            ctl.submit_retrying(1, deadline_s=0.2,
                                backoff=Backoff(base_ms=1, cap_ms=4,
                                                seed=0))
    finally:
        ctl.submit = real


def test_stale_pending_sweep_resolves_abandoned_futures():
    """A frame lost on the wire (injected drop) leaves a _Pending no
    reply will ever pop; the heartbeat sweep must bound that leak by
    resolving + dropping pendings past the horizon."""
    from lux_tpu.serve.fleet.controller import (
        FleetFuture,
        FleetTimeoutError,
        _Pending,
        _WorkerHandle,
    )

    class _C:
        def close(self):
            pass

    ctl = FleetController()
    try:
        h = _WorkerHandle("wx", _C(), {})
        fut = FleetFuture("sssp", 0, None)
        p = _Pending("query", fut)
        rpc = _Pending("rpc")
        h.pending["r1"], h.pending["r2"] = p, rpc
        ctl._sweep_stale_pending(h, p.t0 + 1.0)  # too young: kept
        assert len(h.pending) == 2
        ctl._sweep_stale_pending(h, p.t0 + ctl.PENDING_SWEEP_S + 1.0)
        assert not h.pending
        with pytest.raises(FleetTimeoutError, match="unanswered"):
            fut.result(timeout=0)
        assert rpc.event.is_set() and rpc.error is not None
    finally:
        ctl.close()


def test_stale_degrade_tags_instead_of_error(live_fleet):
    g, fleet = live_fleet
    ctl = fleet.controller
    b = _batches(g, 1, seed=21)[0]
    gen = ctl.admit_writes(*b)["generation"]
    ahead = ctl.generation() + 5  # a bound no replica can meet
    with pytest.raises(StaleReadError):
        ctl.submit(2, min_generation=ahead)
    fut = ctl.submit_retrying(2, deadline_s=60.0, min_generation=ahead,
                              stale_ok=True)
    ans = fut.result(timeout=0)
    assert fut.stale is True  # the explicit degrade tag
    assert fut.generation is not None and fut.generation < ahead
    assert fut.generation >= gen
    # a stale answer is a CORRECT answer for the generation it names
    assert np.array_equal(
        ans, bfs_reference(ctl.journal.log.merged_graph(), 2))
    st = ctl.stats()
    assert st["stale_degraded"] >= 1
    dump = ctl.prom_dump()
    assert "lux_fleet_stale_degraded_total" in dump
    assert "lux_fleet_worker_stale_reads_total" in dump
    # the serving replica counted it too (per-replica label)
    assert 'lux_serve_stale_reads_total{replica="' in dump
    # a bounded read that CAN be satisfied is not tagged stale
    f2 = ctl.submit_retrying(2, deadline_s=60.0, min_generation=gen)
    f2.result(timeout=0)
    assert f2.stale is False and f2.generation >= gen


def test_write_id_idempotence(live_fleet, tmp_path):
    g, fleet = live_fleet
    ctl = fleet.controller
    b = _batches(g, 1, seed=33)[0]
    r1 = ctl.admit_writes(*b, write_id="wid-1")
    r2 = ctl.admit_writes(*b, write_id="wid-1")  # the lost-ack replay
    assert r1["generation"] == r2["generation"]
    assert not r1["deduped"] and r2["deduped"]
    assert ctl.generation() == r1["generation"]  # nothing re-applied
    assert ctl.stats()["write_dedups"] == 1
    # journaled write-ids survive a controller restart (same dir)
    jd = str(tmp_path / "j")
    J = LiveJournal(g, journal_dir=jd)
    s, d, o = _batches(g, 1, seed=34)[0]
    gen = J.admit(s, d, o, write_id="w-persist")
    J2 = LiveJournal(g, journal_dir=jd)
    assert J2.lookup_write("w-persist") == gen
    assert J2.admit(s, d, o, write_id="w-persist") == gen  # no re-apply
    assert J2.generation() == gen


# ----------------------------------------------------------------------
# controller failover (the tentpole acceptance drill)
# ----------------------------------------------------------------------


def test_controller_kill_mid_write_load_failover(small, tmp_path):
    """Kill the controller mid-write-load; the promoted controller
    recovers the ring from worker re-hellos and the generation line
    from journal + live_meta, loses ZERO acked writes, and answers
    bitwise-equal to the merged reference after promotion."""
    g, _sh = small
    root = str(tmp_path / "fleet")
    snap = os.path.join(root, "snap.lux")
    fleet = start_live_fleet(2, g, parts=2, cap=1024,
                             standing=(("sssp", 0),),
                             journal_root=root, snapshot_path=snap)
    ctl = fleet.controller
    sent = []  # (write_id, batch) in admit order
    acked = {}  # write_id -> generation
    stop = threading.Event()
    kill_after = 3

    def writer():
        rng = np.random.default_rng(5)
        mirror = DeltaLog(g)
        i = 0
        while not stop.is_set() and i < 64:
            from lux_tpu.serve.live.bench import churn_batch

            s, d, o = churn_batch(mirror, rng, 8)
            wid = f"fo-{i}"
            sent.append((wid, (s, d, o)))
            try:
                rep = fleet.controller.admit_writes(
                    s, d, o, write_id=wid)
            except Exception:  # noqa: BLE001 — the kill window
                sent.pop()
                break
            mirror.apply(s, d, o)
            acked[wid] = rep["generation"]
            i += 1

    t = threading.Thread(target=writer)
    t.start()
    while len(acked) < kill_after:  # let real write load build up
        time.sleep(0.01)
    ctl.kill()  # the controller process "dies": no drain, no goodbye
    t.join(timeout=60)
    stop.set()
    assert len(acked) >= kill_after
    last_acked = max(acked.values())
    # ---- promote a successor on the authoritative journal dir -------
    endpoints = [("127.0.0.1", w.port) for w in fleet.thread_workers]
    ctl2, rep = promote_live_controller(
        g, os.path.join(root, "controller"), snap, endpoints, seed=1)
    fleet.controller = ctl2  # so close() tears the right one down
    try:
        assert sorted(rep["joined"]) == ["w0", "w1"]
        assert not rep["refused"] and not rep["failed"]
        # zero acked-write loss: the generation line covers every ack,
        # and each acked write's journaled payload matches what was sent
        assert ctl2.generation() >= last_acked
        by_wid = dict(sent)
        for wid, gen in acked.items():
            s, d, o = by_wid[wid]
            arr = ctl2.journal.payload(gen)
            assert np.array_equal(arr[:, 0], np.asarray(s, np.int64)), wid
            assert np.array_equal(arr[:, 1], np.asarray(d, np.int64)), wid
            assert np.array_equal(arr[:, 2], np.asarray(o, np.int64)), wid
            # the retry envelope's idempotent replay finds them too
            assert ctl2.journal.lookup_write(wid) == gen
        assert ctl2.stats()["failovers"] == 1
        # workers were re-synced to the full journal at re-hello
        assert set(ctl2.worker_generations().values()) == {
            ctl2.generation()}
        # bitwise-equal answers after promotion
        merged = ctl2.journal.log.merged_graph()
        for src in (0, 3, 11):
            f = ctl2.submit_retrying(src, deadline_s=60.0,
                                     min_generation=last_acked)
            assert np.array_equal(f.result(timeout=0),
                                  bfs_reference(merged, src)), src
        ctl2.refresh_fleet()
        for wid, ent in ctl2.read_standing_all("sssp").items():
            assert ent["generation"] >= last_acked, wid
            assert np.array_equal(ent["state"],
                                  bfs_reference(merged, 0)), wid
    finally:
        fleet.close()


def test_worker_refuses_controller_behind_its_journal(small, tmp_path):
    """Split-brain guard: a worker whose journal holds generations a
    hello'ing controller's journal does not must refuse the hello —
    a wiped/wrong-dir controller cannot re-sequence acked history."""
    g, _sh = small
    root = str(tmp_path / "fleet")
    fleet = start_live_fleet(1, g, parts=2, cap=512,
                             standing=(("sssp", 0),),
                             journal_root=root)
    try:
        ctl = fleet.controller
        for b in _batches(g, 2):
            ctl.admit_writes(*b)
        ctl.kill()
        # the promoted impostor lost the journal: a FRESH dir at gen 0
        ctl2 = LiveFleetController(
            g, journal_dir=str(tmp_path / "wiped"))
        w = fleet.thread_workers[0]
        with pytest.raises(WorkerRefusedError,
                           match="behind my own journal"):
            ctl2.add_worker("127.0.0.1", w.port)
        # takeover records the refusal instead of retrying forever
        rep = ctl2.takeover([("127.0.0.1", w.port)], deadline_s=5.0)
        assert rep["joined"] == [] and len(rep["refused"]) == 1
        assert "behind my own journal" in next(iter(
            rep["refused"].values()))
        ctl2.close()
        # the REAL successor (authoritative journal dir) is accepted
        ctl3, rep3 = promote_live_controller(
            g, os.path.join(root, "controller"), None,
            [("127.0.0.1", w.port)])
        fleet.controller = ctl3
        assert rep3["joined"] == [w.worker_id]
        assert ctl3.generation() == 2
    finally:
        fleet.close()


# ----------------------------------------------------------------------
# chaos soak
# ----------------------------------------------------------------------


def test_chaos_soak_fixed_seed():
    """The tier-1 chaos instance: one fixed seed, wire faults + worker
    kill/rejoin + bounded and stale reads, every standing invariant
    asserted (failures print seed + plan — the reproduction)."""
    rep = chaos_soak(seed=0, steps=10)
    assert rep["generation"] >= 1 and rep["writes"] >= 1
    assert rep["reads"] >= 1


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(1, 21))
def test_chaos_soak_many_seeds(seed):
    """The acceptance sweep: >= 20 distinct seeds in the slow tier
    (every third seed also kills + promotes the controller)."""
    rep = chaos_soak(seed=seed, steps=14,
                     controller_kill=(seed % 3 == 0))
    assert rep["generation"] >= 1
