"""Distributed Pallas pull (method=pallas over the mesh): must agree with
the all_gather+scan engine — the reduce strategy is an execution detail.
Runs in interpret mode on the CPU mesh (the Mosaic compile is validated
on hardware by tools/tpu_pallas_check.py)."""
from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from lux_tpu.graph import generate
from lux_tpu.models import pagerank as pr
from lux_tpu.parallel import pallas_dist as pd
from lux_tpu.parallel.mesh import make_mesh


@pytest.mark.parametrize("parts", [2, 4])
def test_pallas_dist_matches_scan(parts):
    g = generate.rmat(9, 8, seed=21)
    base = pr.pagerank(g, num_iters=4, num_parts=parts)

    pp = pd.build_pallas_parts(g, parts, v_blk=128, t_chunk=128)
    prog = pr.PageRankProgram(nv=pp.spec.nv)
    s0 = pd.init_state_pallas(prog, pp)
    mesh = make_mesh(parts)
    out = pd.run_pull_fixed_pallas_dist(
        prog, pp, s0, 4, mesh, interpret=True
    )
    got = pp.scatter_to_global(np.asarray(out))
    np.testing.assert_allclose(
        got.astype(np.float64), np.asarray(base, np.float64),
        rtol=1e-5, atol=1e-8,
    )


def test_pallas_dist_uneven_parts():
    """Parts with empty padded tail blocks + ragged chunk counts."""
    g = generate.rmat(8, 4, seed=23)  # sparse: ragged per-part chunks
    pp = pd.build_pallas_parts(g, 3, v_blk=128, t_chunk=128)
    assert pp.arrays.e_src_pos.shape[0] == 3
    prog = pr.PageRankProgram(nv=pp.spec.nv)
    s0 = pd.init_state_pallas(prog, pp)
    out = pd.run_pull_fixed_pallas_dist(
        prog, pp, s0, 3, make_mesh(3), interpret=True
    )
    got = pp.scatter_to_global(np.asarray(out))
    base = pr.pagerank(g, num_iters=3)
    np.testing.assert_allclose(
        got.astype(np.float64), np.asarray(base, np.float64),
        rtol=1e-5, atol=1e-8,
    )


def test_pallas_dist_rejects_min_programs():
    from lux_tpu.models.components import MaxLabelProgram

    g = generate.rmat(6, 4, seed=1)
    pp = pd.build_pallas_parts(g, 2)
    with pytest.raises(ValueError, match="sum-reduce"):
        pd.run_pull_fixed_pallas_dist(
            MaxLabelProgram(), pp, None, 1, make_mesh(2)
        )


def test_cf_pallas_dist_matches_scan():
    from lux_tpu.graph import generate as gen
    from lux_tpu.models import colfilter as cf

    gw = gen.bipartite_ratings(128, 128, 2048, seed=31)
    base = cf.colfilter(gw, num_iters=4, num_parts=2)

    pp = pd.build_pallas_parts(gw, 2, v_blk=128, t_chunk=128)
    prog = cf.CFProgram()
    s0 = pd.init_state_pallas(prog, pp)
    out = pd.run_cf_pallas_dist(prog, pp, s0, 4, make_mesh(2), interpret=True)
    got = pp.scatter_to_global(np.asarray(out))
    np.testing.assert_allclose(
        got.astype(np.float64), np.asarray(base, np.float64),
        rtol=2e-4, atol=1e-6,
    )
