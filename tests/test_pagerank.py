"""PageRank vs oracles: the numpy recurrence oracle and scipy.sparse SpMV."""
import numpy as np
import pytest

from lux_tpu.graph import generate
from lux_tpu.models import pagerank as pr


@pytest.mark.parametrize("num_parts", [1, 3])
def test_pagerank_matches_oracle(num_parts):
    g = generate.rmat(9, 8, seed=42)
    got = pr.pagerank(g, num_iters=10, num_parts=num_parts)
    want = pr.pagerank_reference(g, 10)
    np.testing.assert_allclose(got, want, rtol=3e-5, atol=1e-10)


def test_pagerank_scipy_oracle():
    """Independent oracle: scipy CSR matvec of the same recurrence."""
    scipy_sparse = pytest.importorskip("scipy.sparse")
    g = generate.uniform_random(500, 4000, seed=6)
    deg = g.out_degrees().astype(np.float64)
    A = scipy_sparse.csr_matrix(
        (np.ones(g.ne), g.col_idx, g.row_ptr), shape=(g.nv, g.nv)
    )  # A[v, u] counts edges u -> v
    state = np.where(deg > 0, (1 / g.nv) / np.maximum(deg, 1), 1 / g.nv)
    for _ in range(5):
        acc = A @ state
        rank = 0.85 / g.nv + 0.15 * acc
        state = np.where(deg > 0, rank / np.maximum(deg, 1), rank)
    got = pr.pagerank(g, num_iters=5)
    np.testing.assert_allclose(got, state.astype(np.float32), rtol=3e-5)


def test_pagerank_star():
    """Hand-checkable: star graph, center 0 -> all others."""
    g = generate.star_graph(5, center=0)
    got = pr.pagerank(g, num_iters=1)
    nv, alpha = 5, 0.15
    # init: center pre-divided by deg 4; leaves deg 0 undivided
    c0 = (1 / nv) / 4
    # after 1 iter: leaves get acc=c0; center acc=0
    want = np.full(nv, (1 - alpha) / nv + alpha * c0, np.float32)
    want[0] = ((1 - alpha) / nv) / 4
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_pagerank_mass_conservation():
    """Sum of undivided ranks stays ~1 on a graph with no dangling vertices."""
    g = generate.uniform_random(300, 6000, seed=9)
    assert g.out_degrees().min() > 0
    state = pr.pagerank(g, num_iters=20)
    undivided = state * g.out_degrees()
    assert abs(undivided.sum() - 1.0) < 1e-3


@pytest.mark.parametrize("method", ["scan", "scatter"])
def test_pagerank_methods_agree(method):
    g = generate.rmat(8, 6, seed=10)
    base = pr.pagerank(g, num_iters=5, method="scan")
    got = pr.pagerank(g, num_iters=5, method=method)
    np.testing.assert_allclose(got, base, rtol=1e-6)


def test_pagerank_bf16_close_to_f32():
    g = generate.rmat(9, 8, seed=12)
    f32 = pr.pagerank(g, num_iters=8)
    bf16 = pr.pagerank(g, num_iters=8, dtype="bfloat16")
    # bf16 state storage: ~3 decimal digits; accumulate stays f32
    np.testing.assert_allclose(bf16, f32, rtol=2e-2)
