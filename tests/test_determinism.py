"""Bitwise determinism: identical reruns must produce identical bits.

The reference embraces benign atomics races (atomicAdd float ordering,
sparse-queue duplicate suppression, sssp_gpu.cu:74-81) so its float results
vary run to run.  lux_tpu replaces every atomic with deterministic
segmented reductions and exact queue compaction (SURVEY.md §5: "add a
determinism test the reference could never pass") — so byte equality is a
hard invariant here, including across the distributed paths.
"""
import numpy as np

from lux_tpu.graph import generate
from lux_tpu.models import colfilter as cf, components, pagerank as pr, sssp
from lux_tpu.parallel import mesh as mesh_lib


def bits(a):
    return np.asarray(a).view(np.uint8).tobytes()


def test_pagerank_bitwise_deterministic():
    g = generate.rmat(9, 8, seed=100)
    a = pr.pagerank(g, num_iters=10)
    b = pr.pagerank(g, num_iters=10)
    assert bits(a) == bits(b)


def test_pagerank_dist_bitwise_deterministic():
    g = generate.rmat(9, 8, seed=101)
    mesh = mesh_lib.make_mesh(8)
    from lux_tpu.engine import pull
    from lux_tpu.graph.shards import build_pull_shards
    from lux_tpu.parallel import dist

    shards = build_pull_shards(g, 8)
    prog = pr.PageRankProgram(nv=shards.spec.nv)
    s0 = pull.init_state(prog, shards.arrays)
    a = dist.run_pull_fixed_dist(prog, shards.spec, shards.arrays, s0, 6, mesh)
    b = dist.run_pull_fixed_dist(prog, shards.spec, shards.arrays, s0, 6, mesh)
    assert bits(a) == bits(b)


def test_sssp_and_cc_bitwise_deterministic():
    g = generate.rmat(9, 8, seed=102)
    assert bits(sssp.sssp(g, start=0)) == bits(sssp.sssp(g, start=0))
    assert bits(components.connected_components_push(g)) == bits(
        components.connected_components_push(g)
    )


def test_cf_bitwise_deterministic():
    g = generate.bipartite_ratings(50, 40, 600, seed=103)
    a = cf.colfilter(g, num_iters=8, gamma=1e-3)
    b = cf.colfilter(g, num_iters=8, gamma=1e-3)
    assert bits(a) == bits(b)

def test_pallas_dist_bitwise_deterministic():
    """The distributed Pallas engines rerun bitwise-identically (the MXU
    one-hot reduce has a fixed accumulation order, like every other
    engine — no atomics anywhere)."""
    from lux_tpu.parallel import pallas_dist as pd

    g = generate.rmat(8, 8, seed=104)
    mesh = mesh_lib.make_mesh(4)
    pp = pd.build_pallas_parts(g, 4, v_blk=128, t_chunk=128)
    prog = pr.PageRankProgram(nv=pp.spec.nv)
    s0 = pd.init_state_pallas(prog, pp)
    a = pd.run_pull_fixed_pallas_dist(prog, pp, s0, 5, mesh, interpret=True)
    b = pd.run_pull_fixed_pallas_dist(prog, pp, s0, 5, mesh, interpret=True)
    assert bits(a) == bits(b)

    gw = generate.bipartite_ratings(64, 64, 800, seed=105)
    ppw = pd.build_pallas_parts(gw, 4, v_blk=128, t_chunk=128)
    cprog = cf.CFProgram()
    cs0 = pd.init_state_pallas(cprog, ppw)
    ca = pd.run_cf_pallas_dist(cprog, ppw, cs0, 5, mesh, interpret=True)
    cb = pd.run_cf_pallas_dist(cprog, ppw, cs0, 5, mesh, interpret=True)
    assert bits(ca) == bits(cb)


def test_sorted_layout_bitwise_deterministic():
    """The sort-segments relayout is a FIXED deterministic ordering:
    reruns on the sorted layout are bitwise identical (the float sums
    may differ from the unsorted layout — that is a layout choice, not
    nondeterminism)."""
    import jax

    from lux_tpu.engine import pull
    from lux_tpu.graph.shards import build_pull_shards

    g = generate.rmat(9, 8, seed=104)
    outs = []
    for _ in range(2):
        sh = build_pull_shards(g, 2, sort_segments=True)
        prog = pr.PageRankProgram(nv=sh.spec.nv)
        arr = jax.tree.map(np.asarray, sh.arrays)
        s0 = pull.init_state(prog, arr)
        outs.append(
            sh.scatter_to_global(
                np.asarray(pull.run_pull_fixed(prog, sh.spec, arr, s0, 6))
            )
        )
    assert bits(outs[0]) == bits(outs[1])


def test_scatter_k_resident_bitwise_deterministic():
    """k-resident reduce_scatter (lane pre-sum + tiled psum_scatter):
    bitwise-identical reruns — the collective's reduction order is fixed
    by the mesh, not by a race."""
    from lux_tpu.engine import pull
    from lux_tpu.parallel import scatter as scatter_mod

    g = generate.rmat(9, 8, seed=105)
    mesh = mesh_lib.make_mesh(8)
    ss = scatter_mod.build_scatter_shards(g, 16)
    prog = pr.PageRankProgram(nv=ss.spec.nv)
    s0 = pull.init_state(prog, ss.pull.arrays)
    a = scatter_mod.run_pull_fixed_scatter(prog, ss, s0, 6, mesh)
    b = scatter_mod.run_pull_fixed_scatter(prog, ss, s0, 6, mesh)
    assert bits(np.asarray(a)) == bits(np.asarray(b))
