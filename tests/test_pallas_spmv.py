"""Pallas block-CSR SpMV kernel vs oracles (interpret mode on CPU)."""
import numpy as np
import jax.numpy as jnp
import pytest

from lux_tpu.graph import generate
from lux_tpu.models import pagerank as pr
from lux_tpu.ops import pallas_spmv as ps


def test_blockcsr_layout_covers_all_edges():
    g = generate.rmat(9, 8, seed=80)
    bc = ps.build_blockcsr(g, v_blk=128, t_chunk=128)
    real = bc.e_dst_rel < bc.v_blk
    assert int(real.sum()) == g.ne
    # reconstruct (src, dst) pairs and compare to the CSC edge set
    dst_global = bc.e_dst_rel + bc.chunk_block[:, None] * bc.v_blk
    got = np.stack([bc.e_src_pos[real], dst_global[real]], 1)
    expect = np.stack([g.col_idx, g.dst_of_edges()], 1)
    np.testing.assert_array_equal(
        got[np.lexsort(got.T)], expect[np.lexsort(expect.T)]
    )


@pytest.mark.parametrize("op", ["sum", "min", "max"])
def test_spmv_kernel_matches_oracle(op):
    g = generate.rmat(8, 6, seed=81)
    bc = ps.build_blockcsr(g, v_blk=128, t_chunk=128)
    rng = np.random.default_rng(82)
    state = rng.random(g.nv).astype(np.float32)
    vals = state[bc.e_src_pos]
    neutral = {"sum": 0.0, "min": np.inf, "max": -np.inf}[op]
    # padding needs no masking: dst_rel == v_blk matches no one-hot row
    out = ps.spmv_blockcsr(
        jnp.asarray(vals), jnp.asarray(bc.e_dst_rel),
        jnp.asarray(bc.chunk_block), jnp.asarray(bc.chunk_first),
        op=op, v_blk=bc.v_blk, num_vblocks=bc.num_vblocks, interpret=True,
    )
    # oracle
    fn = {"sum": np.add, "min": np.minimum, "max": np.maximum}[op]
    expect = np.full(bc.num_vblocks * bc.v_blk, neutral, np.float32)
    dst = g.dst_of_edges()
    for e in range(g.ne):
        expect[dst[e]] = fn(expect[dst[e]], state[g.col_idx[e]])
    got = np.asarray(out)
    np.testing.assert_allclose(got[: g.nv], expect[: g.nv], rtol=2e-5)


def test_pagerank_pallas_step_matches_reference():
    g = generate.rmat(8, 8, seed=83)
    bc = ps.build_blockcsr(g, v_blk=128, t_chunk=128)
    deg_small = g.out_degrees()
    nvp = bc.num_vblocks * bc.v_blk
    degree = np.zeros(nvp, np.int32)
    degree[: g.nv] = deg_small
    state = np.zeros(nvp, np.float32)
    state[: g.nv] = np.where(
        deg_small > 0, (1.0 / g.nv) / np.maximum(deg_small, 1), 1.0 / g.nv
    )
    new = ps.pagerank_step_pallas(
        bc, jnp.asarray(state), jnp.asarray(degree), g.nv, interpret=True
    )
    want = pr.pagerank_reference(g, 1)
    np.testing.assert_allclose(np.asarray(new)[: g.nv], want, rtol=3e-5)

def test_pagerank_pallas_full_run():
    g = generate.rmat(8, 8, seed=84)
    got = pr.pagerank_pallas(g, num_iters=5, interpret=True, v_blk=128, t_chunk=128)
    want = pr.pagerank_reference(g, 5)
    np.testing.assert_allclose(got, want, rtol=3e-5)


def test_spmv2d_matches_segment_sum():
    g = generate.uniform_random(100, 900, seed=85)
    bc = ps.build_blockcsr(g, v_blk=128, t_chunk=128)
    K = 8
    rng = np.random.default_rng(86)
    state = rng.random((g.nv, K)).astype(np.float32)
    vals = state[bc.e_src_pos]  # (C, T, K); padding rows drop via one-hot
    out = ps.spmv_blockcsr_2d(
        jnp.asarray(vals), jnp.asarray(bc.e_dst_rel),
        jnp.asarray(bc.chunk_block), jnp.asarray(bc.chunk_first),
        v_blk=bc.v_blk, num_vblocks=bc.num_vblocks, interpret=True,
    )
    expect = np.zeros((g.nv, K), np.float32)
    np.add.at(expect, g.dst_of_edges(), state[g.col_idx])
    np.testing.assert_allclose(np.asarray(out)[: g.nv], expect, rtol=2e-5)


def test_colfilter_pallas_matches_reference():
    from lux_tpu.models import colfilter as cf

    g = generate.bipartite_ratings(60, 40, 700, seed=87)
    got = cf.colfilter_pallas(g, num_iters=4, interpret=True, gamma=1e-3,
                              v_blk=128, t_chunk=128)
    want = cf.colfilter_reference(g, 4, gamma=1e-3)
    np.testing.assert_allclose(got, want, rtol=3e-5, atol=1e-7)


def test_pallas_pagerank_bf16():
    """bf16 state + bf16 MXU inputs (f32 accumulation) tracks the f32
    kernel within bf16 resolution."""
    from lux_tpu.models.pagerank import make_pallas_runner

    g = generate.rmat(8, 6, seed=40)
    run32, s32 = make_pallas_runner(g, interpret=True)
    run16, s16 = make_pallas_runner(g, interpret=True, dtype="bfloat16")
    a = np.asarray(run32(s32, 3))[: g.nv]
    b = np.asarray(run16(s16, 3)).astype(np.float32)[: g.nv]
    np.testing.assert_allclose(b, a, rtol=2e-2, atol=1e-5)


@pytest.mark.parametrize("v_blk,t_chunk", [
    (128, 256), (256, 128), (512, 512), (128, 1024), (256, 512),
])
@pytest.mark.parametrize("op", ["sum", "min"])
def test_spmv_tile_shape_sweep(v_blk, t_chunk, op):
    """The exact (v_blk, t_chunk) grid the chip battery sweeps
    (tpu_pallas_check --sweep), semantics-validated in interpret mode so
    a Mosaic run can only differ by lowering, never by math.  Graph
    includes a hub vertex and empty rows (the power-law shapes of
    SURVEY.md §7.3)."""
    g = generate.rmat(9, 4, seed=84)
    bc = ps.build_blockcsr(g, v_blk=v_blk, t_chunk=t_chunk)
    rng = np.random.default_rng(85)
    state = rng.random(g.nv).astype(np.float32)
    vals = state[bc.e_src_pos]
    out = ps.spmv_blockcsr(
        jnp.asarray(vals), jnp.asarray(bc.e_dst_rel),
        jnp.asarray(bc.chunk_block), jnp.asarray(bc.chunk_first),
        op=op, v_blk=bc.v_blk, num_vblocks=bc.num_vblocks, interpret=True,
    )
    neutral = {"sum": 0.0, "min": np.inf}[op]
    expect = np.full(bc.num_vblocks * bc.v_blk, neutral, np.float32)
    dst = g.dst_of_edges()
    np_fn = {"sum": "add", "min": "minimum"}[op]
    getattr(np, np_fn).at(expect, dst, state[g.col_idx])
    np.testing.assert_allclose(
        np.asarray(out)[: g.nv], expect[: g.nv], rtol=2e-5
    )


def test_spmv_hub_and_empty_rows():
    """Degenerate shapes: one vertex owning most in-edges (a chunk run
    crossing many T boundaries) and zero-degree vertices — the ragged
    cases the reference's block-scan trick handles (SURVEY.md §7.3)."""
    nv = 300
    src = np.concatenate([
        np.arange(250, dtype=np.int64),          # hub: 250 edges -> v7
        np.array([1, 2, 3], dtype=np.int64),     # a few scattered edges
    ])
    dst = np.concatenate([
        np.full(250, 7, dtype=np.int64),
        np.array([100, 100, 299], dtype=np.int64),
    ])
    from lux_tpu.graph.csc import from_edge_list

    g = from_edge_list(src, dst, nv)
    bc = ps.build_blockcsr(g, v_blk=128, t_chunk=128)
    state = np.arange(1, nv + 1, dtype=np.float32)
    vals = state[bc.e_src_pos]
    out = ps.spmv_blockcsr(
        jnp.asarray(vals), jnp.asarray(bc.e_dst_rel),
        jnp.asarray(bc.chunk_block), jnp.asarray(bc.chunk_first),
        op="sum", v_blk=bc.v_blk, num_vblocks=bc.num_vblocks,
        interpret=True,
    )
    expect = np.zeros(bc.num_vblocks * bc.v_blk, np.float32)
    np.add.at(expect, g.dst_of_edges(), state[g.col_idx])
    np.testing.assert_allclose(np.asarray(out)[:nv], expect[:nv], rtol=2e-5)
    assert expect[7] == state[:250].sum()  # the hub really crossed chunks
