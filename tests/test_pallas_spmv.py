"""Pallas block-CSR SpMV kernel vs oracles (interpret mode on CPU)."""
import numpy as np
import jax.numpy as jnp
import pytest

from lux_tpu.graph import generate
from lux_tpu.models import pagerank as pr
from lux_tpu.ops import pallas_spmv as ps


def test_blockcsr_layout_covers_all_edges():
    g = generate.rmat(9, 8, seed=80)
    bc = ps.build_blockcsr(g, v_blk=128, t_chunk=128)
    real = bc.e_dst_rel < bc.v_blk
    assert int(real.sum()) == g.ne
    # reconstruct (src, dst) pairs and compare to the CSC edge set
    dst_global = bc.e_dst_rel + bc.chunk_block[:, None] * bc.v_blk
    got = np.stack([bc.e_src_pos[real], dst_global[real]], 1)
    expect = np.stack([g.col_idx, g.dst_of_edges()], 1)
    np.testing.assert_array_equal(
        got[np.lexsort(got.T)], expect[np.lexsort(expect.T)]
    )


@pytest.mark.parametrize("op", ["sum", "min", "max"])
def test_spmv_kernel_matches_oracle(op):
    g = generate.rmat(8, 6, seed=81)
    bc = ps.build_blockcsr(g, v_blk=128, t_chunk=128)
    rng = np.random.default_rng(82)
    state = rng.random(g.nv).astype(np.float32)
    vals = state[bc.e_src_pos]
    neutral = {"sum": 0.0, "min": np.inf, "max": -np.inf}[op]
    # padding needs no masking: dst_rel == v_blk matches no one-hot row
    out = ps.spmv_blockcsr(
        jnp.asarray(vals), jnp.asarray(bc.e_dst_rel),
        jnp.asarray(bc.chunk_block), jnp.asarray(bc.chunk_first),
        op=op, v_blk=bc.v_blk, num_vblocks=bc.num_vblocks, interpret=True,
    )
    # oracle
    fn = {"sum": np.add, "min": np.minimum, "max": np.maximum}[op]
    expect = np.full(bc.num_vblocks * bc.v_blk, neutral, np.float32)
    dst = g.dst_of_edges()
    for e in range(g.ne):
        expect[dst[e]] = fn(expect[dst[e]], state[g.col_idx[e]])
    got = np.asarray(out)
    np.testing.assert_allclose(got[: g.nv], expect[: g.nv], rtol=2e-5)


def test_pagerank_pallas_step_matches_reference():
    g = generate.rmat(8, 8, seed=83)
    bc = ps.build_blockcsr(g, v_blk=128, t_chunk=128)
    deg_small = g.out_degrees()
    nvp = bc.num_vblocks * bc.v_blk
    degree = np.zeros(nvp, np.int32)
    degree[: g.nv] = deg_small
    state = np.zeros(nvp, np.float32)
    state[: g.nv] = np.where(
        deg_small > 0, (1.0 / g.nv) / np.maximum(deg_small, 1), 1.0 / g.nv
    )
    new = ps.pagerank_step_pallas(
        bc, jnp.asarray(state), jnp.asarray(degree), g.nv, interpret=True
    )
    want = pr.pagerank_reference(g, 1)
    np.testing.assert_allclose(np.asarray(new)[: g.nv], want, rtol=3e-5)

def test_pagerank_pallas_full_run():
    g = generate.rmat(8, 8, seed=84)
    got = pr.pagerank_pallas(g, num_iters=5, interpret=True, v_blk=128, t_chunk=128)
    want = pr.pagerank_reference(g, 5)
    np.testing.assert_allclose(got, want, rtol=3e-5)


def test_spmv2d_matches_segment_sum():
    g = generate.uniform_random(100, 900, seed=85)
    bc = ps.build_blockcsr(g, v_blk=128, t_chunk=128)
    K = 8
    rng = np.random.default_rng(86)
    state = rng.random((g.nv, K)).astype(np.float32)
    vals = state[bc.e_src_pos]  # (C, T, K); padding rows drop via one-hot
    out = ps.spmv_blockcsr_2d(
        jnp.asarray(vals), jnp.asarray(bc.e_dst_rel),
        jnp.asarray(bc.chunk_block), jnp.asarray(bc.chunk_first),
        v_blk=bc.v_blk, num_vblocks=bc.num_vblocks, interpret=True,
    )
    expect = np.zeros((g.nv, K), np.float32)
    np.add.at(expect, g.dst_of_edges(), state[g.col_idx])
    np.testing.assert_allclose(np.asarray(out)[: g.nv], expect, rtol=2e-5)


def test_colfilter_pallas_matches_reference():
    from lux_tpu.models import colfilter as cf

    g = generate.bipartite_ratings(60, 40, 700, seed=87)
    got = cf.colfilter_pallas(g, num_iters=4, interpret=True, gamma=1e-3,
                              v_blk=128, t_chunk=128)
    want = cf.colfilter_reference(g, 4, gamma=1e-3)
    np.testing.assert_allclose(got, want, rtol=3e-5, atol=1e-7)


def test_pallas_pagerank_bf16():
    """bf16 state + bf16 MXU inputs (f32 accumulation) tracks the f32
    kernel within bf16 resolution."""
    from lux_tpu.models.pagerank import make_pallas_runner

    g = generate.rmat(8, 6, seed=40)
    run32, s32 = make_pallas_runner(g, interpret=True)
    run16, s16 = make_pallas_runner(g, interpret=True, dtype="bfloat16")
    a = np.asarray(run32(s32, 3))[: g.nv]
    b = np.asarray(run16(s16, 3)).astype(np.float32)[: g.nv]
    np.testing.assert_allclose(b, a, rtol=2e-2, atol=1e-5)
