"""luxwire-trace (ISSUE 15): distributed request tracing, the
luxstitch causal timeline, and the SLO burn-rate engine.

Pins the acceptance surface: (a) trace contexts are minted at the
fleet entry points, carried on every frame, and recorded as span attrs
whose parent links survive the wire — a query's stitched chain is
``fleet.request -> fleet.attempt -> worker.query``; (b) identity is
deterministic under retries — the kill-mid-write drill's original
admit, the failover takeover's re-hellos, and the dedup-acked replay
stitch into ONE timeline with causal parent links asserted; (c)
luxstitch's clock-skew correction recovers a synthetic cross-machine
offset from the wire's send/recv pairs; (d) SLOs evaluate as
multi-window burn rates with trace-id exemplars, and the Prometheus
surface (scrape() freshness, exemplar suffixes, journal/lag gauges,
merged exposition across a failover) parses with an in-test minimal
Prometheus text parser.
"""
import importlib.util
import json
import os
import threading
import time

import numpy as np
import pytest

from lux_tpu import fault, obs
from lux_tpu.fault.plan import FaultPlan, FaultRule
from lux_tpu.graph import generate
from lux_tpu.graph.shards import build_pull_shards
from lux_tpu.models.sssp import bfs_reference
from lux_tpu.obs import dtrace
from lux_tpu.obs.recorder import Recorder
from lux_tpu.obs.slo import (
    SLOEngine,
    SLOSpec,
    SLOSpecError,
    default_fleet_slos,
    specs_from_json,
)
from lux_tpu.serve.fleet.bench import start_fleet
from lux_tpu.serve.live.controller import (
    promote_live_controller,
    start_live_fleet,
)
from lux_tpu.serve.metrics import ServeMetrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _clean_dtrace_state():
    yield
    dtrace.set_enabled(None)
    fault.uninstall()


@pytest.fixture(scope="module")
def small():
    g = generate.rmat(8, 6, seed=9)
    return g, build_pull_shards(g, 2)


@pytest.fixture()
def rec(tmp_path):
    r = Recorder(run_id="dtr", root=str(tmp_path), enabled=True)
    old = obs.install(r)
    yield r
    r.close()
    obs.install(old)


def read_events(run_dir):
    evs = []
    if not os.path.isdir(run_dir):  # lazy open: nothing written yet
        return evs
    for fn in sorted(os.listdir(run_dir)):
        if fn.startswith("events-") and fn.endswith(".jsonl"):
            with open(os.path.join(run_dir, fn), encoding="utf-8") as f:
                evs.extend(json.loads(ln) for ln in f if ln.strip())
    return evs


def spans_by_name(evs):
    out = {}
    for ev in evs:
        if ev.get("e") == "b":
            out.setdefault(ev["n"], []).append(ev)
    return out


# ----------------------------------------------------------------------
# a minimal Prometheus text parser (the satellite's round-trip oracle)
# ----------------------------------------------------------------------


def prom_parse(text):
    """Strict-enough parser: returns {family: {"help":…, "type":…,
    "samples": [(name, labels_dict, value)]}}.  Enforces the rules the
    exposition format actually has — HELP/TYPE at most once per family,
    samples grouped under their family, every sample line parseable —
    and strips OpenMetrics exemplar suffixes (`# {...} v`)."""
    fams = {}
    cur = None
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            kind = line[2:6].strip().lower()
            rest = line.split(" ", 3)
            fam, payload = rest[2], rest[3] if len(rest) > 3 else ""
            ent = fams.setdefault(fam, {"help": None, "type": None,
                                        "samples": []})
            assert ent[kind] is None, \
                f"{kind.upper()} repeated for family {fam}"
            ent[kind] = payload
            cur = fam
            continue
        assert not line.startswith("#"), f"stray comment: {line!r}"
        sample = line
        if " # {" in sample:  # exemplar suffix
            sample = sample.split(" # {", 1)[0]
        if "{" in sample:
            name = sample.split("{", 1)[0]
            labels_raw = sample.split("{", 1)[1].rsplit("}", 1)[0]
            value = sample.rsplit("}", 1)[1].strip()
            labels = {}
            for pair in filter(None, labels_raw.split(",")):
                k, v = pair.split("=", 1)
                labels[k] = v.strip('"')
        else:
            parts = sample.split()
            assert len(parts) == 2, f"bad sample line: {line!r}"
            name, value = parts
            labels = {}
        float(value)  # must parse
        base = name
        for sfx in ("_bucket", "_sum", "_count"):
            if base.endswith(sfx):
                base = base[: -len(sfx)]
        fam = base if base in fams else name
        assert cur is not None and fam in fams, \
            f"sample {name} before any HELP/TYPE"
        fams[fam]["samples"].append((name, labels, float(value)))
    return fams


# ----------------------------------------------------------------------
# context mechanics
# ----------------------------------------------------------------------


def test_mint_deterministic_from_key():
    a = dtrace.mint(key="w:write-7")
    b = dtrace.mint(key="w:write-7")
    c = dtrace.mint(key="w:write-8")
    assert a.trace_id == b.trace_id and a.span_id == b.span_id
    assert c.trace_id != a.trace_id
    assert a.parent_span_id is None and a.sampled
    # random mints differ
    assert dtrace.mint().trace_id != dtrace.mint().trace_id


def test_child_links_and_wire_round_trip():
    root = dtrace.mint(key="q:r1")
    ch = root.child()
    assert ch.trace_id == root.trace_id
    assert ch.parent_span_id == root.span_id
    assert ch.span_id != root.span_id
    back = dtrace.TraceContext.from_wire(ch.to_wire())
    assert (back.trace_id, back.span_id, back.parent_span_id,
            back.flags) == (ch.trace_id, ch.span_id,
                            ch.parent_span_id, ch.flags)
    assert dtrace.TraceContext.from_wire({"nope": 1}) is None
    assert dtrace.wire_ctx({"op": "query"}) is None
    got = dtrace.child_of({"tc": root.to_wire()})
    assert got.parent_span_id == root.span_id


def test_disable_and_sampling(monkeypatch):
    dtrace.set_enabled(False)
    assert dtrace.mint(key="x") is None
    dtrace.set_enabled(True)
    assert dtrace.mint(key="x") is not None
    dtrace.set_enabled(None)
    monkeypatch.setenv("LUX_DTRACE", "0")
    assert dtrace.mint() is None
    monkeypatch.setenv("LUX_DTRACE", "1")
    # rate 0: context still propagates, but unsampled (no recording)
    monkeypatch.setenv("LUX_DTRACE_SAMPLE", "0.0")
    ctx = dtrace.mint(key="y")
    assert ctx is not None and not ctx.sampled
    assert not ctx.child().sampled  # flags propagate
    monkeypatch.setenv("LUX_DTRACE_SAMPLE", "1.0")
    assert dtrace.mint(key="y").sampled
    # the decision is derived from the trace id: every process (and
    # every retry of a keyed trace) agrees without coordination
    monkeypatch.setenv("LUX_DTRACE_SAMPLE", "0.5")
    draws = {dtrace.mint(key="z").sampled for _ in range(4)}
    assert len(draws) == 1
    monkeypatch.setenv("LUX_DTRACE_SAMPLE", "2.0")
    with pytest.raises(ValueError):
        dtrace.mint()


def test_emit_span_is_stack_neutral(rec):
    rec.emit_span("retro", 1.0, 2.0, ok=True, attrs={"k": 1})
    with rec.span("normal"):
        pass
    rec.flush()
    evs = read_events(rec.run_dir())
    by = spans_by_name(evs)
    assert by["retro"][0]["p"] is None
    # the retroactive span must NOT become the next span's parent
    assert by["normal"][0]["p"] is None
    assert rec.total_count("retro") == 1
    ends = {e["s"]: e for e in evs if e.get("e") == "e"}
    assert ends[by["retro"][0]["s"]]["t"] == 2.0


def test_tspan_unsampled_records_nothing(rec):
    ctx = dtrace.TraceContext("t0", "s0", flags=0)
    with dtrace.tspan("quiet", ctx, a=1) as sp:
        sp.set(b=2)
    dtrace.emit_span("quiet2", ctx, 0.0, 1.0)
    rec.flush()
    assert not [e for e in read_events(rec.run_dir())
                if e.get("e") == "b"]
    # ctx=None degrades to a PLAIN span (single-process behavior),
    # and None-valued attrs are dropped from the log
    with dtrace.tspan("plain", None, a=1, b=None):
        pass
    rec.flush()
    by = spans_by_name(read_events(rec.run_dir()))
    assert by["plain"][0]["a"] == {"a": 1}
    assert "trace" not in by["plain"][0].get("a", {})


def test_tspan_always_keeps_operational_spans_when_unsampled(rec):
    """Operational spans (takeover, republish, delta install, hello)
    predate tracing as UNCONDITIONAL recorder spans; head-sampling
    thins the trace store, not the local flight recorder.  always=True
    records the unsampled span PLAIN — present in the post-mortem, no
    trace attrs (never half-trace)."""
    ctx = dtrace.TraceContext("t0", "s0", flags=0)
    with dtrace.tspan("ops.takeover", ctx, always=True, worker="w0"):
        pass
    rec.flush()
    by = spans_by_name(read_events(rec.run_dir()))
    a = by["ops.takeover"][0].get("a", {})
    assert a == {"worker": "w0"}  # recorded, and trace-attr-free
    # a SAMPLED context is unaffected by the flag: full trace attrs
    with dtrace.tspan("ops.traced", dtrace.TraceContext("t1", "s1"),
                      always=True):
        pass
    rec.flush()
    by = spans_by_name(read_events(rec.run_dir()))
    assert by["ops.traced"][0]["a"]["trace"] == "t1"


# ----------------------------------------------------------------------
# luxstitch: skew correction + causal ordering
# ----------------------------------------------------------------------


def _write_log(run_dir, pid, events):
    os.makedirs(run_dir, exist_ok=True)
    with open(os.path.join(run_dir, f"events-{pid}.jsonl"), "w") as f:
        f.write(json.dumps({"e": "m", "run": "syn", "pid": pid,
                            "wall": 0.0, "mono": 0.0}) + "\n")
        for ev in events:
            f.write(json.dumps(ev) + "\n")


def test_stitch_skew_correction_synthetic(tmp_path):
    """Process 2's clock runs 5 s ahead of process 1; traced frames in
    both directions (1 ms transit) must recover the offset and restore
    send-before-recv ordering."""
    run = str(tmp_path / "syn")
    off = 5.0
    # pid 1 at true time t stamps t; pid 2 stamps t + off
    _write_log(run, 1, [
        {"e": "b", "n": "fleet.request", "s": "1-a-1", "p": None,
         "t": 10.0, "a": {"trace": "T", "span": "r0"}},
        {"e": "e", "s": "1-a-1", "t": 10.5, "ok": True},
        {"e": "p", "n": "dtrace.send", "t": 10.010,
         "a": {"trace": "T", "span": "w1", "op": "query"}},
        {"e": "p", "n": "dtrace.recv", "t": 10.111 + off - off,
         "a": {"trace": "T", "span": "w2", "op": "reply"}},
    ])
    _write_log(run, 2, [
        {"e": "p", "n": "dtrace.recv", "t": 10.011 + off,
         "a": {"trace": "T", "span": "w1", "op": "query"}},
        {"e": "b", "n": "worker.query", "s": "2-b-1", "p": None,
         "t": 10.012 + off,
         "a": {"trace": "T", "span": "s1", "parent_span": "r0"}},
        {"e": "e", "s": "2-b-1", "t": 10.100 + off, "ok": True},
        {"e": "p", "n": "dtrace.send", "t": 10.110 + off,
         "a": {"trace": "T", "span": "w2", "op": "reply"}},
    ])
    luxstitch = _load_tool("luxstitch")
    files = luxstitch.load_files(sorted(
        os.path.join(run, f) for f in os.listdir(run)))
    st = luxstitch.stitch(files)
    offs = st["offsets"]
    base, other = offs[1], offs[2]
    # pid 2's correction must be ~-5 s relative to pid 1 (recovered to
    # within the 1 ms transit asymmetry)
    assert abs((other - base) + off) < 0.005, offs
    tr = st["traces"]["T"]
    names = [sp["name"] for sp in tr["spans"]]
    assert names == ["fleet.request", "worker.query"]
    req, wq = tr["spans"]
    assert wq["depth"] == 1 and wq["parent_span"] == "r0"
    # corrected: the worker span starts AFTER the request began and
    # inside its window — on raw clocks it started 5 s "later"
    assert req["g0"] < wq["g0"] < req["g1"]
    out = []
    luxstitch.render_trace("T", tr, out)
    text = "\n".join(out)
    assert "worker.query" in text and "[2]" in text


def test_stitch_cli_and_faults(tmp_path, capsys):
    run = str(tmp_path / "cli")
    _write_log(run, 7, [
        {"e": "b", "n": "live.admit", "s": "7-a-1", "p": None,
         "t": 1.0, "a": {"trace": "W", "span": "a0"}},
        {"e": "e", "s": "7-a-1", "t": 1.2, "ok": True},
        {"e": "p", "n": "fault.inject", "t": 1.1,
         "a": {"plan": "drill", "seed": 3, "site": "proc",
               "action": "kill", "point": "journal.before_marker"}},
    ])
    luxstitch = _load_tool("luxstitch")
    assert luxstitch.main([run]) == 0
    out = capsys.readouterr().out
    assert "live.admit" in out
    # the injected fault is interleaved with plan + seed (satellite)
    assert "FAULT proc/kill" in out and "seed=3" in out
    assert "plan=drill" in out
    js = str(tmp_path / "st.json")
    assert luxstitch.main([run, "--json", js, "--trace", "W"]) == 0
    data = json.load(open(js))
    assert "W" in data["traces"]
    assert luxstitch.main([run, "--trace", "nope"]) == 2
    assert luxstitch.main(["--root", str(tmp_path), "missing_run"]) == 2


# ----------------------------------------------------------------------
# fleet end-to-end: one traced query's causal chain
# ----------------------------------------------------------------------


def test_traced_query_causal_chain(small, rec):
    g, shards = small
    fleet = start_fleet(2, shards=shards, graph_id="g", mode="thread",
                        buckets=(1, 4))
    ctl = fleet.controller
    ctl.set_slos(default_fleet_slos())
    try:
        with fault.installed(FaultPlan([FaultRule(
                "wire.recv", "delay", op="query", delay_ms=2.0)],
                name="delayed", seed=11)):
            fut = ctl.submit(3, request_id="req-1")
            assert np.array_equal(fut.result(timeout=60),
                                  bfs_reference(g, 3))
        assert fut.trace_id == dtrace.mint(key="q:req-1").trace_id
        # worker-side prom carries a latency exemplar naming the trace
        w = (fleet.thread_workers[0]
             if fleet.thread_workers[0].worker_id == fut.worker_id
             else fleet.thread_workers[1])
        assert f'trace_id="{fut.trace_id}"' in w.prom_text()
        slo = ctl.slo_status()
        assert {r["name"] for r in slo} == {
            "read_availability", "read_latency", "read_freshness",
            "write_ack"}
        av = next(r for r in slo if r["name"] == "read_availability")
        assert av["verdict"] == "ok" and av["total"] == 1
        assert av["exemplar_traces"] == [fut.trace_id]
    finally:
        fleet.close()
    rec.flush()
    evs = read_events(rec.run_dir())
    by = spans_by_name(evs)
    req = [e for e in by["fleet.request"]
           if e["a"]["trace"] == fut.trace_id]
    att = [e for e in by["fleet.attempt"]
           if e["a"]["trace"] == fut.trace_id]
    wq = [e for e in by["worker.query"]
          if e["a"]["trace"] == fut.trace_id]
    assert len(req) == 1 and len(att) == 1 and len(wq) == 1
    # THE causal chain: request -> attempt -> worker hop
    assert att[0]["a"]["parent_span"] == req[0]["a"]["span"]
    assert wq[0]["a"]["parent_span"] == att[0]["a"]["span"]
    # wire skew stamps pair per traced frame (request out, reply back)
    pts = [e for e in evs if e.get("e") == "p"
           and e["n"] in ("dtrace.send", "dtrace.recv")
           and e["a"].get("trace") == fut.trace_id]
    sends = {e["a"]["span"] for e in pts if e["n"] == "dtrace.send"}
    recvs = {e["a"]["span"] for e in pts if e["n"] == "dtrace.recv"}
    assert sends and sends == recvs
    # the dispatch batch names the trace it served
    disp = [e for e in by["serve.dispatch"]
            if fut.trace_id in (e["a"].get("traces") or [])]
    assert disp
    # the injected delay is a point in the same log, with its seed
    inj = [e for e in evs if e.get("e") == "p"
           and e["n"] == "fault.inject"]
    assert inj and inj[0]["a"]["seed"] == 11
    # luxstitch groups the whole thing into one causally-ordered trace
    luxstitch = _load_tool("luxstitch")
    st = luxstitch.stitch(luxstitch.load_files(sorted(
        os.path.join(rec.run_dir(), f)
        for f in os.listdir(rec.run_dir()))))
    tr = st["traces"][fut.trace_id]
    chain = [sp["name"] for sp in tr["spans"]]
    assert chain[:3] == ["fleet.request", "fleet.attempt",
                         "worker.query"]
    assert [sp["depth"] for sp in tr["spans"][:3]] == [0, 1, 2]
    assert tr["faults"], "injected fault not interleaved in the trace"


def test_untraced_when_disabled(small, rec):
    g, shards = small
    dtrace.set_enabled(False)
    fleet = start_fleet(1, shards=shards, graph_id="g", mode="thread",
                        buckets=(1, 4))
    try:
        fut = fleet.controller.submit(3)
        assert np.array_equal(fut.result(timeout=60),
                              bfs_reference(g, 3))
        assert fut.trace_id is None
    finally:
        fleet.close()
    rec.flush()
    evs = read_events(rec.run_dir())
    assert not [e for e in evs
                if e.get("e") == "p" and e["n"].startswith("dtrace.")]
    assert "fleet.request" not in spans_by_name(evs)


# ----------------------------------------------------------------------
# ACCEPTANCE: the kill-mid-write drill stitches into one trace
# ----------------------------------------------------------------------


def test_traced_kill_mid_write_failover_one_trace(small, rec, tmp_path):
    """Admit a write under a write_id; kill the controller; promote a
    successor (takeover + re-hellos, all traced); replay the SAME
    write_id and get the dedup ack.  The stitched timeline must show
    ONE write trace — original live.admit, its live.replicate /
    worker.delta hops with causal parent links, and the dedup-acked
    replay — next to the takeover trace whose worker.hello spans link
    under the promoted controller's fleet.takeover span.  Also pins
    the satellite: the successor's FIRST prom_dump is one valid
    exposition (minimal-parser round trip) carrying the re-helloed
    workers' series, the failover counter, and the live gauges."""
    g, _sh = small
    root = str(tmp_path / "fleet")
    snap = os.path.join(root, "snap.lux")
    fleet = start_live_fleet(2, g, parts=2, cap=1024,
                             standing=(("sssp", 0),),
                             journal_root=root, snapshot_path=snap)
    ctl = fleet.controller
    wid = "acc-w0"
    wtrace = dtrace.mint(key=f"w:{wid}").trace_id
    try:
        src = np.array([0, 1]); dst = np.array([3, 4])
        op = np.ones(2, np.int8)
        rep = ctl.admit_writes(src, dst, op, write_id=wid)
        gen = rep["generation"]
        assert rep["deduped"] is False and len(rep["acked"]) == 2
        ctl.kill()  # the controller vanishes mid-service
        eps = [("127.0.0.1", w.port) for w in fleet.thread_workers]
        ctl2, trep = promote_live_controller(
            g, os.path.join(root, "controller"), snap, eps, seed=1)
        fleet.controller = ctl2
        assert sorted(trep["joined"]) == ["w0", "w1"]
        # the client's retry of the SAME logical write: dedup-acked,
        # and — because trace ids are keyed — in the SAME trace
        rep2 = ctl2.admit_writes(src, dst, op, write_id=wid)
        assert rep2["deduped"] is True and rep2["generation"] == gen
        # ---- satellite: the successor's first merged scrape --------
        text = ctl2.prom_dump()
        fams = prom_parse(text)
        assert fams["lux_fleet_failovers_total"]["samples"][0][2] == 1
        lat = fams["lux_serve_request_latency_seconds"]["samples"]
        assert {s[1].get("replica") for s in lat
                if s[1].get("replica")} == {"w0", "w1"}
        depth = fams["lux_live_journal_depth"]["samples"][0][2]
        assert depth == gen  # epoch batches == committed generation
        lag = fams["lux_live_worker_generation_lag"]["samples"]
        assert {s[1]["worker"] for s in lag} == {"w0", "w1"}
        assert all(s[2] == 0 for s in lag)  # fully re-synced
        occ = fams["lux_serve_engine_cache_occupancy"]["samples"]
        assert {s[1]["replica"] for s in occ} == {"w0", "w1"}
        ctl2.close()
    finally:
        fleet.close()
    # ---- the stitched timeline ------------------------------------
    rec.flush()
    evs = read_events(rec.run_dir())
    by = spans_by_name(evs)
    admits = [e for e in by["live.admit"]
              if e["a"].get("trace") == wtrace]
    # original + dedup replay, SAME trace, both under the keyed root
    assert len(admits) == 2
    assert [bool(e["a"].get("deduped")) for e in admits].count(True) == 1
    reps = [e for e in by["live.replicate"]
            if e["a"].get("trace") == wtrace]
    assert len(reps) == 2  # one per worker
    admit_span = admits[0]["a"]["span"]
    assert all(r["a"]["parent_span"] == admit_span for r in reps)
    deltas = [e for e in by["worker.delta"]
              if e["a"].get("trace") == wtrace]
    assert {d["a"]["parent_span"] for d in deltas} <= {
        r["a"]["span"] for r in reps}
    assert {d["a"].get("generation") for d in deltas} == {gen}
    # the dedup point carries the same trace
    dpts = [e for e in evs if e.get("e") == "p"
            and e["n"] == "live.admit.dedup"]
    assert dpts and dpts[0]["a"]["trace"] == wtrace
    # the takeover trace: fleet.takeover -> fleet.hello -> worker.hello
    tko = by["fleet.takeover"][0]
    ttrace = tko["a"]["trace"]
    hellos = [e for e in by["fleet.hello"]
              if e["a"].get("trace") == ttrace]
    assert len(hellos) == 2
    assert all(h["a"]["parent_span"] == tko["a"]["span"]
               for h in hellos)
    whellos = [e for e in by["worker.hello"]
               if e["a"].get("trace") == ttrace]
    assert {w["a"]["parent_span"] for w in whellos} == {
        h["a"]["span"] for h in hellos}
    # luxstitch: ONE write trace containing both admits + the hops
    luxstitch = _load_tool("luxstitch")
    st = luxstitch.stitch(luxstitch.load_files(sorted(
        os.path.join(rec.run_dir(), f)
        for f in os.listdir(rec.run_dir()))))
    tr = st["traces"][wtrace]
    names = [sp["name"] for sp in tr["spans"]]
    assert names.count("live.admit") == 2
    assert "live.replicate" in names and "worker.delta" in names
    assert ttrace in st["traces"]
    tnames = [sp["name"] for sp in st["traces"][ttrace]["spans"]]
    assert tnames[0] == "fleet.takeover"
    assert "worker.hello" in tnames


# ----------------------------------------------------------------------
# SLO engine
# ----------------------------------------------------------------------


def test_slo_spec_validation_and_round_trip():
    s = SLOSpec("lat", "latency", objective=0.95, threshold_ms=100.0)
    assert SLOSpec.from_dict(s.to_dict()).to_dict() == s.to_dict()
    specs = specs_from_json(json.dumps([s.to_dict()]))
    assert specs[0].name == "lat"
    with pytest.raises(SLOSpecError):
        SLOSpec("x", "nope")
    with pytest.raises(SLOSpecError):
        SLOSpec("x", "availability", objective=1.0)
    with pytest.raises(SLOSpecError):
        SLOSpec("x", "latency")  # threshold required
    with pytest.raises(SLOSpecError):
        SLOSpec("x", "availability", windows=())
    with pytest.raises(SLOSpecError):
        SLOSpec.from_dict({"name": "x", "kind": "availability",
                           "bogus": 1})
    with pytest.raises(SLOSpecError):
        specs_from_json("{}")
    with pytest.raises(SLOSpecError):
        SLOEngine([SLOSpec("a", "availability"),
                   SLOSpec("a", "availability")])


def test_slo_burn_rates_multiwindow():
    clock = [0.0]
    eng = SLOEngine([
        SLOSpec("avail", "availability", objective=0.9,
                windows=((10.0, 2.0), (40.0, 1.5))),
    ], clock=lambda: clock[0])
    # 20 s of clean traffic
    for i in range(20):
        clock[0] += 1.0
        eng.observe_query(0.01, ok=True, trace_id=f"g{i}")
    st = eng.status()[0]
    assert st["verdict"] == "ok" and st["total"] == 20
    # exemplar of last resort: the worst traced observation
    assert len(st["exemplar_traces"]) == 1
    # now a hot burst: 50% failures for 10 s -> burn 5.0 in the short
    # window (> 2.0) but the long window still dilutes (warn, not page)
    for i in range(10):
        clock[0] += 1.0
        eng.observe_query(0.01, ok=bool(i % 2), trace_id=f"b{i}")
    st = eng.status()[0]
    short = st["windows"]["10s"]
    assert short["burning"] and short["burn"] > 2.0
    assert st["verdict"] in ("warn", "burning")
    assert st["exemplar_traces"]  # the offending traces
    assert all(t.startswith("b") for t in st["exemplar_traces"])
    # keep failing long enough and BOTH windows burn -> page
    for i in range(30):
        clock[0] += 1.0
        eng.observe_query(0.01, ok=False, trace_id=f"c{i}")
    st = eng.status()[0]
    assert st["verdict"] == "burning"
    assert all(w["burning"] for w in st["windows"].values())


def test_slo_kinds_latency_staleness_write():
    clock = [0.0]
    eng = SLOEngine([
        SLOSpec("lat", "latency", objective=0.5, threshold_ms=50.0,
                windows=((10.0, 0.9),)),
        SLOSpec("fresh", "staleness", objective=0.5,
                windows=((10.0, 1.5),)),
        SLOSpec("wr", "write_latency", objective=0.5,
                threshold_ms=100.0, windows=((10.0, 1.5),)),
    ], clock=lambda: clock[0])
    for i in range(8):
        clock[0] += 0.5
        eng.observe_query(0.2 if i % 2 else 0.001, ok=True,
                          stale=bool(i % 2), trace_id=f"t{i}")
        eng.observe_write(0.001, ok=True, trace_id=f"w{i}")
    rows = {r["name"]: r for r in eng.status()}
    assert rows["lat"]["bad"] == 4 and rows["lat"]["total"] == 8
    # 50% slow / 50% budget = burn 1.0, over the 0.9 threshold
    assert rows["lat"]["verdict"] == "burning"
    assert rows["fresh"]["bad"] == 4
    assert rows["wr"]["bad"] == 0 and rows["wr"]["verdict"] == "ok"
    # writes never feed query specs and vice versa
    assert rows["wr"]["total"] == 8
    # errored queries don't pollute latency/staleness, only availability
    eng.observe_query(None, ok=False, trace_id="e")
    rows = {r["name"]: r for r in eng.status()}
    assert rows["lat"]["total"] == 8 and rows["fresh"]["total"] == 8
    text = "\n".join(eng.prom_lines())
    assert 'lux_slo_burn_rate{slo="lat",window="10s"}' in text
    assert 'lux_slo_verdict{slo="lat"} 2' in text


def test_slo_no_data_verdict():
    eng = SLOEngine(default_fleet_slos())
    assert {r["verdict"] for r in eng.status()} == {"no_data"}


def test_failed_admit_scores_write_slo(small, tmp_path):
    """An admit that RAISES (invalid batch, replication failure) is
    write_ack-BAD: a fleet where every write fails must not report
    'ok'/'no_data' from slo_status() — the same honesty submit keeps
    for availability by resolving sheds into the future."""
    g, _sh = small
    root = str(tmp_path / "f")
    fleet = start_live_fleet(1, g, parts=2, cap=64, journal_root=root,
                             snapshot_path=os.path.join(root, "s.lux"))
    ctl = fleet.controller
    ctl.set_slos(default_fleet_slos())
    try:
        # an edge absent in BOTH orientations: deleting it raises from
        # the journal apply, nothing journaled, no generation burned
        have = set()
        for d in range(g.nv):
            for s in g.col_idx[g.row_ptr[d]:g.row_ptr[d + 1]]:
                have.add((int(s), int(d)))
        s, d = next((a, b) for a in range(g.nv) for b in range(g.nv)
                    if a != b and (a, b) not in have
                    and (b, a) not in have)
        with pytest.raises(KeyError):
            ctl.admit_writes(np.array([s]), np.array([d]),
                             np.zeros(1, np.int8))
        row = {r["name"]: r for r in ctl.slo_status()}["write_ack"]
        assert (row["bad"], row["total"]) == (1, 1)
        # and a later good write scores good against the same spec
        ctl.admit_writes(np.array([s]), np.array([d]),
                         np.ones(1, np.int8))
        row = {r["name"]: r for r in ctl.slo_status()}["write_ack"]
        assert (row["bad"], row["total"]) == (1, 2)
    finally:
        fleet.close()


# ----------------------------------------------------------------------
# scrape() + exemplars (the metrics satellites)
# ----------------------------------------------------------------------


def test_scrape_fresh_start_never_empty():
    m = ServeMetrics()
    text = m.scrape(queue_depth=0, replica="w9")
    fams = prom_parse(text)
    # mid-burst/fresh scrape carries the live state dump() omits
    assert fams["lux_serve_qps"]["samples"][0][2] == 0.0
    assert fams["lux_serve_queue_depth"]["samples"][0][1] == {
        "replica": "w9"}
    assert fams["lux_serve_requests_completed_total"][
        "samples"][0][2] == 0
    # and qps becomes real once traffic lands, with no snapshot needed
    m.record_done(0.01, 0.001, traversed=5)
    fams = prom_parse(m.scrape(queue_depth=2))
    assert fams["lux_serve_qps"]["samples"][0][2] > 0
    assert fams["lux_serve_queue_depth"]["samples"][0][2] == 2


def test_latency_exemplars_in_dump():
    m = ServeMetrics()
    m.record_done(0.004, 0.001, traversed=1, trace="abc123")
    m.record_done(0.3, 0.001, traversed=1)  # untraced: no exemplar
    text = m.dump()
    line = next(l for l in text.splitlines()
                if 'trace_id="abc123"' in l)
    assert "lux_serve_request_latency_seconds_bucket" in line
    assert line.split(" # ")[0].endswith(" 1")
    assert m.exemplars()[0.005][0] == "abc123"
    prom_parse(text)  # exemplar suffix must not break parsing
    gauges = [("lux_live_generation_lag", 3, "lag")]
    fams = prom_parse(m.scrape(extra_gauges=gauges, replica="w0"))
    assert fams["lux_live_generation_lag"]["samples"][0] == (
        "lux_live_generation_lag", {"replica": "w0"}, 3.0)


def test_fault_inject_point_carries_seed(rec):
    plan = FaultPlan([FaultRule("proc", "delay", point="p.x",
                                delay_ms=0.0)], seed=42, name="s")
    with fault.installed(plan):
        fault.ppoint("p.x")
    rec.flush()
    evs = [e for e in read_events(rec.run_dir())
           if e.get("e") == "p" and e["n"] == "fault.inject"]
    assert evs and evs[0]["a"]["seed"] == 42
    assert evs[0]["a"]["plan"] == "s"


# ----------------------------------------------------------------------
# LUX-O005: trace contexts must stay out of traced bodies
# ----------------------------------------------------------------------


_O005_BAD = """
import jax
from lux_tpu.obs import dtrace

@jax.jit
def step(x):
    ctx = dtrace.mint(key="inside")
    return x + 1
"""

_O005_CLEAN = """
import jax
from lux_tpu.obs import dtrace

def serve(x):
    ctx = dtrace.mint(key="outside")
    with dtrace.tspan("serve", ctx):
        return _step(x)

@jax.jit
def _step(x):
    return x + 1
"""


def test_luxo005_seeded_and_clean(tmp_path):
    from lux_tpu.analysis import check_paths
    from lux_tpu.analysis.obs import ObsChecker

    def run(source, name):
        p = tmp_path / name
        p.write_text(source)
        return check_paths([str(p)], str(tmp_path),
                           checkers=[ObsChecker()])

    finds = run(_O005_BAD, "bad.py")
    assert [f.code for f in finds] == ["LUX-O005"]
    assert "trace-context" in finds[0].message
    assert not run(_O005_CLEAN, "clean.py")
