"""Push engine with Pallas dense rounds: parity vs the scan/scatter
engines and the host oracles on all push paths (VERDICT r2 #3).

All kernel runs use interpret mode (CPU harness); Mosaic numerics are
validated on hardware by tools/tpu_pallas_check.py.
"""
import numpy as np
import pytest

from lux_tpu.engine import push
from lux_tpu.graph import generate
from lux_tpu.graph.push_shards import build_push_shards
from lux_tpu.models import components
from lux_tpu.models.sssp import SSSPProgram, WeightedSSSPProgram, bfs_reference
from lux_tpu.parallel import pallas_dist as pd
from lux_tpu.parallel.mesh import make_mesh


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(8)


@pytest.mark.parametrize("op", ["min", "max"])
def test_kernel_minmax_preserves_int32(op):
    """Dtype-preserving min/max: int32 in -> int32 out, bitwise equal to a
    host reduction over the chunk layout's own (dst, val) pairs."""
    import jax.numpy as jnp

    from lux_tpu.ops import pallas_spmv as ps

    rng = np.random.default_rng(0)
    g = generate.rmat(8, 6, seed=1)
    bc = ps.build_blockcsr(g, v_blk=128, t_chunk=128)
    # values over the whole chunk grid incl. padding slots (values span
    # past 2**24 where float32 would round — the exactness this guards)
    ev = rng.integers(0, 2**28, (bc.num_chunks, bc.t_chunk)).astype(np.int32)
    got = np.asarray(
        ps.spmv_blockcsr(
            jnp.asarray(ev), jnp.asarray(bc.e_dst_rel),
            jnp.asarray(bc.chunk_block), jnp.asarray(bc.chunk_first),
            op=op, v_blk=bc.v_blk, num_vblocks=bc.num_vblocks,
            interpret=True,
        )
    )
    assert got.dtype == np.int32
    # oracle straight off the layout: real slots have dst_rel < v_blk
    mask = bc.e_dst_rel < bc.v_blk
    dstg = (bc.chunk_block[:, None] * bc.v_blk + bc.e_dst_rel)[mask]
    info = np.iinfo(np.int32)
    neutral = info.max if op == "min" else info.min
    want = np.full(bc.num_vblocks * bc.v_blk, neutral, np.int32)
    red = np.minimum if op == "min" else np.maximum
    getattr(red, "at")(want, dstg, ev[mask])
    np.testing.assert_array_equal(got, want)


def test_push_pallas_sssp_matches_oracle_and_scan(mesh8):
    g = generate.rmat(10, 8, seed=7)
    pps = pd.build_push_pallas_shards(g, 8, v_blk=128, t_chunk=128)
    state, iters, edges = pd.run_push_pallas_dist(
        SSSPProgram(nv=pps.spec.nv, start=0), pps, mesh8,
        max_iters=1000, interpret=True,
    )
    got = pps.scatter_to_global(np.asarray(state))
    np.testing.assert_array_equal(got, bfs_reference(g, 0))
    # same direction schedule + edge accounting as the scan engine
    base = build_push_shards(g, 8)
    _, it2, e2 = push.run_push(
        SSSPProgram(nv=base.spec.nv, start=0), base, 1000, method="scan"
    )
    assert int(iters) == int(it2)
    assert push.edges_total(edges) == push.edges_total(e2)


def test_push_pallas_cc_matches_fixpoint(mesh8):
    g = generate.rmat(9, 8, seed=11)
    pps = pd.build_push_pallas_shards(g, 8, v_blk=128, t_chunk=128)
    state, _, _ = pd.run_push_pallas_dist(
        components.MaxLabelProgram(), pps, mesh8, max_iters=1000,
        interpret=True,
    )
    got = pps.scatter_to_global(np.asarray(state))
    np.testing.assert_array_equal(
        got, components.connected_components_push(g)
    )


def test_push_pallas_weighted_sssp_matches_scan(mesh8):
    g = generate.rmat(9, 6, seed=13, weighted=True)
    g.weights[:] = np.maximum(1, np.asarray(g.weights, np.int64) % 9)
    pps = pd.build_push_pallas_shards(g, 8, v_blk=128, t_chunk=128)
    prog = WeightedSSSPProgram(nv=pps.spec.nv, start=0)
    state, _, _ = pd.run_push_pallas_dist(
        prog, pps, mesh8, max_iters=2000, interpret=True
    )
    got = pps.scatter_to_global(np.asarray(state))
    base = build_push_shards(g, 8)
    want_st, _, _ = push.run_push(
        WeightedSSSPProgram(nv=base.spec.nv, start=0), base, 2000,
        method="scan",
    )
    np.testing.assert_array_equal(got, base.scatter_to_global(np.asarray(want_st)))


def test_push_pallas_rejects_sum_programs(mesh8):
    g = generate.rmat(8, 4, seed=2)
    pps = pd.build_push_pallas_shards(g, 8)

    class FakeSum:
        reduce = "sum"

    with pytest.raises(ValueError):
        pd.run_push_pallas_dist(FakeSum(), pps, mesh8)


def test_cli_accepts_pallas_push(capsys):
    from lux_tpu.apps import sssp as app

    rc = app.main(
        ["--rmat-scale", "8", "-ng", "8", "--distributed",
         "--method", "pallas", "-check"]
    )
    assert rc == 0
    assert "[PASS]" in capsys.readouterr().out


def test_cli_pallas_gates():
    from lux_tpu.apps import sssp as app

    with pytest.raises(SystemExit):
        app.main(["--rmat-scale", "8", "--method", "pallas"])  # no mesh
    with pytest.raises(SystemExit):
        app.main(
            ["--rmat-scale", "8", "-ng", "8", "--distributed",
             "--method", "pallas", "--exchange", "ring"]
        )
