"""Benes/Clos permutation routing: host construction + oracles.

The route is the host half of the permuted-gather design (the
measured-fast replacement for XLA's scalar-issue-bound flat gather;
tools/tpu_gather_probe.py rows in .lux_winners.json).  These tests pin
the CONSTRUCTION: every pass must be a true per-digit gather (index
values in range, each batch row a permutation of the digit) and the
composition must replay the exact permutation.
"""
import numpy as np
import pytest

from lux_tpu.ops import route as R


def _check_passes_are_digit_perms(rt):
    """Each pass, viewed with its axis last, must hold a permutation of
    [0, dim) in EVERY batch row — gathers that drop or duplicate lanes
    would still 'apply' but could not be hardware-routed losslessly."""
    for p in rt.passes:
        dim = p.shape[p.axis]
        moved = np.moveaxis(p.idx, p.axis, -1).reshape(-1, dim)
        assert moved.min() >= 0 and moved.max() < dim
        sorted_rows = np.sort(moved, axis=1)
        assert (sorted_rows == np.arange(dim)).all()


@pytest.mark.parametrize("n", [128, 1024, 2048, 16384])
def test_route_random_perm(n, rng):
    perm = rng.permutation(n)
    rt = R.build_route(perm)
    assert len(rt.passes) == 2 * len(rt.dims) - 1
    _check_passes_are_digit_perms(rt)
    x = rng.random(n).astype(np.float32)
    np.testing.assert_array_equal(R.apply_route_np(rt, x), x[perm])


def test_route_identity_and_reverse(rng):
    n = 4096
    for perm in (np.arange(n), np.arange(n)[::-1].copy()):
        rt = R.build_route(perm)
        x = rng.random(n).astype(np.float32)
        np.testing.assert_array_equal(R.apply_route_np(rt, x), x[perm])


def test_route_int_payload(rng):
    """int32 payloads route bit-exactly (edge ids, labels)."""
    n = 2048
    perm = rng.permutation(n)
    rt = R.build_route(perm)
    x = rng.integers(-(2**31), 2**31 - 1, n, dtype=np.int32)
    np.testing.assert_array_equal(R.apply_route_np(rt, x), x[perm])


def test_factor_digits():
    assert R.factor_digits(128) == [128]
    assert R.factor_digits(1024) == [128, 8]
    assert R.factor_digits(2048) == [128, 8, 2]
    assert R.factor_digits(128 * 128) == [128, 128]
    assert R.factor_digits(1 << 24) == [128, 128, 128, 8]
    with pytest.raises(ValueError):
        R.factor_digits(96)


def test_route_mixed_small_digit_first_rejected(rng):
    """dims are caller-overridable; a wrong product must fail loudly."""
    with pytest.raises(AssertionError):
        R.build_route(np.arange(256), dims=[128, 4])


def test_native_and_python_colorings_both_route(rng, monkeypatch):
    """The native colorer (native/lux_route.cc) and the Python Euler
    walk may produce different colorings; both must replay exactly."""
    from lux_tpu import native

    assert native.get_lib() is not None, \
        "native lib must be buildable in CI (toolchain baked in)"
    n = 8192
    perm = rng.permutation(n)
    x = rng.random(n).astype(np.float32)
    rt_native = R.build_route(perm)
    # force the Python path
    monkeypatch.setattr(native, "route_color", lambda *a, **k: None)
    rt_py = R.build_route(perm)
    for rt in (rt_native, rt_py):
        _check_passes_are_digit_perms(rt)
        np.testing.assert_array_equal(R.apply_route_np(rt, x), x[perm])
